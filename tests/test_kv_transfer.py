"""Cross-process KV data plane (runtime/transfer.py) and multi-host
bootstrap (parallel/distributed.py).

The reference's decode engines pull prefilled KV straight from the
prefill engine's device memory over RDMA, keyed by relayed cache ids
(xllm_service/common/types.h:174-177, rpc_service/service.cpp:74-105
GetInstanceInfo). Here the analog is jax.experimental.transfer: offers on
the prefill side, device-to-device pulls on the decode side, with the
/kv/import control message carrying only {addr, uuid, shape, dtype}.

Covers: raw offer/pull roundtrip, the PD e2e parity through the pull
plane (in-process wire path), a REAL two-process PD e2e (decode instance
in a subprocess, KV crossing the process boundary without host staging in
the POST body), and the 2-process jax.distributed global-mesh bootstrap.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from xllm_service_tpu.api import Master
from xllm_service_tpu.api.instance import InstanceServer
from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
from xllm_service_tpu.coordination import MemoryStore

from tests.test_api_e2e import http_post, wait_until

BLOCK = 16
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:  # the pull plane needs jax.experimental.transfer (not in every build)
    from jax.experimental import transfer as _jax_transfer  # noqa: F401

    _HAVE_TRANSFER = True
except ImportError:
    _HAVE_TRANSFER = False

requires_transfer = pytest.mark.skipif(
    not _HAVE_TRANSFER,
    reason="jax.experimental.transfer not available in this jax build",
)


def engine_cfg(name, itype, **kw):
    kw.setdefault("enable_local_kv_transfer", False)
    return EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=BLOCK,
        num_blocks=64, max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64, 128],
        instance_name=name, instance_type=itype,
        **kw,
    )


@requires_transfer
def test_offer_pull_roundtrip():
    """Offer/pull through the process transfer server's TCP transport
    (self-connection; the transport registry supports ONE server per
    process — jaxlib's LocalBulkTransportFactory aborts on a second, so
    instances share the get_transfer_server singleton and true
    cross-process pulls are covered by the subprocess e2e below)."""
    import jax.numpy as jnp

    from xllm_service_tpu.runtime.transfer import get_transfer_server

    srv = get_transfer_server()
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((3, 5, 7)), jnp.float32
    )
    uuid = srv.offer([x])
    got = srv.pull_single(srv.address, uuid, x.shape, np.float32)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(x))
    srv.retract(uuid)
    # bf16 payloads (the serving dtype) survive the wire too.
    import ml_dtypes

    y = jnp.asarray(np.arange(32).reshape(4, 8), jnp.bfloat16)
    uuid = srv.offer([y])
    got = srv.pull_single(srv.address, uuid, y.shape, ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(got, np.float32), np.asarray(y, np.float32)
    )
    srv.retract(uuid)


def _mk_master():
    store = MemoryStore(clock=lambda: 0.0)  # frozen: leases never lapse under GIL stalls
    cfg = ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=5.0,
        load_balance_policy="RR", block_size=BLOCK,
    )
    m = Master(cfg, store=store)
    m.start()
    return m, store


def completion(master, prompt, n=8):
    code, body = http_post(
        master.http_address, "/v1/completions",
        {"model": "llama3-tiny", "prompt": prompt, "max_tokens": n,
         "temperature": 0.0},
        timeout=300.0,
    )
    assert code == 200, body
    return body


@pytest.fixture(scope="module")
def colocated_oracle():
    master, store = _mk_master()
    inst = InstanceServer(
        engine_cfg("mix-oracle", "MIX"), master_rpc_addr=master.rpc_address,
        heartbeat_interval_s=0.2,
    )
    inst.start()
    assert wait_until(
        lambda: sum(master.scheduler.instance_mgr.counts()) == 1
    )
    yield master
    inst.stop()
    master.stop()
    store.close()


@requires_transfer
def test_pull_plane_pd_e2e(colocated_oracle):
    """PD pair with the pull plane enabled (local direct path disabled):
    the handoff POST carries no KV bytes; the decode side pulls from the
    transfer server. Output matches the colocated oracle."""
    master, store = _mk_master()
    pre = InstanceServer(
        engine_cfg("pre-pull", "PREFILL", enable_kv_transfer_server=True),
        master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.2,
    )
    dec = InstanceServer(
        engine_cfg("dec-pull", "DECODE", enable_kv_transfer_server=True),
        master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.2,
    )
    pre.start()
    dec.start()
    try:
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts() == (1, 1, 0)
        )
        assert pre._kv_transfer is not None
        prompt = "z" * (BLOCK * 3 + 5)
        got = completion(master, prompt)
        want = completion(colocated_oracle, prompt)
        assert got["choices"][0]["text"] == want["choices"][0]["text"]
        assert got["usage"] == want["usage"]
    finally:
        pre.stop()
        dec.stop()
        master.stop()
        store.close()


@pytest.mark.slow
@requires_transfer
def test_pd_e2e_cross_process(colocated_oracle):
    """REAL process boundary: the decode instance lives in a subprocess
    with its own JAX runtime; the prefill side offers device-resident KV
    and the subprocess pulls it device-to-device. Greedy output matches
    the colocated oracle (both engines init with the same seed)."""
    master, store = _mk_master()
    pre = InstanceServer(
        engine_cfg("pre-xp", "PREFILL", enable_kv_transfer_server=True),
        master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.2,
    )
    pre.start()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "_decode_proc.py"),
         master.rpc_address, str(BLOCK)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        # Engine boot + registration is the sync point.
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts() == (1, 1, 0),
            timeout=180.0,
        ), "decode subprocess never registered"
        prompt = "q" * (BLOCK * 3 + 5)
        got = completion(master, prompt)
        want = completion(colocated_oracle, prompt)
        assert got["choices"][0]["text"] == want["choices"][0]["text"]
        assert got["usage"] == want["usage"]
    finally:
        proc.kill()
        out, _ = proc.communicate(timeout=30)
        pre.stop()
        master.stop()
        store.close()
    # The pull plane must actually have served the handoff: the prefill
    # side's transfer server issued at least one offer.
    assert pre._kv_transfer is not None


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_jax_distributed_two_process_mesh():
    """parallel/distributed.bootstrap forms a 2-process global device
    mesh (4 CPU devices each -> 8 global) and a cross-process psum runs —
    the v5e-64 multi-host story in miniature."""
    coordinator = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "_dist_proc.py"),
             coordinator, str(pid), "2"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for pid in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"DIST_OK {pid}" in out, out
