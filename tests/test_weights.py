"""Checkpoint loading round-trip + executor e2e.

Covers VERDICT round-1 missing item 1: runtime/weights.py — HF safetensors
→ stacked pytree, all three registered families (Llama, Qwen2-style bias,
Mixtral-style MoE), sharded multi-file checkpoints, and an executor that
serves from a checkpoint dir producing tokens identical to one holding the
same params in memory.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.models import llama
from xllm_service_tpu.models.configs import ModelConfig, get_model_config
from xllm_service_tpu.runtime import weights
from xllm_service_tpu.runtime.executor import ModelExecutor, SamplingBatch

QWEN_TINY = ModelConfig(
    name="qwen-tiny",
    vocab_size=512,
    hidden_size=128,
    intermediate_size=256,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    head_dim=32,
    attn_bias=True,
    max_position_embeddings=1024,
)


def _tree_equal(a, b):
    la = jax.tree.leaves(a)
    lb = jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize(
    "cfg",
    [
        get_model_config("llama3-tiny"),
        QWEN_TINY,
        get_model_config("qwen3-tiny"),
        get_model_config("qwen3-moe-tiny"),
        get_model_config("moe-tiny"),
        get_model_config("deepseek-tiny"),
        get_model_config("deepseek-moe-tiny"),
        get_model_config("deepseek-hetero-tiny"),
    ],
    ids=["llama", "qwen-bias", "qwen3-qknorm", "qwen3-moe", "moe", "mla",
         "mla-moe-shared", "mla-hetero"],
)
def test_save_load_roundtrip(cfg, tmp_path):
    from xllm_service_tpu import models

    family = models.get_module(cfg)
    params = family.init_params(cfg, jax.random.key(7), jnp.bfloat16)
    # Give biases nonzero values so the mapping is actually exercised.
    if cfg.attn_bias:
        lp = params["layers"]
        for k in ("bq", "bk", "bv"):
            lp[k] = jax.random.normal(jax.random.key(hash(k) % 2**31),
                                      lp[k].shape, jnp.bfloat16)
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(params, cfg, ckpt)

    loaded_cfg = weights.config_from_hf(ckpt)
    for f in ("vocab_size", "hidden_size", "num_layers", "num_heads",
              "num_kv_heads", "rope_theta", "rms_norm_eps",
              "tie_word_embeddings", "num_experts", "num_experts_per_tok",
              "attn_bias", "kv_lora_rank", "q_lora_rank",
              "qk_nope_head_dim", "qk_rope_head_dim", "v_head_dim",
              "n_shared_experts", "first_k_dense_replace"):
        assert getattr(loaded_cfg, f) == getattr(cfg, f), f
    if not cfg.is_mla:  # MLA ignores head_dim; HF derives it differently
        assert loaded_cfg.head_dim == cfg.head_dim

    loaded = weights.load_checkpoint(ckpt, cfg, jnp.bfloat16)
    _tree_equal(params, loaded)

    # Same logits through the oracle forward.
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16), np.int32)
    )
    out_a = family.forward_dense(params, cfg, toks)
    out_b = family.forward_dense(loaded, cfg, toks)
    np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))


def test_tied_embeddings_roundtrip(tmp_path):
    cfg = ModelConfig(
        name="tied-tiny", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=2, num_kv_heads=2,
        head_dim=32, tie_word_embeddings=True,
    )
    params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16)
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(params, cfg, ckpt)
    assert weights.config_from_hf(ckpt).tie_word_embeddings
    loaded = weights.load_checkpoint(ckpt, cfg, jnp.bfloat16)
    assert "lm_head" not in loaded
    _tree_equal(params, loaded)


def test_yarn_rope_scaling_roundtrip(tmp_path):
    """save_hf_checkpoint must serialize yarn rope_scaling symmetrically
    with _hf_rope_scaling — a saved DeepSeek-V3-style yarn config used to
    come back as {"rope_type": "yarn"} alone, which config_from_hf rejects
    (KeyError: 'factor') and transformers can't load."""
    cfg = ModelConfig(
        name="yarn-tiny", vocab_size=128, hidden_size=64,
        intermediate_size=128, num_layers=2, num_heads=2, num_kv_heads=2,
        head_dim=32,
        rope_scaling_type="yarn", rope_scaling_factor=40.0,
        rope_original_max_position=4096,
        rope_beta_fast=32.0, rope_beta_slow=1.0,
        rope_mscale=1.0, rope_mscale_all_dim=1.0,
    )
    params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16)
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(params, cfg, ckpt)
    loaded_cfg = weights.config_from_hf(ckpt)
    for f in ("rope_scaling_type", "rope_scaling_factor",
              "rope_original_max_position", "rope_beta_fast",
              "rope_beta_slow", "rope_mscale", "rope_mscale_all_dim",
              "rope_scaling_truncate"):
        assert getattr(loaded_cfg, f) == getattr(cfg, f), f


def test_multi_shard_with_index(tmp_path):
    """Checkpoints split across files + model.safetensors.index.json."""
    cfg = get_model_config("llama3-tiny")
    params = llama.init_params(cfg, jax.random.key(3), jnp.bfloat16)
    ckpt = tmp_path / "ckpt"
    weights.save_hf_checkpoint(params, cfg, str(ckpt))

    # Re-split the single file into two shards + index.
    tensors = dict(weights.read_safetensors(str(ckpt / "model.safetensors")))
    tensors = {k: v.copy() for k, v in tensors.items()}
    names = sorted(tensors)
    half = len(names) // 2
    shard_of = {}
    for i, part in enumerate((names[:half], names[half:])):
        fname = f"model-0000{i + 1}-of-00002.safetensors"
        weights.write_safetensors(
            str(ckpt / fname), {n: tensors[n] for n in part}
        )
        for n in part:
            shard_of[n] = fname
    os.remove(ckpt / "model.safetensors")
    with open(ckpt / "model.safetensors.index.json", "w") as f:
        json.dump({"weight_map": shard_of}, f)

    loaded = weights.load_checkpoint(str(ckpt), cfg, jnp.bfloat16)
    _tree_equal(params, loaded)


def test_missing_tensor_raises(tmp_path):
    cfg = get_model_config("llama3-tiny")
    params = llama.init_params(cfg, jax.random.key(0), jnp.bfloat16)
    ckpt = tmp_path / "ckpt"
    weights.save_hf_checkpoint(params, cfg, str(ckpt))
    tensors = dict(weights.read_safetensors(str(ckpt / "model.safetensors")))
    tensors = {k: v.copy() for k, v in tensors.items()}
    del tensors["model.layers.1.self_attn.q_proj.weight"]
    weights.write_safetensors(str(ckpt / "model.safetensors"), tensors)
    with pytest.raises(ValueError, match="missing"):
        weights.load_checkpoint(str(ckpt), cfg, jnp.bfloat16)


def test_missing_expert_raises(tmp_path):
    """A MoE checkpoint missing ONE expert's tensor must raise, not serve
    uninitialized garbage for that expert."""
    cfg = get_model_config("moe-tiny")
    params = llama.init_params(cfg, jax.random.key(1), jnp.bfloat16)
    ckpt = tmp_path / "ckpt"
    weights.save_hf_checkpoint(params, cfg, str(ckpt))
    tensors = dict(weights.read_safetensors(str(ckpt / "model.safetensors")))
    tensors = {k: v.copy() for k, v in tensors.items()}
    del tensors["model.layers.0.block_sparse_moe.experts.2.w1.weight"]
    weights.write_safetensors(str(ckpt / "model.safetensors"), tensors)
    with pytest.raises(ValueError, match="missing"):
        weights.load_checkpoint(str(ckpt), cfg, jnp.bfloat16)


def test_executor_uses_checkpoint_config(tmp_path):
    """checkpoint_path with a config.json NOT in the registry: the executor
    derives the architecture from the checkpoint (config_from_hf), so real
    HF dirs serve without a pre-registered config."""
    params = llama.init_params(QWEN_TINY, jax.random.key(5), jnp.bfloat16)
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(params, QWEN_TINY, ckpt)
    ecfg = EngineConfig(model="not-in-registry", checkpoint_path=ckpt,
                       num_blocks=16, max_running_requests=2,
                       max_seq_len=128, prefill_buckets=[32])
    exe = ModelExecutor(ecfg)
    assert exe.cfg.attn_bias and exe.cfg.hidden_size == QWEN_TINY.hidden_size
    _tree_equal(params, exe.params)


def test_executor_serves_from_checkpoint(tmp_path):
    """An executor given checkpoint_path produces the exact tokens of one
    holding the same params in memory (greedy decode, real prefill)."""
    ecfg = EngineConfig(model="llama3-tiny", num_blocks=32,
                       max_running_requests=4, max_seq_len=256,
                       prefill_buckets=[32, 64])
    ref = ModelExecutor(ecfg, init_seed=11)
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(ref.params, ref.cfg, ckpt)

    ecfg2 = EngineConfig(model="llama3-tiny", checkpoint_path=ckpt,
                        num_blocks=32, max_running_requests=4,
                        max_seq_len=256, prefill_buckets=[32, 64])
    exe = ModelExecutor(ecfg2, init_seed=0)  # seed irrelevant: weights loaded
    _tree_equal(ref.params, exe.params)

    prompt = np.arange(10, dtype=np.int32) % ref.cfg.vocab_size
    table = np.zeros((ref.max_blocks_per_seq,), np.int32)
    table[0] = 3
    outs = []
    for e in (ref, exe):
        tok, _ = e.prefill(prompt, 0, table)
        toks = [tok]
        R = ecfg.max_running_requests
        batch = SamplingBatch(
            temperature=np.zeros(R, np.float32),
            top_k=np.zeros(R, np.int32),
            top_p=np.ones(R, np.float32),
            seeds=np.zeros(R, np.uint32),
            steps=np.zeros(R, np.int32),
        )
        ids = np.zeros(R, np.int32)
        pos = np.zeros(R, np.int32)
        tables = np.zeros((R, ref.max_blocks_per_seq), np.int32)
        tables[0] = table
        active = np.zeros(R, bool)
        active[0] = True
        cur, p = tok, len(prompt)
        for _ in range(5):
            ids[0], pos[0] = cur, p
            t, _ = e.decode(ids, pos, tables, active, batch)
            cur = int(t[0])
            toks.append(cur)
            p += 1
        outs.append(toks)
    assert outs[0] == outs[1]


def test_executor_serves_hetero_checkpoint(tmp_path):
    """A heterogeneous DeepSeek checkpoint (dense prefix + MoE suffix,
    first_k_dense_replace=1) loads through the executor's sharded path and
    serves: loaded params match, greedy prefill tokens agree."""
    ecfg = EngineConfig(model="deepseek-hetero-tiny", dtype="float32",
                       num_blocks=32, max_running_requests=2,
                       max_seq_len=128, prefill_buckets=[32])
    ref = ModelExecutor(ecfg, init_seed=3)
    assert "dense_layers" in ref.params
    ckpt = str(tmp_path / "ckpt")
    weights.save_hf_checkpoint(ref.params, ref.cfg, ckpt)

    loaded_cfg = weights.config_from_hf(ckpt)
    assert loaded_cfg.first_k_dense_replace == 1

    ecfg2 = EngineConfig(model="deepseek-hetero-tiny", dtype="float32",
                        checkpoint_path=ckpt, num_blocks=32,
                        max_running_requests=2, max_seq_len=128,
                        prefill_buckets=[32])
    exe = ModelExecutor(ecfg2, init_seed=0)
    _tree_equal(ref.params, exe.params)

    prompt = (np.arange(12, dtype=np.int32) * 7 + 1) % ref.cfg.vocab_size
    table = np.zeros((ref.max_blocks_per_seq,), np.int32)
    table[0] = 2
    t_ref, _ = ref.prefill(prompt, 0, table)
    t_exe, _ = exe.prefill(prompt, 0, table)
    assert t_ref == t_exe


def test_hf_sliding_window_gates():
    """HF SWA gates (ADVICE r4 review): Qwen2-style use_sliding_window=
    false and partial max_window_layers must NOT enable the window;
    Mistral-style bare sliding_window must."""
    from xllm_service_tpu.runtime.weights import _hf_sliding_window

    assert _hf_sliding_window({"sliding_window": 4096}) == 4096
    assert _hf_sliding_window({"sliding_window": None}) == 0
    assert _hf_sliding_window(
        {"sliding_window": 32768, "use_sliding_window": False}
    ) == 0
    # HF Qwen2 semantics: layer i slides iff i >= max_window_layers.
    # mwl=28/64 -> mixed stack (unrepresentable): LOUD reject — serving
    # it as full attention would silently diverge from HF beyond the
    # window (advisor finding, round 4).
    with pytest.raises(NotImplementedError, match="max_window_layers"):
        _hf_sliding_window(
            {"sliding_window": 32768, "use_sliding_window": True,
             "max_window_layers": 28, "num_hidden_layers": 64}
        )
    # mwl=64/64 -> ZERO sliding layers: full attention.
    assert _hf_sliding_window(
        {"sliding_window": 32768, "use_sliding_window": True,
         "max_window_layers": 64, "num_hidden_layers": 64}
    ) == 0
    # mwl=0 -> every layer slides: the uniform window applies.
    assert _hf_sliding_window(
        {"sliding_window": 32768, "use_sliding_window": True,
         "max_window_layers": 0, "num_hidden_layers": 64}
    ) == 32768


def test_mistral_arch_loads_with_sliding_window(tmp_path):
    """MistralForCausalLM (Llama layout + SWA) round-trips through
    config_from_hf/load_checkpoint with sliding_window parsed into the
    config (the serving-path window behavior itself is pinned by
    test_model.test_sliding_window_matches_dense)."""
    import dataclasses
    import json as _json

    from xllm_service_tpu.models import llama
    from xllm_service_tpu.models.configs import get_model_config
    from xllm_service_tpu.runtime import weights as W

    cfg = dataclasses.replace(get_model_config("llama3-tiny"),
                              sliding_window=24)
    params = llama.init_params(cfg, jax.random.key(5), dtype=jnp.float32)
    ckpt = str(tmp_path / "mistral")
    W.save_hf_checkpoint(params, cfg, ckpt)
    with open(os.path.join(ckpt, "config.json")) as f:
        hf = _json.load(f)
    hf["architectures"] = ["MistralForCausalLM"]
    hf["model_type"] = "mistral"
    hf["sliding_window"] = 24
    with open(os.path.join(ckpt, "config.json"), "w") as f:
        _json.dump(hf, f)

    cfg2 = W.config_from_hf(ckpt)
    assert cfg2.sliding_window == 24
    assert not cfg2.attn_bias
    loaded = W.load_checkpoint(ckpt, cfg2, dtype=jnp.float32)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(loaded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- Gemma family


def test_gemma_matches_hf_reference(tmp_path):
    """GemmaForCausalLM numerical parity: GELU-tanh gated MLP, sqrt(E)
    embedding scale, zero-centered RMSNorm weights, tied embeddings —
    greedy continuations match transformers' GemmaForCausalLM on the
    same exported weights."""
    torch = pytest.importorskip("torch")
    try:
        from transformers import GemmaConfig, GemmaForCausalLM
    except Exception:
        pytest.skip("transformers lacks Gemma")

    hf_cfg = GemmaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32, rope_theta=10000.0, rms_norm_eps=1e-6,
        max_position_embeddings=1024, attn_implementation="eager",
    )
    torch.manual_seed(3)
    with torch.no_grad():
        hf = GemmaForCausalLM(hf_cfg).eval().float()
    ckpt = str(tmp_path / "gemma")
    os.makedirs(ckpt, exist_ok=True)
    tensors = {n: p.detach().numpy() for n, p in hf.named_parameters()}
    weights.write_safetensors(
        os.path.join(ckpt, "model.safetensors"), tensors
    )
    with open(os.path.join(ckpt, "config.json"), "w") as f:
        json.dump({
            "architectures": ["GemmaForCausalLM"], "model_type": "gemma",
            "vocab_size": 512, "hidden_size": 128,
            "intermediate_size": 256, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "head_dim": 32, "rope_theta": 10000.0, "rms_norm_eps": 1e-6,
            "max_position_embeddings": 1024,
            # deliberately NO tie_word_embeddings key: real Gemma
            # checkpoints omit it (HF default True) — the loader must
            # not demand an lm_head tensor Gemma never ships
        }, f)

    cfg2 = weights.config_from_hf(ckpt)
    assert cfg2.mlp_act == "gelu_tanh"
    assert cfg2.embed_scale and cfg2.norm_zero_centered
    assert cfg2.tie_word_embeddings
    loaded = weights.load_checkpoint(ckpt, cfg2, dtype=jnp.float32)

    rng = np.random.default_rng(6)
    prompt = rng.integers(1, 500, (11,)).tolist()
    ids = torch.tensor([prompt])
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=ids, max_new_tokens=6, do_sample=False,
        )
    want = hf_out[0, len(prompt):].tolist()

    from xllm_service_tpu.runtime.executor import ModelExecutor
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.ops.sampling import SamplingParams

    ecfg = EngineConfig(
        model="gemma-hf", dtype="float32", checkpoint_path=ckpt,
        block_size=16, num_blocks=32, max_running_requests=2,
        max_seq_len=128, prefill_buckets=[16, 32],
    )
    eng = InferenceEngine(ecfg, executor=ModelExecutor(ecfg))
    got = []

    def cb(o):
        for s in o.outputs:
            got.extend(s.token_ids)
        return True

    eng.add_request(EngineRequest(
        "g", prompt, SamplingParams(temperature=0.0, max_new_tokens=6), cb,
    ))
    for _ in range(60):
        if not eng.has_work():
            break
        eng.step()
    assert got == want, (got, want)


def test_gemma_roundtrip(tmp_path):
    """gemma-tiny save/load round trip: zero-centered norm export +
    re-add on load is lossless; dense oracle logits identical."""
    cfg = get_model_config("gemma-tiny")
    params = llama.init_params(cfg, jax.random.key(9), jnp.float32)
    ckpt = str(tmp_path / "g")
    weights.save_hf_checkpoint(params, cfg, ckpt)
    cfg2 = weights.config_from_hf(ckpt)
    assert cfg2.norm_zero_centered and cfg2.embed_scale
    loaded = weights.load_checkpoint(ckpt, cfg2, dtype=jnp.float32)
    _tree_equal(params, loaded)
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 512, (2, 12), np.int32)
    )
    np.testing.assert_array_equal(
        np.asarray(llama.forward_dense(params, cfg, toks)),
        np.asarray(llama.forward_dense(loaded, cfg2, toks)),
    )


def test_phi3_matches_hf_reference(tmp_path):
    """Phi3ForCausalLM (Llama + FUSED qkv_proj/gate_up_proj): the loader
    splits the fused tensors by config geometry; greedy continuations
    match transformers' Phi3ForCausalLM through the real engine."""
    torch = pytest.importorskip("torch")
    try:
        from transformers import Phi3Config, Phi3ForCausalLM
    except Exception:
        pytest.skip("transformers lacks Phi3")

    hf_cfg = Phi3Config(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5,
        max_position_embeddings=1024, pad_token_id=0,
        attn_implementation="eager",
    )
    torch.manual_seed(4)
    with torch.no_grad():
        hf = Phi3ForCausalLM(hf_cfg).eval().float()
    ckpt = str(tmp_path / "phi3")
    os.makedirs(ckpt, exist_ok=True)
    tensors = {n: p.detach().numpy() for n, p in hf.named_parameters()}
    weights.write_safetensors(
        os.path.join(ckpt, "model.safetensors"), tensors
    )
    with open(os.path.join(ckpt, "config.json"), "w") as f:
        json.dump({
            "architectures": ["Phi3ForCausalLM"], "model_type": "phi3",
            "vocab_size": 512, "hidden_size": 64,
            "intermediate_size": 128, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "rope_theta": 10000.0, "rms_norm_eps": 1e-5,
            "max_position_embeddings": 1024,
        }, f)

    cfg2 = weights.config_from_hf(ckpt)

    rng = np.random.default_rng(7)
    prompt = rng.integers(1, 500, (10,)).tolist()
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=torch.tensor([prompt]), max_new_tokens=6,
            do_sample=False,
        )
    want = hf_out[0, len(prompt):].tolist()

    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.runtime.executor import ModelExecutor

    ecfg = EngineConfig(
        model="phi3-hf", dtype="float32", checkpoint_path=ckpt,
        block_size=16, num_blocks=32, max_running_requests=2,
        max_seq_len=128, prefill_buckets=[16, 32],
    )
    eng = InferenceEngine(ecfg, executor=ModelExecutor(ecfg))
    got = []

    def cb(o):
        for s in o.outputs:
            got.extend(s.token_ids)
        return True

    eng.add_request(EngineRequest(
        "p3", prompt, SamplingParams(temperature=0.0, max_new_tokens=6), cb,
    ))
    for _ in range(60):
        if not eng.has_work():
            break
        eng.step()
    assert got == want, (got, want)


def test_unknown_rope_scaling_rejected(tmp_path):
    """Unimplemented rope_scaling types fail LOUDLY — the one failure
    mode the loader refuses is a checkpoint that loads cleanly and
    serves silently diverging logits. (llama3/linear/dynamic/longrope/
    yarn are all implemented — tests/test_rope_scaling.py.)"""
    ckpt = str(tmp_path / "llama-mystery-rope")
    os.makedirs(ckpt, exist_ok=True)
    with open(os.path.join(ckpt, "config.json"), "w") as f:
        json.dump({
            "architectures": ["LlamaForCausalLM"], "vocab_size": 512,
            "hidden_size": 64, "intermediate_size": 128,
            "num_hidden_layers": 2, "num_attention_heads": 4,
            "num_key_value_heads": 2,
            "rope_scaling": {"rope_type": "ntk-mystery", "factor": 4.0},
        }, f)
    with pytest.raises(NotImplementedError, match="ntk-mystery"):
        weights.config_from_hf(ckpt)


def test_mixed_sliding_window_stack_rejected(tmp_path):
    """A genuinely mixed SWA stack (0 < max_window_layers < num_layers
    with use_sliding_window=true) is not representable by the uniform
    scanned layers — it must raise, not silently serve full attention
    (advisor finding, round 4)."""
    ckpt = str(tmp_path / "qwen2-mixed-swa")
    os.makedirs(ckpt, exist_ok=True)
    with open(os.path.join(ckpt, "config.json"), "w") as f:
        json.dump({
            "architectures": ["Qwen2ForCausalLM"], "vocab_size": 512,
            "hidden_size": 64, "intermediate_size": 128,
            "num_hidden_layers": 4, "num_attention_heads": 4,
            "num_key_value_heads": 2, "sliding_window": 32,
            "use_sliding_window": True, "max_window_layers": 2,
        }, f)
    with pytest.raises(NotImplementedError, match="max_window_layers"):
        weights.config_from_hf(ckpt)
