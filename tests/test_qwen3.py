"""Qwen3 family (llama module + qk_norm): per-head RMSNorm on q/k before
RoPE (HF Qwen3Attention), dense and 128-expert-style MoE variants.

Pins three things: the paged prefill/decode path reproduces the dense
oracle with qk_norm on; checkpoints roundtrip through the HF layout
(q_norm/k_norm tensors, Qwen3/Qwen3Moe arch detection, mlp.gate router
naming); and — the gold standard — logits match transformers'
Qwen3ForCausalLM bit-for-tolerance on identical weights, so the norm/RoPE
ordering cannot silently drift from the real architecture.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from xllm_service_tpu.models import llama
from xllm_service_tpu.models.configs import get_model_config

BS = 16
NUM_BLOCKS = 32
MAX_BLOCKS = 8


@pytest.fixture(scope="module")
def qwen3_tiny():
    cfg = get_model_config("qwen3-tiny")
    params = llama.init_params(cfg, jax.random.key(3), dtype=jnp.float32)
    # Random (not unit) norm weights so qk_norm actually shapes the
    # numbers the parity below depends on.
    key = jax.random.key(7)
    kq, kk = jax.random.split(key)
    layers = dict(params["layers"])
    layers["q_head_norm"] = (
        1.0 + 0.3 * jax.random.normal(kq, layers["q_head_norm"].shape)
    ).astype(jnp.float32)
    layers["k_head_norm"] = (
        1.0 + 0.3 * jax.random.normal(kk, layers["k_head_norm"].shape)
    ).astype(jnp.float32)
    params = dict(params)
    params["layers"] = layers
    return cfg, params


def _empty_caches(cfg, dtype=jnp.float32):
    shape = (cfg.num_layers, NUM_BLOCKS, cfg.num_kv_heads, BS, cfg.head_dim)
    return jnp.zeros(shape, dtype), jnp.zeros(shape, dtype)


def test_qwen3_params_carry_qk_norm(qwen3_tiny):
    cfg, params = qwen3_tiny
    assert params["layers"]["q_head_norm"].shape == (
        cfg.num_layers, cfg.head_dim,
    )
    assert params["layers"]["k_head_norm"].shape == (
        cfg.num_layers, cfg.head_dim,
    )


def test_qwen3_paged_matches_dense(qwen3_tiny):
    """Prefill + decode over the paged cache equal the dense forward."""
    cfg, params = qwen3_tiny
    rng = np.random.RandomState(2)
    L = 23
    tokens = list(rng.randint(0, cfg.vocab_size, size=(L,)))

    k, v = _empty_caches(cfg)
    table = np.zeros((MAX_BLOCKS,), np.int32)
    table[:4] = [1, 2, 3, 4]
    logits, k, v = llama.prefill_step(
        params, cfg, k, v,
        jnp.asarray(np.pad(np.array(tokens, np.int32), (0, 32 - L))),
        jnp.int32(0), jnp.int32(L), jnp.asarray(table),
    )
    dense = llama.forward_dense(
        params, cfg, jnp.asarray(tokens, jnp.int32)[None]
    )
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(dense[0, L - 1]),
        rtol=2e-4, atol=2e-4,
    )

    R = 2
    seq = tokens + [int(jnp.argmax(logits))]
    block_tables = np.zeros((R, MAX_BLOCKS), np.int32)
    block_tables[0] = table
    active = np.zeros((R,), bool)
    active[0] = True
    for _ in range(4):
        pos = len(seq) - 1
        ids = np.zeros((R,), np.int32)
        ids[0] = seq[-1]
        positions = np.zeros((R,), np.int32)
        positions[0] = pos
        logits, k, v = llama.decode_step(
            params, cfg, k, v,
            jnp.asarray(ids), jnp.asarray(positions),
            jnp.asarray(block_tables), jnp.asarray(active),
            use_kernel=False,
        )
        dense = llama.forward_dense(
            params, cfg, jnp.asarray(seq, jnp.int32)[None]
        )
        np.testing.assert_allclose(
            np.asarray(logits[0]), np.asarray(dense[0, -1]),
            rtol=2e-4, atol=2e-4,
        )
        seq.append(int(jnp.argmax(logits[0])))


def test_qwen3_matches_transformers(qwen3_tiny, tmp_path):
    """Numerical parity with the HF reference implementation on IDENTICAL
    weights: save our params as an HF checkpoint, load it with
    transformers' Qwen3ForCausalLM, compare full logits."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    if not hasattr(transformers, "Qwen3ForCausalLM"):
        pytest.skip("transformers too old for Qwen3")
    from xllm_service_tpu.runtime.weights import save_hf_checkpoint

    cfg, params = qwen3_tiny
    path = tmp_path / "qwen3-hf"
    save_hf_checkpoint(params, cfg, str(path))

    hf = transformers.Qwen3ForCausalLM.from_pretrained(
        str(path), torch_dtype=torch.float32
    )
    hf.eval()
    rng = np.random.RandomState(5)
    tokens = rng.randint(0, cfg.vocab_size, size=(1, 17)).astype(np.int64)
    with torch.no_grad():
        hf_logits = hf(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        llama.forward_dense(params, cfg, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-3, atol=2e-3)


def test_qwen3_moe_engine_e2e():
    """qwen3-moe-tiny through the executor: greedy continuation equals
    the dense oracle (router renormalized-top-k = shared _mlp math)."""
    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.runtime.executor import ModelExecutor, SamplingBatch

    cfg = EngineConfig(
        model="qwen3-moe-tiny", dtype="float32", block_size=16,
        num_blocks=64, max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64],
    )
    ex = ModelExecutor(cfg, init_seed=13)
    rng = np.random.default_rng(6)
    prompt = rng.integers(1, 500, (21,)).astype(np.int32)
    table = np.zeros((ex.max_blocks_per_seq,), np.int32)
    table[:3] = [1, 2, 3]
    tok, _ = ex.prefill(prompt, 0, table)

    mcfg = ex.cfg
    seq = list(prompt)
    want = []
    for _ in range(4):
        logits = llama.forward_dense(
            ex.params, mcfg, jnp.asarray(seq, jnp.int32)[None]
        )
        nxt = int(jnp.argmax(logits[0, -1]))
        want.append(nxt)
        seq.append(nxt)
    assert tok == want[0]

    got = [tok]
    pos = np.zeros(4, np.int32)
    pos[0] = len(prompt)
    active = np.zeros(4, bool)
    active[0] = True
    tables = np.zeros((4, ex.max_blocks_per_seq), np.int32)
    tables[0] = table
    cur = np.zeros(4, np.int32)
    cur[0] = tok
    batch = SamplingBatch(
        np.zeros(4, np.float32), np.zeros(4, np.int32),
        np.ones(4, np.float32), np.zeros(4, np.uint32), np.zeros(4, np.int32),
    )
    for _ in range(3):
        t, _ = ex.decode(cur, pos, tables, active, batch)
        cur[0] = t[0]
        pos[0] += 1
        got.append(int(t[0]))
    assert got == want
