"""Subprocess body for the distributed-tracing multi-process test: skew
this process's monotonic clock by a LARGE constant (seconds — far above
any real RPC delay), then serve one fake-engine instance against the
parent process's master until stdin closes.

The skew is the point: span timestamps and heartbeat clock stamps both
come from the patched clock, so the parent's assembled trace is only
causally ordered if the master's heartbeat-derived ClockSync offsets
actually cancel the skew. A real fleet's instances have exactly this
property — same clock rate, arbitrary per-host base.

Argv: master_rpc_addr name instance_type skew_s.
"""

import os
import sys
import time


def main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("XLLM_TRACE", "1")
    master_rpc, name, itype = sys.argv[1], sys.argv[2], sys.argv[3]
    skew_s = float(sys.argv[4])

    # Patch BEFORE any xllm import: modules call time.monotonic() by
    # attribute, so this rebases the whole process's monotonic domain
    # (spans, heartbeat send stamps, echo stamps) consistently.
    real_monotonic = time.monotonic
    time.monotonic = lambda: real_monotonic() + skew_s

    from xllm_service_tpu.api import FakeEngine
    from xllm_service_tpu.api.instance import InstanceServer
    from xllm_service_tpu.common.config import EngineConfig

    srv = InstanceServer(
        EngineConfig(
            model="fake-echo", instance_name=name, instance_type=itype,
            block_size=16,
        ),
        master_rpc_addr=master_rpc, heartbeat_interval_s=0.2,
        engine=FakeEngine(token_delay_s=0.002, ttft_ms=1.0),
    )
    srv.start()
    print(f"TRACE_PROC_UP {name} {srv.address}", flush=True)
    sys.stdin.read()  # parent closes stdin at teardown
    srv.stop()


if __name__ == "__main__":
    main()
