"""Presence/frequency penalties (OpenAI sampling surface): on-device
generated-token histograms fused into the sampling step.

Semantics (vLLM-style, documented in SamplingParams): penalties cover
GENERATED tokens only; presence subtracts a flat amount per seen token,
frequency per occurrence. The histogram is donated through every decode
step and (re)seeded from the sequence's generation history on admission —
so preemption-resume and PD import keep penalty state exact.
"""

import threading

import numpy as np
import jax.numpy as jnp

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops import sampling as sampling_ops
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor


def test_apply_penalties_math():
    R, V = 2, 16
    logits = jnp.zeros((R, V))
    counts = (
        jnp.zeros((R, V), jnp.int32).at[0, 3].set(2).at[1, 5].set(1)
    )
    out = np.asarray(
        sampling_ops.apply_penalties(
            logits, counts,
            jnp.asarray([0.5, 0.0]), jnp.asarray([0.25, 1.0]),
        )
    )
    assert np.isclose(out[0, 3], -0.5 - 0.25 * 2)
    assert np.isclose(out[1, 5], -1.0)
    assert np.isclose(out[0, 0], 0.0)  # unseen tokens untouched
    # zero penalties = exact no-op (the runtime-skip branch)
    same = np.asarray(
        sampling_ops.apply_penalties(
            logits, counts, jnp.zeros(R), jnp.zeros(R)
        )
    )
    np.testing.assert_array_equal(same, np.asarray(logits))


def _engine():
    cfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=16,
        num_blocks=64, max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64, 128],
    )
    ex = ModelExecutor(cfg, init_seed=5)
    return InferenceEngine(cfg, executor=ex)


def _run(eng, rid, pp, fp, n=24, prompt=(5, 9, 13)):
    toks, done = [], threading.Event()

    def cb(out):
        for s in out.outputs:
            toks.extend(s.token_ids)
        if out.finished:
            done.set()
        return True

    eng.add_request(
        EngineRequest(
            request_id=rid, prompt_token_ids=list(prompt),
            sampling=SamplingParams(
                temperature=0.0, max_new_tokens=n,
                presence_penalty=pp, frequency_penalty=fp,
            ),
            callback=cb,
        )
    )
    assert done.wait(120)
    return toks


def test_engine_frequency_penalty_kills_repeats():
    eng = _engine()
    eng.start()
    try:
        base = _run(eng, "base", 0.0, 0.0)
        pen = _run(eng, "pen", 0.0, 50.0)
    finally:
        eng.stop()
    # A huge frequency penalty makes greedy argmax unable to repeat ANY
    # generated token; the unpenalized tiny model repeats.
    assert len(set(pen)) == len(pen), pen
    assert len(set(base)) < len(base)


def test_zero_penalty_is_bit_identical():
    """Adding the penalty machinery must not perturb the no-penalty path."""
    eng = _engine()
    eng.start()
    try:
        a = _run(eng, "a", 0.0, 0.0, n=12)
        b = _run(eng, "b", 0.0, 0.0, n=12)
    finally:
        eng.stop()
    assert a == b


def test_counts_reseed_on_slot_reuse():
    """A new request reusing a slot must not inherit the previous
    occupant's histogram (seed_slot_counts clears the row)."""
    eng = _engine()
    eng.start()
    try:
        first = _run(eng, "one", 0.0, 50.0, n=10)
        second = _run(eng, "two", 0.0, 50.0, n=10)
    finally:
        eng.stop()
    # Same prompt + params: identical streams — any count leakage from
    # the first run would shift the second.
    assert first == second


def test_prefill_sampling_applies_penalties():
    """The token sampled at (re)admission sees presence/frequency penalties
    from prior generated tokens (ADVICE r2: previously the first token per
    preemption/PD-resume escaped penalties)."""
    from xllm_service_tpu.runtime.executor import PrefillItem

    cfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=16,
        num_blocks=32, max_running_requests=4, max_seq_len=128,
        prefill_buckets=[32],
    )
    ex = ModelExecutor(cfg, init_seed=11)
    table = np.zeros((8,), np.int32)
    table[0] = 1
    base = PrefillItem(
        token_ids=np.asarray([5, 9, 13], np.int32),
        start_pos=0, block_table=table, temperature=0.0,
    )
    [(tok0, _)] = ex.prefill_batch([base])

    penalized = PrefillItem(
        token_ids=np.asarray([5, 9, 13], np.int32),
        start_pos=0, block_table=table, temperature=0.0,
        presence=50.0, frequency=50.0,
        prior_tokens=np.asarray([tok0], np.int32),
    )
    [(tok1, _)] = ex.prefill_batch([penalized])
    assert tok1 != tok0
