"""Fleet-wide distributed tracing tests (ISSUE 17):

  * obs.spans.ClockSync — heartbeat-fed monotonic-offset bounds and the
    midpoint/min-upper/degenerate estimates;
  * obs.flight.SpanRing — bounded ring, attempt-id collapse, stats;
  * obs.flight.FlightRecorder — anomaly dumps, rate limiting, rotation;
  * assemble_trace + blame_stages — cross-process alignment, PD blame
    edges (handoff must be attributable), colocated fallbacks;
  * build_timeline on redispatch loops — durations attribute to the
    retry attempt, not smeared over the first one (ISSUE 17 satellite);
  * RequestTracer keep-count rotation chain (trace.jsonl.1..N);
  * absorb_exposition kind conflicts — deterministic skip + returned
    names + the master's scrape conflict counter;
  * an in-process PD cluster: GET /trace/<srid> assembles one timeline
    spanning master + prefill + decode, xllm_cluster_scrape_ms rides the
    aggregated /metrics, and XLLM_TRACE=0 leaves the token stream
    byte-identical with zero instance-side span work;
  * a REAL multi-process PD fleet (tests/_trace_proc.py) with seconds of
    injected clock skew per instance: the assembled trace must span >= 3
    processes with zero negative inter-process durations.
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time
from collections import OrderedDict

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from prom_parser import parse_metrics  # noqa: E402

from xllm_service_tpu.api import FakeEngine, Master
from xllm_service_tpu.api.instance import InstanceServer
from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
from xllm_service_tpu.coordination import MemoryStore
from xllm_service_tpu.obs import MetricsRegistry, absorb_exposition
from xllm_service_tpu.obs.flight import FlightRecorder, SpanRing
from xllm_service_tpu.obs.spans import (
    ALL_SPAN_STAGES,
    ClockSync,
    assemble_trace,
    blame_stages,
    build_timeline,
    stage_durations_ms,
    trace_to_chrome,
)
from xllm_service_tpu.service.request import RequestTracer


def wait_until(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def http_get_json(addr, path, timeout=10.0):
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, (json.loads(data) if data else {})


def http_post(addr, path, body, timeout=30.0):
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request(
        "POST", path, body=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, (json.loads(data) if data else {})


def stream_completion(addr, body, timeout=30.0):
    """POST a streamed completion; returns (srid, [event dicts])."""
    host, _, port = addr.partition(":")
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    conn.request(
        "POST", "/v1/completions",
        body=json.dumps(dict(body, stream=True)).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = conn.getresponse()
    assert resp.status == 200, resp.read()
    srid, events = "", []
    for raw in resp:
        ln = raw.decode().strip()
        if not ln.startswith("data: "):
            continue
        payload = ln[len("data: "):]
        if payload == "[DONE]":
            break
        ev = json.loads(payload)
        srid = srid or str(ev.get("id") or "")
        events.append(ev)
    conn.close()
    return srid, events


def pull_trace_until_finished(addr, srid, timeout=15.0):
    """GET /trace/<srid> until the terminal `finish` span lands — the
    master's terminal bookkeeping runs on the lane just AFTER the
    response body is written, so an immediate pull can race it."""
    tr = {}

    def finished():
        nonlocal tr
        code, body = http_get_json(addr, f"/trace/{srid}")
        if code != 200:
            return False
        tr = body
        return any(r.get("stage") == "finish" for r in body.get("spans", []))

    assert wait_until(finished, timeout=timeout), (
        [r.get("stage") for r in tr.get("spans", [])]
    )
    return tr


def first_stage_times(merged):
    """stage -> t_mono_ms of its FIRST record in an assembled trace."""
    first = OrderedDict()
    for rec in merged:
        st = rec.get("stage", "")
        if st and st not in first:
            first[st] = float(rec.get("t_mono_ms", 0.0))
    return first


# --------------------------------------------------------------------- #
# clock alignment units
# --------------------------------------------------------------------- #


class TestClockSync:
    def test_midpoint_of_bounds(self):
        cs = ClockSync()
        cs.sample_upper(10.0)  # o + d_forward
        cs.sample_lower(4.0)   # o - d_backward
        assert cs.offset_ms() == 7.0
        assert cs.samples == 2
        j = cs.to_json()
        assert j["offset_ms"] == 7.0
        assert j["upper_ms"] == 10.0 and j["lower_ms"] == 4.0

    def test_tightest_bounds_win(self):
        cs = ClockSync()
        for u in (12.0, 9.0, 15.0):
            cs.sample_upper(u)
        for lo in (1.0, 5.0, 3.0):
            cs.sample_lower(lo)
        # min upper 9, max lower 5 -> midpoint 7
        assert cs.offset_ms() == 7.0

    def test_upper_only_degrades_to_min_upper(self):
        cs = ClockSync()
        cs.sample_upper(12.0)
        cs.sample_upper(8.0)
        assert cs.offset_ms() == 8.0

    def test_no_samples_is_zero(self):
        assert ClockSync().offset_ms() == 0.0

    def test_crossed_bounds_fall_back_to_upper(self):
        # lower > upper (clock stepped between beats): the intersection
        # is empty; the estimator must not invent a midpoint outside it.
        cs = ClockSync()
        cs.sample_upper(5.0)
        cs.sample_lower(9.0)
        assert cs.offset_ms() == 5.0

    def test_window_bounds_memory(self):
        cs = ClockSync()
        cs.sample_upper(1.0)  # tight early bound ...
        for _ in range(ClockSync.WINDOW):
            cs.sample_upper(50.0)  # ... aged out by a full window
        assert cs.offset_ms() == 50.0


# --------------------------------------------------------------------- #
# span ring + flight recorder units
# --------------------------------------------------------------------- #


class TestSpanRing:
    def test_ring_is_bounded(self):
        ring = SpanRing("p0", capacity=4)
        for i in range(10):
            ring.emit(f"r{i}", "admit", idx=i)
        snap = ring.snapshot()
        assert len(snap) == 4
        assert [r["idx"] for r in snap] == [6, 7, 8, 9]
        st = ring.stats()
        assert st["size"] == 4 and st["emitted"] == 10
        assert st["capacity"] == 4 and st["process"] == "p0"

    def test_for_request_collapses_attempt_ids(self):
        ring = SpanRing("p0")
        ring.emit("cmpl-1#r1", "admit")
        ring.emit("cmpl-1#r2", "admit")
        ring.emit("cmpl-2", "admit")
        assert len(ring.for_request("cmpl-1")) == 2
        assert len(ring.for_request("cmpl-1#r2")) == 2
        assert len(ring.for_request("cmpl-2")) == 1

    def test_none_fields_dropped(self):
        ring = SpanRing("p0")
        ring.emit("r", "admit", peer=None, blocks=3)
        rec = ring.snapshot()[0]
        assert "peer" not in rec and rec["blocks"] == 3


class TestFlightRecorder:
    def test_trigger_dumps_ring(self, tmp_path):
        ring = SpanRing("p0")
        ring.emit("r1", "admit")
        fr = FlightRecorder(ring, str(tmp_path), min_interval_s=0.0)
        path = fr.trigger("slo_breach", "r1", ttft_ms=912.0)
        assert path and os.path.exists(path)
        body = json.load(open(path))
        assert body["reason"] == "slo_breach"
        assert body["service_request_id"] == "r1"
        assert body["context"]["ttft_ms"] == 912.0
        # the trigger itself lands in the ring, so the dump records it
        stages = [r["stage"] for r in body["spans"]]
        assert stages == ["admit", "flight_dump"]
        assert "flight_dump" in ALL_SPAN_STAGES

    def test_rate_limit_counts_but_skips_dump(self, tmp_path):
        reg = MetricsRegistry()
        ring = SpanRing("p0")
        fr = FlightRecorder(
            ring, str(tmp_path), min_interval_s=60.0, registry=reg,
        )
        assert fr.trigger("breaker_eject", "r1") is not None
        assert fr.trigger("breaker_eject", "r2") is None  # rate-limited
        files = [n for n in os.listdir(tmp_path) if n.startswith("flight-")]
        assert len(files) == 1
        # ... but the counter and the ring record BOTH triggers
        assert sum(
            1 for r in ring.snapshot() if r["stage"] == "flight_dump"
        ) == 2
        fams = parse_metrics(reg.render())
        assert sum(fams["xllm_flight_dumps_total"].values(
            reason="breaker_eject"
        )) == 2

    def test_rotation_keeps_newest(self, tmp_path):
        ring = SpanRing("p0")
        fr = FlightRecorder(ring, str(tmp_path), keep=2, min_interval_s=0.0)
        for i in range(4):
            assert fr.trigger("fenced_rpc", f"r{i}")
        files = sorted(
            n for n in os.listdir(tmp_path) if n.startswith("flight-")
        )
        assert files == ["flight-000003.json", "flight-000004.json"]

    def test_never_raises(self, tmp_path):
        ring = SpanRing("p0")
        # unwritable directory: trigger must swallow the failure
        fr = FlightRecorder(
            ring, os.path.join(str(tmp_path), "f\0bad"), min_interval_s=0.0,
        )
        assert fr.trigger("kv_handoff_stall", "r1") is None


# --------------------------------------------------------------------- #
# assembly + blame units
# --------------------------------------------------------------------- #


def _rec(stage, t, srid="cmpl-x", **kw):
    return {
        "type": "stage", "service_request_id": srid, "stage": stage,
        "t_mono_ms": float(t), "timestamp_ms": 0, **kw,
    }


class TestAssembleTrace:
    def test_offsets_cancel_injected_skew(self):
        # prefill clock is 5s BEHIND the master, decode 3s AHEAD; the
        # provided offsets (o = master - instance) must realign them so
        # every cross-process causal edge is non-negative.
        master = [_rec("receive", 100.0), _rec("dispatch", 110.0),
                  _rec("first_token", 130.0), _rec("finish", 160.0)]
        prefill = [_rec("admit", -4885.0), _rec("handoff_send", -4875.0)]
        decode = [_rec("decode_admit", 3140.0)]
        merged = assemble_trace(
            "master", master,
            [("pf", prefill, 5000.0), ("dec", decode, -3000.0)],
        )
        assert [r["process"] for r in merged] == [
            "master", "master", "pf", "pf", "master", "dec", "master",
        ]
        first = first_stage_times(merged)
        chain = ("receive", "dispatch", "admit", "handoff_send",
                 "decode_admit", "finish")
        for a, b in zip(chain, chain[1:]):
            assert first[b] - first[a] >= 0.0, (a, b, first)

    def test_tie_keeps_master_before_instance(self):
        merged = assemble_trace(
            "master", [_rec("dispatch", 50.0)],
            [("pf", [_rec("admit", 50.0)], 0.0)],
        )
        assert [r["process"] for r in merged] == ["master", "pf"]

    def test_chrome_export_one_track_per_process(self):
        merged = assemble_trace(
            "master", [_rec("receive", 0.0), _rec("finish", 10.0)],
            [("pf", [_rec("admit", 2.0)], 0.0),
             ("dec", [_rec("decode_admit", 5.0)], 0.0)],
        )
        chrome = trace_to_chrome(merged)
        metas = [e for e in chrome["traceEvents"] if e["ph"] == "M"]
        assert {e["args"]["name"] for e in metas} == {"master", "pf", "dec"}
        assert len({e["pid"] for e in metas}) == 3


class TestBlameStages:
    def test_pd_trace_blames_handoff(self):
        merged = assemble_trace(
            "master",
            [_rec("receive", 0.0), _rec("dispatch", 10.0),
             _rec("first_token", 35.0), _rec("finish", 160.0)],
            [("pf", [_rec("admit", 12.0), _rec("handoff_send", 40.0)], 0.0),
             ("dec", [_rec("decode_admit", 140.0)], 0.0)],
        )
        blame = blame_stages(merged)
        assert blame["queue"] == 10.0
        assert blame["prefill"] == 28.0
        assert blame["handoff"] == 100.0
        # decode anchors at decode_admit, NOT first_token: the prefill
        # side pushes the first token BEFORE the handoff, so that anchor
        # would double-count the whole handoff window.
        assert blame["decode"] == 20.0
        assert blame["total"] == 160.0
        assert blame["host_gap"] == 160.0 - (10.0 + 28.0 + 100.0 + 20.0)
        assert max(
            ("queue", "prefill", "handoff", "decode", "host_gap"),
            key=lambda k: blame[k],
        ) == "handoff"

    def test_colocated_fallbacks(self):
        blame = blame_stages([
            _rec("receive", 0.0), _rec("dispatch", 5.0),
            _rec("first_token", 30.0), _rec("finish", 50.0),
        ])
        assert blame["queue"] == 5.0
        assert blame["prefill"] == 25.0   # dispatch -> first_token
        assert blame["handoff"] == 0.0
        assert blame["decode"] == 20.0    # first_token -> finish
        assert blame["host_gap"] == 0.0
        assert blame["total"] == 50.0

    def test_empty_trace(self):
        blame = blame_stages([])
        assert blame["total"] == 0.0 and blame["host_gap"] == 0.0


class TestRedispatchTimeline:
    """ISSUE 17 satellite: a fault-replayed request's spans must charge
    each inter-stage gap to the attempt that was actually running."""

    def test_durations_attribute_to_retry_attempt(self):
        recs = [
            _rec("receive", 0.0),
            _rec("dispatch", 5.0, attempt=1),
            _rec("redispatch", 45.0, attempt=2),   # attempt 1 died at 45
            _rec("dispatch", 47.0, attempt=2),
            _rec("first_token", 60.0),
            _rec("finish", 80.0),
        ]
        timeline = build_timeline(recs)["cmpl-x"]
        durs = stage_durations_ms(timeline)
        assert [s for s, _ in durs] == [
            "receive", "dispatch", "redispatch", "dispatch",
            "first_token", "finish",
        ]
        by_attempt = {}
        for (stage, dur), rec in zip(durs, timeline):
            if stage == "dispatch":
                by_attempt[rec["attempt"]] = dur
        # 40ms of dead first attempt stays on attempt 1; the retry is
        # only charged its own 13ms to first token.
        assert by_attempt == {1: 40.0, 2: 13.0}

    def test_attempt_wire_ids_collapse_into_one_timeline(self):
        ring = SpanRing("pf")
        ring.emit("cmpl-9#r1", "admit")
        ring.emit("cmpl-9#r2", "admit")
        merged = assemble_trace(
            "master",
            [_rec("dispatch", 0.0, srid="cmpl-9"),
             _rec("redispatch", 1.0, srid="cmpl-9")],
            [("pf", ring.for_request("cmpl-9"), 0.0)],
        )
        assert len(merged) == 4

    def test_non_monotonic_still_rejected(self):
        with pytest.raises(ValueError, match="non-monotonic"):
            build_timeline([_rec("receive", 10.0), _rec("finish", 5.0)])


# --------------------------------------------------------------------- #
# tracer rotation chain (ISSUE 17 satellite)
# --------------------------------------------------------------------- #


class TestTracerRotationChain:
    def test_keep_count_chain(self, tmp_path):
        tracer = RequestTracer(
            str(tmp_path), enabled=True, max_bytes=600, keep=3,
        )
        for i in range(120):
            tracer.stage(f"r{i:04d}", "receive", pad="x" * 40)
        tracer.close()
        for n in (1, 2, 3):
            assert (tmp_path / f"trace.jsonl.{n}").exists(), n
        assert not (tmp_path / "trace.jsonl.4").exists()
        assert tracer.dropped == 0

        def first_id(path):
            with open(path) as f:
                return json.loads(f.readline())["service_request_id"]

        # the chain is ordered: .1 is the newest rotated window, .N the
        # oldest still kept
        ids = [
            first_id(tmp_path / f"trace.jsonl.{n}") for n in (1, 2, 3)
        ]
        assert ids == sorted(ids, reverse=True), ids
        # the live file (possibly empty right after a rotation) only ever
        # holds records NEWER than the whole rotated chain
        with open(tmp_path / "trace.jsonl") as f:
            line = f.readline()
        if line:
            assert json.loads(line)["service_request_id"] > ids[0]

    def test_default_keep_one_drops_older(self, tmp_path):
        tracer = RequestTracer(str(tmp_path), enabled=True, max_bytes=600)
        for i in range(120):
            tracer.stage(f"r{i:04d}", "receive", pad="x" * 40)
        tracer.close()
        assert (tmp_path / "trace.jsonl.1").exists()
        assert not (tmp_path / "trace.jsonl.2").exists()


# --------------------------------------------------------------------- #
# prom merge kind conflicts (ISSUE 17 satellite)
# --------------------------------------------------------------------- #


class TestAbsorbKindConflicts:
    GAUGE = "# TYPE xllm_t_conf gauge\nxllm_t_conf 1\n"
    COUNTER = "# TYPE xllm_t_conf_total counter\nxllm_t_conf_total 1\n"
    BAD = "# TYPE xllm_t_conf counter\nxllm_t_conf 7\n"

    def test_conflicting_family_skipped_and_reported(self):
        fams = OrderedDict()
        assert absorb_exposition(fams, self.GAUGE, {"instance": "a"}) == []
        conflicts = absorb_exposition(fams, self.BAD, {"instance": "b"})
        assert conflicts == ["xllm_t_conf"]
        kind, _help, samples = fams["xllm_t_conf"]
        # first-seen kind wins; the conflicting samples are NOT merged
        assert kind == "gauge"
        assert len(samples) == 1 and 'instance="a"' in samples[0][0]

    def test_first_seen_wins_regardless_of_order(self):
        fams = OrderedDict()
        assert absorb_exposition(fams, self.BAD, {"instance": "b"}) == []
        assert absorb_exposition(
            fams, self.GAUGE, {"instance": "a"}
        ) == ["xllm_t_conf"]
        assert fams["xllm_t_conf"][0] == "counter"

    def test_clean_merge_reports_nothing(self):
        fams = OrderedDict()
        assert absorb_exposition(fams, self.GAUGE, {"instance": "a"}) == []
        assert absorb_exposition(fams, self.GAUGE, {"instance": "b"}) == []
        assert len(fams["xllm_t_conf"][2]) == 2


# --------------------------------------------------------------------- #
# in-process PD cluster: collector, scrape histogram, trace-off diff
# --------------------------------------------------------------------- #


def _make_pd_stack(tmp_path, prefix, trace_env):
    saved = os.environ.get("XLLM_TRACE")
    os.environ["XLLM_TRACE"] = trace_env
    try:
        store = MemoryStore(clock=lambda: 0.0)
        cfg = ServiceConfig(
            host="127.0.0.1", http_port=0, rpc_port=0,
            heartbeat_interval_s=0.2, master_lease_ttl_s=2.0,
            num_ordered_output_streams=8, block_size=16,
            trace_dir=str(tmp_path / f"{prefix}-trace"),
        )
        master = Master(cfg, store=store)
        master.start()
        servers = []
        for name, itype in (
            (f"{prefix}-pf", "PREFILL"), (f"{prefix}-dec", "DECODE"),
        ):
            srv = InstanceServer(
                EngineConfig(
                    model="fake-echo", instance_name=name,
                    instance_type=itype, block_size=16,
                ),
                master_rpc_addr=master.rpc_address,
                heartbeat_interval_s=0.2,
                engine=FakeEngine(token_delay_s=0.002, ttft_ms=1.0),
            )
            srv.start()
            servers.append(srv)
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts() == (1, 1, 0)
        )
        return store, master, servers
    finally:
        if saved is None:
            os.environ.pop("XLLM_TRACE", None)
        else:
            os.environ["XLLM_TRACE"] = saved


def _teardown_stack(store, master, servers):
    for srv in servers:
        try:
            srv.stop()
        except Exception:
            pass
    master.stop()
    store.close()


@pytest.fixture(scope="module")
def traced_pd_cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("traced-pd")
    store, master, servers = _make_pd_stack(tmp, "tpd", "1")
    yield master, servers
    _teardown_stack(store, master, servers)


class TestTraceCollector:
    def test_assembled_trace_spans_three_processes(self, traced_pd_cluster):
        master, _servers = traced_pd_cluster
        srid, events = stream_completion(
            master.http_address,
            {"model": "fake-echo", "prompt": "trace me end to end",
             "max_tokens": 6, "temperature": 0.0},
        )
        assert srid and events
        tr = pull_trace_until_finished(master.http_address, srid)
        assert set(tr["processes"]) >= {"master", "tpd-pf", "tpd-dec"}
        stages = {r["stage"] for r in tr["spans"]}
        assert {"receive", "dispatch", "admit", "handoff_send",
                "decode_admit", "finish"} <= stages, stages
        first = first_stage_times(tr["spans"])
        chain = ("receive", "dispatch", "admit", "handoff_send",
                 "decode_admit")
        for a, b in zip(chain, chain[1:]):
            assert first[b] - first[a] >= 0.0, (a, b, first)
        blame = tr["blame_ms"]
        assert blame["total"] > 0.0
        assert all(
            blame[k] >= 0.0
            for k in ("queue", "prefill", "handoff", "decode", "host_gap")
        )
        # Perfetto export carries one named track per process
        metas = [
            e for e in tr["chrome"]["traceEvents"] if e["ph"] == "M"
        ]
        assert {"master", "tpd-pf", "tpd-dec"} <= {
            e["args"]["name"] for e in metas
        }

    def test_unknown_srid_404(self, traced_pd_cluster):
        master, _servers = traced_pd_cluster
        code, _body = http_get_json(
            master.http_address, "/trace/cmpl-never-dispatched"
        )
        assert code == 404

    def test_instance_trace_route_serves_ring(self, traced_pd_cluster):
        master, servers = traced_pd_cluster
        srid, _events = stream_completion(
            master.http_address,
            {"model": "fake-echo", "prompt": "ring route", "max_tokens": 4,
             "temperature": 0.0},
        )
        pf = servers[0]
        code, body = http_get_json(pf.address, f"/trace?srid={srid}")
        assert code == 200
        assert body["process"] == "tpd-pf"
        assert any(r["stage"] == "admit" for r in body["spans"])

    def test_scrape_ms_histogram_in_aggregation(self, traced_pd_cluster):
        """ISSUE 17 satellite: per-instance scrape latency rides the
        master's aggregated /metrics as a labelled histogram."""
        master, _servers = traced_pd_cluster
        host, _, port = master.http_address.partition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=10.0)
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        text = resp.read().decode()
        conn.close()
        assert resp.status == 200
        fams = parse_metrics(text)
        fam = fams["xllm_cluster_scrape_ms"]
        assert fam.kind == "histogram"
        for inst in ("tpd-pf", "tpd-dec"):
            counts = [
                v for n, labels, v in fam.samples
                if n.endswith("_count") and labels.get("instance") == inst
            ]
            assert counts and counts[0] >= 1, inst


class TestTracingOffDifferential:
    def test_disabled_tracing_is_invisible_on_the_token_path(
        self, tmp_path,
    ):
        """XLLM_TRACE=0 must leave the token stream byte-identical and do
        ZERO instance-side span work (no hook installed, nothing
        emitted) — tracing is free when it is off."""
        req = {
            "model": "fake-echo", "prompt": "differential stream",
            "max_tokens": 6, "temperature": 0.0,
        }

        def run(trace_env, prefix):
            store, master, servers = _make_pd_stack(
                tmp_path, prefix, trace_env,
            )
            try:
                _srid, events = stream_completion(master.http_address, req)
                emitted = sum(
                    srv.span_ring.stats()["emitted"] for srv in servers
                )
                hooks = [
                    getattr(srv.engine, "span_hook", None)
                    for srv in servers
                ]
                return events, emitted, hooks
            finally:
                _teardown_stack(store, master, servers)

        ev_on, emitted_on, hooks_on = run("1", "don")
        ev_off, emitted_off, hooks_off = run("0", "doff")

        # the streams are byte-identical once the per-run envelope ids
        # (request id, wall-clock stamp) are masked
        def normalize(events):
            out = []
            for ev in events:
                ev = dict(ev)
                ev.pop("id", None)
                ev.pop("created", None)
                out.append(json.dumps(ev, sort_keys=True))
            return out

        assert normalize(ev_on) == normalize(ev_off)
        assert emitted_on > 0
        assert emitted_off == 0
        assert all(h is not None for h in hooks_on)
        assert all(h is None for h in hooks_off)


# --------------------------------------------------------------------- #
# master scrape-conflict counter (satellite, e2e half)
# --------------------------------------------------------------------- #


class TestScrapeConflictCounter:
    def test_conflicting_instance_exposition_counted(
        self, traced_pd_cluster,
    ):
        """Point one instance's scrape address at a stub that serves a
        kind-conflicting family: the aggregated exposition must stay
        strictly parseable (family skipped) and the conflict counter must
        tick (tests/test_obs.py scrape-failure precedent)."""
        import http.server

        master, _servers = traced_pd_cluster
        # conflicts with the master-local gauge of the same name
        body = (
            "# TYPE xllm_service_inflight_requests counter\n"
            "xllm_service_inflight_requests 3\n"
        ).encode()

        class Stub(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Stub)
        th = threading.Thread(target=httpd.serve_forever, daemon=True)
        th.start()
        mgr = master.scheduler.instance_mgr
        meta = mgr.get_instance("tpd-pf")
        orig = meta.http_address
        meta.http_address = "127.0.0.1:%d" % httpd.server_address[1]
        try:
            before = master._m_scrape_conflicts.get()
            host, _, port = master.http_address.partition(":")
            conn = http.client.HTTPConnection(host, int(port), timeout=10.0)
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            text = resp.read().decode()
            conn.close()
            assert resp.status == 200
            fams = parse_metrics(text)  # still strictly parseable
            assert fams["xllm_service_inflight_requests"].kind == "gauge"
            assert master._m_scrape_conflicts.get() > before
        finally:
            meta.http_address = orig
            httpd.shutdown()
            httpd.server_close()
            th.join(timeout=5)


# --------------------------------------------------------------------- #
# REAL multi-process fleet with injected clock skew
# --------------------------------------------------------------------- #


class TestMultiProcessTrace:
    def test_skewed_fleet_assembles_causally(self, tmp_path):
        """Two instance processes with +4s / -3s monotonic skew: the
        heartbeat clock alignment must cancel seconds of skew down to
        RPC-delay precision, so the assembled trace spans 3 processes
        with ZERO negative inter-process durations (ISSUE 17
        acceptance)."""
        helper = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "_trace_proc.py"
        )
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env["XLLM_TRACE"] = "1"
        store = MemoryStore(clock=lambda: 0.0)
        cfg = ServiceConfig(
            host="127.0.0.1", http_port=0, rpc_port=0,
            heartbeat_interval_s=0.2, master_lease_ttl_s=5.0,
            num_ordered_output_streams=8, block_size=16,
            trace_dir=str(tmp_path / "mp-trace"),
        )
        master = Master(cfg, store=store)
        master.start()
        skews = {"mp-pf": 4.0, "mp-dec": -3.0}
        procs = []
        try:
            for name, itype in (("mp-pf", "PREFILL"), ("mp-dec", "DECODE")):
                procs.append(subprocess.Popen(
                    [sys.executable, helper, master.rpc_address, name,
                     itype, str(skews[name])],
                    env=env, stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True,
                ))
            for p in procs:
                deadline = time.monotonic() + 120
                line = ""
                while time.monotonic() < deadline:
                    line = p.stdout.readline()
                    if not line or line.startswith("TRACE_PROC_UP"):
                        break
                assert line.startswith("TRACE_PROC_UP"), (
                    f"helper died: {line!r}"
                )
            assert wait_until(
                lambda: master.scheduler.instance_mgr.counts() == (1, 1, 0),
                timeout=30,
            )

            # Clock convergence: offsets must approach -skew (o = master
            # - instance) before span alignment means anything. The
            # lower bound needs the SECOND beat (echoed reply stamp).
            def aligned():
                return all(
                    abs(master.clock_offset_ms(n) + skews[n] * 1000.0)
                    < 500.0
                    for n in skews
                )

            assert wait_until(aligned, timeout=30), {
                n: master.clock_offset_ms(n) for n in skews
            }

            srid, events = stream_completion(
                master.http_address,
                {"model": "fake-echo", "prompt": "skewed fleet trace",
                 "max_tokens": 4, "temperature": 0.0},
                timeout=60.0,
            )
            assert srid and events
            tr = pull_trace_until_finished(
                master.http_address, srid, timeout=30.0,
            )
            assert len(set(tr["processes"])) >= 3
            assert set(tr["processes"]) >= {"master", "mp-pf", "mp-dec"}
            for n in skews:
                assert abs(
                    tr["offsets_ms"][n] + skews[n] * 1000.0
                ) < 500.0, tr["offsets_ms"]

            # zero negative inter-process durations along the causal
            # chain, despite 7s of relative skew between the instances
            first = first_stage_times(tr["spans"])
            chain = ("receive", "dispatch", "admit", "handoff_send",
                     "decode_admit")
            for a, b in zip(chain, chain[1:]):
                assert a in first and b in first, (first.keys())
                assert first[b] - first[a] >= 0.0, (a, b, first)
            fin = first.get("finish")
            assert fin is not None and fin - first["decode_admit"] >= 0.0
            blame = tr["blame_ms"]
            assert blame["total"] > 0.0
            assert all(
                blame[k] >= 0.0 for k in
                ("queue", "prefill", "handoff", "decode", "host_gap")
            )
        finally:
            for p in procs:
                try:
                    p.stdin.close()
                except Exception:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=15)
                except Exception:
                    p.kill()
            master.stop()
            store.close()
