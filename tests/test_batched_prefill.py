"""Batched/overlapped prefill (round-1 weak item 4).

Admission prefills concurrent waiting requests in shared compiled steps:
same-bucket prompts ride ONE device call, so TTFT under a burst stacks
sub-linearly instead of one-jit-call-per-request. Parity with the
sequential path must be exact (greedy).
"""

import threading

import numpy as np

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor, PrefillItem


def _cfg(**kw):
    base = dict(
        model="llama3-tiny",
        num_blocks=96,
        max_running_requests=16,
        max_seq_len=256,
        prefill_buckets=[32, 64],
    )
    base.update(kw)
    return EngineConfig(**base)


def test_prefill_batch_matches_sequential():
    """prefill_batch over mixed-length items == one-at-a-time prefill."""
    exe_a = ModelExecutor(_cfg(), init_seed=3)
    exe_b = ModelExecutor(_cfg(), init_seed=3)

    rng = np.random.default_rng(0)
    items = []
    base_block = 1
    for i, n in enumerate([5, 17, 33, 9]):
        table = np.zeros((exe_a.max_blocks_per_seq,), np.int32)
        nb = (n + 1 + exe_a.block_size - 1) // exe_a.block_size
        table[:nb] = np.arange(base_block, base_block + nb)
        base_block += nb
        items.append(
            PrefillItem(
                token_ids=rng.integers(0, 512, n).astype(np.int32),
                start_pos=0,
                block_table=table,
            )
        )

    seq_results = [
        exe_a.prefill(it.token_ids, it.start_pos, it.block_table)
        for it in items
    ]
    batch_results = exe_b.prefill_batch(items)
    # Tokens must match exactly; logprobs only to float tolerance (the P=1
    # and P=4 programs reduce in different orders).
    assert [t for t, _ in seq_results] == [t for t, _ in batch_results]
    np.testing.assert_allclose(
        [l for _, l in seq_results], [l for _, l in batch_results], atol=1e-4
    )
    # Caches identical outside garbage block 0 (masked/padded rows collide
    # there with nondeterministic winners — by design).
    np.testing.assert_array_equal(
        np.asarray(exe_a.k_cache.data)[:, 1:], np.asarray(exe_b.k_cache.data)[:, 1:]
    )


def test_burst_shares_compiled_steps():
    """8 concurrent same-bucket prompts are admitted in at most 2 batched
    prefill calls (not 8 sequential ones)."""
    exe = ModelExecutor(_cfg(), init_seed=1)
    calls = []
    orig = exe._prefill_group

    def counting(group):
        calls.append(len(group))
        return orig(group)

    exe._prefill_group = counting

    eng = InferenceEngine(_cfg(), executor=exe)
    done = []
    rng = np.random.default_rng(7)
    # Enqueue BEFORE starting the engine so one _admit sees the full burst.
    for i in range(8):
        ev = threading.Event()
        done.append(ev)

        def cb(out, ev=ev):
            if out.finished:
                ev.set()
            return True

        eng.add_request(
            EngineRequest(
                request_id=f"b{i}",
                prompt_token_ids=[int(t) for t in rng.integers(0, 512, 20 + i)],
                sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
                callback=cb,
            )
        )
    eng.start()
    try:
        for ev in done:
            assert ev.wait(120.0)
    finally:
        eng.stop()
    assert sum(calls) == 8  # every request prefilled exactly once
    assert len(calls) <= 2, f"burst used {len(calls)} prefill steps: {calls}"
    assert max(calls) == 8


def test_engine_batched_greedy_parity():
    """Concurrent requests through the batching engine produce the same
    greedy streams as the same requests run one at a time."""
    prompts = [
        [int(t) for t in np.random.default_rng(i).integers(0, 512, 8 + 3 * i)]
        for i in range(5)
    ]

    def run(concurrent: bool):
        eng = InferenceEngine(_cfg(), executor=ModelExecutor(_cfg(), init_seed=4))
        eng.start()
        results = {}
        try:
            events = []
            for i, p in enumerate(prompts):
                toks = []
                results[i] = toks
                ev = threading.Event()
                events.append(ev)

                def cb(out, toks=toks, ev=ev):
                    for s in out.outputs:
                        toks.extend(s.token_ids)
                    if out.finished:
                        ev.set()
                    return True

                eng.add_request(
                    EngineRequest(
                        request_id=f"r{i}",
                        prompt_token_ids=p,
                        sampling=SamplingParams(temperature=0.0, max_new_tokens=6),
                        callback=cb,
                    )
                )
                if not concurrent:
                    assert ev.wait(120.0)
            for ev in events:
                assert ev.wait(120.0)
        finally:
            eng.stop()
        return results

    assert run(False) == run(True)
