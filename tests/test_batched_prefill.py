"""Batched/overlapped prefill (round-1 weak item 4).

Admission prefills concurrent waiting requests in shared compiled steps:
same-bucket prompts ride ONE device call, so TTFT under a burst stacks
sub-linearly instead of one-jit-call-per-request. Parity with the
sequential path must be exact (greedy).
"""

import threading
import time

import numpy as np

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor, PrefillItem


def _cfg(**kw):
    base = dict(
        model="llama3-tiny",
        num_blocks=96,
        max_running_requests=16,
        max_seq_len=256,
        prefill_buckets=[32, 64],
    )
    base.update(kw)
    return EngineConfig(**base)


def test_prefill_batch_matches_sequential():
    """prefill_batch over mixed-length items == one-at-a-time prefill."""
    exe_a = ModelExecutor(_cfg(), init_seed=3)
    exe_b = ModelExecutor(_cfg(), init_seed=3)

    rng = np.random.default_rng(0)
    items = []
    base_block = 1
    for i, n in enumerate([5, 17, 33, 9]):
        table = np.zeros((exe_a.max_blocks_per_seq,), np.int32)
        nb = (n + 1 + exe_a.block_size - 1) // exe_a.block_size
        table[:nb] = np.arange(base_block, base_block + nb)
        base_block += nb
        items.append(
            PrefillItem(
                token_ids=rng.integers(0, 512, n).astype(np.int32),
                start_pos=0,
                block_table=table,
            )
        )

    seq_results = [
        exe_a.prefill(it.token_ids, it.start_pos, it.block_table)
        for it in items
    ]
    batch_results = exe_b.prefill_batch(items)
    # Tokens must match exactly; logprobs only to float tolerance (the P=1
    # and P=4 programs reduce in different orders).
    assert [t for t, _ in seq_results] == [t for t, _ in batch_results]
    np.testing.assert_allclose(
        [l for _, l in seq_results], [l for _, l in batch_results], atol=1e-4
    )
    # Caches identical outside garbage block 0 (masked/padded rows collide
    # there with nondeterministic winners — by design).
    np.testing.assert_array_equal(
        np.asarray(exe_a.k_cache.data)[:, 1:], np.asarray(exe_b.k_cache.data)[:, 1:]
    )


def test_burst_shares_compiled_steps():
    """8 concurrent same-bucket prompts are admitted in at most 2 batched
    prefill calls (not 8 sequential ones)."""
    exe = ModelExecutor(_cfg(), init_seed=1)
    calls = []
    orig = exe._prefill_group

    def counting(group):
        calls.append(len(group))
        return orig(group)

    exe._prefill_group = counting

    # Split stepping: this test counts _prefill_group calls, i.e. the
    # SPLIT batched-prefill plumbing (the escape hatch since ISSUE 9).
    # The mixed-step equivalent (a burst riding few fused dispatches) is
    # covered in tests/test_ragged_attention.py.
    eng = InferenceEngine(_cfg(enable_mixed_step=False), executor=exe)
    done = []
    rng = np.random.default_rng(7)
    # Enqueue BEFORE starting the engine so one _admit sees the full burst.
    for i in range(8):
        ev = threading.Event()
        done.append(ev)

        def cb(out, ev=ev):
            if out.finished:
                ev.set()
            return True

        eng.add_request(
            EngineRequest(
                request_id=f"b{i}",
                prompt_token_ids=[int(t) for t in rng.integers(0, 512, 20 + i)],
                sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
                callback=cb,
            )
        )
    eng.start()
    try:
        for ev in done:
            assert ev.wait(120.0)
    finally:
        eng.stop()
    assert sum(calls) == 8  # every request prefilled exactly once
    assert len(calls) <= 2, f"burst used {len(calls)} prefill steps: {calls}"
    assert max(calls) == 8


def test_engine_batched_greedy_parity():
    """Concurrent requests through the batching engine produce the same
    greedy streams as the same requests run one at a time."""
    prompts = [
        [int(t) for t in np.random.default_rng(i).integers(0, 512, 8 + 3 * i)]
        for i in range(5)
    ]

    def run(concurrent: bool):
        eng = InferenceEngine(_cfg(), executor=ModelExecutor(_cfg(), init_seed=4))
        eng.start()
        results = {}
        try:
            events = []
            for i, p in enumerate(prompts):
                toks = []
                results[i] = toks
                ev = threading.Event()
                events.append(ev)

                def cb(out, toks=toks, ev=ev):
                    for s in out.outputs:
                        toks.extend(s.token_ids)
                    if out.finished:
                        ev.set()
                    return True

                eng.add_request(
                    EngineRequest(
                        request_id=f"r{i}",
                        prompt_token_ids=p,
                        sampling=SamplingParams(temperature=0.0, max_new_tokens=6),
                        callback=cb,
                    )
                )
                if not concurrent:
                    assert ev.wait(120.0)
            for ev in events:
                assert ev.wait(120.0)
        finally:
            eng.stop()
        return results

    assert run(False) == run(True)


def test_chunked_prefill_interleaves_decode():
    """A prompt longer than max_prefill_tokens prefills across MULTIPLE
    engine steps (strict per-step budget), with decode steps for running
    sequences in between — one long prompt must not stall every running
    request's token cadence (SURVEY §7 hard part 3). Output must equal the
    dense-oracle continuation regardless of chunk boundaries."""
    import threading

    import jax.numpy as jnp

    from xllm_service_tpu.models import llama
    from xllm_service_tpu.models.configs import get_model_config
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine

    cfg = EngineConfig(
        model="llama3-tiny",
        dtype="float32",
        block_size=16,
        num_blocks=96,
        max_running_requests=4,
        max_seq_len=512,
        prefill_buckets=[32, 64, 128, 256, 512],
        max_prefill_tokens=48,  # long prompt => several chunks
    )
    ex = ModelExecutor(cfg, init_seed=3)
    eng = InferenceEngine(cfg, executor=ex)
    mcfg = get_model_config("llama3-tiny")

    def oracle(prompt, n):
        seq = list(prompt)
        for _ in range(n):
            logits = llama.forward_dense(
                ex.params, mcfg, jnp.asarray(seq, jnp.int32)[None]
            )
            seq.append(int(jnp.argmax(logits[0, -1])))
        return seq[len(prompt):]

    rng = np.random.default_rng(12)
    short_prompt = rng.integers(1, 500, (8,)).tolist()
    long_prompt = rng.integers(1, 500, (200,)).tolist()  # ~5 chunks of 48

    events = []  # ("short"|"long", token) in emission order
    short_done, long_done = threading.Event(), threading.Event()

    def cb(name, done):
        def _cb(out):
            for so in out.outputs:
                for t in so.token_ids:
                    events.append((name, t))
            if out.finished:
                done.set()
            return True

        return _cb

    eng.start()
    try:
        eng.add_request(
            EngineRequest(
                request_id="short",
                prompt_token_ids=short_prompt,
                sampling=SamplingParams(temperature=0.0, max_new_tokens=24),
                callback=cb("short", short_done),
            )
        )
        # Let the short request begin decoding, then add the long one.
        deadline = time.monotonic() + 60
        while (
            sum(1 for n, _ in events if n == "short") < 2
            and time.monotonic() < deadline
        ):
            time.sleep(0.01)
        idx_at_add = len(events)  # marker: long request exists from here
        eng.add_request(
            EngineRequest(
                request_id="long",
                prompt_token_ids=long_prompt,
                sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
                callback=cb("long", long_done),
            )
        )
        assert short_done.wait(120) and long_done.wait(120)
    finally:
        eng.stop()

    # Correctness: both streams equal their oracle continuations.
    short_toks = [t for n, t in events if n == "short"]
    long_toks = [t for n, t in events if n == "long"]
    assert short_toks == oracle(short_prompt, 24)
    assert long_toks == oracle(long_prompt, 4)

    # Interleaving: between the long request's ARRIVAL (idx_at_add) and
    # its FIRST token, the short request kept producing — one decode step
    # runs after each of the >= 4 prefill chunks; without chunking the
    # whole 200-token prefill lands in one step and at most ~1 short
    # token could sneak into that window.
    first_long = events.index(("long", long_toks[0]))
    assert first_long >= idx_at_add
    short_during_prefill = sum(
        1 for n, _ in events[idx_at_add:first_long] if n == "short"
    )
    assert short_during_prefill >= 3, events[idx_at_add:first_long]
