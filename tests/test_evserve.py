"""Event-driven front end (api/evserve): parser units, server behavior over
real sockets, backpressure, deadline handling, and the subsystem's reason to
exist — >1k concurrent SSE streams through the master on loop + pool
threads instead of a thread per stream.
"""

import http.client
import json
import socket
import threading
import time

import pytest

from xllm_service_tpu.api import FakeEngine, Master
from xllm_service_tpu.api.evserve import (
    EventLoopHttpServer,
    ParseError,
    RequestParser,
)
from xllm_service_tpu.api.evserve.loadgen import run_sse_load
from xllm_service_tpu.api.http_utils import SseWriter
from xllm_service_tpu.api.instance import InstanceServer
from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
from xllm_service_tpu.coordination import MemoryStore

from tests.test_api_e2e import http_get, http_post, sse_post, wait_until


# --------------------------------------------------------------------------- #
# parser units
# --------------------------------------------------------------------------- #


class TestRequestParser:
    def test_single_request_with_body(self):
        p = RequestParser()
        raw = (
            b"POST /v1/x HTTP/1.1\r\nHost: a\r\nContent-Length: 4\r\n"
            b"X-Request-Id: r1\r\n\r\nabcd"
        )
        reqs = p.feed(raw)
        assert len(reqs) == 1
        r = reqs[0]
        assert r.method == "POST" and r.target == "/v1/x"
        assert r.body == b"abcd"
        assert r.headers.get("x-request-id") == "r1"  # case-insensitive
        assert r.keep_alive  # HTTP/1.1 default

    def test_byte_at_a_time(self):
        p = RequestParser()
        raw = b"GET /hello HTTP/1.1\r\nHost: a\r\n\r\n"
        got = []
        for i in range(len(raw)):
            got += p.feed(raw[i : i + 1])
        assert len(got) == 1 and got[0].target == "/hello"
        assert got[0].body == b""

    def test_pipelined_pair_in_one_feed(self):
        p = RequestParser()
        one = b"POST /a HTTP/1.1\r\nContent-Length: 2\r\n\r\nhi"
        two = b"GET /b HTTP/1.1\r\nConnection: close\r\n\r\n"
        reqs = p.feed(one + two)
        assert [r.target for r in reqs] == ["/a", "/b"]
        assert reqs[0].body == b"hi"
        assert not reqs[1].keep_alive

    def test_malformed_request_line(self):
        with pytest.raises(ParseError) as ei:
            RequestParser().feed(b"NONSENSE\r\n\r\n")
        assert ei.value.status == 400

    def test_oversized_head(self):
        p = RequestParser(max_head_bytes=128)
        with pytest.raises(ParseError) as ei:
            p.feed(b"GET /x HTTP/1.1\r\nX-Pad: " + b"a" * 256)
        assert ei.value.status == 431

    def test_oversized_body_rejected_up_front(self):
        p = RequestParser(max_body_bytes=8)
        with pytest.raises(ParseError) as ei:
            p.feed(b"POST /x HTTP/1.1\r\nContent-Length: 9999\r\n\r\n")
        assert ei.value.status == 413

    def test_chunked_request_body_rejected(self):
        with pytest.raises(ParseError) as ei:
            RequestParser().feed(
                b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
            )
        assert ei.value.status == 501


# --------------------------------------------------------------------------- #
# standalone server behavior
# --------------------------------------------------------------------------- #


def _make_server(app, **kw):
    srv = EventLoopHttpServer("127.0.0.1", 0, app, workers=4, **kw)
    srv.start()
    return srv


def _echo_app(h):
    if h.command == "GET":
        h.send_json({"route": h.route, "q": h.query()})
    else:
        h.send_json({"body": h.read_json(), "xrid": h.x_request_id()})


class TestEventServer:
    def test_get_post_roundtrip(self):
        srv = _make_server(_echo_app)
        try:
            code, body = http_get(f"127.0.0.1:{srv.port}", "/r?a=1")
            assert code == 200 and body == {"route": "/r", "q": {"a": "1"}}
            code, body = http_post(
                f"127.0.0.1:{srv.port}", "/p", {"k": "v"},
                headers={"x-request-id": "rid-9"},
            )
            assert code == 200
            assert body == {"body": {"k": "v"}, "xrid": "rid-9"}
        finally:
            srv.stop()

    def test_keep_alive_and_pipelining_one_socket(self):
        srv = _make_server(_echo_app)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            one = b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n"
            two = b"GET /b HTTP/1.1\r\nHost: x\r\n\r\n"
            s.sendall(one + two)  # pipelined: second sent before first reply
            buf = b""
            deadline = time.monotonic() + 5
            while buf.count(b'"route"') < 2 and time.monotonic() < deadline:
                buf += s.recv(4096)
            assert b'"/a"' in buf and b'"/b"' in buf
            assert buf.count(b"HTTP/1.1 200") == 2
            s.close()
        finally:
            srv.stop()
        st = srv.stats()
        assert st["requests_total"] == 2 and st["accepted_total"] == 1

    def test_handler_exception_becomes_500(self):
        def boom(h):
            raise RuntimeError("kaput")

        srv = _make_server(boom)
        try:
            code, body = http_get(f"127.0.0.1:{srv.port}", "/x")
            assert code == 500
            assert body["error"]["type"] == "invalid_request_error"
        finally:
            srv.stop()

    def test_malformed_request_gets_400_then_close(self):
        srv = _make_server(_echo_app)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(b"BOGUS\r\n\r\n")
            buf = b""
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                buf += chunk
            assert buf.startswith(b"HTTP/1.1 400")
            s.close()
        finally:
            srv.stop()

    def test_idle_connection_reaped(self):
        srv = _make_server(_echo_app, idle_timeout_s=0.3)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n")
            s.settimeout(5.0)
            # Drain the (possibly split) response until the idle sweep
            # closes the socket; a hang past 5 s raises socket.timeout.
            while s.recv(4096):
                pass
            s.close()
        finally:
            srv.stop()

    def test_sse_stream_from_foreign_thread(self):
        """Lane-thread shape: the handler returns deferred; another thread
        writes SSE events into the parked exchange; the connection then
        serves a SECOND request (keep-alive survives chunked SSE)."""
        done_holder = {}

        def app(h):
            if h.route == "/stream":
                class _S:  # minimal ClientStream-ish: done + abandon
                    done = threading.Event()

                    def abandon(self):
                        self.done.set()

                stream = _S()
                sse = SseWriter(h)

                def producer():
                    for i in range(5):
                        sse.send({"i": i})
                    sse.send_done()
                    stream.done.set()

                h.hold(stream, 30.0, fail=lambda: None)
                threading.Thread(target=producer, daemon=True).start()
                done_holder["stream"] = stream
            else:
                h.send_json({"after": "sse"})

        srv = _make_server(app)
        try:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            conn.request("POST", "/stream", body=b"{}",
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("Content-Type") == "text/event-stream"
            payloads = []
            for raw in resp:
                line = raw.decode().strip()
                if line.startswith("data: "):
                    payloads.append(line[6:])
            assert payloads[-1] == "[DONE]" and len(payloads) == 6
            # same socket, next exchange
            conn.request("GET", "/after")
            resp2 = conn.getresponse()
            assert json.loads(resp2.read()) == {"after": "sse"}
            conn.close()
        finally:
            srv.stop()

    def test_deferred_deadline_fires_fail(self):
        """hold() on the event backend enforces the deadline with a loop
        timer — no thread blocks waiting for it."""

        def app(h):
            class _S:
                done = threading.Event()

                def abandon(self):
                    self.done.set()

            stream = _S()

            def fail():
                h.send_error_json(504, "deadline", "service_error")
                stream.done.set()

            h.hold(stream, 0.3, fail)

        srv = _make_server(app)
        try:
            t0 = time.monotonic()
            code, body = http_post(f"127.0.0.1:{srv.port}", "/gen", {},
                                   timeout=10.0)
            took = time.monotonic() - t0
            assert code == 504 and body["error"]["message"] == "deadline"
            assert 0.2 < took < 5.0
        finally:
            srv.stop()

    def test_slow_client_backpressure_drops_connection(self):
        """A client that stops reading its stream gets dropped once the
        per-connection outbox cap is hit, and the producer sees write
        failures (which is what cancels generation upstream)."""
        result = {}

        def app(h):
            sse = SseWriter(h)
            writes = 0
            payload = {"pad": "x" * 4096}
            while writes < 100_000:
                if not sse.send(payload):
                    break
                writes += 1
            result["writes"] = writes
            result["closed"] = sse.closed
            sse.close()

        srv = _make_server(app, max_stream_buffer=16 * 1024)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(
                b"POST /stream HTTP/1.1\r\nHost: x\r\nContent-Length: 2\r\n"
                b"\r\n{}"
            )
            # never read: kernel buffers fill, then the server-side cap
            assert wait_until(lambda: "writes" in result, timeout=30.0)
            assert result["closed"]
            # bounded: kernel buffers + 16 KiB cap, nowhere near 100k events
            assert result["writes"] < 2000
            assert wait_until(
                lambda: srv.stats()["slow_client_closes"] == 1, timeout=5.0
            )
            s.close()
        finally:
            srv.stop()

    def test_client_death_finalizes_held_exchange(self):
        """A client that dies mid-hold must not leak the active_streams
        gauge or pin the handler until the deadline: Connection.close()
        finalizes the parked exchange immediately."""

        def app(h):
            class _S:
                done = threading.Event()

                def abandon(self):
                    self.done.set()

            h.hold(_S(), 30.0, fail=lambda: None)  # park, never produce

        srv = _make_server(app)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(
                b"POST /gen HTTP/1.1\r\nHost: x\r\nContent-Length: 2\r\n"
                b"\r\n{}"
            )
            assert wait_until(
                lambda: srv.stats()["active_streams"] == 1, timeout=5.0
            )
            s.close()  # client dies; loop sees EOF
            assert wait_until(
                lambda: srv.stats()["active_streams"] == 0, timeout=5.0
            )
        finally:
            srv.stop()

    def test_rejected_request_is_never_dispatched(self):
        """After a 413 the parser is half-consumed; bytes that keep
        arriving must be discarded, not fed back in — or the oversized
        body buffers in full and the rejected request reaches the app."""
        served = []

        def app(h):
            served.append(h.path)
            h.send_json({"ok": True})

        srv = _make_server(app, max_body_bytes=1024)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(
                b"POST /side-effect HTTP/1.1\r\nHost: x\r\n"
                b"Content-Length: 999999\r\n\r\n"
            )
            body = b""
            s.settimeout(5.0)
            try:
                # Keep sending the "body" while reading the rejection.
                for _ in range(20):
                    try:
                        s.sendall(b"x" * 4096)
                    except OSError:
                        break
                while True:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    body += chunk
            except (ConnectionResetError, BrokenPipeError, socket.timeout):
                pass
            assert body.startswith(b"HTTP/1.1 413")
            s.close()
            time.sleep(0.2)
            assert served == []  # the rejected request never ran
        finally:
            srv.stop()

    def test_pipelining_depth_cap_drops_connection(self):
        """A client that pipelines absurdly deep (each buffered request
        can hold up to 64 MB of body) is dropped, not buffered forever."""
        block = threading.Event()

        def app(h):  # first request parks a worker so pending piles up
            block.wait(10.0)
            h.send_json({"ok": True})

        srv = _make_server(app)
        try:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            one = b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n"
            s.sendall(one * 200)  # far past the 64-deep pipeline cap
            s.settimeout(10.0)
            # Server closes the connection; with nothing flushed the close
            # may arrive as EOF or RST.
            try:
                while s.recv(4096):
                    pass
            except (ConnectionResetError, BrokenPipeError):
                pass
            s.close()
        finally:
            block.set()
            srv.stop()

    def test_max_connections_refused(self):
        srv = _make_server(_echo_app, max_connections=2)
        socks = []
        try:
            for _ in range(2):
                s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
                s.sendall(b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n")
                assert s.recv(4096).startswith(b"HTTP/1.1 200")
                socks.append(s)
            extra = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            extra.sendall(b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n")
            extra.settimeout(5.0)
            # Shed with an explicit one-shot 503 then close. The close can
            # still race the client's send into an RST on a loaded host, so
            # a reset (rather than the 503) is tolerated — the stats
            # assertion below is what proves the shed happened.
            try:
                data = extra.recv(4096)
            except ConnectionResetError:
                data = b""
            assert data == b"" or data.startswith(b"HTTP/1.1 503 ")
            extra.close()
            assert srv.stats()["rejected_connections"] == 1
        finally:
            for s in socks:
                s.close()
            srv.stop()


# --------------------------------------------------------------------------- #
# master e2e on the event backend
# --------------------------------------------------------------------------- #


@pytest.fixture(scope="module")
def ev_cluster():
    store = MemoryStore(clock=lambda: 0.0)
    cfg = ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.5, http_backend="event",
        load_balance_policy="RR", block_size=16,
    )
    master = Master(cfg, store=store)
    master.start()
    instances = []
    for i in range(2):
        srv = InstanceServer(
            EngineConfig(model="fake-echo", instance_name=f"evmix{i}",
                         instance_type="MIX", block_size=16),
            master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.5,
            engine=FakeEngine(token_delay_s=0.001, ttft_ms=2.0),
        )
        srv.start()
        instances.append(srv)
    assert wait_until(
        lambda: sum(master.scheduler.instance_mgr.counts()) == 2
    )
    yield master, instances, store
    for srv in instances:
        srv.stop()
    master.stop()
    store.close()


NUM_STREAMS = 1024
TOKENS = 4


class TestMasterOnEventBackend:
    def test_nonstream_completion(self, ev_cluster):
        master = ev_cluster[0]
        code, body = http_post(
            master.http_address, "/v1/completions",
            {"model": "fake-echo", "prompt": "abc", "max_tokens": 8},
        )
        assert code == 200 and body["choices"][0]["text"] == "cba"

    def test_stream_completion_and_xrid(self, ev_cluster):
        master = ev_cluster[0]
        host, _, port = master.http_address.partition(":")
        conn = http.client.HTTPConnection(host, int(port), timeout=30)
        conn.request(
            "POST", "/v1/completions",
            body=json.dumps({"model": "fake-echo", "prompt": "hi",
                             "max_tokens": 4, "stream": True}).encode(),
            headers={"Content-Type": "application/json",
                     "x-request-id": "ev-rid-1"},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.getheader("x-request-id") == "ev-rid-1"
        text = ""
        for raw in resp:
            line = raw.decode().strip()
            if line.startswith("data: ") and line != "data: [DONE]":
                ev = json.loads(line[6:])
                if ev.get("choices"):
                    text += ev["choices"][0]["text"]
        assert text == "ih"
        conn.close()

    def test_request_deadline_maps_to_504(self, ev_cluster):
        master, instances, _ = ev_cluster
        old = master._request_timeout_s
        master._request_timeout_s = 0.4
        # an engine that never produces: deadline must fire via loop timer
        slow = InstanceServer(
            EngineConfig(model="fake-echo", instance_name="evslow",
                         instance_type="MIX", block_size=16),
            master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.5,
            engine=FakeEngine(token_delay_s=0.001, ttft_ms=120_000.0),
        )
        slow.start()
        try:
            assert wait_until(
                lambda: sum(master.scheduler.instance_mgr.counts()) == 3
            )
            # stop the fast instances from taking the request: round-robin
            # routing — aim a few requests so at least one lands on evslow
            codes = []
            for _ in range(3):
                code, body = http_post(
                    master.http_address, "/v1/completions",
                    {"model": "fake-echo", "prompt": "zz", "max_tokens": 2},
                    timeout=30.0,
                )
                codes.append(code)
            assert 504 in codes, codes
        finally:
            master._request_timeout_s = old
            slow.stop()
            assert wait_until(
                lambda: sum(master.scheduler.instance_mgr.counts()) == 2,
                timeout=15.0,
            )

    def test_1k_concurrent_streams(self, ev_cluster):
        """The tentpole claim: >1k concurrent SSE streams through one
        master front end, driven by a single-threaded event client. Every
        stream must deliver all its tokens and the [DONE] terminator."""
        master = ev_cluster[0]
        bodies = [
            {
                "model": "fake-echo",
                "prompt": f"s{i:04d}" + "ab",
                "max_tokens": TOKENS,
                "temperature": 0.0,
                "stream": True,
            }
            for i in range(NUM_STREAMS)
        ]
        t0 = time.monotonic()
        results = run_sse_load(
            master.http_address, "/v1/completions", bodies, timeout_s=180.0
        )
        wall = time.monotonic() - t0
        bad = [(i, r.error) for i, r in enumerate(results) if not r.ok]
        assert not bad, f"{len(bad)} streams failed: {bad[:5]}"
        total_tokens = 0
        for i, r in enumerate(results):
            assert r.events[-1] == "[DONE]"
            texts = [
                json.loads(e)["choices"][0]["text"]
                for e in r.events[:-1]
                if json.loads(e).get("choices")
            ]
            assert len(texts) == TOKENS, f"stream {i}: {len(texts)} tokens"
            # fake engine echoes the reversed prompt
            want = (bodies[i]["prompt"][::-1])[:TOKENS]
            assert "".join(texts) == want
            total_tokens += len(texts)
        ttfts = sorted(r.ttft_s for r in results)
        summary = {
            "metric": "evserve_1k_streams",
            "streams": NUM_STREAMS,
            "total_tokens": total_tokens,
            "wall_s": round(wall, 3),
            "throughput_tok_s": round(total_tokens / wall, 1),
            "ttft_p50_s": round(ttfts[len(ttfts) // 2], 3),
            "ttft_p99_s": round(ttfts[int(len(ttfts) * 0.99)], 3),
        }
        print("\nEVLOAD " + json.dumps(summary))
        # the front end held every stream concurrently on a fixed-size
        # thread budget — the gauge proves they overlapped
        st = master.http.stats()
        assert st["accepted_total"] >= NUM_STREAMS
        assert wait_until(lambda: master.http.stats()["active_streams"] == 0)

    def test_metrics_exposes_frontend_gauges(self, ev_cluster):
        master = ev_cluster[0]
        code, body = http_get(master.http_address, "/metrics")
        assert code == 200
        assert 'xllm_http_requests_total{plane="http"}' in body
        assert 'xllm_http_open_connections{plane="rpc"}' in body
        # Prometheus text format: ONE TYPE line per metric, with both
        # planes' samples grouped contiguously under it (a duplicate TYPE
        # line fails the entire scrape).
        assert body.count("# TYPE xllm_http_requests_total") == 1
        lines = body.splitlines()
        i = lines.index("# TYPE xllm_http_requests_total counter")
        assert lines[i + 1].startswith('xllm_http_requests_total{plane="http"}')
        assert lines[i + 2].startswith('xllm_http_requests_total{plane="rpc"}')


class TestThreadedBackendStillWorks:
    def test_completion_roundtrip(self):
        """The fallback backend stays selectable and functional."""
        store = MemoryStore(clock=lambda: 0.0)
        cfg = ServiceConfig(host="127.0.0.1", http_port=0, rpc_port=0,
                            heartbeat_interval_s=0.5,
                            http_backend="threaded", block_size=16)
        master = Master(cfg, store=store)
        master.start()
        srv = InstanceServer(
            EngineConfig(model="fake-echo", instance_name="thr0",
                         instance_type="MIX", block_size=16),
            master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.5,
            engine=FakeEngine(token_delay_s=0.001, ttft_ms=2.0),
        )
        srv.start()
        try:
            assert wait_until(
                lambda: sum(master.scheduler.instance_mgr.counts()) == 1
            )
            code, body = http_post(
                master.http_address, "/v1/completions",
                {"model": "fake-echo", "prompt": "xy", "max_tokens": 4},
            )
            assert code == 200 and body["choices"][0]["text"] == "yx"
            events = sse_post(
                master.http_address, "/v1/completions",
                {"model": "fake-echo", "prompt": "xy", "max_tokens": 4,
                 "stream": True},
            )
            assert events[-1] == "[DONE]"
        finally:
            srv.stop()
            master.stop()
            store.close()

    def test_unknown_backend_rejected(self):
        from xllm_service_tpu.api.http_utils import make_http_server

        with pytest.raises(ValueError):
            make_http_server("carrier-pigeon", "127.0.0.1", 0)
