"""Multi-device SERVING parity on the virtual 8-device CPU mesh.

Covers VERDICT round-1 weak item 3: production decode/prefill through
ModelExecutor + InferenceEngine actually executing with tp>1 / dp>1,
exercising kv_cache_sharding — not just the training dryrun. Token streams
must match the tp=1 oracle exactly (greedy) on the same weights.
"""

import threading

import numpy as np
import pytest

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor, SamplingBatch


def _engine_cfg(**kw) -> EngineConfig:
    base = dict(
        model="llama3-tiny",
        num_blocks=64,
        max_running_requests=4,
        max_seq_len=256,
        prefill_buckets=[32, 64],
    )
    base.update(kw)
    return EngineConfig(**base)


def _greedy_tokens(exe: ModelExecutor, prompt: np.ndarray, steps: int):
    """Prefill one sequence then greedy-decode `steps` tokens."""
    table = np.zeros((exe.max_blocks_per_seq,), np.int32)
    table[0] = 2
    table[1] = 3
    tok, lp = exe.prefill(prompt, 0, table)
    toks, lps = [tok], [lp]
    R = exe.R
    ids = np.zeros(R, np.int32)
    pos = np.zeros(R, np.int32)
    tables = np.zeros((R, exe.max_blocks_per_seq), np.int32)
    tables[0] = table
    active = np.zeros(R, bool)
    active[0] = True
    batch = SamplingBatch(
        temperature=np.zeros(R, np.float32),
        top_k=np.zeros(R, np.int32),
        top_p=np.ones(R, np.float32),
        seeds=np.zeros(R, np.uint32),
        steps=np.zeros(R, np.int32),
    )
    cur, p = tok, len(prompt)
    for _ in range(steps):
        ids[0], pos[0] = cur, p
        t, l = exe.decode(ids, pos, tables, active, batch)
        cur = int(t[0])
        toks.append(cur)
        lps.append(float(l[0]))
        p += 1
    return toks, lps


@pytest.mark.parametrize("dp,tp", [(1, 2), (2, 1), (2, 2)], ids=["tp2", "dp2", "dp2tp2"])
def test_executor_sharded_decode_parity(cpu_devices, dp, tp):
    """tp/dp-sharded executor produces the tp=1 oracle's exact greedy
    tokens (same init seed -> identical weights regardless of sharding)."""
    prompt = (np.arange(11, dtype=np.int32) * 7 + 3) % 512
    ref_exe = ModelExecutor(_engine_cfg(), init_seed=5)
    ref_toks, ref_lps = _greedy_tokens(ref_exe, prompt, 6)

    exe = ModelExecutor(_engine_cfg(dp_size=dp, tp_size=tp), init_seed=5)
    assert exe.mesh.shape == {"dp": dp, "tp": tp}
    toks, lps = _greedy_tokens(exe, prompt, 6)
    assert toks == ref_toks
    # bf16 activations + tp-parallel psum reduce in different orders:
    # tokens must be identical, logprobs only close.
    np.testing.assert_allclose(lps, ref_lps, atol=0.05)


def test_executor_sharded_qwen3_parity(cpu_devices):
    """tp=2 over the Qwen3 family: the replicated q/k head-norm leaves
    (parallel/sharding.py qk_norm specs) compose with head-sharded
    attention; greedy tokens match the tp=1 oracle."""
    # float32: this seed lands a near-tie on the first token, and bf16
    # psum ordering across tp legitimately flips it.
    prompt = (np.arange(12, dtype=np.int32) * 11 + 5) % 500
    ref = ModelExecutor(
        _engine_cfg(model="qwen3-tiny", dtype="float32"), init_seed=9
    )
    ref_toks, _ = _greedy_tokens(ref, prompt, 5)
    exe = ModelExecutor(
        _engine_cfg(model="qwen3-tiny", dtype="float32", tp_size=2),
        init_seed=9,
    )
    toks, _ = _greedy_tokens(exe, prompt, 5)
    assert toks == ref_toks


@pytest.mark.parametrize("model", ["llama3-tiny", "deepseek-tiny"],
                         ids=["gqa", "mla"])
def test_executor_sharded_int8_decode_parity(cpu_devices, model):
    """tp=2 + int8 KV: the grouped [.., H, G, BS] scale plane shards
    along heads with the data (kv_scale_sharding, 5-dim spec) — GQA —
    or replicates — MLA — and greedy tokens still match the tp=1 int8
    oracle. Pins the sharded alloc + scatter + gather paths the
    single-chip validator can't."""
    prompt = (np.arange(13, dtype=np.int32) * 5 + 2) % 512
    ref_exe = ModelExecutor(
        _engine_cfg(model=model, kv_cache_dtype="int8"), init_seed=5
    )
    ref_toks, _ = _greedy_tokens(ref_exe, prompt, 5)

    exe = ModelExecutor(
        _engine_cfg(model=model, kv_cache_dtype="int8", tp_size=2),
        init_seed=5,
    )
    assert exe.k_cache.quantized
    toks, _ = _greedy_tokens(exe, prompt, 5)
    assert toks == ref_toks


def _run_engine(exe: ModelExecutor, prompts, steps: int):
    eng = InferenceEngine(exe.engine_cfg, executor=exe)
    eng.start()
    results = {}
    events = []
    try:
        for i, prompt in enumerate(prompts):
            done = threading.Event()
            events.append(done)
            toks = []
            results[i] = toks

            def cb(out, toks=toks, done=done):
                for s in out.outputs:
                    toks.extend(s.token_ids)
                if out.finished:
                    done.set()
                return True

            eng.add_request(
                EngineRequest(
                    request_id=f"r{i}",
                    prompt_token_ids=list(prompt),
                    sampling=SamplingParams(
                        temperature=0.0, max_new_tokens=steps
                    ),
                    callback=cb,
                )
            )
        for done in events:
            assert done.wait(60.0), "engine request timed out"
    finally:
        eng.stop()
    return results


@pytest.mark.parametrize(
    "model", ["moe-tiny", "deepseek-hetero-tiny"],
    ids=["moe", "mla-hetero"],
)
@pytest.mark.parametrize("dp,tp,ep", [(1, 1, 2), (1, 2, 2), (2, 1, 2)],
                         ids=["ep2", "tp2ep2", "dp2ep2"])
def test_moe_ep_decode_parity(cpu_devices, dp, tp, ep, model):
    """MoE decode with experts sharded over an ep axis (EP serving path —
    the combine contraction makes XLA emit the psum) matches the
    single-device dense-all-experts oracle token for token. Covers the
    Mixtral-style GQA MoE and the heterogeneous DeepSeek stack (dense
    prefix + MoE suffix: the split-stack two-scan path with per-stack
    sharding specs)."""
    prompt = (np.arange(13, dtype=np.int32) * 5 + 2) % 512
    ref_exe = ModelExecutor(_engine_cfg(model=model), init_seed=7)
    ref_toks, ref_lps = _greedy_tokens(ref_exe, prompt, 6)

    exe = ModelExecutor(
        _engine_cfg(model=model, dp_size=dp, tp_size=tp, ep_size=ep),
        init_seed=7,
    )
    assert exe.mesh.shape == {"dp": dp, "tp": tp, "ep": ep}
    if model == "deepseek-hetero-tiny":
        assert "dense_layers" in exe.params
    toks, lps = _greedy_tokens(exe, prompt, 6)
    assert toks == ref_toks
    np.testing.assert_allclose(lps, ref_lps, atol=0.05)


def test_engine_tp2_parity(cpu_devices):
    """Full continuous-batching engine over a tp=2 mesh: token streams for
    concurrent greedy requests match the tp=1 engine's."""
    prompts = [
        [(i * 13 + j * 5 + 1) % 512 for j in range(9 + i)] for i in range(3)
    ]
    ref = _run_engine(ModelExecutor(_engine_cfg(), init_seed=9), prompts, 8)
    tp2 = _run_engine(
        ModelExecutor(_engine_cfg(tp_size=2), init_seed=9), prompts, 8
    )
    assert ref == tp2
