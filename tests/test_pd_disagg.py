"""PD disaggregation at the engine level: prefill on engine A, KV handoff,
decode continuation on engine B. Greedy output across the handoff must be
identical to a single colocated engine (the correctness bar for the
reference's prefill->decode split, SURVEY.md §2.2)."""

import threading

import numpy as np
import pytest

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor

BS = 16


def make_engine(seed=0, num_blocks=64):
    cfg = EngineConfig(
        model="llama3-tiny",
        dtype="float32",
        block_size=BS,
        num_blocks=num_blocks,
        max_running_requests=4,
        max_seq_len=256,
        prefill_buckets=[32, 64, 128, 256],
    )
    return InferenceEngine(cfg, executor=ModelExecutor(cfg, init_seed=seed))


class Collector:
    def __init__(self):
        self.tokens = []
        self.outputs = []
        self.finished = threading.Event()

    def __call__(self, out):
        self.outputs.append(out)
        for so in out.outputs:
            self.tokens.extend(so.token_ids)
        if out.finished:
            self.finished.set()
        return True


def run(eng, max_steps=100):
    for _ in range(max_steps):
        if not eng.has_work():
            break
        eng.step()


@pytest.fixture(scope="module")
def engines():
    # identical init_seed => identical weights on both sides
    return make_engine(seed=0), make_engine(seed=0)


@pytest.mark.parametrize("prompt_len", [23, 40, 7])
def test_handoff_matches_colocated(engines, prompt_len):
    a, b = engines
    rng = np.random.RandomState(prompt_len)
    prompt = [int(x) for x in rng.randint(0, 500, size=prompt_len)]
    n_new = 8

    # oracle: colocated run on a fresh engine with the same weights
    oracle_eng = make_engine(seed=0)
    c0 = Collector()
    oracle_eng.add_request(
        EngineRequest("oracle", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=n_new), c0)
    )
    run(oracle_eng)
    assert c0.finished.is_set()

    # disaggregated: prefill on A with handoff, decode on B
    handoffs = []
    ca, cb = Collector(), Collector()
    a.add_request(
        EngineRequest(
            "req-a", list(prompt),
            SamplingParams(temperature=0.0, max_new_tokens=n_new), ca,
            prefill_only=True, handoff=handoffs.append,
        )
    )
    run(a)
    assert len(handoffs) == 1
    h = handoffs[0]
    assert ca.tokens == [h.first_token]
    assert h.token_ids == prompt + [h.first_token]
    assert h.num_full_blocks == prompt_len // BS
    # A's slot + block refs released
    assert not a._running and a.block_mgr.usage < 1.0

    b.import_sequence(
        EngineRequest(
            "req-b", list(prompt),
            SamplingParams(temperature=0.0, max_new_tokens=n_new), cb,
        ),
        h,
    )
    run(b)
    assert cb.finished.is_set()
    combined = ca.tokens + cb.tokens
    assert combined == c0.tokens, (combined, c0.tokens)
    # usage accounting survives the handoff
    final = cb.outputs[-1]
    assert final.usage.num_prompt_tokens == prompt_len
    assert final.usage.num_generated_tokens == n_new


def test_import_dedups_against_local_cache(engines):
    a, b = engines
    rng = np.random.RandomState(99)
    prompt = [int(x) for x in rng.randint(0, 500, size=3 * BS + 5)]

    handoffs = []
    ca = Collector()
    a.add_request(
        EngineRequest("h1", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=4), ca,
                      prefill_only=True, handoff=handoffs.append)
    )
    run(a)
    h = handoffs[0]
    cb = Collector()
    b.import_sequence(
        EngineRequest("d1", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=4), cb), h
    )
    run(b)
    assert cb.finished.is_set()
    # same prefix handed off again: B already caches those hashes
    before = [b.block_mgr.lookup_hash(x) for x in h.block_hashes]
    assert all(x is not None for x in before)
    handoffs2 = []
    ca2 = Collector()
    a.add_request(
        EngineRequest("h2", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=4), ca2,
                      prefill_only=True, handoff=handoffs2.append)
    )
    run(a)
    cb2 = Collector()
    b.import_sequence(
        EngineRequest("d2", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=4), cb2),
        handoffs2[0],
    )
    run(b)
    assert cb2.finished.is_set()
    after = [b.block_mgr.lookup_hash(x) for x in h.block_hashes]
    assert after == before  # dedup: no re-import under new block ids


def test_short_prompt_pure_recompute(engines):
    """Prompt shorter than one block: no KV migrates, decode side recomputes."""
    a, b = engines
    prompt = [5, 6, 7]
    handoffs = []
    ca, cb = Collector(), Collector()
    a.add_request(
        EngineRequest("s1", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=5), ca,
                      prefill_only=True, handoff=handoffs.append)
    )
    run(a)
    h = handoffs[0]
    assert h.num_full_blocks == 0 and h.kv is None
    b.import_sequence(
        EngineRequest("s1d", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=5), cb), h
    )
    run(b)
    assert cb.finished.is_set()
    oracle_eng = make_engine(seed=0)
    c0 = Collector()
    oracle_eng.add_request(
        EngineRequest("o", list(prompt),
                      SamplingParams(temperature=0.0, max_new_tokens=5), c0)
    )
    run(oracle_eng)
    assert ca.tokens + cb.tokens == c0.tokens
