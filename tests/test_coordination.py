"""Coordination store + master election tests.

Covers the etcd semantics the reference relies on (SURVEY.md §3.5): prefix
scans, watch PUT/DELETE delivery, lease expiry => key deletion => watch
event, compare-create election txn, guarded batch delete, and watch-driven
master takeover/failover.
"""

import threading
import time

import pytest

from xllm_service_tpu.coordination import (
    MASTER_KEY,
    MasterElection,
    MemoryStore,
    EventType,
    connect,
    reset_memory_namespace,
)


def wait_until(pred, timeout=5.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture
def store():
    st = MemoryStore()
    yield st
    st.close()


class TestMemoryStore:
    def test_get_set_remove(self, store):
        assert store.get("k") is None
        assert store.set("k", "v")
        assert store.get("k") == "v"
        assert store.remove("k")
        assert store.get("k") is None
        assert not store.remove("k")

    def test_prefix_scan(self, store):
        store.set("XLLM:PREFILL:a", "1")
        store.set("XLLM:PREFILL:b", "2")
        store.set("XLLM:DECODE:c", "3")
        got = store.get_prefix("XLLM:PREFILL:")
        assert got == {"XLLM:PREFILL:a": "1", "XLLM:PREFILL:b": "2"}

    def test_json_roundtrip(self, store):
        store.set_json("j", {"a": [1, 2], "b": "x"})
        assert store.get_json("j") == {"a": [1, 2], "b": "x"}

    def test_watch_put_delete(self, store):
        events = []
        done = threading.Event()

        def cb(evs):
            events.extend(evs)
            if len(events) >= 2:
                done.set()

        store.add_watch("W:", cb)
        store.set("W:x", "1")
        store.set("other", "ignored")
        store.remove("W:x")
        assert done.wait(5.0)
        assert [(e.type, e.key) for e in events] == [
            (EventType.PUT, "W:x"),
            (EventType.DELETE, "W:x"),
        ]

    def test_remove_watch_stops_delivery(self, store):
        events = []
        wid = store.add_watch("W:", lambda evs: events.extend(evs))
        store.remove_watch(wid)
        store.set("W:x", "1")
        time.sleep(0.2)
        assert events == []

    def test_lease_expiry_deletes_and_notifies(self, store):
        deleted = threading.Event()
        store.add_watch(
            "L:",
            lambda evs: deleted.set()
            if any(e.type == EventType.DELETE for e in evs)
            else None,
        )
        lease = store.grant_lease(ttl_s=0.2)
        store.set("L:inst", "meta", lease_id=lease)
        assert store.get("L:inst") == "meta"
        assert deleted.wait(5.0)
        assert store.get("L:inst") is None

    def test_keepalive_refreshes(self, store):
        lease = store.grant_lease(ttl_s=0.3)
        store.set("K:x", "v", lease_id=lease)
        for _ in range(4):
            time.sleep(0.15)
            assert store.keepalive(lease)
        assert store.get("K:x") == "v"
        # stop refreshing -> expires
        assert wait_until(lambda: store.get("K:x") is None)
        assert not store.keepalive(lease)

    def test_revoke_lease_deletes_keys(self, store):
        lease = store.grant_lease(ttl_s=30)
        store.set("R:x", "v", lease_id=lease)
        store.revoke_lease(lease)
        assert store.get("R:x") is None

    def test_compare_create_single_winner(self, store):
        wins = sum(
            store.compare_create("E:master", f"id{i}") for i in range(5)
        )
        assert wins == 1
        assert store.get("E:master") == "id0"

    def test_guarded_remove(self, store):
        store.set("G:guard", "me")
        store.set("G:a", "1")
        store.set("G:b", "2")
        assert not store.guarded_remove(["G:a"], "G:guard", "not-me")
        assert store.get("G:a") == "1"
        assert store.guarded_remove(["G:a", "G:b"], "G:guard", "me")
        assert store.get("G:a") is None and store.get("G:b") is None

    def test_memory_namespace_shared(self):
        reset_memory_namespace("t1")
        a = connect("memory://t1")
        b = connect("memory://t1")
        assert a is b
        a.set("x", "1")
        assert b.get("x") == "1"
        reset_memory_namespace("t1")


class TestMasterElection:
    def test_first_wins_second_watches(self, store):
        # Generous TTL: a 0.3 s lease on the REAL clock flaked once under
        # full-suite load (keepalive beat starved past the TTL, svc2 took
        # over mid-assert). Nothing here waits on expiry, so the longer
        # lease costs nothing.
        e1 = MasterElection(store, "svc1", lease_ttl_s=3.0)
        e2 = MasterElection(store, "svc2", lease_ttl_s=3.0)
        e1.start()
        e2.start()
        assert e1.is_master and not e2.is_master
        assert store.get(MASTER_KEY) == "svc1"
        e1.stop()
        e2.stop()

    def test_failover_on_master_death(self, store):
        lost = threading.Event()
        elected2 = threading.Event()
        e1 = MasterElection(store, "svc1", lease_ttl_s=0.2, on_lost=lost.set)
        e2 = MasterElection(
            store, "svc2", lease_ttl_s=0.2, on_elected=elected2.set
        )
        e1.start()
        e2.start()
        assert e1.is_master
        # Simulate svc1 crash: stop keepalives by force-expiring its lease.
        with e1._mu:
            lease = e1._lease_id
        store.expire_lease_now(lease)
        assert elected2.wait(5.0), "svc2 should take over after lease expiry"
        assert e2.is_master
        assert store.get(MASTER_KEY) == "svc2"
        e1.stop()
        e2.stop()

    def test_clean_stop_releases_mastership(self, store):
        elected2 = threading.Event()
        e1 = MasterElection(store, "svc1", lease_ttl_s=0.3)
        e2 = MasterElection(
            store, "svc2", lease_ttl_s=0.3, on_elected=elected2.set
        )
        e1.start()
        e2.start()
        e1.stop()  # revokes lease -> DELETE -> e2 takeover
        assert elected2.wait(5.0)
        assert store.get(MASTER_KEY) == "svc2"
        e2.stop()
