"""Unified ragged paged-attention (ISSUE 9, docs/KERNELS.md).

Three layers of differential coverage:

1. KERNEL: ops/pallas/ragged_paged_attention.py in interpret mode vs the
   ragged_attention_blockwise oracle over fuzzed mixed batches — ragged
   prefill lengths (incl. unaligned tails), decode rows, dead rows,
   prefix hits (pos0 > 0), GQA ratios, bf16 + int8 KV, sliding window,
   and the packed-cache dispatcher path.

2. ENGINE: mixed-step engines (the default ragged step builder) emit
   streams BYTE-IDENTICAL to split-step engines — greedy and seeded
   sampling, overlap and sync modes, chunked prefill, prefix hits,
   staggered and concurrent arrivals. This is the contract that lets the
   fused hot loop replace the alternating prefill/decode steps: the
   model's mixed_step keeps each half's split-program shapes
   (models/llama.py docstring), so fusing the dispatch cannot change
   what a client receives.

3. HATCHES: XLLM_MIXED_STEP / EngineConfig.enable_mixed_step routing,
   automatic split fallback for guided + speculative + prefill_only, and
   the XLLM_RAGGED_ATTENTION_KERNEL=1 interpret-mode engine e2e (the
   Pallas branch actually serving an engine run on CPU).
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.ops import kv_cache as kvc
from xllm_service_tpu.ops.attention import (
    ragged_attention_blockwise,
    ragged_paged_attention,
)
from xllm_service_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention_kernel,
)
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor

# --------------------------------------------------------------- kernel


def make_mixed_case(rng, seg_lens, Hq=8, Hkv=4, D=128, BS=16, MB=8,
                    num_blocks=64, dtype=jnp.float32):
    """A mixed batch over a shared KV pool: per-row random valid length
    (<= capacity; decode rows always 1 unless killed) and a random
    absolute start (prefix hits / decode context)."""
    B = len(seg_lens)
    T = sum(seg_lens)
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), dtype)
    k = jnp.asarray(rng.standard_normal((num_blocks, Hkv, BS, D)), dtype)
    v = jnp.asarray(rng.standard_normal((num_blocks, Hkv, BS, D)), dtype)
    bt = jnp.asarray(
        rng.choice(
            np.arange(1, num_blocks), size=(B, MB), replace=False
        ).astype(np.int32)
    )
    q_len = np.zeros((B,), np.int32)
    pos0 = np.zeros((B,), np.int32)
    for b, cap in enumerate(seg_lens):
        q_len[b] = 1 if cap == 1 else rng.integers(1, cap + 1)
        pos0[b] = rng.integers(0, MB * BS - q_len[b] + 1)
    return q, k, v, bt, jnp.asarray(q_len), jnp.asarray(pos0)


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("gqa", [1, 4])
def test_ragged_kernel_fuzzed_mixed_batches(seed, gqa):
    """Fuzzed decode+prefill mixes (unaligned tails, prefix offsets)
    match the blockwise oracle."""
    rng = np.random.default_rng(seed)
    Hkv = 4
    # decode singletons interleaved with ragged prefill capacities
    seg_lens = (1, 1, int(rng.integers(2, 33)), 1, int(rng.integers(2, 33)))
    q, k, v, bt, q_len, pos0 = make_mixed_case(
        rng, seg_lens, Hq=Hkv * gqa, Hkv=Hkv
    )
    scale = q.shape[-1] ** -0.5
    ref = ragged_attention_blockwise(
        q, k, v, bt, q_len, pos0, seg_lens, scale
    )
    out = ragged_paged_attention_kernel(
        q, k, v, bt, q_len, pos0, seg_lens, scale, interpret=True, tile_q=16
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ragged_kernel_dead_rows_zero():
    """q_len 0 rows (inactive decode slots / padded prefill lanes) emit
    zeros; live rows are untouched by their presence."""
    rng = np.random.default_rng(3)
    seg_lens = (1, 1, 16, 8)
    q, k, v, bt, q_len, pos0 = make_mixed_case(rng, seg_lens)
    q_len = jnp.asarray([1, 0, 16, 0], jnp.int32)
    # The override raises row lengths past what the helper drew pos0 for;
    # re-clamp so every row's context still fits its MB*BS block table.
    pos0 = jnp.minimum(pos0, 8 * 16 - q_len)
    scale = 0.125
    out = np.asarray(ragged_paged_attention_kernel(
        q, k, v, bt, q_len, pos0, seg_lens, scale, interpret=True, tile_q=16
    ))
    ref = np.asarray(ragged_attention_blockwise(
        q, k, v, bt, q_len, pos0, seg_lens, scale
    ))
    assert np.all(out[1] == 0) and np.all(out[18:] == 0)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ragged_kernel_tiles_cross_row_boundaries():
    """A tile smaller than one row's segment AND a tile holding many
    rows both reduce exactly (the row-iteration/online-softmax no-op
    merge argument in the kernel docstring)."""
    rng = np.random.default_rng(4)
    seg_lens = (1,) * 12 + (40,)  # tile_q=16: tiles mix decode rows,
    q, k, v, bt, q_len, pos0 = make_mixed_case(rng, seg_lens, MB=4)
    scale = 0.125
    ref = ragged_attention_blockwise(
        q, k, v, bt, q_len, pos0, seg_lens, scale
    )
    out = ragged_paged_attention_kernel(
        q, k, v, bt, q_len, pos0, seg_lens, scale, interpret=True, tile_q=16
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_ragged_kernel_bf16():
    rng = np.random.default_rng(5)
    seg_lens = (1, 24, 1, 9)
    q, k, v, bt, q_len, pos0 = make_mixed_case(
        rng, seg_lens, dtype=jnp.bfloat16
    )
    scale = 0.125
    ref = ragged_attention_blockwise(
        q, k, v, bt, q_len, pos0, seg_lens, scale
    )
    out = ragged_paged_attention_kernel(
        q, k, v, bt, q_len, pos0, seg_lens, scale, interpret=True, tile_q=16
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2,
    )


def test_ragged_kernel_int8():
    """int8 KV: pool-native grouped scales stream and dequantize in VMEM
    (same tolerance budget as the flash-prefill int8 case — dequant_tile
    rounds to bf16 before the score matmul)."""
    rng = np.random.default_rng(6)
    # BS=128: int8 [G, BS] scale tiles carry BS on lanes (chip rule).
    seg_lens = (1, 1, 24, 17)
    q, k, v, bt, q_len, pos0 = make_mixed_case(
        rng, seg_lens, BS=128, MB=2, num_blocks=16
    )
    kq, vq = kvc.quantize_pool(k), kvc.quantize_pool(v)
    scale = 0.125
    ref = ragged_attention_blockwise(
        q, kq, vq, bt, q_len, pos0, seg_lens, scale
    )
    out = ragged_paged_attention_kernel(
        q, kq, vq, bt, q_len, pos0, seg_lens, scale, interpret=True,
        tile_q=16,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-2, rtol=2e-2
    )


def test_ragged_kernel_sliding_window():
    rng = np.random.default_rng(7)
    seg_lens = (1, 32, 1)
    q, k, v, bt, q_len, pos0 = make_mixed_case(rng, seg_lens)
    scale = 0.125
    for window in (8, 24):
        ref = ragged_attention_blockwise(
            q, k, v, bt, q_len, pos0, seg_lens, scale, window=window
        )
        out = ragged_paged_attention_kernel(
            q, k, v, bt, q_len, pos0, seg_lens, scale, interpret=True,
            tile_q=16, window=window,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
        )


def test_ragged_dispatcher_packed_cache(monkeypatch):
    """head_dim < 128 rides the packed-pair cache layout through the
    dispatcher (kernel_io_for/pack_queries) — kernel branch forced via
    use_kernel + interpret, packed shapes opted in."""
    monkeypatch.setenv("XLLM_PACKED_KV_KERNEL", "1")
    rng = np.random.default_rng(8)
    Hq, Hkv, D, BS, MB, NB = 4, 2, 32, 16, 4, 32
    seg_lens = (1, 12, 1)
    T = sum(seg_lens)
    q = jnp.asarray(rng.standard_normal((T, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((NB, Hkv, BS, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((NB, Hkv, BS, D)), jnp.float32)
    kp = kvc.as_paged(kvc.pack_pool(k)) if hasattr(kvc, "pack_pool") else None
    if kp is None:
        pytest.skip("no packed-pool helper in this build")
    vp = kvc.as_paged(kvc.pack_pool(v))
    bt = jnp.asarray(
        rng.choice(np.arange(1, NB // 4), size=(3, MB),
                   replace=False).astype(np.int32)
    )
    q_len = jnp.asarray([1, 12, 1], jnp.int32)
    pos0 = jnp.asarray([20, 0, 5], jnp.int32)
    scale = D ** -0.5
    ref = ragged_paged_attention(
        q, kp, vp, bt, q_len, pos0, seg_lens, scale, use_kernel=False
    )
    out = ragged_paged_attention(
        q, kp, vp, bt, q_len, pos0, seg_lens, scale, use_kernel=True,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
    )


# --------------------------------------------------------------- engine

BS = 16


def _cfg(**kw):
    base = dict(
        model="llama3-tiny",
        num_blocks=96,
        max_running_requests=8,
        max_seq_len=512,
        block_size=BS,
        prefill_buckets=[32, 64, 128],
    )
    base.update(kw)
    return EngineConfig(**base)


def _run_engine(cfg, requests, stagger=False, ex_cfg=None):
    """Drive `requests` [(rid, tokens, sampling)] through an engine;
    returns {rid: [token_ids]} with per-request completion waits."""
    eng = InferenceEngine(
        cfg, executor=ModelExecutor(ex_cfg or _cfg(), init_seed=11)
    )
    eng.start()
    results, events = {}, []
    try:
        for rid, toks, s in requests:
            out_toks = []
            results[rid] = out_toks
            ev = threading.Event()
            events.append(ev)

            def cb(out, out_toks=out_toks, ev=ev):
                for so in out.outputs:
                    out_toks.extend(so.token_ids)
                if out.finished:
                    ev.set()
                return True

            eng.add_request(EngineRequest(
                request_id=rid, prompt_token_ids=list(toks),
                sampling=s, callback=cb,
            ))
            if stagger:
                assert ev.wait(120.0)
        for ev in events:
            assert ev.wait(120.0)
    finally:
        eng.stop()
    return results


def _requests(n=5, greedy=True, base_len=9, seed0=100):
    reqs = []
    for i in range(n):
        toks = [
            int(t) for t in
            np.random.default_rng(seed0 + i).integers(
                0, 512, base_len + 11 * i
            )
        ]
        s = (
            SamplingParams(temperature=0.0, max_new_tokens=6)
            if greedy else
            SamplingParams(
                temperature=0.9, top_k=40, top_p=0.95, seed=7 + i,
                max_new_tokens=6,
            )
        )
        reqs.append((f"r{i}", toks, s))
    return reqs


@pytest.mark.parametrize("greedy", [True, False])
def test_mixed_equals_split_byte_identical(greedy):
    """The acceptance differential: a mixed-step engine's emitted streams
    == a split-step engine's, token for token, greedy AND seeded
    sampling, concurrent arrivals."""
    reqs = _requests(greedy=greedy)
    mixed = _run_engine(_cfg(enable_mixed_step=True), reqs)
    split = _run_engine(_cfg(enable_mixed_step=False), reqs)
    assert mixed == split


def test_mixed_equals_split_sync_mode():
    """Sync engines force split stepping; the overlapped mixed engine
    must still match them byte-for-byte (overlap ≡ sync ≡ split)."""
    reqs = _requests(n=4)
    mixed = _run_engine(_cfg(enable_mixed_step=True), reqs)
    syncd = _run_engine(_cfg(sync_engine=True), reqs)
    assert mixed == syncd


def test_mixed_equals_split_chunked_prefill():
    """Prompts spanning several prefill chunks (max_prefill_tokens caps
    each cut): the pipelined chunk walk must land the same KV and the
    same streams as split mode, staggered and concurrent."""
    reqs = _requests(n=3, base_len=3 * BS + 5)
    for stagger in (False, True):
        mixed = _run_engine(
            _cfg(enable_mixed_step=True, max_prefill_tokens=2 * BS),
            reqs, stagger=stagger,
        )
        split = _run_engine(
            _cfg(enable_mixed_step=False, max_prefill_tokens=2 * BS),
            reqs, stagger=stagger,
        )
        assert mixed == split


def test_mixed_equals_split_prefix_hit():
    """A re-sent prompt hits the prefix cache in both modes and the
    follow-up stream stays identical (pos0 > 0 rows in the mixed batch)."""
    shared = [int(t) for t in np.random.default_rng(55).integers(
        0, 512, 4 * BS)]
    reqs = [
        ("warm", shared + [1, 2, 3],
         SamplingParams(temperature=0.0, max_new_tokens=4)),
        ("hit", shared + [4, 5, 6],
         SamplingParams(temperature=0.0, max_new_tokens=4)),
    ]
    mixed = _run_engine(_cfg(enable_mixed_step=True), reqs, stagger=True)
    split = _run_engine(_cfg(enable_mixed_step=False), reqs, stagger=True)
    assert mixed == split


def test_burst_shares_mixed_dispatches():
    """The mixed-mode analogue of the split burst test: 6 concurrent
    one-chunk prompts ride few fused dispatches (each carrying several
    prefill rows), not one dispatch per request."""
    cfg = _cfg(enable_mixed_step=True)
    eng = InferenceEngine(cfg, executor=ModelExecutor(_cfg(), init_seed=11))
    rng = np.random.default_rng(9)
    events = []
    for i in range(6):
        ev = threading.Event()
        events.append(ev)

        def cb(out, ev=ev):
            if out.finished:
                ev.set()
            return True

        eng.add_request(EngineRequest(
            request_id=f"b{i}",
            prompt_token_ids=[int(t) for t in rng.integers(0, 512, 20 + i)],
            sampling=SamplingParams(temperature=0.0, max_new_tokens=4),
            callback=cb,
        ))
    eng.start()
    try:
        for ev in events:
            assert ev.wait(120.0)
    finally:
        eng.stop()
    assert eng.mixed_steps >= 1
    # All 6 same-bucket prompts fused into at most 2 prefill-carrying
    # dispatches (PREFILL_GROUP_MAX bounds one; the budget may split).
    assert eng.mixed_steps <= 2, f"burst used {eng.mixed_steps} mixed steps"


# -------------------------------------------------------------- hatches


def test_env_hatch_overrides_config(monkeypatch):
    monkeypatch.setenv("XLLM_MIXED_STEP", "0")
    eng = InferenceEngine(
        _cfg(enable_mixed_step=True),
        executor=ModelExecutor(_cfg(), init_seed=11),
    )
    assert not eng.mixed_step_enabled
    monkeypatch.setenv("XLLM_MIXED_STEP", "1")
    eng = InferenceEngine(
        _cfg(enable_mixed_step=False),
        executor=ModelExecutor(_cfg(), init_seed=11),
    )
    assert eng.mixed_step_enabled


def test_speculative_rides_pipeline(monkeypatch):
    """Speculative decoding no longer forces sync stepping (ISSUE 13):
    the composed path is the default, and XLLM_SPEC_PIPELINE=0 (or
    enable_spec_pipeline=False) degrades it back to sync verify."""
    eng = InferenceEngine(
        _cfg(speculative_tokens=3),
        executor=ModelExecutor(_cfg(), init_seed=11),
    )
    assert not eng._force_sync
    monkeypatch.setenv("XLLM_SPEC_PIPELINE", "0")
    assert eng._force_sync  # live per-step decision: env flip lands
    monkeypatch.delenv("XLLM_SPEC_PIPELINE")
    eng2 = InferenceEngine(
        _cfg(speculative_tokens=3, enable_spec_pipeline=False),
        executor=ModelExecutor(_cfg(), init_seed=11),
    )
    assert eng2._force_sync
    monkeypatch.setenv("XLLM_SPEC_PIPELINE", "1")
    assert not eng2._force_sync  # =1 force-enables over a False config


def test_guided_request_rides_mixed_batch():
    """A guided request admitted under mixed stepping rides the mixed
    batch (final chunk under an in-graph mask row) and decodes
    host-paced inside the pipeline (ISSUE 13) — and plain requests
    around it still finish."""
    reqs = _requests(n=2)
    cfg = _cfg(enable_mixed_step=True)
    eng = InferenceEngine(cfg, executor=ModelExecutor(_cfg(), init_seed=11))
    eng.start()
    done = []
    try:
        for rid, toks, s in reqs:
            ev = threading.Event()
            done.append(ev)

            def cb(out, ev=ev):
                if out.finished:
                    ev.set()
                return True

            eng.add_request(EngineRequest(
                request_id=rid, prompt_token_ids=toks, sampling=s,
                callback=cb,
            ))
        ev = threading.Event()
        done.append(ev)

        def gcb(out, ev=ev):
            if out.finished:
                ev.set()
            return True

        eng.add_request(EngineRequest(
            request_id="guided",
            prompt_token_ids=[1, 2, 3, 4],
            sampling=SamplingParams(temperature=0.0, max_new_tokens=8),
            callback=gcb,
            guided="json",
        ))
        for ev in done:
            assert ev.wait(120.0)
    finally:
        eng.stop()


def test_ragged_kernel_engine_e2e_interpret(monkeypatch):
    """The Pallas ragged kernel actually SERVES an engine run (interpret
    mode on CPU, packed tiny-model cache opted in) and the greedy streams
    match the reference-path mixed engine. llama3-packed-tiny is the one
    tiny geometry that is kernel-eligible: head_dim 64 with 2 kv heads
    packs pairwise into 128-lane cache rows (kv_pack_factor P=2);
    llama3-tiny's D=32/Hkv=2 can never pack (P=4 doesn't divide 2)."""
    reqs = _requests(n=3)
    cfg = _cfg(enable_mixed_step=True, model="llama3-packed-tiny")
    monkeypatch.setenv("XLLM_PACKED_KV_KERNEL", "1")
    ref = _run_engine(
        cfg, reqs, ex_cfg=_cfg(model="llama3-packed-tiny")
    )
    monkeypatch.setenv("XLLM_RAGGED_ATTENTION_KERNEL", "1")
    monkeypatch.setenv("XLLM_RAGGED_INTERPRET", "1")
    eng = InferenceEngine(
        cfg,
        executor=ModelExecutor(
            _cfg(model="llama3-packed-tiny"), init_seed=11
        ),
    )
    assert eng._kernel_names["mixed"] == "ragged"
    eng.start()
    results, events = {}, []
    try:
        for rid, toks, s in reqs:
            out_toks = []
            results[rid] = out_toks
            ev = threading.Event()
            events.append(ev)

            def cb(out, out_toks=out_toks, ev=ev):
                for so in out.outputs:
                    out_toks.extend(so.token_ids)
                if out.finished:
                    ev.set()
                return True

            eng.add_request(EngineRequest(
                request_id=rid, prompt_token_ids=list(toks), sampling=s,
                callback=cb,
            ))
        for ev in events:
            assert ev.wait(300.0)
    finally:
        eng.stop()
    assert eng.mixed_steps >= 1
    assert results == ref


# ------------------------------------------------------------ hatch lint


class TestKernelHatchLint:
    def test_lint_clean(self):
        """Every XLLM_*_KERNEL hatch in ops/ is documented with its
        default in docs/ARCHITECTURE.md (and no stale rows) — flipped
        defaults can't drift undocumented (ISSUE 9 satellite)."""
        import os
        import sys

        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "scripts"),
        )
        import check_kernel_hatches

        assert check_kernel_hatches.main() == 0
