"""Qwen2-VL M-RoPE: the (t, h, w) position streams through the LLM.

Three layers of proof:
  * ops-level: equal streams make apply_mrope identical to apply_rope
    (why text tokens and decode steps need no special handling);
  * the engine's host-side position algorithm matches HF
    Qwen2VLModel.get_rope_index on image-bearing prompts;
  * full-model parity: a tiny HF Qwen2VLForConditionalGeneration and
    our engine (combined checkpoint, HF tower embeds injected) produce
    the SAME greedy continuation for an image prompt — rope streams,
    the post-image position compression (rope_delta), and decode all
    line up.
"""

import json as _json
import os as _os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from xllm_service_tpu.ops import rope as rope_ops

SECTION = (4, 6, 6)  # head_dim 32 -> half 16


def test_equal_streams_reduce_to_standard_rope():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((5, 4, 32)), jnp.float32)
    pos = jnp.asarray([3, 9, 0, 17, 2], jnp.int32)
    std = rope_ops.apply_rope(x, pos, 10000.0)
    tri = rope_ops.apply_mrope(
        x, jnp.stack([pos, pos, pos]), 10000.0, SECTION
    )
    np.testing.assert_allclose(np.asarray(tri), np.asarray(std), atol=1e-6)
    # and diverging streams actually change the rotation
    tri2 = rope_ops.apply_mrope(
        x, jnp.stack([pos, pos + 1, pos]), 10000.0, SECTION
    )
    assert not np.allclose(np.asarray(tri2), np.asarray(std))


def _tiny_hf():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2VLConfig, Qwen2VLForConditionalGeneration

    cfg = Qwen2VLConfig(
        vision_config=dict(
            depth=2, embed_dim=64, num_heads=4, patch_size=8,
            spatial_merge_size=2, temporal_patch_size=2, mlp_ratio=4,
            hidden_size=128, image_size=32,
        ),
        hidden_size=128, intermediate_size=256, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, vocab_size=512,
        max_position_embeddings=512, rope_theta=10000.0,
        rope_scaling={"type": "mrope", "mrope_section": list(SECTION)},
        image_token_id=7, vision_start_token_id=8, vision_end_token_id=9,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    with torch.no_grad():
        return Qwen2VLForConditionalGeneration(cfg).eval().float(), cfg


# prompt: text, text, <vision_start>, 4x<image>, <vision_end>, text
PROMPT = [10, 20, 8, 7, 7, 7, 7, 9, 30]
MM_POS = [3, 4, 5, 6]


def test_engine_positions_match_hf_get_rope_index():
    torch = pytest.importorskip("torch")

    hf, cfg = _tiny_hf()
    ids = torch.tensor([PROMPT])
    grid = torch.tensor([[1, 4, 4]])
    hf_pos, hf_delta = hf.model.get_rope_index(
        ids, image_grid_thw=grid, attention_mask=torch.ones_like(ids)
    )
    # ours
    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import (
        EngineRequest, InferenceEngine, _Seq,
    )
    from xllm_service_tpu.runtime.executor import ModelExecutor
    import dataclasses

    from xllm_service_tpu.models.configs import get_model_config

    mcfg = dataclasses.replace(
        get_model_config("llama3-tiny"), mrope_section=SECTION
    )
    ecfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=16, num_blocks=32,
        max_running_requests=2, max_seq_len=128, prefill_buckets=[16, 32],
    )
    eng = InferenceEngine(
        ecfg, executor=ModelExecutor(ecfg, model_cfg=mcfg)
    )
    seq = _Seq(
        EngineRequest(
            "m", PROMPT, SamplingParams(), lambda o: True,
            mm_embeds=np.zeros((4, 128), np.float32), mm_positions=MM_POS,
        ),
        0,
    )
    ours = eng._mrope_positions(seq)
    np.testing.assert_array_equal(ours, hf_pos[:, 0].numpy())
    assert seq.rope_delta == int(hf_delta[0])


def test_full_model_greedy_parity_with_hf(tmp_path):
    """Tiny HF Qwen2-VL vs our engine on the SAME weights and image:
    identical greedy continuations. The tower embeds are taken from HF's
    visual (tower parity is pinned separately in test_multimodal), so
    this isolates the LLM's M-RoPE streams + rope_delta decode path."""
    torch = pytest.importorskip("torch")

    hf, cfg = _tiny_hf()
    # ---- export the text stack in Qwen2 layout + combined config
    from xllm_service_tpu.runtime import weights as W

    ckpt = str(tmp_path / "q2vl")
    _os.makedirs(ckpt, exist_ok=True)
    tensors = {}
    for n, p in hf.named_parameters():
        if n.startswith("model.language_model."):
            n = "model." + n[len("model.language_model."):]
        elif n.startswith("model.visual."):
            n = n[len("model."):]
        tensors[n] = p.detach().numpy()
    if "lm_head.weight" not in tensors:  # tied embeddings
        tensors["lm_head.weight"] = tensors["model.embed_tokens.weight"]
    W.write_safetensors(_os.path.join(ckpt, "model.safetensors"), tensors)
    with open(_os.path.join(ckpt, "config.json"), "w") as f:
        _json.dump({
            "architectures": ["Qwen2VLForConditionalGeneration"],
            "model_type": "qwen2_vl",
            "vocab_size": 512, "hidden_size": 128,
            "intermediate_size": 256, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "rope_theta": 10000.0, "rms_norm_eps": 1e-6,
            "max_position_embeddings": 512,
            "tie_word_embeddings": bool(cfg.tie_word_embeddings),
            "rope_scaling": {"type": "mrope",
                             "mrope_section": list(SECTION)},
            "vision_config": {
                "model_type": "qwen2_vl", "embed_dim": 64, "depth": 2,
                "num_heads": 4, "patch_size": 8, "image_size": 32,
                "mlp_ratio": 4, "spatial_merge_size": 2,
                "temporal_patch_size": 2, "hidden_size": 128,
            },
        }, f)

    # ---- the image: identical pixel patches on both sides
    from xllm_service_tpu.models import vision as V

    vcfg = V.get_vision_config("qwen2vl-tiny")
    rng = np.random.default_rng(3)
    img = rng.random((1, 32, 32, 3)).astype(np.float32)
    rows, _, _ = V._qwen2vl_patch_rows(jnp.asarray(img), vcfg)
    with torch.no_grad():
        embeds = hf.model.visual(
            torch.from_numpy(np.asarray(rows[0], np.float32)),
            grid_thw=torch.tensor([[1, 4, 4]]),
        ).numpy()  # [4, 128]

    # ---- HF greedy continuation
    ids = torch.tensor([PROMPT])
    with torch.no_grad():
        hf_out = hf.generate(
            input_ids=ids,
            pixel_values=torch.from_numpy(np.asarray(rows[0], np.float32)),
            image_grid_thw=torch.tensor([[1, 4, 4]]),
            attention_mask=torch.ones_like(ids),
            max_new_tokens=6, do_sample=False,
        )
    want = hf_out[0, len(PROMPT):].tolist()

    # ---- ours: engine over the combined checkpoint, HF embeds injected
    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.runtime.executor import ModelExecutor

    ecfg = EngineConfig(
        model="q2vl", dtype="float32", checkpoint_path=ckpt, block_size=16,
        num_blocks=32, max_running_requests=2, max_seq_len=128,
        prefill_buckets=[16, 32],
    )
    ex = ModelExecutor(ecfg)
    assert ex.cfg.mrope_section == SECTION
    eng = InferenceEngine(ecfg, executor=ex)
    got = []

    def cb(o):
        for s in o.outputs:
            got.extend(s.token_ids)
        return True

    eng.add_request(EngineRequest(
        "p", PROMPT,
        SamplingParams(temperature=0.0, max_new_tokens=6), cb,
        mm_embeds=embeds, mm_positions=MM_POS,
    ))
    for _ in range(60):
        if not eng.has_work():
            break
        eng.step()
    assert got == want, (got, want)

def test_media_seq_survives_preemption_with_exact_positions():
    """A preempted media sequence re-prefills prompt + generated tokens;
    the M-RoPE streams must extend over the generated history with the
    compressed continuation (review finding, r4) — the resumed greedy
    continuation equals an undisturbed run."""
    import dataclasses

    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.models.configs import get_model_config
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
    from xllm_service_tpu.runtime.executor import ModelExecutor

    mcfg = dataclasses.replace(
        get_model_config("llama3-tiny"), mrope_section=SECTION
    )
    embeds = np.random.default_rng(2).standard_normal(
        (4, 128)
    ).astype(np.float32)

    def run(disturb: bool):
        ecfg = EngineConfig(
            model="llama3-tiny", dtype="float32", block_size=16,
            num_blocks=48, max_running_requests=3, max_seq_len=128,
            prefill_buckets=[16, 32, 64],
        )
        eng = InferenceEngine(
            ecfg, executor=ModelExecutor(ecfg, model_cfg=mcfg, init_seed=4)
        )
        got = {}

        def cb(tag):
            def f(o):
                for s in o.outputs:
                    got.setdefault(tag, []).extend(s.token_ids)
                return True
            return f

        eng.add_request(EngineRequest(
            "victim", PROMPT,
            SamplingParams(temperature=0.0, max_new_tokens=24),
            cb("victim"), mm_embeds=embeds, mm_positions=MM_POS,
            offline=True,
        ))
        for _ in range(6):
            eng.step()
        if disturb:
            # online burst preempts the running offline media decode
            for i in range(3):
                eng.add_request(EngineRequest(
                    f"on{i}", [11, 12, 13],
                    SamplingParams(temperature=0.0, max_new_tokens=4),
                    cb(f"on{i}"),
                ))
        for _ in range(400):
            if not eng.has_work():
                break
            eng.step()
        return got["victim"]

    undisturbed = run(False)
    resumed = run(True)
    assert len(undisturbed) == 24
    assert resumed == undisturbed


def test_epd_qwen2vl_combined_checkpoint_uses_mrope(tmp_path):
    """The production Qwen2-VL EPD shape: ONE combined checkpoint dir —
    the ENCODE instance hosts its visual tower, the LM instance its text
    stack (mrope_section from config.json) — served over the full HTTP
    path. The LM engine must actually engage the M-RoPE streams for the
    image span."""
    torch = pytest.importorskip("torch")
    import time

    jax.config.update("jax_platforms", "cpu")
    from xllm_service_tpu.api import Master
    from xllm_service_tpu.api.instance import InstanceServer
    from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
    from xllm_service_tpu.coordination import MemoryStore
    from xllm_service_tpu.runtime import weights as W
    from tests.test_api_e2e import http_post, wait_until
    from tests.test_multimodal import _raw_data_url

    hf, cfg = _tiny_hf()
    ckpt = str(tmp_path / "q2vl-epd")
    _os.makedirs(ckpt, exist_ok=True)
    tensors = {}
    for n, p in hf.named_parameters():
        if n.startswith("model.language_model."):
            n = "model." + n[len("model.language_model."):]
        elif n.startswith("model.visual."):
            n = n[len("model."):]
        tensors[n] = p.detach().numpy()
    if "lm_head.weight" not in tensors:
        tensors["lm_head.weight"] = tensors["model.embed_tokens.weight"]
    W.write_safetensors(_os.path.join(ckpt, "model.safetensors"), tensors)
    with open(_os.path.join(ckpt, "config.json"), "w") as f:
        _json.dump({
            "architectures": ["Qwen2VLForConditionalGeneration"],
            "model_type": "qwen2_vl",
            "vocab_size": 512, "hidden_size": 128,
            "intermediate_size": 256, "num_hidden_layers": 2,
            "num_attention_heads": 4, "num_key_value_heads": 2,
            "rope_theta": 10000.0, "rms_norm_eps": 1e-6,
            "max_position_embeddings": 512,
            "tie_word_embeddings": bool(cfg.tie_word_embeddings),
            "rope_scaling": {"type": "mrope",
                             "mrope_section": list(SECTION)},
            "vision_config": {
                "model_type": "qwen2_vl", "embed_dim": 64, "depth": 2,
                "num_heads": 4, "patch_size": 8, "image_size": 32,
                "mlp_ratio": 4, "spatial_merge_size": 2,
                "temporal_patch_size": 2, "hidden_size": 128,
            },
        }, f)

    store = MemoryStore(clock=lambda: 0.0)
    master = Master(ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=1.0, block_size=16,
        mm_tokens_per_media=4,
    ), store=store)
    master.start()

    def mk(name, itype):
        ecfg = EngineConfig(
            model="q2vl", dtype="float32", block_size=16, num_blocks=64,
            max_running_requests=4, max_seq_len=256,
            prefill_buckets=[32, 64, 128], instance_name=name,
            instance_type=itype, checkpoint_path=ckpt,
        )
        srv = InstanceServer(
            ecfg, master_rpc_addr=master.rpc_address,
            heartbeat_interval_s=0.2,
        )
        srv.start()
        return srv

    enc = mk("mr-e", "ENCODE")
    mix = mk("mr-m", "MIX")
    try:
        assert mix.engine.executor.cfg.mrope_section == SECTION
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts()[2] == 1
            and sum(master.scheduler.instance_mgr.counts()) == 2
        )
        img = np.full((32, 32, 3), 0.7, np.float32)
        code, body = http_post(
            master.http_address, "/v1/chat/completions",
            {"model": "q2vl", "max_tokens": 6, "temperature": 0.0,
             "messages": [{"role": "user", "content": [
                 {"type": "text", "text": "d "},
                 {"type": "image_url",
                  "image_url": {"url": _raw_data_url(img)}},
             ]}]},
            timeout=300.0,
        )
        assert code == 200, body
        # the LM engine built (t, h, w) streams for the image span
        deadline = time.monotonic() + 5
        used = False
        while time.monotonic() < deadline and not used:
            used = any(
                s.rope_pos3 is not None
                for s in list(mix.engine._running.values())
            ) or getattr(mix.engine, "_mrope_seen", False)
            time.sleep(0.05)
        # _running may already be empty (request finished): assert via a
        # direct engine-level probe instead when so
        if not used:
            from xllm_service_tpu.runtime.engine import _Seq, EngineRequest
            from xllm_service_tpu.ops.sampling import SamplingParams

            seq = _Seq(EngineRequest(
                "probe", PROMPT, SamplingParams(), lambda o: True,
                mm_embeds=np.zeros((4, 128), np.float32),
                mm_positions=MM_POS,
            ), 0)
            assert mix.engine._mrope_active(seq)
            pos = mix.engine._mrope_positions(seq)
            assert pos[1, 3] != pos[2, 4] or seq.rope_delta < 0
        assert body["choices"][0]["message"]["content"]
    finally:
        enc.stop()
        mix.stop()
        master.stop()
        store.close()


def test_epd_qwen25vl_combined_checkpoint(tmp_path):
    """Qwen2.5-VL production EPD shape: one combined checkpoint dir
    (visual.* window-attention tower + Qwen2 text stack with
    mrope_section) served over the full HTTP path."""
    torch = pytest.importorskip("torch")
    try:
        from transformers.models.qwen2_5_vl.configuration_qwen2_5_vl import (
            Qwen2_5_VLVisionConfig,
        )
        from transformers.models.qwen2_5_vl.modeling_qwen2_5_vl import (
            Qwen2_5_VisionTransformerPretrainedModel,
        )
    except Exception:
        pytest.skip("transformers lacks Qwen2.5-VL")

    jax.config.update("jax_platforms", "cpu")
    from xllm_service_tpu.api import Master
    from xllm_service_tpu.api.instance import InstanceServer
    from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
    from xllm_service_tpu.coordination import MemoryStore
    from xllm_service_tpu.models import vision as V
    from xllm_service_tpu.runtime import weights as W
    from tests.test_api_e2e import http_post, wait_until
    from tests.test_multimodal import _raw_data_url

    vcfg = V.get_vision_config("qwen25vl-tiny")
    hf_vis_cfg = Qwen2_5_VLVisionConfig(
        depth=vcfg.num_layers, hidden_size=vcfg.hidden_size,
        intermediate_size=vcfg.intermediate_size,
        out_hidden_size=128, num_heads=vcfg.num_heads,
        patch_size=vcfg.patch_size,
        spatial_merge_size=vcfg.spatial_merge_size,
        temporal_patch_size=vcfg.temporal_patch_size,
        window_size=vcfg.window_size,
        fullatt_block_indexes=list(vcfg.fullatt_block_indexes),
        hidden_act="silu", attn_implementation="eager",
    )
    torch.manual_seed(1)
    with torch.no_grad():
        tower = (
            Qwen2_5_VisionTransformerPretrainedModel(hf_vis_cfg)
            .eval().float()
        )
    # text side: tiny llama-layout stack exported in Qwen2 layout
    import dataclasses

    from xllm_service_tpu.models import llama
    from xllm_service_tpu.models.configs import get_model_config

    lcfg = dataclasses.replace(
        get_model_config("llama3-tiny"), name="q25vl-text", attn_bias=True
    )
    lparams = llama.init_params(lcfg, jax.random.key(8), dtype=jnp.float32)
    ckpt = str(tmp_path / "q25vl-full")
    W.save_hf_checkpoint(lparams, lcfg, ckpt)
    tensors = dict(
        W.read_safetensors(_os.path.join(ckpt, "model.safetensors"))
    )
    tensors = {k: np.array(v) for k, v in tensors.items()}
    for n, p in tower.named_parameters():
        tensors["visual." + n] = p.detach().numpy()
    W.write_safetensors(_os.path.join(ckpt, "model.safetensors"), tensors)
    with open(_os.path.join(ckpt, "config.json")) as f:
        combined = _json.load(f)
    combined["architectures"] = ["Qwen2_5_VLForConditionalGeneration"]
    combined["model_type"] = "qwen2_5_vl"
    combined["rope_scaling"] = {
        "type": "mrope", "mrope_section": list(SECTION)
    }
    combined["vision_config"] = {
        "model_type": "qwen2_5_vl",
        "hidden_size": vcfg.hidden_size,
        "intermediate_size": vcfg.intermediate_size,
        "out_hidden_size": 128,
        "depth": vcfg.num_layers, "num_heads": vcfg.num_heads,
        "patch_size": vcfg.patch_size, "image_size": vcfg.image_size,
        "spatial_merge_size": vcfg.spatial_merge_size,
        "temporal_patch_size": vcfg.temporal_patch_size,
        "window_size": vcfg.window_size,
        "fullatt_block_indexes": list(vcfg.fullatt_block_indexes),
    }
    with open(_os.path.join(ckpt, "config.json"), "w") as f:
        _json.dump(combined, f)

    store = MemoryStore(clock=lambda: 0.0)
    master = Master(ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=1.0, block_size=16,
        mm_tokens_per_media=vcfg.out_tokens,  # 16
    ), store=store)
    master.start()

    def mk(name, itype):
        ecfg = EngineConfig(
            model="q25vl", dtype="float32", block_size=16, num_blocks=64,
            max_running_requests=4, max_seq_len=256,
            prefill_buckets=[32, 64, 128], instance_name=name,
            instance_type=itype, checkpoint_path=ckpt,
        )
        srv = InstanceServer(
            ecfg, master_rpc_addr=master.rpc_address,
            heartbeat_interval_s=0.2,
        )
        srv.start()
        return srv

    enc = mk("q25-e", "ENCODE")
    mix = mk("q25-m", "MIX")
    try:
        assert mix.engine.executor.cfg.mrope_section == SECTION
        assert enc.engine.executor.cfg.arch == "qwen25vl"
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts()[2] == 1
            and sum(master.scheduler.instance_mgr.counts()) == 2
        )
        img_a = np.full((64, 64, 3), 0.9, np.float32)
        img_b = np.zeros((64, 64, 3), np.float32)

        def ask(img):
            code, body = http_post(
                master.http_address, "/v1/chat/completions",
                {"model": "q25vl", "max_tokens": 6, "temperature": 0.0,
                 "messages": [{"role": "user", "content": [
                     {"type": "text", "text": "d "},
                     {"type": "image_url",
                      "image_url": {"url": _raw_data_url(img)}},
                 ]}]},
                timeout=300.0,
            )
            assert code == 200, body
            return body["choices"][0]["message"]["content"]

        assert ask(img_a) != ask(img_b)
    finally:
        enc.stop()
        mix.stop()
        master.stop()
        store.close()
