"""Full-stack e2e with the REAL JAX engine behind the instance server:
curl-shaped HTTP -> master -> forwarded prefill -> continuous-batching
engine on CPU -> generations push -> SSE/JSON back. Also checks the engine's
KV cache events reach the master's global prefix index (the KV Cache Pool
pipeline, SURVEY.md §3.4).
"""

import http.client
import json
import time

import pytest

from xllm_service_tpu.api import Master
from xllm_service_tpu.api.instance import InstanceServer
from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
from xllm_service_tpu.coordination import MemoryStore

from tests.test_api_e2e import http_get, http_post, sse_post, wait_until

BLOCK = 16


@pytest.fixture(scope="module")
def stack():
    store = MemoryStore(clock=lambda: 0.0)  # frozen: leases never lapse under GIL stalls
    cfg = ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=1.0,
        load_balance_policy="CAR", block_size=BLOCK,
    )
    master = Master(cfg, store=store)
    master.start()
    ecfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=BLOCK,
        num_blocks=64, max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64, 128],
        instance_name="real0", instance_type="MIX",
    )
    inst = InstanceServer(
        ecfg, master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.2
    )
    inst.start()
    assert wait_until(lambda: sum(master.scheduler.instance_mgr.counts()) == 1)
    yield master, inst, store
    inst.stop()
    master.stop()
    store.close()


def test_nonstream_completion(stack):
    master, inst, _ = stack
    code, body = http_post(
        master.http_address, "/v1/completions",
        {"model": "llama3-tiny", "prompt": "hello world", "max_tokens": 8,
         "temperature": 0.0},
        timeout=300.0,
    )
    assert code == 200, body
    c = body["choices"][0]
    assert c["finish_reason"] in ("stop", "length")
    assert body["usage"]["completion_tokens"] >= 1
    assert isinstance(c["text"], str) and c["text"]


def test_stream_completion_and_determinism(stack):
    master, _, _ = stack
    req = {"model": "llama3-tiny", "prompt": "hello world", "max_tokens": 8,
           "temperature": 0.0, "stream": True}
    events = sse_post(master.http_address, "/v1/completions", req, timeout=300.0)
    assert events[-1] == "[DONE]"
    text = "".join(
        e["choices"][0]["text"] for e in events[:-1] if e.get("choices")
    )
    # greedy decode must match the non-stream result for the same prompt
    code, body = http_post(
        master.http_address, "/v1/completions",
        {**req, "stream": False}, timeout=300.0,
    )
    assert text == body["choices"][0]["text"]


def test_cache_events_reach_global_index(stack):
    master, _, _ = stack
    # a prompt longer than one block must commit prefix blocks -> heartbeat
    # -> master's global KV index
    prompt = "x" * (BLOCK * 3)
    http_post(
        master.http_address, "/v1/completions",
        {"model": "llama3-tiny", "prompt": prompt, "max_tokens": 2,
         "temperature": 0.0},
        timeout=300.0,
    )
    ids = master.scheduler.tokenizer.encode(prompt)

    def matched():
        return master.scheduler.kvcache_mgr.match(ids).hbm_scores.get("real0", 0)

    assert wait_until(lambda: matched() >= 1, timeout=10.0)


def test_chat_stream(stack):
    master, _, _ = stack
    events = sse_post(
        master.http_address, "/v1/chat/completions",
        {"model": "llama3-tiny",
         "messages": [{"role": "user", "content": "hi"}],
         "max_tokens": 4, "temperature": 0.0, "stream": True},
        timeout=300.0,
    )
    assert events[-1] == "[DONE]"
    assert events[0]["choices"][0]["delta"].get("role") == "assistant"


def test_embeddings_endpoint(stack):
    """/v1/embeddings end-to-end (the reference 501s this endpoint —
    serving it exceeds parity): master tokenizes, instance pools
    normalized hidden states; deterministic, unit-norm, input-sensitive."""
    import numpy as np

    master, inst, _ = stack
    code, body = http_post(
        master.http_address, "/v1/embeddings",
        {"model": "llama3-tiny",
         "input": ["hello world", "a very different sentence"]},
        timeout=300.0,
    )
    assert code == 200, body
    assert body["object"] == "list" and len(body["data"]) == 2
    v0 = np.asarray(body["data"][0]["embedding"], np.float32)
    v1 = np.asarray(body["data"][1]["embedding"], np.float32)
    assert v0.shape == (128,)  # llama3-tiny hidden_size
    np.testing.assert_allclose(np.linalg.norm(v0), 1.0, atol=1e-3)
    np.testing.assert_allclose(np.linalg.norm(v1), 1.0, atol=1e-3)
    assert abs(float(v0 @ v1)) < 0.999  # different inputs, different vectors
    assert body["usage"]["prompt_tokens"] > 0

    # Determinism + single-string form.
    code2, body2 = http_post(
        master.http_address, "/v1/embeddings",
        {"model": "llama3-tiny", "input": "hello world"},
        timeout=60.0,
    )
    assert code2 == 200
    np.testing.assert_allclose(
        np.asarray(body2["data"][0]["embedding"], np.float32), v0, atol=1e-5
    )

    # Validation errors.
    code3, body3 = http_post(
        master.http_address, "/v1/embeddings", {"input": []}, timeout=30.0
    )
    assert code3 == 400
