"""Native SentencePiece-Unigram family (native/sp_tokenizer.cpp +
tokenizer/native_sp.py) — the reference's sentencepiece_tokenizer.cpp
analog. The .model fixtures are hand-built protobufs (the sentencepiece
pip package is not in this image), and Viterbi optimality is pinned to a
pure-Python dynamic-programming oracle over the same pieces.
"""

import json
import os
import struct

import pytest

from xllm_service_tpu.tokenizer import create_tokenizer
from xllm_service_tpu.tokenizer.native_sp import NativeSPTokenizer, try_load


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        out += bytes([b | (0x80 if n else 0)])
        if not n:
            return out


def _piece(p: str, score: float, t: int = 1) -> bytes:
    body = b"\x0a" + _varint(len(p.encode())) + p.encode()
    body += b"\x15" + struct.pack("<f", score)
    body += b"\x18" + _varint(t)
    return b"\x0a" + _varint(len(body)) + body


def _write_model(dirpath, pieces, add_dummy_prefix=True):
    blob = b"".join(_piece(*p) for p in pieces)
    norm = (
        (b"\x18\x01" if add_dummy_prefix else b"\x18\x00")
        + b"\x20\x01"  # remove_extra_whitespaces
        + b"\x28\x01"  # escape_whitespaces
    )
    blob += b"\x1a" + _varint(len(norm)) + norm
    with open(os.path.join(dirpath, "tokenizer.model"), "wb") as f:
        f.write(blob)


BASE_PIECES = [
    ("<unk>", 0.0, 2), ("<s>", 0.0, 3), ("</s>", 0.0, 3),
    ("▁hello", -1.0, 1), ("▁world", -1.2, 1), ("▁", -2.0, 1),
    ("hello", -3.0, 1), ("he", -4.0, 1), ("llo", -4.5, 1),
    ("wor", -5.0, 1), ("ld", -5.0, 1), ("lo", -6.0, 1),
] + [(c, -8.0, 1) for c in "abcdefghijklmnopqrstuvwxyz"]


@pytest.fixture()
def sp_dir(tmp_path):
    _write_model(str(tmp_path), BASE_PIECES)
    return str(tmp_path)


def _oracle(pieces, text, add_dummy_prefix=True):
    """Reference Viterbi (max sum of piece scores; UNK penalty
    min_score - 10 per unknown char), over the escaped text."""
    table = {
        p: (i, s) for i, (p, s, t) in enumerate(pieces) if t in (1, 4)
    }
    unk = next(i for i, (_, _, t) in enumerate(pieces) if t == 2)
    min_score = min(s for _, s, _ in pieces)
    s = text.replace(" ", "▁")
    if add_dummy_prefix and s:
        s = "▁" + s
    n = len(s)
    best = [-1e30] * (n + 1)
    back = [None] * (n + 1)
    best[0] = 0.0
    for i in range(n):
        if best[i] <= -1e29:
            continue
        for j in range(i + 1, n + 1):
            sub = s[i:j]
            if sub in table:
                pid, sc = table[sub]
                if best[i] + sc > best[j]:
                    best[j] = best[i] + sc
                    back[j] = (i, pid)
        j = i + 1
        cand = best[i] + min_score - 10.0
        if cand > best[j]:
            best[j] = cand
            back[j] = (i, unk)
    ids = []
    pos = n
    while pos > 0:
        i, pid = back[pos]
        ids.append(pid)
        pos = i
    return ids[::-1]


def test_viterbi_matches_oracle(sp_dir):
    tok = try_load(sp_dir)
    assert isinstance(tok, NativeSPTokenizer)
    for text in [
        "hello world", "held", "low", "hello", "woldhello",
        "a b c", "world world world", "",
    ]:
        assert tok.encode(text) == _oracle(BASE_PIECES, text), text


def test_roundtrip_and_specials(sp_dir):
    with open(os.path.join(sp_dir, "tokenizer_config.json"), "w") as f:
        json.dump({"bos_token": "<s>", "eos_token": "</s>"}, f)
    tok = try_load(sp_dir)
    assert tok.decode(tok.encode("hello world")) == "hello world"
    assert tok.bos_token_id == 1 and tok.eos_token_id == 2
    assert tok.id_to_token(3) == "▁hello"
    assert tok.token_to_id("▁world") == 4
    assert tok.vocab_size == len(BASE_PIECES)


def test_unknown_char_falls_to_unk(sp_dir):
    tok = try_load(sp_dir)
    ids = tok.encode("Q")
    assert tok._unk in ids


def test_byte_fallback_model(tmp_path):
    """A model with the full <0xXX> byte alphabet encodes unknown chars
    as byte pieces, and decode restores the exact text."""
    pieces = [("<unk>", 0.0, 2), ("▁", -2.0, 1)]
    pieces += [(c, -6.0, 1) for c in "xyz"]
    byte_base = len(pieces)
    pieces += [(f"<0x{b:02X}>", -9.0, 6) for b in range(256)]
    _write_model(str(tmp_path), pieces)
    tok = try_load(str(tmp_path))
    assert tok is not None
    ids = tok.encode("xQz")  # Q and é have no pieces -> bytes
    toks = [tok.id_to_token(i) for i in ids]
    assert "<0x51>" in toks, toks  # 'Q'
    assert tok.decode(ids) == "xQz"
    ids2 = tok.encode("é")
    assert tok.decode(ids2) == "é"  # two UTF-8 bytes restored


def test_factory_selects_native_sp(sp_dir):
    tok = create_tokenizer(sp_dir)
    assert isinstance(tok, NativeSPTokenizer)


def test_charsmap_models_decline(tmp_path):
    """A model whose normalizer carries a precompiled charsmap (NFKC) is
    OUT of the native family's scope — try_load must decline so the
    factory falls back to transformers."""
    blob = b"".join(_piece(*p) for p in BASE_PIECES)
    norm = b"\x12" + _varint(4) + b"\x01\x02\x03\x04" + b"\x18\x01"
    blob += b"\x1a" + _varint(len(norm)) + norm
    with open(os.path.join(tmp_path, "tokenizer.model"), "wb") as f:
        f.write(blob)
    assert try_load(str(tmp_path)) is None


def test_special_tokens_split_from_text(sp_dir):
    """Chat templates inject special tokens as TEXT ('<s>...'); encode
    must emit their control ids, never Viterbi-segment the surface form
    (real sentencepiece excludes CONTROL pieces from matching too)."""
    tok = try_load(sp_dir)
    ids = tok.encode("<s>hello world</s>")
    assert ids[0] == 1 and ids[-1] == 2, ids
    inner = ids[1:-1]
    assert inner == tok.encode("hello world")


def test_embedded_nul_byte(tmp_path):
    """Explicit-length ABI: a NUL byte mid-text must not truncate (byte
    fallback encodes it like real sentencepiece)."""
    pieces = [("<unk>", 0.0, 2), ("▁", -2.0, 1)]
    pieces += [(c, -6.0, 1) for c in "ab"]
    pieces += [(f"<0x{b:02X}>", -9.0, 6) for b in range(256)]
    _write_model(str(tmp_path), pieces)
    tok = try_load(str(tmp_path))
    ids = tok.encode("a\x00b")
    toks = [tok.id_to_token(i) for i in ids]
    assert "<0x00>" in toks, toks
    assert tok.decode(ids) == "a\x00b"
