"""EtcdGatewayStore against a REAL etcd binary (VERDICT r3 #7).

The fake-gateway suite (tests/test_etcd_gateway.py) pins the wire
protocol; this suite validates the semantics only real etcd enforces —
server-side lease TTL expiry, the v3 watch stream, compare-create txns
under contention, and master failover driven by a real lease lapsing.

The build image ships no etcd and installs are off, so the suite
auto-skips unless an `etcd` binary is on PATH or named by
XLLM_ETCD_BIN. Run it wherever etcd exists:

    XLLM_ETCD_BIN=/usr/local/bin/etcd python -m pytest tests/test_etcd_real.py

Reference semantics being matched: etcd_client.cpp:47-62 (TTL-lease
compare-create election), :90-99 (guarded txn deletes), :156-193
(watch streams).
"""

import os
import shutil
import socket
import subprocess
import time

import pytest

from xllm_service_tpu.coordination.store import EtcdGatewayStore, EventType

ETCD = os.environ.get("XLLM_ETCD_BIN") or shutil.which("etcd")

pytestmark = pytest.mark.skipif(
    ETCD is None,
    reason="no etcd binary (set XLLM_ETCD_BIN or put etcd on PATH)",
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def etcd_addr(tmp_path):
    client = _free_port()
    peer = _free_port()
    proc = subprocess.Popen(
        [
            ETCD,
            "--data-dir", str(tmp_path / "etcd-data"),
            "--listen-client-urls", f"http://127.0.0.1:{client}",
            "--advertise-client-urls", f"http://127.0.0.1:{client}",
            "--listen-peer-urls", f"http://127.0.0.1:{peer}",
            "--initial-advertise-peer-urls", f"http://127.0.0.1:{peer}",
            "--initial-cluster", f"default=http://127.0.0.1:{peer}",
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    addr = f"127.0.0.1:{client}"
    try:
        deadline = time.monotonic() + 20
        last = None
        while time.monotonic() < deadline:
            try:
                EtcdGatewayStore(addr)  # ctor pings
                break
            except Exception as e:  # noqa: BLE001
                last = e
                time.sleep(0.2)
        else:
            raise RuntimeError(f"etcd never came up: {last}")
        yield addr
    finally:
        proc.terminate()
        proc.wait(timeout=10)


def _wait(pred, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def test_kv_txn_and_prefix_real(etcd_addr):
    st = EtcdGatewayStore(etcd_addr)
    assert st.get("missing") is None
    st.set("XLLM:PREFILL:a", "1")
    st.set("XLLM:PREFILL:b", '{"x": "ünïcode"}')
    assert st.get("XLLM:PREFILL:b") == '{"x": "ünïcode"}'
    assert st.get_prefix("XLLM:PREFILL:") == {
        "XLLM:PREFILL:a": "1",
        "XLLM:PREFILL:b": '{"x": "ünïcode"}',
    }
    # compare-create under contention: exactly one winner
    assert st.compare_create("XLLM:SERVICE:MASTER", "m1")
    assert not st.compare_create("XLLM:SERVICE:MASTER", "m2")
    # guarded removes re-check the guard (etcd_client.cpp:90-99)
    st.set("guard", "me")
    st.set("a", "1")
    assert not st.guarded_remove(["a"], "guard", "not-me")
    assert st.get("a") == "1"
    assert st.guarded_remove(["a"], "guard", "me")
    assert st.get("a") is None


def test_real_lease_ttl_expires_key(etcd_addr):
    """Real server-side TTL: a key under an un-kept lease vanishes after
    the TTL (the liveness mechanism instance registration rides)."""
    st = EtcdGatewayStore(etcd_addr)
    lid = st.grant_lease(1.0)  # etcd clamps to >= 1s
    st.set("XLLM:MIX:inst0", "meta", lease_id=lid)
    assert st.get("XLLM:MIX:inst0") == "meta"
    assert st.keepalive(lid)
    assert _wait(lambda: st.get("XLLM:MIX:inst0") is None, timeout=20.0)
    assert not st.keepalive(lid)


def test_real_watch_stream(etcd_addr):
    st = EtcdGatewayStore(etcd_addr)
    got = []
    wid = st.add_watch("XLLM:WATCHME:", lambda evs: got.extend(evs))
    time.sleep(0.5)
    st.set("XLLM:WATCHME:a", "v1")
    st.set("XLLM:OTHER:z", "ignored")
    st.remove("XLLM:WATCHME:a")
    assert _wait(lambda: len(got) >= 2)
    assert got[0].type == EventType.PUT and got[0].value == "v1"
    assert got[1].type == EventType.DELETE
    assert all(not e.key.startswith("XLLM:OTHER") for e in got)
    st.remove_watch(wid)


def test_real_lease_expiry_fires_watch_delete(etcd_addr):
    """The full failure-detection chain on real etcd: lease lapses ->
    etcd deletes the key -> the watch stream delivers DELETE (what
    drives instance removal + request re-dispatch)."""
    st = EtcdGatewayStore(etcd_addr)
    got = []
    st.add_watch("XLLM:MIX:", lambda evs: got.extend(evs))
    time.sleep(0.5)
    lid = st.grant_lease(1.0)
    st.set("XLLM:MIX:dying", "meta", lease_id=lid)
    assert _wait(
        lambda: any(
            e.type == EventType.DELETE and e.key == "XLLM:MIX:dying"
            for e in got
        ),
        timeout=20.0,
    )


def test_real_master_failover(etcd_addr):
    """Two MasterElection replicas on real etcd: one wins; when it stops
    keeping its lease alive, the real TTL lapses and the other takes
    over via its watch."""
    from xllm_service_tpu.coordination import MasterElection

    e1 = MasterElection(
        EtcdGatewayStore(etcd_addr), "replica-1", lease_ttl_s=1.0
    )
    e2 = MasterElection(
        EtcdGatewayStore(etcd_addr), "replica-2", lease_ttl_s=1.0
    )
    e1.start()
    assert _wait(lambda: e1.is_master)
    e2.start()
    time.sleep(0.5)
    assert not e2.is_master
    # CRASH, not graceful stop (stop() revokes the lease): cease
    # keepalives and let the REAL server-side TTL lapse.
    e1._stop.set()
    assert _wait(lambda: e2.is_master, timeout=30.0)
    e1.stop()
    e2.stop()