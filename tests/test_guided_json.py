"""Guided decoding: the byte-level JSON automaton and its abstract
token-mask table."""

import json

import numpy as np
import pytest

from xllm_service_tpu.guided import json_fsm as J


def accepts(text: str, top_object: bool = True) -> bool:
    st = J.advance_bytes(
        J.initial_state(), text.encode(), top_object=top_object
    )
    return J.is_complete(st)


VALID_OBJECTS = [
    '{}',
    '{"a": 1}',
    '{"a": -0.5e+3, "b": [1, 2, {"c": null}], "d": "x\\n\\"y\\u00e9"}',
    '{"nested": {"deep": [[], {}, [true, false]]}}',
    ' { "ws" : [ 1 , 2 ] } ',
    '{"empty": [], "eo": {}}',
    '{"num": 0, "n2": 0.5, "n3": 10e2, "n4": -0}',
]

INVALID = [
    '',
    '  {"a": 1}',    # ws runs cap at ONE byte (budget-exhaustion guard)
    '{"a":  1}',
    '[1, 2]',        # top level must be an object in json_object mode
    '"str"',
    '{',
    '{"a"}',
    '{"a": }',
    '{"a": 1,}',     # trailing comma
    '{"a": 1 "b": 2}',
    '{"a": 01}',     # leading zero
    '{"a": +1}',
    '{"a": 1.}',
    '{"a": .5}',
    '{"a": tru}',
    '{"a": truee}',
    '{"a": "\\x"}',  # bad escape
    '{"a": [1,]}',
    '{"a": 1}}',
    '{"a": "unterminated',
    "{'a': 1}",      # single quotes
    '{"a": nan}',
]


@pytest.mark.parametrize("text", VALID_OBJECTS)
def test_accepts_valid(text):
    json.loads(text)  # sanity: Python agrees it's valid
    assert accepts(text)


@pytest.mark.parametrize("text", INVALID)
def test_rejects_invalid(text):
    assert not accepts(text)


def test_top_object_false_accepts_bare_values():
    for text in ['[1, 2]', '"s"', '42', 'true', 'null', '-1.5e-3']:
        json.loads(text)
        assert accepts(text, top_object=False), text
    assert not accepts('1 2', top_object=False)


def test_random_generated_json_roundtrip():
    """Randomly built JSON objects all pass; random mutations that break
    json.loads are (almost always) rejected — and every FSM-accepted
    string MUST parse."""
    rng = np.random.default_rng(0)

    def rand_value(depth):
        kind = rng.integers(0, 6 if depth < 3 else 4)
        if kind == 0:
            return rng.integers(-1000, 1000) * (0.5 ** int(rng.integers(0, 3)))
        if kind == 1:
            return rng.choice([True, False, None])
        if kind == 2:
            chars = 'abc XYZ0"\\\n\té'
            n = int(rng.integers(0, 8))
            return ''.join(rng.choice(list(chars)) for _ in range(n))
        if kind == 3:
            return int(rng.integers(-10, 10))
        if kind == 4:
            return [rand_value(depth + 1) for _ in range(rng.integers(0, 4))]
        return {
            f"k{i}": rand_value(depth + 1)
            for i in range(rng.integers(0, 4))
        }

    for _ in range(60):
        obj = {f"k{i}": rand_value(0) for i in range(rng.integers(0, 5))}
        text = json.dumps(obj)
        assert accepts(text), text

    # FSM-accepted => json.loads parses (soundness, the property that
    # actually matters for the product)
    for _ in range(200):
        obj = {"k": rand_value(0)}
        text = json.dumps(obj)
        cut = int(rng.integers(1, len(text) + 1))
        st = J.advance_bytes(J.initial_state(), text[:cut].encode())
        if J.is_complete(st):
            json.loads(text[:cut])


def test_incremental_prefix_states_never_reject_valid():
    text = '{"a": [1, {"b": "c\\u00e9"}, null], "d": -2.5e-1}'
    st = J.initial_state()
    for b in text.encode():
        st = J.advance_byte(st, b)
        assert st is not None
    assert J.is_complete(st)


# --------------------------------------------------------- mask table


def _byte_vocab():
    """The test vocab: token id i = byte i (ByteTokenizer layout), plus a
    few multi-byte tokens at the top."""
    toks = [bytes([i]) for i in range(256)]
    toks += [b'{"', b'":', b'",', b'"}', b'true', b'null', b'1}',
             b'": ', b', "', b']}', b'}}', b'"a"', b'[]']
    return toks


def test_mask_table_soundness_greedy_walk():
    """From the initial state, repeatedly pick any allowed token and
    advance: every reachable emission stays parseable-or-extendable, and
    EOS is allowed exactly when the object is complete."""
    toks = _byte_vocab()
    eos = [3]  # arbitrary byte token reserved as EOS
    toks[3] = b""  # specials carry no bytes
    table = J.token_mask_table(toks, eos)
    assert table.shape == (J.NUM_MASK_STATES, len(toks))

    rng = np.random.default_rng(1)
    for trial in range(40):
        st = J.initial_state()
        out = b""
        for _ in range(60):
            row = table[J.abstract_index(st)]
            allowed = np.nonzero(row)[0]
            assert allowed.size, f"empty mask at {st!r} after {out!r}"
            t = int(rng.choice(allowed))
            if t == 3:  # EOS
                assert J.is_complete(st), out
                # string content may contain non-UTF8 bytes (the mask
                # constrains JSON structure, not text encoding)
                json.loads(out.decode("utf-8", errors="replace"))
                break
            nst = J.advance_bytes(st, toks[t])
            assert nst is not None, (out, toks[t], st)
            st = nst
            out += toks[t]

    # EOS allowed ONLY in DONE rows
    st = J.advance_bytes(J.initial_state(), b'{"a": 1')
    assert not table[J.abstract_index(st), 3]
    st = J.advance_bytes(J.initial_state(), b'{"a": 1}')
    assert J.is_complete(st)
    assert table[J.abstract_index(st), 3]


def test_mask_conservative_multi_close():
    """A token closing more than the visible top is mask-rejected even
    when the true stack could absorb it; single closers stay allowed."""
    toks = _byte_vocab()
    table = J.token_mask_table(toks, eos_ids=[])
    st = J.advance_bytes(J.initial_state(), b'{"a": {"b": 1')
    row = table[J.abstract_index(st)]
    assert row[ord("}")]  # close inner object
    idx_close2 = toks.index(b"}}")
    assert not row[idx_close2]  # would need to see below the top
    # after closing the inner object the host state knows the real stack
    st2 = J.advance_bytes(st, b"}")
    assert table[J.abstract_index(st2), ord("}")]


def test_deep_nesting_abstract_vs_exact_agreement():
    """For single-byte tokens the abstract mask must agree EXACTLY with
    the real automaton at any depth (conservatism only affects
    multi-close tokens)."""
    toks = [bytes([i]) for i in range(128)]
    table = J.token_mask_table(toks, eos_ids=[])
    prefixes = [
        b'{"a": [',
        b'{"a": [[',
        b'{"a": [{"b": [1, ',
        b'{"a": {"b": {"c": ',
        b'{"a": [1, 2.5, ',
        b'{"a": "str',
        b'{"a": tr',
    ]
    for p in prefixes:
        st = J.advance_bytes(J.initial_state(), p)
        assert st is not None, p
        row = table[J.abstract_index(st)]
        for b in range(128):
            real = J.advance_byte(st, b) is not None
            assert bool(row[b]) == real, (p, chr(b), bool(row[b]), real)


# ------------------------------------------------- engine + service e2e


def _engine_guided(spec=0):
    from xllm_service_tpu.common.config import EngineConfig
    from xllm_service_tpu.runtime.engine import InferenceEngine
    from xllm_service_tpu.runtime.executor import ModelExecutor
    from xllm_service_tpu.tokenizer import ByteTokenizer

    cfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=16, num_blocks=64,
        max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64, 128], speculative_tokens=spec,
    )
    eng = InferenceEngine(cfg, executor=ModelExecutor(cfg), eos_token_ids=(2,))
    tok = ByteTokenizer()
    tb = tok.token_bytes_table(eng.executor.cfg.vocab_size)
    table = J.token_mask_table(tb, eos_ids=[2])
    eng.set_guided_context(table, tb)
    return eng, tb


def _run_guided(eng, sampling, prompt=None, max_steps=300):
    from xllm_service_tpu.runtime.engine import EngineRequest

    out = {"tokens": [], "finish": None}

    def cb(o):
        for s in o.outputs:
            out["tokens"].extend(s.token_ids)
            if o.finished:
                out["finish"] = s.finish_reason
        return True

    eng.add_request(EngineRequest(
        "g", list(prompt or [10, 20, 30]), sampling, cb, guided="json",
    ))
    for _ in range(max_steps):
        if not eng.has_work():
            break
        eng.step()
    return out


@pytest.mark.parametrize("temp", [0.0, 1.0], ids=["greedy", "sampled"])
def test_engine_guided_output_is_valid_json_prefix(temp):
    """A random-weight model under the JSON mask emits a byte stream that
    the automaton never rejects; if it finished via EOS the output parses."""
    from xllm_service_tpu.ops.sampling import SamplingParams
    from xllm_service_tpu.common.types import FinishReason

    eng, tb = _engine_guided()
    out = _run_guided(
        eng, SamplingParams(temperature=temp, seed=5, max_new_tokens=60)
    )
    assert out["tokens"], "nothing generated"
    data = b"".join(tb[t] for t in out["tokens"] if t != 2)
    st = J.advance_bytes(J.initial_state(), data)
    assert st is not None, data
    assert data.lstrip()[:1] == b"{", data
    if out["finish"] == FinishReason.STOP:  # EOS: must be complete JSON
        assert J.is_complete(st), data
        json.loads(data.decode("utf-8", errors="replace"))


def test_engine_guided_spec_matches_plain():
    """Guided + speculative decoding == guided plain decoding, token for
    token (the verify scan applies the same per-position masks)."""
    from xllm_service_tpu.ops.sampling import SamplingParams

    sp = SamplingParams(temperature=0.8, seed=9, max_new_tokens=24)
    eng0, _ = _engine_guided(spec=0)
    eng3, _ = _engine_guided(spec=3)
    a = _run_guided(eng0, sp)
    b = _run_guided(eng3, sp)
    assert a["tokens"] == b["tokens"]


def test_service_response_format_e2e():
    """response_format={"type": "json_object"} through the real HTTP
    stack: output is a valid JSON prefix; unsupported types 400."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from xllm_service_tpu.api import Master
    from xllm_service_tpu.api.instance import InstanceServer
    from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
    from xllm_service_tpu.coordination import MemoryStore
    from tests.test_api_e2e import http_post, wait_until

    store = MemoryStore(clock=lambda: 0.0)
    scfg = ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=1.0, block_size=16,
    )
    master = Master(scfg, store=store)
    master.start()
    ecfg = EngineConfig(
        model="llama3-tiny", dtype="float32", block_size=16, num_blocks=64,
        max_running_requests=4, max_seq_len=256,
        prefill_buckets=[32, 64, 128],
        instance_name="g0", instance_type="MIX",
    )
    inst = InstanceServer(
        ecfg, master_rpc_addr=master.rpc_address, heartbeat_interval_s=0.2
    )
    inst.start()
    try:
        assert wait_until(
            lambda: sum(master.scheduler.instance_mgr.counts()) == 1
        )
        code, body = http_post(
            master.http_address, "/v1/completions",
            {"model": "llama3-tiny", "prompt": "give me json",
             "max_tokens": 40, "temperature": 0.0,
             "response_format": {"type": "json_object"}},
            timeout=300.0,
        )
        assert code == 200, body
        text = body["choices"][0]["text"]
        st = J.advance_bytes(
            J.initial_state(), text.encode("utf-8", errors="replace")
        )
        assert st is not None, text
        assert text.lstrip()[:1] == "{", text

        code, body = http_post(
            master.http_address, "/v1/completions",
            {"model": "llama3-tiny", "prompt": "x", "max_tokens": 2,
             "response_format": {"type": "json_schema"}},
            timeout=60.0,
        )
        assert code == 400, (code, body)
        assert "json_schema.schema" in body["error"]["message"]
        code, body = http_post(
            master.http_address, "/v1/completions",
            {"model": "llama3-tiny", "prompt": "x", "max_tokens": 2,
             "response_format": {"type": "grammar"}},
            timeout=60.0,
        )
        assert code == 400, (code, body)
        assert "not supported" in body["error"]["message"]
    finally:
        inst.stop()
        master.stop()
        store.close()


def test_guided_survives_pd_handoff():
    """response_format through a PREFILL -> DECODE pair: the decode peer
    continues the mask mid-stream (state rebuilt from the handed-off
    first token)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from xllm_service_tpu.api import Master
    from xllm_service_tpu.api.instance import InstanceServer
    from xllm_service_tpu.common.config import EngineConfig, ServiceConfig
    from xllm_service_tpu.coordination import MemoryStore
    from tests.test_api_e2e import http_post, wait_until

    store = MemoryStore(clock=lambda: 0.0)
    scfg = ServiceConfig(
        host="127.0.0.1", http_port=0, rpc_port=0,
        heartbeat_interval_s=0.2, master_lease_ttl_s=1.0, block_size=16,
    )
    master = Master(scfg, store=store)
    master.start()

    def mk(name, itype):
        ecfg = EngineConfig(
            model="llama3-tiny", dtype="float32", block_size=16,
            num_blocks=64, max_running_requests=4, max_seq_len=256,
            prefill_buckets=[32, 64, 128],
            instance_name=name, instance_type=itype,
        )
        srv = InstanceServer(
            ecfg, master_rpc_addr=master.rpc_address,
            heartbeat_interval_s=0.2,
        )
        srv.start()
        return srv

    p0, d0 = mk("p0", "PREFILL"), mk("d0", "DECODE")
    try:
        assert wait_until(
            lambda: master.scheduler.instance_mgr.counts() == (1, 1, 0)
        )
        code, body = http_post(
            master.http_address, "/v1/completions",
            {"model": "llama3-tiny", "prompt": "json please",
             "max_tokens": 30, "temperature": 0.0,
             "response_format": {"type": "json_object"}},
            timeout=300.0,
        )
        assert code == 200, body
        text = body["choices"][0]["text"]
        st = J.advance_bytes(
            J.initial_state(), text.encode("utf-8", errors="replace")
        )
        assert st is not None, text
        assert text.lstrip()[:1] == "{", text
    finally:
        p0.stop()
        d0.stop()
        master.stop()
        store.close()
