"""graftlint: fixture-based unit tests per pass + the repo-wide
zero-findings run (tier-1) + runtime halves (thread-ownership asserts,
lock-order sanitizer synthetics).

Each pass is exercised against synthetic in-memory projects
(Project.from_sources) with a positive (trips), a negative (clean), and
a waiver case — the analyzers are production code for CI and get the
same coverage discipline as the engine. The final class runs
`scripts/graftlint.py --all` over the real tree and requires exit 0:
the lint landing clean IS the acceptance criterion (ISSUE 10).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from xllm_service_tpu.analysis import (  # noqa: E402
    BlockingUnderLockPass,
    FaultPointsPass,
    HatchRegistryPass,
    LockDisciplinePass,
    MetricNamesPass,
    Project,
    ShardingRulesPass,
    SpanStagesPass,
    ThreadJoinsPass,
    ThreadOwnershipPass,
    all_passes,
    run_passes,
)


def proj(src, tests=None, docs=None):
    return Project.from_sources({"pkg/m.py": src}, tests=tests, docs=docs)


def run_one(p, src, **kw):
    return p.run(proj(src, **kw))


# ---------------------------------------------------------------------------
# lock-discipline
# ---------------------------------------------------------------------------


class TestLockDiscipline:
    def test_declared_guard_violation_trips(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []  # guarded by: self._mu\n"
            "    def bad(self):\n"
            "        self._q.append(1)\n"
        )
        fs = run_one(LockDisciplinePass(), src)
        assert len(fs) == 1 and "declared guarded by self._mu" in fs[0].message
        assert fs[0].line == 7

    def test_declared_guard_under_lock_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []  # guarded by: self._mu\n"
            "    def good(self):\n"
            "        with self._mu:\n"
            "            self._q.append(1)\n"
            "            self._q = []\n"
        )
        assert run_one(LockDisciplinePass(), src) == []

    def test_locked_suffix_and_holds_annotation_exempt(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []  # guarded by: self._mu\n"
            "    def _drain_locked(self):\n"
            "        self._q.append(1)\n"
            "    def helper(self):  # graftlint: holds=self._mu\n"
            "        self._q.append(2)\n"
        )
        assert run_one(LockDisciplinePass(), src) == []

    def test_init_only_marker_exempts_constructor_extension(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._init_x()\n"
            "    def _init_x(self):  # graftlint: init-only\n"
            "        self._q = []  # guarded by: self._mu\n"
            "        self._q.append(0)\n"
        )
        assert run_one(LockDisciplinePass(), src) == []

    def test_majority_locked_inference_trips_on_straggler(self):
        body = "\n".join(
            f"    def m{i}(self):\n"
            f"        with self._mu:\n"
            f"            self._q.append({i})" for i in range(3)
        )
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []\n"
            f"{body}\n"
            "    def straggler(self):\n"
            "        self._q.append(9)\n"
        )
        fs = run_one(LockDisciplinePass(), src)
        assert len(fs) == 1 and "majority-locked" in fs[0].message

    def test_inference_needs_quorum(self):
        # 2 locked sites < MIN_LOCKED_SITES: no inference, no finding
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []\n"
            "    def a(self):\n"
            "        with self._mu:\n"
            "            self._q.append(1)\n"
            "    def b(self):\n"
            "        with self._mu:\n"
            "            self._q.append(2)\n"
            "    def c(self):\n"
            "        self._q.append(3)\n"
        )
        assert run_one(LockDisciplinePass(), src) == []

    def test_condition_alias_counts_as_lock(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._cv = threading.Condition(self._mu)\n"
            "        self._q = []  # guarded by: self._mu\n"
            "    def good(self):\n"
            "        with self._cv:\n"
            "            self._q.append(1)\n"
        )
        assert run_one(LockDisciplinePass(), src) == []

    def test_waiver_suppresses_and_is_counted(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "        self._q = []  # guarded by: self._mu\n"
            "    def bad(self):\n"
            "        self._q.append(1)  # graftlint: allow=lock-discipline -- probe\n"
        )
        res = run_passes([LockDisciplinePass()], proj(src))
        assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------


class TestBlockingUnderLock:
    def test_rpc_under_lock_trips(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "    def bad(self):\n"
            "        with self._mu:\n"
            "            post_json(1)\n"
        )
        fs = run_one(BlockingUnderLockPass(), src)
        assert len(fs) == 1 and "post_json" in fs[0].message

    def test_rpc_after_lock_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "    def good(self):\n"
            "        with self._mu:\n"
            "            x = 1\n"
            "        post_json(x)\n"
        )
        assert run_one(BlockingUnderLockPass(), src) == []

    def test_sleep_join_queue_trips(self):
        src = (
            "import threading, time\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "    def bad(self):\n"
            "        with self._mu:\n"
            "            time.sleep(1)\n"
            "            self._thread.join()\n"
            "            self._queue.put(1)\n"
        )
        msgs = [f.message for f in run_one(BlockingUnderLockPass(), src)]
        assert len(msgs) == 3
        assert any("time.sleep" in m for m in msgs)
        assert any(".join()" in m for m in msgs)
        assert any(".put()" in m for m in msgs)

    def test_condition_self_wait_not_flagged(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._cv = threading.Condition()\n"
            "    def ok(self):\n"
            "        with self._cv:\n"
            "            self._cv.wait(timeout=1)\n"
        )
        assert run_one(BlockingUnderLockPass(), src) == []

    def test_shared_lock_condition_wait_not_flagged(self):
        # MemoryStore idiom: Condition(self._mu), wait under self._mu
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.RLock()\n"
            "        self._cv = threading.Condition(self._mu)\n"
            "    def ok(self):\n"
            "        with self._mu:\n"
            "            self._cv.wait(timeout=1)\n"
        )
        assert run_one(BlockingUnderLockPass(), src) == []

    def test_foreign_wait_under_lock_trips(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "    def bad(self, ev):\n"
            "        with self._mu:\n"
            "            ev.wait(5)\n"
        )
        fs = run_one(BlockingUnderLockPass(), src)
        assert len(fs) == 1 and ".wait()" in fs[0].message

    def test_nonblocking_queue_and_str_join_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._mu = threading.Lock()\n"
            "    def ok(self):\n"
            "        with self._mu:\n"
            "            self._queue.put(1, block=False)\n"
            "            s = ','.join(['a'])\n"
            "            p = os.path.join('a', 'b')\n"
        )
        assert run_one(BlockingUnderLockPass(), src) == []

    def test_module_level_lock_and_waiver(self):
        src = (
            "import threading, time\n"
            "_install_mu = threading.Lock()\n"
            "def bad():\n"
            "    with _install_mu:\n"
            "        time.sleep(1)\n"
        )
        fs = run_one(BlockingUnderLockPass(), src)
        assert len(fs) == 1
        src_waived = src.replace(
            "time.sleep(1)",
            "time.sleep(1)  # graftlint: allow=blocking-under-lock -- probe",
        )
        res = run_passes([BlockingUnderLockPass()], proj(src_waived))
        assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------------------
# thread-ownership (static)
# ---------------------------------------------------------------------------


class TestThreadOwnershipStatic:
    SRC = (
        "from xllm_service_tpu.common.concurrency import (\n"
        "    claim_thread, thread_owned)\n"
        "class E:\n"
        "    def _loop(self):\n"
        "        claim_thread(self, 'engine')\n"
        "        self._slot_admit(1)\n"
        "    @thread_owned('engine')\n"
        "    def _step(self):\n"
        "        self._slot_admit(2)\n"
        "    @thread_owned('engine')\n"
        "    def _slot_admit(self, s):\n"
        "        pass\n"
        "    def off_thread(self):\n"
        "        self._slot_admit(3)\n"
    )

    def test_unowned_call_site_trips_owned_and_claimer_pass(self):
        fs = run_one(ThreadOwnershipPass(), self.SRC)
        assert len(fs) == 1
        assert "off_thread" in fs[0].message and fs[0].line == 14

    def test_nested_def_does_not_inherit_ownership(self):
        src = (
            "from xllm_service_tpu.common.concurrency import thread_owned\n"
            "class E:\n"
            "    @thread_owned('engine')\n"
            "    def _step(self):\n"
            "        def cb():\n"
            "            self._slot_admit(1)\n"
            "        return cb\n"
            "    @thread_owned('engine')\n"
            "    def _slot_admit(self, s):\n"
            "        pass\n"
        )
        fs = run_one(ThreadOwnershipPass(), src)
        assert len(fs) == 1 and fs[0].line == 6

    def test_engine_chain_is_fully_marked_in_repo(self):
        # the real engine: zero findings means every call site of an
        # owned method is itself owned or the claiming loop
        assert ThreadOwnershipPass().run(Project.load(REPO)) == []


# ---------------------------------------------------------------------------
# thread-joins
# ---------------------------------------------------------------------------


class TestThreadJoins:
    def test_unjoined_self_thread_trips(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "        self._t.start()\n"
        )
        fs = run_one(ThreadJoinsPass(), src)
        assert len(fs) == 1 and "never joins" in fs[0].message

    def test_joined_thread_clean(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)\n"
            "        self._t.start()\n"
            "    def stop(self):\n"
            "        self._t.join(timeout=2)\n"
        )
        assert run_one(ThreadJoinsPass(), src) == []

    def test_waiver(self):
        src = (
            "import threading\n"
            "class C:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._run)"
            "  # graftlint: allow=thread-joins -- probe\n"
        )
        res = run_passes([ThreadJoinsPass()], proj(src))
        assert res.findings == [] and len(res.waived) == 1


# ---------------------------------------------------------------------------
# hatch-registry
# ---------------------------------------------------------------------------


class TestHatchRegistry:
    DOCS = {"docs/ARCHITECTURE.md": (
        "| Hatch | Gates | Default |\n"
        "|---|---|---|\n"
        "| `XLLM_DOCUMENTED` | a thing | ON |\n"
        "| `XLLM_STALE_ROW` | gone | OFF |\n"
        "| `XLLM_EMPTY_DEFAULT` | a thing | - |\n"
    )}

    def test_undocumented_stale_and_empty_default_trip(self):
        src = (
            "import os\n"
            "a = os.environ.get('XLLM_DOCUMENTED', '')\n"
            "b = os.environ.get('XLLM_UNDOCUMENTED', '')\n"
            "c = os.environ.get('XLLM_EMPTY_DEFAULT', '')\n"
        )
        fs = run_one(HatchRegistryPass(), src, docs=self.DOCS)
        msgs = "\n".join(f.message for f in fs)
        assert len(fs) == 3
        assert "XLLM_UNDOCUMENTED" in msgs and "no row" in msgs
        assert "XLLM_STALE_ROW" in msgs and "stale row" in msgs
        assert "XLLM_EMPTY_DEFAULT" in msgs and "empty Default" in msgs

    def test_kernel_token_reference_requires_row(self):
        # *_KERNEL hatches keep the legacy rule: a bare token reference
        # (helper/dispatch-table form, no environ read) needs a row too,
        # reported once at its first reference.
        src = (
            "HATCHES = ['XLLM_PHANTOM_KERNEL']\n"
            "ALSO = 'XLLM_PHANTOM_KERNEL'\n"
        )
        fs = run_one(HatchRegistryPass(), src, docs=self.DOCS)
        kernel = [f for f in fs if "XLLM_PHANTOM_KERNEL" in f.message]
        assert len(kernel) == 1 and kernel[0].line == 1

    def test_documented_hatch_clean(self):
        src = "import os\nx = os.environ.get('XLLM_DOCUMENTED', '1')\n"
        docs = {"docs/ARCHITECTURE.md": (
            "| Hatch | Gates | Default |\n|---|---|---|\n"
            "| `XLLM_DOCUMENTED` | a thing | ON |\n"
        )}
        assert run_one(HatchRegistryPass(), src, docs=docs) == []

    def test_repo_registry_is_complete(self):
        # every real env read documented, every row live (satellite:
        # the full XLLM_* surface, not just *_KERNEL)
        assert HatchRegistryPass().run(Project.load(REPO)) == []


# ---------------------------------------------------------------------------
# metric-names / fault-points (legacy passes, absorbed)
# ---------------------------------------------------------------------------


class TestLegacyPasses:
    def test_metric_names_static_violations(self):
        src = (
            "reg.counter('xllm_good_total', 'd')\n"
            "reg.counter('xllm_bad_counter', 'd')\n"
            "reg.gauge('xllm_bad_total', 'd')\n"
            "reg.histogram('xllm_bad_bucket', 'd')\n"
            "reg.counter('BadName', 'd')\n"
        )
        fs = run_one(MetricNamesPass(runtime=False), src)
        assert len(fs) == 4
        assert fs[0].line == 2  # first violation anchored to its line

    def test_fault_points_dup_uncovered_required(self):
        src = (
            "faults.point('a.b')\n"
            "faults.point('a.b')\n"
            "faults.point('c.d')\n"
        )
        fs = run_one(FaultPointsPass(), src, tests={"tests/t.py": "a.b"})
        msgs = "\n".join(f.message for f in fs)
        assert "defined at 2 sites" in msgs          # dup (both sites)
        assert "'c.d' is not referenced" in msgs     # uncovered
        assert "required point" in msgs              # REQUIRED_POINTS gone

    def test_fault_points_clean_fixture(self):
        from xllm_service_tpu.analysis import REQUIRED_POINTS
        src = "\n".join(
            f"faults.point('{p}')" for p in sorted(REQUIRED_POINTS)
        )
        tests = {"tests/t.py": " ".join(sorted(REQUIRED_POINTS))}
        assert run_one(FaultPointsPass(), src, tests=tests) == []


# ---------------------------------------------------------------------------
# span-stages (distributed-tracing vocabulary + trace-plane registry)
# ---------------------------------------------------------------------------


class TestSpanStages:
    def _pass(self, planes=()):
        return SpanStagesPass(
            vocab=("admit", "finish", "handoff_send"), planes=planes,
        )

    def test_off_vocabulary_stage_trips(self):
        src = (
            'self._span(srid, "admit", n=1)\n'
            'self._span(srid, "not_a_stage")\n'
            'ring.emit(srid, "handoff_send")\n'
        )
        fs = run_one(self._pass(), src)
        assert len(fs) == 1
        assert fs[0].line == 2
        assert "not_a_stage" in fs[0].message

    def test_all_emit_surfaces_are_scanned(self):
        src = (
            'tracer.stage(srid, "bogus_a")\n'
            'ring.emit(srid, "bogus_b")\n'
            'self.span_hook("", "bogus_c", n=1)\n'
            'self._span_hook(srid, "bogus_d")\n'
        )
        fs = run_one(self._pass(), src)
        assert {f.line for f in fs} == {1, 2, 3, 4}

    def test_non_literal_stage_is_skipped(self):
        src = 'self._tracer.stage(srid, terminal, code=1)\n'
        assert run_one(self._pass(), src) == []

    def test_trace_plane_needle_missing_trips(self):
        planes = (
            ("pkg/m.py", 'fwd["trace"] = ctx', "dispatch plane"),
            ("pkg/gone.py", "x", "vanished plane"),
        )
        src = 'fwd = {}\n'
        fs = run_one(self._pass(planes=planes), src)
        msgs = "\n".join(f.message for f in fs)
        assert "no longer forwards trace context" in msgs
        assert "file is gone" in msgs

    def test_trace_plane_clean_fixture(self):
        planes = (("pkg/m.py", 'fwd["trace"] = ctx', "dispatch plane"),)
        src = 'fwd["trace"] = ctx\n'
        assert run_one(self._pass(planes=planes), src) == []

    def test_repo_vocabulary_is_the_canonical_tuple(self):
        from xllm_service_tpu.obs.spans import ALL_SPAN_STAGES
        assert SpanStagesPass().vocab == frozenset(ALL_SPAN_STAGES)

    def test_registry_rows_point_at_live_needles(self):
        # The shipped TRACE_PLANES rows must hold on the real tree (the
        # repo-wide run below enforces this too; this pins the registry
        # itself so a row edit can't silently no-op the check).
        from xllm_service_tpu.analysis import TRACE_PLANES
        assert len(TRACE_PLANES) >= 6
        project = Project.load(REPO)
        assert SpanStagesPass(vocab=None).run(project) == []


# ---------------------------------------------------------------------------
# framework: waiver bookkeeping
# ---------------------------------------------------------------------------


class TestFramework:
    def test_stale_waiver_is_a_finding(self):
        src = (
            "import threading\n"
            "x = 1  # graftlint: allow=lock-discipline -- nothing here\n"
        )
        res = run_passes(all_passes(runtime=False), proj(src))
        assert any("stale waiver" in f.message for f in res.stale_waivers)
        assert res.failed

    def test_unknown_pass_waiver_is_a_finding(self):
        src = "x = 1  # graftlint: allow=no-such-pass -- typo\n"
        res = run_passes(all_passes(runtime=False), proj(src))
        assert any("unknown pass" in f.message for f in res.stale_waivers)

    def test_pass_catalog_has_the_contracted_passes(self):
        ids = {p.id for p in all_passes(runtime=False)}
        assert {
            "lock-discipline", "blocking-under-lock", "thread-ownership",
            "thread-joins", "hatch-registry", "metric-names",
            "fault-points", "span-stages",
        } <= ids


# ---------------------------------------------------------------------------
# runtime: thread-ownership asserts
# ---------------------------------------------------------------------------


class TestThreadOwnershipRuntime:
    def _mk(self):
        from xllm_service_tpu.common.concurrency import thread_owned

        class Eng:
            @thread_owned("engine")
            def slot(self):
                return threading.get_ident()

        return Eng()

    def test_unclaimed_passes_anywhere(self):
        eng = self._mk()
        assert eng.slot() == threading.get_ident()

    def test_claimed_blocks_foreign_thread_and_release_reopens(self):
        from xllm_service_tpu.common import concurrency

        if not concurrency.checks_enabled():
            pytest.skip("XLLM_THREAD_CHECKS off in this environment")
        eng = self._mk()
        errs = []
        done = threading.Event()

        def owner():
            concurrency.claim_thread(eng, "engine")
            eng.slot()  # owner passes
            done.wait(5)

        t = threading.Thread(target=owner, daemon=True)
        t.start()
        for _ in range(100):
            if getattr(eng, "_thread_owner_engine", None) is not None:
                break
            time.sleep(0.01)
        with pytest.raises(concurrency.ThreadOwnershipError):
            eng.slot()  # foreign thread trips
        done.set()
        t.join(timeout=5)
        concurrency.release_thread(eng, "engine")
        assert eng.slot() == threading.get_ident()  # released: open again


# ---------------------------------------------------------------------------
# runtime: lock-order sanitizer synthetics
# ---------------------------------------------------------------------------


class TestLocktrace:
    @pytest.fixture()
    def traced(self):
        from xllm_service_tpu.obs import locktrace

        was = locktrace.active()
        if not was:
            locktrace.install()
        with locktrace.isolated():
            yield locktrace
        if not was:
            locktrace.uninstall()

    def test_abba_cycle_trips(self, traced):
        a = threading.Lock()
        b = threading.Lock()
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        rep = traced.report()
        assert rep["cycles"], rep
        sites = {s for cyc in rep["cycles"] for s in cyc}
        assert any("test_graftlint.py" in s for s in sites)

    def test_consistent_order_clean(self, traced):
        a = threading.Lock()
        b = threading.Lock()
        for _ in range(3):
            with a:
                with b:
                    pass
        rep = traced.report()
        assert rep["cycles"] == [] and rep["edges"] >= 1

    def test_rlock_reentrancy_is_not_a_self_cycle(self, traced):
        r = threading.RLock()
        with r:
            with r:
                pass
        assert traced.report()["cycles"] == []

    def test_same_class_instances_nested_is_one_self_cycle(self, traced):
        # two locks from ONE creation site = one lockdep class; nesting
        # them is a real order hazard and must report exactly ONE cycle
        a, b = threading.Lock(), threading.Lock()
        with a:
            with b:
                pass
        cycles = traced.report()["cycles"]
        assert len(cycles) == 1 and cycles[0][0] == cycles[0][-1]

    def test_held_across_fault_point_recorded(self, traced):
        from xllm_service_tpu.common import faults

        mu = threading.Lock()
        with mu:
            faults.point("lint.probe")
        rep = traced.report()
        assert any(p == "lint.probe" for p, _ in rep["point_holds"])

    def test_point_without_lock_clean(self, traced):
        from xllm_service_tpu.common import faults

        faults.point("lint.probe2")
        assert traced.report()["point_holds"] == {}

    def test_condition_wait_stack_bookkeeping(self, traced):
        # wait() fully releases the condition's lock; after the with
        # block the thread's held-stack must be empty, so a subsequent
        # acquire records NO cv->l2 edge (a bookkeeping leak here would
        # fabricate edges and eventually false cycles).
        cv = threading.Condition()
        l2 = threading.Lock()

        def waiter():
            with cv:
                cv.wait(timeout=0.05)
            with l2:
                pass

        t = threading.Thread(target=waiter, daemon=True)
        t.start()
        t.join(timeout=5)
        rep = traced.report()
        assert rep["edges"] == 0 and rep["cycles"] == [], rep


# ---------------------------------------------------------------------------
# sharding-rules
# ---------------------------------------------------------------------------


class TestShardingRules:
    RULES = (
        "def param_shardings(cfg, mesh):\n"
        "    layers = {'attn_norm': 1, 'wq': 1}\n"
        "    layers.update({'w_gate': 1})\n"
        "    layers['wo'] = 1\n"
        "    return {'embed': 1, 'layers': layers}\n"
    )

    def _proj(self, model_src, rules_src=None):
        return Project.from_sources({
            "xllm_service_tpu/models/llama.py": model_src,
            "xllm_service_tpu/parallel/sharding.py": (
                rules_src if rules_src is not None else self.RULES
            ),
        })

    def test_unruled_leaf_trips(self):
        src = (
            "def init_params(cfg, key, dtype):\n"
            "    layers = {'attn_norm': 1, 'wq': 1}\n"
            "    layers['w_new_proj'] = 2\n"
            "    return {'embed': 1, 'layers': layers}\n"
        )
        fs = ShardingRulesPass().run(self._proj(src))
        assert len(fs) == 1 and "w_new_proj" in fs[0].message

    def test_ruled_tree_clean(self):
        src = (
            "def init_params(cfg, key, dtype):\n"
            "    layers = {'attn_norm': 1, 'wq': 1}\n"
            "    layers.update({'w_gate': 1, 'wo': 1})\n"
            "    return {'embed': 1, 'layers': layers}\n"
        )
        assert ShardingRulesPass().run(self._proj(src)) == []

    def test_runtime_lora_leaves_exempt(self):
        src = (
            "def init_params(cfg, key, dtype):\n"
            "    layers = {'wq': 1, 'lora_wq_a': 1}\n"
            "    return {'layers': layers}\n"
        )
        assert ShardingRulesPass().run(self._proj(src)) == []

    def test_missing_rules_file_trips(self):
        src = "def init_params(cfg, key, dtype):\n    return {'wq': 1}\n"
        fs = ShardingRulesPass().run(
            Project.from_sources(
                {"xllm_service_tpu/models/llama.py": src}
            )
        )
        assert len(fs) == 1 and "sharding.py" in fs[0].message

    def test_helper_created_leaf_trips(self):
        # deepseek builds its whole per-layer leaf dict (the MoE
        # expert/router leaves included) in _layer_stack — the pass must
        # walk init_params' local-call closure, or a new expert leaf
        # added out of line would silently replicate (ISSUE 15).
        src = (
            "def _layer_stack(cfg, key):\n"
            "    layers = {'wq': 1}\n"
            "    layers.update({'w_expert_bias': 1})\n"
            "    return layers\n"
            "def init_params(cfg, key, dtype):\n"
            "    return {'embed': 1, 'layers': _layer_stack(cfg, key)}\n"
        )
        fs = ShardingRulesPass().run(self._proj(src))
        assert len(fs) == 1 and "w_expert_bias" in fs[0].message

    def test_helper_created_ruled_leaf_clean(self):
        src = (
            "def _layer_stack(cfg, key):\n"
            "    return {'wq': 1, 'w_gate': 1, 'wo': 1}\n"
            "def init_params(cfg, key, dtype):\n"
            "    return {'embed': 1, 'layers': _layer_stack(cfg, key)}\n"
        )
        assert ShardingRulesPass().run(self._proj(src)) == []

    # -- ppermute axis-vocabulary rule (ISSUE 18) -----------------------

    def _ring_proj(self, ring_src):
        return Project.from_sources({
            "xllm_service_tpu/ops/collective_matmul.py": ring_src,
            "xllm_service_tpu/parallel/sharding.py": self.RULES,
        })

    def test_ppermute_literal_bad_axis_trips(self):
        src = (
            "import jax\n"
            "def ring(x, perm):\n"
            "    return jax.lax.ppermute(x, 'tp2', perm)\n"
        )
        fs = ShardingRulesPass().run(self._ring_proj(src))
        assert len(fs) == 1 and "'tp2'" in fs[0].message

    def test_ppermute_mesh_axes_clean(self):
        src = (
            "import jax\n"
            "def ring(x, perm):\n"
            "    x = jax.lax.ppermute(x, 'tp', perm)\n"
            "    x = jax.lax.ppermute(x, 'sp', perm)\n"
            "    return jax.lax.ppermute(x, axis_name='pp', perm=perm)\n"
        )
        assert ShardingRulesPass().run(self._ring_proj(src)) == []

    def test_ppermute_param_default_resolved(self):
        # The real call sites pass the axis through a parameter with a
        # string default (ring_attention's sp_axis="sp") — the pass must
        # see through that indirection.
        src = (
            "import jax\n"
            "def ring(x, perm, axis='tpp'):\n"
            "    return jax.lax.ppermute(x, axis, perm)\n"
        )
        fs = ShardingRulesPass().run(self._ring_proj(src))
        assert len(fs) == 1 and "'tpp'" in fs[0].message

    def test_ppermute_closure_default_resolved(self):
        # pipeline.py's shape: outer fn takes pp_axis="pp", the ppermute
        # sits in a nested local fn reading it from the closure.
        src = (
            "import jax\n"
            "def outer(x, perm, pp_axis='pp'):\n"
            "    def local(y):\n"
            "        return jax.lax.ppermute(y, pp_axis, perm)\n"
            "    return local(x)\n"
        )
        assert ShardingRulesPass().run(self._ring_proj(src)) == []

    def test_ppermute_dynamic_axis_skipped(self):
        # An axis the pass cannot resolve statically is skipped, never
        # guessed — no false positive on a plumbed-through variable.
        src = (
            "import jax\n"
            "def ring(x, perm, axis):\n"
            "    return jax.lax.ppermute(x, axis, perm)\n"
        )
        assert ShardingRulesPass().run(self._ring_proj(src)) == []

    def test_ppermute_local_assign_resolved(self):
        src = (
            "import jax\n"
            "def ring(x, perm):\n"
            "    ax = 'expert'\n"
            "    return jax.lax.ppermute(x, ax, perm)\n"
        )
        fs = ShardingRulesPass().run(self._ring_proj(src))
        assert len(fs) == 1 and "'expert'" in fs[0].message


# ---------------------------------------------------------------------------
# the real tree: repo-wide zero findings (tier-1 acceptance)
# ---------------------------------------------------------------------------


class TestRepoWide:
    def test_graftlint_all_exits_zero(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
             "--all"],
            capture_output=True, text=True, cwd=REPO, timeout=300,
        )
        assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
        assert "OK" in r.stdout

    def test_graftlint_list_and_unknown_pass(self):
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
             "--list"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert r.returncode == 0 and "lock-discipline" in r.stdout
        r2 = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "graftlint.py"),
             "--pass", "nope"],
            capture_output=True, text=True, cwd=REPO, timeout=120,
        )
        assert r2.returncode == 2
