"""JSON serde round-trips for control-plane types (wire parity with
reference types.h serialize_to_json/parse_from_json)."""

from xllm_service_tpu.common.types import (
    CacheLocations,
    InstanceMetaInfo,
    InstanceType,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    Routing,
)


def test_routing_roundtrip():
    r = Routing(prefill_name="p0", decode_name="d0")
    j = r.to_json()
    assert j == {"prefill_name": "p0", "decode_name": "d0"}
    assert Routing.from_json(j) == r


def test_load_metrics_roundtrip():
    m = LoadMetrics(
        waiting_requests_num=7, gpu_cache_usage_perc=0.42,
        moe_hot_expert_frac=0.31, kv_stall_ms_ewma=12.5,
    )
    assert LoadMetrics.from_json(m.to_json()) == m
    # Reference wire field names preserved, plus the expert-hotness
    # (ISSUE 15, docs/MOE.md) and handoff-stall (ISSUE 16,
    # docs/PD_DISAGGREGATION.md "Goodput controller") extensions.
    assert set(m.to_json()) == {
        "waiting_requests_num", "gpu_cache_usage_perc",
        "moe_hot_expert_frac", "kv_stall_ms_ewma",
    }
    # The extensions are OPTIONAL on the wire: a reference-shaped
    # payload (old-build instance) decodes with the fields inert at 0.0.
    old = LoadMetrics.from_json(
        {"waiting_requests_num": 7, "gpu_cache_usage_perc": 0.42}
    )
    assert old.moe_hot_expert_frac == 0.0
    assert old.kv_stall_ms_ewma == 0.0
    assert old.waiting_requests_num == 7


def test_instance_meta_roundtrip():
    info = InstanceMetaInfo(
        name="inst-0",
        rpc_address="10.0.0.1:9889",
        http_address="10.0.0.1:9888",
        type=InstanceType.PREFILL,
        cluster_ids=[0, 1],
        addrs=["10.0.0.1:7000"],
        k_cache_ids=[11, 12],
        v_cache_ids=[21, 22],
        dp_size=2,
        tp_size=4,
        ttft_profiling_data=[(128, 30.0), (1024, 180.0)],
        tpot_profiling_data=[(1, 128, 8.0), (8, 4096, 12.0)],
    )
    back = InstanceMetaInfo.deserialize(info.serialize())
    assert back.name == info.name
    assert back.type == InstanceType.PREFILL
    assert back.ttft_profiling_data == info.ttft_profiling_data
    assert back.tpot_profiling_data == info.tpot_profiling_data
    assert back.k_cache_ids == [11, 12]


def test_cache_locations():
    loc = CacheLocations(hbm_instance_set={"a"}, dram_instance_set={"b"})
    back = CacheLocations.from_json(loc.to_json())
    assert back == loc
    assert not loc.empty()
    assert CacheLocations().empty()


def test_kvcache_event_roundtrip():
    ev = KvCacheEvent(
        stored_cache={b"\x01" * 16},
        removed_cache={b"\x02" * 16},
        offload_cache={b"\x03" * 16: "dram"},
    )
    back = KvCacheEvent.from_json(ev.to_json())
    assert back == ev
    assert not ev.empty()
    assert KvCacheEvent().empty()


def test_instance_type_parse():
    assert InstanceType.parse("prefill") == InstanceType.PREFILL
    assert InstanceType.parse(2) == InstanceType.DECODE
    assert InstanceType.parse(InstanceType.MIX) == InstanceType.MIX


def test_latency_metrics():
    lm = LatencyMetrics(recent_max_ttft=120, recent_max_tbt=15)
    assert LatencyMetrics.from_json(lm.to_json()) == lm
