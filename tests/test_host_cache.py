"""Engine-side host (DRAM) cache tier (round-1 missing item 4).

Committed HBM blocks evicted under pressure are copied to the host pool
(heartbeat delta: offload_cache['dram']), then re-imported on a later
prefix match (delta: stored — re-promotion), and the service index follows
the tier transitions (reference global_kvcache_mgr.cpp:177-225 contract).
"""

import threading

import numpy as np
import pytest

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import KvCacheEvent
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.engine import EngineRequest, InferenceEngine
from xllm_service_tpu.runtime.executor import ModelExecutor
from xllm_service_tpu.runtime.host_cache import HostKVPool


def test_host_pool_lru():
    pool = HostKVPool(2)
    a = np.zeros((2, 1, 2, 4, 8), np.float32)
    assert pool.put(b"h1", a) == []
    assert pool.put(b"h2", a) == []
    assert pool.get(b"h1") is not None  # h1 now MRU
    evicted = pool.put(b"h3", a)  # h2 was LRU
    assert [h for h, _ in evicted] == [b"h2"]
    np.testing.assert_array_equal(evicted[0][1], a)
    assert pool.get(b"h2") is None
    assert b"h1" in pool and b"h3" in pool


def test_ssd_pool_roundtrip(tmp_path):
    from xllm_service_tpu.runtime.host_cache import SsdKVPool

    pool = SsdKVPool(str(tmp_path / "ssd"), 2)
    a = np.arange(24, dtype=np.float32).reshape(2, 1, 2, 2, 3)
    assert pool.put(b"s1", a) == []
    assert pool.put(b"s2", a * 2) == []
    np.testing.assert_array_equal(pool.get(b"s1"), a)  # s1 now MRU
    assert pool.put(b"s3", a * 3) == [b"s2"]
    assert pool.get(b"s2") is None
    np.testing.assert_array_equal(pool.get(b"s3"), a * 3)


def test_dram_to_ssd_demotion_and_reimport(tmp_path):
    """HBM -> DRAM -> SSD -> HBM: a block squeezed through all three tiers
    re-imports from disk on a later prefix match, with the right events."""
    cfg = EngineConfig(
        model="llama3-tiny", num_blocks=4, block_size=16,
        max_running_requests=2, max_seq_len=64, prefill_buckets=[48],
        num_host_blocks=1, num_ssd_blocks=8,
        ssd_cache_dir=str(tmp_path / "ssd"),
    )
    exe = ModelExecutor(cfg, init_seed=2)
    items = []
    orig = exe.prefill_batch

    def spy(batch):
        items.extend(batch)
        return orig(batch)

    exe.prefill_batch = spy
    # Prefill rides the FUSED mixed step by default (ISSUE 9,
    # docs/KERNELS.md) — watch both entry points so the start_pos
    # assertions hold under either step builder.
    morig = exe.mixed_start

    def mixed_spy(batch, *args, **kwargs):
        items.extend(batch)
        return morig(batch, *args, **kwargs)

    exe.mixed_start = mixed_spy
    engine = InferenceEngine(cfg, executor=exe)
    engine.start()
    try:
        bs = cfg.block_size
        prompt_a = [(i * 11 + 1) % 512 for i in range(40)]  # 2 full blocks
        prompt_b = [(i * 7 + 3) % 512 for i in range(40)]
        hashes_a = prefix_block_hashes(prompt_a, bs, engine.block_mgr.seed)

        def run(prompt):
            ev = threading.Event()
            engine.add_request(
                EngineRequest(
                    request_id=f"t{len(items)}",
                    prompt_token_ids=list(prompt),
                    sampling=SamplingParams(temperature=0.0, max_new_tokens=2),
                    callback=lambda out, ev=ev: (
                        ev.set() if out.finished else None
                    ) or True,
                )
            )
            assert ev.wait(120.0)

        run(prompt_a)
        engine.take_cache_event()
        # B evicts A's 2 committed blocks: host pool holds 1, so one of
        # them demotes straight through to SSD.
        run(prompt_b)
        ev = engine.take_cache_event()
        tiers = {ev.offload_cache.get(hh) for hh in hashes_a[:2]}
        assert "ssd" in tiers and "dram" in tiers, ev.to_json()
        assert engine.ssd_pool is not None and len(engine.ssd_pool) >= 1

        # A again: both blocks come back (one from DRAM, one from disk).
        n_before = len(items)
        run(prompt_a)
        assert items[n_before].start_pos >= 2 * bs, (
            f"tiered re-import missed: start_pos={items[n_before].start_pos}"
        )
        ev2 = engine.take_cache_event()
        assert set(hashes_a[:2]) <= ev2.stored_cache  # re-promoted
    finally:
        engine.stop()


class _EngineHarness:
    def __init__(self, num_host_blocks: int):
        self.cfg = EngineConfig(
            model="llama3-tiny",
            num_blocks=4,  # 3 usable: tight enough to force eviction
            block_size=16,
            max_running_requests=2,
            max_seq_len=64,
            prefill_buckets=[48],
            num_host_blocks=num_host_blocks,
        )
        self.exe = ModelExecutor(self.cfg, init_seed=2)
        self.prefill_items = []
        orig = self.exe.prefill_batch

        def spy(items):
            self.prefill_items.extend(items)
            return orig(items)

        self.exe.prefill_batch = spy
        # Watch the fused mixed step too (the default builder since
        # ISSUE 9) — same PrefillItem contract, so start_pos assertions
        # are step-builder-agnostic.
        morig = self.exe.mixed_start

        def mixed_spy(items, *args, **kwargs):
            self.prefill_items.extend(items)
            return morig(items, *args, **kwargs)

        self.exe.mixed_start = mixed_spy
        self.engine = InferenceEngine(self.cfg, executor=self.exe)
        self.engine.start()

    def run(self, prompt, max_new=2):
        ev = threading.Event()

        def cb(out):
            if out.finished:
                ev.set()
            return True

        self.engine.add_request(
            EngineRequest(
                request_id=f"req{id(prompt) % 1000}-{len(self.prefill_items)}",
                prompt_token_ids=list(prompt),
                sampling=SamplingParams(temperature=0.0, max_new_tokens=max_new),
                callback=cb,
            )
        )
        assert ev.wait(120.0)

    def stop(self):
        self.engine.stop()


def test_offload_reimport_cycle():
    h = _EngineHarness(num_host_blocks=8)
    try:
        bs = h.cfg.block_size
        prompt_a = [(i * 11 + 1) % 512 for i in range(40)]  # 2 full blocks
        prompt_b = [(i * 7 + 3) % 512 for i in range(40)]

        h.run(prompt_a)
        ev_a = h.engine.take_cache_event()
        hashes_a = prefix_block_hashes(prompt_a, bs, h.engine.block_mgr.seed)
        assert set(hashes_a[:2]) <= ev_a.stored_cache
        assert not ev_a.offload_cache

        # B forces eviction of A's committed blocks -> host offload.
        h.run(prompt_b)
        ev_b = h.engine.take_cache_event()
        offloaded = {hh for hh in hashes_a[:2] if hh in ev_b.offload_cache}
        assert offloaded, f"no offload events: {ev_b.to_json()}"
        for hh in offloaded:
            assert ev_b.offload_cache[hh] == "dram"
            assert hh in h.engine.host_pool

        # A again: host blocks re-import, prefill starts past them.
        n_items_before = len(h.prefill_items)
        h.run(prompt_a)
        item = h.prefill_items[n_items_before]
        assert item.start_pos >= bs, (
            f"host re-import missed: start_pos={item.start_pos}"
        )
        ev_a2 = h.engine.take_cache_event()
        # re-promotion: at least the re-imported hashes are stored again
        assert offloaded & ev_a2.stored_cache
    finally:
        h.stop()


def test_service_index_follows_tiers():
    """The engine's real event stream drives the service index through
    hbm -> dram -> hbm for the same instance."""
    from xllm_service_tpu.cluster.global_kvcache_mgr import GlobalKVCacheMgr
    from xllm_service_tpu.coordination.store import MemoryStore

    h = _EngineHarness(num_host_blocks=8)
    try:
        bs = h.cfg.block_size
        prompt_a = [(i * 11 + 1) % 512 for i in range(40)]
        prompt_b = [(i * 7 + 3) % 512 for i in range(40)]
        mgr = GlobalKVCacheMgr(
            MemoryStore(clock=lambda: 0.0),  # frozen clock
            is_master=lambda: True, block_size=bs,
            murmur_hash3_seed=h.engine.block_mgr.seed,
        )
        inst = "engine-0"

        h.run(prompt_a)
        mgr.record_updated_kvcaches(inst, h.engine.take_cache_event())
        hashes_a = prefix_block_hashes(prompt_a, bs, h.engine.block_mgr.seed)
        loc = mgr.lookup(hashes_a[0])
        assert inst in loc.hbm_instance_set

        h.run(prompt_b)
        mgr.record_updated_kvcaches(inst, h.engine.take_cache_event())
        loc = mgr.lookup(hashes_a[0])
        assert inst in loc.dram_instance_set
        assert inst not in loc.hbm_instance_set

        h.run(prompt_a)
        mgr.record_updated_kvcaches(inst, h.engine.take_cache_event())
        loc = mgr.lookup(hashes_a[0])
        assert inst in loc.hbm_instance_set
        assert inst not in loc.dram_instance_set
    finally:
        h.stop()


def test_no_host_pool_means_removed_events():
    h = _EngineHarness(num_host_blocks=0)
    try:
        assert h.engine.host_pool is None
        prompt_a = [(i * 11 + 1) % 512 for i in range(40)]
        prompt_b = [(i * 7 + 3) % 512 for i in range(40)]
        h.run(prompt_a)
        h.engine.take_cache_event()
        h.run(prompt_b)
        ev = h.engine.take_cache_event()
        assert ev.removed_cache and not ev.offload_cache
    finally:
        h.stop()
