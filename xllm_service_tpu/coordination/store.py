"""Coordination store: discovery, replicated state, liveness, election.

TPU-native redesign of the reference's single coordination backend
(reference: xllm_service/scheduler/etcd_client/etcd_client.{h,cpp}) behind a
narrow interface so the service tier is testable without a live etcd
(SURVEY.md §4 calls out that the reference has no such seam and therefore no
automatable integration tests).

Semantics preserved from the reference:
  * typed get/set/remove + prefix scans (etcd_client.h:37-118);
  * watches on key prefixes firing PUT/DELETE events (etcd_client.cpp:156-193);
  * TTL leases whose expiry deletes the attached keys, which is the entire
    liveness mechanism (instance death => lease expiry => watch DELETE =>
    registry removal; SURVEY.md §3.5);
  * compare-create transaction used for master election
    (etcd_client.cpp:47-62);
  * guarded batch delete that re-checks the master key inside the txn
    (etcd_client.cpp:90-99).

Backends: `MemoryStore` (in-process, process-global named namespaces so a
service and fake instances in one test share a view) and `EtcdGatewayStore`
(etcd v3 HTTP/JSON gateway over stdlib urllib — no extra deps). Select via
address: "memory://[ns]" or "etcd://host:port".
"""

from __future__ import annotations

import base64
import enum
import json
import random
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from xllm_service_tpu.common import faults

# Watch-stream reconnects across every EtcdGatewayStore in the process
# (exported as xllm_coord_watch_reconnects_total by the scheduler's
# registry — the store itself has no registry to avoid an obs dependency
# in the coordination layer).
_watch_reconnects_mu = threading.Lock()
_watch_reconnects = 0


def watch_reconnects_total() -> int:
    with _watch_reconnects_mu:
        return _watch_reconnects


def _count_watch_reconnect() -> None:
    global _watch_reconnects
    with _watch_reconnects_mu:
        _watch_reconnects += 1


def _watch_backoff_s(attempt: int, base_s: float = 0.1, max_s: float = 5.0) -> float:
    """Jittered exponential backoff for watch-stream reconnects: a blind
    fixed sleep (the old 1.0 s) synchronizes every watcher in the fleet
    into reconnect waves against a recovering etcd; jitter + growth spread
    them out. `attempt` counts consecutive failures since the last healthy
    stream (0-based)."""
    return min(base_s * (2 ** min(attempt, 16)), max_s) * random.uniform(0.5, 1.5)


class EventType(enum.Enum):
    PUT = "PUT"
    DELETE = "DELETE"


@dataclass
class WatchEvent:
    type: EventType
    key: str
    value: str = ""  # empty for DELETE


# Callback receives a batch of events (one etcd watch response may carry many).
WatchCallback = Callable[[List[WatchEvent]], None]


class CoordinationStore:
    """Abstract coordination backend (reference: etcd_client.h:32-144)."""

    # -- plain KV ----------------------------------------------------------
    def get(self, key: str) -> Optional[str]:
        raise NotImplementedError

    def set(self, key: str, value: str, lease_id: int = 0) -> bool:
        raise NotImplementedError

    def remove(self, key: str) -> bool:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        raise NotImplementedError

    def set_many(self, kvs: Dict[str, str], lease_id: int = 0) -> bool:
        ok = True
        for k, v in kvs.items():
            ok = self.set(k, v, lease_id) and ok
        return ok

    # -- watches -----------------------------------------------------------
    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        raise NotImplementedError

    def remove_watch(self, watch_id: int) -> None:
        raise NotImplementedError

    # -- leases ------------------------------------------------------------
    def grant_lease(self, ttl_s: float) -> int:
        raise NotImplementedError

    def keepalive(self, lease_id: int) -> bool:
        """Refresh; False if the lease already expired."""
        raise NotImplementedError

    def revoke_lease(self, lease_id: int) -> None:
        raise NotImplementedError

    # -- transactions ------------------------------------------------------
    def compare_create(self, key: str, value: str, lease_id: int = 0) -> bool:
        """Atomically create `key` iff it does not exist (election txn,
        reference: etcd_client.cpp:47-62). True iff this caller won."""
        raise NotImplementedError

    def guarded_remove(self, keys: List[str], guard_key: str, guard_value: str) -> bool:
        """Delete `keys` iff guard_key still holds guard_value
        (reference: etcd_client.cpp:90-99 re-checks mastership)."""
        raise NotImplementedError

    def compare_create_with_epoch(
        self, key: str, value: str, epoch_key: str, lease_id: int = 0
    ) -> int:
        """Election txn WITH fencing: atomically create `key` iff absent
        AND bump the monotonically increasing counter at `epoch_key`
        (unleased — it must outlive every master) in the SAME transaction.
        Returns the new epoch (>= 1) when this caller won, 0 otherwise.

        The epoch is the split-brain fence: every master->instance RPC
        carries it, instances persist the highest seen and reject lower —
        a deposed-but-unaware master's dispatches are structurally
        rejected (docs/FAULT_TOLERANCE.md, control plane).

        Default implementation composes compare_create + set (atomic
        enough for single-writer backends); MemoryStore and
        EtcdGatewayStore override with genuinely transactional versions.
        """
        if not self.compare_create(key, value, lease_id):
            return 0
        epoch = int(self.get(epoch_key) or 0) + 1
        self.set(epoch_key, str(epoch))
        return epoch

    def close(self) -> None:
        pass

    # -- typed helpers (reference: templated JSON get/set, etcd_client.h) --
    def get_json(self, key: str) -> Optional[Any]:
        raw = self.get(key)
        return None if raw is None else json.loads(raw)

    def set_json(self, key: str, value: Any, lease_id: int = 0) -> bool:
        return self.set(key, json.dumps(value), lease_id)


# ---------------------------------------------------------------------------
# In-memory backend
# ---------------------------------------------------------------------------


class _Lease:
    __slots__ = ("lease_id", "ttl_s", "expires_at", "keys")

    def __init__(self, lease_id: int, ttl_s: float, now: float):
        self.lease_id = lease_id
        self.ttl_s = ttl_s
        self.expires_at = now + ttl_s
        self.keys: set = set()


class MemoryStore(CoordinationStore):
    """Process-local store with full etcd semantics.

    Watch callbacks run on a dedicated notifier thread (the reference defers
    watch handling to a threadpool for the same deadlock-avoidance reason,
    instance_mgr.cpp:58-67); lease expiry runs on a sweeper thread and
    produces DELETE events exactly like an etcd lease timeout.
    """

    def __init__(self, clock=None) -> None:
        # `clock`: monotonic-seconds callable driving LEASE TIME only
        # (watch/notify stay real-threaded). Tests that don't exercise
        # liveness inject a frozen clock so leases can never expire
        # underneath them — an XLA compile hogging the GIL past a
        # wall-clock TTL was the suite's recurring flake (rounds 1-2);
        # failure-detection tests advance a manual clock instead of
        # sleeping.
        self._clock = clock or time.monotonic
        self._mu = threading.RLock()
        self._kv: Dict[str, str] = {}
        self._key_lease: Dict[str, int] = {}
        self._leases: Dict[int, _Lease] = {}
        self._watches: Dict[int, Tuple[str, WatchCallback]] = {}
        self._next_watch_id = 1
        self._next_lease_id = 1
        self._event_q: List[List[WatchEvent]] = []
        self._event_cv = threading.Condition(self._mu)
        self._closed = False
        self._notifier = threading.Thread(
            target=self._notify_loop, name="memstore-notify", daemon=True
        )
        self._sweeper = threading.Thread(
            target=self._sweep_loop, name="memstore-sweep", daemon=True
        )
        self._notifier.start()
        self._sweeper.start()

    # -- internals ---------------------------------------------------------
    def _emit(self, events: List[WatchEvent]) -> None:
        # caller holds _mu
        if events:
            self._event_q.append(events)
            self._event_cv.notify_all()

    def _notify_loop(self) -> None:
        while True:
            with self._mu:
                while not self._event_q and not self._closed:
                    self._event_cv.wait(timeout=0.5)
                if self._closed and not self._event_q:
                    return
                batch = self._event_q.pop(0)
                watches = list(self._watches.values())
            for prefix, cb in watches:
                sub = [e for e in batch if e.key.startswith(prefix)]
                if sub:
                    try:
                        # Chaos hook: a dropped delivery simulates a lost
                        # etcd watch response (one watcher misses one
                        # batch; liveness then rests on prefix re-scans /
                        # lease expiry, exactly as with a real etcd blip).
                        faults.point(
                            "store.watch", prefix=prefix, key=sub[0].key
                        )
                        cb(sub)
                    except Exception:  # watch callbacks must not kill the loop
                        pass

    def _sweep_loop(self) -> None:
        while True:
            time.sleep(0.05)
            with self._mu:
                if self._closed:
                    return
                now = self._clock()
                expired = [l for l in self._leases.values() if l.expires_at <= now]
                events: List[WatchEvent] = []
                for lease in expired:
                    for key in lease.keys:
                        if self._key_lease.get(key) == lease.lease_id:
                            self._kv.pop(key, None)
                            self._key_lease.pop(key, None)
                            events.append(WatchEvent(EventType.DELETE, key))
                    del self._leases[lease.lease_id]
                self._emit(events)

    def _attach(self, key: str, lease_id: int) -> None:
        # caller holds _mu
        old = self._key_lease.pop(key, None)
        if old is not None and old in self._leases:
            self._leases[old].keys.discard(key)
        if lease_id:
            if lease_id not in self._leases:
                raise KeyError(f"unknown lease {lease_id}")
            self._leases[lease_id].keys.add(key)
            self._key_lease[key] = lease_id

    # -- KV ----------------------------------------------------------------
    def get(self, key: str) -> Optional[str]:
        with self._mu:
            return self._kv.get(key)

    def set(self, key: str, value: str, lease_id: int = 0) -> bool:
        with self._mu:
            if lease_id and lease_id not in self._leases:
                return False
            self._kv[key] = value
            self._attach(key, lease_id)
            self._emit([WatchEvent(EventType.PUT, key, value)])
            return True

    def remove(self, key: str) -> bool:
        with self._mu:
            if key not in self._kv:
                return False
            del self._kv[key]
            self._attach(key, 0)
            self._emit([WatchEvent(EventType.DELETE, key)])
            return True

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        with self._mu:
            return {k: v for k, v in self._kv.items() if k.startswith(prefix)}

    # -- watches -----------------------------------------------------------
    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        with self._mu:
            wid = self._next_watch_id
            self._next_watch_id += 1
            self._watches[wid] = (prefix, callback)
            return wid

    def remove_watch(self, watch_id: int) -> None:
        with self._mu:
            self._watches.pop(watch_id, None)

    # -- leases ------------------------------------------------------------
    def grant_lease(self, ttl_s: float) -> int:
        with self._mu:
            lid = self._next_lease_id
            self._next_lease_id += 1
            self._leases[lid] = _Lease(lid, ttl_s, self._clock())
            return lid

    def keepalive(self, lease_id: int) -> bool:
        with self._mu:
            lease = self._leases.get(lease_id)
            if lease is None:
                return False
            lease.expires_at = self._clock() + lease.ttl_s
            return True

    def revoke_lease(self, lease_id: int) -> None:
        with self._mu:
            lease = self._leases.pop(lease_id, None)
            if lease is None:
                return
            events = []
            for key in lease.keys:
                if self._key_lease.get(key) == lease_id:
                    self._kv.pop(key, None)
                    self._key_lease.pop(key, None)
                    events.append(WatchEvent(EventType.DELETE, key))
            self._emit(events)

    # -- txns --------------------------------------------------------------
    def compare_create(self, key: str, value: str, lease_id: int = 0) -> bool:
        with self._mu:
            if key in self._kv:
                return False
            if lease_id and lease_id not in self._leases:
                return False
            self._kv[key] = value
            self._attach(key, lease_id)
            self._emit([WatchEvent(EventType.PUT, key, value)])
            return True

    def compare_create_with_epoch(
        self, key: str, value: str, epoch_key: str, lease_id: int = 0
    ) -> int:
        with self._mu:
            if key in self._kv:
                return 0
            if lease_id and lease_id not in self._leases:
                return 0
            epoch = int(self._kv.get(epoch_key, "0")) + 1
            self._kv[key] = value
            self._attach(key, lease_id)
            self._kv[epoch_key] = str(epoch)
            self._attach(epoch_key, 0)  # the fence outlives the lease
            self._emit([
                WatchEvent(EventType.PUT, key, value),
                WatchEvent(EventType.PUT, epoch_key, str(epoch)),
            ])
            return epoch

    def guarded_remove(self, keys: List[str], guard_key: str, guard_value: str) -> bool:
        with self._mu:
            if self._kv.get(guard_key) != guard_value:
                return False
            events = []
            for key in keys:
                if key in self._kv:
                    del self._kv[key]
                    self._attach(key, 0)
                    events.append(WatchEvent(EventType.DELETE, key))
            self._emit(events)
            return True

    def close(self) -> None:
        with self._mu:
            self._closed = True
            self._event_cv.notify_all()
        # Join OUTSIDE _mu (both loops need it to observe _closed), and
        # never from a watch callback running on the notifier itself.
        me = threading.current_thread()
        if self._notifier is not me:
            self._notifier.join(timeout=2)
        if self._sweeper is not me:
            self._sweeper.join(timeout=2)

    # Test hook: force-expire a lease without waiting for wall-clock TTL.
    def expire_lease_now(self, lease_id: int) -> None:
        with self._mu:
            lease = self._leases.get(lease_id)
            if lease is not None:
                lease.expires_at = 0.0


# Process-global named namespaces: "memory://ns" returns the same store for
# every component in this process, which is how tests wire a service replica
# set and fake instances together without sockets.
_MEMORY_STORES: Dict[str, MemoryStore] = {}
_MEMORY_MU = threading.Lock()


def _memory_store(namespace: str) -> MemoryStore:
    with _MEMORY_MU:
        st = _MEMORY_STORES.get(namespace)
        if st is None:
            st = MemoryStore()
            _MEMORY_STORES[namespace] = st
        return st


def reset_memory_namespace(namespace: str = "") -> None:
    """Drop a named in-process store (test isolation)."""
    with _MEMORY_MU:
        st = _MEMORY_STORES.pop(namespace, None)
    if st is not None:
        st.close()


# ---------------------------------------------------------------------------
# etcd v3 HTTP/JSON gateway backend
# ---------------------------------------------------------------------------


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


def _prefix_range_end(prefix: str) -> str:
    b = bytearray(prefix.encode())
    for i in reversed(range(len(b))):
        if b[i] < 0xFF:
            b[i] += 1
            return bytes(b[: i + 1]).decode("latin-1")
    return "\0"


class EtcdGatewayStore(CoordinationStore):
    """etcd v3 over its HTTP/JSON gateway (/v3/kv/..., /v3/lease/...).

    Matches the reference's etcd-cpp-apiv3 usage (etcd_client.cpp) without a
    client library. Watches are long-poll streams on /v3/watch, one reader
    thread per watch. This backend is exercised only when an etcd endpoint is
    reachable; unit tests use MemoryStore.
    """

    def __init__(self, addr: str):
        self._base = f"http://{addr}"
        self._watches: Dict[int, Tuple[threading.Thread, Any]] = {}
        self._next_watch_id = 1
        self._mu = threading.Lock()
        # Connectivity ping, mirroring the reference ctor's PING put
        # (etcd_client.cpp:24-33) — fail fast if etcd is unreachable.
        self._post("/v3/kv/put", {"key": _b64("XLLM:SERVICE:PING"), "value": _b64("1")})

    def _post(self, path: str, body: Dict[str, Any], timeout: float = 5.0) -> Dict[str, Any]:
        req = urllib.request.Request(
            self._base + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())

    def get(self, key: str) -> Optional[str]:
        r = self._post("/v3/kv/range", {"key": _b64(key)})
        kvs = r.get("kvs", [])
        return _unb64(kvs[0]["value"]) if kvs else None

    def set(self, key: str, value: str, lease_id: int = 0) -> bool:
        body: Dict[str, Any] = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            body["lease"] = str(lease_id)
        self._post("/v3/kv/put", body)
        return True

    def remove(self, key: str) -> bool:
        r = self._post("/v3/kv/deleterange", {"key": _b64(key)})
        return int(r.get("deleted", 0)) > 0

    def get_prefix(self, prefix: str) -> Dict[str, str]:
        r = self._post(
            "/v3/kv/range",
            {"key": _b64(prefix), "range_end": _b64(_prefix_range_end(prefix))},
        )
        return {_unb64(kv["key"]): _unb64(kv["value"]) for kv in r.get("kvs", [])}

    def grant_lease(self, ttl_s: float) -> int:
        r = self._post("/v3/lease/grant", {"TTL": str(max(1, int(ttl_s)))})
        return int(r["ID"])

    def keepalive(self, lease_id: int) -> bool:
        r = self._post("/v3/lease/keepalive", {"ID": str(lease_id)})
        return int(r.get("result", {}).get("TTL", 0)) > 0

    def revoke_lease(self, lease_id: int) -> None:
        self._post("/v3/lease/revoke", {"ID": str(lease_id)})

    def compare_create(self, key: str, value: str, lease_id: int = 0) -> bool:
        put: Dict[str, Any] = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            put["lease"] = str(lease_id)
        r = self._post(
            "/v3/kv/txn",
            {
                # create_revision == 0  <=>  key absent (reference election txn)
                "compare": [
                    {"key": _b64(key), "target": "CREATE", "create_revision": "0"}
                ],
                "success": [{"request_put": put}],
            },
        )
        return bool(r.get("succeeded", False))

    def compare_create_with_epoch(
        self, key: str, value: str, epoch_key: str, lease_id: int = 0
    ) -> int:
        """One etcd txn: [master absent AND epoch unchanged since read]
        -> [put master (leased), put epoch+1 (unleased)]. The epoch
        compare closes the read->txn window: two candidates racing the
        same vacancy both read epoch N, but only the txn winner commits
        N+1 — the loser's compare fails and it re-reads."""
        put_master: Dict[str, Any] = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            put_master["lease"] = str(lease_id)
        for _ in range(8):
            cur = self.get(epoch_key)
            nxt = int(cur or 0) + 1
            compare: List[Dict[str, Any]] = [
                {"key": _b64(key), "target": "CREATE", "create_revision": "0"}
            ]
            if cur is None:
                compare.append(
                    {"key": _b64(epoch_key), "target": "CREATE",
                     "create_revision": "0"}
                )
            else:
                compare.append(
                    {"key": _b64(epoch_key), "target": "VALUE",
                     "value": _b64(cur)}
                )
            r = self._post(
                "/v3/kv/txn",
                {
                    "compare": compare,
                    "success": [
                        {"request_put": put_master},
                        {"request_put": {
                            "key": _b64(epoch_key), "value": _b64(str(nxt))
                        }},
                    ],
                },
            )
            if r.get("succeeded", False):
                return nxt
            if self.get(key) is not None:
                return 0  # someone else holds the master key: lost
            # epoch moved under us (a master won and died inside the
            # window) — re-read and retry the txn
        return 0

    def guarded_remove(self, keys: List[str], guard_key: str, guard_value: str) -> bool:
        r = self._post(
            "/v3/kv/txn",
            {
                "compare": [
                    {"key": _b64(guard_key), "target": "VALUE", "value": _b64(guard_value)}
                ],
                "success": [
                    {"request_delete_range": {"key": _b64(k)}} for k in keys
                ],
            },
        )
        return bool(r.get("succeeded", False))

    def add_watch(self, prefix: str, callback: WatchCallback) -> int:
        stop = threading.Event()

        def reader() -> None:
            body = json.dumps(
                {
                    "create_request": {
                        "key": _b64(prefix),
                        "range_end": _b64(_prefix_range_end(prefix)),
                    }
                }
            ).encode()
            failures = 0
            while not stop.is_set():
                try:
                    req = urllib.request.Request(
                        self._base + "/v3/watch",
                        data=body,
                        headers={"Content-Type": "application/json"},
                    )
                    with urllib.request.urlopen(req, timeout=3600) as resp:
                        for line in resp:
                            if stop.is_set():
                                return
                            # A delivered response proves the stream is
                            # healthy again: reset the backoff ladder.
                            failures = 0
                            msg = json.loads(line.decode())
                            events = []
                            for ev in msg.get("result", {}).get("events", []):
                                kv = ev.get("kv", {})
                                etype = (
                                    EventType.DELETE
                                    if ev.get("type") == "DELETE"
                                    else EventType.PUT
                                )
                                events.append(
                                    WatchEvent(
                                        etype,
                                        _unb64(kv.get("key", "")),
                                        _unb64(kv["value"]) if kv.get("value") else "",
                                    )
                                )
                            if events:
                                callback(events)
                except Exception:
                    if not stop.is_set():
                        # Jittered exponential reconnect (counted): the
                        # old blind 1.0 s sleep marched every watcher in
                        # the fleet into synchronized reconnect storms
                        # against a recovering etcd.
                        _count_watch_reconnect()
                        time.sleep(_watch_backoff_s(failures))
                        failures += 1

        t = threading.Thread(target=reader, name=f"etcd-watch-{prefix}", daemon=True)
        t.start()
        with self._mu:
            wid = self._next_watch_id
            self._next_watch_id += 1
            self._watches[wid] = (t, stop)
            return wid

    def remove_watch(self, watch_id: int) -> None:
        with self._mu:
            entry = self._watches.pop(watch_id, None)
        if entry is not None:
            entry[1].set()


def connect(addr: str) -> CoordinationStore:
    """Open a coordination backend from an address string
    (reference: --etcd_addr flag, global_gflags.cpp)."""
    if addr.startswith("memory://"):
        return _memory_store(addr[len("memory://"):])
    if addr.startswith("etcd://"):
        return EtcdGatewayStore(addr[len("etcd://"):])
    # Bare host:port means etcd, matching the reference flag format.
    return EtcdGatewayStore(addr)
