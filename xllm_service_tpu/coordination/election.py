"""Master election over the coordination store.

The reference inlines this in the scheduler: a compare-create transaction on
`XLLM:SERVICE:MASTER` with a 3 s TTL lease, a keepalive/heartbeat loop while
master, and a watch-triggered takeover when the key vanishes
(reference: scheduler.cpp:27,38-42,113-121,132-149; etcd_client.cpp:47-62).
Here it is a reusable component with explicit elected/lost callbacks, and the
keepalive loop *detects* lease loss (store unreachable / lease expired) and
demotes itself — the reference silently keeps believing it is master.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from xllm_service_tpu.common import faults
from xllm_service_tpu.coordination.store import (
    CoordinationStore,
    EventType,
    WatchEvent,
)

MASTER_KEY = "XLLM:SERVICE:MASTER"
# Monotonic fencing epoch, bumped in the SAME store transaction that wins
# the master key (compare_create_with_epoch). Unleased: the fence must
# outlive every master so a successor always commits a higher value.
MASTER_EPOCH_KEY = MASTER_KEY + ":EPOCH"
# The active master's instance-plane (rpc) address, written under its
# election lease: deposed masters hand it to heartbeating instances so
# the fleet re-points even when a /reconcile never reached them.
MASTER_RPC_KEY = MASTER_KEY + ":RPC"


class MasterElection:
    def __init__(
        self,
        store: CoordinationStore,
        identity: str,
        lease_ttl_s: float = 3.0,
        on_elected: Optional[Callable[[], None]] = None,
        on_lost: Optional[Callable[[], None]] = None,
        master_key: str = MASTER_KEY,
        epoch_key: str = "",
    ) -> None:
        self._store = store
        self._identity = identity
        self._ttl = lease_ttl_s
        self._on_elected = on_elected
        self._on_lost = on_lost
        self._key = master_key
        self._epoch_key = epoch_key or master_key + ":EPOCH"
        self._mu = threading.Lock()
        self._is_master = False
        self._lease_id = 0
        self._epoch = 0  # epoch of OUR last won term (sticky after demote)
        self._stop = threading.Event()
        self._keepalive_thread: Optional[threading.Thread] = None
        self._watch_id: Optional[int] = None

    # -- public ------------------------------------------------------------
    @property
    def is_master(self) -> bool:
        with self._mu:
            return self._is_master

    @property
    def epoch(self) -> int:
        """Fencing epoch of this replica's most recent won term (0 =
        never elected). Deliberately sticky across demotion: a deposed
        master keeps stamping its OLD epoch on any straggler RPC, which
        is exactly what lets instances reject it."""
        with self._mu:
            return self._epoch

    @property
    def identity(self) -> str:
        return self._identity

    def current_master(self) -> Optional[str]:
        return self._store.get(self._key)

    def start(self) -> None:
        """Campaign once, then watch for vacancies (reference startup order:
        try election first, fall back to watching, scheduler.cpp:38-68)."""
        if not self._campaign():
            self._watch_id = self._store.add_watch(self._key, self._on_watch)
            # Re-check after installing the watch: the master may have died
            # between our failed campaign and the watch registration.
            if self._store.get(self._key) is None:
                self._campaign()

    def kill(self) -> None:
        """UNGRACEFUL death for fault injection: keepalives and watches
        stop but the lease is NOT revoked — the master key lingers until
        TTL expiry, exactly like a crashed master process. Standbys take
        over only once the store's liveness mechanism notices."""
        self._stop.set()
        if self._watch_id is not None:
            self._store.remove_watch(self._watch_id)
            self._watch_id = None
        with self._mu:
            self._is_master = False
        if self._keepalive_thread is not None:
            self._keepalive_thread.join(timeout=2.0)
            self._keepalive_thread = None

    def stop(self) -> None:
        self._stop.set()
        if self._watch_id is not None:
            self._store.remove_watch(self._watch_id)
            self._watch_id = None
        with self._mu:
            was_master, lease = self._is_master, self._lease_id
            self._is_master = False
        if was_master and lease:
            try:
                self._store.revoke_lease(lease)
            except Exception:
                pass
        if self._keepalive_thread is not None:
            self._keepalive_thread.join(timeout=2.0)
            self._keepalive_thread = None

    # -- internals ---------------------------------------------------------
    def _campaign(self) -> bool:
        # Join the PREVIOUS term's keepalive thread before starting a new
        # one: a demote->re-elect cycle used to overwrite the handle while
        # the old loop could still be mid-iteration, leaking a live
        # keepalive thread per cycle (and letting a stale loop touch the
        # new term's lease bookkeeping). The old loop exits on its own —
        # _is_master is already False — so the join is bounded.
        prev = self._keepalive_thread
        if prev is not None and prev is not threading.current_thread():
            prev.join(timeout=2.0)
            self._keepalive_thread = None
        lease = self._store.grant_lease(self._ttl)
        epoch = self._store.compare_create_with_epoch(
            self._key, self._identity, self._epoch_key, lease
        )
        if epoch:
            with self._mu:
                self._is_master = True
                self._lease_id = lease
                self._epoch = epoch
            t = threading.Thread(
                target=self._keepalive_loop, name="master-keepalive", daemon=True
            )
            # start() BEFORE publishing the handle: a concurrent stop()
            # must never observe (and join) a created-but-unstarted
            # thread. If stop() lands inside this window it joins the
            # previous handle (or None); the fresh loop exits on its own
            # at the first _stop check.
            t.start()
            self._keepalive_thread = t
            if self._on_elected:
                self._on_elected()
            return True
        self._store.revoke_lease(lease)
        return False

    def _keepalive_loop(self) -> None:
        period = max(0.05, self._ttl / 3.0)
        while not self._stop.wait(period):
            with self._mu:
                lease = self._lease_id if self._is_master else 0
            if not lease:
                return
            ok = False
            try:
                # Chaos hook: a dropped keepalive simulates the master's
                # store link partitioning — the lease lapses, a standby
                # takes over, and THIS replica must demote + fence.
                faults.point(
                    "election.keepalive",
                    identity=self._identity, lease=lease,
                )
                ok = self._store.keepalive(lease)
            except Exception:
                ok = False
            if not ok:
                self._demote()
                return

    def _demote(self) -> None:
        with self._mu:
            if not self._is_master:
                return
            self._is_master = False
            self._lease_id = 0
        if self._on_lost:
            self._on_lost()
        # Go back to watching for the next vacancy. The DELETE may already
        # have fired before the watch existed (our own lease expiry), so
        # re-check the key and campaign immediately if it is vacant — same
        # race start() closes after its failed first campaign.
        if self._watch_id is None and not self._stop.is_set():
            self._watch_id = self._store.add_watch(self._key, self._on_watch)
            try:
                vacant = self._store.get(self._key) is None
            except Exception:
                vacant = False
            if vacant and self._campaign() and self._watch_id is not None:
                self._store.remove_watch(self._watch_id)
                self._watch_id = None

    def _on_watch(self, events: List[WatchEvent]) -> None:
        if self._stop.is_set():
            return
        for ev in events:
            if ev.key == self._key and ev.type == EventType.DELETE:
                # Vacancy: attempt takeover (reference:
                # handle_master_service_watch, scheduler.cpp:132-149).
                if not self.is_master and self._campaign():
                    if self._watch_id is not None:
                        self._store.remove_watch(self._watch_id)
                        self._watch_id = None
                return
