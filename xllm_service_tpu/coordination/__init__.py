"""Coordination: store backends (memory / etcd gateway) and master election."""

from xllm_service_tpu.coordination.election import (
    MASTER_EPOCH_KEY,
    MASTER_KEY,
    MASTER_RPC_KEY,
    MasterElection,
)
from xllm_service_tpu.coordination.store import (
    CoordinationStore,
    EtcdGatewayStore,
    EventType,
    MemoryStore,
    WatchEvent,
    connect,
    reset_memory_namespace,
)

__all__ = [
    "MASTER_EPOCH_KEY",
    "MASTER_KEY",
    "MASTER_RPC_KEY",
    "MasterElection",
    "CoordinationStore",
    "EtcdGatewayStore",
    "EventType",
    "MemoryStore",
    "WatchEvent",
    "connect",
    "reset_memory_namespace",
]
