"""xllm_service_tpu — a TPU-native clustered LLM serving framework.

A ground-up rebuild of the capabilities of xllm-service (jd-opensource's
cluster service layer, see /root/reference) plus the engine tier it delegates
to, designed TPU-first:

- Engine tier: JAX/XLA/Pallas continuous-batching inference runtime with a
  paged KV cache, pjit/shard_map parallelism over `jax.sharding.Mesh`, and
  Pallas kernels for the hot ops (paged attention).
- Service tier: OpenAI-compatible HTTP front end, etcd-style coordination
  (with an in-memory backend for tests), instance registry with dynamic
  prefill/decode role flipping, global prefix-cache index keyed by chained
  murmur3 block hashes, and round-robin / cache-aware / SLO-aware routing.

Layering follows SURVEY.md; reference file:line citations appear in each
module's docstring.
"""

__version__ = "0.1.0"
