"""Ring attention: sequence/context-parallel exact attention for long
prefill (SURVEY.md §5 long-context row; the task brief makes SP
first-class).

TPU-first design: the sequence axis is sharded over an `sp` mesh axis.
Each device keeps its QUERY shard resident and the K/V shards rotate
around the ring with `jax.lax.ppermute` over ICI — sp steps of
(block attention + online-softmax merge), compute overlapping the
neighbor exchange. HBM never holds more than 1/sp of the context per
device, so max context scales linearly with the ring size; the math is
EXACT (flash-style log-sum-exp accumulation, not an approximation).

Blockwise/causal: with causal masking, chunks entirely in the future of a
query shard contribute nothing; their scores are masked to -inf and the
merge is a no-op (the ppermute still runs — the ring must stay in
lockstep; skipping compute for dead chunks is a `lax.cond` refinement
that does not change results).

GQA throughout: q [B, L, Hq, D], k/v [B, L, Hkv, D], Hq % Hkv == 0.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_scores(q, k, scale):
    """q [B, Lq, Hkv, G, D] f32, k [B, Lk, Hkv, D] f32 ->
    scores [B, Hkv, G, Lq, Lk] f32."""
    return jnp.einsum("bqhgd,bkhd->bhgqk", q, k) * scale


def _ring_attention_local(
    q,  # [B, Lc, Hq, D] — this device's query shard
    k,  # [B, Lc, Hkv, D] — this device's (initial) K shard
    v,  # [B, Lc, Hkv, D]
    *,
    axis_name: str,
    scale: float,
    causal: bool,
):
    B, Lc, Hq, D = q.shape
    Hkv = k.shape[2]
    G = Hq // Hkv
    n = (
        jax.lax.axis_size(axis_name)
        if hasattr(jax.lax, "axis_size")
        else jax.lax.psum(1, axis_name)  # jax < 0.5 spelling
    )
    me = jax.lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, Lc, Hkv, G, D)
    rows = me * Lc + jnp.arange(Lc, dtype=jnp.int32)  # global query positions

    m0 = jnp.full((B, Hkv, G, Lc, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Hkv, G, Lc, 1), jnp.float32)
    a0 = jnp.zeros((B, Lc, Hkv, G, D), jnp.float32)
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(s, carry):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        # After s forward rotations this device holds chunk (me - s) mod n.
        src = jax.lax.rem(me - s + n, n)
        cols = src * Lc + jnp.arange(Lc, dtype=jnp.int32)

        scores = _block_scores(qf, k_cur.astype(jnp.float32), scale)
        if causal:
            mask = cols[None, :] <= rows[:, None]  # [Lc_q, Lc_k]
            scores = jnp.where(mask[None, None, None], scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)  # [B,Hkv,G,Lq,1]
        m_new = jnp.maximum(m_prev, m_cur)
        # All-masked blocks keep m_new at NEG_INF: exp(0)=1 would pollute l,
        # so clamp the shift to stay a no-op.
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        p = jnp.exp(scores - m_new)
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhgqk,bkhd->bqhgd", p, v_cur.astype(jnp.float32))
        acc = acc * jnp.moveaxis(alpha, -2, 1)[..., 0][..., None] + pv

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, acc, k_nxt, v_nxt

    m, l, acc, _, _ = jax.lax.fori_loop(0, n, body, (m0, l0, a0, k, v))
    l_q = jnp.moveaxis(l, -2, 1)[..., 0][..., None]  # [B, Lc, Hkv, G, 1]
    out = acc / jnp.maximum(l_q, 1e-30)
    return out.reshape(B, Lc, Hq, D).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, L, Hq, D], L sharded over sp
    k: jnp.ndarray,  # [B, L, Hkv, D]
    v: jnp.ndarray,
    mesh: Mesh,
    sp_axis: str = "sp",
    scale: Optional[float] = None,
    causal: bool = True,
    tp_axis: Optional[str] = None,
) -> jnp.ndarray:
    """Sequence-parallel exact attention over `mesh`'s `sp_axis`.

    Call under `jit` with the mesh installed; inputs carry (or are given)
    shardings with L split over `sp_axis`. Returns [B, L, Hq, D] with the
    same sequence sharding.

    `tp_axis` COMPOSES sequence and tensor parallelism: the head axis
    additionally shards over that mesh axis (Hq and Hkv both divisible
    by its size — GQA grouping is per-shard). The ring's ppermute runs
    over sp only; heads need no cross-device communication, so the tp
    dimension is purely spatial here and the surrounding projections
    keep their Megatron sharding on the SAME mesh (VERDICT r4 #6)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    spec = P(None, sp_axis, tp_axis, None)
    local = functools.partial(
        _ring_attention_local,
        axis_name=sp_axis,
        scale=scale,
        causal=causal,
    )
    if hasattr(jax, "shard_map"):
        fn = jax.shard_map(
            local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
    else:  # jax < 0.6: the API (and the check_vma knob, née check_rep)
        from jax.experimental.shard_map import shard_map as _shard_map

        fn = _shard_map(
            local, mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False,
        )
    return fn(q, k, v)
