"""Rotary position embeddings.

Engine-tier op (the reference's RoPE lives in the absent CUDA engine —
SURVEY.md §2.3). Pure jnp: XLA fuses the sin/cos + elementwise rotation into
surrounding matmuls on TPU, so no Pallas kernel is warranted here.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(
    x: jnp.ndarray,  # [..., num_heads, head_dim]
    positions: jnp.ndarray,  # [...] int32, broadcastable to x's batch dims
    theta: float,
) -> jnp.ndarray:
    """Rotate pairs (x[2i], x[2i+1]) by positions * inv_freq[i].

    Uses the interleaved-pair convention expressed as split-half rotation on
    a de-interleaved view — matches HF Llama when weights are loaded with the
    standard permutation; for random-init + self-consistent decode any
    consistent convention is exact.
    """
    half = x.shape[-1] // 2
    inv_freq = rope_frequencies(x.shape[-1], theta)  # [half]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    cos = jnp.cos(angles)[..., None, :]  # [..., 1, half]
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)
