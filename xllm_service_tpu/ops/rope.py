"""Rotary position embeddings.

Engine-tier op (the reference's RoPE lives in the absent CUDA engine —
SURVEY.md §2.3). Pure jnp: XLA fuses the sin/cos + elementwise rotation into
surrounding matmuls on TPU, so no Pallas kernel is warranted here.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def _rotate(x: jnp.ndarray, angles: jnp.ndarray) -> jnp.ndarray:
    """Split-half rotation by per-(token, frequency) `angles` [..., half]
    — the single rotation convention both rope variants share (a future
    convention change must hit both or equal-streams M-RoPE would
    silently diverge from the standard path decode relies on)."""
    half = x.shape[-1] // 2
    cos = jnp.cos(angles)[..., None, :]  # [..., 1, half]
    sin = jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def apply_mrope(
    x: jnp.ndarray,  # [T, num_heads, head_dim]
    positions3: jnp.ndarray,  # [3, T] int32 — (t, h, w) position streams
    theta: float,
    section,  # static tuple of half-dim section sizes, e.g. (16, 24, 24)
) -> jnp.ndarray:
    """Multimodal rotary embedding (Qwen2-VL M-RoPE, HF
    apply_multimodal_rotary_pos_emb): frequency band i takes its ANGLE
    from position stream section_of(i) — the first `section[0]` inverse
    frequencies from the temporal stream, the next `section[1]` from the
    height stream, the rest from width. When the three streams are equal
    (every text token, every decode step) this IS apply_rope; image
    spans inside a prompt are where the streams diverge."""
    import numpy as np

    half = x.shape[-1] // 2
    assert sum(section) == half, (section, half)
    inv_freq = rope_frequencies(x.shape[-1], theta)  # [half]
    sel = np.repeat(np.arange(len(section)), section)  # [half] -> stream id
    pos_sel = positions3[jnp.asarray(sel)]  # [half, T]
    angles = pos_sel.T.astype(jnp.float32) * inv_freq  # [T, half]
    return _rotate(x, angles)


def apply_rope(
    x: jnp.ndarray,  # [..., num_heads, head_dim]
    positions: jnp.ndarray,  # [...] int32, broadcastable to x's batch dims
    theta: float,
) -> jnp.ndarray:
    """Rotate pairs (x[2i], x[2i+1]) by positions * inv_freq[i].

    Uses the interleaved-pair convention expressed as split-half rotation on
    a de-interleaved view — matches HF Llama when weights are loaded with the
    standard permutation; for random-init + self-consistent decode any
    consistent convention is exact.
    """
    inv_freq = rope_frequencies(x.shape[-1], theta)  # [half]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    return _rotate(x, angles)
