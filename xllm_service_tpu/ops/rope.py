"""Rotary position embeddings.

Engine-tier op (the reference's RoPE lives in the absent CUDA engine —
SURVEY.md §2.3). Pure jnp: XLA fuses the sin/cos + elementwise rotation into
surrounding matmuls on TPU, so no Pallas kernel is warranted here.
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np


def rope_frequencies(head_dim: int, theta: float) -> jnp.ndarray:
    """Inverse frequencies [head_dim/2], float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def rope_parameters(head_dim: int, cfg) -> tuple:
    """(inv_freq [head_dim/2] np.float32, output_scale float) honoring HF
    `rope_scaling` semantics (transformers modeling_rope_utils):

      - ""         plain theta frequencies
      - "linear"   positions stretched by `factor` (inv_freq / factor)
      - "dynamic"  NTK-scaled base, FROZEN at the extended range
                   original * factor. HF recomputes the base per forward
                   from the live sequence length, which is incoherent with
                   a paged KV cache (earlier keys would need re-rotation);
                   freezing at the full extended range is the serving
                   semantic (matches HF exactly for a single forward of
                   that length).
      - "llama3"   per-band wavelength interpolation (Llama-3.1/3.2)
      - "longrope" per-band short/long factor tables (Phi-3 128k).
                   rope_parameters returns the SHORT-table frequencies
                   (exact HF for any sequence within the original
                   context); apply_rope_scaled selects short/long PER
                   POSITION (pos < original -> short), which is coherent
                   with a paged KV cache — HF instead switches the whole
                   table per forward once seq_len exceeds the original,
                   retroactively re-rotating earlier positions, which a
                   cache-carrying engine cannot do (vLLM makes the same
                   per-position choice). Output additionally scales by
                   sqrt(1 + ln(factor)/ln(orig)) per HF, in BOTH modes
                   (HF fixes attention_scaling at init from the config
                   factor).

    `cfg` is duck-typed (ModelConfig or any object with the rope_* fields)
    so this op layer needs no import from models/. All math is numpy —
    static at trace time, so under jit the table is a compile-time
    constant. Unrecognized types raise at config parse (runtime/weights.
    config_from_hf), never here.
    """
    theta = float(cfg.rope_theta)
    exponent = np.arange(0, head_dim, 2, dtype=np.float32) / head_dim
    inv = _plain_inv_freq(head_dim, theta)
    typ = getattr(cfg, "rope_scaling_type", "") or ""
    if not typ:
        return inv, 1.0
    factor = float(getattr(cfg, "rope_scaling_factor", 1.0))
    orig = _orig_max_position(cfg)
    if typ == "linear":
        return inv / factor, 1.0
    if typ == "dynamic":
        # HF: base * ((factor * seq_len / orig) - (factor - 1)) ** (d/(d-2)),
        # here with seq_len pinned to orig * factor.
        base = theta * (factor * factor - factor + 1.0) ** (
            head_dim / (head_dim - 2)
        )
        return (1.0 / base**exponent).astype(np.float32), 1.0
    if typ == "llama3":
        lo = float(getattr(cfg, "rope_low_freq_factor", 1.0))
        hi = float(getattr(cfg, "rope_high_freq_factor", 4.0))
        low_wl, high_wl = orig / lo, orig / hi
        wavelen = 2.0 * np.pi / inv
        scaled = np.where(wavelen > low_wl, inv / factor, inv)
        smooth = (orig / wavelen - lo) / (hi - lo)
        smoothed = (1.0 - smooth) * scaled / factor + smooth * scaled
        medium = (wavelen >= high_wl) & (wavelen <= low_wl)
        return np.where(medium, smoothed, scaled).astype(np.float32), 1.0
    if typ == "longrope":
        short, _, mscale = _longrope_tables(head_dim, cfg, inv, orig)
        return short, mscale
    if typ == "yarn":
        # HF _compute_yarn_parameters (arxiv 2309.00071): blend the
        # interpolated (inv/factor) and extrapolated (inv) tables with a
        # linear ramp between the beta_fast/beta_slow correction dims;
        # the attention factor follows the paper's 0.1*ln(s)+1 mscale —
        # DeepSeek configs supply mscale/mscale_all_dim and get the
        # RATIO (their checkpoints also scale the softmax temperature,
        # which the MLA attention applies — models/deepseek.py).
        bf = float(getattr(cfg, "rope_beta_fast", 32.0)) or 32.0
        bs = float(getattr(cfg, "rope_beta_slow", 1.0)) or 1.0
        msc = float(getattr(cfg, "rope_mscale", 0.0))
        msc_all = float(getattr(cfg, "rope_mscale_all_dim", 0.0))
        att = float(getattr(cfg, "rope_attention_factor", 0.0))
        if not att:
            if msc and msc_all:
                att = yarn_mscale(factor, msc) / yarn_mscale(
                    factor, msc_all
                )
            else:
                att = yarn_mscale(factor)

        def corr_dim(rot: float) -> float:
            return (
                head_dim * math.log(orig / (rot * 2.0 * math.pi))
            ) / (2.0 * math.log(theta))

        low, high = corr_dim(bf), corr_dim(bs)
        if getattr(cfg, "rope_scaling_truncate", True):
            low, high = math.floor(low), math.ceil(high)
        low, high = max(low, 0), min(high, head_dim - 1)
        if low == high:
            high += 0.001  # HF's singularity guard
        ramp = np.clip(
            (np.arange(head_dim // 2, dtype=np.float32) - low)
            / (high - low),
            0.0, 1.0,
        )
        extrap = 1.0 - ramp
        return (
            (inv / factor) * (1.0 - extrap) + inv * extrap
        ).astype(np.float32), float(att)
    raise NotImplementedError(f"rope_scaling type {typ!r}")


def yarn_mscale(scale: float, mscale: float = 1.0) -> float:
    """The yarn paper's attention-temperature term (HF get_mscale);
    DeepSeek's attention ALSO multiplies its softmax scale by
    yarn_mscale(factor, mscale_all_dim)^2 — models/deepseek.py."""
    if scale <= 1.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def _plain_inv_freq(head_dim: int, theta: float) -> np.ndarray:
    """Unscaled inverse-frequency table — the single base-convention
    source for every scaling type (numpy: static at trace time)."""
    exponent = np.arange(0, head_dim, 2, dtype=np.float32) / head_dim
    return (1.0 / theta**exponent).astype(np.float32)


def _orig_max_position(cfg) -> int:
    return int(getattr(cfg, "rope_original_max_position", 0)) or int(
        cfg.max_position_embeddings
    )


def _longrope_tables(head_dim: int, cfg, inv: np.ndarray, orig: int):
    """(short_inv_freq, long_inv_freq, attention_scale) for longrope."""
    tables = []
    for name in ("rope_short_factor", "rope_long_factor"):
        ext = np.asarray(getattr(cfg, name), dtype=np.float32)
        if ext.shape != inv.shape:
            raise ValueError(
                f"longrope {name} table has {ext.shape[0]} entries; "
                f"head_dim {head_dim} needs {inv.shape[0]}"
            )
        tables.append((inv / ext).astype(np.float32))
    mscale = float(getattr(cfg, "rope_attention_factor", 0.0))
    if not mscale:
        ctx_factor = cfg.max_position_embeddings / orig
        mscale = (
            math.sqrt(1.0 + math.log(ctx_factor) / math.log(orig))
            if ctx_factor > 1.0
            else 1.0
        )
    return tables[0], tables[1], mscale


def _rotate(
    x: jnp.ndarray, angles: jnp.ndarray, scale: float = 1.0
) -> jnp.ndarray:
    """Split-half rotation by per-(token, frequency) `angles` [..., half]
    — the single rotation convention both rope variants share (a future
    convention change must hit both or equal-streams M-RoPE would
    silently diverge from the standard path decode relies on). `scale`
    multiplies cos AND sin (HF longrope attention_factor placement), i.e.
    scales the rotated output."""
    half = x.shape[-1] // 2
    cos = scale * jnp.cos(angles)[..., None, :]  # [..., 1, half]
    sin = scale * jnp.sin(angles)[..., None, :]
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def apply_mrope(
    x: jnp.ndarray,  # [T, num_heads, head_dim]
    positions3: jnp.ndarray,  # [3, T] int32 — (t, h, w) position streams
    theta: float,
    section,  # static tuple of half-dim section sizes, e.g. (16, 24, 24)
) -> jnp.ndarray:
    """Multimodal rotary embedding (Qwen2-VL M-RoPE, HF
    apply_multimodal_rotary_pos_emb): frequency band i takes its ANGLE
    from position stream section_of(i) — the first `section[0]` inverse
    frequencies from the temporal stream, the next `section[1]` from the
    height stream, the rest from width. When the three streams are equal
    (every text token, every decode step) this IS apply_rope; image
    spans inside a prompt are where the streams diverge."""
    import numpy as np

    half = x.shape[-1] // 2
    assert sum(section) == half, (section, half)
    inv_freq = rope_frequencies(x.shape[-1], theta)  # [half]
    sel = np.repeat(np.arange(len(section)), section)  # [half] -> stream id
    pos_sel = positions3[jnp.asarray(sel)]  # [half, T]
    angles = pos_sel.T.astype(jnp.float32) * inv_freq  # [T, half]
    return _rotate(x, angles)


def apply_rope(
    x: jnp.ndarray,  # [..., num_heads, head_dim]
    positions: jnp.ndarray,  # [...] int32, broadcastable to x's batch dims
    theta: float,
) -> jnp.ndarray:
    """Rotate pairs (x[2i], x[2i+1]) by positions * inv_freq[i].

    Uses the interleaved-pair convention expressed as split-half rotation on
    a de-interleaved view — matches HF Llama when weights are loaded with the
    standard permutation; for random-init + self-consistent decode any
    consistent convention is exact.
    """
    inv_freq = rope_frequencies(x.shape[-1], theta)  # [half]
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    return _rotate(x, angles)


def apply_rope_scaled(
    x: jnp.ndarray,  # [..., num_heads, head_dim]
    positions: jnp.ndarray,  # [...] int32, broadcastable to x's batch dims
    cfg,  # ModelConfig-like: rope_theta + rope_scaling_* fields
) -> jnp.ndarray:
    """apply_rope honoring the config's HF rope_scaling (rope_parameters).

    The model call sites route through here; configs without scaling
    (rope_scaling_type == "") reduce exactly to apply_rope. longrope
    selects the short/long table PER POSITION (pos < original context ->
    short) — exact HF inside the original context, cache-coherent beyond
    it (see rope_parameters docstring)."""
    head_dim = x.shape[-1]
    pos = positions[..., None].astype(jnp.float32)
    if getattr(cfg, "rope_scaling_type", "") == "longrope":
        inv = _plain_inv_freq(head_dim, float(cfg.rope_theta))
        orig = _orig_max_position(cfg)
        short_t, long_t, scale = _longrope_tables(head_dim, cfg, inv, orig)
        angles = jnp.where(pos < orig, pos * short_t, pos * long_t)
        return _rotate(x, angles, scale)
    inv_freq, scale = rope_parameters(head_dim, cfg)
    angles = pos * inv_freq
    return _rotate(x, angles, scale)
