"""Latency-hiding collective matmuls for the sharded hot loop.

ROADMAP item 6 (ISSUE 18). PRs 12/13 made every GQA and MoE kernel
dispatch per-shard, but the hot loop still serializes compute against
its collectives: the tp o-proj/down-proj matmuls contract over a
sharded axis and the GSPMD partitioner lowers them as local-matmul
THEN all-reduce — the reduction sits on the critical path after the
compute it depends on. This module decomposes those sites into
`lax.ppermute`-based collective-matmul pipelines (the classic ring
reduce-scatter + ring all-gather schedule):

  * **Ring reduce-scatter matmul** — the output-column axis E splits
    into n chunks of Ec = E/n. At step 0 shard i computes its local
    tile of chunk (i-1) mod n; at step s it rotates the running
    partial one hop around the ring (i -> i+1) and adds its tile of
    chunk (i-1-s) mod n. Each tile matmul is independent of the
    in-flight permute, so XLA schedules the collective-permute DMA
    under the next tile's compute — the reduction rides beneath the
    matmul instead of after it. After n-1 steps shard i holds the
    FULLY reduced chunk i.
  * **Ring all-gather** — n-1 more hops rotate the reduced chunks so
    every shard reassembles the replicated [.., E] output (the serving
    steps consume the o-proj/down-proj output replicated, exactly like
    the psum the schedule replaces).

2(n-1) permutes total, each of size |out|/n — same bytes on the wire
as the all-reduce it replaces, but pipelined under compute.

Numerics: the ring adds partials in ring order while the GSPMD
all-reduce uses its own reduction tree, so arrays may differ by f32
reduction-order noise (~1e-6) — the PR-12 contract: token streams must
stay BIT-EQUAL, which tests/test_overlap_collectives.py pins across
tp x ep virtual meshes. The ep expert-combine is stricter: per-slot
values are exact zeros on non-owning shards, so `ring_all_reduce`
reproduces the psum bits exactly.

Hatch: `XLLM_OVERLAP_COLLECTIVES=1` opts in (default OFF — serving
keeps the GSPMD psum lowering until the overlap validates on chip);
`=0` always wins. The tp context is the one the executor already
declares before every jitted step family (ops.attention's per-thread
shard context, read raw — the overlap tier gates on its own hatch,
not on XLLM_SHARDED_KERNELS). Ineligible geometries (axis extent that
doesn't divide H or E) fall back to the caller's einsum, so the hatch
can never change which shapes serve.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def overlap_collectives_enabled() -> bool:
    """Whether the sharded hot loop decomposes its tp/ep combines into
    ring collective-matmul pipelines. Opt-in; =0 always wins."""
    return os.environ.get("XLLM_OVERLAP_COLLECTIVES", "0") not in (
        "", "0", "false", "off",
    )


def tp_overlap_context() -> Optional[Tuple[object, str]]:
    """(mesh, axis) for the tp ring when the overlap hatch is on and the
    executor has declared a tp>1 shard context for this thread; else
    None. Reads the RAW context (ops.attention declares it for any tp>1
    mesh) — XLLM_SHARDED_KERNELS gates kernel dispatch, not this tier."""
    if not overlap_collectives_enabled():
        return None
    from xllm_service_tpu.ops import attention as att

    return att.declared_shard_context()


# Trace-time instrumentation: how many matmul sites actually took the
# ring schedule (the engine's per-step counter multiplies this by
# dispatches; the differential suite asserts it moved). Thread-local
# like the shard context — one engine thread per executor.
_TRACE_TLS = threading.local()


def overlap_sites_traced() -> int:
    return getattr(_TRACE_TLS, "sites", 0)


def _note_site() -> None:
    _TRACE_TLS.sites = getattr(_TRACE_TLS, "sites", 0) + 1


def _shard_map_fn():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    return shard_map


def _ring_perm(n: int):
    return [(j, (j + 1) % n) for j in range(n)]


def ring_all_reduce(x: jnp.ndarray, axis: str, n: int) -> jnp.ndarray:
    """Drop-in `lax.psum(x, axis)` replacement inside a shard_map body:
    ring reduce-scatter over x's LAST axis followed by a ring
    all-gather, so each hop's add overlaps the next hop's permute.
    Falls back to psum when the last axis doesn't split n ways.

    Used by the grouped-MoE ep combine: per-slot outputs are exact
    zeros off the owning shard, so ring order reproduces the psum bits
    exactly (0 + v == v + 0 == v in every order)."""
    E = x.shape[-1]
    if n <= 1 or E % n != 0:
        return jax.lax.psum(x, axis)
    Ec = E // n
    i = jax.lax.axis_index(axis).astype(jnp.int32)
    perm = _ring_perm(n)
    last = x.ndim - 1

    def chunk(c):
        return jax.lax.dynamic_slice_in_dim(x, c * Ec, Ec, axis=last)

    # Reduce-scatter: after step s, the partial travelling through
    # shard i covers chunk (i-1-s) mod n summed over s+1 shards; the
    # final hop lands chunk i on shard i fully reduced.
    acc = chunk((i - 1) % n)
    for s in range(1, n):
        acc = jax.lax.ppermute(acc, axis, perm) + chunk((i - 1 - s) % n)

    # All-gather: rotate the reduced chunks back around the ring.
    out = jnp.zeros_like(x)
    out = jax.lax.dynamic_update_slice_in_dim(out, acc, i * Ec, axis=last)
    g = acc
    for s in range(1, n):
        g = jax.lax.ppermute(g, axis, perm)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, g, ((i - s) % n) * Ec, axis=last
        )
    return out


def _ring_matmul_body(x, w, *, axis: str, n: int):
    """Per-shard body: x [..., H/n] (this shard's slice of the
    contraction axis), w [H/n, E] (this shard's row block) ->
    [..., E] replicated fully-reduced product.

    The tile matmul at step s is independent of the permute launched at
    step s, which is what lets XLA hide the DMA under compute."""
    E = w.shape[-1]
    Ec = E // n
    i = jax.lax.axis_index(axis).astype(jnp.int32)
    perm = _ring_perm(n)

    def tile(c):
        wc = jax.lax.dynamic_slice_in_dim(w, c * Ec, Ec, axis=1)
        return jnp.matmul(x, wc)

    acc = tile((i - 1) % n)
    for s in range(1, n):
        acc = jax.lax.ppermute(acc, axis, perm) + tile((i - 1 - s) % n)

    out = jnp.zeros(x.shape[:-1] + (E,), acc.dtype)
    last = out.ndim - 1
    out = jax.lax.dynamic_update_slice_in_dim(out, acc, i * Ec, axis=last)
    g = acc
    for s in range(1, n):
        g = jax.lax.ppermute(g, axis, perm)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, g, ((i - s) % n) * Ec, axis=last
        )
    return out


def maybe_overlap_matmul(
    x: jnp.ndarray, w: jnp.ndarray
) -> Optional[jnp.ndarray]:
    """Overlapped row-parallel matmul `x @ w` (x [..., H] with H the
    mesh-sharded contraction axis, w [H, E]) when the hatch + a tp>1
    context apply and the geometry divides; else None — the caller
    keeps its original einsum so the default path's lowering (and
    bits) are untouched when the hatch is off."""
    ctx = tp_overlap_context()
    if ctx is None:
        return None
    mesh, axis = ctx
    n = int(mesh.shape[axis])
    H, E = int(w.shape[0]), int(w.shape[1])
    if n <= 1 or H % n != 0 or E % n != 0 or int(x.shape[-1]) != H:
        return None
    from jax.sharding import PartitionSpec as P

    x_spec = P(*([None] * (x.ndim - 1) + [axis]))
    fn = _shard_map_fn()(
        lambda xb, wb: _ring_matmul_body(xb, wb, axis=axis, n=n),
        mesh=mesh,
        in_specs=(x_spec, P(axis, None)),
        out_specs=P(),
        check_rep=False,
    )
    _note_site()
    return fn(x, w)
