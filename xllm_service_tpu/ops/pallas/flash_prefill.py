"""Pallas TPU flash-attention kernel for chunked paged prefill.

The prefill half of SURVEY.md §7 hard part #1 (the decode half is
ops/pallas/paged_attention.py; the reference's CUDA analogs live in its
absent engine submodule). The executor scatters a prefill chunk's K/V
rows into the paged pool FIRST (models/llama.py prefill_batch_step), so
attention here reads everything — prefix AND chunk — from the cache:
query at absolute position p attends to cache positions 0..p.

Design (flash, manual double-buffered DMA, chunked blocks — the decode
kernel's loop structure with a query-tile axis):
  * grid = (P, Hkv, NT): one program per (sequence, KV head, query tile).
    A tile is TQ consecutive chunk positions; its G = Hq//Hkv query heads
    ride along as TQ*G sublane rows, so scores are ONE
    [TQ*G, C*BS] MXU matmul per inner step.
  * the inner fori_loop streams cache blocks HBM→VMEM through a 2-slot
    buffer (C block-table entries per iteration, next chunk's DMA
    overlapped with compute). Its bound is the tile's OWN context
    length — ceil((start_pos + min((t+1)*TQ, true_len)) / (C*BS)) — so
    early tiles don't pay for late context and padded tiles run nothing.
  * causal + ragged masking by absolute position: row r (query position
    start_pos + t*TQ + r//G) keeps column c*span + j iff that cache
    position <= its own, and rows past true_len are dead (l=0 → zeros).
  * int8 caches: sub-channel scales ride pool-native as [N, Hkv, G, BS]
    f32 — one [G, BS] tile DMA per (block, head) — and tiles dequantize
    in VMEM via the shared expansion matmul (paged_attention.dequant_tile
    explains why column folding is off the table).

Layouts: q [P, Lpad, Hq, D] (chunk-relative), caches [N, Hkv, BS, D],
block_table [P, MB] int32, start_pos/true_len [P] int32. Returns
[P, Lpad, Hq, D]. Parity oracle: ops/attention.prefill_attention_blockwise
(tests/test_pallas_kernels.py drives interpret mode on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic
from xllm_service_tpu.ops.pallas.paged_attention import dequant_tile

NEG_INF = -1e30


def _prefill_kernel(
    # scalar prefetch
    block_table_ref,  # [P, MBp] SMEM
    start_pos_ref,    # [P] SMEM
    true_len_ref,     # [P] SMEM
    # inputs
    q_ref,            # [1, 1, 1, Rp, D] VMEM (one tile's TQ*G rows)
    k_hbm,            # [N, Hkv, BS, D] HBM
    v_hbm,            # [N, Hkv, BS, D] HBM
    *rest,            # quantized: ks_hbm, vs_hbm [N, Hkv, G, BS] f32; then
    # o_ref + scratch (quantized scale bufs are [2, C, G, BS] f32)
    block_size: int,
    chunk: int,
    tile_q: int,
    groups: int,
    scale: float,
    quantized: bool,
    scale_groups: int = 8,
    window: int = 0,
):
    if quantized:
        ks_hbm, vs_hbm, o_ref, k_buf, v_buf, sems, ks_buf, vs_buf, ssems = rest
    else:
        o_ref, k_buf, v_buf, sems = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = ssems = None
    p = pl.program_id(0)
    h = pl.program_id(1)
    t = pl.program_id(2)
    start = start_pos_ref[p]
    n_valid = true_len_ref[p]
    span = chunk * block_size

    # This tile's context: positions 0 .. start + min((t+1)*TQ, true_len).
    tile_lo = t * tile_q  # first chunk-relative position of the tile
    ctx = start + jnp.minimum(tile_lo + tile_q, n_valid)
    nc = jnp.where(tile_lo < n_valid, pl.cdiv(ctx, span), 0)
    # Sliding window: the chunk walk starts at the first chunk holding any
    # in-window column (earliest window start across the tile's rows is
    # start + tile_lo - window + 1); earlier blocks never stream, so SWA
    # prefill bandwidth is O(L * window), not O(L * context).
    c0 = (
        jnp.maximum(start + tile_lo - window + 1, 0) // span
        if window > 0 else 0
    )

    def dmas(slot, c_idx, blk):
        off = c_idx * block_size
        out = [
            mosaic.async_copy(
                mosaic.checked_at(k_hbm, blk, h),
                mosaic.checked_at(k_buf, slot, pl.ds(off, block_size)),
                sems.at[slot, 0, c_idx],
            ),
            mosaic.async_copy(
                mosaic.checked_at(v_hbm, blk, h),
                mosaic.checked_at(v_buf, slot, pl.ds(off, block_size)),
                sems.at[slot, 1, c_idx],
            ),
        ]
        if quantized:
            # Head h's [G, BS] scale tile (blk, h on untiled dims).
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(ks_hbm, blk, h),
                    mosaic.checked_at(ks_buf, slot, c_idx),
                    ssems.at[slot, 0, c_idx],
                )
            )
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(vs_hbm, blk, h),
                    mosaic.checked_at(vs_buf, slot, c_idx),
                    ssems.at[slot, 1, c_idx],
                )
            )
        return out

    def start_chunk(slot, c):
        for c_idx in range(chunk):
            blk = block_table_ref[p, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.start()

    def wait_chunk(slot, c):
        for c_idx in range(chunk):
            blk = block_table_ref[p, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.wait()

    @pl.when(nc > 0)
    def _first():
        start_chunk(jax.lax.rem(c0, 2) if window > 0 else 0, c0)

    q = q_ref[0, 0, 0]  # [Rp, D]
    Rp, D = q.shape
    # Absolute position of each query row: start + tile_lo + row // G.
    row_pos = start + tile_lo + (
        jax.lax.broadcasted_iota(jnp.int32, (Rp, 1), 0) // groups
    )
    row_valid = tile_lo + (
        jax.lax.broadcasted_iota(jnp.int32, (Rp, 1), 0) // groups
    ) < n_valid

    def body(c, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        k_tile = k_buf[slot]
        if quantized:
            k_tile = dequant_tile(
                k_tile, ks_buf[slot], chunk, block_size, scale_groups
            )
        scores = (
            jax.lax.dot_general(
                q, k_tile,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Rp, C*BS] f32
        col_pos = c * span + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        keep = (col_pos <= row_pos) & row_valid
        if window > 0:
            # HF SWA semantics: position p attends [p-window+1, p].
            keep &= col_pos > row_pos - window
        scores = jnp.where(keep, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        # Fully-masked-so-far rows: keep alpha/p at 0 so acc stays 0.
        alpha = jnp.where(
            m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - m_new)
        )
        pmat = jnp.where(
            m_new <= NEG_INF / 2, 0.0, jnp.exp(scores - m_new)
        )
        l_new = alpha * l_prev + jnp.sum(pmat, axis=-1, keepdims=True)
        if quantized:
            v_tile = dequant_tile(
                v_buf[slot], vs_buf[slot], chunk, block_size, scale_groups
            )
            pv = jnp.dot(
                pmat.astype(jnp.bfloat16), v_tile,
                preferred_element_type=jnp.float32,
            )
        else:
            pv = jnp.dot(
                pmat.astype(k_buf.dtype), v_buf[slot],
                preferred_element_type=jnp.float32,
            )
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((Rp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Rp, 1), jnp.float32)
    a0 = jnp.zeros((Rp, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(c0, nc, body, (m0, l0, a0))
    o_ref[0, 0, 0] = jnp.where(
        l > 0, acc / jnp.maximum(l, 1e-30), 0.0
    ).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(
    jax.jit,
    static_argnames=("scale", "interpret", "chunk", "tile_q", "window"),
)
def flash_prefill_kernel(
    q: jnp.ndarray,            # [P, Lpad, Hq, D]
    k_cache,                   # [N, Hkv, BS, D] plain array or PagedKV
    v_cache,
    block_table: jnp.ndarray,  # [P, MB] int32
    start_pos: jnp.ndarray,    # [P] int32
    true_len: jnp.ndarray,     # [P] int32
    scale: float,
    interpret: bool = False,
    chunk: int = 4,
    tile_q: int = 128,
    window: int = 0,
) -> jnp.ndarray:
    from xllm_service_tpu.ops import kv_cache as kvc

    k_cache = kvc.as_paged(k_cache)
    v_cache = kvc.as_paged(v_cache)
    quantized = k_cache.quantized
    k_data, v_data = k_cache.data, v_cache.data

    P, Lpad, Hq, D = q.shape
    N, Hkv, BS, _ = k_data.shape
    MB = block_table.shape[1]
    G = Hq // Hkv
    TQ = min(tile_q, _round_up(Lpad, 8))
    # Rows per tile must satisfy 8-sublane tiling: TQ*G padded via TQ.
    while (TQ * G) % 8:
        TQ += 1
    Lp = _round_up(Lpad, TQ)
    NT = Lp // TQ
    Rp = TQ * G
    C = max(1, min(chunk, MB))

    qt = q
    if Lp != Lpad:
        qt = jnp.pad(qt, ((0, 0), (0, Lp - Lpad), (0, 0), (0, 0)))
    # [P, Lp, Hq, D] -> [P, Hkv, NT, TQ*G, D], rows position-major so
    # row // G is the chunk-relative query offset within the tile.
    qt = qt.reshape(P, NT, TQ, Hkv, G, D)
    qt = qt.transpose(0, 3, 1, 2, 4, 5).reshape(P, Hkv, NT, Rp, D)

    MBp = _round_up(MB, C)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec(
            (1, 1, 1, Rp, D), lambda p, h, t, bt, sp, tl: (p, h, t, 0, 0)
        ),
        hbm,
        hbm,
    ]
    inputs = [
        bt, start_pos.astype(jnp.int32), true_len.astype(jnp.int32),
        qt, k_data, v_data,
    ]
    scratch = [
        pltpu.VMEM((2, C * BS, D), k_data.dtype),
        pltpu.VMEM((2, C * BS, D), v_data.dtype),
        pltpu.SemaphoreType.DMA((2, 2, C)),
    ]
    SG = k_cache.scale.shape[-2] if quantized else 8  # sub-channel groups
    kv_bytes_per_row = D * k_data.dtype.itemsize
    if quantized:
        in_specs += [hbm, hbm]
        # Pool-native [N, Hkv, G, BS] grouped plane (see kv_cache.py).
        inputs += [
            k_cache.scale.astype(jnp.float32),
            v_cache.scale.astype(jnp.float32),
        ]
        scratch += [
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, C)),
        ]
        # Per-block scale tile is [G, BS] f32: 4*G bytes per row.
        kv_bytes_per_row += 4 * SG

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(P, Hkv, NT),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, 1, Rp, D), lambda p, h, t, bt, sp, tl: (p, h, t, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _prefill_kernel, block_size=BS, chunk=C, tile_q=TQ, groups=G,
        scale=scale, quantized=quantized,
        scale_groups=SG, window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, Hkv, NT, Rp, D), q.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            # ~L^2/2 causal flops per (seq, head-group); bytes dominated by
            # re-streaming the context per query tile.
            flops=2 * P * Hq * D * Lp * (Lp + 2 * MB * BS) // 2,
            bytes_accessed=(
                P * Lp * Hq * D * 4
                + P * NT * MB * BS * Hkv * kv_bytes_per_row
            ),
            transcendentals=P * Hq * Lp * MB * BS // max(NT, 1),
        ),
        interpret=interpret,
    )(*inputs)
    # [P, Hkv, NT, TQ*G, D] -> [P, Lp, Hq, D] -> slice chunk rows.
    out = out.reshape(P, Hkv, NT, TQ, G, D).transpose(0, 2, 3, 1, 4, 5)
    return out.reshape(P, Lp, Hq, D)[:, :Lpad]
