"""Pallas TPU paged-attention decode kernel.

The engine's hottest op (SURVEY.md §7 hard part #1; the reference's CUDA
analog lives in the absent engine submodule). One query token per running
sequence attends to that sequence's paged KV context.

Design (flash-decode, manual double-buffered DMA, chunked blocks):
  * grid = (R, Hkv): one program per (sequence, KV head). The K/V caches
    stay in HBM (`pl.ANY`); the kernel streams this sequence's blocks
    through a 2-slot VMEM buffer with `make_async_copy`, overlapping the
    next chunk's DMA with the current chunk's compute.
  * each inner iteration processes a CHUNK of `C` consecutive block-table
    entries as one [C*BS, D] tile -> a single [Gp, C*BS] score matmul.
    Shape search on real hardware: one-block-per-grid-step (4096 programs)
    and one-block-per-iteration (16 iters of ~10 ns MXU work) are both
    loop-latency-bound (~300 ns/step floor), and an 8x head-unrolled body
    stalls the Mosaic compiler; C=4 chunking cuts iteration count 4x with
    no code-size growth.
  * the block table and sequence lengths ride in scalar-prefetch SMEM; the
    inner `fori_loop` bound is the sequence's true chunk count, so no
    bandwidth is spent on other sequences' blocks. Padding entries within
    a live chunk DMA the reserved garbage block and are masked out of the
    softmax by column index.
  * GQA: the G = Hq//Hkv query heads of one KV head are processed together,
    zero-padded to Gp = roundup(G, 8) sublanes to satisfy TPU tiling;
    scores are bf16-in/f32-accum on the MXU (the fast path).

Cache layout matches ops/attention.py: k/v `[num_blocks, Hkv, BS, D]`;
q `[R, Hq, D]`; block_table `[R, MB]` int32; seq_lens `[R]` int32 (context
length INCLUDING the current token). Returns `[R, Hq, D]`.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic

NEG_INF = -1e30


def dequant_tile(tile, s_buf, chunk, block_size, scale_groups):
    """VMEM dequant of an int8 cache tile [CH*BS, D] with sub-channel
    scales [CH, G, BS] (the pool's [.., H, G, BS] plane, one head's [G,
    BS] tile DMA'd per block): expand the scales to the D lanes via a
    constant 0/1 matmul (E[g, d] = 1 iff lane d's group is g) contracting
    the G axis — no lane reshapes or sublane-dynamic slices, which Mosaic
    rejects. HBM already moved int8 bytes; this is VPU/MXU work on
    resident data. Shared by every int8 kernel path (GQA + MLA, decode +
    prefill + multi-query).

    Why scales aren't folded into score/probability columns anymore (the
    round-2 scheme): column folding needs ONE scale per cache row, but a
    per-row scale plane cannot be tiled legally on every tp shard —
    Mosaic DMA slices must be (8, 128)-tile multiples and tp slices Hkv
    to 1 on production llama shards. Grouped [G % 8 == 0, BS] tiles are
    shard-invariant, and sub-channel grouping buys precision."""
    D = tile.shape[-1]
    gsz = D // scale_groups
    E = (
        jax.lax.broadcasted_iota(jnp.int32, (scale_groups, D), 1) // gsz
        == jax.lax.broadcasted_iota(jnp.int32, (scale_groups, D), 0)
    ).astype(jnp.float32)
    s_exp = jax.lax.dot_general(
        s_buf, E,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [CH, BS, D]
    s_exp = s_exp.reshape(chunk * block_size, D)
    return (tile.astype(jnp.float32) * s_exp).astype(jnp.bfloat16)


def _decode_kernel(
    # scalar prefetch
    block_table_ref,  # [R, MBp] SMEM (padded to a multiple of C with 0s)
    seq_lens_ref,     # [R]      SMEM
    # inputs
    q_ref,            # [1, 1, Gp, D] VMEM
    k_hbm,            # [N, Hkv, BS, D] HBM (pl.ANY) — bf16 or int8
    v_hbm,            # [N, Hkv, BS, D] HBM (pl.ANY)
    *rest,            # quantized: ks_hbm, vs_hbm [N, Hkv, G, BS] f32, then
    # output
    #   o_ref         # [1, 1, Gp, D] VMEM
    # scratch
    #   k_buf, v_buf  # [2, C*BS, D] VMEM (cache dtype)
    #   sems          # [2, 2, C] DMA semaphores
    #   (quantized)   ks_buf, vs_buf [2, C, G, BS] f32 + ssems [2, 2, C]
    block_size: int,
    chunk: int,
    scale: float,
    quantized: bool,
    s_rows: int = 1,
    gp: int = 0,
    scale_groups: int = 8,
    window: int = 0,
):
    if quantized:
        ks_hbm, vs_hbm, o_ref, k_buf, v_buf, sems, ks_buf, vs_buf, ssems = rest
    else:
        o_ref, k_buf, v_buf, sems = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = ssems = None
    r = pl.program_id(0)
    h = pl.program_id(1)
    seq_len = seq_lens_ref[r]
    span = chunk * block_size
    # Sliding-window attention: the chunk walk starts at the first chunk
    # holding any in-window position (earliest window start across the
    # s_rows queries is seq_len - window) — blocks wholly below it never
    # stream, so SWA decode bandwidth is O(window), not O(context).
    c_lo = (
        jnp.maximum(seq_len - window, 0) // span if window > 0 else 0
    )
    if s_rows == 1:
        nc = pl.cdiv(seq_len, span)  # chunks to process
    else:
        # Multi-query (speculative verify): query row s attends to context
        # seq_len + s, so the chunk walk must cover the LAST row's context;
        # inactive slots (seq_len = 0) still process no chunks. Clamp to
        # the table width: near max_seq_len the caller may have sized the
        # table for fewer than S extra rows (true_len < S) — rows past
        # that bound are garbage the sampler never emits, and walking
        # beyond the table would read out-of-bounds SMEM block ids.
        nc = jnp.minimum(
            jnp.where(seq_len == 0, 0, pl.cdiv(seq_len + s_rows - 1, span)),
            block_table_ref.shape[1] // chunk,
        )

    def dmas(slot, c_idx, blk):
        off = c_idx * block_size
        out = [
            mosaic.async_copy(
                    mosaic.checked_at(k_hbm, blk, h),
                    mosaic.checked_at(k_buf, slot, pl.ds(off, block_size)),
                    sems.at[slot, 0, c_idx],
                ),
            mosaic.async_copy(
                    mosaic.checked_at(v_hbm, blk, h),
                    mosaic.checked_at(v_buf, slot, pl.ds(off, block_size)),
                    sems.at[slot, 1, c_idx],
                ),
        ]
        if quantized:
            # Head h's [G, BS] scale tile (blk, h on untiled dims).
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(ks_hbm, blk, h),
                    mosaic.checked_at(ks_buf, slot, c_idx),
                    ssems.at[slot, 0, c_idx],
                )
            )
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(vs_hbm, blk, h),
                    mosaic.checked_at(vs_buf, slot, c_idx),
                    ssems.at[slot, 1, c_idx],
                )
            )
        return out

    def start_chunk(slot, c):
        for c_idx in range(chunk):  # static, small
            blk = block_table_ref[r, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.start()

    def wait_chunk(slot, c):
        for c_idx in range(chunk):
            blk = block_table_ref[r, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.wait()

    # Inactive decode slots carry seq_len = 0: issue no DMAs (their
    # semaphores would never be awaited and could satisfy a later grid
    # step's wait early) and emit zeros.
    @pl.when(nc > c_lo)
    def _first():
        start_chunk(jax.lax.rem(c_lo, 2), c_lo)

    q = q_ref[0, 0]  # [Gp, D], model dtype (bf16 on TPU)

    def body(c, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        k_tile = k_buf[slot]
        if quantized:
            k_tile = dequant_tile(
                k_tile, ks_buf[slot], chunk, block_size, scale_groups
            )
        scores = (
            jax.lax.dot_general(
                q, k_tile,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Gp, C*BS] f32
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        if s_rows == 1:
            valid = c * span + col < seq_len
            if window > 0:
                valid &= c * span + col >= seq_len - window
        else:
            # q tile rows are [S, Gp] flattened: row // gp is the query's
            # offset from the first fed position (causal within the step).
            row = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = c * span + col < seq_len + row // gp
            if window > 0:
                valid &= c * span + col >= seq_len + row // gp - window
        scores = jnp.where(valid, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        if quantized:
            v_tile = dequant_tile(
                v_buf[slot], vs_buf[slot], chunk, block_size, scale_groups
            )
            pv = jnp.dot(
                p.astype(jnp.bfloat16), v_tile,
                preferred_element_type=jnp.float32,
            )  # [Gp, D] f32
        else:
            pv = jnp.dot(
                p.astype(k_buf.dtype), v_buf[slot],
                preferred_element_type=jnp.float32,
            )
        return m_new, l_new, acc * alpha + pv

    Gp, D = q_ref.shape[2], q_ref.shape[3]
    m0 = jnp.full((Gp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Gp, 1), jnp.float32)
    a0 = jnp.zeros((Gp, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(c_lo, nc, body, (m0, l0, a0))
    # an active slot always has seq_len >= 1 (l > 0); inactive slots get 0
    o_ref[0, 0] = jnp.where(
        nc > c_lo, acc / jnp.maximum(l, 1e-30), 0.0
    ).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "chunk", "window")
)
def paged_attention_kernel(
    q: jnp.ndarray,            # [R, Hq, D]
    k_cache,                   # [N, Hkv, BS, D] plain array or PagedKV
    v_cache,
    block_table: jnp.ndarray,  # [R, MB] int32
    seq_lens: jnp.ndarray,     # [R] int32
    scale: float,
    interpret: bool = False,
    chunk: int = 4,
    window: int = 0,
) -> jnp.ndarray:
    from xllm_service_tpu.ops import kv_cache as kvc

    k_cache = kvc.as_paged(k_cache)
    v_cache = kvc.as_paged(v_cache)
    quantized = k_cache.quantized
    k_data, v_data = k_cache.data, v_cache.data

    R, Hq, D = q.shape
    N, Hkv, BS, _ = k_data.shape
    MB = block_table.shape[1]
    G = Hq // Hkv
    Gp = _round_up(G, 8)
    C = max(1, min(chunk, MB))

    qr = q.reshape(R, Hkv, G, D)
    if Gp != G:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, Gp - G), (0, 0)))
    MBp = _round_up(MB, C)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        # Chunk-tail entries point at the reserved garbage block 0; their
        # columns are masked out by seq_len anyway.
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    # Pin the caches to HBM explicitly: under pl.ANY the compiler may place
    # a small cache in VMEM, where the [BS, D] per-block slice is illegal
    # for D < 128 (lane-padded tiling); HBM DMA slices are contiguous.
    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, 1, Gp, D), lambda r, h, bt, sl: (r, h, 0, 0)),
        hbm,
        hbm,
    ]
    inputs = [bt, seq_lens.astype(jnp.int32), qr, k_data, v_data]
    scratch = [
        pltpu.VMEM((2, C * BS, D), k_data.dtype),
        pltpu.VMEM((2, C * BS, D), v_data.dtype),
        pltpu.SemaphoreType.DMA((2, 2, C)),
    ]
    SG = k_cache.scale.shape[-2] if quantized else 8  # sub-channel groups
    kv_bytes_per_row = D * k_data.dtype.itemsize
    if quantized:
        in_specs += [hbm, hbm]
        # Pool-native [N, Hkv, G, BS] grouped plane (kv_cache.py) — no
        # per-call relayout, tile-legal on every tp shard.
        inputs += [
            k_cache.scale.astype(jnp.float32),
            v_cache.scale.astype(jnp.float32),
        ]
        scratch += [
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, C)),
        ]
        # Per-block scale tile is [G, BS] f32: 4*G bytes per row.
        kv_bytes_per_row += 4 * SG

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, Gp, D), lambda r, h, bt, sl: (r, h, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _decode_kernel, block_size=BS, chunk=C, scale=scale,
        quantized=quantized,
        scale_groups=SG, window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, Hkv, Gp, D), q.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * R * Hkv * Gp * D * MB * BS,  # qk + pv
            bytes_accessed=(
                R * Hq * D * 4 + 2 * R * MB * BS * Hkv * kv_bytes_per_row
            ),
            transcendentals=R * Hkv * Gp * MB * BS,
        ),
        interpret=interpret,
    )(*inputs)
    return out[:, :, :G, :].reshape(R, Hq, D)


@functools.partial(
    jax.jit, static_argnames=("scale", "interpret", "chunk", "window")
)
def multiquery_paged_attention_kernel(
    q: jnp.ndarray,            # [R, S, Hq, D] — S consecutive query tokens
    k_cache,                   # [N, Hkv, BS, D] plain array or PagedKV
    v_cache,
    block_table: jnp.ndarray,  # [R, MB] int32
    seq_lens: jnp.ndarray,     # [R] int32 — context INCLUDING the FIRST
    # query token; row s of a sequence attends to seq_lens + s rows
    scale: float,
    interpret: bool = False,
    chunk: int = 4,
    window: int = 0,
) -> jnp.ndarray:
    """Speculative-verify attention: the decode kernel with S query rows
    per sequence. Same HBM traffic as one decode step (each KV row streams
    once), S times the MXU work — the shape speculative decoding wants.
    The S*G query heads of one KV head ride one [S*Gp, D] tile; causal
    masking within the step is by tile-row // Gp. Returns [R, S, Hq, D]."""
    from xllm_service_tpu.ops import kv_cache as kvc

    k_cache = kvc.as_paged(k_cache)
    v_cache = kvc.as_paged(v_cache)
    quantized = k_cache.quantized
    k_data, v_data = k_cache.data, v_cache.data

    R, S, Hq, D = q.shape
    N, Hkv, BS, _ = k_data.shape
    MB = block_table.shape[1]
    G = Hq // Hkv
    Gp = _round_up(G, 8)
    C = max(1, min(chunk, MB))

    # [R, S, Hkv, G, D] -> [R, Hkv, S, Gp, D] -> [R, Hkv, S*Gp, D]
    qr = jnp.swapaxes(q.reshape(R, S, Hkv, G, D), 1, 2)
    if Gp != G:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, 0), (0, Gp - G), (0, 0)))
    qr = qr.reshape(R, Hkv, S * Gp, D)
    MBp = _round_up(MB, C)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, 1, S * Gp, D), lambda r, h, bt, sl: (r, h, 0, 0)),
        hbm,
        hbm,
    ]
    inputs = [bt, seq_lens.astype(jnp.int32), qr, k_data, v_data]
    scratch = [
        pltpu.VMEM((2, C * BS, D), k_data.dtype),
        pltpu.VMEM((2, C * BS, D), v_data.dtype),
        pltpu.SemaphoreType.DMA((2, 2, C)),
    ]
    SG = k_cache.scale.shape[-2] if quantized else 8  # sub-channel groups
    kv_bytes_per_row = D * k_data.dtype.itemsize
    if quantized:
        in_specs += [hbm, hbm]
        # Pool-native [N, Hkv, G, BS] grouped plane (kv_cache.py) — no
        # per-call relayout, tile-legal on every tp shard.
        inputs += [
            k_cache.scale.astype(jnp.float32),
            v_cache.scale.astype(jnp.float32),
        ]
        scratch += [
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, C)),
        ]
        # Per-block scale tile is [G, BS] f32: 4*G bytes per row.
        kv_bytes_per_row += 4 * SG

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, S * Gp, D), lambda r, h, bt, sl: (r, h, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _decode_kernel, block_size=BS, chunk=C, scale=scale,
        quantized=quantized, s_rows=S, gp=Gp,
        scale_groups=SG, window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, Hkv, S * Gp, D), q.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * R * Hkv * S * Gp * D * MB * BS,
            bytes_accessed=(
                R * S * Hq * D * 4 + 2 * R * MB * BS * Hkv * kv_bytes_per_row
            ),
            transcendentals=R * Hkv * S * Gp * MB * BS,
        ),
        interpret=interpret,
    )(*inputs)
    # [R, Hkv, S*Gp, D] -> [R, Hkv, S, Gp, D] -> [R, S, Hq, D]
    out = out.reshape(R, Hkv, S, Gp, D)[:, :, :, :G, :]
    return jnp.swapaxes(out, 1, 2).reshape(R, S, Hq, D)
