"""Static Mosaic DMA layout-legality rules, enforced at trace time.

Round 3's first silicon contact surfaced two Mosaic rules that interpret
mode NEVER enforces (BASELINE.md, third round-3 session) — an entire
round-2 int8 scale layout passed every CPU test and failed on first chip
contact. This module encodes those rules so a kernel layout can never
again pass interpret and fail silicon:

  1. **Tile-multiple extents.** A DMA slice's extents on the last two
     (tiled) axes must be (8, 128)-tile multiples even at full extent —
     exactly the bound the chip enforced; dtype-finer tiling (bf16
     (16,128), int8 (32,128)) has not been observed to reject 8-row
     multiples, so 8 is the rule until silicon says otherwise. The
     round-2/3 failures this catches: a flat [N, BS*G] f32 scale plane
     sliced [1, BS*G] (1 sublane row), a [..., BS, G] plane with G=8
     lanes, and the unpadded [BS, 576] MLA latent row (576 % 128 != 0).
  2. **Dynamic offsets ride only on untiled leading dims.** A traced
     (non-Python-int) index may address any dim strictly before the last
     two; the tiled trailing dims take only static offsets.

`async_copy` is a drop-in for `pltpu.make_async_copy` that validates
both endpoint shapes (shapes are static at Pallas trace time, so these
are plain Python checks — zero runtime cost on chip, and they fire in
interpret mode and under CPU tests alike). `check_slice_indices`
validates rule 2 for an `.at[...]` index tuple; kernels route their
`.at` slicing through `checked_at`.

tests/test_pallas_kernels.py pins the ruleset: the known-bad round-2
layouts are rejected, every current kernel's copies pass.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu


class MosaicLayoutError(ValueError):
    """A DMA layout that interpret mode accepts but real Mosaic rejects."""


SUBLANE = 8  # empirically enforced sublane granularity (see module doc)


def check_copy_shape(shape: Sequence[int], dtype, what: str = "copy") -> None:
    """Rule 1: extents on the last two dims must be (8, 128) multiples."""
    if len(shape) == 0:
        return
    lanes = shape[-1]
    if lanes % 128:
        raise MosaicLayoutError(
            f"{what}: lane extent {lanes} (shape {tuple(shape)}, dtype "
            f"{jnp.dtype(dtype).name}) is not a multiple of 128 — Mosaic "
            f"rejects this DMA on real hardware even though interpret "
            f"mode accepts it (chip finding, round 3). Lane-pad the "
            f"layout (see kv_cache.mla_cache_dim / kv_pack_factor)."
        )
    if len(shape) >= 2 and shape[-2] % SUBLANE:
        raise MosaicLayoutError(
            f"{what}: sublane extent {shape[-2]} (shape {tuple(shape)}, "
            f"dtype {jnp.dtype(dtype).name}) is not a multiple of the "
            f"{SUBLANE}-row tile — Mosaic rejects sub-tile sublane "
            f"slices on real hardware (the round-2 flat scale plane "
            f"failed exactly here). Group rows so the slice covers "
            f"whole tiles (see kv_cache GQA_SCALE_GROUPS)."
        )


def check_slice_indices(ndim: int, idx: Sequence[Any], what: str = "at") -> None:
    """Rule 2: dynamic (traced) offsets only on dims before the last two.

    `idx` holds the per-dim indices passed to `.at[...]` (ints, traced
    scalars, or `pl.ds(...)` objects). A python int is static; anything
    else is treated as dynamic unless it is a `pl.ds` whose start is a
    python int."""
    for d, ix in enumerate(idx):
        if isinstance(ix, int) or ix is None or isinstance(ix, slice):
            continue
        start = getattr(ix, "start", None)
        if start is not None and isinstance(start, int):
            continue  # static pl.ds
        if d >= ndim - 2:
            raise MosaicLayoutError(
                f"{what}: dynamic offset on dim {d} of a {ndim}-d ref — "
                f"Mosaic only accepts dynamic DMA offsets on untiled "
                f"leading dims (before the last two). Restructure the "
                f"layout so the dynamic index (block id, head) rides a "
                f"leading dim (chip finding, round 3)."
            )


def checked_at(ref, *idx):
    """`ref.at[*idx]` with rule-2 validation on the index tuple."""
    check_slice_indices(len(ref.shape), idx)
    return ref.at[tuple(idx)]


def hbm_space():
    """The HBM memory-space enum across jax versions: newer jax exposes
    `pltpu.MemorySpace.HBM` (the explicit pin the kernels want — under
    ANY the compiler may place a small cache in VMEM where sub-128-lane
    block slices are illegal); older releases only have
    `pltpu.TPUMemorySpace.ANY`, their equivalent for DMA-from-HBM
    operands."""
    ms = getattr(pltpu, "MemorySpace", None)
    if ms is not None and hasattr(ms, "HBM"):
        return ms.HBM
    return pltpu.TPUMemorySpace.ANY


def compiler_params(**kw):
    """`pltpu.CompilerParams(**kw)` with fallback to the pre-rename
    `TPUCompilerParams` (jax < 0.5) — one shim instead of a per-kernel
    version check."""
    cp = getattr(pltpu, "CompilerParams", None)
    if cp is None:
        cp = pltpu.TPUCompilerParams
    return cp(**kw)


def async_copy(src, dst, sem):
    """`pltpu.make_async_copy` with rule-1 validation on both endpoints.

    The copied extents are the (already-sliced) ref shapes; dims of size
    1 at the front (e.g. the [1, BS, C] result of `.at[blk, 0]` keeping
    a unit axis) don't participate in tiling and are ignored beyond the
    last two."""
    check_copy_shape(src.shape, src.dtype, what="DMA src")
    check_copy_shape(dst.shape, dst.dtype, what="DMA dst")
    return pltpu.make_async_copy(src, dst, sem)
