"""Pallas TPU decode kernel for Multi-head Latent Attention (DeepSeek).

The MLA decode op (ops/attention.mla_paged_attention_gather) is, like GQA
decode, HBM-bandwidth-bound — but its traffic is the compressed latent
cache (kv_rank + rope_dim floats/token, shared by ALL heads), so the
gather fallback's weakness is different: XLA materializes the gathered
context [R, MB*BS, C] per layer in HBM before the einsum. This kernel
streams the sequence's latent blocks HBM→VMEM once and fuses scores +
online softmax + latent-context accumulation, never materializing the
gathered context.

Design (one program per SEQUENCE — no head axis in the grid):
  * the latents are shared across heads, so all Hq heads' scores for a
    chunk come from ONE [Hqp, C] x [C, T] matmul — MXU-shaped (Hq is 128
    for DeepSeek-V3); the grid is just (R,).
  * double-buffered chunk DMA with scalar-prefetched block tables, same
    scheme as the GQA kernel (ops/pallas/paged_attention.py).
  * pv accumulates in LATENT space ([Hqp, kv_rank]) — W_UV is applied by
    the caller once per token, outside the kernel, exactly like the
    absorbed gather path.

Cache layout: c_cache [N, 1, BS, C] (ops/attention.py MLA contract);
q_lat [R, Hq, C]; block_table [R, MB]; seq_lens [R]. Returns
[R, Hq, kv_rank]. C (576 for V3) need not be a multiple of 128 — Mosaic
lane-pads the VMEM tiles.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic

NEG_INF = -1e30


from xllm_service_tpu.ops.pallas.paged_attention import dequant_tile

_dequant_tile = dequant_tile  # shared with mla_prefill (historical name)


def _mla_kernel(
    # scalar prefetch
    block_table_ref,  # [R, MBp] SMEM
    seq_lens_ref,     # [R] SMEM
    # inputs
    q_ref,            # [1, Hqp, C] VMEM
    c_hbm,            # [N, 1, BS, C] HBM — bf16 or int8
    *rest,            # quantized: cs_hbm [N, 1, G, BS] f32, then
    # output
    #   o_ref         # [1, Hqp, KVR] VMEM
    # scratch
    #   c_buf         # [2, CH*BS, C] VMEM (cache dtype)
    #   sems          # [2, CH] DMA semaphores
    #   (quantized)   s_buf [2, CH, G, BS] f32 + ssems [2, CH]
    block_size: int,
    chunk: int,
    scale: float,
    kv_rank: int,
    s_rows: int = 1,
    hqp: int = 0,
    quantized: bool = False,
    scale_groups: int = 1,
):
    if quantized:
        cs_hbm, o_ref, c_buf, sems, s_buf, ssems = rest
    else:
        o_ref, c_buf, sems = rest
        cs_hbm = s_buf = ssems = None
    r = pl.program_id(0)
    seq_len = seq_lens_ref[r]
    span = chunk * block_size
    if s_rows == 1:
        nc = pl.cdiv(seq_len, span)
    else:
        # Multi-query (speculative verify): row s attends to seq_len + s
        # context rows; clamp to the table width (true_len < S near
        # max_seq_len) and keep inactive slots at zero chunks.
        nc = jnp.minimum(
            jnp.where(seq_len == 0, 0, pl.cdiv(seq_len + s_rows - 1, span)),
            block_table_ref.shape[1] // chunk,
        )

    def dmas(slot, c_idx, blk):
        out = [
            mosaic.async_copy(
                    mosaic.checked_at(c_hbm, blk, 0),
                    mosaic.checked_at(c_buf, slot, pl.ds(c_idx * block_size, block_size)),
                    sems.at[slot, c_idx],
                )
        ]
        if quantized:
            # Full-extent [G, BS] scale tile (blk on the untiled dim).
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(cs_hbm, blk, 0),
                    mosaic.checked_at(s_buf, slot, c_idx),
                    ssems.at[slot, c_idx],
                )
            )
        return out

    def start_chunk(slot, c):
        for c_idx in range(chunk):
            for d in dmas(slot, c_idx, block_table_ref[r, c * chunk + c_idx]):
                d.start()

    def wait_chunk(slot, c):
        for c_idx in range(chunk):
            for d in dmas(slot, c_idx, block_table_ref[r, c * chunk + c_idx]):
                d.wait()

    @pl.when(nc > 0)
    def _first():
        start_chunk(0, 0)

    q = q_ref[0]  # [Hqp, C]

    def body(c, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        tile = c_buf[slot]  # [CH*BS, C]
        if quantized:
            tile = _dequant_tile(
                tile, s_buf[slot], chunk, block_size, scale_groups
            )
        scores = (
            jax.lax.dot_general(
                q, tile,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Hqp, CH*BS]
        col = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
        if s_rows == 1:
            valid = c * span + col < seq_len
        else:
            row = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 0)
            valid = c * span + col < seq_len + row // hqp
        scores = jnp.where(valid, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(tile.dtype), tile[:, :kv_rank],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Hqp, KVR]
        return m_new, l_new, acc * alpha + pv

    Hqp = q_ref.shape[1]
    m0 = jnp.full((Hqp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Hqp, 1), jnp.float32)
    a0 = jnp.zeros((Hqp, kv_rank), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nc, body, (m0, l0, a0))
    o_ref[0] = jnp.where(
        nc > 0, acc / jnp.maximum(l, 1e-30), 0.0
    ).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _mla_common(c_cache):
    """Split a plain-or-PagedKV latent cache into (data, scales, groups).

    Scales stay in their pool-native [N, 1, G, BS] layout (G groups on
    sublanes, BS on lanes, G a multiple of 8 — kv_cache.mla_scale_groups
    guarantees it): each block's DMA is then a full-extent [G, BS] tile
    with the dynamic block id on the untiled leading dim. Mosaic accepts
    only full (8,128)-tile-aligned extents on the last two dims of a DMA
    slice (chip finding, round 3) — both the old flat [N, BS*G] plane
    (1-sublane row slices) and a [.., BS, G] layout (G non-128 lanes)
    fail to compile on real hardware."""
    from xllm_service_tpu.ops import kv_cache as kvc

    c_cache = kvc.as_paged(c_cache)
    data = c_cache.data
    if not c_cache.quantized:
        return data, None, 1
    sc = c_cache.scale
    if sc.ndim != data.ndim or sc.shape[-2] % 8:
        raise ValueError(
            f"int8 MLA caches need grouped [N, 1, G, BS] scales with "
            f"G % 8 == 0 (got scale shape {sc.shape}); allocate via "
            f"kv_cache.alloc_cache with kv_cache.mla_scale_groups"
        )
    return data, sc.astype(jnp.float32), sc.shape[-2]


@functools.partial(
    jax.jit, static_argnames=("scale", "kv_rank", "interpret", "chunk")
)
def mla_attention_kernel(
    q_lat: jnp.ndarray,        # [R, Hq, C]
    c_cache,                   # [N, 1, BS, C] plain array or PagedKV
    block_table: jnp.ndarray,  # [R, MB] int32
    seq_lens: jnp.ndarray,     # [R] int32
    scale: float,
    kv_rank: int,
    interpret: bool = False,
    chunk: int = 4,
) -> jnp.ndarray:
    data, scales, G = _mla_common(c_cache)
    quantized = scales is not None
    R, Hq, C = q_lat.shape
    N, _, BS, _ = data.shape
    MB = block_table.shape[1]
    Hqp = _round_up(Hq, 8)
    CH = max(1, min(chunk, MB))

    qr = q_lat
    if Hqp != Hq:
        qr = jnp.pad(qr, ((0, 0), (0, Hqp - Hq), (0, 0)))
    MBp = _round_up(MB, CH)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, Hqp, C), lambda r, bt, sl: (r, 0, 0)),
        hbm,
    ]
    inputs = [bt, seq_lens.astype(jnp.int32), qr, data]
    scratch = [
        pltpu.VMEM((2, CH * BS, C), data.dtype),
        pltpu.SemaphoreType.DMA((2, CH)),
    ]
    row_bytes = C * data.dtype.itemsize
    if quantized:
        in_specs.append(hbm)
        inputs.append(scales)
        scratch += [
            pltpu.VMEM((2, CH, G, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, CH)),
        ]
        row_bytes += 4 * G
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, Hqp, kv_rank), lambda r, bt, sl: (r, 0, 0)),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _mla_kernel, block_size=BS, chunk=CH, scale=scale, kv_rank=kv_rank,
        quantized=quantized, scale_groups=G,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, Hqp, kv_rank), q_lat.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * R * Hqp * C * MB * BS + 2 * R * Hqp * kv_rank * MB * BS,
            bytes_accessed=R * MB * BS * row_bytes,
            transcendentals=R * Hqp * MB * BS,
        ),
        interpret=interpret,
    )(*inputs)
    return out[:, :Hq, :]


@functools.partial(
    jax.jit, static_argnames=("scale", "kv_rank", "interpret", "chunk")
)
def mla_multiquery_attention_kernel(
    q_lat: jnp.ndarray,        # [R, S, Hq, C] — S consecutive query tokens
    c_cache,                   # [N, 1, BS, C] plain array or PagedKV
    block_table: jnp.ndarray,  # [R, MB] int32
    seq_lens: jnp.ndarray,     # [R] int32 — context INCLUDING the FIRST
    # query token; row s attends to seq_lens + s rows
    scale: float,
    kv_rank: int,
    interpret: bool = False,
    chunk: int = 4,
) -> jnp.ndarray:
    """Speculative-verify MLA attention: the decode kernel with S query
    rows per sequence riding one [S*Hqp, C] tile — same latent-cache HBM
    traffic as one decode step, S times the MXU work. Causal masking
    within the step is by tile-row // Hqp. Returns [R, S, Hq, kv_rank]."""
    data, scales, G = _mla_common(c_cache)
    quantized = scales is not None
    R, S, Hq, C = q_lat.shape
    N, _, BS, _ = data.shape
    MB = block_table.shape[1]
    Hqp = _round_up(Hq, 8)
    CH = max(1, min(chunk, MB))

    qr = q_lat
    if Hqp != Hq:
        qr = jnp.pad(qr, ((0, 0), (0, 0), (0, Hqp - Hq), (0, 0)))
    qr = qr.reshape(R, S * Hqp, C)
    MBp = _round_up(MB, CH)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, S * Hqp, C), lambda r, bt, sl: (r, 0, 0)),
        hbm,
    ]
    inputs = [bt, seq_lens.astype(jnp.int32), qr, data]
    scratch = [
        pltpu.VMEM((2, CH * BS, C), data.dtype),
        pltpu.SemaphoreType.DMA((2, CH)),
    ]
    row_bytes = C * data.dtype.itemsize
    if quantized:
        in_specs.append(hbm)
        inputs.append(scales)
        scratch += [
            pltpu.VMEM((2, CH, G, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, CH)),
        ]
        row_bytes += 4 * G
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(R,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, S * Hqp, kv_rank), lambda r, bt, sl: (r, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _mla_kernel, block_size=BS, chunk=CH, scale=scale, kv_rank=kv_rank,
        s_rows=S, hqp=Hqp, quantized=quantized, scale_groups=G,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((R, S * Hqp, kv_rank), q_lat.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * R * S * Hqp * (C + kv_rank) * MB * BS,
            bytes_accessed=R * MB * BS * row_bytes,
            transcendentals=R * S * Hqp * MB * BS,
        ),
        interpret=interpret,
    )(*inputs)
    return out.reshape(R, S, Hqp, kv_rank)[:, :, :Hq, :]
