"""Pallas TPU ragged paged-attention kernel for MIXED prefill+decode batches.

The unified dispatch the Ragged Paged Attention paper (arxiv 2604.15464)
argues for, and ISSUE 9's tentpole: ONE kernel launch serves a batch
mixing chunked-prefill rows (arbitrary query length, prefix-aware start
offsets, causal + ragged masking by absolute position) and decode rows
(query length 1) over the same paged KV pool — replacing the separate
decode-kernel + flash-prefill launches and the prefill/decode batch split
in the engine hot loop (runtime/engine.py mixed step).

Contract (shared with ops.attention.ragged_attention_blockwise, the
CPU/parity oracle):

  * queries ride FLATTENED: q [T, Hq, D], the concatenation of every
    row's query-token segment. Per-row segment CAPACITIES `seg_lens`
    (static tuple, sum == T) fix each row's offset q_lo[b] at trace
    time; the dynamic `q_len[b] <= seg_lens[b]` marks the valid prefix
    (0 = dead row — inactive decode slot or padded prefill lane).
  * `pos0[b]` is the ABSOLUTE position of row b's first query token, so
    token j of row b sits at position pos0[b]+j and attends cache
    positions 0..pos0[b]+j within block_tables[b] (prefix-cache hits
    simply raise pos0; decode rows are seg 1 with pos0 = seq_len-1).

Design (the decode/flash kernels' manual double-buffered DMA structure
with a RAGGED query-tile axis):

  * grid = (NT, Hkv): one program per (flattened query tile, KV head).
    A tile is TQ consecutive flattened tokens — tiles freely CROSS row
    boundaries (a 128-token tile can hold 128 decode rows, one prefill
    chunk's slab, or a mix), which is what makes the launch count
    independent of batch composition.
  * per tile, the kernel loops over the rows overlapping it (row ranges
    are static per tile — segment offsets are static — and ride scalar
    prefetch), and per row streams that row's context blocks HBM→VMEM
    through the 2-slot buffer, C block-table entries per inner step.
    Scores for the whole [TQ*G, C*BS] tile are ONE MXU matmul per step;
    rows not owned by the current row-iteration mask to NEG_INF and
    fall out of the online-softmax merge exactly (their alpha is 1 and
    p is 0), so the flash accumulator needs no per-row state.
  * TPU grid programs execute sequentially per core, so serializing a
    tile's rows costs nothing vs the old per-row grid — total DMA and
    MXU work is identical; what the fusion buys is one launch, shared
    weight-stage scheduling in the surrounding step, and no
    prefill-vs-decode step alternation.
  * the chunk walk per (row, tile) is context-bounded: it covers only
    cache positions the row's tokens IN THIS TILE can see
    (ceil((pos0 + last_local_token + 1) / span)), and sliding-window
    rows skip blocks wholly below the window.
  * int8 caches stream pool-native [N, Hkv, G, BS] grouped scale tiles
    and dequantize in VMEM via the shared expansion matmul
    (paged_attention.dequant_tile) — the unified grouped scale contract
    from BASELINE.md round 3.

Layouts: q [T, Hq_packed, D] (GQA head packing via the
kernel_io_for/pack_queries contract happens in the ops.attention
dispatcher), caches [N, Hkv, BS, D], block_tables [B, MB] int32,
q_len/pos0 [B] int32. Returns [T, Hq, D]; dead rows emit zeros.
Chip validation: scripts/validate_kernel_tpu.py ragged-* cases (queued
via scripts/tpu_supervisor.py; opt-in XLLM_RAGGED_ATTENTION_KERNEL=1
until PARITY OK per the repo convention).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic
from xllm_service_tpu.ops.pallas.paged_attention import dequant_tile

NEG_INF = -1e30


def _ragged_kernel(
    # scalar prefetch
    tile_start_ref,   # [NT] SMEM — first row overlapping each tile
    tile_cnt_ref,     # [NT] SMEM — rows overlapping each tile
    q_lo_ref,         # [B] SMEM — static segment offsets (flat tokens)
    q_len_ref,        # [B] SMEM — dynamic valid tokens per row
    pos0_ref,         # [B] SMEM — absolute position of first query token
    bt_ref,           # [B, MBp] SMEM block tables (padded to C multiple)
    # inputs
    q_ref,            # [1, 1, TQ*G, D] VMEM — one tile's query rows
    k_hbm,            # [N, Hkv, BS, D] HBM
    v_hbm,            # [N, Hkv, BS, D] HBM
    *rest,            # quantized: ks_hbm, vs_hbm [N, Hkv, G, BS] f32; then
    # o_ref + scratch (k_buf/v_buf [2, C*BS, D], sems; quantized adds
    # [2, C, G, BS] f32 scale bufs + ssems)
    block_size: int,
    chunk: int,
    tile_q: int,
    groups: int,
    scale: float,
    quantized: bool,
    scale_groups: int = 8,
    window: int = 0,
):
    if quantized:
        ks_hbm, vs_hbm, o_ref, k_buf, v_buf, sems, ks_buf, vs_buf, ssems = rest
    else:
        o_ref, k_buf, v_buf, sems = rest
        ks_hbm = vs_hbm = ks_buf = vs_buf = ssems = None
    t = pl.program_id(0)
    h = pl.program_id(1)
    span = chunk * block_size
    tile_lo = t * tile_q  # first flattened token index of this tile

    q = q_ref[0, 0]  # [TQ*G, D]
    Rp, D = q.shape
    # Flattened-token index of each q-tile row (rows are token-major,
    # G head-group rows per token).
    tok_local = jax.lax.broadcasted_iota(jnp.int32, (Rp, 1), 0) // groups

    def dmas(slot, c_idx, blk):
        off = c_idx * block_size
        out = [
            mosaic.async_copy(
                mosaic.checked_at(k_hbm, blk, h),
                mosaic.checked_at(k_buf, slot, pl.ds(off, block_size)),
                sems.at[slot, 0, c_idx],
            ),
            mosaic.async_copy(
                mosaic.checked_at(v_hbm, blk, h),
                mosaic.checked_at(v_buf, slot, pl.ds(off, block_size)),
                sems.at[slot, 1, c_idx],
            ),
        ]
        if quantized:
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(ks_hbm, blk, h),
                    mosaic.checked_at(ks_buf, slot, c_idx),
                    ssems.at[slot, 0, c_idx],
                )
            )
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(vs_hbm, blk, h),
                    mosaic.checked_at(vs_buf, slot, c_idx),
                    ssems.at[slot, 1, c_idx],
                )
            )
        return out

    def start_chunk(b, slot, c):
        for c_idx in range(chunk):  # static, small
            blk = bt_ref[b, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.start()

    def wait_chunk(b, slot, c):
        for c_idx in range(chunk):
            blk = bt_ref[b, c * chunk + c_idx]
            for d in dmas(slot, c_idx, blk):
                d.wait()

    def row_body(bi, carry):
        b = tile_start_ref[t] + bi
        lo = q_lo_ref[b]
        ln = q_len_ref[b]
        p0 = pos0_ref[b]
        # Overlap of row b's VALID tokens with this tile, in flat coords.
        s = jnp.maximum(lo, tile_lo)
        e = jnp.minimum(lo + ln, tile_lo + tile_q)
        # Context the overlap's LAST token sees: pos0 + (e-1-lo) + 1 cols.
        ctx = p0 + (e - lo)
        nc = jnp.where(e > s, pl.cdiv(ctx, span), 0)
        # Sliding window: the FIRST overlapping token's window start
        # bounds the chunk walk from below (later tokens see later
        # windows); blocks wholly below it never stream.
        c_lo = (
            jnp.maximum(p0 + (s - lo) - window + 1, 0) // span
            if window > 0 else 0
        )

        @pl.when(nc > c_lo)
        def _first():
            start_chunk(b, jax.lax.rem(c_lo, 2), c_lo)

        # Absolute position of each q-tile row FOR THIS ROW-ITERATION
        # (only rows owned by b keep their scores).
        row_pos = p0 + (tile_lo + tok_local - lo)
        owned = (tok_local >= s - tile_lo) & (tok_local < e - tile_lo)

        def chunk_body(c, carry):
            m_prev, l_prev, acc = carry
            slot = jax.lax.rem(c, 2)

            @pl.when(c + 1 < nc)
            def _prefetch():
                start_chunk(b, jax.lax.rem(c + 1, 2), c + 1)

            wait_chunk(b, slot, c)
            k_tile = k_buf[slot]
            if quantized:
                k_tile = dequant_tile(
                    k_tile, ks_buf[slot], chunk, block_size, scale_groups
                )
            scores = (
                jax.lax.dot_general(
                    q, k_tile,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [Rp, C*BS] f32
            col_pos = c * span + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1
            )
            keep = owned & (col_pos <= row_pos)
            if window > 0:
                keep &= col_pos > row_pos - window
            scores = jnp.where(keep, scores, NEG_INF)

            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            # Untouched rows (m == NEG_INF) keep alpha/p at 0 so their
            # accumulator stays 0; rows owned by EARLIER iterations see
            # all-NEG_INF scores here, making alpha 1 and p 0 — an exact
            # no-op on their finished state.
            alpha = jnp.where(
                m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - m_new)
            )
            pmat = jnp.where(
                m_new <= NEG_INF / 2, 0.0, jnp.exp(scores - m_new)
            )
            l_new = alpha * l_prev + jnp.sum(pmat, axis=-1, keepdims=True)
            if quantized:
                v_tile = dequant_tile(
                    v_buf[slot], vs_buf[slot], chunk, block_size,
                    scale_groups,
                )
                pv = jnp.dot(
                    pmat.astype(jnp.bfloat16), v_tile,
                    preferred_element_type=jnp.float32,
                )
            else:
                pv = jnp.dot(
                    pmat.astype(k_buf.dtype), v_buf[slot],
                    preferred_element_type=jnp.float32,
                )
            return m_new, l_new, acc * alpha + pv

        return jax.lax.fori_loop(c_lo, nc, chunk_body, carry)

    m0 = jnp.full((Rp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Rp, 1), jnp.float32)
    a0 = jnp.zeros((Rp, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(
        0, tile_cnt_ref[t], row_body, (m0, l0, a0)
    )
    o_ref[0, 0] = jnp.where(
        l > 0, acc / jnp.maximum(l, 1e-30), 0.0
    ).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _tile_row_ranges(seg_lens, tile_q: int, n_tiles: int):
    """Static per-tile (first_row, row_count) over the segment layout.
    Segments are contiguous and ordered, so overlapping rows form a
    contiguous range; tiles past the last token carry (0, 0)."""
    q_lo = []
    off = 0
    for s in seg_lens:
        q_lo.append(off)
        off += s
    starts, counts = [], []
    for t in range(n_tiles):
        lo_t, hi_t = t * tile_q, (t + 1) * tile_q
        rows = [
            b for b, s in enumerate(seg_lens)
            if q_lo[b] < hi_t and q_lo[b] + s > lo_t
        ]
        starts.append(rows[0] if rows else 0)
        counts.append(len(rows))
    return q_lo, starts, counts


@functools.partial(
    jax.jit,
    static_argnames=(
        "seg_lens", "scale", "interpret", "chunk", "tile_q", "window",
    ),
)
def ragged_paged_attention_kernel(
    q: jnp.ndarray,            # [T, Hq, D] — flattened ragged queries
    k_cache,                   # [N, Hkv, BS, D] plain array or PagedKV
    v_cache,
    block_tables: jnp.ndarray,  # [B, MB] int32
    q_len: jnp.ndarray,        # [B] int32 (dynamic; <= seg_lens[b])
    pos0: jnp.ndarray,         # [B] int32
    seg_lens: tuple,           # static per-row segment capacities
    scale: float,
    interpret: bool = False,
    chunk: int = 4,
    tile_q: int = 128,
    window: int = 0,
) -> jnp.ndarray:
    from xllm_service_tpu.ops import kv_cache as kvc

    k_cache = kvc.as_paged(k_cache)
    v_cache = kvc.as_paged(v_cache)
    quantized = k_cache.quantized
    k_data, v_data = k_cache.data, v_cache.data

    T, Hq, D = q.shape
    N, Hkv, BS, _ = k_data.shape
    B, MB = block_tables.shape
    assert sum(seg_lens) == T and len(seg_lens) == B, (
        f"seg_lens {seg_lens} inconsistent with q [T={T}] / tables [B={B}]"
    )
    G = Hq // Hkv
    TQ = max(8, min(tile_q, _round_up(T, 8)))
    Tp = _round_up(T, TQ)
    NT = Tp // TQ
    Rp = TQ * G  # q-tile rows; TQ % 8 == 0 keeps sublane tiling legal
    C = max(1, min(chunk, MB))

    q_lo, tile_start, tile_cnt = _tile_row_ranges(seg_lens, TQ, NT)

    qt = q
    if Tp != T:
        qt = jnp.pad(qt, ((0, Tp - T), (0, 0), (0, 0)))
    # [Tp, Hq, D] -> [Hkv, NT, TQ*G, D], rows token-major so row // G is
    # the tile-local token index.
    qt = qt.reshape(Tp, Hkv, G, D).transpose(1, 0, 2, 3)
    qt = qt.reshape(Hkv, NT, Rp, D)

    MBp = _round_up(MB, C)
    bt = block_tables.astype(jnp.int32)
    if MBp != MB:
        # Chunk-tail entries point at the reserved garbage block 0; their
        # columns are masked out by position anyway.
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, 1, Rp, D), lambda t, h, *_: (h, t, 0, 0)),
        hbm,
        hbm,
    ]
    inputs = [
        jnp.asarray(tile_start, jnp.int32),
        jnp.asarray(tile_cnt, jnp.int32),
        jnp.asarray(q_lo, jnp.int32),
        q_len.astype(jnp.int32),
        pos0.astype(jnp.int32),
        bt,
        qt, k_data, v_data,
    ]
    scratch = [
        pltpu.VMEM((2, C * BS, D), k_data.dtype),
        pltpu.VMEM((2, C * BS, D), v_data.dtype),
        pltpu.SemaphoreType.DMA((2, 2, C)),
    ]
    SG = k_cache.scale.shape[-2] if quantized else 8  # sub-channel groups
    kv_bytes_per_row = D * k_data.dtype.itemsize
    if quantized:
        in_specs += [hbm, hbm]
        # Pool-native [N, Hkv, G, BS] grouped plane (kv_cache.py) — no
        # per-call relayout, tile-legal on every tp shard.
        inputs += [
            k_cache.scale.astype(jnp.float32),
            v_cache.scale.astype(jnp.float32),
        ]
        scratch += [
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.VMEM((2, C, SG, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, 2, C)),
        ]
        kv_bytes_per_row += 4 * SG

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(NT, Hkv),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Rp, D), lambda t, h, *_: (h, t, 0, 0)),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _ragged_kernel, block_size=BS, chunk=C, tile_q=TQ, groups=G,
        scale=scale, quantized=quantized, scale_groups=SG, window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Hkv, NT, Rp, D), q.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel", "parallel"),
        ),
        cost_estimate=pl.CostEstimate(
            # Each row streams its context once per tile it spans.
            flops=4 * Tp * Hq * D * MB * BS // max(1, len(seg_lens)),
            bytes_accessed=(
                Tp * Hq * D * 4 + NT * MB * BS * Hkv * kv_bytes_per_row
            ),
            transcendentals=Tp * Hq * MB * BS,
        ),
        interpret=interpret,
    )(*inputs)
    # [Hkv, NT, TQ*G, D] -> [Tp, Hq, D] -> drop padding.
    out = out.reshape(Hkv, Tp, G, D).transpose(1, 0, 2, 3)
    return out.reshape(Tp, Hq, D)[:T]
