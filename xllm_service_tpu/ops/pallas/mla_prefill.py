"""Pallas TPU flash kernel for MLA (DeepSeek) chunked paged prefill.

Prefill sibling of ops/pallas/mla_attention.py (decode) — same latent
trick: the compressed cache row (kv_rank + rope_dim floats) is shared by
ALL heads, so one [TQ*Hq, C] x [C, CH*BS] matmul scores a whole query
tile against a chunk of latent blocks, and pv accumulates in LATENT
space ([.., kv_rank]); W_UV is applied by the caller once per output
token (absorbed form). The gather/blockwise fallback's weakness is the
same as decode's: XLA materializes the gathered context per layer.

Structure mirrors ops/pallas/flash_prefill.py: grid (P, NT) — no head
axis, heads ride as sublane rows — double-buffered block DMA bounded by
each tile's OWN context length, online softmax, causal + ragged masking
by absolute position.

Layouts: q_lat [P, Lpad, Hq, C] (chunk-relative), cache [N, 1, BS, C],
block_table [P, CB] int32, start_pos/true_len [P] int32. Returns
[P, Lpad, Hq, kv_rank]. Oracle: ops/attention.mla_prefill_blockwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic

NEG_INF = -1e30


def _mla_prefill_kernel(
    # scalar prefetch
    block_table_ref,  # [P, MBp] SMEM
    start_pos_ref,    # [P] SMEM
    true_len_ref,     # [P] SMEM
    # inputs
    q_ref,            # [1, 1, Rp, C] VMEM (one tile's TQ*Hq rows)
    c_hbm,            # [N, 1, BS, C] HBM — bf16 or int8
    *rest,            # quantized: cs_hbm [N, 1, G, BS] f32, then
    # output
    #   o_ref         # [1, 1, Rp, KVR] VMEM
    # scratch
    #   c_buf         # [2, CH*BS, C] VMEM (cache dtype)
    #   sems          # [2, CH]
    #   (quantized)   s_buf [2, CH, G, BS] f32 + ssems [2, CH]
    block_size: int,
    chunk: int,
    tile_q: int,
    heads: int,
    scale: float,
    kv_rank: int,
    quantized: bool = False,
    scale_groups: int = 1,
):
    if quantized:
        cs_hbm, o_ref, c_buf, sems, s_buf, ssems = rest
    else:
        o_ref, c_buf, sems = rest
        cs_hbm = s_buf = ssems = None
    p = pl.program_id(0)
    t = pl.program_id(1)
    start = start_pos_ref[p]
    n_valid = true_len_ref[p]
    span = chunk * block_size

    tile_lo = t * tile_q
    ctx = start + jnp.minimum(tile_lo + tile_q, n_valid)
    nc = jnp.where(tile_lo < n_valid, pl.cdiv(ctx, span), 0)

    def dmas(slot, c_idx, blk):
        out = [
            mosaic.async_copy(
                    mosaic.checked_at(c_hbm, blk, 0),
                    mosaic.checked_at(c_buf, slot, pl.ds(c_idx * block_size, block_size)),
                    sems.at[slot, c_idx],
                )
        ]
        if quantized:
            # Full-extent [G, BS] scale tile (blk on the untiled dim);
            # see mla_attention._mla_common for why.
            out.append(
                mosaic.async_copy(
                    mosaic.checked_at(cs_hbm, blk, 0),
                    mosaic.checked_at(s_buf, slot, c_idx),
                    ssems.at[slot, c_idx],
                )
            )
        return out

    def start_chunk(slot, c):
        for c_idx in range(chunk):
            for d in dmas(slot, c_idx, block_table_ref[p, c * chunk + c_idx]):
                d.start()

    def wait_chunk(slot, c):
        for c_idx in range(chunk):
            for d in dmas(slot, c_idx, block_table_ref[p, c * chunk + c_idx]):
                d.wait()

    @pl.when(nc > 0)
    def _first():
        start_chunk(0, 0)

    q = q_ref[0, 0]  # [Rp, C]
    Rp = q.shape[0]
    row_off = jax.lax.broadcasted_iota(jnp.int32, (Rp, 1), 0) // heads
    row_pos = start + tile_lo + row_off
    row_valid = tile_lo + row_off < n_valid

    def body(c, carry):
        m_prev, l_prev, acc = carry
        slot = jax.lax.rem(c, 2)

        @pl.when(c + 1 < nc)
        def _prefetch():
            start_chunk(jax.lax.rem(c + 1, 2), c + 1)

        wait_chunk(slot, c)
        tile = c_buf[slot]  # [CH*BS, C]
        if quantized:
            from xllm_service_tpu.ops.pallas.mla_attention import (
                _dequant_tile,
            )

            tile = _dequant_tile(
                tile, s_buf[slot], chunk, block_size, scale_groups
            )
        scores = (
            jax.lax.dot_general(
                q, tile,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )  # [Rp, CH*BS]
        col_pos = c * span + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1
        )
        keep = (col_pos <= row_pos) & row_valid
        scores = jnp.where(keep, scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.where(
            m_prev <= NEG_INF / 2, 0.0, jnp.exp(m_prev - m_new)
        )
        pmat = jnp.where(
            m_new <= NEG_INF / 2, 0.0, jnp.exp(scores - m_new)
        )
        l_new = alpha * l_prev + jnp.sum(pmat, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            pmat.astype(tile.dtype), tile[:, :kv_rank],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [Rp, KVR]
        return m_new, l_new, acc * alpha + pv

    m0 = jnp.full((Rp, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Rp, 1), jnp.float32)
    a0 = jnp.zeros((Rp, kv_rank), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, nc, body, (m0, l0, a0))
    o_ref[0, 0] = jnp.where(
        l > 0, acc / jnp.maximum(l, 1e-30), 0.0
    ).astype(o_ref.dtype)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(
    jax.jit, static_argnames=("scale", "kv_rank", "interpret", "chunk", "tile_q")
)
def mla_flash_prefill_kernel(
    q_lat: jnp.ndarray,        # [P, Lpad, Hq, C]
    c_cache,                   # [N, 1, BS, C] plain array or PagedKV
    block_table: jnp.ndarray,  # [P, MB] int32
    start_pos: jnp.ndarray,    # [P] int32
    true_len: jnp.ndarray,     # [P] int32
    scale: float,
    kv_rank: int,
    interpret: bool = False,
    chunk: int = 4,
    tile_q: int = 128,
) -> jnp.ndarray:
    from xllm_service_tpu.ops.pallas.mla_attention import _mla_common

    c_data, scales, G = _mla_common(c_cache)
    quantized = scales is not None
    c_cache = c_data
    P, Lpad, Hq, C = q_lat.shape
    N, _, BS, _ = c_cache.shape
    MB = block_table.shape[1]
    TQ = min(tile_q, _round_up(Lpad, 8))
    while (TQ * Hq) % 8:
        TQ += 1
    Lp = _round_up(Lpad, TQ)
    NT = Lp // TQ
    Rp = TQ * Hq
    CH = max(1, min(chunk, MB))

    qt = q_lat
    if Lp != Lpad:
        qt = jnp.pad(qt, ((0, 0), (0, Lp - Lpad), (0, 0), (0, 0)))
    # [P, Lp, Hq, C] -> [P, NT, TQ*Hq, C]: rows position-major so
    # row // Hq is the chunk-relative query offset within the tile.
    qt = qt.reshape(P, NT, Rp, C)

    MBp = _round_up(MB, CH)
    bt = block_table.astype(jnp.int32)
    if MBp != MB:
        bt = jnp.pad(bt, ((0, 0), (0, MBp - MB)))

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    in_specs = [
        pl.BlockSpec((1, 1, Rp, C), lambda p, t, bt, sp, tl: (p, t, 0, 0)),
        hbm,
    ]
    inputs = [
        bt, start_pos.astype(jnp.int32), true_len.astype(jnp.int32),
        qt, c_cache,
    ]
    scratch = [
        pltpu.VMEM((2, CH * BS, C), c_cache.dtype),
        pltpu.SemaphoreType.DMA((2, CH)),
    ]
    row_bytes = C * c_cache.dtype.itemsize
    if quantized:
        in_specs.append(hbm)
        inputs.append(scales)
        scratch += [
            pltpu.VMEM((2, CH, G, BS), jnp.float32),
            pltpu.SemaphoreType.DMA((2, CH)),
        ]
        row_bytes += 4 * G
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(P, NT),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, 1, Rp, kv_rank), lambda p, t, bt, sp, tl: (p, t, 0, 0)
        ),
        scratch_shapes=scratch,
    )
    kernel = functools.partial(
        _mla_prefill_kernel, block_size=BS, chunk=CH, tile_q=TQ, heads=Hq,
        scale=scale, kv_rank=kv_rank, quantized=quantized, scale_groups=G,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((P, NT, Rp, kv_rank), q_lat.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * P * Hq * (C + kv_rank) * Lp * MB * BS // max(NT, 1),
            bytes_accessed=(
                P * Lp * Hq * C * 4
                + P * NT * MB * BS * row_bytes
            ),
            transcendentals=P * Hq * Lp * MB * BS // max(NT, 1),
        ),
        interpret=interpret,
    )(*inputs)
    return out.reshape(P, Lp, Hq, kv_rank)[:, :Lpad]
