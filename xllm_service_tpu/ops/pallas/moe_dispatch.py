"""Pallas TPU grouped ragged MoE expert dispatch: ONE kernel launch over
variable-size per-expert token groups.

The MoE serving shape the xLLM Technical Report's engine (arxiv
2510.14686) is built around, with the PR-9 ragged-attention design DNA
(ISSUE 15 tentpole): router top-k produces X token groups of dynamic,
wildly unequal sizes; instead of X per-expert matmul launches (dispatch
overhead and dead launches for empty experts) or a dense all-experts
einsum (compute ∝ total params instead of ACTIVE params), one launch
walks the grouped token buffer tile by tile and streams only the expert
weights the live rows in each tile actually need.

Contract (shared with ops.moe.moe_blockwise, the CPU/parity oracle):

  * tokens ride GROUPED: xg [G, E] is the capacity-padded per-expert
    layout — expert e's tokens occupy rows [e*cap, e*cap + occ[e]), in
    router-assignment order; rows past occ[e] (and the padding tail
    past Xl*cap) are DEAD and emit zeros. `cap` is the STATIC per-group
    capacity (the seg_lens analog — group offsets e*cap are fixed at
    trace time), `occ` the dynamic occupancy (the q_len analog;
    occ[e] == 0 = empty expert). ops.moe builds this layout in-graph
    from the router output (scatter by expert*cap + rank).
  * weights ride pre-split on the F axis so every DMA offset is a
    LEADING-dim index (mosaic_rules rule 2): w_gate/w_up
    [Xl, NF, E, FT], w_down [Xl, NF, FT, E] with NF*FT == F. The
    wrapper relayouts from the model's [Xl, E, F]/[Xl, F, E] leaves;
    a production checkpoint loader can persist this layout and skip
    the per-call transpose.

Design (the ragged-attention kernel's structure with expert-weight DMA
in place of KV-page DMA):

  * grid = (NT,): one program per TT-row tile of the grouped buffer.
    Tiles freely CROSS group boundaries (cap need not be a TT
    multiple), so the launch count depends only on G, not on how the
    router skewed the groups.
  * per tile, the kernel loops over the experts overlapping it (the
    range is STATIC — group offsets are static — and rides scalar
    prefetch like the ragged kernel's tile_start/tile_cnt), and per
    expert streams that expert's weights HBM→VMEM through a 2-slot
    double buffer, one [E, FT]+[E, FT]+[FT, E] f-chunk per inner step
    (F-chunking keeps VMEM residency at 6·E·FT·itemsize regardless of
    F; E itself is not tiled — DeepSeek-V3-scale E needs an E-tile
    axis before chip validation, noted in docs/MOE.md).
  * the whole [TT, E] x [E, FT] gate/up matmuls are ONE MXU issue per
    chunk; rows not owned by the current expert (other groups, dead
    capacity tail) mask their activations to 0 before the down-proj
    accumulation, so the accumulator needs no per-expert state. A
    tile whose overlap with an expert's LIVE prefix is empty skips
    that expert's DMA and compute entirely — with a balanced router
    the streamed/computed work tracks occ (≈ T·K rows, the ACTIVE
    params), not X·cap.
  * TPU grid programs run sequentially per core, so serializing a
    tile's experts costs nothing vs per-expert launches — the fusion
    buys one launch, expert skipping at tile granularity, and weight
    DMA overlapped with the previous chunk's matmuls.

Following the repo's opt-in-until-chip-validated convention the kernel
is NEW silicon surface: XLLM_MOE_KERNEL=1 opts in (XLLM_MOE_INTERPRET=1
drives it in interpret mode on CPU for CI), queued as moe-* cases for
the next chip window (docs/KERNELS.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from xllm_service_tpu.ops.pallas import mosaic_rules as mosaic


def tile_rows(group_rows: int, tile_q: int = 128) -> int:
    """Static tile height over the grouped token buffer: TT rows per
    program, 8-row (sublane) aligned, capped at `tile_q`."""
    r = (group_rows + 7) // 8 * 8
    return max(8, min(tile_q, r))


def f_chunk(F: int, cap: int = 512) -> int:
    """Static F-axis chunk: the largest 128-multiple divisor of F that is
    <= cap — one double-buffered [E, FT] weight slice per inner step."""
    ft = min(F, cap)
    ft -= ft % 128
    while F % ft:
        ft -= 128
    return ft


def _tile_expert_ranges(n_tiles: int, tt: int, cap: int, n_experts: int):
    """Static per-tile (first_expert, expert_count): group offsets are
    e*cap, so the experts overlapping tile t form a contiguous static
    range; tiles wholly in the padding tail carry (0, 0)."""
    first, cnt = [], []
    for t in range(n_tiles):
        lo, hi = t * tt, (t + 1) * tt
        f = min(lo // cap, n_experts)
        c = max(0, min(-(-hi // cap), n_experts) - f)
        first.append(f if c else 0)
        cnt.append(c)
    return first, cnt


def _moe_kernel(
    # scalar prefetch
    occ_ref,        # [Xl] SMEM — dynamic live rows per expert group
    tfirst_ref,     # [NT] SMEM — first expert overlapping each tile
    tcnt_ref,       # [NT] SMEM — experts overlapping each tile
    # inputs
    x_ref,          # [TT, E] VMEM — one tile of grouped token rows
    wg_hbm,         # [Xl, NF, E, FT] HBM
    wu_hbm,         # [Xl, NF, E, FT] HBM
    wd_hbm,         # [Xl, NF, FT, E] HBM
    # outputs + scratch
    o_ref,          # [TT, E] VMEM
    wg_buf,         # [2, E, FT] VMEM
    wu_buf,         # [2, E, FT] VMEM
    wd_buf,         # [2, FT, E] VMEM
    sems,           # DMA sems [2, 3]
    *,
    cap: int,
    tt: int,
    n_f: int,
    act: str,
):
    # The ONE activation selector (ops/moe.py) — kernel, oracle, and
    # dense path must stay in lockstep on activation semantics.
    from xllm_service_tpu.ops.moe import _act_fn

    t = pl.program_id(0)
    x = x_ref[...]  # [TT, E]
    row0 = t * tt
    rows = row0 + jax.lax.broadcasted_iota(jnp.int32, (tt, 1), 0)
    activate = _act_fn(act)

    def dmas(slot, e, c):
        return [
            mosaic.async_copy(
                mosaic.checked_at(wg_hbm, e, c),
                mosaic.checked_at(wg_buf, slot),
                sems.at[slot, 0],
            ),
            mosaic.async_copy(
                mosaic.checked_at(wu_hbm, e, c),
                mosaic.checked_at(wu_buf, slot),
                sems.at[slot, 1],
            ),
            mosaic.async_copy(
                mosaic.checked_at(wd_hbm, e, c),
                mosaic.checked_at(wd_buf, slot),
                sems.at[slot, 2],
            ),
        ]

    def expert_body(bi, acc):
        e = tfirst_ref[t] + bi
        lo = e * cap
        # Overlap of the expert's LIVE prefix with this tile: empty →
        # the whole f-chunk walk (DMA included) is skipped, which is
        # what makes compute track occupancy instead of X*cap.
        s = jnp.maximum(lo, row0)
        en = jnp.minimum(lo + occ_ref[e], row0 + tt)
        nc = jnp.where(en > s, n_f, 0)

        @pl.when(nc > 0)
        def _first():
            for d in dmas(0, e, 0):
                d.start()

        owned = (rows >= s) & (rows < en)  # [TT, 1]

        def f_body(c, acc):
            slot = jax.lax.rem(c, 2)

            @pl.when(c + 1 < nc)
            def _prefetch():
                for d in dmas(jax.lax.rem(c + 1, 2), e, c + 1):
                    d.start()

            for d in dmas(slot, e, c):
                d.wait()
            gate = jax.lax.dot_general(
                x, wg_buf[slot],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [TT, FT] f32
            up = jax.lax.dot_general(
                x, wu_buf[slot],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            h = activate(gate) * up
            # Rows owned by OTHER experts (or dead) contribute exactly 0
            # to the accumulator — groups are disjoint, so each live row
            # is written by precisely one expert iteration.
            h = jnp.where(owned, h, 0.0)
            pv = jnp.dot(
                h.astype(wd_buf.dtype), wd_buf[slot],
                preferred_element_type=jnp.float32,
            )  # [TT, E] f32
            return acc + pv

        return jax.lax.fori_loop(0, nc, f_body, acc)

    acc0 = jnp.zeros((tt, x.shape[-1]), jnp.float32)
    acc = jax.lax.fori_loop(0, tcnt_ref[t], expert_body, acc0)
    o_ref[...] = acc.astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("cap", "act", "interpret", "tile_q", "f_cap"),
)
def moe_grouped_dispatch_kernel(
    xg: jnp.ndarray,   # [G, E] grouped token rows (G = Xl*cap padded to TT)
    occ: jnp.ndarray,  # [Xl] int32 — live rows per expert group (<= cap)
    w_gate: jnp.ndarray,  # [Xl, E, F]
    w_up: jnp.ndarray,    # [Xl, E, F]
    w_down: jnp.ndarray,  # [Xl, F, E]
    cap: int,
    act: str = "silu",
    interpret: bool = False,
    tile_q: int = 128,
    f_cap: int = 512,
) -> jnp.ndarray:
    """One grouped ragged expert dispatch. Returns og [G, E] in xg.dtype
    with dead rows zeroed; the caller scatter-combines per-slot outputs
    by router weight (ops.moe.grouped_moe)."""
    G, E = xg.shape
    Xl, _, F = w_gate.shape
    TT = tile_rows(Xl * cap, tile_q)
    assert G % TT == 0 and G >= Xl * cap, (
        f"grouped buffer [{G}] must cover Xl*cap={Xl * cap} rows padded "
        f"to the {TT}-row tile (ops.moe builds this layout)"
    )
    FT = f_chunk(F, f_cap)
    NF = F // FT
    NT = G // TT
    tfirst, tcnt = _tile_expert_ranges(NT, TT, cap, Xl)

    # Leading-dim F split (mosaic rule 2: DMA offsets ride only untiled
    # leading dims): w_gate/w_up pay one relayout transpose per call —
    # the production loader can persist this layout — w_down's split is
    # a free reshape.
    wg = w_gate.reshape(Xl, E, NF, FT).transpose(0, 2, 1, 3)
    wu = w_up.reshape(Xl, E, NF, FT).transpose(0, 2, 1, 3)
    wd = w_down.reshape(Xl, NF, FT, E)

    hbm = pl.BlockSpec(memory_space=mosaic.hbm_space())
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(NT,),
        in_specs=[
            pl.BlockSpec((TT, E), lambda t, *_: (t, 0)),
            hbm,
            hbm,
            hbm,
        ],
        out_specs=pl.BlockSpec((TT, E), lambda t, *_: (t, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, E, FT), wg.dtype),
            pltpu.VMEM((2, E, FT), wu.dtype),
            pltpu.VMEM((2, FT, E), wd.dtype),
            pltpu.SemaphoreType.DMA((2, 3)),
        ],
    )
    kernel = functools.partial(
        _moe_kernel, cap=cap, tt=TT, n_f=NF, act=act,
    )
    wbytes = wg.dtype.itemsize
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((G, E), xg.dtype),
        compiler_params=mosaic.compiler_params(
            dimension_semantics=("parallel",),
        ),
        cost_estimate=pl.CostEstimate(
            # Upper bound: every grouped row live (the tile walk skips
            # dead spans at runtime).
            flops=6 * G * E * F,
            bytes_accessed=(
                2 * G * E * xg.dtype.itemsize + 3 * Xl * E * F * wbytes
            ),
            transcendentals=G * F,
        ),
        interpret=interpret,
    )(
        occ.astype(jnp.int32),
        jnp.asarray(tfirst, jnp.int32),
        jnp.asarray(tcnt, jnp.int32),
        xg, wg, wu, wd,
    )
