"""Paged KV cache representation, including the int8-quantized variant.

Decode attention is HBM-bandwidth-bound: every step streams the whole live
context's K/V through the chip (SURVEY.md §7 hard part 1). Storing the
cache as int8 with one scale per (token-row, kv-head) halves that traffic
— the decisive lever on v5e where HBM BW (~819 GB/s), not MXU FLOPs, caps
decode throughput. The reference's engine-side analog is its KV-cache
quantization config (engine tier, absent submodule; service-visible
contract is only the block/hash layout, which is unchanged here: the
block-size and chained-hash contract hashes TOKEN IDS, not cache bytes).

Representation: a `PagedKV` NamedTuple so the cache flows through
`jax.lax.scan`/`jit`/donation as a pytree wherever a plain array did.

  * bf16 mode:  PagedKV(data=[..., N, Hkv, BS, D] bf16, scale=None)
  * int8 mode:  PagedKV(data=[..., N, Hkv, BS, D] int8,
                        scale=[..., N, Hkv, BS] f32)

Quantization is symmetric per row (one token's one head, D lanes):
scale = max|row| / 127, data = round(row / scale). Dequantized compute
stays bf16/f32; only storage and HBM transfer shrink.

Plain jnp.ndarray caches remain accepted everywhere (`as_paged`), so the
bf16 path and all existing callers/tests are untouched.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp


class PagedKV(NamedTuple):
    data: jnp.ndarray
    scale: Optional[jnp.ndarray] = None

    @property
    def quantized(self) -> bool:
        return self.scale is not None

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


CacheLike = Union[jnp.ndarray, PagedKV]


def as_paged(cache: CacheLike) -> PagedKV:
    return cache if isinstance(cache, PagedKV) else PagedKV(cache, None)


def raw(cache: CacheLike) -> jnp.ndarray:
    """The storage array (for shape/dtype introspection)."""
    return cache.data if isinstance(cache, PagedKV) else cache


def quantize_rows(rows: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """rows [..., D] -> (int8 [..., D], scale [...]) symmetric per-row."""
    amax = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(rows.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


def dequantize(data: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):
    """data int8 [..., D], scale [...] -> [..., D] in `dtype`."""
    return (data.astype(jnp.float32) * scale[..., None]).astype(dtype)


def set_rows(cache: CacheLike, data_index, scale_index, rows: jnp.ndarray):
    """Generic quantize-or-cast cache write: `rows` [..., D] land at
    `cache.data[data_index]` (and, when quantized, their per-row scales at
    `cache.scale[scale_index]`). The single place the write-side
    quantization branch lives — scatter_rows / PD import / SP scatter all
    route through here."""
    if isinstance(cache, PagedKV) and cache.quantized:
        q, s = quantize_rows(rows)
        return PagedKV(
            cache.data.at[data_index].set(q),
            cache.scale.at[scale_index].set(s),
        )
    if isinstance(cache, PagedKV):
        return PagedKV(
            cache.data.at[data_index].set(rows.astype(cache.data.dtype)),
            None,
        )
    return cache.at[data_index].set(rows.astype(cache.dtype))


def scatter_rows(
    cache: CacheLike,
    blk: jnp.ndarray,  # [T] int32 block ids (0 = garbage block)
    offset: jnp.ndarray,  # [T] int32 in-block offsets
    rows: jnp.ndarray,  # [T, Hkv, D] model-dtype K or V rows
) -> CacheLike:
    """Write per-token rows into cache slots [N, Hkv, BS, D] (one layer's
    cache — the layer axis is already sliced off by the caller's scan)."""
    return set_rows(
        cache,
        (blk, slice(None), offset, slice(None)),
        (blk, slice(None), offset),
        rows,
    )


def gather_block(cache: CacheLike, block_id, dtype=jnp.bfloat16):
    """One block [Hkv, BS, D] dequantized to `dtype` (blockwise prefill)."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize(cache.data[block_id], cache.scale[block_id], dtype)
    return raw(cache)[block_id].astype(dtype)


def gather_blocks(cache: CacheLike, block_table: jnp.ndarray, dtype=None):
    """Gather + dequantize blocks via a block table of any shape [...B];
    returns [...B, Hkv, BS, D]."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize(
            cache.data[block_table], cache.scale[block_table],
            dtype or jnp.bfloat16,
        )
    out = raw(cache)[block_table]
    return out if dtype is None else out.astype(dtype)


def alloc_cache(
    shape: Tuple[int, ...],  # [..., N, Hkv, BS, D]
    dtype,
    quantized: bool,
) -> PagedKV:
    if quantized:
        return PagedKV(
            jnp.zeros(shape, jnp.int8), jnp.zeros(shape[:-1], jnp.float32)
        )
    return PagedKV(jnp.zeros(shape, dtype), None)
