"""Paged KV cache representation, including the int8-quantized variant.

Decode attention is HBM-bandwidth-bound: every step streams the whole live
context's K/V through the chip (SURVEY.md §7 hard part 1). Storing the
cache as int8 with one scale per (token-row, kv-head) halves that traffic
— the decisive lever on v5e where HBM BW (~819 GB/s), not MXU FLOPs, caps
decode throughput. The reference's engine-side analog is its KV-cache
quantization config (engine tier, absent submodule; service-visible
contract is only the block/hash layout, which is unchanged here: the
block-size and chained-hash contract hashes TOKEN IDS, not cache bytes).

Representation: a `PagedKV` NamedTuple so the cache flows through
`jax.lax.scan`/`jit`/donation as a pytree wherever a plain array did.

  * bf16 mode:  PagedKV(data=[..., N, H, BS, D] bf16, scale=None)
  * int8 mode:  PagedKV(data=[..., N, H, BS, D] int8,
                        scale=[..., N, H, G, BS] f32), G % 8 == 0

ONE scale layout for both families: sub-channel grouped, G groups per
row on the SUBLANE axis with BS on lanes (GQA: H = Hkv kv-heads, G = 8
groups of D/8 lanes; MLA: H = 1, D = the lane-padded latent dim, G from
mla_scale_groups). The layout is dictated by real-hardware Mosaic DMA
rules (learned on chip, round 3): a DMA slice's shape must be a multiple
of the (8, 128) tile on the last two dims — even at full extent — and
dynamic offsets may ride only on untiled leading dims. [G, BS] per
(block, head) with G % 8 == 0 satisfies that on EVERY tp shard (a
per-head or head-padded plane would go sub-tile once tp slices Hkv below
8, which is exactly the llama tp=8 production layout); heads stay a
leading dim so the scale plane shards identically to the data
(parallel/sharding.kv_scale_sharding). The MLA latent dim C is itself
lane-padded to 128 by `ModelConfig.mla_cache_dim` for the same reason.

Quantization is symmetric per (row, group): scale = max|group| / 127,
data = round(group / scale). Sub-channel grouping also quantizes a
high-magnitude segment independently of its neighbors (ADVICE r2 for the
MLA concat(c_kv, k_pe) row; for GQA it just buys precision). Dequantized
compute stays bf16/f32; only storage and HBM transfer shrink.

Plain jnp.ndarray caches remain accepted everywhere (`as_paged`), so the
bf16 path and all existing callers/tests are untouched.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp


class PagedKV(NamedTuple):
    data: jnp.ndarray
    scale: Optional[jnp.ndarray] = None

    @property
    def quantized(self) -> bool:
        return self.scale is not None

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


CacheLike = Union[jnp.ndarray, PagedKV]


def _ceil8(x: int) -> int:
    return (x + 7) // 8 * 8


# Sub-channel groups per GQA cache row (head dims are 8-multiples, so 8
# groups of D/8 lanes always divide evenly and the [G, BS] scale tile is
# Mosaic-legal).
GQA_SCALE_GROUPS = 8


def kv_pack_factor(num_kv_heads: int, head_dim: int) -> int:
    """KV heads PACKED per cache row for head_dim < 128 models.

    Mosaic DMA slices need 128-multiple lane extents (chip finding,
    round 3), so a [BS, 64] per-(block, head) tile can never ride the
    Pallas kernels. Packing P = 128 // head_dim consecutive heads into
    one 128-lane row ([N, Hkv/P, BS, P*D]) makes every model with a
    dividing head_dim kernel-eligible: kernels see an ordinary D'=128
    cache; wrappers embed queries block-diagonally (zeros in the other
    heads' lanes keep scores exact) and slice outputs back. Returns 1
    (no packing) when head_dim >= 128, doesn't divide 128, or doesn't
    divide the head count."""
    if head_dim >= 128 or 128 % head_dim or num_kv_heads % (128 // head_dim):
        return 1
    return 128 // head_dim


def mla_scale_groups(
    kv_lora_rank: int, rope_dim: int, cache_dim: Optional[int] = None
) -> int:
    """Scale-group count for an int8 MLA latent cache row.

    Constraints: the group size must (a) divide kv_lora_rank so the
    latent/RoPE boundary falls on a group boundary (the two segments
    quantize independently — ADVICE r2), (b) divide the (lane-padded)
    cache_dim exactly, and (c) yield a group COUNT that is a multiple of
    8, because the groups live on the sublane axis of the pool's
    [..., G, BS] scale plane and Mosaic DMA requires 8-aligned sublane
    extents. Start from gcd(kvr, rope, 128) — a power of two — and halve
    until the count is 8-aligned (always terminates: cache_dim is a
    multiple of 128 when padded, and gsz=1 gives a 128-multiple count)."""
    dim = cache_dim if cache_dim is not None else kv_lora_rank + rope_dim
    gsz = math.gcd(math.gcd(kv_lora_rank, rope_dim), 128)
    while gsz > 1 and (dim % gsz or (dim // gsz) % 8):
        gsz //= 2
    return dim // gsz


def as_paged(cache: CacheLike) -> PagedKV:
    return cache if isinstance(cache, PagedKV) else PagedKV(cache, None)


def raw(cache: CacheLike) -> jnp.ndarray:
    """The storage array (for shape/dtype introspection)."""
    return cache.data if isinstance(cache, PagedKV) else cache


def scale_groups_of(cache: PagedKV) -> int:
    """Sub-channel group count of a quantized pool cache."""
    return cache.scale.shape[-2] if cache.scale is not None else 1


def quantize_rows(
    rows: jnp.ndarray, groups: int = 1
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """rows [..., D] -> (int8 [..., D], scale) symmetric per-row.

    groups=1: one scale per row (scale [...]).
    groups=S: sub-channel quantization — the D lanes split into S equal
    segments, each with its own scale (scale [..., S], groups LAST; pool
    planes store them with BS last — the write paths below relayout)."""
    f = rows.astype(jnp.float32)
    if groups > 1:
        g = f.reshape(*f.shape[:-1], groups, f.shape[-1] // groups)
        scale = jnp.maximum(jnp.max(jnp.abs(g), axis=-1), 1e-8) / 127.0
        q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
        return q.reshape(rows.shape).astype(jnp.int8), scale
    amax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(data: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):
    """Row-layout inverse of quantize_rows: data int8 [..., D], scale
    [...] or [..., S] (grouped, groups LAST) -> [..., D]. Grouping is
    inferred from rank: scale.ndim == data.ndim means the last scale axis
    is the per-row group count."""
    if scale.ndim == data.ndim:
        S = scale.shape[-1]
        g = data.astype(jnp.float32).reshape(
            *data.shape[:-1], S, data.shape[-1] // S
        )
        return (g * scale[..., None]).reshape(data.shape).astype(dtype)
    return (data.astype(jnp.float32) * scale[..., None]).astype(dtype)


def dequantize_pool(data: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):
    """Pool-LAYOUT dequant: data int8 [..., H, BS, D] with grouped scale
    [..., H, G, BS] — transpose to row-major groups-last and delegate."""
    return dequantize(data, jnp.swapaxes(scale, -1, -2), dtype)


def set_rows(
    cache: CacheLike,
    data_index,
    scale_index,
    rows: jnp.ndarray,
    mode: str = "token",
):
    """Generic quantize-or-cast cache write: `rows` [..., D] land at
    `cache.data[data_index]` (and, when quantized, their per-row scales at
    `cache.scale[scale_index]`). The single place the write-side
    quantization branch lives — scatter_rows / block import / SP scatter
    all route through here.

    `mode` tells set_rows how the scale slot is laid out so the quantized
    scale values (groups LAST, from quantize_rows) can be relayouted into
    the pool's tile-aligned [..., H, G, BS] planes:
      * "token": scale_index consumed the in-block (BS) position — the
        slot already trails with the group axis; values land as-is.
      * "block": scale_index addresses whole blocks — the slot keeps the
        pool's trailing [G, BS] dims, so the quantized [..., BS, G]
        values transpose.
    """
    if isinstance(cache, PagedKV) and cache.quantized:
        q, s = quantize_rows(rows, cache.scale.shape[-2])
        if mode == "block":
            s = jnp.swapaxes(s, -1, -2)  # [..., G, BS]
        return PagedKV(
            cache.data.at[data_index].set(q),
            cache.scale.at[scale_index].set(s),
        )
    if isinstance(cache, PagedKV):
        return PagedKV(
            cache.data.at[data_index].set(rows.astype(cache.data.dtype)),
            None,
        )
    return cache.at[data_index].set(rows.astype(cache.dtype))


def scatter_rows(
    cache: CacheLike,
    blk: jnp.ndarray,  # [T] int32 block ids (0 = garbage block)
    offset: jnp.ndarray,  # [T] int32 in-block offsets
    rows: jnp.ndarray,  # [T, Hkv, D] model-dtype K or V rows
) -> CacheLike:
    """Write per-token rows into cache slots [N, Hkv, BS, D] (one layer's
    cache — the layer axis is already sliced off by the caller's scan)."""
    return set_rows(
        cache,
        (blk, slice(None), offset, slice(None)),
        # Pool scales are [N, H, G, BS]: offset picks the BS lane, the
        # slices keep heads and groups -> slot [T, H, G], matching the
        # groups-last quantized values exactly.
        (blk, slice(None), slice(None), offset),
        rows,
        mode="token",
    )


def set_blocks(cache: CacheLike, ids: jnp.ndarray, blocks: jnp.ndarray):
    """Write whole blocks [..., P, heads, BS, D] at block ids along the N
    axis of a pooled cache [..., N, heads, BS, D] (leading layer dims
    untouched). Used by the PD/tier migration import path."""
    idx = (slice(None), ids)
    return set_rows(cache, idx, idx, blocks, mode="block")


def pack_rows(rows: jnp.ndarray, cache: "CacheLike") -> jnp.ndarray:
    """Relayout per-token rows [..., Hkv, D] to a cache's packed row shape
    [..., Hc, Dc] (consecutive heads concatenate on lanes — the inverse of
    unpack_rows). No-op for unpacked caches. The ONE place the write-side
    packing reshape lives."""
    hc = raw(cache).shape[-3]
    if hc == rows.shape[-2]:
        return rows
    return rows.reshape(*rows.shape[:-2], hc, -1)


def unpack_rows(x: jnp.ndarray, pack: int) -> jnp.ndarray:
    """Undo kv_pack_factor packing on a gathered cache slice
    [..., Hc, BS, Dc] -> [..., Hc*pack, BS, Dc/pack] (consecutive heads
    were concatenated on lanes, so head order is preserved)."""
    if pack == 1:
        return x
    *lead, hc, bs, dc = x.shape
    x = x.reshape(*lead, hc, bs, pack, dc // pack)
    x = jnp.moveaxis(x, -2, -3)
    return x.reshape(*lead, hc * pack, bs, dc // pack)


def quantize_pool(cache: jnp.ndarray, groups: int = GQA_SCALE_GROUPS) -> PagedKV:
    """Quantize a whole dense cache array [..., N, H, BS, D] into a
    pool-LAYOUT PagedKV ([..., N, H, G, BS] scales). Test/bench helper —
    production pools allocate zeroed via alloc_cache and quantize
    incrementally through set_rows."""
    if groups % 8 or cache.shape[-1] % groups:
        raise ValueError(
            f"quantize_pool: groups={groups} must be a multiple of 8 "
            f"dividing the row dim {cache.shape[-1]} (see alloc_cache)"
        )
    q, s = quantize_rows(cache, groups)
    return PagedKV(q, jnp.swapaxes(s, -1, -2))


def gather_block(cache: CacheLike, block_id, dtype=jnp.bfloat16):
    """One block [Hkv, BS, D] dequantized to `dtype` (blockwise prefill)."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize_pool(
            cache.data[block_id], cache.scale[block_id], dtype
        )
    return raw(cache)[block_id].astype(dtype)


def gather_blocks(cache: CacheLike, block_table: jnp.ndarray, dtype=None):
    """Gather + dequantize blocks via a block table of any shape [...B];
    returns [...B, Hkv, BS, D]."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize_pool(
            cache.data[block_table], cache.scale[block_table],
            dtype or jnp.bfloat16,
        )
    out = raw(cache)[block_table]
    return out if dtype is None else out.astype(dtype)


def alloc_cache(
    shape: Tuple[int, ...],  # [..., N, H, BS, D]
    dtype,
    quantized: bool,
    scale_groups: int = GQA_SCALE_GROUPS,
) -> PagedKV:
    if quantized:
        if scale_groups % 8 or shape[-1] % scale_groups:
            raise ValueError(
                f"scale_groups={scale_groups} must be a multiple of 8 "
                f"dividing the row dim {shape[-1]} (Mosaic sublane tiling"
                f" of the [..., G, BS] scale plane)"
            )
        # [..., N, H, G, BS] — groups on sublanes, BS on lanes.
        scale_shape = shape[:-2] + (scale_groups, shape[-2])
        return PagedKV(
            jnp.zeros(shape, jnp.int8), jnp.zeros(scale_shape, jnp.float32)
        )
    return PagedKV(jnp.zeros(shape, dtype), None)
