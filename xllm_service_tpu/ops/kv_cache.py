"""Paged KV cache representation, including the int8-quantized variant.

Decode attention is HBM-bandwidth-bound: every step streams the whole live
context's K/V through the chip (SURVEY.md §7 hard part 1). Storing the
cache as int8 with one scale per (token-row, kv-head) halves that traffic
— the decisive lever on v5e where HBM BW (~819 GB/s), not MXU FLOPs, caps
decode throughput. The reference's engine-side analog is its KV-cache
quantization config (engine tier, absent submodule; service-visible
contract is only the block/hash layout, which is unchanged here: the
block-size and chained-hash contract hashes TOKEN IDS, not cache bytes).

Representation: a `PagedKV` NamedTuple so the cache flows through
`jax.lax.scan`/`jit`/donation as a pytree wherever a plain array did.

  * bf16 mode:  PagedKV(data=[..., N, Hkv, BS, D] bf16, scale=None)
  * int8 mode:  PagedKV(data=[..., N, Hkv, BS, D] int8,
                        scale=[..., N, Hkv, BS] f32)

Quantization is symmetric per row (one token's one head, D lanes):
scale = max|row| / 127, data = round(row / scale). Dequantized compute
stays bf16/f32; only storage and HBM transfer shrink.

Plain jnp.ndarray caches remain accepted everywhere (`as_paged`), so the
bf16 path and all existing callers/tests are untouched.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp


class PagedKV(NamedTuple):
    data: jnp.ndarray
    scale: Optional[jnp.ndarray] = None

    @property
    def quantized(self) -> bool:
        return self.scale is not None

    @property
    def shape(self):
        return self.data.shape

    @property
    def dtype(self):
        return self.data.dtype


CacheLike = Union[jnp.ndarray, PagedKV]


def mla_scale_groups(kv_lora_rank: int, rope_dim: int) -> int:
    """Scale-group count for an int8 MLA latent cache row of
    kv_lora_rank + rope_dim lanes: group size gcd(kvr, rope) puts the
    latent/RoPE boundary on a group boundary (see quantize_rows)."""
    import math

    return (kv_lora_rank + rope_dim) // math.gcd(kv_lora_rank, rope_dim)


def as_paged(cache: CacheLike) -> PagedKV:
    return cache if isinstance(cache, PagedKV) else PagedKV(cache, None)


def raw(cache: CacheLike) -> jnp.ndarray:
    """The storage array (for shape/dtype introspection)."""
    return cache.data if isinstance(cache, PagedKV) else cache


def quantize_rows(
    rows: jnp.ndarray, groups: int = 1
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """rows [..., D] -> (int8 [..., D], scale) symmetric per-row.

    groups=1: one scale per row (scale [...]).
    groups=S: sub-channel quantization — the D lanes split into S equal
    segments, each with its own scale (scale [..., S]). Used for MLA latent
    caches, where one scale across concat(c_kv, k_pe) lets whichever
    segment has the smaller magnitude lose precision to the other; a group
    size dividing kv_lora_rank puts the latent/RoPE boundary on a group
    boundary so the segments quantize independently (ADVICE r2)."""
    f = rows.astype(jnp.float32)
    if groups > 1:
        g = f.reshape(*f.shape[:-1], groups, f.shape[-1] // groups)
        scale = jnp.maximum(jnp.max(jnp.abs(g), axis=-1), 1e-8) / 127.0
        q = jnp.clip(jnp.round(g / scale[..., None]), -127, 127)
        return q.reshape(rows.shape).astype(jnp.int8), scale
    amax = jnp.max(jnp.abs(f), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def dequantize(data: jnp.ndarray, scale: jnp.ndarray, dtype=jnp.bfloat16):
    """data int8 [..., D], scale [...] or [..., S] (grouped) -> [..., D].

    Grouping is inferred from rank: scale.ndim == data.ndim means the last
    scale axis is the per-row group count."""
    if scale.ndim == data.ndim:
        S = scale.shape[-1]
        g = data.astype(jnp.float32).reshape(
            *data.shape[:-1], S, data.shape[-1] // S
        )
        return (g * scale[..., None]).reshape(data.shape).astype(dtype)
    return (data.astype(jnp.float32) * scale[..., None]).astype(dtype)


def set_rows(cache: CacheLike, data_index, scale_index, rows: jnp.ndarray):
    """Generic quantize-or-cast cache write: `rows` [..., D] land at
    `cache.data[data_index]` (and, when quantized, their per-row scales at
    `cache.scale[scale_index]`). The single place the write-side
    quantization branch lives — scatter_rows / PD import / SP scatter all
    route through here."""
    if isinstance(cache, PagedKV) and cache.quantized:
        groups = (
            cache.scale.shape[-1]
            if cache.scale.ndim == cache.data.ndim
            else 1
        )
        q, s = quantize_rows(rows, groups)
        return PagedKV(
            cache.data.at[data_index].set(q),
            cache.scale.at[scale_index].set(s),
        )
    if isinstance(cache, PagedKV):
        return PagedKV(
            cache.data.at[data_index].set(rows.astype(cache.data.dtype)),
            None,
        )
    return cache.at[data_index].set(rows.astype(cache.dtype))


def scatter_rows(
    cache: CacheLike,
    blk: jnp.ndarray,  # [T] int32 block ids (0 = garbage block)
    offset: jnp.ndarray,  # [T] int32 in-block offsets
    rows: jnp.ndarray,  # [T, Hkv, D] model-dtype K or V rows
) -> CacheLike:
    """Write per-token rows into cache slots [N, Hkv, BS, D] (one layer's
    cache — the layer axis is already sliced off by the caller's scan)."""
    return set_rows(
        cache,
        (blk, slice(None), offset, slice(None)),
        (blk, slice(None), offset),
        rows,
    )


def gather_block(cache: CacheLike, block_id, dtype=jnp.bfloat16):
    """One block [Hkv, BS, D] dequantized to `dtype` (blockwise prefill)."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize(cache.data[block_id], cache.scale[block_id], dtype)
    return raw(cache)[block_id].astype(dtype)


def gather_blocks(cache: CacheLike, block_table: jnp.ndarray, dtype=None):
    """Gather + dequantize blocks via a block table of any shape [...B];
    returns [...B, Hkv, BS, D]."""
    if isinstance(cache, PagedKV) and cache.quantized:
        return dequantize(
            cache.data[block_table], cache.scale[block_table],
            dtype or jnp.bfloat16,
        )
    out = raw(cache)[block_table]
    return out if dtype is None else out.astype(dtype)


def alloc_cache(
    shape: Tuple[int, ...],  # [..., N, Hkv, BS, D]
    dtype,
    quantized: bool,
    scale_groups: int = 1,
) -> PagedKV:
    if quantized:
        scale_shape = (
            shape[:-1] + (scale_groups,) if scale_groups > 1 else shape[:-1]
        )
        return PagedKV(
            jnp.zeros(shape, jnp.int8), jnp.zeros(scale_shape, jnp.float32)
        )
    return PagedKV(jnp.zeros(shape, dtype), None)
