"""RMSNorm (engine-tier op; SURVEY.md §2.3). Computed in float32 for
stability, cast back to input dtype; XLA fuses this into adjacent ops."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf / jnp.sqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)
