"""Batched token sampling with per-request parameters.

Engine-tier op (reference delegates sampling to the absent CUDA engine;
logprob wire shape constrained by proto/xllm_rpc_service.proto:85-113).

All functions are jit-safe over a fixed batch R: every request carries its
own (temperature, top_k, top_p, greedy-flag, seed) so one compiled step
serves any mixture — no recompilation on batch composition changes.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@dataclass
class SamplingParams:
    """Host-side per-request sampling spec (OpenAI-compatible surface)."""

    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    # min_p (vLLM semantics): drop tokens whose probability is below
    # min_p * max-probability. 0 disables. Applied with top-k/top-p.
    min_p: float = 0.0
    seed: int = 0
    logprobs: bool = False
    top_logprobs: int = 0
    max_new_tokens: int = 512
    stop_token_ids: tuple = ()
    ignore_eos: bool = False
    # OpenAI penalties over GENERATED tokens (vLLM semantics — the prompt
    # is not penalized): presence subtracts a flat amount from every
    # already-sampled token's logit; frequency subtracts per occurrence.
    presence_penalty: float = 0.0
    frequency_penalty: float = 0.0
    # OpenAI logit_bias: ((token_id, bias), ...) pairs added to the
    # token's logit before filtering/sampling; bias in [-100, 100]
    # (-100 effectively bans, +100 effectively forces).
    logit_bias: tuple = ()

    @property
    def greedy(self) -> bool:
        return self.temperature <= 0.0


def apply_top_k_top_p(
    logits: jnp.ndarray, top_k: jnp.ndarray, top_p: jnp.ndarray,
    min_p: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Combined per-row top-k + nucleus + min-p filtering with ONE
    descending argsort (the sort over V dominates sampling cost at vocab
    ~128K). top_k<=0, top_p>=1, and min_p<=0 disable their respective
    filters; the argmax is always kept."""
    R, vocab = logits.shape
    order = jnp.argsort(logits, axis=-1)[:, ::-1]  # descending
    sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
    ranks = jnp.arange(vocab, dtype=jnp.int32)[None, :]

    k = jnp.where(top_k <= 0, vocab, jnp.minimum(top_k, vocab))
    keep_k = ranks < k[:, None]

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Token i is kept if the cumulative mass *before* it is < top_p.
    keep_p = (cum - probs) < top_p[:, None]

    keep_sorted = keep_k & keep_p
    if min_p is not None:
        # vLLM semantics: prob >= min_p * max-prob (column 0 after the
        # descending sort holds the max).
        floor = jnp.where(min_p > 0, min_p, 0.0)[:, None] * probs[:, :1]
        keep_sorted = keep_sorted & (probs >= floor)
    keep_sorted = keep_sorted.at[:, 0].set(True)
    keep = jnp.zeros_like(keep_sorted).at[jnp.arange(R)[:, None], order].set(
        keep_sorted
    )
    return jnp.where(keep, logits, NEG_INF)


def apply_penalties(
    logits: jnp.ndarray,  # [R, V] float32
    counts: jnp.ndarray,  # [R, V] int32 — generated-token occurrence counts
    presence: jnp.ndarray,  # [R] float32
    frequency: jnp.ndarray,  # [R] float32
) -> jnp.ndarray:
    """OpenAI presence/frequency penalties over generated tokens. The
    count update (scatter-add of the sampled token) lives with the caller
    so the counts array can be donated through the decode step. Skipped at
    runtime (lax.cond) when no live row has a penalty — the [R, V]
    elementwise pass is real HBM traffic at V~128K."""
    active = (presence != 0.0) | (frequency != 0.0)

    def apply(x):
        cf = counts.astype(jnp.float32)
        seen = (counts > 0).astype(jnp.float32)
        return x - presence[:, None] * seen - frequency[:, None] * cf

    return jax.lax.cond(jnp.any(active), apply, lambda x: x, logits)


def sample_tokens(
    logits: jnp.ndarray,  # [R, V] float32
    temperature: jnp.ndarray,  # [R] float32; <=0 means greedy
    top_k: jnp.ndarray,  # [R] int32; 0 disables
    top_p: jnp.ndarray,  # [R] float32; 1.0 disables
    step_keys: jnp.ndarray,  # [R, 2] uint32 PRNG keys (pre-folded per step)
    counts: jnp.ndarray | None = None,  # [R, V] int32 generated-token counts
    presence: jnp.ndarray | None = None,  # [R] float32
    frequency: jnp.ndarray | None = None,  # [R] float32
    bias_ids: jnp.ndarray | None = None,  # [R, K] int32 (pad: id 0, bias 0)
    bias_vals: jnp.ndarray | None = None,  # [R, K] float32
    allowed: jnp.ndarray | None = None,  # [R, V] bool (guided decoding)
    min_p: jnp.ndarray | None = None,  # [R] float32; 0 disables
):
    """Returns (token_ids [R], logprob_of_chosen [R], logprobs [R, V])."""
    logits = logits.astype(jnp.float32)
    if bias_ids is not None and bias_vals is not None:
        # OpenAI logit_bias: sparse per-request add BEFORE penalties /
        # filtering / softmax, so greedy, sampling, and reported logprobs
        # all see the biased distribution. Padding rows carry (0, 0.0) —
        # adding zero to token 0 is a no-op.
        R = logits.shape[0]
        logits = logits.at[
            jnp.arange(R, dtype=jnp.int32)[:, None], bias_ids
        ].add(bias_vals)
    if counts is not None and presence is not None and frequency is not None:
        logits = apply_penalties(logits, counts, presence, frequency)
    if allowed is not None:
        # Guided decoding (JSON mode): hard-mask disallowed tokens LAST so
        # no bias or penalty can resurrect them; reported logprobs are
        # over the allowed set.
        logits = jnp.where(allowed, logits, NEG_INF)
    logprobs_full = jax.nn.log_softmax(logits, axis=-1)

    greedy_ids = jnp.argmax(logits, axis=-1)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    # The argsort over V (~128K) dominates sampling cost; skip it at
    # runtime (lax.cond — real control flow on TPU) when NO live row has a
    # filter enabled: greedy rows and filters-off rows don't need it.
    vocab = logits.shape[-1]
    needs_filter = (temperature > 0) & (
        ((top_k > 0) & (top_k < vocab))
        | (top_p < 1.0)
        | ((min_p > 0) if min_p is not None else False)
    )
    scaled = jax.lax.cond(
        jnp.any(needs_filter),
        lambda x: apply_top_k_top_p(x, top_k, top_p, min_p),
        lambda x: x,
        scaled,
    )

    def sample_one(key, row):
        return jax.random.categorical(jax.random.wrap_key_data(key), row)

    sampled_ids = jax.vmap(sample_one)(step_keys, scaled)

    token_ids = jnp.where(temperature > 0, sampled_ids, greedy_ids).astype(jnp.int32)
    chosen_logprob = jnp.take_along_axis(
        logprobs_full, token_ids[:, None], axis=-1
    )[:, 0]
    return token_ids, chosen_logprob, logprobs_full


def speculative_sample(
    logits: jnp.ndarray,  # [R, S, V] — verify-pass logits, position-major
    drafts: jnp.ndarray,  # [R, S-1] int32 — proposed tokens d_1..d_k
    temperature: jnp.ndarray,  # [R]
    top_k: jnp.ndarray,  # [R]
    top_p: jnp.ndarray,  # [R]
    step_keys: jnp.ndarray,  # [R, S, 2] — per-position keys (step_base + j)
    limits: jnp.ndarray,  # [R] int32 — max tokens this row may emit (<= S)
    active: jnp.ndarray,  # [R] bool
    counts: jnp.ndarray | None = None,  # [R, V] int32 (donated by caller)
    presence: jnp.ndarray | None = None,  # [R]
    frequency: jnp.ndarray | None = None,  # [R]
    bias_ids: jnp.ndarray | None = None,  # [R, K]
    bias_vals: jnp.ndarray | None = None,  # [R, K]
    allowed: jnp.ndarray | None = None,  # [R, S, V] bool per-position masks
    min_p: jnp.ndarray | None = None,  # [R]
):
    """Speculative acceptance for point-mass (n-gram / prompt-lookup) drafts.

    Position j's logits condition on [x_0, d_1..d_j] (the verify pass fed
    the last accepted token then the drafts). Sample t_j ~ p_j with the SAME
    per-step key schedule the sequential decode path would use at step
    base+j, and keep emitting while t_j equals the draft. This is *exactly*
    sequential sampling, not an approximation: accepting d_j with
    probability p_j(d_j) and otherwise emitting a sample from
    p_j(x | x != d_j) is the same joint law as emitting t_j ~ p_j outright —
    the standard speculative rejection rule collapses to equality-coupling
    when the draft distribution is a point mass. Consequently the
    speculative engine reproduces the non-speculative token stream
    bit-for-bit under identical seeds (tests/test_speculative.py asserts
    this), while emitting up to S tokens per verify step.

    Penalty exactness: the scan threads `counts` through the positions, so
    each emitted token penalizes later positions inside the same verify
    step just as it would across sequential decode steps.

    Returns (tokens [R, S], logprobs [R, S], n_emit [R], counts').
    Rows emit their first n_emit tokens; the rest is garbage.
    """
    R, S, V = logits.shape
    logits = logits.astype(jnp.float32)
    # pad drafts with an impossible token so position S-1 never "accepts"
    drafts_p = jnp.concatenate(
        [drafts.astype(jnp.int32), jnp.full((R, 1), -1, jnp.int32)], axis=1
    )
    have_counts = counts is not None
    if not have_counts:
        counts = jnp.zeros((R, 1), jnp.int32)  # dummy carry

    have_mask = allowed is not None
    if not have_mask:
        allowed = jnp.zeros((R, S, 1), bool)  # dummy scan input

    def body(carry, xs):
        cnts, going = carry
        lg, keys_j, d_j, j, allow_j = xs
        tok, lp, _ = sample_tokens(
            lg, temperature, top_k, top_p, keys_j,
            counts=cnts if have_counts else None,
            presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=allow_j if have_mask else None,
            min_p=min_p,
        )
        emit = going & (j < limits)
        if have_counts:
            cnts = cnts.at[jnp.arange(R), tok].add(emit.astype(jnp.int32))
        going = emit & (tok == d_j)
        return (cnts, going), (tok, lp, emit)

    (counts, _), (toks, lps, emits) = jax.lax.scan(
        body,
        (counts, active),
        (
            jnp.swapaxes(logits, 0, 1),  # [S, R, V]
            jnp.swapaxes(step_keys, 0, 1),  # [S, R, 2]
            drafts_p.T,  # [S, R]
            jnp.arange(S, dtype=jnp.int32),
            jnp.swapaxes(allowed, 0, 1),  # [S, R, V] (or dummy)
        ),
    )
    n_emit = jnp.sum(emits.astype(jnp.int32), axis=0)  # [R]
    return toks.T, lps.T, n_emit, counts


def pack_logit_bias(rows, n_rows: int):
    """Pack per-row ((token_id, bias), ...) tuples into the sparse
    [n_rows, K] (ids, vals) arrays sample_tokens takes; K is pow2-bucketed
    to bound compile count, padding entries are (0, 0.0) — adding zero to
    token 0 is a no-op. Returns (None, None) when no row has bias."""
    import numpy as np

    if not any(rows):
        return None, None
    K = 1
    while K < max(len(r) for r in rows if r):
        K *= 2
    ids = np.zeros((n_rows, K), np.int32)
    vals = np.zeros((n_rows, K), np.float32)
    for i, r in enumerate(rows):
        for j, (tid, bv) in enumerate(r[:K] if r else ()):
            ids[i, j] = tid
            vals[i, j] = bv
    return ids, vals


def make_step_keys(base_seeds: jnp.ndarray, steps: jnp.ndarray) -> jnp.ndarray:
    """Per-request keys folded with the generation step index: [R] -> [R, 2].

    `steps` may be a scalar (all rows at the same step) or a [R] array
    (continuous-batching: every slot at its own step). This is the ONLY
    seed-folding definition — executor prefill and decode both call it, so
    prefill and decode RNG streams can never diverge (PD-disagg resume
    depends on that)."""

    def one(seed, st):
        k = jax.random.key(seed)
        k = jax.random.fold_in(k, st)
        return jax.random.key_data(k)

    steps = jnp.broadcast_to(jnp.asarray(steps, jnp.int32), base_seeds.shape)
    return jax.vmap(one)(base_seeds, steps)
