"""Grouped ragged MoE expert dispatch: routing-to-groups layout, the
CPU/parity oracle, expert-parallel (ep) shard_map wrapping, and the
XLLM_MOE_KERNEL dispatch decision.

The serving-tier counterpart of ops/pallas/moe_dispatch.py (ISSUE 15;
docs/MOE.md). The model layer (models/llama.py `_mlp_block`) hands the
router's top-k output here; this module owns everything below it:

  * **Group layout** — the ragged-attention metadata contract applied
    to experts: STATIC per-group capacity `cap` (group g's rows start
    at g*cap, fixed at trace time — the seg_lens analog) with DYNAMIC
    occupancy `occ[g] = min(assignments, cap)` (the q_len analog).
    Assignments are ranked in router order by a cumsum over the
    one-hot expert matrix; rank >= cap is a CAPACITY OVERFLOW — the
    slot contributes zero to its token (standard MoE capacity-drop
    semantics) and is counted for the obs instruments. The default
    capacity is LOSSLESS (cap = T: a group can never exceed the token
    count), so nothing drops unless XLLM_MOE_CAPACITY_FACTOR opts into
    a tighter buffer.
  * **ep dispatch** — under a declared expert-parallel shard context
    (runtime/executor.py sets it from the mesh, mirroring the PR-12
    attention tp context) the dispatch wraps in `shard_map` over `ep`:
    tokens and routing metadata replicate (the "token shuffle" is each
    shard selecting the slots its expert slice owns), each shard runs
    ONE grouped dispatch over its X/ep-expert slice, and the combine is
    a psum of per-slot outputs. Per-slot values are bit-identical to
    the single-device dispatch (fixed-shape matmuls; non-local slots
    contribute exact zeros), which is what lets the EP differential
    suite (tests/test_moe_engine.py) demand byte-identical token
    streams. GSPMD alone cannot partition the Pallas launch — the same
    silent-replication failure PR 12 fixed for attention — so
    XLLM_SHARDED_KERNELS=0 also drops the MoE kernel back to the
    oracle under plain GSPMD.
  * **Dispatch decision** — XLLM_MOE_KERNEL follows the repo's
    opt-in-until-chip-validated convention (=1 opt in, =0 force the
    oracle/dense, XLLM_MOE_INTERPRET=1 drives the kernel branch on CPU
    for CI); `moe_kernel_eligible` is the tile/lane gate
    (gqa_kernel_eligible's analog: E and F must be 128-lane multiples).

The DENSE all-experts einsum in models/llama.py `_mlp` stays the
default serving path — grouped dispatch is a different numeric regime
(different matmul shapes), so flipping it on changes streams vs dense;
within the grouped regime every engine mode and mesh size is
byte-stable, which the differential suite pins.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp


# ------------------------------------------------------------ hatches

def grouped_moe_enabled() -> bool:
    """Whether MoE blocks route through the grouped ragged dispatch
    instead of the dense all-experts einsum. Opt-in (serving default
    stays dense until moe-* chip cases validate); the interpret hook
    opts in on its own — it exists to DRIVE the grouped branch on CPU
    (the XLLM_RAGGED_INTERPRET convention). =0 always wins."""
    env = os.environ.get("XLLM_MOE_KERNEL")
    if env == "0":
        return False
    return env == "1" or moe_interpret()


def moe_interpret() -> bool:
    """CI hook: run the grouped Pallas kernel in interpret mode on CPU."""
    return os.environ.get("XLLM_MOE_INTERPRET") == "1"


def moe_kernel_eligible(E: int, F: int, on: bool) -> bool:
    """Tile/lane eligibility for the grouped Pallas kernel (the
    gqa_kernel_eligible analog): token rows carry E lanes, weight
    chunks FT lanes — both must be 128 multiples (mosaic_rules rule 1).
    `on` is the platform gate (_on_tpu() or interpret)."""
    return on and E % 128 == 0 and F % 128 == 0


def moe_capacity(T: int, X: int, K: int) -> int:
    """Static per-expert group capacity for a T-token dispatch. Default
    LOSSLESS (cap = T); XLLM_MOE_CAPACITY_FACTOR=f sizes the classic
    balanced-load buffer ceil(f * T*K/X) instead — overflow drops (and
    is counted by the obs instruments)."""
    f = os.environ.get("XLLM_MOE_CAPACITY_FACTOR")
    if not f:
        return T
    cap = int(math.ceil(float(f) * T * K / max(X, 1)))
    return max(1, min(T, cap))


def resolved_moe_dispatch(E: int, F: int) -> str:
    """The MoE dispatch the serving path would take RIGHT NOW for this
    geometry — what kernel_report()/bench report instead of the raw env
    var: "dense" (the all-experts einsum), "grouped" (the Pallas
    kernel), or "grouped-ref" (grouped semantics on the blockwise
    oracle — enabled but kernel-ineligible, e.g. CPU without the
    interpret hook)."""
    from xllm_service_tpu.ops.attention import _on_tpu

    if not grouped_moe_enabled():
        return (
            "dense (forced-off)"
            if os.environ.get("XLLM_MOE_KERNEL") == "0"
            else "dense"
        )
    if moe_kernel_eligible(E, F, _on_tpu() or moe_interpret()):
        return "grouped"
    return "grouped-ref"


# -------------------------------------------------- ep shard context
# Mirrors ops.attention's per-thread tp context: the executor declares
# its mesh before every jitted-step entry; the grouped dispatch wraps
# in shard_map over `ep` when the axis is real. Shares the PR-12
# XLLM_SHARDED_KERNELS escape hatch — with it off, ep>1 meshes serve
# the grouped ORACLE under plain GSPMD instead (correct, no per-shard
# launch).

_EP_TLS = threading.local()


def set_ep_context(mesh, axis: str = "ep") -> None:
    """Declare the mesh the current thread's MoE dispatches run under
    (None clears). Ignored for meshes without a >1 `axis` extent."""
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        _EP_TLS.ctx = (mesh, axis)
    else:
        _EP_TLS.ctx = None


def ep_context():
    """(mesh, axis) when per-shard MoE dispatch applies, else None."""
    from xllm_service_tpu.ops.attention import sharded_kernels_enabled

    ctx = getattr(_EP_TLS, "ctx", None)
    if ctx is None or not sharded_kernels_enabled():
        return None
    return ctx


# ----------------------------------------------------------- stats sink
# Expert-load / capacity-overflow instruments without touching the model
# step signatures OR the scan structure: grouped_moe runs inside every
# step family's layer scan, where a side-channel traced value would leak
# (UnexpectedTracerError) and an extra scan output would rewrite six
# model functions — so each grouped dispatch instead emits its
# (assignment counts, dropped, capacity rows) through an UNORDERED
# jax.debug.callback to a per-thread host sink the executor registers at
# every step entry (runtime/executor.py moe_stats). The callback is
# async (never blocks the device or the overlap pipeline), fires once
# per MoE layer per step only when the grouped dispatch is enabled, and
# is absent from the trace entirely when no sink is registered.

_STATS_TLS = threading.local()


def set_stats_sink(sink) -> None:
    """Register the calling thread's stats sink —
    `sink(counts: np.ndarray[X], dropped: int, cap_rows: int)`, called
    from JAX's callback thread once per grouped dispatch — or None to
    clear. Read at TRACE time (the jitted steps bake the sink in), the
    same lifetime as every other per-thread context here."""
    _STATS_TLS.sink = sink


def _record(counts: jnp.ndarray, dropped: jnp.ndarray, cap_rows: int):
    sink = getattr(_STATS_TLS, "sink", None)
    if sink is None:
        return

    def emit(c, d, sink=sink, rows=cap_rows):
        import numpy as np

        sink(np.asarray(c), int(d), rows)

    jax.debug.callback(emit, counts, dropped, ordered=False)


# --------------------------------------------------------- the oracle

def _act_fn(act: str):
    """Gated-MLP activation by config name — THE selector shared by the
    dense path (models/llama.py _act delegates), the blockwise oracle,
    and the Pallas kernel, so the three can never drift on activation
    semantics."""
    if act == "gelu_tanh":
        return lambda t: jax.nn.gelu(t, approximate=True)
    return jax.nn.silu


def moe_blockwise(
    xg: jnp.ndarray,     # [G, E] grouped token rows (kernel layout)
    occ: jnp.ndarray,    # [Xl] int32 live rows per group
    w_gate: jnp.ndarray,  # [Xl, E, F]
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,  # [Xl, F, E]
    cap: int,
    act: str = "silu",
) -> jnp.ndarray:
    """Blockwise oracle for the grouped-dispatch contract: one
    fixed-shape [cap, E] FFN per expert group via lax.scan, dead rows
    (rank >= occ, padding tail) zeroed. Exact; the CPU/parity reference
    for ops/pallas/moe_dispatch.py AND the serving path when the
    grouped dispatch is enabled but the kernel is ineligible. The
    per-expert shapes are mesh-size-independent, which is what keeps
    per-slot outputs bit-identical between ep shards and one device."""
    G, E = xg.shape
    Xl = w_gate.shape[0]
    activate = _act_fn(act)
    xe = xg[: Xl * cap].reshape(Xl, cap, E)
    ranks = jnp.arange(cap, dtype=jnp.int32)[:, None]  # [cap, 1]

    def body(_, inp):
        xrows, wg, wu, wd, oc = inp
        gate = jax.lax.dot_general(
            xrows, wg,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        up = jax.lax.dot_general(
            xrows, wu,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        h = activate(gate) * up
        h = jnp.where(ranks < oc, h, 0.0)
        out = jnp.dot(
            h.astype(wd.dtype), wd, preferred_element_type=jnp.float32,
        )
        return None, out.astype(xg.dtype)

    _, og = jax.lax.scan(
        body, None, (xe, w_gate, w_up, w_down, occ.astype(jnp.int32))
    )
    og = og.reshape(Xl * cap, E)
    if G > Xl * cap:
        og = jnp.concatenate(
            [og, jnp.zeros((G - Xl * cap, E), og.dtype)], axis=0
        )
    return og


# ------------------------------------------------------- the dispatch

def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _dispatch_local(
    x: jnp.ndarray,        # [T, E] token rows (replicated under ep)
    loc_e: jnp.ndarray,    # [S] int32 — slot expert id, LOCAL index
    rank: jnp.ndarray,     # [S] int32 — slot rank within its expert
    live: jnp.ndarray,     # [S] bool — local AND under capacity
    tok: jnp.ndarray,      # [S] int32 — slot token index
    counts_l: jnp.ndarray,  # [Xl] int32 — local per-expert assignments
    w_gate: jnp.ndarray,   # [Xl, E, F] local expert slice
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,
    cap: int,
    act: str,
    use_kernel: bool,
    interpret: bool,
) -> jnp.ndarray:
    """Grouped dispatch over ONE expert slice: build the capacity-padded
    group buffer, run the kernel (or oracle), gather per-slot outputs.
    Returns y_slots [S, E] f32 with dead slots exactly 0."""
    from xllm_service_tpu.ops.pallas.moe_dispatch import (
        moe_grouped_dispatch_kernel,
        tile_rows,
    )

    T, E = x.shape
    Xl = w_gate.shape[0]
    TT = tile_rows(Xl * cap)
    Gp = _round_up(Xl * cap, TT)
    occ = jnp.minimum(counts_l.astype(jnp.int32), cap)
    dst = jnp.where(live, loc_e * cap + rank, Gp)  # dead → garbage row
    xg = jnp.zeros((Gp + 1, E), x.dtype).at[dst].set(x[tok])
    if use_kernel:
        og = moe_grouped_dispatch_kernel(
            xg[:Gp], occ, w_gate, w_up, w_down, cap, act=act,
            interpret=interpret,
        )
    else:
        og = moe_blockwise(xg[:Gp], occ, w_gate, w_up, w_down, cap, act)
    og = jnp.concatenate([og, jnp.zeros((1, E), og.dtype)], axis=0)
    return og[dst].astype(jnp.float32)  # dead slots read the zero row


def grouped_moe(
    x: jnp.ndarray,        # [T, E]
    topi: jnp.ndarray,     # [T, K] int32 router top-k expert ids
    weights: jnp.ndarray,  # [T, K] f32 router combine weights
    w_gate: jnp.ndarray,   # [X, E, F]
    w_up: jnp.ndarray,
    w_down: jnp.ndarray,   # [X, F, E]
    act: str = "silu",
    cap: Optional[int] = None,
    use_kernel: Optional[bool] = None,
    interpret: Optional[bool] = None,
    row_mask: Optional[jnp.ndarray] = None,  # [T] bool; False = padding
) -> jnp.ndarray:
    """Routed-expert block via the grouped ragged dispatch: ONE launch
    per expert slice instead of X per-expert launches or the dense
    all-experts einsum. Returns y [T, E] in x.dtype (the shared-expert
    tail stays with the caller — it is dense and family-specific).

    `row_mask` marks the LIVE token rows: padding lanes and inactive
    decode slots (False) are excluded from routing — they neither count
    in the expert-load stats (a mostly-idle R-slot batch must not feed
    the master garbage hotness) nor consume group capacity (under
    XLLM_MOE_CAPACITY_FACTOR a padding row taking a capacity slot would
    displace a REAL token's expert contribution), and their output rows
    are exactly 0 (discarded downstream, like the dense path's garbage
    rows)."""
    T, K = topi.shape
    X, E, F = w_gate.shape
    if cap is None:
        cap = moe_capacity(T, X, K)
    cap = max(1, min(cap, T))
    interp = moe_interpret() if interpret is None else interpret
    if use_kernel is None:
        from xllm_service_tpu.ops.attention import _on_tpu

        use_kernel = moe_kernel_eligible(E, F, _on_tpu() or interp)
        if (
            use_kernel
            and getattr(_EP_TLS, "ctx", None) is not None
            and ep_context() is None
        ):
            # An ep mesh is declared but XLLM_SHARDED_KERNELS=0 dropped
            # the shard_map wrap: a pallas_call under plain GSPMD would
            # run replicated over gathered weights (the PR-12 failure
            # mode) — serve the partitionable oracle instead.
            use_kernel = False

    # Global slot metadata (replicated under ep so every shard ranks
    # identically): slot s = (token s//K, choice s%K). Dead rows (the
    # row_mask) zero out of the one-hot BEFORE ranking, so they hold no
    # rank, no capacity, and no stats.
    flat_e = topi.reshape(T * K).astype(jnp.int32)
    oh = (
        flat_e[:, None] == jnp.arange(X, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)  # [S, X]
    slot_ok = None
    if row_mask is not None:
        slot_ok = jnp.repeat(row_mask.reshape(T), K)
        oh = oh * slot_ok[:, None].astype(jnp.int32)
    counts = oh.sum(axis=0)  # [X]
    rank = jnp.take_along_axis(
        jnp.cumsum(oh, axis=0) - oh, flat_e[:, None], axis=1
    )[:, 0]
    live = rank < cap
    if slot_ok is not None:
        live = live & slot_ok
    dropped = jnp.sum(jnp.maximum(counts - cap, 0))
    _record(counts, dropped, X * cap)
    tok = jnp.arange(T * K, dtype=jnp.int32) // K

    ctx = ep_context()
    n_shards = ctx[0].shape[ctx[1]] if ctx is not None else 1
    if ctx is not None and n_shards > 1 and X % n_shards == 0:
        from jax.sharding import PartitionSpec as P
        from xllm_service_tpu.ops import collective_matmul as cm_ops

        # Trace-time hatch read (the jitted steps bake it in, like
        # every other kernel hatch here).
        overlap = cm_ops.overlap_collectives_enabled()
        mesh, axis = ctx
        Xl = X // n_shards

        def body(xb, fe, rk, lv, tk, cnts, wgb, wub, wdb):
            lo = jax.lax.axis_index(axis).astype(jnp.int32) * Xl
            local = (fe >= lo) & (fe < lo + Xl)
            counts_l = jax.lax.dynamic_slice(cnts, (lo,), (Xl,))
            y = _dispatch_local(
                xb, fe - lo, rk, lv & local, tk, counts_l,
                wgb, wub, wdb, cap, act, use_kernel, interp,
            )
            # The combine "shuffle": each slot's value lives on exactly
            # one shard (the rest contribute exact zeros), so the psum
            # reproduces the single-device per-slot bits. Under
            # XLLM_OVERLAP_COLLECTIVES the psum decomposes into the
            # ring reduce-scatter/all-gather schedule so the combine
            # pipelines under the dispatch compute — still bit-exact
            # (adding exact zeros commutes in every order).
            if overlap:
                return cm_ops.ring_all_reduce(y, axis, n_shards)
            return jax.lax.psum(y, axis)

        shard_map = (
            jax.shard_map if hasattr(jax, "shard_map")
            else __import__(
                "jax.experimental.shard_map", fromlist=["shard_map"]
            ).shard_map
        )
        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P())
            + (P(axis, None, None),) * 3,
            out_specs=P(),
            check_rep=False,
        )
        y_slots = fn(
            x, flat_e, rank, live, tok, counts, w_gate, w_up, w_down,
        )
    else:
        y_slots = _dispatch_local(
            x, flat_e, rank, live, tok, counts,
            w_gate, w_up, w_down, cap, act, use_kernel, interp,
        )

    y = jnp.sum(
        y_slots.reshape(T, K, E)
        * weights.astype(jnp.float32).reshape(T, K, 1),
        axis=1,
    )
    return y.astype(x.dtype)
