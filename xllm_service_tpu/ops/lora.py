"""Multi-LoRA application for batched serving.

Per-request low-rank adapters over one set of base weights (the
vLLM/punica-class serving feature; no reference analog — the engine tier
is an absent submodule there). TPU-first formulation: instead of
gathering each slot's adapter matrices (a [R, E, r] HBM gather per
projection per layer — hundreds of MB/step), compute the low-rank path
against ALL adapters and select per slot:

    xa    = einsum('...e, aer -> ...ar', x, A)     # [..., n_a, r]
    delta = einsum('...ar, aro -> ...ao', xa, B)   # [..., n_a, out]
    out  += take_along_axis(delta, idx)[..., 0, :] * scaling

Extra FLOPs scale with n_a * r — for n_a <= 16, r <= 32 this is < 1% of
the base matmul; HBM reads the stacked A/B once per layer (a few percent
of base weight traffic). XLA fuses the chain; no dynamic shapes, no
scatter/gather of weight matrices.

Adapter index 0 is the reserved BASE row (all zeros): base-model
requests ride the same compiled step with a guaranteed-zero delta.

Adapter leaves live INSIDE params["layers"] under "lora_<name>_a" /
"lora_<name>_b" keys with layer-major stacking [L, n_a, E, r] /
[L, n_a, r, out], so the existing scan/jit/sharding plumbing carries
them with zero signature changes; model code applies them when the keys
are present (static pytree structure — presence is a trace-time branch).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp


def apply(
    x: jnp.ndarray,          # [..., E]
    a: jnp.ndarray,          # [n_a, E, r]   (one layer's slice)
    b: jnp.ndarray,          # [n_a, r, out]
    idx: jnp.ndarray,        # [...] int32 — broadcastable to x's batch dims
    scaling: float | jnp.ndarray = 1.0,
) -> jnp.ndarray:
    """The LoRA delta for every row's own adapter. Returns [..., out]."""
    xa = jnp.einsum(
        "...e,aer->...ar", x.astype(a.dtype), a
    )  # [..., n_a, r]
    delta = jnp.einsum("...ar,aro->...ao", xa, b)  # [..., n_a, out]
    # idx may be a scalar (vmapped per-sequence paths) or per-row
    idx_b = jnp.broadcast_to(
        jnp.asarray(idx, jnp.int32), x.shape[:-1]
    )
    sel = jnp.take_along_axis(
        delta, idx_b[..., None, None], axis=-2
    )[..., 0, :]
    return (sel * scaling).astype(x.dtype)


def maybe_apply(
    lp: Dict[str, jnp.ndarray],
    name: str,
    x: jnp.ndarray,
    idx: Optional[jnp.ndarray],
    scaling,
) -> Optional[jnp.ndarray]:
    """The delta for projection `name` if this layer carries adapters for
    it (and a batch index was provided); None otherwise. Presence of the
    lora_* keys is static, so the no-adapter path traces to nothing."""
    a = lp.get(f"lora_{name}_a")
    if a is None or idx is None:
        return None
    return apply(x, a, lp[f"lora_{name}_b"], idx, scaling)
