"""Paged attention over a block-structured KV cache.

Engine-tier hot op (the reference's paged-attention CUDA kernel lives in the
absent submodule; the service-visible contract is only the 128-token block
size + chained hashing — SURVEY.md §2.3). Two implementations:

  * `paged_attention_gather` — pure-jnp reference: gathers each sequence's
    blocks via its block table and runs masked SDPA. Exact; used on CPU
    (tests) and as the correctness oracle for the Pallas kernel.
  * `ops/pallas/paged_attention.py` — TPU Pallas kernel that streams KV
    blocks HBM→VMEM per (sequence, kv-head) program with the block table in
    scalar memory. Selected on TPU via `ops.attention.paged_attention`.

Cache layout (one layer): k_cache, v_cache `[num_blocks, num_kv_heads,
block_size, head_dim]` — KV-head-major within a block so the Pallas kernel
DMAs a [block_size, head_dim] tile per (block, head) with TPU-legal tiling;
the KV-head axis shards over the `tp` mesh axis.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp

from xllm_service_tpu.ops import kv_cache as kvc

NEG_INF = -1e30


# ------------------------------------------------- sharded kernel dispatch
# Pallas kernels are opaque custom calls to XLA's GSPMD partitioner: under
# a tp>1 mesh it cannot partition them, so a kernel launched from inside
# the jitted step would silently run replicated over a gathered cache —
# exactly the degradation the per-shard tier exists to kill. The serving
# dispatchers below therefore wrap every kernel launch in `shard_map`
# over the tp axis when a shard context is declared: each shard runs ONE
# kernel over its own contiguous slice of query heads and KV heads
# (attention is head-independent, so no collectives are needed), the GQA
# packing/eligibility trio evaluates against the PER-SHARD cache
# geometry inside the mapped body, and the fused mixed/spec steps stay
# one-launch-per-shard. XLLM_SHARDED_KERNELS=0 is the escape hatch back
# to the pre-shard_map GSPMD behavior (docs/SHARDING.md).
#
# The context is per-thread (each engine thread serves one executor) and
# read at TRACE time — the same lifetime every other kernel hatch here
# has (the jitted steps bake the decision in at first trace).

_SHARD_TLS = threading.local()


def sharded_kernels_enabled() -> bool:
    import os

    return os.environ.get("XLLM_SHARDED_KERNELS") != "0"


def set_shard_context(mesh, axis: str = "tp") -> None:
    """Declare the mesh the current thread's kernel dispatches run under
    (runtime/executor.py sets it before every jitted step family so the
    trace captures the right mesh; None clears). Ignored for meshes
    without a >1 `axis` extent."""
    if mesh is not None and mesh.shape.get(axis, 1) > 1:
        _SHARD_TLS.ctx = (mesh, axis)
    else:
        _SHARD_TLS.ctx = None


def shard_context():
    """(mesh, axis) when per-shard kernel dispatch applies, else None."""
    ctx = getattr(_SHARD_TLS, "ctx", None)
    if ctx is None or not sharded_kernels_enabled():
        return None
    return ctx


def declared_shard_context():
    """The raw (mesh, axis) the executor declared for this thread,
    ignoring the XLLM_SHARDED_KERNELS gate — that hatch escapes KERNEL
    dispatch to GSPMD; consumers with their own hatch (the overlap
    collectives tier, ops/collective_matmul.py) still need the mesh."""
    return getattr(_SHARD_TLS, "ctx", None)


def _shard_map_fn():
    if hasattr(jax, "shard_map"):
        return jax.shard_map
    from jax.experimental.shard_map import shard_map

    return shard_map


def _cache_shard_spec(cache, axis: str):
    """shard_map spec pytree for a per-layer cache operand: data
    [N, Hc, BS, D] and int8 scale [N, Hc, G, BS] both carry the head
    axis at dim 1."""
    from jax.sharding import PartitionSpec as P

    spec = P(None, axis, None, None)
    if isinstance(cache, kvc.PagedKV):
        return kvc.PagedKV(spec, spec if cache.scale is not None else None)
    return spec


def _shardable(q: jnp.ndarray, k_cache, ctx) -> bool:
    """Whether this (query, cache) pair can shard over ctx's axis: the
    query heads and the per-shard cache geometry must divide evenly —
    gqa_kernel_eligible re-checks the cache side per shard."""
    if ctx is None:
        return False
    n = ctx[0].shape[ctx[1]]
    return q.shape[-2] % n == 0 and kvc.raw(k_cache).shape[-3] % n == 0


def _sharded_kernel_call(body, ctx, q_spec_ndim: int, q, k_cache, v_cache,
                         *rep_args):
    """Run `body(q, k, v, *rep_args)` once per tp shard via shard_map.

    `body` receives PER-SHARD operands (Hq/tp query heads, Hc/tp cache
    rows) and must do its own packing (kernel_io_for inside the body sees
    the per-shard geometry). Tables/lengths/positions replicate; the
    output's head axis is at `q_spec_ndim - 1` == ndim-2 of q."""
    from jax.sharding import PartitionSpec as P

    mesh, axis = ctx
    head_ax = q_spec_ndim - 2
    q_spec = P(*(
        axis if i == head_ax else None for i in range(q_spec_ndim)
    ))
    fn = _shard_map_fn()(
        body,
        mesh=mesh,
        in_specs=(
            q_spec,
            _cache_shard_spec(k_cache, axis),
            _cache_shard_spec(v_cache, axis),
        ) + (P(),) * len(rep_args),
        out_specs=q_spec,
        check_rep=False,
    )
    return fn(q, k_cache, v_cache, *rep_args)


def _pack_ratio(cache, q_head_dim: int) -> int:
    """Heads packed per cache row (kv_cache.kv_pack_factor layouts):
    1 for ordinary caches, cache_row_dim / head_dim for packed ones."""
    return kvc.raw(cache).shape[-1] // q_head_dim


def _pack_lanes(heads: int, pack: int, groups: int) -> jnp.ndarray:
    """[Hq, pack] one-hot of which packed lane-block each query head's
    kv head occupies (query head h -> kv head h // groups)."""
    i = (jnp.arange(heads, dtype=jnp.int32) // groups) % pack
    return jax.nn.one_hot(i, pack, dtype=jnp.float32)


def kernel_io_for(cache, q: jnp.ndarray):
    """(pack, kv_heads, packed_q) for a kernel call against `cache` —
    the one place the pack/derive trio lives (review r3)."""
    pack = _pack_ratio(cache, q.shape[-1])
    kv_heads = kvc.raw(cache).shape[-3] * pack
    return pack, kv_heads, pack_queries(q, pack, kv_heads)


def _packed_kernel_allowed(pack: int) -> bool:
    """Packed-pair shapes are a NEW on-chip shape class validated only in
    interpret mode so far; per the repo's opt-in-until-chip-validated
    convention they ride the kernels only under XLLM_PACKED_KV_KERNEL=1
    (scripts/validate_kernel_tpu.py carries the packed cases; flip the
    default once they report PARITY OK on silicon)."""
    import os

    return pack == 1 or os.environ.get("XLLM_PACKED_KV_KERNEL") == "1"


def pack_queries(q: jnp.ndarray, pack: int, kv_heads: int) -> jnp.ndarray:
    """Embed queries block-diagonally for a packed cache: [..., Hq, D] ->
    [..., Hq, pack*D] with head h's vector in its kv head's lane block and
    zeros elsewhere — zeros keep q·k scores exact against packed K rows,
    and the pv garbage lanes are discarded by unpack_outputs."""
    if pack == 1:
        return q
    *lead, hq, d = q.shape
    oh = _pack_lanes(hq, pack, hq // kv_heads).astype(q.dtype)
    return jnp.einsum("...hd,hp->...hpd", q, oh).reshape(*lead, hq, pack * d)


def unpack_outputs(o: jnp.ndarray, pack: int, kv_heads: int) -> jnp.ndarray:
    """Select each query head's own lane block from packed attention
    output: [..., Hq, pack*D] -> [..., Hq, D]."""
    if pack == 1:
        return o
    *lead, hq, dp = o.shape
    oh = _pack_lanes(hq, pack, hq // kv_heads).astype(o.dtype)
    o = o.reshape(*lead, hq, pack, dp // pack)
    return jnp.einsum("...hpd,hp->...hd", o, oh)


def gather_context(
    k_cache,  # [num_blocks, Hkv, block_size, D] (plain or PagedKV)
    v_cache,
    block_table: jnp.ndarray,  # [R, max_blocks] int32
    unpack: int = 1,
):
    """Gather each sequence's context as [R, max_blocks*block_size, Hkv, D].
    Quantized (int8) caches are dequantized after the gather — only the
    sequence's own blocks pay the dequant, not the whole pool. `unpack`
    undoes packed-pair rows (head_dim < 128 layouts) on the gathered
    slice only."""
    k_ctx = kvc.unpack_rows(kvc.gather_blocks(k_cache, block_table), unpack)
    v_ctx = kvc.unpack_rows(kvc.gather_blocks(v_cache, block_table), unpack)
    k_ctx = jnp.swapaxes(k_ctx, 2, 3)
    v_ctx = jnp.swapaxes(v_ctx, 2, 3)
    R, MB, BS, H, D = k_ctx.shape
    return k_ctx.reshape(R, MB * BS, H, D), v_ctx.reshape(R, MB * BS, H, D)


def _sdpa(
    q: jnp.ndarray,  # [R, Lq, Hq, D]
    k: jnp.ndarray,  # [R, Lk, Hkv, D]
    v: jnp.ndarray,  # [R, Lk, Hkv, D]
    mask: jnp.ndarray,  # [R, Lq, Lk] bool (True = attend)
    scale: float,
) -> jnp.ndarray:
    R, Lq, Hq, D = q.shape
    Hkv = k.shape[2]
    groups = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(R, Lq, Hkv, groups, D)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # [R, Hkv, groups, Lq, Lk]
    scores = jnp.einsum("rqhgd,rkhd->rhgqk", qf, kf) * scale
    scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("rhgqk,rkhd->rqhgd", probs, vf)
    return out.reshape(R, Lq, Hq, D).astype(q.dtype)


def paged_attention_gather(
    q: jnp.ndarray,  # [R, Hq, D] — one query token per sequence (decode)
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_table: jnp.ndarray,  # [R, max_blocks]
    seq_lens: jnp.ndarray,  # [R] context length INCLUDING current token
    scale: float,
    window: int = 0,
) -> jnp.ndarray:
    """Decode-step attention: each query attends to its first seq_lens cache
    rows — the LAST `window` of them when sliding-window attention is on
    (window > 0, HF semantics: positions [pos-window+1, pos]). Returns
    [R, Hq, D]."""
    k_ctx, v_ctx = gather_context(
        k_cache, v_cache, block_table,
        unpack=_pack_ratio(k_cache, q.shape[-1]),
    )
    Lk = k_ctx.shape[1]
    cols = jnp.arange(Lk, dtype=jnp.int32)[None, :]  # [1, Lk]
    mask = cols < seq_lens[:, None]  # [R, Lk]
    if window > 0:
        mask = mask & (cols >= seq_lens[:, None] - window)
    out = _sdpa(q[:, None], k_ctx, v_ctx, mask[:, None, :], scale)
    return out[:, 0]


def prefill_attention_gather(
    q: jnp.ndarray,  # [L, Hq, D] — chunk of new tokens for ONE sequence
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_table: jnp.ndarray,  # [max_blocks]
    start_pos: jnp.ndarray,  # scalar int32: tokens already in cache (prefix hit)
    true_len: jnp.ndarray,  # scalar int32: valid tokens in this chunk
    scale: float,
    window: int = 0,
) -> jnp.ndarray:
    """Chunked-prefill attention for one sequence: rows are chunk positions
    start_pos..start_pos+L, columns the sequence's cache rows (which already
    contain this chunk's K/V — caller scatters before attending). Causal;
    window > 0 restricts each row to its last `window` positions.
    Reference oracle — materializes the full [L, Lk] score matrix; the
    serving path uses prefill_attention_blockwise. Returns [L, Hq, D]."""
    k_ctx, v_ctx = gather_context(
        k_cache, v_cache, block_table[None],
        unpack=_pack_ratio(k_cache, q.shape[-1]),
    )
    L = q.shape[0]
    Lk = k_ctx.shape[1]
    rows = start_pos + jnp.arange(L, dtype=jnp.int32)  # absolute positions
    cols = jnp.arange(Lk, dtype=jnp.int32)
    causal = cols[None, :] <= rows[:, None]
    if window > 0:
        causal = causal & (cols[None, :] > rows[:, None] - window)
    valid_row = jnp.arange(L, dtype=jnp.int32) < true_len
    mask = causal & valid_row[:, None]
    out = _sdpa(q[None], k_ctx, v_ctx, mask[None], scale)
    return out[0]


def prefill_attention_blockwise(
    q: jnp.ndarray,  # [L, Hq, D]
    k_cache: jnp.ndarray,  # [num_blocks, Hkv, BS, D]
    v_cache: jnp.ndarray,
    block_table: jnp.ndarray,  # [CB] — sliced to the context bound
    start_pos: jnp.ndarray,  # scalar int32
    true_len: jnp.ndarray,  # scalar int32
    scale: float,
    window: int = 0,
) -> jnp.ndarray:
    """Flash-style prefill: lax.scan over KV blocks with online-softmax
    accumulation. Peak memory is O(L * BS) per step instead of the dense
    O(L * CB*BS) score matrix — a full 8K x 8K bf16 prefill's f32 scores
    (~8.5 GB for 32 heads) would not fit v5e HBM. Exact (log-sum-exp
    merge), parity-tested against prefill_attention_gather."""
    L, Hq, D = q.shape
    pack = _pack_ratio(k_cache, D)
    Hkv = kvc.raw(k_cache).shape[-3] * pack
    BS = kvc.raw(k_cache).shape[-2]
    G = Hq // Hkv
    qf = q.astype(jnp.float32).reshape(L, Hkv, G, D)
    rows = start_pos + jnp.arange(L, dtype=jnp.int32)  # absolute positions
    valid_row = jnp.arange(L, dtype=jnp.int32) < true_len

    # One [L, Hkv, G, *] layout throughout the carry.
    m0 = jnp.full((L, Hkv, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((L, Hkv, G, 1), jnp.float32)
    a0 = jnp.zeros((L, Hkv, G, D), jnp.float32)

    def body(carry, inputs):
        m_prev, l_prev, acc = carry
        blk_idx, blk_id = inputs
        k_blk = kvc.unpack_rows(
            kvc.gather_block(k_cache, blk_id, jnp.float32), pack
        )  # [Hkv, BS, D]
        v_blk = kvc.unpack_rows(
            kvc.gather_block(v_cache, blk_id, jnp.float32), pack
        )
        cols = blk_idx * BS + jnp.arange(BS, dtype=jnp.int32)
        scores = (
            jnp.einsum("qhgd,hkd->qhgk", qf, k_blk) * scale
        )  # [L, Hkv, G, BS]
        mask = (cols[None, :] <= rows[:, None]) & valid_row[:, None]
        if window > 0:
            mask = mask & (cols[None, :] > rows[:, None] - window)
        scores = jnp.where(mask[:, None, None], scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)  # >= m_prev by construction
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        p = jnp.exp(scores - m_new)
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("qhgk,hkd->qhgd", p, v_blk)
        return (m_new, l_new, acc), None

    CB = block_table.shape[0]
    (m, l, acc), _ = jax.lax.scan(
        body,
        (m0, l0, a0),
        (jnp.arange(CB, dtype=jnp.int32), block_table.astype(jnp.int32)),
    )
    out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(L, Hq, D).astype(q.dtype)



def _kernel_tile_ok(cache, lane_dim: int, on: bool) -> bool:
    """Mosaic tile-legality gate for every Pallas kernel path (chip
    findings, round 3): DMA slice dims must be tile MULTIPLES on the
    last two dims. `lane_dim` is the per-row lane width (head_dim D for
    GQA, the lane-padded latent dim C for MLA) and must be a 128
    multiple; BS sits on sublanes of the [BS, lane_dim] data slice (16
    bf16; int8's stricter bound is subsumed below); int8 additionally
    streams [G, BS] scale tiles with BS on LANES, so quantized caches
    need BS % 128."""
    BS = kvc.raw(cache).shape[-2]
    cq = isinstance(cache, kvc.PagedKV) and cache.quantized
    return (
        on
        and lane_dim % 128 == 0
        and (BS % 128 == 0 if cq else BS % 16 == 0)
    )


def _gqa_kernel_ok(k_cache, on: bool) -> bool:
    # Gate on the CACHE row width: packed head_dim<128 layouts carry
    # 128-lane rows and are kernel-eligible; unpacked narrow rows are not.
    return _kernel_tile_ok(k_cache, kvc.raw(k_cache).shape[-1], on)


def gqa_kernel_eligible(
    k_cache, q_head_dim: int, on: bool, shards: int = 1
) -> bool:
    """THE tile/lane/packing eligibility gate for every GQA Pallas path
    (decode, flash prefill, multi-query verify, ragged mixed) — one
    predicate instead of a per-dispatcher copy of the `_kernel_tile_ok`
    + `_packed_kernel_allowed` pair (ISSUE 9 satellite). `on` is the
    platform gate (_on_tpu() or interpret). `shards` > 1 evaluates the
    PER-SHARD cache geometry of the shard_map'd dispatch: the (possibly
    packed) cache-head axis must split evenly over tp or the per-shard
    kernel is declined (the caller then serves the GSPMD path; the
    config-level resolve_kv_packing fallback normally prevents this, but
    the gate must hold for hand-built caches too)."""
    if shards > 1 and kvc.raw(k_cache).shape[-3] % shards:
        return False
    return _gqa_kernel_ok(k_cache, on) and _packed_kernel_allowed(
        _pack_ratio(k_cache, q_head_dim)
    )


def _mla_kernel_ok(c_cache, on: bool) -> bool:
    return _kernel_tile_ok(c_cache, kvc.raw(c_cache).shape[-1], on)


def prefill_attention(
    q: jnp.ndarray,  # [P, Lpad, Hq, D] — the batched chunk's queries
    k_cache,
    v_cache,
    block_tables: jnp.ndarray,  # [P, CB]
    start_pos: jnp.ndarray,  # [P]
    true_len: jnp.ndarray,  # [P]
    scale: float,
    use_kernel: bool | None = None,
    interpret: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Batched chunked-prefill attention over the paged cache; Pallas
    flash kernel (ops/pallas/flash_prefill.py) on TPU, vmapped blockwise
    scan elsewhere. window > 0 = sliding-window attention (each position
    attends its last `window` positions; kernels also skip blocks wholly
    below the window). Same eligibility rules as the decode kernel (D a
    lane multiple; int8 additionally needs BS scale rows 128-wide); env
    override XLLM_PREFILL_ATTENTION_KERNEL=0/1 forces the path, and
    `interpret` lets CI drive the kernel branch on CPU."""
    import os

    # One eligibility predicate for BOTH Pallas paths (flash prefill and
    # the multi-query verify kernel). Under a shard context (tp>1) each
    # kernel launches per-shard via shard_map and the packing trio
    # (kernel_io_for) evaluates the per-shard cache geometry inside the
    # mapped body.
    # Packed-pair caches (head_dim < 128): queries embed block-diagonally
    # into the 128-lane rows; outputs slice back (pack_queries docstring).
    ctx = shard_context() if _shardable(q, k_cache, shard_context()) else None
    shards = ctx[0].shape[ctx[1]] if ctx is not None else 1
    kernel_ok = gqa_kernel_eligible(
        k_cache, q.shape[-1], _on_tpu() or interpret, shards=shards
    )

    # Speculative-verify shapes (a handful of query rows per sequence):
    # the multi-query decode kernel streams each KV row ONCE like a decode
    # step — the flash-prefill kernel would pad S~4 rows to a 128-row
    # query tile. Default ON for bf16 since the mq-bf16 case validated on
    # a real v5e chip (round 3, scripts/validate_kernel_tpu.py); int8
    # stays opt-in (XLLM_MQ_ATTENTION_KERNEL=1) until mq-int8 validates
    # on the grouped scale layout. =0 disables outright.
    S = q.shape[1]
    mq_env = os.environ.get("XLLM_MQ_ATTENTION_KERNEL")
    kq_mq = isinstance(k_cache, kvc.PagedKV) and k_cache.quantized
    if (
        use_kernel is None
        and S <= 8
        and kernel_ok
        and (mq_env == "1" if kq_mq else mq_env != "0")
        # The function-wide kill switch keeps covering EVERY kernel path
        # here: =0 forces the blockwise reference even for mq shapes.
        and os.environ.get("XLLM_PREFILL_ATTENTION_KERNEL") != "0"
    ):
        from xllm_service_tpu.ops.pallas.paged_attention import (
            multiquery_paged_attention_kernel,
        )

        seq_lens = jnp.where(true_len > 0, start_pos + 1, 0)

        def mq_body(qq, kk, vv, bt, sl):
            pack, kv_heads, q_packed = kernel_io_for(kk, qq)
            return unpack_outputs(
                multiquery_paged_attention_kernel(
                    q_packed, kk, vv, bt, sl, scale,
                    interpret=interpret, window=window,
                ),
                pack, kv_heads,
            )

        if ctx is not None:
            return _sharded_kernel_call(
                mq_body, ctx, 4, q, k_cache, v_cache, block_tables,
                seq_lens,
            )
        return mq_body(q, k_cache, v_cache, block_tables, seq_lens)

    env = os.environ.get("XLLM_PREFILL_ATTENTION_KERNEL")
    if use_kernel is None:
        use_kernel = (env != "0") if kernel_ok else (env == "1")
    if use_kernel:
        from xllm_service_tpu.ops.pallas.flash_prefill import (
            flash_prefill_kernel,
        )

        def flash_body(qq, kk, vv, bt, sp, tl):
            pack, kv_heads, q_packed = kernel_io_for(kk, qq)
            return unpack_outputs(
                flash_prefill_kernel(
                    q_packed, kk, vv, bt, sp, tl, scale,
                    interpret=interpret, window=window,
                ),
                pack, kv_heads,
            )

        if ctx is not None:
            return _sharded_kernel_call(
                flash_body, ctx, 4, q, k_cache, v_cache, block_tables,
                start_pos, true_len,
            )
        return flash_body(
            q, k_cache, v_cache, block_tables, start_pos, true_len
        )
    return jax.vmap(
        lambda qi, ti, sp, tl: prefill_attention_blockwise(
            qi, k_cache, v_cache, ti, sp, tl, scale, window=window
        )
    )(q, block_tables, start_pos, true_len)


# ----------------------------------------------------------------- MLA
# Multi-head Latent Attention (DeepSeek-V2/V3): the paged cache stores ONE
# compressed row per token — concat(c_kv [kv_rank], k_pe [rope_dim]) — and
# decode runs in ABSORBED form: queries are projected into the latent space
# (q_nope @ W_UK per head) so scores and the attention-weighted context are
# computed directly against cache rows, with the per-head V up-projection
# applied once to the [kv_rank] context vector. This is what makes the
# ~3.5x-smaller cache also a bandwidth win: no per-head K/V is ever
# materialized for cached tokens.


def mla_paged_attention_gather(
    q_lat: jnp.ndarray,  # [R, Hq, C] — concat(absorbed q_nope, roped q_pe)
    c_cache,  # [N, 1, BS, C] plain or PagedKV (C = kv_rank + rope_dim)
    block_table: jnp.ndarray,  # [R, MB] int32
    seq_lens: jnp.ndarray,  # [R] int32 (INCLUDING current token)
    scale: float,
    kv_rank: int,
) -> jnp.ndarray:
    """Decode-step MLA attention. Returns the attention-weighted LATENT
    context [R, Hq, kv_rank] (caller applies W_UV per head)."""
    ctx = kvc.gather_blocks(c_cache, block_table, jnp.float32)
    R, MB, _, BS, C = ctx.shape
    ctx = ctx.reshape(R, MB * BS, C)
    scores = (
        jnp.einsum("rhc,rtc->rht", q_lat.astype(jnp.float32), ctx) * scale
    )
    cols = jnp.arange(MB * BS, dtype=jnp.int32)[None, None, :]
    scores = jnp.where(cols < seq_lens[:, None, None], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("rht,rtk->rhk", p, ctx[:, :, :kv_rank])
    return out.astype(q_lat.dtype)


def mla_paged_attention(
    q_lat, c_cache, block_table, seq_lens, scale, kv_rank,
    use_kernel: bool | None = None, interpret: bool = False,
):
    """Decode MLA attention; Pallas kernel on TPU (opt-in via
    XLLM_MLA_ATTENTION_KERNEL=1 until validated on hardware — the GQA
    kernel went through the same gate in round 1), gather elsewhere.
    Int8 latent caches ride the kernel too (sub-channel scales stream in
    a separate plane and dequantize in VMEM); `interpret` lets CI drive
    the kernel branch on CPU."""
    import os

    if use_kernel is None:
        env = os.environ.get("XLLM_MLA_ATTENTION_KERNEL")
        use_kernel = (
            env == "1"
            and _mla_kernel_ok(c_cache, _on_tpu() or interpret)
        )
    if use_kernel:
        from xllm_service_tpu.ops.pallas.mla_attention import (
            mla_attention_kernel,
        )

        return mla_attention_kernel(
            q_lat, c_cache, block_table, seq_lens, scale, kv_rank,
            interpret=interpret,
        )
    return mla_paged_attention_gather(
        q_lat, c_cache, block_table, seq_lens, scale, kv_rank
    )


def mla_prefill_attention(
    q_lat: jnp.ndarray,  # [P, Lpad, Hq, C] — the batched chunk's queries
    c_cache,
    block_tables: jnp.ndarray,  # [P, CB]
    start_pos: jnp.ndarray,  # [P]
    true_len: jnp.ndarray,  # [P]
    scale: float,
    kv_rank: int,
    use_kernel: bool | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Batched MLA chunked-prefill attention; Pallas flash kernel
    (ops/pallas/mla_prefill.py) on TPU, vmapped blockwise scan elsewhere.
    Int8 latent caches ride both kernel branches (sub-channel scales
    stream in their own plane, VMEM dequant); XLLM_MLA_PREFILL_KERNEL=0/1
    forces the flash path, `interpret` drives the kernel branches in
    CI."""
    import os

    quantized = isinstance(c_cache, kvc.PagedKV) and c_cache.quantized
    # Speculative-verify shapes: the multi-query MLA decode kernel streams
    # each latent row once (see the GQA analog in prefill_attention);
    # int8 latent caches dequantize in VMEM inside the kernel.
    # Opt-in via XLLM_MQ_ATTENTION_KERNEL=1 until chip-validated.
    S = q_lat.shape[1]
    if (
        use_kernel is None
        and S <= 8
        and _mla_kernel_ok(c_cache, _on_tpu() or interpret)
        and os.environ.get("XLLM_MQ_ATTENTION_KERNEL") == "1"
    ):
        from xllm_service_tpu.ops.pallas.mla_attention import (
            mla_multiquery_attention_kernel,
        )

        seq_lens = jnp.where(true_len > 0, start_pos + 1, 0)
        return mla_multiquery_attention_kernel(
            q_lat, c_cache, block_tables, seq_lens, scale,
            kv_rank, interpret=interpret,
        )
    if use_kernel is None:
        env = os.environ.get("XLLM_MLA_PREFILL_KERNEL")
        # int8 stays OPT-IN (env == "1") until the mla-prefill-int8 chip
        # case validates — the convention for every unvalidated kernel
        # path; bf16 keeps its existing default.
        kernel_ok = (
            _mla_kernel_ok(c_cache, _on_tpu() or interpret)
            and not quantized
        )
        use_kernel = (env != "0") if kernel_ok else (env == "1")
    if use_kernel:
        from xllm_service_tpu.ops.pallas.mla_prefill import (
            mla_flash_prefill_kernel,
        )

        return mla_flash_prefill_kernel(
            q_lat, c_cache, block_tables, start_pos, true_len,
            scale, kv_rank, interpret=interpret,
        )
    return jax.vmap(
        lambda qi, ti, sp, tl: mla_prefill_blockwise(
            qi, c_cache, ti, sp, tl, scale, kv_rank
        )
    )(q_lat, block_tables, start_pos, true_len)


def mla_prefill_blockwise(
    q_lat: jnp.ndarray,  # [Lq, Hq, C] for ONE sequence's chunk
    c_cache,  # [N, 1, BS, C]
    block_table: jnp.ndarray,  # [CB] — sliced to the context bound
    start_pos: jnp.ndarray,  # scalar int32
    true_len: jnp.ndarray,  # scalar int32
    scale: float,
    kv_rank: int,
) -> jnp.ndarray:
    """Flash-style causal MLA prefill over latent blocks (online softmax,
    O(Lq * BS) peak score memory). Returns [Lq, Hq, kv_rank]."""
    Lq, Hq, C = q_lat.shape
    BS = kvc.raw(c_cache).shape[2]
    qf = q_lat.astype(jnp.float32)
    rows = start_pos + jnp.arange(Lq, dtype=jnp.int32)
    valid_row = jnp.arange(Lq, dtype=jnp.int32) < true_len

    m0 = jnp.full((Lq, Hq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Lq, Hq, 1), jnp.float32)
    a0 = jnp.zeros((Lq, Hq, kv_rank), jnp.float32)

    def body(carry, inputs):
        m_prev, l_prev, acc = carry
        blk_idx, blk_id = inputs
        blk = kvc.gather_block(c_cache, blk_id, jnp.float32)[0]  # [BS, C]
        cols = blk_idx * BS + jnp.arange(BS, dtype=jnp.int32)
        scores = jnp.einsum("qhc,kc->qhk", qf, blk) * scale  # [Lq, Hq, BS]
        mask = (cols[None, :] <= rows[:, None]) & valid_row[:, None]
        scores = jnp.where(mask[:, None, :], scores, NEG_INF)

        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        alpha = jnp.where(m_prev <= NEG_INF / 2, 0.0, alpha)
        p = jnp.exp(scores - m_new)
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, p)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("qhk,kc->qhc", p, blk[:, :kv_rank])
        return (m_new, l_new, acc), None

    CB = block_table.shape[0]
    (m, l, acc), _ = jax.lax.scan(
        body,
        (m0, l0, a0),
        (jnp.arange(CB, dtype=jnp.int32), block_table.astype(jnp.int32)),
    )
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q_lat.dtype)


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def paged_attention(
    q, k_cache, v_cache, block_table, seq_lens, scale,
    use_kernel: bool | None = None, window: int = 0,
    interpret: bool = False,
):
    """Decode paged attention; Pallas kernel on TPU, gather fallback elsewhere.

    The kernel is the DEFAULT on TPU since round 2: validated on a real v5e
    chip (scripts/validate_kernel_tpu.py — max |err| vs the gather oracle
    0.002 in bf16, 2.5-8x faster across llama-8B/70B-class decode shapes).
    Set XLLM_PAGED_ATTENTION_KERNEL=0 to force the gather path, =1 to force
    the kernel even where the default heuristics decline it. Under a
    declared shard context (set_shard_context; tp>1 meshes) the kernel
    launches per-shard through shard_map — one launch per tp shard over
    its own head slice — instead of degrading to a GSPMD-replicated
    custom call.

    head_dim < 128 models ride the kernel through the packed-pair cache
    layout (kv_cache.kv_pack_factor: a bare [BS, 64] block slice is below
    one 128-lane Mosaic tile — observed on-chip as a tpu.memref_slice
    verification failure — so P heads pack per 128-lane row and queries
    embed block-diagonally, see pack_queries)."""
    import os

    ctx = shard_context() if _shardable(q, k_cache, shard_context()) else None
    shards = ctx[0].shape[ctx[1]] if ctx is not None else 1
    env = os.environ.get("XLLM_PAGED_ATTENTION_KERNEL")
    if use_kernel is None:
        kernel_ok = gqa_kernel_eligible(
            k_cache, q.shape[-1], _on_tpu() or interpret, shards=shards
        )
        use_kernel = (env != "0") if kernel_ok else (env == "1")
    if use_kernel:
        try:
            from xllm_service_tpu.ops.pallas.paged_attention import (
                paged_attention_kernel,
            )
        except ImportError:
            use_kernel = False
        else:
            def body(qq, kk, vv, bt, sl):
                # Per-shard packing: kernel_io_for reads the (per-shard,
                # under shard_map) cache geometry.
                pack, kv_heads, q_packed = kernel_io_for(kk, qq)
                return unpack_outputs(
                    paged_attention_kernel(
                        q_packed, kk, vv, bt, sl, scale,
                        window=window, interpret=interpret,
                    ),
                    pack, kv_heads,
                )

            if ctx is not None:
                return _sharded_kernel_call(
                    body, ctx, 3, q, k_cache, v_cache, block_table,
                    seq_lens,
                )
            return body(q, k_cache, v_cache, block_table, seq_lens)
    return paged_attention_gather(
        q, k_cache, v_cache, block_table, seq_lens, scale, window=window
    )


# ------------------------------------------------ ragged mixed batches
# One attention call for a batch mixing chunked-prefill rows (arbitrary
# query length, prefix-aware start offsets) and decode rows (query length
# 1) over the same paged KV — the Ragged Paged Attention shape (arxiv
# 2604.15464; docs/KERNELS.md). The flattened-query contract:
#
#   q        [T, Hq, D]   — all rows' query tokens, segment-concatenated
#   seg_lens tuple (static) — per-row segment CAPACITY; sum == T. A row's
#                             tokens live at [q_lo[b], q_lo[b]+q_len[b])
#                             with q_lo = exclusive prefix sum of seg_lens
#   q_len    [B] int32    — valid tokens per row (<= seg_lens[b]; 0 = dead)
#   pos0     [B] int32    — ABSOLUTE position of the row's first query
#                             token (prefix hits / decode context offset)
#   tables   [B, CB]      — per-row block table
#
# Row b's token j sits at absolute position pos0[b]+j and attends cache
# positions 0..pos0[b]+j (causal; `window` restricts to the trailing
# window). Decode rows are seg_lens[b] == 1 with pos0 = seq_len - 1.


def ragged_attention_blockwise(
    q: jnp.ndarray,  # [T, Hq, D] flattened ragged queries
    k_cache,
    v_cache,
    block_tables: jnp.ndarray,  # [B, CB]
    q_len: jnp.ndarray,  # [B] int32
    pos0: jnp.ndarray,  # [B] int32
    seg_lens: tuple,  # static per-row segment capacities
    scale: float,
    window: int = 0,
) -> jnp.ndarray:
    """Blockwise oracle for the ragged mixed contract: each row runs the
    chunked-prefill blockwise scan (prefill_attention_blockwise handles
    query length 1 — a decode row — exactly like the decode gather, and
    arbitrary ragged lengths with prefix offsets). Exact; the CPU/parity
    reference for ops/pallas/ragged_paged_attention.py. Returns
    [T, Hq, D] with dead rows (q_len 0) zeroed."""
    outs = []
    off = 0
    for b, seg in enumerate(seg_lens):
        out_b = prefill_attention_blockwise(
            q[off:off + seg], k_cache, v_cache, block_tables[b],
            pos0[b], q_len[b], scale, window=window,
        )
        # Blockwise emits acc/l with l=0 rows zeroed already; mask the
        # padded tail explicitly so dead segments are deterministic.
        valid = (
            jnp.arange(seg, dtype=jnp.int32)[:, None, None] < q_len[b]
        )
        outs.append(jnp.where(valid, out_b, 0).astype(q.dtype))
        off += seg
    return jnp.concatenate(outs, axis=0)


def ragged_kernel_enabled(
    k_cache, q_head_dim: int, use_kernel: bool | None = None,
    interpret: bool = False, shards: int = 1,
) -> bool:
    """Dispatch decision for the ragged mixed kernel. Follows the repo's
    opt-in-until-chip-validated convention: the kernel is NEW silicon
    surface (queued in scripts/validate_kernel_tpu.py as ragged-*), so
    the default is OFF even on TPU until parity lands —
    XLLM_RAGGED_ATTENTION_KERNEL=1 opts in, =0 forces the reference
    path, and `interpret` (the XLLM_RAGGED_INTERPRET CI hook) opts in
    on its own — the hook exists to DRIVE the kernel branch on CPU, so
    it must select it, not merely flavor it (=0 still wins).
    Tile/lane/packing eligibility via the shared gate."""
    import os

    if use_kernel is not None:
        return use_kernel and gqa_kernel_eligible(
            k_cache, q_head_dim, _on_tpu() or interpret, shards=shards
        )
    env = os.environ.get("XLLM_RAGGED_ATTENTION_KERNEL")
    if env == "0":
        return False
    return (env == "1" or interpret) and gqa_kernel_eligible(
        k_cache, q_head_dim, _on_tpu() or interpret, shards=shards
    )


def ragged_paged_attention(
    q: jnp.ndarray,  # [T, Hq, D]
    k_cache,
    v_cache,
    block_tables: jnp.ndarray,  # [B, CB]
    q_len: jnp.ndarray,  # [B]
    pos0: jnp.ndarray,  # [B]
    seg_lens: tuple,
    scale: float,
    use_kernel: bool | None = None,
    interpret: bool = False,
    window: int = 0,
) -> jnp.ndarray:
    """Ragged mixed-batch paged attention: ONE Pallas dispatch over
    prefill + decode rows when the kernel is enabled
    (ragged_kernel_enabled), blockwise oracle otherwise — ONE dispatch
    PER TP SHARD under a shard context (the fused mixed/spec engine
    steps stay one-launch-per-shard on multi-chip meshes). GQA head
    packing rides the kernel_io_for/pack_queries contract like every
    other GQA kernel path; int8 caches stream pool-native grouped
    scales."""
    ctx = shard_context() if _shardable(q, k_cache, shard_context()) else None
    shards = ctx[0].shape[ctx[1]] if ctx is not None else 1
    if ragged_kernel_enabled(
        k_cache, q.shape[-1], use_kernel, interpret, shards=shards
    ):
        from xllm_service_tpu.ops.pallas.ragged_paged_attention import (
            ragged_paged_attention_kernel,
        )

        def body(qq, kk, vv, bt, ql, p0):
            pack, kv_heads, q_packed = kernel_io_for(kk, qq)
            return unpack_outputs(
                ragged_paged_attention_kernel(
                    q_packed, kk, vv, bt, ql, p0, seg_lens, scale,
                    interpret=interpret, window=window,
                ),
                pack, kv_heads,
            )

        if ctx is not None:
            return _sharded_kernel_call(
                body, ctx, 3, q, k_cache, v_cache, block_tables,
                q_len, pos0,
            )
        return body(q, k_cache, v_cache, block_tables, q_len, pos0)
    return ragged_attention_blockwise(
        q, k_cache, v_cache, block_tables, q_len, pos0, seg_lens, scale,
        window=window,
    )


def mixed_attention(
    q_dec: jnp.ndarray,  # [R, Hq, D] — decode slots (some inactive)
    q_pf: jnp.ndarray,  # [P, Lpad, Hq, D] — prefill chunk rows
    k_cache,
    v_cache,
    dec_tables: jnp.ndarray,  # [R, CBd]
    dec_seq_lens: jnp.ndarray,  # [R] context INCLUDING this token; 0 = off
    pf_tables: jnp.ndarray,  # [P, CBp]
    pf_start: jnp.ndarray,  # [P]
    pf_len: jnp.ndarray,  # [P]
    scale: float,
    use_ragged: bool | None = None,
    interpret: bool = False,
    window: int = 0,
):
    """Attention for one MIXED engine step (models.llama.mixed_step):
    decode slots and chunked-prefill rows against the same paged KV.

    Ragged kernel on: the whole batch flattens into ONE Pallas dispatch
    (seg_lens = R decode singletons + P Lpad segments). Otherwise the
    reference path runs each half through its own serving dispatcher —
    the Pallas decode kernel + flash prefill on TPU, gather + blockwise
    on CPU — so mixed-step outputs match the split engine's byte for
    byte while still fusing the rest of the step into one dispatch.
    The halves may carry different context-bucket table widths (the
    executor buckets each exactly like its split program); the ragged
    flatten pads the narrower table with garbage-block-0 entries, which
    the kernel's context bound never walks."""
    R = q_dec.shape[0]
    P, Lpad = q_pf.shape[0], q_pf.shape[1]
    if ragged_kernel_enabled(
        k_cache, q_dec.shape[-1], use_ragged, interpret
    ):
        seg_lens = (1,) * R + (Lpad,) * P
        q_flat = jnp.concatenate(
            [q_dec, q_pf.reshape(P * Lpad, *q_pf.shape[2:])], axis=0
        )
        CB = max(dec_tables.shape[1], pf_tables.shape[1])
        dt = jnp.pad(dec_tables, ((0, 0), (0, CB - dec_tables.shape[1])))
        pt = jnp.pad(pf_tables, ((0, 0), (0, CB - pf_tables.shape[1])))
        tables = jnp.concatenate([dt, pt], axis=0)
        q_len = jnp.concatenate(
            [jnp.minimum(dec_seq_lens, 1), pf_len]
        ).astype(jnp.int32)
        pos0 = jnp.concatenate(
            [jnp.maximum(dec_seq_lens - 1, 0), pf_start]
        ).astype(jnp.int32)
        out = ragged_paged_attention(
            q_flat, k_cache, v_cache, tables, q_len, pos0, seg_lens,
            scale, use_kernel=True, interpret=interpret, window=window,
        )
        return out[:R], out[R:].reshape(q_pf.shape)
    # Reference pair: EXACTLY the split engine's dispatchers. interpret
    # is deliberately NOT forwarded — it is the ragged-branch CI hook,
    # and leaking it here would flip the prefill half onto the
    # interpret-mode flash kernel while split-step engines run
    # blockwise, breaking the mixed ≡ split byte-parity contract.
    dec_out = paged_attention(
        q_dec, k_cache, v_cache, dec_tables, dec_seq_lens, scale,
        window=window,
    )
    pf_out = prefill_attention(
        q_pf, k_cache, v_cache, pf_tables, pf_start, pf_len, scale,
        window=window,
    )
    return dec_out, pf_out


def mixed_prefill_attention(
    q_a: jnp.ndarray,  # [A, La, Hq, D] — speculative verify rows (q_len<=La)
    q_b: jnp.ndarray,  # [B, Lb, Hq, D] — chunked-prefill rows
    k_cache,
    v_cache,
    a_tables: jnp.ndarray,  # [A, CBa]
    a_start: jnp.ndarray,  # [A]
    a_len: jnp.ndarray,  # [A] (0 = inactive row)
    b_tables: jnp.ndarray,  # [B, CBb]
    b_start: jnp.ndarray,  # [B]
    b_len: jnp.ndarray,  # [B]
    scale: float,
    use_ragged: bool | None = None,
    interpret: bool = False,
    window: int = 0,
):
    """Attention for one fused speculative MIXED step
    (models.llama.mixed_verify_step): TWO prefill-shaped halves — the
    multi-query verify rows [A, S] and the chunked-prefill rows
    [B, Lpad] — against the same paged KV.

    Ragged kernel on: the whole heterogeneous batch flattens into ONE
    Pallas dispatch (seg_lens = A S-segments + B Lpad-segments — a
    verify row is just a ragged row with q_len = k+1, which the kernel
    already serves; docs/KERNELS.md). Otherwise each half runs the exact
    split serving dispatcher (prefill_attention — the program the sync
    verify and split prefill paths use), so composed-step outputs match
    sync+split byte for byte. `interpret` is the ragged-branch CI hook
    only and is deliberately not forwarded to the reference pair, same
    as mixed_attention."""
    A, La = q_a.shape[0], q_a.shape[1]
    B, Lb = q_b.shape[0], q_b.shape[1]
    if ragged_kernel_enabled(
        k_cache, q_a.shape[-1], use_ragged, interpret
    ):
        seg_lens = (La,) * A + (Lb,) * B
        q_flat = jnp.concatenate(
            [
                q_a.reshape(A * La, *q_a.shape[2:]),
                q_b.reshape(B * Lb, *q_b.shape[2:]),
            ],
            axis=0,
        )
        CB = max(a_tables.shape[1], b_tables.shape[1])
        at = jnp.pad(a_tables, ((0, 0), (0, CB - a_tables.shape[1])))
        bt = jnp.pad(b_tables, ((0, 0), (0, CB - b_tables.shape[1])))
        tables = jnp.concatenate([at, bt], axis=0)
        q_len = jnp.concatenate([a_len, b_len]).astype(jnp.int32)
        pos0 = jnp.concatenate([a_start, b_start]).astype(jnp.int32)
        out = ragged_paged_attention(
            q_flat, k_cache, v_cache, tables, q_len, pos0, seg_lens,
            scale, use_kernel=True, interpret=interpret, window=window,
        )
        return (
            out[: A * La].reshape(q_a.shape),
            out[A * La:].reshape(q_b.shape),
        )
    return (
        prefill_attention(
            q_a, k_cache, v_cache, a_tables, a_start, a_len, scale,
            window=window,
        ),
        prefill_attention(
            q_b, k_cache, v_cache, b_tables, b_start, b_len, scale,
            window=window,
        ),
    )


def resolved_kernel_report(
    k_cache, q_head_dim: int, ragged_interpret: bool = False,
    shards: int = 1,
) -> dict:
    """The dispatch decisions the serving paths would take RIGHT NOW for
    this cache/geometry — what actually runs, not which env var is set
    (bench.py reports these; ISSUE 9 satellite: `attention_kernel:
    default` told the record nothing). Values name the winning
    implementation; a path whose env hatch forces it off reports the
    fallback with a ` (forced-off)` marker. `shards` > 1 resolves the
    per-shard (shard_map) dispatch of a tp mesh: the report's `shards`
    key is how many kernel launches one engine dispatch fans into —
    asserted (not assumed) by the virtual-mesh differential suite."""
    import os

    # The interpret hook drives only the RAGGED branch on CPU (the
    # decode/prefill serving dispatchers never see it from the engine),
    # so the platform gate for those stays _on_tpu().
    on = _on_tpu()
    eligible = gqa_kernel_eligible(k_cache, q_head_dim, on, shards=shards)

    def resolve(env_name: str, kernel: str, fallback: str) -> str:
        env = os.environ.get(env_name)
        if env == "0":
            return f"{fallback} (forced-off)"
        if env == "1":
            return kernel
        return kernel if eligible else fallback

    dec = resolve("XLLM_PAGED_ATTENTION_KERNEL", "paged", "gather")
    pf = resolve("XLLM_PREFILL_ATTENTION_KERNEL", "flash", "blockwise")
    ragged = (
        "ragged"
        if ragged_kernel_enabled(
            k_cache, q_head_dim, interpret=ragged_interpret, shards=shards
        )
        else (
            "split (forced-off)"
            if os.environ.get("XLLM_RAGGED_ATTENTION_KERNEL") == "0"
            else "split"
        )
    )
    kq = isinstance(k_cache, kvc.PagedKV) and k_cache.quantized
    mq_env = os.environ.get("XLLM_MQ_ATTENTION_KERNEL")
    # The prefill dispatcher's function-wide kill switch covers its mq
    # branch too (prefill_attention requires != "0"), so the report must
    # mirror it — mq never runs with the prefill kernels forced off.
    mq_on = (
        eligible
        and os.environ.get("XLLM_PREFILL_ATTENTION_KERNEL") != "0"
        and (mq_env == "1" if kq else mq_env != "0")
    )
    return {
        "decode": dec,
        "prefill": pf,
        "mixed": ragged,
        "mq": "mq" if mq_on else "blockwise",
        # Kernel launches one engine dispatch fans into: tp under the
        # shard_map tier, 1 on single-device meshes (or with the
        # XLLM_SHARDED_KERNELS=0 escape hatch back to GSPMD).
        "shards": shards,
    }


def resolved_mla_kernel_report(c_cache) -> dict:
    """MLA counterpart of resolved_kernel_report: mirrors the actual
    dispatch decisions of mla_paged_attention / mla_prefill_attention —
    including the _mla_kernel_ok tile/platform gate those dispatchers
    apply — not just the env vars. MLA families keep split stepping
    (docs/KERNELS.md)."""
    import os

    ok = _mla_kernel_ok(c_cache, _on_tpu())
    quantized = isinstance(c_cache, kvc.PagedKV) and c_cache.quantized
    dec_env = os.environ.get("XLLM_MLA_ATTENTION_KERNEL")
    pf_env = os.environ.get("XLLM_MLA_PREFILL_KERNEL")
    mq_env = os.environ.get("XLLM_MQ_ATTENTION_KERNEL")
    # mla_paged_attention: opt-in (env == "1") AND tile-eligible.
    dec = "mla" if (dec_env == "1" and ok) else "gather"
    # mla_prefill_attention: default-on for eligible bf16 latents
    # (kernel_ok = ok and not quantized); env == "1" forces, "0" kills.
    pf_ok = ok and not quantized
    if (pf_env != "0") if pf_ok else (pf_env == "1"):
        pf = "mla-flash"
    elif pf_ok and pf_env == "0":
        pf = "blockwise (forced-off)"
    else:
        pf = "blockwise"
    return {
        "decode": dec,
        "prefill": pf,
        "mixed": "split",
        "mq": "mla-mq" if (ok and mq_env == "1") else "blockwise",
        # MLA's latent cache has no KV-head axis to shard — the kernels
        # stay single-launch (docs/SHARDING.md).
        "shards": 1,
    }
