"""Int8/int4 weight quantization (W8/W4).

Decode is HBM-bandwidth-bound and the weights dominate its traffic
(every step streams all params once). Storing matmul weights as int8
with one scale per output channel halves that traffic and halves
per-device param residency — the lever that fits llama3-70b-class
models on v5e chips (__graft_entry__ dress-rehearsal budget:
bf16 params alone exceed one chip at the largest buildable tp).

Representation: a weight leaf becomes {"q": int8[..., in, out],
"s": model_dtype[..., out]} — a plain dict, so it flows through
lax.scan / jit / shardings as a pytree wherever the array did.
Quantization is symmetric per output channel over the CONTRACTING
axis (-2 for every stacked matmul leaf in models/llama.py:
[L, in, out], [L, X, in, out]).

Compute: `wt()` dequantizes at the use site — q.astype * s — which XLA
fuses into the consuming matmul's operand read on TPU, so HBM still
moves int8 bytes. The gather paths (embed/lm_head) are NOT quantized
(dequant-at-use would materialize the full table per step; their share
of 70B-class params is ~1.5%).

W4 (`bits=4`): native jnp.int4 leaves (XLA packs two per byte on TPU —
quarter-size weights) with GROUP-WISE scales along the contracting axis
(`group` values per scale, default 128) — per-channel symmetric int4
would be too coarse on real checkpoints. The scale tensor keeps the
leaf's rank ([..., in/group, out]), so its sharding spec is the weight's
own spec (a tp-sharded contracting axis shards the group axis
identically). Falls back to one group (per-channel) when the contracting
axis is not divisible by `group`.
"""

from __future__ import annotations

from typing import Any, Dict, Union

import jax.numpy as jnp

QuantLeaf = Dict[str, jnp.ndarray]
WeightLike = Union[jnp.ndarray, QuantLeaf]


def is_quant(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def quantize_weight(
    w: jnp.ndarray, dtype=None, bits: int = 8, group: int = 128
) -> QuantLeaf:
    """w [..., in, out] -> {"q": int8|int4 same shape, "s": scales}.

    bits=8: symmetric per-output-channel over the contracting (-2) axis;
    s is [..., out]. bits=4: symmetric per (group, output-channel) with
    `group` contracting values per scale; s is [..., in/group, out]
    (one group when `in` is not divisible). `dtype` sets the scale dtype
    (defaults to w's)."""
    f = w.astype(jnp.float32)
    if bits == 8:
        amax = jnp.max(jnp.abs(f), axis=-2)
        s = jnp.maximum(amax, 1e-8) / 127.0
        q = jnp.clip(jnp.round(f / s[..., None, :]), -127, 127).astype(
            jnp.int8
        )
        return {"q": q, "s": s.astype(dtype or w.dtype)}
    if bits != 4:
        raise ValueError(f"bits={bits}: expected 8 or 4")
    In, Out = f.shape[-2], f.shape[-1]
    g = group if In % group == 0 else In
    fg = f.reshape(*f.shape[:-2], In // g, g, Out)
    amax = jnp.max(jnp.abs(fg), axis=-2)  # [..., in/g, out]
    s = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(fg / s[..., None, :]), -7, 7).astype(jnp.int4)
    return {
        "q": q.reshape(f.shape),
        "s": s.astype(dtype or w.dtype),
    }


def wt(leaf: WeightLike) -> jnp.ndarray:
    """Weight at a use site: dequantize an int8/int4 leaf (fused into the
    consuming matmul by XLA), pass plain arrays through."""
    if is_quant(leaf):
        q, s = leaf["q"], leaf["s"]
        if q.dtype == jnp.int4:
            In, Out = q.shape[-2], q.shape[-1]
            g = In // s.shape[-2]
            qf = q.astype(s.dtype).reshape(
                *q.shape[:-2], In // g, g, Out
            )
            return (qf * s[..., :, None, :]).reshape(q.shape)
        return q.astype(s.dtype) * s[..., None, :]
    return leaf


def wdtype(leaf: WeightLike):
    """Compute dtype of a weight leaf (dict or array)."""
    return leaf["s"].dtype if is_quant(leaf) else leaf.dtype
