"""Int8 weight quantization (W8): per-output-channel scales.

Decode is HBM-bandwidth-bound and the weights dominate its traffic
(every step streams all params once). Storing matmul weights as int8
with one scale per output channel halves that traffic and halves
per-device param residency — the lever that fits llama3-70b-class
models on v5e chips (__graft_entry__ dress-rehearsal budget:
bf16 params alone exceed one chip at the largest buildable tp).

Representation: a weight leaf becomes {"q": int8[..., in, out],
"s": model_dtype[..., out]} — a plain dict, so it flows through
lax.scan / jit / shardings as a pytree wherever the array did.
Quantization is symmetric per output channel over the CONTRACTING
axis (-2 for every stacked matmul leaf in models/llama.py:
[L, in, out], [L, X, in, out]).

Compute: `wt()` dequantizes at the use site — q.astype * s — which XLA
fuses into the consuming matmul's operand read on TPU, so HBM still
moves int8 bytes. The gather paths (embed/lm_head) are NOT quantized
(dequant-at-use would materialize the full table per step; their share
of 70B-class params is ~1.5%).
"""

from __future__ import annotations

from typing import Any, Dict, Union

import jax.numpy as jnp

QuantLeaf = Dict[str, jnp.ndarray]
WeightLike = Union[jnp.ndarray, QuantLeaf]


def is_quant(leaf: Any) -> bool:
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def quantize_weight(w: jnp.ndarray, dtype=None) -> QuantLeaf:
    """w [..., in, out] -> {"q": int8 same shape, "s": [..., out]}.
    Symmetric per-output-channel over the contracting (-2) axis; `dtype`
    sets the scale dtype (defaults to w's)."""
    f = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(f), axis=-2)
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(f / s[..., None, :]), -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(dtype or w.dtype)}


def wt(leaf: WeightLike) -> jnp.ndarray:
    """Weight at a use site: dequantize an int8 leaf (fused into the
    consuming matmul by XLA), pass plain arrays through."""
    if is_quant(leaf):
        return leaf["q"].astype(leaf["s"].dtype) * leaf["s"][..., None, :]
    return leaf


def wdtype(leaf: WeightLike):
    """Compute dtype of a weight leaf (dict or array)."""
    return leaf["s"].dtype if is_quant(leaf) else leaf.dtype
