"""JSON-Schema-constrained byte automaton for structured outputs
(OpenAI `response_format: {"type": "json_schema", ...}`).

Generalizes guided/json_fsm.py from "any JSON object" to "a JSON
document matching this schema". Same two-layer architecture:

  * EXACT host tracking: `advance_byte` walks a hashable state tuple
    (surface, aux, frame stack, ws flag) byte by byte. Unlike the
    generic automaton, the FULL stack is part of the state — schema
    masks are per-request anyway, so there is no abstract/visible-top
    approximation and no sentinel conservatism.
  * LAZY device mask rows: the mask for a state is computed on first
    visit by simulating every vocab byte-string whose first byte the
    state accepts (`token_bitmap`), memoized by state key, and written
    into the executor table's dynamic-row region
    (ModelExecutor.update_guided_row). States inside free-form regions
    (string content, numbers) are CONSTANT across content bytes, so a
    generation visits O(schema size) distinct states, not O(output
    length).

Supported subset (the OpenAI structured-outputs strict profile):
  object (ordered properties, required subset, additionalProperties
  must be false), array (items + minItems/maxItems), string, enum /
  const over strings/numbers/bools/null, integer, number, boolean,
  null, `anyOf` over any of these (incl. type-list unions like
  ["string", "null"], which compile to the same alternative sets), and
  internal NON-recursive $ref into $defs/definitions (the shape
  pydantic's model_json_schema emits — Optional[X] arrives as anyOf).
  Properties are emitted in DECLARATION ORDER (optional ones may be
  skipped) — the order OpenAI's implementation produces; it keeps the
  automaton finite and small. anyOf runs as an NFA: the MULTI surface
  carries the set of parallel branch states, advancing all of them per
  byte, dropping dead ones and collapsing when they converge — byte
  prefixes shared between branches (e.g. integer vs number) stay
  ambiguous exactly as long as the input does. oneOf / allOf /
  recursive $ref / pattern / numeric ranges are rejected at compile
  time (HTTP 400), not silently ignored.

Whitespace: one byte between tokens, as in json_fsm (unbounded legal
whitespace lets a masked model burn its budget on emptiness).

Reference vestige for guided decoding overall: the reference exposes
no structured outputs (its OpenAI surface stops at plain completions);
this tracks the OpenAI API the reference's HTTP tier mirrors
(xllm_service/http_service/service.cpp:286-424).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

# Surfaces (schema automaton's own, smaller than json_fsm's: object/key
# bookkeeping lives in the frame stack, literal sets in aux).
(
    V_START,    # expecting the first byte of a value (aux = node id)
    LIT,        # inside a literal-alternative set (aux = alt suffixes)
    STR,        # free string content (aux = ())
    STR_ESC,    # after backslash in a free string
    NUM_SIGN,   # after '-' (aux = ("int"|"num",))
    NUM_INT,    # integer digits — may end here
    NUM_Z,      # leading zero — '.', 'e' (number only) or end
    NUM_DOT,    # after '.' needing a digit
    NUM_FRAC,   # fraction digits — may end here
    NUM_E,      # after e/E needing sign/digit
    NUM_ESIGN,  # after exponent sign needing digit
    NUM_EXP,    # exponent digits — may end here
    KEY,        # inside an object key (aux = ((prop_idx, suffix), ...))
    COLON,      # expecting ':' (aux = (prop_idx,))
    POST,       # after a complete value: ',' / '}' / ']' per top frame
    DONE,       # complete document: whitespace + EOS only
    MULTI,      # anyOf NFA: aux = tuple of parallel sub-States
) = range(17)

WS = frozenset(b" \t\n\r")
DIGITS = frozenset(b"0123456789")
_NUM_MAY_END = {NUM_INT, NUM_Z, NUM_FRAC, NUM_EXP}
_WS_OK = {V_START, KEY, COLON, POST, DONE}
# KEY-surface aux marker: at a post-',' boundary a key is MANDATORY ('}'
# would make a trailing comma); post-'{' boundaries use aux=().
_KEY_REQUIRED = ("!",)


class SchemaError(ValueError):
    """Schema outside the supported strict subset (surface as HTTP 400)."""


# ------------------------------------------------------------- compilation


class SchemaSpec:
    """Compiled schema: a flat node list (id 0 = root). Hashable by the
    canonical JSON of the source schema (mask-row caches key on it)."""

    def __init__(self, nodes: List[dict], source_key: str):
        self.nodes = nodes
        self.source_key = source_key


def _enc_str(s: str) -> bytes:
    """JSON-encoded string WITHOUT the surrounding quotes (escapes kept:
    candidate matching runs over encoded bytes, so values needing
    escapes match exactly)."""
    return json.dumps(s, ensure_ascii=False)[1:-1].encode("utf-8")


def _enc_value(v) -> bytes:
    """Full JSON encoding of a scalar enum/const alternative."""
    if isinstance(v, (dict, list)):
        raise SchemaError("enum/const values must be scalars")
    return json.dumps(v, ensure_ascii=False).encode("utf-8")


_UNSUPPORTED = (
    "oneOf", "allOf", "not", "if", "then", "else",
    "patternProperties", "pattern", "format", "minimum", "maximum",
    "exclusiveMinimum", "exclusiveMaximum", "multipleOf", "minLength",
    "maxLength", "uniqueItems", "prefixItems",
)


def compile_schema(schema: dict) -> SchemaSpec:
    """Validate + flatten a schema dict. Raises SchemaError outside the
    supported subset. Internal, NON-recursive `$ref` into `$defs` /
    `definitions` resolves inline (pydantic's model_json_schema always
    emits nested models this way); recursive schemas describe unbounded
    documents and are rejected."""
    if not isinstance(schema, dict):
        raise SchemaError("schema must be an object")
    defs = {}
    for key in ("$defs", "definitions"):
        d = schema.get(key)
        if isinstance(d, dict):
            for name, sub in d.items():
                defs[f"#/{key}/{name}"] = sub
    nodes: List[dict] = []
    ref_stack: List[str] = []  # cycle detection across $ref chains
    ref_memo: Dict[str, int] = {}  # each def compiles ONCE (expansion is
    # pure, subtrees are immutable) — without this, a DAG of doubling
    # refs compiles to 2^N nodes from a KB-sized request body

    def build(node: dict) -> int:
        if not isinstance(node, dict):
            raise SchemaError("schema node must be an object")
        # Reject unsupported keywords FIRST — including as $ref siblings
        # (draft 2020-12 allows them; silently dropping a constraint
        # would violate the "rejected, not ignored" contract).
        for k in _UNSUPPORTED:
            if k in node:
                raise SchemaError(f"unsupported schema keyword: {k}")
        ref = node.get("$ref")
        if ref is not None:
            if not isinstance(ref, str):
                raise SchemaError("$ref must be a string")
            extra = set(node) - {"$ref", "$defs", "definitions",
                                 "title", "description", "default"}
            if extra:
                raise SchemaError(
                    f"$ref with constraint siblings is not supported: "
                    f"{sorted(extra)}"
                )
            if len(ref_stack) >= 64:
                # pure-ref chains never touch the node cap; bound the
                # build() recursion (RecursionError would 500, not 400)
                raise SchemaError("$ref chain too deep (> 64)")
            if ref not in defs:
                raise SchemaError(
                    f"unresolvable $ref {ref!r} (only internal "
                    f"#/$defs/... and #/definitions/... are supported)"
                )
            if ref in ref_stack:
                raise SchemaError(
                    f"recursive $ref {ref!r}: recursive schemas describe "
                    f"unbounded documents and are not supported"
                )
            if ref in ref_memo:
                return ref_memo[ref]
            ref_stack.append(ref)
            try:
                nid = build(defs[ref])
            finally:
                ref_stack.pop()
            ref_memo[ref] = nid
            return nid
        if len(nodes) > 4096:
            raise SchemaError("schema too large (> 4096 nodes)")
        nid = len(nodes)
        nodes.append({})  # reserve slot (children reference by id)
        if "anyOf" in node:
            alts = node["anyOf"]
            if not isinstance(alts, list) or not alts:
                raise SchemaError("anyOf must be a non-empty array")
            extra = set(node) - {"anyOf", "$defs", "definitions",
                                 "title", "description", "default"}
            if extra:
                raise SchemaError(
                    f"anyOf with constraint siblings is not supported: "
                    f"{sorted(extra)}"
                )
            nodes[nid] = {
                "kind": "anyOf",
                "branches": tuple(build(sub) for sub in alts),
            }
            return nid
        if "const" in node:
            nodes[nid] = {
                "kind": "enum", "alts": (_enc_value(node["const"]),)
            }
            return nid
        if "enum" in node:
            vals = node["enum"]
            if not isinstance(vals, list) or not vals:
                raise SchemaError("enum must be a non-empty array")
            nodes[nid] = {
                "kind": "enum",
                "alts": tuple(sorted({_enc_value(v) for v in vals})),
            }
            return nid
        t = node.get("type")
        if isinstance(t, list):
            # Type-list unions (["string", "null"]) compile as anyOf over
            # single-type copies of the node.
            if not t:
                raise SchemaError("type list must be non-empty")
            nodes[nid] = {
                "kind": "anyOf",
                "branches": tuple(
                    build({**node, "type": tt}) for tt in t
                ),
            }
            return nid
        if t == "object":
            props = node.get("properties") or {}
            if not isinstance(props, dict):
                raise SchemaError("properties must be an object")
            if node.get("additionalProperties", None) is not False:
                raise SchemaError(
                    "objects require additionalProperties: false "
                    "(strict structured outputs)"
                )
            required = node.get("required") or []
            unknown = set(required) - set(props)
            if unknown:
                raise SchemaError(f"required lists unknown keys: {unknown}")
            plist = []
            for name, sub in props.items():
                plist.append(
                    (_enc_str(name), build(sub), name in set(required))
                )
            nodes[nid] = {"kind": "object", "props": tuple(plist)}
            return nid
        if t == "array":
            if "items" not in node:
                raise SchemaError("arrays require an items schema")
            mn = int(node.get("minItems", 0))
            mx = node.get("maxItems")
            mx = int(mx) if mx is not None else None
            if mx is not None and mx < mn:
                raise SchemaError("maxItems < minItems")
            nodes[nid] = {
                "kind": "array", "items": build(node["items"]),
                "min": mn, "max": mx,
            }
            return nid
        if t == "string":
            nodes[nid] = {"kind": "string"}
            return nid
        if t in ("integer", "number"):
            nodes[nid] = {"kind": t}
            return nid
        if t == "boolean":
            nodes[nid] = {"kind": "enum", "alts": (b"true", b"false")}
            return nid
        if t == "null":
            nodes[nid] = {"kind": "enum", "alts": (b"null",)}
            return nid
        raise SchemaError(
            f"unsupported or missing type: {t!r} (every node needs an "
            f"explicit type, enum, or const)"
        )

    build(schema)
    # NO sort_keys: property DECLARATION ORDER is part of the contract
    # (two schemas differing only in order compile to different
    # automata and must not share a memo entry).
    key = json.dumps(schema, separators=(",", ":"))
    return SchemaSpec(nodes, key)


# ------------------------------------------------------------- the automaton
#
# State: (surface, aux, stack, ws)
#   stack frames: ("o", node_id, next_prop_idx) | ("a", node_id, count)
#   aux by surface: V_START -> (node_id,); LIT -> alt suffix tuple;
#   NUM_* -> ("int"|"num",); KEY -> ((prop_idx, suffix), ...);
#   COLON -> (prop_idx,); else ().

State = Tuple[int, tuple, tuple, bool]


def initial_state(spec: SchemaSpec) -> State:
    return (V_START, (0,), (), False)


def is_complete(st: Optional[State]) -> bool:
    if st is None:
        return False
    s, aux, stack, _ = st
    if s == MULTI:
        # an anyOf document is complete iff ANY live branch is
        return any(is_complete(sub) for sub in aux)
    if stack:
        return False
    if s == DONE:
        return True
    # lazy number end at top level
    if s in _NUM_MAY_END:
        return True
    # a completable literal alternative (empty suffix present)
    return s == LIT and b"" in aux


def _merge_states(results) -> Optional[State]:
    """Collapse a list of parallel branch states: dedupe (order-
    preserving, so equal inputs yield equal MULTI states), flatten
    nested MULTIs, collapse singletons. None when no branch survives."""
    flat = []
    for r in results:
        if r is None:
            continue
        if r[0] == MULTI:
            flat.extend(r[1])
        else:
            flat.append(r)
    out = tuple(dict.fromkeys(flat))
    if not out:
        return None
    if len(out) == 1:
        return out[0]
    return (MULTI, out, (), False)


def _key_candidates(spec: SchemaSpec, node_id: int, idx: int):
    """Keys emittable at property position idx: every optional property
    until (and including) the first required one."""
    props = spec.nodes[node_id]["props"]
    out = []
    for j in range(idx, len(props)):
        name, _, req = props[j]
        out.append((j, name))
        if req:
            break
    return out


def _may_close(spec: SchemaSpec, node_id: int, idx: int) -> bool:
    """'}' legal at property position idx iff no required property
    remains at/after idx."""
    props = spec.nodes[node_id]["props"]
    return all(not req for _, _, req in props[idx:])


def _pop_value(spec: SchemaSpec, stack: tuple) -> State:
    """A value just completed under `stack` — surface for what follows."""
    if not stack:
        return (DONE, (), (), False)
    return (POST, (), stack, False)


def _start_value(spec: SchemaSpec, node_id: int, stack: tuple,
                 b: int) -> Optional[State]:
    """Dispatch byte b as the first byte of a value of node `node_id`."""
    node = spec.nodes[node_id]
    kind = node["kind"]
    if kind == "anyOf":
        # NFA start: byte b may open any branch; live alternatives run
        # in parallel under MULTI until the input disambiguates.
        return _merge_states(
            _start_value(spec, branch, stack, b)
            for branch in node["branches"]
        )
    if kind == "enum":
        alive = tuple(a[1:] for a in node["alts"] if a and a[0] == b)
        if not alive:
            return None
        if b"" in alive and len(alive) == 1:
            return _pop_value(spec, stack)
        return (LIT, alive, stack, False)
    if kind == "object":
        if b != 0x7B:  # '{'
            return None
        # KEY with aux=() is the "at a key boundary" position: '"' opens
        # a candidate key, '}' closes if no required property remains.
        return (KEY, (), stack + (("o", node_id, 0),), False)
    if kind == "array":
        if b != 0x5B:  # '['
            return None
        return (V_START, (node["items"],), stack + (("a", node_id, 0),),
                False)
    if kind == "string":
        if b != 0x22:
            return None
        return (STR, (), stack, False)
    if kind in ("integer", "number"):
        k = "int" if kind == "integer" else "num"
        if b == 0x2D:  # '-'
            return (NUM_SIGN, (k,), stack, False)
        if b == 0x30:
            return (NUM_Z, (k,), stack, False)
        if b in DIGITS:
            return (NUM_INT, (k,), stack, False)
        return None
    raise AssertionError(kind)


def advance_byte(spec: SchemaSpec, st: State, b: int) -> Optional[State]:
    s, aux, stack, ws = st

    # ---- anyOf NFA: advance every live branch, drop the dead
    if s == MULTI:
        return _merge_states(
            advance_byte_top(spec, sub, b) for sub in aux
        )

    # ---- literal alternative set
    if s == LIT:
        alive = tuple(a[1:] for a in aux if a and a[0] == b)
        if alive:
            if alive == (b"",):
                return _pop_value(spec, stack)
            return (LIT, alive, stack, False)
        if b"" in aux:
            # a completable (number) alternative ends lazily here
            nxt = _pop_value(spec, stack)
            return advance_byte(spec, nxt, b)
        return None

    # ---- free string value
    if s == STR:
        if b == 0x22:
            return _pop_value(spec, stack)
        if b == 0x5C:
            return (STR_ESC, (), stack, False)
        if b >= 0x20:
            return (STR, (), stack, False)
        return None
    if s == STR_ESC:
        if bytes([b]) in b'"\\/bfnrtu':
            return (STR, (), stack, False)
        return None

    # ---- numbers (aux = ("int"|"num",))
    if s in (NUM_SIGN, NUM_DOT, NUM_E, NUM_ESIGN):
        if s == NUM_E and b in b"+-":
            return (NUM_ESIGN, aux, stack, False)
        if b in DIGITS:
            if s == NUM_SIGN:
                return (NUM_Z if b == 0x30 else NUM_INT, aux, stack, False)
            if s == NUM_DOT:
                return (NUM_FRAC, aux, stack, False)
            return (NUM_EXP, aux, stack, False)
        return None
    if s in _NUM_MAY_END:
        num = aux[0] == "num"
        if b in DIGITS:
            if s == NUM_Z:
                return None
            return (s, aux, stack, False)
        if num and b == 0x2E and s in (NUM_INT, NUM_Z):
            return (NUM_DOT, aux, stack, False)
        if num and b in b"eE" and s in (NUM_INT, NUM_Z, NUM_FRAC):
            return (NUM_E, aux, stack, False)
        nxt = _pop_value(spec, stack)
        return advance_byte(spec, nxt, b)

    # ---- whitespace (one byte max between tokens; NOT inside a key
    # string — KEY with candidate-suffix aux is mid-string, where a space
    # is a content byte the suffixes must match; the _KEY_REQUIRED
    # boundary marker still takes inter-token whitespace)
    if b in WS and not (s == KEY and aux and aux != _KEY_REQUIRED):
        if not ws and s in _WS_OK:
            return (s, aux, stack, True)
        return None

    # ---- value start
    if s == V_START:
        return _start_value(spec, aux[0], stack, b)

    # ---- object key position (top frame is ("o", node, idx))
    if s == KEY:
        frame = stack[-1]
        _, node_id, idx = frame
        if not aux or aux == _KEY_REQUIRED:
            # At a key boundary: '"' opens a key. '}' may close ONLY at
            # the post-'{' boundary (aux=()); after a ',' a key is
            # mandatory — '{"a": 1,}' is not JSON (review finding, r4).
            if (
                b == 0x7D and not aux
                and _may_close(spec, node_id, idx)
            ):
                return _pop_value(spec, stack[:-1])
            if b == 0x22:
                cands = _key_candidates(spec, node_id, idx)
                if not cands:
                    return None
                return (KEY, tuple((j, n) for j, n in cands), stack, False)
            return None
        # inside the key string: match candidate suffixes
        alive = tuple(
            (j, n[1:]) for j, n in aux if n and n[0] == b
        )
        done = [j for j, n in aux if n == b""]
        if b == 0x22 and done:
            # key complete: bind property `done[0]` (suffix-free match is
            # unique — JSON-encoded names are distinct)
            j = done[0]
            return (COLON, (j,), stack, False)
        if alive:
            return (KEY, alive, stack, False)
        return None

    if s == COLON:
        if b == 0x3A:
            j = aux[0]
            _, node_id, _ = stack[-1]
            props = spec.nodes[node_id]["props"]
            nstack = stack[:-1] + (("o", node_id, j + 1),)
            return (V_START, (props[j][1],), nstack, False)
        return None

    # ---- after a complete value
    if s == POST:
        frame = stack[-1]
        if frame[0] == "o":
            _, node_id, idx = frame
            if b == 0x2C and _key_candidates(spec, node_id, idx):
                return (KEY, _KEY_REQUIRED, stack, False)
            if b == 0x7D and _may_close(spec, node_id, idx):
                return _pop_value(spec, stack[:-1])
            return None
        _, node_id, count = frame
        node = spec.nodes[node_id]
        count += 1
        if b == 0x2C and (node["max"] is None or count < node["max"]):
            nstack = stack[:-1] + (("a", node_id, count),)
            return (V_START, (node["items"],), nstack, False)
        if b == 0x5D and count >= node["min"]:
            return _pop_value(spec, stack[:-1])
        return None

    return None  # DONE + non-ws


# Array-first-element special case: '[' pushes ("a", node, 0) and V_START;
# ']' immediately after '[' (empty array) must be legal when min == 0.
# V_START handles only value bytes, so patch: _start_value of the items
# node returning None for b == ']' falls here via a wrapper.


def advance_byte_top(spec: SchemaSpec, st: State, b: int) -> Optional[State]:
    """advance_byte + the empty-array special case (']' at an array's
    first V_START position)."""
    s, aux, stack, ws = st
    if (
        s == V_START and b == 0x5D and stack and stack[-1][0] == "a"
        and stack[-1][2] == 0
    ):
        node = spec.nodes[stack[-1][1]]
        if node["min"] == 0:
            return _pop_value(spec, stack[:-1])
    return advance_byte(spec, st, b)


def advance_bytes(
    spec: SchemaSpec, st: Optional[State], data: bytes
) -> Optional[State]:
    for b in data:
        if st is None:
            return None
        st = advance_byte_top(spec, st, b)
    return st


# ------------------------------------------------------------- mask bitmaps


def build_first_byte_index(token_bytes: List[bytes]):
    """byte -> [(token_bytes, [ids])] over unique non-empty surfaces."""
    uniq: Dict[bytes, List[int]] = {}
    for tid, tb in enumerate(token_bytes):
        if tb:
            uniq.setdefault(bytes(tb), []).append(tid)
    index: Dict[int, List[Tuple[bytes, List[int]]]] = {}
    for tb, ids in uniq.items():
        index.setdefault(tb[0], []).append((tb, ids))
    return index


def token_bitmap(
    spec: SchemaSpec,
    st: State,
    first_byte_index,
    vocab_size: int,
    eos_ids: List[int],
) -> np.ndarray:
    """[V] bool allowed-token bitmap for one exact state: a token is
    allowed iff every byte advances the automaton. EOS is allowed iff
    the document is complete at this state. Cost is bounded by the
    tokens whose FIRST byte the state accepts; free-content states are
    constant across content, so each distinct state is computed once
    per schema (the engine memoizes by state key)."""
    bits = np.zeros(vocab_size, dtype=bool)
    for b in range(256):
        if advance_byte_top(spec, st, b) is None:
            continue
        for tb, ids in first_byte_index.get(b, ()):
            cur: Optional[State] = st
            for byte in tb:
                cur = advance_byte_top(spec, cur, byte)
                if cur is None:
                    break
            if cur is not None:
                bits[ids] = True
    if is_complete(st):
        for e in eos_ids:
            if 0 <= e < vocab_size:
                bits[e] = True
    return bits
