"""Byte-level JSON automaton for guided decoding (OpenAI
`response_format: {"type": "json_object"}`).

Two layers:

  * EXACT host tracking: `JsonState` carries (surface, bracket stack,
    pending-literal suffix); `advance_bytes` walks emitted tokens byte
    by byte — O(len) per emitted token, one state per request.
  * ABSTRACT mask states for the on-device token mask: the allowed-token
    set from a position depends only on (surface, literal suffix,
    top-of-stack, depth==1?). The stack below the top is unknown to the
    mask, so a token may close AT MOST the visible top bracket; a token
    with content past that close is conservatively rejected (the model
    emits single closers instead — still fully expressive, never
    invalid; the host recomputes the exact state after every emission).
    `token_mask_table` simulates every distinct vocab byte string from
    every abstract state into one bool table [NUM_MASK_STATES, V],
    built once per tokenizer and cached on device.

The automaton accepts exactly the JSON value grammar (RFC 8259, with the
\\uXXXX escape simplified to \\u + 4 ordinary string bytes — hex digits
are legal content bytes, so acceptance is unchanged) plus inter-token
whitespace CAPPED AT ONE CONSECUTIVE BYTE — unbounded whitespace runs
would let a masked model spend its whole token budget on legal
emptiness (observed: greedy decode under the mask emitting only tabs).
json.dumps-style output (", " separators) is unaffected. Restricted to
one top-level object when `top_object=True` (what json_object mode
promises). No trailing commas ('[' and ',' expect different states).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

(
    S_VALUE,        # expecting a value (top level / after ':' / after ',')
    S_ARR_FIRST,    # right after '[': a value or ']'
    S_OBJ_FIRST,    # after '{': key string or '}'
    S_OBJ_KEY,      # after ',' in object: key string
    S_OBJ_COLON,    # after key string: ':'
    S_OBJ_NEXT,     # after a member value: ',' or '}'
    S_ARR_NEXT,     # after an element value: ',' or ']'
    S_STR,          # inside a value string
    S_STR_ESC,      # after backslash in a value string
    S_KEYSTR,       # inside a key string
    S_KEYSTR_ESC,   # after backslash in a key string
    S_NUM_SIGN,     # after '-' needing first digit
    S_NUM_INT,      # integer digits — value may end here
    S_NUM_Z,        # leading zero — only '.', 'e', or end may follow
    S_NUM_DOT,      # after '.' needing a digit
    S_NUM_FRAC,     # fraction digits — value may end here
    S_NUM_E,        # after 'e'/'E' needing sign or digit
    S_NUM_ESIGN,    # after exponent sign needing digit
    S_NUM_EXP,      # exponent digits — value may end here
    S_LIT,          # inside true/false/null (suffix tracked)
    S_DONE,         # complete top-level value; only whitespace (+EOS)
    NUM_SURFACES,
) = range(22)

WS = frozenset(b" \t\n\r")
DIGITS = frozenset(b"0123456789")
_NUM_END_OK = {S_NUM_INT, S_NUM_Z, S_NUM_FRAC, S_NUM_EXP}
_LITERALS = (b"true", b"false", b"null")
# every literal suffix a token boundary can land on
_LIT_SUFFIXES = sorted(
    {w[i:] for w in _LITERALS for i in range(1, len(w))}
)


class JsonState:
    """Exact configuration: surface + bracket stack + literal suffix +
    just-saw-whitespace flag (ws runs cap at one byte)."""

    __slots__ = ("surface", "stack", "lit", "ws")

    def __init__(self, surface: int, stack: Tuple[str, ...] = (),
                 lit: bytes = b"", ws: bool = False):
        self.surface = surface
        self.stack = stack
        self.lit = lit
        self.ws = ws

    def key(self):
        return (self.surface, self.stack, self.lit, self.ws)

    def __eq__(self, other):
        return isinstance(other, JsonState) and self.key() == other.key()

    def __hash__(self):
        return hash(self.key())

    def __repr__(self):
        return (
            f"JsonState({self.surface}, {self.stack}, {self.lit!r}, "
            f"ws={self.ws})"
        )


def initial_state(top_object: bool = True) -> JsonState:
    return JsonState(S_VALUE)


def _close(stack: Tuple[str, ...]) -> Tuple[int, Tuple[str, ...]]:
    """Surface after a value completes under the given (new) stack."""
    if not stack:
        return S_DONE, stack
    return (S_OBJ_NEXT if stack[-1] == "o" else S_ARR_NEXT), stack


def advance_byte(
    st: JsonState, b: int, top_object: bool = True
) -> Optional[JsonState]:
    """One byte through the EXACT automaton; None rejects."""
    s, stack, lit = st.surface, st.stack, st.lit
    c = bytes([b])

    if s == S_LIT:
        if lit and b == lit[0]:
            rest = lit[1:]
            if rest:
                return JsonState(S_LIT, stack, rest)
            ns, stack = _close(stack)
            return JsonState(ns, stack)
        return None

    if s in (S_STR, S_KEYSTR):
        if b == 0x22:
            if s == S_KEYSTR:
                return JsonState(S_OBJ_COLON, stack)
            ns, stack = _close(stack)
            return JsonState(ns, stack)
        if b == 0x5C:
            return JsonState(
                S_STR_ESC if s == S_STR else S_KEYSTR_ESC, stack
            )
        if b >= 0x20:
            return JsonState(s, stack)
        return None
    if s in (S_STR_ESC, S_KEYSTR_ESC):
        if c in b'"\\/bfnrtu':
            return JsonState(S_STR if s == S_STR_ESC else S_KEYSTR, stack)
        return None

    if s in (S_NUM_SIGN, S_NUM_DOT, S_NUM_E, S_NUM_ESIGN):
        if s == S_NUM_E and b in b"+-":
            return JsonState(S_NUM_ESIGN, stack)
        if b in DIGITS:
            if s == S_NUM_SIGN:
                return JsonState(S_NUM_Z if b == 0x30 else S_NUM_INT, stack)
            if s == S_NUM_DOT:
                return JsonState(S_NUM_FRAC, stack)
            return JsonState(S_NUM_EXP, stack)
        return None
    if s in _NUM_END_OK:
        if b in DIGITS:
            if s == S_NUM_Z:
                return None  # no leading zeros
            return JsonState(s, stack)
        if b == 0x2E and s in (S_NUM_INT, S_NUM_Z):
            return JsonState(S_NUM_DOT, stack)
        if b in b"eE" and s in (S_NUM_INT, S_NUM_Z, S_NUM_FRAC):
            return JsonState(S_NUM_E, stack)
        # number ends lazily: close it, re-dispatch this byte
        ns, nstack = _close(stack)
        return advance_byte(JsonState(ns, nstack), b, top_object)

    if b in WS:
        if not st.ws and s in (
            S_VALUE, S_ARR_FIRST, S_OBJ_FIRST, S_OBJ_KEY, S_OBJ_COLON,
            S_OBJ_NEXT, S_ARR_NEXT, S_DONE,
        ):
            return JsonState(s, stack, ws=True)
        return None

    if s in (S_VALUE, S_ARR_FIRST):
        if s == S_ARR_FIRST and b == 0x5D:  # empty array
            ns, nstack = _close(stack[:-1])
            return JsonState(ns, nstack)
        if top_object and not stack and b != 0x7B:
            return None  # json_object: top level must be an object
        if b == 0x7B:
            return JsonState(S_OBJ_FIRST, stack + ("o",))
        if b == 0x5B:
            return JsonState(S_ARR_FIRST, stack + ("a",))
        if b == 0x22:
            return JsonState(S_STR, stack)
        if b == 0x2D:
            return JsonState(S_NUM_SIGN, stack)
        if b == 0x30:
            return JsonState(S_NUM_Z, stack)
        if b in DIGITS:
            return JsonState(S_NUM_INT, stack)
        for word in _LITERALS:
            if b == word[0]:
                return JsonState(S_LIT, stack, word[1:])
        return None
    if s == S_OBJ_FIRST:
        if b == 0x22:
            return JsonState(S_KEYSTR, stack)
        if b == 0x7D:
            ns, nstack = _close(stack[:-1])
            return JsonState(ns, nstack)
        return None
    if s == S_OBJ_KEY:
        if b == 0x22:
            return JsonState(S_KEYSTR, stack)
        return None
    if s == S_OBJ_COLON:
        if b == 0x3A:
            return JsonState(S_VALUE, stack)
        return None
    if s == S_OBJ_NEXT:
        if b == 0x2C:
            return JsonState(S_OBJ_KEY, stack)
        if b == 0x7D:
            ns, nstack = _close(stack[:-1])
            return JsonState(ns, nstack)
        return None
    if s == S_ARR_NEXT:
        if b == 0x2C:
            return JsonState(S_VALUE, stack)
        if b == 0x5D:
            ns, nstack = _close(stack[:-1])
            return JsonState(ns, nstack)
        return None
    return None  # S_DONE with a non-ws byte


def advance_bytes(
    st: Optional[JsonState], data: bytes, top_object: bool = True
) -> Optional[JsonState]:
    for b in data:
        if st is None:
            return None
        st = advance_byte(st, b, top_object)
    return st


def is_complete(st: Optional[JsonState]) -> bool:
    """A complete top-level value: DONE, or a top-level number that may
    end here (numbers terminate lazily — no byte closes them)."""
    if st is None or st.stack:
        return False
    return st.surface == S_DONE or st.surface in _NUM_END_OK


# ------------------------------------------------------- abstract mask rows

_TOPS = ("", "o", "a")


_WS_SURFACES = {
    S_VALUE, S_ARR_FIRST, S_OBJ_FIRST, S_OBJ_KEY, S_OBJ_COLON,
    S_OBJ_NEXT, S_ARR_NEXT, S_DONE,
}


def _abstract_states():
    out = []
    for s in range(NUM_SURFACES):
        lits = _LIT_SUFFIXES if s == S_LIT else [b""]
        ws_opts = (False, True) if s in _WS_SURFACES else (False,)
        for lit in lits:
            for ws in ws_opts:
                for top in _TOPS:
                    for depth1 in (True, False):
                        if top == "" and not depth1:
                            continue
                        out.append((s, lit, ws, top, depth1))
    return out


# bump when the automaton or abstract-state layout changes — persistent
# mask-table caches key on this (a stale table would silently mis-mask)
FSM_VERSION = 2

_ABSTRACT = _abstract_states()
_ABSTRACT_INDEX = {a: i for i, a in enumerate(_ABSTRACT)}
NUM_MASK_STATES = len(_ABSTRACT)
_SENTINEL = "?"  # unknown stack below the visible top


def abstract_index(st: JsonState) -> int:
    top = st.stack[-1] if st.stack else ""
    depth1 = len(st.stack) <= 1
    lit = st.lit if st.surface == S_LIT else b""
    ws = st.ws if st.surface in _WS_SURFACES else False
    return _ABSTRACT_INDEX[(st.surface, lit, ws, top, depth1)]


def _seed_state(abstract) -> JsonState:
    s, lit, ws, top, depth1 = abstract
    if top == "":
        stack: Tuple[str, ...] = ()
    elif depth1:
        stack = (top,)
    else:
        stack = (_SENTINEL, top)
    return JsonState(s, stack, lit, ws)


def token_allowed_from(abstract, token: bytes, top_object: bool) -> bool:
    """Simulate one token from the seeded abstract state. A token may
    close at most the VISIBLE top bracket: once only the sentinel
    remains, any further byte rejects (the context below the top is
    unknown to the mask)."""
    st: Optional[JsonState] = _seed_state(abstract)
    for b in token:
        if st is None:
            return False
        if st.stack == (_SENTINEL,):
            return False  # content past the visible top's close
        st = advance_byte(st, b, top_object)
    if st is None:
        return False
    # Landing exactly on the sentinel is fine — the host recomputes the
    # true state — unless the simulation had to INTERPRET the sentinel
    # (it never does: _close reads the symbol only to pick obj/arr, and
    # we stopped before any byte was consumed under it).
    return True


def token_mask_table(
    token_bytes: List[bytes], eos_ids: List[int], top_object: bool = True
) -> np.ndarray:
    """[NUM_MASK_STATES, V] bool allowed-token table. EOS ids are allowed
    exactly in DONE rows; empty-byte tokens (specials) are disallowed
    everywhere."""
    V = len(token_bytes)
    table = np.zeros((NUM_MASK_STATES, V), dtype=bool)
    uniq = {}
    for tid, tb in enumerate(token_bytes):
        uniq.setdefault(bytes(tb), []).append(tid)
    uniq.pop(b"", None)
    for ai, abstract in enumerate(_ABSTRACT):
        for tb, ids in uniq.items():
            if token_allowed_from(abstract, tb, top_object):
                table[ai, ids] = True
    for top in _TOPS:
        for d1 in (True, False):
            for ws in (False, True):
                key = (S_DONE, b"", ws, top, d1)
                if key in _ABSTRACT_INDEX:
                    for e in eos_ids:
                        if 0 <= e < V:
                            table[_ABSTRACT_INDEX[key], e] = True
    return table
