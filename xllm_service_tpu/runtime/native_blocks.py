"""ctypes binding for the C++ block store (native/block_store.cpp).

`NativeBlockManager` is interface-identical to runtime/block_manager.py's
BlockManager — the engine picks whichever `create_block_manager` returns.
The native core owns the hot bookkeeping (free lists, refcounts, hash
index, LRU, event deltas); the chained murmur3 hashing already lives in
native/murmur3.cpp. Set XLLM_NATIVE_BLOCKS=0 to force the Python store.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, List, Optional, Sequence, Tuple

from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import KvCacheEvent
from xllm_service_tpu.runtime.block_manager import BlockManager, OutOfBlocksError

_NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "native")
)
_SRC = os.path.join(_NATIVE_DIR, "block_store.cpp")
_LIB = os.path.join(_NATIVE_DIR, "libxllm_blockstore.so")

_lib = None
_lib_lock = threading.Lock()
_lib_failed = False
_lib_error = ""

_TIERS = ("dram", "ssd")

logger = __import__("logging").getLogger(__name__)


def _check_hash(block_hash: bytes) -> bytes:
    """The C side reads exactly 16 bytes — network-origin hashes (PD
    handoffs) MUST be length-checked before they cross the ABI."""
    if not isinstance(block_hash, bytes) or len(block_hash) != 16:
        raise ValueError(
            f"block hash must be 16 bytes, got "
            f"{len(block_hash) if isinstance(block_hash, bytes) else type(block_hash)}"
        )
    return block_hash


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(
                _SRC
            ) > os.path.getmtime(_LIB):
                tmp = f"{_LIB}.{os.getpid()}.tmp"
                subprocess.run(
                    ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                     "-o", tmp, _SRC],
                    check=True,
                    capture_output=True,
                )
                os.replace(tmp, _LIB)
            lib = ctypes.CDLL(_LIB)
            P, I, C = ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p
            IP = ctypes.POINTER(ctypes.c_int32)
            lib.xbs_new.restype = P
            lib.xbs_new.argtypes = [I, I]
            lib.xbs_free_store.argtypes = [P]
            lib.xbs_num_free.argtypes = [P]
            lib.xbs_num_free.restype = I
            lib.xbs_num_referenced.argtypes = [P]
            lib.xbs_num_referenced.restype = I
            lib.xbs_allocate.argtypes = [P, I, IP, IP, C, ctypes.POINTER(I)]
            lib.xbs_allocate.restype = I
            lib.xbs_acquire.argtypes = [P, I]
            lib.xbs_release.argtypes = [P, IP, I]
            lib.xbs_release.restype = I
            lib.xbs_commit.argtypes = [P, I, C]
            lib.xbs_commit.restype = I
            lib.xbs_lookup.argtypes = [P, C]
            lib.xbs_lookup.restype = I
            lib.xbs_match_prefix.argtypes = [P, C, I, IP]
            lib.xbs_match_prefix.restype = I
            lib.xbs_record_removed_unless_hot.argtypes = [P, C]
            lib.xbs_record_offload.argtypes = [P, C, I]
            lib.xbs_record_evicted.argtypes = [P, C, I]
            lib.xbs_event_counts.argtypes = [P] + [ctypes.POINTER(I)] * 3
            lib.xbs_take_events.argtypes = [
                P, C, I, ctypes.POINTER(I),
                C, I, ctypes.POINTER(I),
                C, IP, I, ctypes.POINTER(I),
            ]
            lib.xbs_take_events.restype = I
            _lib = lib
        except Exception as e:
            global _lib_error
            _lib_failed = True
            detail = ""
            if isinstance(e, subprocess.CalledProcessError):
                detail = (e.stderr or b"").decode(errors="replace")[-2000:]
            _lib_error = f"{e!r} {detail}".strip()
            logger.warning(
                "native block store unavailable, falling back to the "
                "Python store: %s", _lib_error,
            )
    return _lib


class NativeBlockManager:
    """Drop-in replacement for BlockManager backed by the C++ store."""

    def __init__(self, num_blocks: int, block_size: int, seed: int = 1024):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        lib = _load()
        assert lib is not None, "native block store unavailable"
        self._lib = lib
        self._store = lib.xbs_new(num_blocks, block_size)
        assert self._store, "xbs_new failed"
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.seed = seed
        self.on_evict: Optional[
            Callable[[List[Tuple[int, bytes]]], Sequence[bytes]]
        ] = None
        # Python-side mirror of the committed-hash set: the C store owns
        # the authoritative index but exposes no iteration, and the
        # committed snapshot feeds takeover reconciliation and the
        # fabric's post-ejection cache resync (engine.cache_snapshot).
        # Maintained on the engine thread: commit_block adds, the
        # allocate() eviction report removes.
        self._committed: set = set()

    def __del__(self):
        store, self._store = getattr(self, "_store", None), None
        if store:
            self._lib.xbs_free_store(store)

    # ------------------------------------------------------------------ util

    @property
    def num_free_blocks(self) -> int:
        return self._lib.xbs_num_free(self._store)

    @property
    def num_referenced_blocks(self) -> int:
        """Blocks with live references — 0 when the engine is drained."""
        return self._lib.xbs_num_referenced(self._store)

    @property
    def usage(self) -> float:
        total = self.num_blocks - 1
        return (total - self.num_free_blocks) / max(total, 1)

    def can_allocate(self, n: int) -> bool:
        return self.num_free_blocks >= n

    # ------------------------------------------------------------- allocate

    def allocate(self, n: int) -> List[int]:
        out = (ctypes.c_int32 * max(n, 1))()
        ev_ids = (ctypes.c_int32 * max(n, 1))()
        ev_hashes = ctypes.create_string_buffer(16 * max(n, 1))
        n_ev = ctypes.c_int(0)
        rc = self._lib.xbs_allocate(
            self._store, n, out, ev_ids, ev_hashes, ctypes.byref(n_ev)
        )
        if rc != 0:
            raise OutOfBlocksError(
                f"need {n} blocks, only {self.num_free_blocks} free"
            )
        if n_ev.value:
            hashed = [
                (int(ev_ids[i]), ev_hashes.raw[i * 16:(i + 1) * 16])
                for i in range(n_ev.value)
            ]
            saved: Sequence[bytes] = ()
            if self.on_evict is not None:
                try:
                    saved = set(self.on_evict(hashed))
                except Exception:
                    saved = ()
            for _, h in hashed:
                self._lib.xbs_record_evicted(
                    self._store, h, 0 if h in saved else -1
                )
                self._committed.discard(h)
        return [int(out[i]) for i in range(n)]

    def acquire_cached(self, block_id: int) -> None:
        self._lib.xbs_acquire(self._store, block_id)

    def free(self, block_ids: Sequence[int]) -> None:
        n = len(block_ids)
        if not n:
            return
        arr = (ctypes.c_int32 * n)(*block_ids)
        rc = self._lib.xbs_release(self._store, arr, n)
        if rc != 0:
            # The C side released every valid id (no leaked tail); fail
            # loudly for the invalid one like BlockManager's assert.
            raise RuntimeError(f"double/invalid free in {list(block_ids)}")

    # --------------------------------------------------------- prefix cache

    def commit_block(self, block_id: int, block_hash: bytes) -> None:
        self._lib.xbs_commit(self._store, block_id, _check_hash(block_hash))
        # Mirror add is correct even when the C side no-ops a duplicate
        # commit: the hash IS committed (under the earlier block).
        self._committed.add(block_hash)

    def committed_hashes(self) -> List[bytes]:
        """Every committed hash (reconcile manifests / cache resync).
        Racy off-thread read by design — callers tolerate one-beat drift;
        the retry only guards resize-during-iteration."""
        for _ in range(3):
            try:
                return list(self._committed)
            except RuntimeError:
                continue
        return []

    def lookup_hash(self, block_hash: bytes) -> Optional[int]:
        if not isinstance(block_hash, bytes) or len(block_hash) != 16:
            return None  # malformed (network-origin) hash: a clean miss
        got = self._lib.xbs_lookup(self._store, block_hash)
        return None if got < 0 else int(got)

    def match_prefix(
        self,
        token_ids: Sequence[int],
        hashes: Optional[List[bytes]] = None,
    ) -> Tuple[int, List[int]]:
        if hashes is None:
            hashes = prefix_block_hashes(token_ids, self.block_size, self.seed)
        if not hashes:
            return 0, []
        for h in hashes:
            _check_hash(h)
        blob = b"".join(hashes)
        out = (ctypes.c_int32 * len(hashes))()
        n = self._lib.xbs_match_prefix(self._store, blob, len(hashes), out)
        return n * self.block_size, [int(out[i]) for i in range(n)]

    # ------------------------------------------------------------ heartbeat

    def record_host_removed(self, block_hash: bytes) -> None:
        self._lib.xbs_record_removed_unless_hot(
            self._store, _check_hash(block_hash)
        )

    def record_tier_offload(self, block_hash: bytes, tier: str) -> None:
        self._lib.xbs_record_offload(
            self._store, _check_hash(block_hash), _TIERS.index(tier)
        )

    def take_cache_event(self) -> KvCacheEvent:
        n_s, n_r, n_o = ctypes.c_int(), ctypes.c_int(), ctypes.c_int()
        while True:
            self._lib.xbs_event_counts(
                self._store, ctypes.byref(n_s), ctypes.byref(n_r),
                ctypes.byref(n_o),
            )
            cap_s, cap_r, cap_o = (
                max(n_s.value, 1) + 64,
                max(n_r.value, 1) + 64,
                max(n_o.value, 1) + 64,
            )
            sb = ctypes.create_string_buffer(16 * cap_s)
            rb = ctypes.create_string_buffer(16 * cap_r)
            ob = ctypes.create_string_buffer(16 * cap_o)
            tiers = (ctypes.c_int32 * cap_o)()
            rc = self._lib.xbs_take_events(
                self._store,
                sb, cap_s, ctypes.byref(n_s),
                rb, cap_r, ctypes.byref(n_r),
                ob, tiers, cap_o, ctypes.byref(n_o),
            )
            if rc == 0:
                break
        return KvCacheEvent(
            stored_cache={
                sb.raw[i * 16:(i + 1) * 16] for i in range(n_s.value)
            },
            removed_cache={
                rb.raw[i * 16:(i + 1) * 16] for i in range(n_r.value)
            },
            offload_cache={
                ob.raw[i * 16:(i + 1) * 16]: _TIERS[tiers[i]]
                for i in range(n_o.value)
            },
        )


def native_available() -> bool:
    return _load() is not None


def create_block_manager(num_blocks: int, block_size: int, seed: int = 1024):
    """Factory: the C++ store when buildable (default), else the Python
    one. XLLM_NATIVE_BLOCKS=0 forces Python; =1 requires native."""
    pref = os.environ.get("XLLM_NATIVE_BLOCKS", "")
    if pref == "0":
        return BlockManager(num_blocks, block_size, seed=seed)
    if native_available():
        return NativeBlockManager(num_blocks, block_size, seed=seed)
    if pref == "1":
        raise RuntimeError(
            f"XLLM_NATIVE_BLOCKS=1 but the native store failed to build: "
            f"{_lib_error}"
        )
    return BlockManager(num_blocks, block_size, seed=seed)
