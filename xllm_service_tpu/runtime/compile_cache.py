"""Persistent AOT compile cache keying for the executor's bucket-program
family (ISSUE 18 tentpole b — the host-side dispatch war).

PR 11 measured a 2.7-4 s recompile ambush on the first post-idle
dispatch of each bucket-program variant. Two layers kill that class:

  * **On-disk persistence** — the executor routes jax's persistent
    compilation cache into a subdirectory KEYED by (config hash, jax
    version, mesh shape), so a restarted instance with the same
    geometry reloads every compiled executable from disk instead of
    re-running XLA, while a changed config/mesh/jax build gets a fresh
    keyspace (no silent reuse of stale executables across geometries
    that happen to share program shapes).
  * **Prewarm enumeration** — `ModelExecutor.prewarm_programs()` walks
    the FULL bucket-program family the engine can dispatch (context
    buckets x step builders x spec/guided variants) and compiles each
    through its jit entry point, populating both the in-process jit
    dispatch caches (zero fresh lowerings afterwards — the engine's
    compile-cache hit/miss instruments count against this) and the
    keyed on-disk cache (warm restarts skip the XLA invocations).

Hatches: `XLLM_COMPILE_CACHE=0` disables the keyed persistent cache
(and drops prewarm back to the basic split-step warmup);
`XLLM_COMPILE_CACHE_DIR` overrides EngineConfig.compilation_cache_dir
without a config edit (the bench's cold-vs-warm A/B lever).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from typing import Optional


def compile_cache_enabled() -> bool:
    """Whether the keyed persistent compile cache (and the full-family
    prewarm that feeds it) is on. Default ON when a cache dir is
    configured; =0 always wins."""
    return os.environ.get("XLLM_COMPILE_CACHE", "1") not in (
        "0", "false", "off",
    )


def resolve_cache_dir(engine_cfg) -> str:
    """The base cache directory: XLLM_COMPILE_CACHE_DIR overrides the
    config field; "" (no dir anywhere, or XLLM_COMPILE_CACHE=0) means
    no persistent cache."""
    if not compile_cache_enabled():
        return ""
    return (
        os.environ.get("XLLM_COMPILE_CACHE_DIR", "")
        or getattr(engine_cfg, "compilation_cache_dir", "")
        or ""
    )


def _cfg_items(cfg) -> list:
    if dataclasses.is_dataclass(cfg):
        d = dataclasses.asdict(cfg)
    elif hasattr(cfg, "__dict__"):
        d = dict(vars(cfg))
    else:
        d = {"repr": repr(cfg)}
    # The cache location must not key the cache contents (pointing the
    # same geometry at a new dir would otherwise also change its key).
    d.pop("compilation_cache_dir", None)
    return sorted((k, repr(v)) for k, v in d.items())


def cache_key(engine_cfg, model_cfg, mesh) -> str:
    """Stable hex key for one executor geometry: engine + model config
    hash, jax version, mesh (axis name, extent) pairs. Anything that
    changes compiled programs MUST move the key — XLA's own cache keys
    catch HLO-level drift, this layer keeps unrelated geometries from
    interleaving in one directory (and makes `rm -rf <dir>/<key>` a
    targeted invalidation)."""
    import jax

    h = hashlib.sha256()
    for part in (
        repr(_cfg_items(engine_cfg)),
        repr(_cfg_items(model_cfg)),
        jax.__version__,
        repr(sorted((str(a), int(n)) for a, n in dict(mesh.shape).items())),
    ):
        h.update(part.encode())
        h.update(b"\x00")
    return h.hexdigest()[:16]


def keyed_dir(base: str, key: str) -> str:
    """The keyed cache subdirectory (created on first use)."""
    path = os.path.join(base, key)
    os.makedirs(path, exist_ok=True)
    return path


def cache_entries(base: str, key: str) -> int:
    """How many compiled executables the keyed cache holds on disk
    (the bench's cold/warm discriminator; -atime bookkeeping files
    don't count)."""
    path = os.path.join(base, key)
    if not os.path.isdir(path):
        return 0
    return sum(1 for f in os.listdir(path) if f.endswith("-cache"))
