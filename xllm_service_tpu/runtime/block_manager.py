"""Paged KV-cache block manager with content-addressed prefix caching.

Engine-tier counterpart of the service's global cache index: allocates
fixed-size token blocks, commits full blocks under their chained murmur3
hash (common/hashing.py — the cross-tier invariant), serves intra-instance
prefix-cache hits, evicts LRU, and accumulates the stored/removed deltas
that the heartbeat reports as a KvCacheEvent
(reference contract: proto/xllm_rpc_service.proto:44-48;
global_kvcache_mgr.cpp:177-225 consumes these on the service side).

Block 0 is reserved as the garbage slot for masked scatter writes
(models/llama.py) and is never allocated.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import KvCacheEvent


class OutOfBlocksError(RuntimeError):
    pass


@dataclass
class _BlockInfo:
    ref_count: int = 0
    hash: Optional[bytes] = None  # set once the block is full + committed


class BlockManager:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        seed: int = 1024,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.seed = seed
        self._blocks: Dict[int, _BlockInfo] = {
            i: _BlockInfo() for i in range(1, num_blocks)
        }
        self._free: List[int] = list(range(1, num_blocks))
        # hash -> block_id for committed blocks (both live and evictable).
        self._hash_to_block: Dict[bytes, int] = {}
        # Evictable committed blocks in LRU order: block_id -> None.
        self._evictable: OrderedDict[int, None] = OrderedDict()
        # Heartbeat deltas. Guarded by _ev_mu: the heartbeat thread drains
        # them (take_cache_event) while the engine thread mutates.
        self._ev_mu = threading.Lock()
        self._stored: Set[bytes] = set()
        self._removed: Set[bytes] = set()
        self._offloaded: Dict[bytes, str] = {}
        # Optional host-offload hook: called as on_evict([(block_id, hash),
        # ...]) with ALL of an allocation's committed victims BEFORE their
        # device blocks are reused (ONE batched device->host copy, not one
        # sync per block); returns the iterable of hashes actually saved —
        # those become offload_cache['dram'] deltas instead of
        # removed_cache (reference proto:47).
        self.on_evict = None
        # Lifetime eviction count (engine-thread only, like the rest of
        # the class) — exported as xllm_engine_block_evictions_total.
        self.evictions_total = 0

    # ------------------------------------------------------------------ util

    @property
    def num_free_blocks(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def num_referenced_blocks(self) -> int:
        """Blocks with live references — 0 when the engine is drained
        (stress-harness invariant; mirrors NativeBlockManager). Like the
        rest of this class, call from the engine thread or after stop()."""
        return sum(1 for b in self._blocks.values() if b.ref_count > 0)

    @property
    def usage(self) -> float:
        total = self.num_blocks - 1
        return (total - self.num_free_blocks) / max(total, 1)

    def can_allocate(self, n: int) -> bool:
        return self.num_free_blocks >= n

    # ------------------------------------------------------------- allocate

    def _evict_batch(self, victims: List[int]) -> None:
        """Un-commit a batch of LRU victims, offering their content to the
        host tier in ONE hook call (one bulk device->host copy)."""
        self.evictions_total += len(victims)
        hashed = [
            (b, self._blocks[b].hash)
            for b in victims
            if self._blocks[b].hash is not None
        ]
        for _, h in hashed:
            del self._hash_to_block[h]
        saved: Set[bytes] = set()
        if self.on_evict is not None and hashed:
            try:
                saved = set(self.on_evict(hashed))
            except Exception:
                saved = set()
        with self._ev_mu:
            for b, h in hashed:
                if h in saved:
                    self._offloaded[h] = "dram"
                    # A transient removal recorded earlier in this batch
                    # (host-pool LRU churn) must not ride the same beat as
                    # the offload — the master applies removed last.
                    self._removed.discard(h)
                else:
                    self._removed.add(h)
                self._stored.discard(h)
                self._blocks[b].hash = None

    def allocate(self, n: int) -> List[int]:
        if not self.can_allocate(n):
            raise OutOfBlocksError(
                f"need {n} blocks, only {self.num_free_blocks} free"
            )
        out = []
        while len(out) < n and self._free:
            out.append(self._free.pop())
        victims = []
        while len(out) + len(victims) < n:
            victim, _ = self._evictable.popitem(last=False)  # LRU
            victims.append(victim)
        if victims:
            self._evict_batch(victims)
            out.extend(victims)
        for b in out:
            self._blocks[b].ref_count = 1
        return out

    def acquire_cached(self, block_id: int) -> None:
        """Take a reference on a committed block found via match_prefix."""
        info = self._blocks[block_id]
        if info.ref_count == 0:
            self._evictable.pop(block_id, None)
        info.ref_count += 1

    def free(self, block_ids: Sequence[int]) -> None:
        for b in block_ids:
            info = self._blocks[b]
            info.ref_count -= 1
            assert info.ref_count >= 0, f"double free of block {b}"
            if info.ref_count == 0:
                if info.hash is not None:
                    self._evictable[b] = None  # keep cached, evictable
                else:
                    self._free.append(b)

    # --------------------------------------------------------- prefix cache

    def commit_block(self, block_id: int, block_hash: bytes) -> None:
        """Register a now-full block under its chained hash. If the hash is
        already cached by another block, the new block stays uncommitted
        (duplicate content; dedup happens on the next match)."""
        if block_hash in self._hash_to_block:
            return
        info = self._blocks[block_id]
        if info.hash is not None:
            return
        info.hash = block_hash
        self._hash_to_block[block_hash] = block_id
        with self._ev_mu:
            self._stored.add(block_hash)
            self._removed.discard(block_hash)
            # Re-promotion: an offloaded block recommitted to HBM (host
            # re-import or recompute) moves the index entry back to the hot
            # tier.
            self._offloaded.pop(block_hash, None)

    def lookup_hash(self, block_hash: bytes) -> Optional[int]:
        """Block id currently committed under this hash, if any."""
        return self._hash_to_block.get(block_hash)

    def committed_hashes(self) -> List[bytes]:
        """Every committed hash (reconcile manifests / cache resync).
        Racy off-thread read by design — callers tolerate one-beat drift;
        the retry only guards resize-during-iteration."""
        for _ in range(3):
            try:
                return list(self._hash_to_block)
            except RuntimeError:
                continue
        return []

    def match_prefix(
        self,
        token_ids: Sequence[int],
        hashes: Optional[List[bytes]] = None,
    ) -> Tuple[int, List[int]]:
        """Longest cached prefix: returns (num_cached_tokens, block_ids) and
        takes a reference on each matched block (same walk as the service's
        GlobalKVCacheMgr.match — global_kvcache_mgr.cpp:73-131). Pass
        `hashes` when the caller already computed the chain (the engine's
        host-tier continuation reuses it)."""
        if hashes is None:
            hashes = prefix_block_hashes(token_ids, self.block_size, self.seed)
        matched: List[int] = []
        for h in hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            matched.append(b)
        for b in matched:
            self.acquire_cached(b)
        return len(matched) * self.block_size, matched

    # ------------------------------------------------------------ heartbeat

    def record_tier_offload(self, block_hash: bytes, tier: str) -> None:
        """A colder tier (dram->ssd demotion) now holds this hash. No-op if
        HBM still holds it — the hot location stays authoritative."""
        with self._ev_mu:
            if block_hash in self._hash_to_block:
                return
            self._offloaded[block_hash] = tier
            self._removed.discard(block_hash)
            self._stored.discard(block_hash)

    def record_host_removed(self, block_hash: bytes) -> None:
        """The host tier dropped this hash. Only emit a removal if NO tier
        still holds it (an HBM re-promotion must not be un-indexed)."""
        with self._ev_mu:
            self._offloaded.pop(block_hash, None)
            if block_hash not in self._hash_to_block:
                self._removed.add(block_hash)
                self._stored.discard(block_hash)

    def take_cache_event(self) -> KvCacheEvent:
        """Drain accumulated deltas for the next heartbeat (called from the
        heartbeat thread — atomic swap under the event lock)."""
        with self._ev_mu:
            ev = KvCacheEvent(
                stored_cache=self._stored,
                removed_cache=self._removed,
                offload_cache=self._offloaded,
            )
            self._stored = set()
            self._removed = set()
            self._offloaded = {}
        return ev
