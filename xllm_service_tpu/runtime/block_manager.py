"""Paged KV-cache block manager with content-addressed prefix caching.

Engine-tier counterpart of the service's global cache index: allocates
fixed-size token blocks, commits full blocks under their chained murmur3
hash (common/hashing.py — the cross-tier invariant), serves intra-instance
prefix-cache hits, evicts LRU, and accumulates the stored/removed deltas
that the heartbeat reports as a KvCacheEvent
(reference contract: proto/xllm_rpc_service.proto:44-48;
global_kvcache_mgr.cpp:177-225 consumes these on the service side).

Block 0 is reserved as the garbage slot for masked scatter writes
(models/llama.py) and is never allocated.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import KvCacheEvent


class OutOfBlocksError(RuntimeError):
    pass


@dataclass
class _BlockInfo:
    ref_count: int = 0
    hash: Optional[bytes] = None  # set once the block is full + committed


class BlockManager:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        seed: int = 1024,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.seed = seed
        self._blocks: Dict[int, _BlockInfo] = {
            i: _BlockInfo() for i in range(1, num_blocks)
        }
        self._free: List[int] = list(range(1, num_blocks))
        # hash -> block_id for committed blocks (both live and evictable).
        self._hash_to_block: Dict[bytes, int] = {}
        # Evictable committed blocks in LRU order: block_id -> None.
        self._evictable: OrderedDict[int, None] = OrderedDict()
        # Heartbeat deltas.
        self._stored: Set[bytes] = set()
        self._removed: Set[bytes] = set()

    # ------------------------------------------------------------------ util

    @property
    def num_free_blocks(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def usage(self) -> float:
        total = self.num_blocks - 1
        return (total - self.num_free_blocks) / max(total, 1)

    def can_allocate(self, n: int) -> bool:
        return self.num_free_blocks >= n

    # ------------------------------------------------------------- allocate

    def _pop_free_block(self) -> int:
        if self._free:
            return self._free.pop()
        if self._evictable:
            victim, _ = self._evictable.popitem(last=False)  # LRU
            info = self._blocks[victim]
            if info.hash is not None:
                del self._hash_to_block[info.hash]
                self._removed.add(info.hash)
                self._stored.discard(info.hash)
                info.hash = None
            return victim
        raise OutOfBlocksError("KV cache exhausted")

    def allocate(self, n: int) -> List[int]:
        if not self.can_allocate(n):
            raise OutOfBlocksError(
                f"need {n} blocks, only {self.num_free_blocks} free"
            )
        out = []
        for _ in range(n):
            b = self._pop_free_block()
            self._blocks[b].ref_count = 1
            out.append(b)
        return out

    def acquire_cached(self, block_id: int) -> None:
        """Take a reference on a committed block found via match_prefix."""
        info = self._blocks[block_id]
        if info.ref_count == 0:
            self._evictable.pop(block_id, None)
        info.ref_count += 1

    def free(self, block_ids: Sequence[int]) -> None:
        for b in block_ids:
            info = self._blocks[b]
            info.ref_count -= 1
            assert info.ref_count >= 0, f"double free of block {b}"
            if info.ref_count == 0:
                if info.hash is not None:
                    self._evictable[b] = None  # keep cached, evictable
                else:
                    self._free.append(b)

    # --------------------------------------------------------- prefix cache

    def commit_block(self, block_id: int, block_hash: bytes) -> None:
        """Register a now-full block under its chained hash. If the hash is
        already cached by another block, the new block stays uncommitted
        (duplicate content; dedup happens on the next match)."""
        if block_hash in self._hash_to_block:
            return
        info = self._blocks[block_id]
        if info.hash is not None:
            return
        info.hash = block_hash
        self._hash_to_block[block_hash] = block_id
        self._stored.add(block_hash)
        self._removed.discard(block_hash)

    def lookup_hash(self, block_hash: bytes) -> Optional[int]:
        """Block id currently committed under this hash, if any."""
        return self._hash_to_block.get(block_hash)

    def match_prefix(self, token_ids: Sequence[int]) -> Tuple[int, List[int]]:
        """Longest cached prefix: returns (num_cached_tokens, block_ids) and
        takes a reference on each matched block (same walk as the service's
        GlobalKVCacheMgr.match — global_kvcache_mgr.cpp:73-131)."""
        hashes = prefix_block_hashes(token_ids, self.block_size, self.seed)
        matched: List[int] = []
        for h in hashes:
            b = self._hash_to_block.get(h)
            if b is None:
                break
            matched.append(b)
        for b in matched:
            self.acquire_cached(b)
        return len(matched) * self.block_size, matched

    # ------------------------------------------------------------ heartbeat

    def take_cache_event(self) -> KvCacheEvent:
        """Drain accumulated deltas for the next heartbeat."""
        ev = KvCacheEvent(stored_cache=self._stored, removed_cache=self._removed)
        self._stored = set()
        self._removed = set()
        return ev
