"""Encoder-stage executor + engine shim (EPD stage E).

Runs the vision encoder (models/vision.py) behind the same instance
lifecycle the LM engines use: the ENCODE instance registers with the
master, heartbeats load metrics, and serves `/encode` — media parts in,
LM-ready embedding tokens out, pushed to the prefill peer's `/mm/import`.

TPU design: image batches are bucketed to powers of two and encoded in one
jitted call; weights stay resident.

Encoder fabric (docs/EPD.md): with `enable_encoder_fabric` on, the engine
grows two serving-tier mechanisms the EPD paper (arXiv 2501.05460) scales
with —

  * a **cross-request micro-batcher**: `/encode` handlers submit media
    items into one admission queue; a batcher thread coalesces same-kind
    same-shape items from DIFFERENT requests into one tower dispatch,
    bounded by a deadline (encoder_batch_window_ms) and a pow2 size cap
    (encoder_batch_max — the towers pad batches to pow2, so the cap
    clamps to a power of two and a full window never pads);
  * a **media-hash-keyed embedding LRU**: items keyed by their front-door
    content hash resolve from cache without a tower dispatch; insertions
    and evictions ride heartbeats as KvCacheEvent deltas into the
    master's fleet embedding index (cluster/encoder_fabric.py), with the
    full-snapshot resync contract the prefix fabric hardened.

The legacy per-request `encode`/`encode_video`/`encode_audio` entry
points are untouched — they ARE the `XLLM_ENCODER_FABRIC=0` path.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.common.types import (
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
)
from xllm_service_tpu.models import vision


def _load_or_init_tower(kind: str, model: str, dtype: str,
                        init_seed: int, checkpoint_path: str,
                        loader, get_config, init_params):
    """Shared load-or-init for encoder towers: a set-but-broken
    checkpoint path fails LOUDLY (same contract as the LM executor),
    never silently serving random-init embeddings. Returns
    (jnp_dtype, cfg, params)."""
    import os

    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    if checkpoint_path:
        if not os.path.exists(
            os.path.join(checkpoint_path, "config.json")
        ):
            raise FileNotFoundError(
                f"{kind} checkpoint dir {checkpoint_path!r} has no "
                f"config.json"
            )
        cfg, params = loader(checkpoint_path, dtype=jdtype)
    else:
        cfg = get_config(model)
        params = init_params(cfg, jax.random.key(init_seed), jdtype)
    return jdtype, cfg, params


class VisionExecutor:
    def __init__(self, model: str = "vit-tiny", dtype: str = "float32",
                 init_seed: int = 0, checkpoint_path: str = ""):
        from xllm_service_tpu.runtime.weights import load_vision_checkpoint

        self.dtype, self.cfg, self.params = _load_or_init_tower(
            "vision", model, dtype, init_seed, checkpoint_path,
            load_vision_checkpoint, vision.get_vision_config,
            vision.init_vision_params,
        )
        self._jit = jax.jit(
            lambda p, imgs: vision.encode_images(p, self.cfg, imgs)
        )
        self._video_jit = jax.jit(
            lambda p, frames: vision.encode_video(p, self.cfg, frames)
        )

    @staticmethod
    def _pow2(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def encode(self, images: np.ndarray) -> np.ndarray:
        """[B, S, S, 3] float32 in [0,1] -> [B, out_tokens, out_dim]."""
        B = images.shape[0]
        P = self._pow2(max(B, 1))
        if P != B:
            images = np.concatenate(
                [images, np.zeros((P - B, *images.shape[1:]), images.dtype)]
            )
        out = self._jit(self.params, jnp.asarray(images, jnp.float32))
        return np.asarray(out[:B], np.float32)

    def encode_video(self, frames: np.ndarray) -> np.ndarray:
        """[T, S, S, 3] float32 video frames -> flat media tokens
        [T//tps * tokens_per_slice, out_dim] (qwen2vl tower; per-slice
        attention — models/vision.encode_video). Frame counts bucket to
        the next multiple of 2*tps by repeating the LAST frame (the HF
        processor's own pad-to-temporal-patch convention), keeping the
        jit shape set small; padded slices' tokens are sliced off."""
        tps = getattr(self.cfg, "temporal_patch_size", 2)
        T = frames.shape[0]
        want_slices = max((T + tps - 1) // tps, 1)
        bucket = self._pow2(want_slices) * tps
        if bucket != T:
            pad = np.repeat(frames[-1:], bucket - T, axis=0)
            frames = np.concatenate([frames, pad])
        out = self._video_jit(self.params, jnp.asarray(frames, jnp.float32))
        per_slice = out.shape[0] // (bucket // tps)
        return np.asarray(out[: want_slices * per_slice], np.float32)


class AudioExecutor:
    """EPD stage E, audio modality: the Qwen2-Audio tower
    (models/audio.py) behind the same jit-once discipline as the vision
    towers. Input is the service tier's log-mel features
    (service/audio_processor.py); output is LM-ready media tokens."""

    def __init__(self, model: str = "audio-tiny", dtype: str = "float32",
                 init_seed: int = 0, checkpoint_path: str = ""):
        from xllm_service_tpu.models import audio as audio_mod
        from xllm_service_tpu.runtime.weights import load_audio_checkpoint

        self.dtype, self.cfg, self.params = _load_or_init_tower(
            "audio", model, dtype, init_seed, checkpoint_path,
            load_audio_checkpoint, audio_mod.get_audio_config,
            audio_mod.init_audio_params,
        )
        self._jit = jax.jit(
            lambda p, mel: audio_mod.encode_audio(p, self.cfg, mel)
        )

    def encode_audio(self, mel: np.ndarray) -> np.ndarray:
        """[B, M, T] log-mel -> [B, out_tokens, out_dim]."""
        B = mel.shape[0]
        P = VisionExecutor._pow2(max(B, 1))
        if P != B:
            mel = np.concatenate(
                [mel, np.zeros((P - B, *mel.shape[1:]), mel.dtype)]
            )
        out = self._jit(self.params, jnp.asarray(mel, jnp.float32))
        return np.asarray(out[:B], np.float32)


def _is_audio_model(model: str, checkpoint_path: str) -> bool:
    """An ENCODE instance hosts ONE modality: audio iff the model names
    a registered AudioConfig or the checkpoint carries audio_config."""
    import json
    import os

    from xllm_service_tpu.models import audio as audio_mod

    if checkpoint_path:
        cfg_path = os.path.join(checkpoint_path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                return "audio_config" in json.load(f)
    try:
        audio_mod.get_audio_config(model)
        return True
    except KeyError:
        return False


class _EmbeddingLRU:
    """Media-hash-keyed embedding cache (encoder fabric, docs/EPD.md).

    Keys are the 16-byte front-door content digests
    (service/image_processor.media_content_hash); values the LM-ready
    embedding rows ([tokens, D] float32). Insertions/evictions accumulate
    as a KvCacheEvent delta drained by the heartbeat (the master's fleet
    embedding index mirrors this LRU the way the KV index mirrors the
    block pools); `snapshot_event` serves the master-requested resync
    after a breaker ejection pruned this encoder's locations."""

    def __init__(self, capacity: int):
        from collections import OrderedDict

        self.capacity = max(int(capacity), 0)
        self._mu = threading.Lock()
        self._od: "Dict[bytes, np.ndarray]" = OrderedDict()
        self._stored: set = set()
        self._removed: set = set()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: bytes) -> Optional[np.ndarray]:
        with self._mu:
            arr = self._od.get(key)
            if arr is None:
                self.misses += 1
                return None
            self._od.move_to_end(key)
            self.hits += 1
            return arr

    def put(self, key: bytes, arr: np.ndarray) -> None:
        if not self.capacity:
            return
        with self._mu:
            if key in self._od:
                self._od.move_to_end(key)
                return
            self._od[key] = arr
            self._stored.add(key)
            self._removed.discard(key)
            while len(self._od) > self.capacity:
                old, _ = self._od.popitem(last=False)
                self.evictions += 1
                self._removed.add(old)
                self._stored.discard(old)

    def __len__(self) -> int:
        with self._mu:
            return len(self._od)

    def take_event(self) -> KvCacheEvent:
        with self._mu:
            ev = KvCacheEvent(
                stored_cache=set(self._stored),
                removed_cache=set(self._removed),
            )
            self._stored.clear()
            self._removed.clear()
            return ev

    def snapshot_event(self) -> KvCacheEvent:
        with self._mu:
            return KvCacheEvent(stored_cache=set(self._od.keys()))


class _PendingEncode:
    """One media item queued for the micro-batcher: resolves to the
    item's embedding rows (or an error) via `result()`."""

    __slots__ = ("kind", "arr", "key", "_event", "out", "err")

    def __init__(self, kind: str, arr: np.ndarray, key: Optional[bytes]):
        self.kind = kind
        self.arr = arr
        self.key = key
        self._event = threading.Event()
        self.out: Optional[np.ndarray] = None
        self.err: Optional[BaseException] = None

    def resolve(self, out: Optional[np.ndarray],
                err: Optional[BaseException] = None) -> None:
        self.out = out
        self.err = err
        self._event.set()

    def result(self, timeout: float = 300.0) -> np.ndarray:
        if not self._event.wait(timeout):
            raise TimeoutError("encoder micro-batcher timed out")
        if self.err is not None:
            raise self.err
        return self.out


class EncoderEngine:
    """Engine-interface adapter so InstanceServer can host an ENCODE role:
    start/stop, heartbeat metric sources, and the encode entry points.
    Hosts ONE modality executor — vision (image + qwen2vl video) or
    audio — chosen by the model name / checkpoint config. (Tests may
    construct it with BOTH executors to exercise mixed-kind requests.)"""

    def __init__(self, executor: Optional[VisionExecutor] = None,
                 model: str = "vit-tiny", checkpoint_path: str = "",
                 dtype: str = "float32",
                 audio_executor: Optional[AudioExecutor] = None,
                 cfg=None):
        if executor is None and audio_executor is None:
            if _is_audio_model(model, checkpoint_path):
                audio_executor = AudioExecutor(
                    model, dtype=dtype, checkpoint_path=checkpoint_path
                )
            else:
                executor = VisionExecutor(
                    model, dtype=dtype, checkpoint_path=checkpoint_path
                )
        self.executor = executor  # vision; None on audio-only instances
        self.audio_executor = audio_executor
        self._active = 0
        self._mu = threading.Lock()
        self._latency_window: List[Tuple[float, float]] = []

        # Encoder fabric state (docs/EPD.md). cfg is the instance's
        # EngineConfig; direct constructions (tests) get the defaults.
        from xllm_service_tpu.common.config import EngineConfig
        from xllm_service_tpu.obs import MetricsRegistry

        self.cfg = cfg if cfg is not None else EngineConfig(
            model=model, instance_type="ENCODE"
        )
        self._batch_window_s = max(
            float(getattr(self.cfg, "encoder_batch_window_ms", 5.0)), 0.0
        ) / 1000.0
        bmax = max(int(getattr(self.cfg, "encoder_batch_max", 8)), 1)
        # Clamp to a power of two: the towers pad batches UP to pow2, so
        # a full admission window must never pad.
        self._batch_max = 1 << (bmax.bit_length() - 1)
        self.emb_cache = _EmbeddingLRU(
            getattr(self.cfg, "encoder_cache_entries", 256)
        )
        self._admit_q: "queue.Queue[Optional[_PendingEncode]]" = queue.Queue()
        self._batch_thread: Optional[threading.Thread] = None
        self._batch_started = False

        self.metrics = MetricsRegistry()
        self.metrics.gauge(
            "xllm_encoder_queue_depth",
            "Media items waiting in the encoder micro-batcher admission "
            "queue",
        ).set_function(self._admit_q.qsize)
        self._m_batches = self.metrics.counter(
            "xllm_encoder_batches_total",
            "Tower dispatches issued by the encoder micro-batcher",
        )
        self._m_batch_items = self.metrics.counter(
            "xllm_encoder_batched_items_total",
            "Media items served by micro-batcher tower dispatches",
        )
        self._m_occupancy = self.metrics.histogram(
            "xllm_encoder_batch_occupancy",
            "Media items coalesced per micro-batcher tower dispatch "
            "(cross-request batching; 1 = no coalescing)",
            buckets=(1, 2, 4, 8, 16, 32, 64),
        )
        self.metrics.counter(
            "xllm_encoder_cache_hits_total",
            "Media items resolved from the encoder-local embedding cache "
            "(tower dispatch skipped)",
        ).set_function(lambda: self.emb_cache.hits)
        self.metrics.counter(
            "xllm_encoder_cache_misses_total",
            "Media items that missed the encoder-local embedding cache",
        ).set_function(lambda: self.emb_cache.misses)
        self.metrics.counter(
            "xllm_encoder_cache_evictions_total",
            "Embedding-cache LRU evictions (heartbeat deltas retract the "
            "fleet-index locations)",
        ).set_function(lambda: self.emb_cache.evictions)

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if not self._batch_started:
            self._batch_started = True
            # Fresh thread each start: a stopped engine restarted by a
            # late encode_media must not re-start a dead Thread object.
            self._batch_thread = threading.Thread(
                target=self._batch_loop, name="encoder-batcher", daemon=True
            )
            self._batch_thread.start()

    def stop(self) -> None:
        if self._batch_started:
            self._batch_started = False
            self._admit_q.put(None)
            if self._batch_thread is not None:
                self._batch_thread.join(timeout=5.0)

    # -- heartbeat sources ---------------------------------------------
    def get_load_metrics(self) -> LoadMetrics:
        with self._mu:
            active = self._active
        return LoadMetrics(
            waiting_requests_num=active + self._admit_q.qsize(),
            gpu_cache_usage_perc=0.0,
        )

    def get_latency_metrics(self, window_s: float = 30.0) -> LatencyMetrics:
        now = time.monotonic()
        with self._mu:
            self._latency_window = [
                (t, ms) for t, ms in self._latency_window
                if now - t <= window_s
            ]
            mx = max((ms for _, ms in self._latency_window), default=0)
        return LatencyMetrics(recent_max_ttft=int(mx), recent_max_tbt=0)

    def take_cache_event(self) -> KvCacheEvent:
        """Heartbeat delta: embedding-LRU insertions/evictions since the
        last beat (media content hashes). The master folds these into its
        fleet embedding index (cluster/encoder_fabric.py)."""
        return self.emb_cache.take_event()

    def cache_snapshot_event(self) -> KvCacheEvent:
        """Full embedding-LRU snapshot for a master-requested resync
        (breaker ejection pruned this encoder's index locations; deltas
        alone cannot rebuild them — docs/KV_CACHE.md contract)."""
        return self.emb_cache.snapshot_event()

    def profiling_data(self):
        return [], []

    # -- work -----------------------------------------------------------
    def _timed(self, fn, arg: np.ndarray) -> np.ndarray:
        """Shared active-count + latency-window accounting for both
        encode paths (one place to change — review finding, r5)."""
        with self._mu:
            self._active += 1
        t0 = time.monotonic()
        try:
            return fn(arg)
        finally:
            ms = (time.monotonic() - t0) * 1000
            with self._mu:
                self._active -= 1
                self._latency_window.append((time.monotonic(), ms))

    def encode(self, images: np.ndarray) -> np.ndarray:
        return self._timed(self.executor.encode, images)

    def encode_video(self, frames: np.ndarray) -> np.ndarray:
        return self._timed(self.executor.encode_video, frames)

    def encode_audio(self, mel: np.ndarray) -> np.ndarray:
        return self._timed(self.audio_executor.encode_audio, mel)

    # -- encoder fabric: cache + cross-request micro-batcher -----------

    def encode_media_submit(
        self, kind: str, arr: np.ndarray, key: Optional[bytes] = None
    ) -> _PendingEncode:
        """Fabric entry point for ONE media item (kind: img|video|audio).
        Checks the embedding LRU first (a hit resolves immediately —
        re-sent media skips the tower); misses join the admission queue
        where the batcher coalesces same-kind/-shape items from OTHER
        requests into one tower dispatch. Non-blocking: callers submit
        every item of a request before waiting, so a multi-item request
        batches against itself too."""
        p = _PendingEncode(kind, arr, key)
        if key is not None:
            cached = self.emb_cache.get(key)
            if cached is not None:
                p.resolve(cached)
                return p
        if not self._batch_started:
            self.start()  # direct constructions (tests) skip start()
        self._admit_q.put(p)
        return p

    def encode_media(
        self, kind: str, arr: np.ndarray, key: Optional[bytes] = None,
        timeout: float = 300.0,
    ) -> np.ndarray:
        return self.encode_media_submit(kind, arr, key).result(timeout)

    def _batch_loop(self) -> None:
        while True:
            item = self._admit_q.get()
            if item is None:
                return
            batch = [item]
            deadline = time.monotonic() + self._batch_window_s
            while len(batch) < self._batch_max:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    # Deadline-bounded: whatever coalesced, dispatches.
                    break
                try:
                    nxt = self._admit_q.get(timeout=timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._admit_q.put(None)  # re-post the stop sentinel
                    break
                batch.append(nxt)
            self._dispatch_batch(batch)

    def _dispatch_batch(self, batch: List[_PendingEncode]) -> None:
        """One gathered admission window: group by (kind, shape) — only
        identical geometries stack — dedup identical content keys inside
        a group (two requests racing the same image encode once), then
        one tower dispatch per stackable group; videos dispatch per item
        (their token count varies with frame count)."""
        groups: Dict[tuple, List[_PendingEncode]] = {}
        for p in batch:
            groups.setdefault((p.kind, tuple(p.arr.shape)), []).append(p)
        for (kind, _shape), group in groups.items():
            try:
                if kind == "video":
                    for p in group:
                        out = self._timed(self.executor.encode_video, p.arr)
                        self._finish_item(p, out, [p])
                        self._m_batches.inc()
                        self._m_batch_items.inc()
                        self._m_occupancy.observe(1)
                    continue
                uniq: Dict[object, List[_PendingEncode]] = {}
                for p in group:
                    uniq.setdefault(
                        p.key if p.key is not None else id(p), []
                    ).append(p)
                fn = (
                    self.encode_audio if kind == "audio" else self.encode
                )
                stacked = np.stack([ps[0].arr for ps in uniq.values()])
                out = fn(stacked)  # [U, tokens, D]
                for row, ps in zip(out, uniq.values()):
                    self._finish_item(ps[0], row, ps)
                self._m_batches.inc()
                self._m_batch_items.inc(len(group))
                self._m_occupancy.observe(len(group))
            except BaseException as e:  # noqa: BLE001 — resolve waiters
                for p in group:
                    if not p._event.is_set():
                        p.resolve(None, e)

    def _finish_item(
        self, first: _PendingEncode, out: np.ndarray,
        waiters: List[_PendingEncode],
    ) -> None:
        out = np.asarray(out, np.float32)
        if first.key is not None:
            self.emb_cache.put(first.key, out)
        for p in waiters:
            p.resolve(out)
