"""Encoder-stage executor + engine shim (EPD stage E).

Runs the vision encoder (models/vision.py) behind the same instance
lifecycle the LM engines use: the ENCODE instance registers with the
master, heartbeats load metrics, and serves `/encode` — media parts in,
LM-ready embedding tokens out, pushed to the prefill peer's `/mm/import`.

TPU design: image batches are bucketed to powers of two and encoded in one
jitted call; weights stay resident.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from xllm_service_tpu.common.types import (
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
)
from xllm_service_tpu.models import vision


def _load_or_init_tower(kind: str, model: str, dtype: str,
                        init_seed: int, checkpoint_path: str,
                        loader, get_config, init_params):
    """Shared load-or-init for encoder towers: a set-but-broken
    checkpoint path fails LOUDLY (same contract as the LM executor),
    never silently serving random-init embeddings. Returns
    (jnp_dtype, cfg, params)."""
    import os

    jdtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    if checkpoint_path:
        if not os.path.exists(
            os.path.join(checkpoint_path, "config.json")
        ):
            raise FileNotFoundError(
                f"{kind} checkpoint dir {checkpoint_path!r} has no "
                f"config.json"
            )
        cfg, params = loader(checkpoint_path, dtype=jdtype)
    else:
        cfg = get_config(model)
        params = init_params(cfg, jax.random.key(init_seed), jdtype)
    return jdtype, cfg, params


class VisionExecutor:
    def __init__(self, model: str = "vit-tiny", dtype: str = "float32",
                 init_seed: int = 0, checkpoint_path: str = ""):
        from xllm_service_tpu.runtime.weights import load_vision_checkpoint

        self.dtype, self.cfg, self.params = _load_or_init_tower(
            "vision", model, dtype, init_seed, checkpoint_path,
            load_vision_checkpoint, vision.get_vision_config,
            vision.init_vision_params,
        )
        self._jit = jax.jit(
            lambda p, imgs: vision.encode_images(p, self.cfg, imgs)
        )
        self._video_jit = jax.jit(
            lambda p, frames: vision.encode_video(p, self.cfg, frames)
        )

    @staticmethod
    def _pow2(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def encode(self, images: np.ndarray) -> np.ndarray:
        """[B, S, S, 3] float32 in [0,1] -> [B, out_tokens, out_dim]."""
        B = images.shape[0]
        P = self._pow2(max(B, 1))
        if P != B:
            images = np.concatenate(
                [images, np.zeros((P - B, *images.shape[1:]), images.dtype)]
            )
        out = self._jit(self.params, jnp.asarray(images, jnp.float32))
        return np.asarray(out[:B], np.float32)

    def encode_video(self, frames: np.ndarray) -> np.ndarray:
        """[T, S, S, 3] float32 video frames -> flat media tokens
        [T//tps * tokens_per_slice, out_dim] (qwen2vl tower; per-slice
        attention — models/vision.encode_video). Frame counts bucket to
        the next multiple of 2*tps by repeating the LAST frame (the HF
        processor's own pad-to-temporal-patch convention), keeping the
        jit shape set small; padded slices' tokens are sliced off."""
        tps = getattr(self.cfg, "temporal_patch_size", 2)
        T = frames.shape[0]
        want_slices = max((T + tps - 1) // tps, 1)
        bucket = self._pow2(want_slices) * tps
        if bucket != T:
            pad = np.repeat(frames[-1:], bucket - T, axis=0)
            frames = np.concatenate([frames, pad])
        out = self._video_jit(self.params, jnp.asarray(frames, jnp.float32))
        per_slice = out.shape[0] // (bucket // tps)
        return np.asarray(out[: want_slices * per_slice], np.float32)


class AudioExecutor:
    """EPD stage E, audio modality: the Qwen2-Audio tower
    (models/audio.py) behind the same jit-once discipline as the vision
    towers. Input is the service tier's log-mel features
    (service/audio_processor.py); output is LM-ready media tokens."""

    def __init__(self, model: str = "audio-tiny", dtype: str = "float32",
                 init_seed: int = 0, checkpoint_path: str = ""):
        from xllm_service_tpu.models import audio as audio_mod
        from xllm_service_tpu.runtime.weights import load_audio_checkpoint

        self.dtype, self.cfg, self.params = _load_or_init_tower(
            "audio", model, dtype, init_seed, checkpoint_path,
            load_audio_checkpoint, audio_mod.get_audio_config,
            audio_mod.init_audio_params,
        )
        self._jit = jax.jit(
            lambda p, mel: audio_mod.encode_audio(p, self.cfg, mel)
        )

    def encode_audio(self, mel: np.ndarray) -> np.ndarray:
        """[B, M, T] log-mel -> [B, out_tokens, out_dim]."""
        B = mel.shape[0]
        P = VisionExecutor._pow2(max(B, 1))
        if P != B:
            mel = np.concatenate(
                [mel, np.zeros((P - B, *mel.shape[1:]), mel.dtype)]
            )
        out = self._jit(self.params, jnp.asarray(mel, jnp.float32))
        return np.asarray(out[:B], np.float32)


def _is_audio_model(model: str, checkpoint_path: str) -> bool:
    """An ENCODE instance hosts ONE modality: audio iff the model names
    a registered AudioConfig or the checkpoint carries audio_config."""
    import json
    import os

    from xllm_service_tpu.models import audio as audio_mod

    if checkpoint_path:
        cfg_path = os.path.join(checkpoint_path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                return "audio_config" in json.load(f)
    try:
        audio_mod.get_audio_config(model)
        return True
    except KeyError:
        return False


class EncoderEngine:
    """Engine-interface adapter so InstanceServer can host an ENCODE role:
    start/stop, heartbeat metric sources, and the encode entry points.
    Hosts ONE modality executor — vision (image + qwen2vl video) or
    audio — chosen by the model name / checkpoint config."""

    def __init__(self, executor: Optional[VisionExecutor] = None,
                 model: str = "vit-tiny", checkpoint_path: str = "",
                 dtype: str = "float32",
                 audio_executor: Optional[AudioExecutor] = None):
        if executor is None and audio_executor is None:
            if _is_audio_model(model, checkpoint_path):
                audio_executor = AudioExecutor(
                    model, dtype=dtype, checkpoint_path=checkpoint_path
                )
            else:
                executor = VisionExecutor(
                    model, dtype=dtype, checkpoint_path=checkpoint_path
                )
        self.executor = executor  # vision; None on audio-only instances
        self.audio_executor = audio_executor
        self._active = 0
        self._mu = threading.Lock()
        self._latency_window: List[Tuple[float, float]] = []

    # -- lifecycle ------------------------------------------------------
    def start(self) -> None:
        pass

    def stop(self) -> None:
        pass

    # -- heartbeat sources ---------------------------------------------
    def get_load_metrics(self) -> LoadMetrics:
        with self._mu:
            return LoadMetrics(
                waiting_requests_num=self._active, gpu_cache_usage_perc=0.0
            )

    def get_latency_metrics(self, window_s: float = 30.0) -> LatencyMetrics:
        now = time.monotonic()
        with self._mu:
            self._latency_window = [
                (t, ms) for t, ms in self._latency_window
                if now - t <= window_s
            ]
            mx = max((ms for _, ms in self._latency_window), default=0)
        return LatencyMetrics(recent_max_ttft=int(mx), recent_max_tbt=0)

    def take_cache_event(self) -> KvCacheEvent:
        return KvCacheEvent()

    def profiling_data(self):
        return [], []

    # -- work -----------------------------------------------------------
    def _timed(self, fn, arg: np.ndarray) -> np.ndarray:
        """Shared active-count + latency-window accounting for both
        encode paths (one place to change — review finding, r5)."""
        with self._mu:
            self._active += 1
        t0 = time.monotonic()
        try:
            return fn(arg)
        finally:
            ms = (time.monotonic() - t0) * 1000
            with self._mu:
                self._active -= 1
                self._latency_window.append((time.monotonic(), ms))

    def encode(self, images: np.ndarray) -> np.ndarray:
        return self._timed(self.executor.encode, images)

    def encode_video(self, frames: np.ndarray) -> np.ndarray:
        return self._timed(self.executor.encode_video, frames)

    def encode_audio(self, mel: np.ndarray) -> np.ndarray:
        return self._timed(self.audio_executor.encode_audio, mel)
