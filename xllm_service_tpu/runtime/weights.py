"""Checkpoint loading: HuggingFace safetensors → the stacked-layer pytree.

Engine-tier component. The reference's engine (the absent xLLM submodule —
SURVEY.md §2.3) loads real HF checkpoints and relays `model_name` in
InstanceMetaInfo (reference xllm_service/common/types.h:169-171 analog);
here the executor (runtime/executor.py) calls `load_checkpoint` when
`EngineConfig.checkpoint_path` is set.

Design:
  * Self-contained safetensors parser (the format: u64 header length +
    JSON header + raw little-endian tensor data). mmap'd reads — no copy
    until the dtype cast — and bfloat16 via ml_dtypes, which the
    `safetensors` pip package's numpy API can't always represent.
  * HF Llama/Qwen2/Mixtral name mapping → per-layer tensors STACKED on a
    leading layer axis (models/llama.py contract). torch `nn.Linear`
    stores [out, in]; our einsum contracts [in, out], so every projection
    transposes on load.
  * RoPE: ops/rope.py applies split-half rotation — the same convention HF
    checkpoints are stored in — so q/k weights load with NO head
    permutation (only the transpose).
  * Each stacked leaf is `jax.device_put` with its NamedSharding from
    parallel/sharding.py, so a tp>1 mesh receives only its shard per
    device; host RAM briefly holds the full stacked array per leaf.
  * `save_hf_checkpoint` writes the inverse mapping (HF names, HF layouts)
    — round-trip tested in tests/test_weights.py and usable for exporting.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import re
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

from xllm_service_tpu.models.configs import ModelConfig

Params = Dict[str, Any]

_ST_DTYPES = {
    "F64": np.float64,
    "F32": np.float32,
    "F16": np.float16,
    "BF16": ml_dtypes.bfloat16,
    "I64": np.int64,
    "I32": np.int32,
    "I16": np.int16,
    "I8": np.int8,
    "U8": np.uint8,
    "BOOL": np.bool_,
}
_ST_NAMES = {np.dtype(v): k for k, v in _ST_DTYPES.items()}


# ------------------------------------------------------------- safetensors IO


def read_safetensors(path: str) -> Iterator[Tuple[str, np.ndarray]]:
    """Yield (name, array) from one .safetensors file, zero-copy via mmap.

    Arrays are views into the mapping — cast or copy before the file goes
    away (load_checkpoint always casts into the staging buffer).
    """
    with open(path, "rb") as f:
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen))
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
        base = 8 + hlen
        for name, meta in header.items():
            if name == "__metadata__":
                continue
            dtype = _ST_DTYPES[meta["dtype"]]
            begin, end = meta["data_offsets"]
            arr = np.frombuffer(
                mm, dtype=dtype, count=int(np.prod(meta["shape"], dtype=np.int64)),
                offset=base + begin,
            ).reshape(meta["shape"])
            assert arr.nbytes == end - begin, f"{name}: size mismatch"
            yield name, arr


def write_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    header: Dict[str, Any] = {}
    offset = 0
    arrays = {}
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        arrays[name] = arr
        header[name] = {
            "dtype": _ST_NAMES[arr.dtype],
            "shape": list(arr.shape),
            "data_offsets": [offset, offset + arr.nbytes],
        }
        offset += arr.nbytes
    blob = json.dumps(header).encode()
    # Pad header to 8-byte alignment (spec allows trailing spaces).
    blob += b" " * (-len(blob) % 8)
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(blob)))
        f.write(blob)
        for arr in arrays.values():
            f.write(arr.tobytes())


def _shard_files(path: str) -> list:
    """All .safetensors files of a checkpoint dir, index-aware."""
    index = os.path.join(path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(path, v) for v in weight_map.values()})
    files = sorted(
        os.path.join(path, f)
        for f in os.listdir(path)
        if f.endswith(".safetensors")
    )
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    return files


# ----------------------------------------------------------------- HF config


def _hf_sliding_window(hf: dict) -> int:
    """SWA window from an HF config, honoring the gates HF applies.

    Qwen2/Qwen3 configs carry a sliding_window VALUE but disable it via
    use_sliding_window=false. HF's max_window_layers semantics (Qwen2
    modeling: layer i slides iff i >= max_window_layers, i.e. the FIRST
    mwl layers use full attention): mwl == 0 means every layer slides —
    exactly our uniform-window stack; mwl >= num_layers means zero SWA
    layers — full attention, exactly HF. A genuinely MIXED stack
    (0 < mwl < num_layers with use_sliding_window=true) can't be
    represented by the scanned uniform layers and serving it as full
    attention would diverge from HF beyond the window — fail LOUDLY
    instead (same principle as the unsupported-rope_scaling reject)."""
    window = int(hf.get("sliding_window") or 0)
    if not window:
        return 0
    if not hf.get("use_sliding_window", True):
        return 0
    mwl = hf.get("max_window_layers")
    if mwl is None or int(mwl) == 0:
        return window
    if int(mwl) >= int(hf["num_hidden_layers"]):
        return 0
    raise NotImplementedError(
        f"mixed sliding-window stack (max_window_layers={mwl} of "
        f"{hf['num_hidden_layers']} layers, use_sliding_window=true) is "
        "not representable by the uniform scanned stack; refusing to "
        "serve it as full attention"
    )


def _hf_rope_scaling(hf: dict) -> dict:
    """ModelConfig rope_scaling_* fields from an HF config dict.

    Implemented types (ops/rope.rope_parameters does the math): linear,
    dynamic NTK, llama3 (Llama-3.1/3.2), longrope (Phi-3, incl. the older
    "su" spelling), and yarn (real DeepSeek-V2/V3, incl. their
    mscale/mscale_all_dim attention scaling). "default"/mrope-only
    entries are no-ops. ANY other type raises — the one silent failure
    mode this loader refuses is a checkpoint that loads cleanly and
    serves diverging logits."""
    rs = hf.get("rope_scaling")
    if not rs or rs.get("mrope_section"):
        # mrope_section-only configs (Qwen2-VL) declare type "default"/
        # "mrope" — M-RoPE is handled by the _mrope_section path.
        return {}
    rtype = str(rs.get("rope_type") or rs.get("type") or "default")
    if rtype == "default":
        return {}
    if rtype == "linear":
        return dict(
            rope_scaling_type="linear",
            rope_scaling_factor=float(rs["factor"]),
        )
    if rtype == "dynamic":
        return dict(
            rope_scaling_type="dynamic",
            rope_scaling_factor=float(rs["factor"]),
            rope_original_max_position=int(
                rs.get("original_max_position_embeddings") or 0
            ),
        )
    if rtype == "llama3":
        return dict(
            rope_scaling_type="llama3",
            rope_scaling_factor=float(rs["factor"]),
            rope_low_freq_factor=float(rs["low_freq_factor"]),
            rope_high_freq_factor=float(rs["high_freq_factor"]),
            rope_original_max_position=int(
                rs["original_max_position_embeddings"]
            ),
        )
    if rtype == "yarn":
        return dict(
            rope_scaling_type="yarn",
            rope_scaling_factor=float(rs["factor"]),
            rope_original_max_position=int(
                rs.get("original_max_position_embeddings") or 0
            ),
            rope_beta_fast=float(rs.get("beta_fast") or 32.0),
            rope_beta_slow=float(rs.get("beta_slow") or 1.0),
            rope_mscale=float(rs.get("mscale") or 0.0),
            rope_mscale_all_dim=float(rs.get("mscale_all_dim") or 0.0),
            rope_attention_factor=float(rs.get("attention_factor") or 0.0),
            rope_scaling_truncate=bool(rs.get("truncate", True)),
        )
    if rtype in ("longrope", "su"):
        # Phi-3 keeps original_max_position_embeddings at the TOP level
        # of config.json; newer HF layouts put it inside rope_scaling.
        orig = int(
            rs.get("original_max_position_embeddings")
            or hf.get("original_max_position_embeddings")
            or 0
        )
        if not orig:
            raise ValueError(
                "longrope rope_scaling needs original_max_position_"
                "embeddings (in rope_scaling or at the config top level)"
            )
        return dict(
            rope_scaling_type="longrope",
            rope_short_factor=tuple(
                float(v) for v in rs["short_factor"]
            ),
            rope_long_factor=tuple(float(v) for v in rs["long_factor"]),
            rope_original_max_position=orig,
            rope_attention_factor=float(rs.get("attention_factor") or 0.0),
        )
    raise NotImplementedError(
        f"rope_scaling type {rtype!r} is not supported (implemented: "
        "linear, dynamic, llama3, longrope, yarn); refusing to load a "
        "checkpoint that would serve silently diverging logits"
    )


def config_from_hf(path: str, name: Optional[str] = None) -> ModelConfig:
    """Build a ModelConfig from an HF checkpoint dir's config.json.

    Covers the registered families: Llama (LlamaForCausalLM), Qwen2
    (Qwen2ForCausalLM: adds QKV bias), Mixtral (MixtralForCausalLM: MoE).
    """
    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    archs = hf.get("architectures") or ["LlamaForCausalLM"]
    arch = archs[0]
    if arch in (
        "Qwen2VLForConditionalGeneration",
        "Qwen2_5_VLForConditionalGeneration",
    ):
        # Qwen2-VL / Qwen2.5-VL: the text tower is a plain Qwen2 stack
        # (the `visual.*` tensors load separately via
        # load_vision_checkpoint); newer HF configs nest the text fields
        # under text_config. mrope_section feeds the full M-RoPE path
        # (ops/rope.apply_mrope + engine position streams).
        hf = {**hf, **(hf.get("text_config") or {})}
        arch = "Qwen2ForCausalLM"
        rs = hf.get("rope_scaling") or {}
        if rs.get("mrope_section"):
            hf["_mrope_section"] = tuple(int(v) for v in rs["mrope_section"])
    num_heads = hf["num_attention_heads"]
    head_dim = hf.get("head_dim") or hf["hidden_size"] // num_heads
    common = dict(
        name=name or hf.get("model_type", "hf-model"),
        vocab_size=hf["vocab_size"],
        hidden_size=hf["hidden_size"],
        intermediate_size=hf["intermediate_size"],
        num_layers=hf["num_hidden_layers"],
        num_heads=num_heads,
        num_kv_heads=hf.get("num_key_value_heads", num_heads),
        head_dim=head_dim,
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        max_position_embeddings=hf.get("max_position_embeddings", 8192),
        tie_word_embeddings=bool(hf.get("tie_word_embeddings", False)),
        sliding_window=_hf_sliding_window(hf),
        mrope_section=tuple(hf.get("_mrope_section") or ()),
    )
    common.update(_hf_rope_scaling(hf))
    if arch == "GemmaForCausalLM":
        # Gemma: Llama tensor layout + GELU-tanh gated MLP, sqrt(E)
        # embedding scale, zero-centered RMSNorm weights (the loader
        # adds 1 below so ops/norms.rms_norm stays uniform). Real Gemma
        # config.json files OMIT tie_word_embeddings (HF's GemmaConfig
        # defaults it True and drops default-valued keys), so the
        # absent-key default flips to True here — False would demand an
        # lm_head tensor no Gemma checkpoint ships.
        common.update(
            mlp_act="gelu_tanh", embed_scale=True,
            norm_zero_centered=True,
            tie_word_embeddings=bool(
                hf.get("tie_word_embeddings", True)
            ),
        )
        arch = "LlamaForCausalLM"
    if arch == "Qwen2ForCausalLM":
        common["attn_bias"] = True
    elif arch == "Qwen3ForCausalLM":
        common["qk_norm"] = True
    elif arch == "Qwen3MoeForCausalLM":
        # Non-uniform sparsity (dense layers interleaved mid-stack) has no
        # stacked-leaf layout here — same scope rule as DeepSeek's
        # moe_layer_freq guard below.
        if int(hf.get("decoder_sparse_step") or 1) != 1 or hf.get(
            "mlp_only_layers"
        ):
            raise NotImplementedError(
                "Qwen3-MoE checkpoints with decoder_sparse_step != 1 or "
                "mlp_only_layers interleave dense layers mid-stack; only "
                "uniformly-sparse stacks are supported"
            )
        common.update(
            qk_norm=True,
            num_experts=hf["num_local_experts"]
            if "num_local_experts" in hf
            else hf["num_experts"],
            num_experts_per_tok=hf["num_experts_per_tok"],
            moe_intermediate_size=hf["moe_intermediate_size"],
            # HF Qwen3MoeSparseMoeBlock honors this key (skips the
            # top-k renorm when false)
            norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
        )
    elif arch == "MixtralForCausalLM":
        common.update(
            num_experts=hf["num_local_experts"],
            num_experts_per_tok=hf["num_experts_per_tok"],
            moe_intermediate_size=hf["intermediate_size"],
        )
    elif arch in ("DeepseekV2ForCausalLM", "DeepseekV3ForCausalLM"):
        # MLA family. first_k_dense_replace (real V2/V3: first layers
        # dense) maps to the split dense-prefix/MoE-suffix stack; a
        # non-unit moe_layer_freq (interleaved dense layers mid-stack)
        # remains out of scope for the two-scan layout.
        if int(hf.get("moe_layer_freq") or 1) != 1:
            raise NotImplementedError(
                "DeepSeek checkpoints with moe_layer_freq != 1 interleave "
                "dense and MoE layers mid-stack; only a dense PREFIX "
                "(first_k_dense_replace) is supported"
            )
        common["first_k_dense_replace"] = int(
            hf.get("first_k_dense_replace") or 0
        )
        common.update(
            kv_lora_rank=hf["kv_lora_rank"],
            q_lora_rank=int(hf.get("q_lora_rank") or 0),
            qk_nope_head_dim=hf["qk_nope_head_dim"],
            qk_rope_head_dim=hf["qk_rope_head_dim"],
            v_head_dim=hf["v_head_dim"],
        )
        if int(hf.get("n_routed_experts") or 0) > 0:
            common.update(
                num_experts=hf["n_routed_experts"],
                num_experts_per_tok=hf["num_experts_per_tok"],
                moe_intermediate_size=hf["moe_intermediate_size"],
                n_shared_experts=int(hf.get("n_shared_experts") or 0),
                # DeepSeek routing semantics (V2: softmax +
                # group_limited_greedy, no renorm, scaling 16; V3:
                # sigmoid + noaux_tc with correction bias, renorm,
                # scaling 2.5) — models/llama._mlp implements them all.
                scoring_func=str(hf.get("scoring_func") or "softmax"),
                topk_method=str(hf.get("topk_method") or "plain"),
                n_group=int(hf.get("n_group") or 0),
                topk_group=int(hf.get("topk_group") or 0),
                norm_topk_prob=bool(hf.get("norm_topk_prob", True)),
                routed_scaling_factor=float(
                    hf.get("routed_scaling_factor") or 1.0
                ),
            )
    elif arch == "Phi3ForCausalLM":
        # Phi-3's fused tensors split on load. longrope-scaled variants
        # (128k) are handled by _hf_rope_scaling above (per-band
        # short/long factor tables + HF attention factor).
        pass
    elif arch not in ("LlamaForCausalLM", "MistralForCausalLM"):
        # Mistral is architecturally Llama (same tensor names, bias-free
        # QKV) + sliding-window attention, which _hf_sliding_window
        # already picked up from the config. Phi-3 is Llama with FUSED
        # qkv_proj / gate_up_proj tensors, split on load by the config's
        # head/intermediate geometry (load_checkpoint).
        raise ValueError(f"unsupported architecture {arch!r}")
    return ModelConfig(**common)


# ------------------------------------------------------------- name mapping

# Leaf spec: (pytree path, transpose). Layer leaves live under "layers" and
# get a layer index from the HF name; expert leaves also get an expert index.


def _hf_leaf(cfg: ModelConfig, hf_name: str):
    """Map one HF tensor name → (leaf_key, layer, expert, transpose) or None.

    leaf_key is a top-level key ("embed", "final_norm", "lm_head") or a
    "layers.<name>" key; transpose flips torch's [out, in] Linear layout to
    our [in, out] einsum layout.
    """
    if hf_name == "model.embed_tokens.weight":
        return ("embed", None, None, False)
    if hf_name == "model.norm.weight":
        return ("final_norm", None, None, False)
    if hf_name == "lm_head.weight":
        if cfg.tie_word_embeddings:
            return None  # tied: unembed reads params["embed"]
        return ("lm_head", None, None, True)
    if not hf_name.startswith("model.layers."):
        return None
    rest = hf_name[len("model.layers."):]
    layer_s, _, tail = rest.partition(".")
    layer = int(layer_s)
    simple = {
        "input_layernorm.weight": ("layers.attn_norm", False),
        "self_attn.q_proj.weight": ("layers.wq", True),
        "self_attn.k_proj.weight": ("layers.wk", True),
        "self_attn.v_proj.weight": ("layers.wv", True),
        "self_attn.q_proj.bias": ("layers.bq", False),
        "self_attn.k_proj.bias": ("layers.bk", False),
        "self_attn.v_proj.bias": ("layers.bv", False),
        "self_attn.o_proj.weight": ("layers.wo", True),
        # Qwen3 QK-norm (per-head RMSNorm weights over head_dim).
        "self_attn.q_norm.weight": ("layers.q_head_norm", False),
        "self_attn.k_norm.weight": ("layers.k_head_norm", False),
        "post_attention_layernorm.weight": ("layers.mlp_norm", False),
        "mlp.gate_proj.weight": ("layers.w_gate", True),
        "mlp.up_proj.weight": ("layers.w_up", True),
        "mlp.down_proj.weight": ("layers.w_down", True),
        "block_sparse_moe.gate.weight": ("layers.router", True),
        "mlp.gate.weight": ("layers.router", True),
        "mlp.gate.e_score_correction_bias": ("layers.router_bias", False),
    }
    if cfg.is_mla:
        # DeepSeek-V2/V3 MLA projections. q_proj is the direct-q (V2-Lite)
        # form and maps to w_q; kv_b_proj carries the per-head k_nope AND v
        # up-projections interleaved per head — staged whole under a pseudo
        # leaf and split into w_uk/w_uv after all shards land.
        simple.update(
            {
                "self_attn.q_proj.weight": ("layers.w_q", True),
                "self_attn.q_a_proj.weight": ("layers.w_dq", True),
                "self_attn.q_a_layernorm.weight": ("layers.q_norm", False),
                "self_attn.q_b_proj.weight": ("layers.w_uq", True),
                "self_attn.kv_a_proj_with_mqa.weight": ("layers.w_dkv", True),
                "self_attn.kv_a_layernorm.weight": ("layers.kv_norm", False),
                "self_attn.kv_b_proj.weight": ("layers._w_ukv", True),
                "mlp.shared_experts.gate_proj.weight": ("layers.w_sh_gate", True),
                "mlp.shared_experts.up_proj.weight": ("layers.w_sh_up", True),
                "mlp.shared_experts.down_proj.weight": ("layers.w_sh_down", True),
            }
        )
    if tail in simple:
        key, transpose = simple[tail]
        key, layer = _route_stack(cfg, key, layer)
        return (key, layer, None, transpose)
    for prefix in ("block_sparse_moe.experts.", "mlp.experts."):
        if tail.startswith(prefix):
            sub = tail[len(prefix):]
            expert_s, _, w = sub.partition(".")
            expert = int(expert_s)
            moe = {
                "w1.weight": "layers.w_gate",  # gate_proj (mixtral names)
                "w3.weight": "layers.w_up",  # up_proj
                "w2.weight": "layers.w_down",  # down_proj
                "gate_proj.weight": "layers.w_gate",  # deepseek names
                "up_proj.weight": "layers.w_up",
                "down_proj.weight": "layers.w_down",
            }
            if w in moe:
                key, layer = _route_stack(cfg, moe[w], layer)
                return (key, layer, expert, True)
    return None


def _route_stack(cfg: ModelConfig, key: str, layer: int) -> Tuple[str, int]:
    """Heterogeneous DeepSeek stacks: HF layer i < first_k_dense_replace
    lands in the `dense_layers` prefix stack (same leaf names, dense MLP
    dims); later layers land in `layers` re-indexed from 0."""
    kd = cfg.first_k_dense_replace
    if kd == 0 or not key.startswith("layers."):
        return key, layer
    if layer < kd:
        return "dense_layers." + key[len("layers."):], layer
    return key, layer - kd


def _stack_shapes(
    cfg: ModelConfig, pre: str, L: int, moe: bool
) -> Dict[str, Tuple[int, ...]]:
    """Shapes of one stacked-layer leaf set (`pre` is "layers." or
    "dense_layers."), mirroring the family module's _layer_stack/init."""
    E = cfg.hidden_size
    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    shapes: Dict[str, Tuple[int, ...]] = {
        pre + "attn_norm": (L, E),
        pre + "mlp_norm": (L, E),
    }
    if cfg.is_mla:
        dn, dr, dv = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.v_head_dim
        kvr, qr = cfg.kv_lora_rank, cfg.q_lora_rank
        shapes.update(
            {
                pre + "w_dkv": (L, E, kvr + dr),
                pre + "kv_norm": (L, kvr),
                pre + "_w_ukv": (L, kvr, Hq * (dn + dv)),
                pre + "wo": (L, Hq * dv, E),
            }
        )
        if qr > 0:
            shapes.update(
                {
                    pre + "w_dq": (L, E, qr),
                    pre + "q_norm": (L, qr),
                    pre + "w_uq": (L, qr, Hq * (dn + dr)),
                }
            )
        else:
            shapes[pre + "w_q"] = (L, E, Hq * (dn + dr))
    else:
        shapes.update(
            {
                pre + "wq": (L, E, Hq * D),
                pre + "wk": (L, E, Hkv * D),
                pre + "wv": (L, E, Hkv * D),
                pre + "wo": (L, Hq * D, E),
            }
        )
        if cfg.attn_bias:
            shapes.update(
                {
                    pre + "bq": (L, Hq * D),
                    pre + "bk": (L, Hkv * D),
                    pre + "bv": (L, Hkv * D),
                }
            )
        if cfg.qk_norm:
            shapes.update(
                {
                    pre + "q_head_norm": (L, D),
                    pre + "k_head_norm": (L, D),
                }
            )
    if moe:
        X, Fm = cfg.num_experts, cfg.moe_intermediate_size
        shapes.update(
            {
                pre + "router": (L, E, X),
                pre + "w_gate": (L, X, E, Fm),
                pre + "w_up": (L, X, E, Fm),
                pre + "w_down": (L, X, Fm, E),
            }
        )
        if cfg.topk_method == "noaux_tc":
            shapes[pre + "router_bias"] = (L, X)
        if cfg.n_shared_experts > 0:
            Fs = cfg.n_shared_experts * Fm
            shapes.update(
                {
                    pre + "w_sh_gate": (L, E, Fs),
                    pre + "w_sh_up": (L, E, Fs),
                    pre + "w_sh_down": (L, Fs, E),
                }
            )
    else:
        F = cfg.intermediate_size
        shapes.update(
            {
                pre + "w_gate": (L, E, F),
                pre + "w_up": (L, E, F),
                pre + "w_down": (L, F, E),
            }
        )
    return shapes


def _leaf_shapes(cfg: ModelConfig) -> Dict[str, Tuple[int, ...]]:
    """Target (host staging) shape per leaf key — mirrors the family
    module's init_params. For MLA, the kv_b up-projection stages under the
    pseudo leaf `layers._w_ukv` (HF interleaves k_nope and v per head in
    one tensor); load_checkpoint splits it into w_uk/w_uv afterwards.
    Heterogeneous DeepSeek stacks add a `dense_layers.` prefix set."""
    E = cfg.hidden_size
    kd = cfg.first_k_dense_replace
    shapes: Dict[str, Tuple[int, ...]] = {
        "embed": (cfg.vocab_size, E),
        "final_norm": (E,),
    }
    shapes.update(
        _stack_shapes(cfg, "layers.", cfg.num_layers - kd, cfg.is_moe)
    )
    if kd > 0:
        shapes.update(_stack_shapes(cfg, "dense_layers.", kd, False))
    if not cfg.tie_word_embeddings:
        shapes["lm_head"] = (E, cfg.vocab_size)
    return shapes


_NORM_SUFFIXES = (
    "final_norm",
    "attn_norm",
    "mlp_norm",
    "kv_norm",
    "q_norm",
    "router_bias",  # V3 selection bias: f32 like HF's buffer
)


def _is_norm_leaf(key: str) -> bool:
    return key.rsplit(".", 1)[-1] in _NORM_SUFFIXES


def load_checkpoint(
    path: str,
    cfg: ModelConfig,
    dtype=jnp.bfloat16,
    shardings: Optional[Dict[str, Any]] = None,
) -> Params:
    """Load an HF safetensors checkpoint dir into the stacked param pytree.

    Norm weights stage as float32 (matching init_params — rms_norm computes
    in f32); everything else as `dtype`. When `shardings` (the pytree from
    parallel/sharding.param_shardings) is given, each finished leaf is
    device_put with its NamedSharding so devices receive only their shard.
    """
    if not os.path.isdir(path):
        raise FileNotFoundError(f"checkpoint dir {path!r} does not exist")
    np_dtype = ml_dtypes.bfloat16 if dtype == jnp.bfloat16 else np.dtype(dtype)
    shapes = _leaf_shapes(cfg)
    expert_leaves = (
        {"layers.w_gate", "layers.w_up", "layers.w_down"} if cfg.is_moe else set()
    )
    staging: Dict[str, np.ndarray] = {}
    # Completeness tracking: [stack_len] per layer leaf (leading dim of the
    # leaf's shape — the stacks differ in length for heterogeneous models),
    # [stack_len, X] per expert leaf (every expert must land — a missing
    # expert must raise, not serve np.empty garbage), [1] per top-level.
    filled: Dict[str, np.ndarray] = {}
    for k, s in shapes.items():
        if k in expert_leaves:
            filled[k] = np.zeros((s[0], cfg.num_experts), bool)
        elif "." in k:
            filled[k] = np.zeros(s[0], bool)
        else:
            filled[k] = np.zeros(1, bool)

    def stage(key: str) -> np.ndarray:
        if key not in staging:
            want = np.float32 if _is_norm_leaf(key) else np_dtype
            staging[key] = np.empty(shapes[key], dtype=want)
        return staging[key]

    for file in _shard_files(path):
        for name, arr in read_safetensors(file):
            # Phi-3 fuses QKV and gate/up into single tensors; split by
            # the config's head/intermediate geometry (row order q,k,v /
            # gate,up — HF Phi3Attention/Phi3MLP slicing).
            mfused = re.match(
                r"model\.layers\.(\d+)\.self_attn\.qkv_proj\.weight$", name
            )
            if mfused:
                li = int(mfused.group(1))
                qd = cfg.num_heads * cfg.head_dim
                kd = cfg.num_kv_heads * cfg.head_dim
                if arr.shape[0] != qd + 2 * kd:
                    raise ValueError(
                        f"{name}: fused qkv has {arr.shape[0]} rows, "
                        f"config geometry needs {qd + 2 * kd}"
                    )
                for key, chunk in (
                    ("layers.wq", arr[:qd]),
                    ("layers.wk", arr[qd:qd + kd]),
                    ("layers.wv", arr[qd + kd:qd + 2 * kd]),
                ):
                    np.copyto(stage(key)[li], chunk.T, casting="unsafe")
                    filled[key][li] = True
                continue
            mfused = re.match(
                r"model\.layers\.(\d+)\.mlp\.gate_up_proj\.weight$", name
            )
            if mfused:
                li = int(mfused.group(1))
                F = cfg.intermediate_size
                if arr.shape[0] != 2 * F:
                    raise ValueError(
                        f"{name}: fused gate_up has {arr.shape[0]} rows, "
                        f"config geometry needs {2 * F}"
                    )
                for key, chunk in (
                    ("layers.w_gate", arr[:F]),
                    ("layers.w_up", arr[F:2 * F]),
                ):
                    np.copyto(stage(key)[li], chunk.T, casting="unsafe")
                    filled[key][li] = True
                continue
            spec = _hf_leaf(cfg, name)
            if spec is None:
                continue
            key, layer, expert, transpose = spec
            if key not in shapes:
                raise ValueError(
                    f"{name} maps to {key!r} which this config lacks "
                    f"(attn_bias={cfg.attn_bias}, is_moe={cfg.is_moe})"
                )
            buf = stage(key)
            src = arr.T if transpose else arr
            if layer is None:
                np.copyto(buf, src, casting="unsafe")
                filled[key][0] = True
            elif expert is None:
                np.copyto(buf[layer], src, casting="unsafe")
                filled[key][layer] = True
            else:
                np.copyto(buf[layer, expert], src, casting="unsafe")
                filled[key][layer, expert] = True

    missing = [k for k, f in filled.items() if not f.all()]
    if missing:
        raise ValueError(f"checkpoint {path} is missing tensors for {missing}")

    if cfg.is_mla:
        # Split HF's interleaved kv_b up-projection into the absorbed-form
        # tensors the model consumes: [n, kvr, Hq*(dn+dv)] ->
        # w_uk [n, Hq, kvr, dn] + w_uv [n, Hq, kvr, dv] — per stack.
        dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
        for pre in ("layers.", "dense_layers."):
            if pre + "_w_ukv" not in staging:
                continue
            raw = staging.pop(pre + "_w_ukv")
            raw = raw.reshape(
                raw.shape[0], cfg.kv_lora_rank, cfg.num_heads, dn + dv
            )
            staging[pre + "w_uk"] = np.ascontiguousarray(
                np.transpose(raw[..., :dn], (0, 2, 1, 3))
            )
            staging[pre + "w_uv"] = np.ascontiguousarray(
                np.transpose(raw[..., dn:], (0, 2, 1, 3))
            )

    if cfg.norm_zero_centered:
        # Gemma convention: checkpoint stores w, computation uses (1+w).
        for key, buf in staging.items():
            if _is_norm_leaf(key):
                buf += 1.0
    params: Params = {"layers": {}}
    if cfg.first_k_dense_replace > 0:
        params["dense_layers"] = {}
    for key, buf in staging.items():
        leaf = jnp.asarray(buf)
        stack, _, sub = key.partition(".")
        if shardings is not None:
            sh = shardings[stack][sub] if sub else shardings[key]
            leaf = jax.device_put(leaf, sh)
        if sub:
            params[stack][sub] = leaf
        else:
            params[key] = leaf
    return params


# ------------------------------------------------------------ vision towers


def vision_config_from_hf(path: str, out_dim: int = 0):
    """VisionConfig from an HF checkpoint dir carrying a SigLIP-layout
    vision tower (config.json `vision_config`, or a bare vision-model
    config). `out_dim` overrides the projector target (defaults to the
    tower hidden size when the checkpoint has no projector). CLIP-style
    class-token towers are rejected at load (see load_vision_checkpoint)."""
    from xllm_service_tpu.models.vision import VisionConfig

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    vc = hf.get("vision_config", hf)
    if (
        vc.get("model_type") == "qwen2_5_vl"
        or "fullatt_block_indexes" in vc
    ):
        return _qwen25vl_vision_config(hf, vc, out_dim)
    if vc.get("model_type") == "qwen2_vl" or "embed_dim" in vc:
        return _qwen2vl_vision_config(hf, vc, out_dim)
    image_size = int(vc["image_size"])
    patch = int(vc["patch_size"])
    if image_size % patch:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size {patch} "
            f"(conv-with-remainder towers are not supported)"
        )
    n_patches = (image_size // patch) ** 2
    return VisionConfig(
        name=hf.get("model_type", "siglip") + "-vision",
        image_size=image_size,
        patch_size=patch,
        hidden_size=int(vc["hidden_size"]),
        intermediate_size=int(vc["intermediate_size"]),
        num_layers=int(vc["num_hidden_layers"]),
        num_heads=int(vc["num_attention_heads"]),
        out_tokens=n_patches,  # no pooling: LLaVA-style full patch grid
        out_dim=out_dim or int(vc["hidden_size"]),
        rms_norm_eps=float(vc.get("layer_norm_eps", 1e-6)),
        arch="siglip",
    )


def _qwen2vl_vision_config(hf: dict, vc: dict, out_dim: int = 0):
    """VisionConfig for an HF Qwen2VLVisionConfig dict (embed_dim is the
    tower width; vision_config.hidden_size is the LLM dim the PatchMerger
    projects into). The HF processor's dynamic resolution maps to
    per-request grids; this serving path fixes a square input size
    (image_size keyword in vision_config, else 448 — 32x32 patches)."""
    from xllm_service_tpu.models.vision import VisionConfig

    E = int(vc["embed_dim"])
    merge = int(vc.get("spatial_merge_size", 2))
    image_size = int(vc.get("image_size", 448))
    patch = int(vc["patch_size"])
    if image_size % patch:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size {patch}"
        )
    grid = image_size // patch
    if grid % merge:
        raise ValueError(
            f"image_size {image_size} / patch {patch} not divisible by "
            f"spatial_merge_size {merge}"
        )
    return VisionConfig(
        name="qwen2_vl-visual",
        image_size=image_size,
        patch_size=patch,
        hidden_size=E,
        intermediate_size=int(E * float(vc.get("mlp_ratio", 4))),
        num_layers=int(vc["depth"]),
        num_heads=int(vc["num_heads"]),
        out_tokens=grid * grid // (merge * merge),
        out_dim=out_dim or int(vc.get("hidden_size") or E),
        rms_norm_eps=1e-6,  # HF hardcodes LayerNorm(eps=1e-6)
        arch="qwen2vl",
        spatial_merge_size=merge,
        temporal_patch_size=int(vc.get("temporal_patch_size", 2)),
    )


def _qwen25vl_vision_config(hf: dict, vc: dict, out_dim: int = 0):
    """VisionConfig for an HF Qwen2_5_VLVisionConfig dict (hidden_size is
    the TOWER width here, out_hidden_size the LLM dim — the names moved
    between the two generations)."""
    from xllm_service_tpu.models.vision import VisionConfig

    E = int(vc["hidden_size"])
    merge = int(vc.get("spatial_merge_size", 2))
    image_size = int(vc.get("image_size", 448))
    patch = int(vc["patch_size"])
    if image_size % patch:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size {patch}"
        )
    grid = image_size // patch
    if grid % merge:
        raise ValueError(
            f"image_size {image_size} / patch {patch} not divisible by "
            f"spatial_merge_size {merge}"
        )
    return VisionConfig(
        name="qwen2_5_vl-visual",
        image_size=image_size,
        patch_size=patch,
        hidden_size=E,
        intermediate_size=int(vc["intermediate_size"]),
        num_layers=int(vc["depth"]),
        num_heads=int(vc["num_heads"]),
        out_tokens=grid * grid // (merge * merge),
        out_dim=out_dim or int(vc.get("out_hidden_size") or E),
        rms_norm_eps=1e-6,
        arch="qwen25vl",
        spatial_merge_size=merge,
        temporal_patch_size=int(vc.get("temporal_patch_size", 2)),
        window_size=int(vc.get("window_size", 112)),
        fullatt_block_indexes=tuple(
            int(i) for i in (vc.get("fullatt_block_indexes") or ())
        ),
    )


# HF Qwen2_5_VisionTransformer layer tensor name -> (leaf key, transpose).
_QWEN25VL_LAYER = {
    "norm1.weight": ("ln1_w", False),
    "attn.qkv.weight": ("wqkv", True),
    "attn.qkv.bias": ("bqkv", False),
    "attn.proj.weight": ("wo", True),
    "attn.proj.bias": ("bo", False),
    "norm2.weight": ("ln2_w", False),
    "mlp.gate_proj.weight": ("w_gate", True),
    "mlp.gate_proj.bias": ("b_gate", False),
    "mlp.up_proj.weight": ("w_up", True),
    "mlp.up_proj.bias": ("b_up", False),
    "mlp.down_proj.weight": ("w_down", True),
    "mlp.down_proj.bias": ("b_down", False),
}
_QWEN25VL_SIMPLE = {
    "visual.merger.ln_q.weight": ("merger_ln_w", False, np.float32),
    "visual.merger.mlp.0.weight": ("merger_fc1", True, None),
    "visual.merger.mlp.0.bias": ("merger_b1", False, None),
    "visual.merger.mlp.2.weight": ("merger_fc2", True, None),
    "visual.merger.mlp.2.bias": ("merger_b2", False, None),
}


# HF Qwen2VisionTransformer layer tensor name -> (leaf key, transpose).
_QWEN2VL_LAYER = {
    "norm1.weight": ("ln1_w", False),
    "norm1.bias": ("ln1_b", False),
    "attn.qkv.weight": ("wqkv", True),
    "attn.qkv.bias": ("bqkv", False),
    "attn.proj.weight": ("wo", True),
    "attn.proj.bias": ("bo", False),
    "norm2.weight": ("ln2_w", False),
    "norm2.bias": ("ln2_b", False),
    "mlp.fc1.weight": ("fc1", True),
    "mlp.fc1.bias": ("b1", False),
    "mlp.fc2.weight": ("fc2", True),
    "mlp.fc2.bias": ("b2", False),
}
_QWEN2VL_SIMPLE = {
    "visual.merger.ln_q.weight": ("merger_ln_w", False, np.float32),
    "visual.merger.ln_q.bias": ("merger_ln_b", False, np.float32),
    "visual.merger.mlp.0.weight": ("merger_fc1", True, None),
    "visual.merger.mlp.0.bias": ("merger_b1", False, None),
    "visual.merger.mlp.2.weight": ("merger_fc2", True, None),
    "visual.merger.mlp.2.bias": ("merger_b2", False, None),
}


def _load_qwen2vl_visual(path: str, cfg, dtype, np_dtype):
    """Qwen2-VL `visual.*` tower -> the models/vision.py qwen2vl pytree.
    Conv3d patch embed [E, C, T, P, P] flattens to the [(C, T, Ph, Pw), E]
    matmul layout (_qwen2vl_patch_rows builds rows in exactly that
    order)."""
    from xllm_service_tpu.models.vision import init_vision_params

    E, L, P = cfg.hidden_size, cfg.num_layers, cfg.patch_size
    T = cfg.temporal_patch_size
    layer_map = (
        _QWEN25VL_LAYER if cfg.arch == "qwen25vl" else _QWEN2VL_LAYER
    )
    simple_map = (
        _QWEN25VL_SIMPLE if cfg.arch == "qwen25vl" else _QWEN2VL_SIMPLE
    )
    # Stage over EMPTY buffers shaped by init (no random generation —
    # unlike the SigLIP path, every tensor must land or this raises, so
    # values are always overwritten; a 675M-param tower shouldn't pay a
    # full random init to be discarded).
    params = jax.tree.map(
        lambda x: np.empty(x.shape, x.dtype),
        jax.eval_shape(
            lambda: init_vision_params(cfg, jax.random.key(0), dtype)
        ),
    )
    needed = {"patch_embed"} | {k for k, _, _ in simple_map.values()}
    needed |= {f"layers.{k}" for k, _ in layer_map.values()}
    landed = set()
    layer_seen = {
        f"layers.{k}": np.zeros(L, bool) for k, _ in layer_map.values()
    }
    for file in _shard_files(path):
        for name, arr in read_safetensors(file):
            if not name.startswith("visual."):
                continue
            if name == "visual.patch_embed.proj.weight":
                w = np.asarray(arr).reshape(E, 3 * T * P * P).T
                params["patch_embed"] = w.astype(np_dtype)
                landed.add("patch_embed")
            elif name in simple_map:
                key, transpose, want = simple_map[name]
                src = np.asarray(arr).T if transpose else np.asarray(arr)
                params[key] = src.astype(want or np_dtype)
                landed.add(key)
            elif name.startswith("visual.blocks."):
                rest = name[len("visual.blocks."):]
                layer_s, _, tail = rest.partition(".")
                if tail in layer_map:
                    key, transpose = layer_map[tail]
                    src = arr.T if transpose else arr
                    buf = params["layers"][key]
                    np.copyto(buf[int(layer_s)], src, casting="unsafe")
                    layer_seen[f"layers.{key}"][int(layer_s)] = True
    for k, seen in layer_seen.items():
        if seen.all():
            landed.add(k)
    missing = sorted(needed - landed)
    if missing:
        raise ValueError(
            f"qwen2vl visual checkpoint {path} missing tensors: {missing}"
        )
    return cfg, jax.tree.map(jnp.asarray, params)


# Per-layer tensor map for the Qwen2-Audio (Whisper-layout) tower:
# HF tail -> (stacked leaf, transpose). k_proj is bias-free (Whisper).
_AUDIO_LAYER = {
    "self_attn_layer_norm.weight": ("ln1_w", False),
    "self_attn_layer_norm.bias": ("ln1_b", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.v_proj.bias": ("bv", False),
    "self_attn.out_proj.weight": ("wo", True),
    "self_attn.out_proj.bias": ("bo", False),
    "final_layer_norm.weight": ("ln2_w", False),
    "final_layer_norm.bias": ("ln2_b", False),
    "fc1.weight": ("fc1", True),
    "fc1.bias": ("b1", False),
    "fc2.weight": ("fc2", True),
    "fc2.bias": ("b2", False),
}

_AUDIO_SIMPLE = {
    # HF name -> (leaf, transpose_spec). Conv kernels [D, C, 3] map to
    # the unfolded-einsum layout [3, C, D].
    "audio_tower.conv1.weight": ("conv1_w", (2, 1, 0)),
    "audio_tower.conv1.bias": ("conv1_b", None),
    "audio_tower.conv2.weight": ("conv2_w", (2, 1, 0)),
    "audio_tower.conv2.bias": ("conv2_b", None),
    "audio_tower.embed_positions.weight": ("pos_embed", None),
    "audio_tower.layer_norm.weight": ("ln_post_w", None),
    "audio_tower.layer_norm.bias": ("ln_post_b", None),
    "multi_modal_projector.linear.weight": ("proj", (1, 0)),
    "multi_modal_projector.linear.bias": ("proj_b", None),
}


def audio_config_from_hf(path: str, out_dim: int = 0):
    """AudioConfig from an HF Qwen2AudioForConditionalGeneration (or
    bare encoder) checkpoint dir: config.json `audio_config` carries the
    Whisper geometry; the projector target comes from text_config (or
    `out_dim`)."""
    from xllm_service_tpu.models.audio import AudioConfig

    with open(os.path.join(path, "config.json")) as f:
        hf = json.load(f)
    ac = hf.get("audio_config", hf)
    text = hf.get("text_config") or {}
    return AudioConfig(
        name=hf.get("model_type", "qwen2_audio") + "-audio",
        num_mel_bins=int(ac["num_mel_bins"]),
        mel_frames=2 * int(ac["max_source_positions"]),
        hidden_size=int(ac["d_model"]),
        intermediate_size=int(ac["encoder_ffn_dim"]),
        num_layers=int(ac["encoder_layers"]),
        num_heads=int(ac["encoder_attention_heads"]),
        out_dim=int(
            out_dim or text.get("hidden_size")
            or hf.get("hidden_size") or ac["d_model"]
        ),
    )


def load_audio_checkpoint(path: str, cfg=None, dtype=jnp.float32):
    """Load the Qwen2-Audio tower + projector (`audio_tower.*`,
    `multi_modal_projector.linear.*` — HF modeling_qwen2_audio layout)
    into the models/audio.py pytree. Returns (AudioConfig, params);
    missing tensors raise (no silent random-init serving)."""
    from xllm_service_tpu.models.audio import init_audio_params

    cfg = cfg or audio_config_from_hf(path)
    np_dtype = (
        ml_dtypes.bfloat16 if dtype == jnp.bfloat16 else np.dtype(dtype)
    )
    L = cfg.num_layers
    params = jax.tree.map(
        lambda x: np.zeros(x.shape, np_dtype),
        jax.eval_shape(
            lambda: init_audio_params(cfg, jax.random.key(0), dtype)
        ),
    )
    needed = {k for k, _ in _AUDIO_SIMPLE.values()}
    needed |= {f"layers.{k}" for k, _ in _AUDIO_LAYER.values()}
    landed = set()
    layer_seen = {
        f"layers.{k}": np.zeros(L, bool) for k, _ in _AUDIO_LAYER.values()
    }
    for file in _shard_files(path):
        for name, arr in read_safetensors(file):
            if name in _AUDIO_SIMPLE:
                key, perm = _AUDIO_SIMPLE[name]
                src = np.asarray(arr)
                if perm is not None:
                    src = src.transpose(perm)
                params[key] = np.ascontiguousarray(src).astype(np_dtype)
                landed.add(key)
            elif name.startswith("audio_tower.layers."):
                rest = name[len("audio_tower.layers."):]
                layer_s, _, tail = rest.partition(".")
                if tail in _AUDIO_LAYER:
                    key, transpose = _AUDIO_LAYER[tail]
                    src = arr.T if transpose else arr
                    np.copyto(
                        params["layers"][key][int(layer_s)], src,
                        casting="unsafe",
                    )
                    layer_seen[f"layers.{key}"][int(layer_s)] = True
    for k, seen in layer_seen.items():
        if seen.all():
            landed.add(k)
    missing = sorted(needed - landed)
    if missing:
        raise ValueError(
            f"qwen2-audio checkpoint {path} missing tensors: {missing}"
        )
    return cfg, jax.tree.map(jnp.asarray, params)


def save_qwen2audio_tower(params, cfg, path: str) -> None:
    """Inverse of load_audio_checkpoint (HF Qwen2-Audio layout) — CI
    round-trips and synthetic-tower export."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(
            {
                "model_type": "qwen2_audio",
                "audio_config": {
                    "model_type": "qwen2_audio_encoder",
                    "num_mel_bins": cfg.num_mel_bins,
                    "d_model": cfg.hidden_size,
                    "encoder_layers": cfg.num_layers,
                    "encoder_attention_heads": cfg.num_heads,
                    "encoder_ffn_dim": cfg.intermediate_size,
                    "max_source_positions": cfg.conv_frames,
                },
                "text_config": {"hidden_size": cfg.out_dim},
            },
            f,
        )

    def host(x) -> np.ndarray:
        a = np.asarray(x)
        return (
            a.astype(ml_dtypes.bfloat16)
            if a.dtype == ml_dtypes.bfloat16 else a
        )

    tensors: Dict[str, np.ndarray] = {}
    for name, (key, perm) in _AUDIO_SIMPLE.items():
        src = host(params[key])
        if perm is not None:
            inv = np.argsort(perm)
            src = np.ascontiguousarray(src.transpose(tuple(inv)))
        tensors[name] = src
    lp = params["layers"]
    for i in range(cfg.num_layers):
        for tail, (key, transpose) in _AUDIO_LAYER.items():
            t = host(lp[key])[i]
            tensors[f"audio_tower.layers.{i}.{tail}"] = (
                np.ascontiguousarray(t.T if transpose else t)
            )
    write_safetensors(os.path.join(path, "model.safetensors"), tensors)


def save_qwen2vl_visual(params, cfg, path: str) -> None:
    """Inverse of the qwen2vl branch of load_vision_checkpoint (HF
    Qwen2-VL `visual.*` layout) — round-trip tested; exports synthetic
    towers for CI."""
    if cfg.arch != "qwen2vl":
        # Fail BEFORE config.json is written: a qwen25vl tower uses
        # different layer maps and would KeyError mid-write, leaving a
        # half-written checkpoint dir (advisor finding, round 4).
        raise ValueError(
            f"save_qwen2vl_visual handles arch 'qwen2vl' only, got "
            f"{cfg.arch!r}"
        )
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(
            {
                "model_type": "qwen2_vl",
                "vision_config": {
                    "model_type": "qwen2_vl",
                    "embed_dim": cfg.hidden_size,
                    "hidden_size": cfg.out_dim,
                    "depth": cfg.num_layers,
                    "num_heads": cfg.num_heads,
                    "patch_size": cfg.patch_size,
                    "image_size": cfg.image_size,
                    "mlp_ratio": cfg.intermediate_size / cfg.hidden_size,
                    "spatial_merge_size": cfg.spatial_merge_size,
                    "temporal_patch_size": cfg.temporal_patch_size,
                },
            },
            f, indent=2,
        )

    E, P, T = cfg.hidden_size, cfg.patch_size, cfg.temporal_patch_size
    lp = params["layers"]
    arrays = {
        "visual.patch_embed.proj.weight": np.asarray(
            params["patch_embed"]
        ).T.reshape(E, 3, T, P, P),
    }
    for name, (key, transpose, _w) in _QWEN2VL_SIMPLE.items():
        a = np.asarray(params[key])
        arrays[name] = a.T if transpose else a
    for i in range(cfg.num_layers):
        for tail, (key, transpose) in _QWEN2VL_LAYER.items():
            a = np.asarray(lp[key][i])
            arrays[f"visual.blocks.{i}.{tail}"] = a.T if transpose else a
    write_safetensors(os.path.join(path, "model.safetensors"), arrays)


# HF SiglipVisionModel tensor name -> (leaf key, transpose). Layer leaves
# carry "layers." and a layer index parsed from the name.
_VISION_SIMPLE = {
    "vision_model.embeddings.position_embedding.weight": ("pos_embed", False),
    "vision_model.post_layernorm.weight": ("final_norm_w", False),
    "vision_model.post_layernorm.bias": ("final_norm_b", False),
}
_VISION_LAYER = {
    "layer_norm1.weight": ("ln1_w", False),
    "layer_norm1.bias": ("ln1_b", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.v_proj.bias": ("bv", False),
    "self_attn.out_proj.weight": ("wo", True),
    "self_attn.out_proj.bias": ("bo", False),
    "layer_norm2.weight": ("ln2_w", False),
    "layer_norm2.bias": ("ln2_b", False),
    "mlp.fc1.weight": ("w_up", True),
    "mlp.fc1.bias": ("b_up", False),
    "mlp.fc2.weight": ("w_down", True),
    "mlp.fc2.bias": ("b_down", False),
}


def load_vision_checkpoint(
    path: str, cfg=None, dtype=jnp.bfloat16, out_dim: int = 0
):
    """Load an HF SiglipVisionModel-layout checkpoint dir into the
    models/vision.py `siglip` param pytree. Returns (VisionConfig, params).

    The conv patch embedding [E, 3, P, P] flattens to the patchify
    matmul's [P*P*3, E] layout ((py, px, c) lane order — models/vision.py
    _patchify). A `multi_modal_projector.linear.weight` (or `proj.weight`)
    maps to the LM-dim projector when present; otherwise the projector
    initializes to identity-like random and `out_dim` falls back to the
    tower width (caller projects downstream)."""
    from xllm_service_tpu.models.vision import init_vision_params

    cfg = cfg or vision_config_from_hf(path, out_dim=out_dim)
    np_dtype = ml_dtypes.bfloat16 if dtype == jnp.bfloat16 else np.dtype(dtype)
    if cfg.arch in ("qwen2vl", "qwen25vl"):
        return _load_qwen2vl_visual(path, cfg, dtype, np_dtype)
    E, L, P = cfg.hidden_size, cfg.num_layers, cfg.patch_size

    # Stage over random init so an absent projector keeps a usable leaf;
    # every TOWER leaf must land (tracked below). np.array: a WRITABLE
    # host copy (np.asarray of a jax array is read-only).
    params = jax.tree.map(
        lambda x: np.array(x), init_vision_params(cfg, jax.random.key(0), dtype)
    )
    needed = {"patch_embed", "patch_bias", "pos_embed",
              "final_norm_w", "final_norm_b"}
    needed |= {f"layers.{k}" for k, _ in _VISION_LAYER.values()}
    landed = set()
    layer_seen: Dict[str, np.ndarray] = {
        f"layers.{k}": np.zeros(L, bool) for k, _ in _VISION_LAYER.values()
    }

    for file in _shard_files(path):
        for name, arr in read_safetensors(file):
            # VLM checkpoints prefix the tower (e.g. "vision_tower.");
            # strip anything before "vision_model.".
            if "vision_model." in name:
                name = name[name.index("vision_model."):]
            if name == "vision_model.embeddings.patch_embedding.weight":
                # conv [E, 3, P, P] -> [(py, px, c), E]
                w = np.transpose(arr, (2, 3, 1, 0)).reshape(P * P * 3, E)
                params["patch_embed"] = w.astype(np_dtype)
                landed.add("patch_embed")
            elif name == "vision_model.embeddings.patch_embedding.bias":
                params["patch_bias"] = np.asarray(arr, np_dtype)
                landed.add("patch_bias")
            elif name in _VISION_SIMPLE:
                key, _t = _VISION_SIMPLE[name]
                if key == "pos_embed" and arr.shape[0] != cfg.num_patches:
                    # CLIP-style towers carry a class token (num_patches+1
                    # rows) and a different computation (pre_layrnorm,
                    # quick_gelu) — reject loudly instead of broadcasting
                    # garbage inside the jitted encode.
                    raise ValueError(
                        f"position embedding has {arr.shape[0]} rows, "
                        f"expected {cfg.num_patches}: class-token (CLIP) "
                        f"towers are not supported; use a SigLIP-layout "
                        f"tower"
                    )
                want = (
                    np.float32 if key.startswith(("final_norm",)) else np_dtype
                )
                params[key] = np.asarray(arr, want)
                landed.add(key)
            elif name.startswith("vision_model.encoder.layers."):
                rest = name[len("vision_model.encoder.layers."):]
                layer_s, _, tail = rest.partition(".")
                if tail in _VISION_LAYER:
                    key, transpose = _VISION_LAYER[tail]
                    src = arr.T if transpose else arr
                    buf = params["layers"][key]
                    np.copyto(buf[int(layer_s)], src, casting="unsafe")
                    layer_seen[f"layers.{key}"][int(layer_s)] = True
            elif name in (
                "multi_modal_projector.linear.weight", "proj.weight"
            ):
                params["proj"] = np.asarray(arr.T, np_dtype)
                landed.add("proj")
            elif name in (
                "multi_modal_projector.linear.bias", "proj.bias"
            ):
                params["proj_bias"] = np.asarray(arr, np_dtype)
                landed.add("proj_bias")

    for k, seen in layer_seen.items():
        if seen.all():
            landed.add(k)
    missing = sorted(needed - landed)
    if missing:
        raise ValueError(f"vision checkpoint {path} missing tensors: {missing}")
    if "proj" in landed:
        # The checkpoint's own projector decides the output dim (without
        # one, the random-init projector already staged at cfg.out_dim
        # stands). A weight without a bias keeps bias = 0 at the RIGHT
        # width.
        proj_dim = int(params["proj"].shape[1])
        if proj_dim != cfg.out_dim:
            import dataclasses

            cfg = dataclasses.replace(cfg, out_dim=proj_dim)
        if "proj_bias" not in landed:
            params["proj_bias"] = np.zeros((proj_dim,), np_dtype)
    return cfg, jax.tree.map(jnp.asarray, params)


def save_vision_checkpoint(params, cfg, path: str) -> None:
    """Inverse of load_vision_checkpoint (HF SiglipVisionModel layout) —
    round-trip tested; usable for exporting synthetic towers."""
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(
            {
                "model_type": "siglip_vision_model",
                "vision_config": {
                    "image_size": cfg.image_size,
                    "patch_size": cfg.patch_size,
                    "hidden_size": cfg.hidden_size,
                    "intermediate_size": cfg.intermediate_size,
                    "num_hidden_layers": cfg.num_layers,
                    "num_attention_heads": cfg.num_heads,
                    "layer_norm_eps": cfg.rms_norm_eps,
                },
            },
            f, indent=2,
        )

    def host(x) -> np.ndarray:
        a = np.asarray(x)
        return a.astype(ml_dtypes.bfloat16) if a.dtype == ml_dtypes.bfloat16 else a

    E, P = cfg.hidden_size, cfg.patch_size
    lp = params["layers"]
    tensors: Dict[str, np.ndarray] = {
        "vision_model.embeddings.patch_embedding.weight": np.ascontiguousarray(
            np.transpose(
                host(params["patch_embed"]).reshape(P, P, 3, E), (3, 2, 0, 1)
            )
        ),
        "vision_model.embeddings.patch_embedding.bias": host(params["patch_bias"]),
        "vision_model.embeddings.position_embedding.weight": host(params["pos_embed"]),
        "vision_model.post_layernorm.weight": host(params["final_norm_w"]),
        "vision_model.post_layernorm.bias": host(params["final_norm_b"]),
        "proj.weight": np.ascontiguousarray(host(params["proj"]).T),
        "proj.bias": host(params["proj_bias"]),
    }
    for i in range(cfg.num_layers):
        pre = f"vision_model.encoder.layers.{i}."
        for tail, (key, transpose) in _VISION_LAYER.items():
            t = host(lp[key])[i]
            tensors[pre + tail] = np.ascontiguousarray(t.T if transpose else t)
    write_safetensors(os.path.join(path, "model.safetensors"), tensors)


# ---------------------------------------------------------------- HF export


def save_hf_checkpoint(params: Params, cfg: ModelConfig, path: str) -> None:
    """Write params back out as an HF-layout checkpoint dir (config.json +
    model.safetensors) — the inverse of load_checkpoint. Used by the
    round-trip test and for exporting synthetic checkpoints."""
    os.makedirs(path, exist_ok=True)
    if cfg.norm_zero_centered:
        arch = "GemmaForCausalLM"
    elif cfg.is_mla and (
        cfg.topk_method == "noaux_tc" or cfg.scoring_func == "sigmoid"
    ):
        # V3 routing can't run under the V2 gate (transformers'
        # DeepseekV2MoEGate has no noaux_tc/sigmoid branch).
        arch = "DeepseekV3ForCausalLM"
    elif cfg.is_mla:
        arch = "DeepseekV2ForCausalLM"
    elif cfg.is_moe and cfg.qk_norm:
        arch = "Qwen3MoeForCausalLM"
    elif cfg.is_moe:
        arch = "MixtralForCausalLM"
    elif cfg.qk_norm:
        arch = "Qwen3ForCausalLM"
    elif cfg.attn_bias:
        arch = "Qwen2ForCausalLM"
    else:
        arch = "LlamaForCausalLM"
    hf_cfg = {
        "architectures": [arch],
        "model_type": arch[: -len("ForCausalLM")].lower(),
        "vocab_size": cfg.vocab_size,
        "hidden_size": cfg.hidden_size,
        "intermediate_size": (
            cfg.moe_intermediate_size
            if (cfg.is_moe and not cfg.is_mla and not cfg.qk_norm)
            else cfg.intermediate_size
        ),
        "num_hidden_layers": cfg.num_layers,
        "num_attention_heads": cfg.num_heads,
        "num_key_value_heads": cfg.num_kv_heads,
        "head_dim": cfg.head_dim,
        "rope_theta": cfg.rope_theta,
        "rms_norm_eps": cfg.rms_norm_eps,
        "max_position_embeddings": cfg.max_position_embeddings,
        "tie_word_embeddings": cfg.tie_word_embeddings,
    }
    if cfg.is_moe and cfg.qk_norm:
        hf_cfg.update(
            num_experts=cfg.num_experts,
            num_experts_per_tok=cfg.num_experts_per_tok,
            moe_intermediate_size=cfg.moe_intermediate_size,
        )
    if cfg.is_mla:
        hf_cfg.update(
            kv_lora_rank=cfg.kv_lora_rank,
            q_lora_rank=cfg.q_lora_rank or None,
            qk_nope_head_dim=cfg.qk_nope_head_dim,
            qk_rope_head_dim=cfg.qk_rope_head_dim,
            v_head_dim=cfg.v_head_dim,
            first_k_dense_replace=cfg.first_k_dense_replace,
        )
        if cfg.is_moe:
            hf_cfg.update(
                n_routed_experts=cfg.num_experts,
                num_experts_per_tok=cfg.num_experts_per_tok,
                moe_intermediate_size=cfg.moe_intermediate_size,
                n_shared_experts=cfg.n_shared_experts,
                scoring_func=cfg.scoring_func,
                # transformers' V2 gate knows only greedy /
                # group_limited_greedy; our internal "plain" maps back
                topk_method=(
                    "greedy" if cfg.topk_method == "plain"
                    else cfg.topk_method
                ),
                n_group=cfg.n_group or None,
                topk_group=cfg.topk_group or None,
                norm_topk_prob=cfg.norm_topk_prob,
                routed_scaling_factor=cfg.routed_scaling_factor,
            )
    elif cfg.is_moe:
        hf_cfg["num_local_experts"] = cfg.num_experts
        hf_cfg["num_experts_per_tok"] = cfg.num_experts_per_tok
    if cfg.sliding_window:
        hf_cfg["sliding_window"] = cfg.sliding_window
    if cfg.rope_scaling_type:
        # Inverse of _hf_rope_scaling — lets the HF-parity tests load the
        # same rope-scaled geometry through transformers.
        rs: Dict[str, Any] = {"rope_type": cfg.rope_scaling_type}
        if cfg.rope_scaling_type in ("linear", "dynamic", "llama3", "yarn"):
            rs["factor"] = cfg.rope_scaling_factor
        if cfg.rope_scaling_type == "yarn":
            rs["beta_fast"] = cfg.rope_beta_fast
            rs["beta_slow"] = cfg.rope_beta_slow
            rs["truncate"] = cfg.rope_scaling_truncate
            if cfg.rope_mscale:
                rs["mscale"] = cfg.rope_mscale
            if cfg.rope_mscale_all_dim:
                rs["mscale_all_dim"] = cfg.rope_mscale_all_dim
            if cfg.rope_attention_factor:
                rs["attention_factor"] = cfg.rope_attention_factor
            if cfg.rope_original_max_position:
                rs["original_max_position_embeddings"] = (
                    cfg.rope_original_max_position
                )
        if cfg.rope_scaling_type == "llama3":
            rs["low_freq_factor"] = cfg.rope_low_freq_factor
            rs["high_freq_factor"] = cfg.rope_high_freq_factor
            rs["original_max_position_embeddings"] = (
                cfg.rope_original_max_position
            )
        if cfg.rope_scaling_type == "longrope":
            rs["short_factor"] = list(cfg.rope_short_factor)
            rs["long_factor"] = list(cfg.rope_long_factor)
            if cfg.rope_attention_factor:
                rs["attention_factor"] = cfg.rope_attention_factor
            # Phi-3 keeps the original context at the config top level.
            hf_cfg["original_max_position_embeddings"] = (
                cfg.rope_original_max_position
            )
        hf_cfg["rope_scaling"] = rs
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_cfg, f, indent=2)

    def host(x) -> np.ndarray:
        a = np.asarray(x)
        return a.astype(ml_dtypes.bfloat16) if a.dtype == ml_dtypes.bfloat16 else a

    def norm_out(x) -> np.ndarray:
        # Gemma checkpoints store zero-centered norm weights (load adds 1)
        return host(x) - 1.0 if cfg.norm_zero_centered else host(x)

    tensors: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": host(params["embed"]),
        "model.norm.weight": norm_out(params["final_norm"]),
    }
    if not cfg.tie_word_embeddings:
        tensors["lm_head.weight"] = host(params["lm_head"]).T
    kd = cfg.first_k_dense_replace
    for hf_i in range(cfg.num_layers):
        # Heterogeneous stacks: HF layer hf_i < kd reads the dense-prefix
        # stack (dense MLP names); later layers read the main stack.
        if kd and hf_i < kd:
            lp, i, layer_moe = params["dense_layers"], hf_i, False
        else:
            lp, i, layer_moe = params["layers"], hf_i - kd, cfg.is_moe
        pre = f"model.layers.{hf_i}."
        tensors[pre + "input_layernorm.weight"] = norm_out(lp["attn_norm"])[i]
        tensors[pre + "post_attention_layernorm.weight"] = norm_out(lp["mlp_norm"])[i]
        if cfg.is_mla:
            dn, dv = cfg.qk_nope_head_dim, cfg.v_head_dim
            kvr, Hq = cfg.kv_lora_rank, cfg.num_heads
            tensors[pre + "self_attn.kv_a_proj_with_mqa.weight"] = host(
                lp["w_dkv"]
            )[i].T
            tensors[pre + "self_attn.kv_a_layernorm.weight"] = host(
                lp["kv_norm"]
            )[i]
            # Re-interleave w_uk/w_uv per head into HF's kv_b_proj layout
            # [Hq*(dn+dv), kvr] (the inverse of load_checkpoint's split).
            uk = np.transpose(host(lp["w_uk"])[i], (1, 0, 2))  # [kvr,Hq,dn]
            uv = np.transpose(host(lp["w_uv"])[i], (1, 0, 2))  # [kvr,Hq,dv]
            kv_b = np.concatenate([uk, uv], axis=-1).reshape(
                kvr, Hq * (dn + dv)
            )
            tensors[pre + "self_attn.kv_b_proj.weight"] = kv_b.T
            if cfg.q_lora_rank > 0:
                tensors[pre + "self_attn.q_a_proj.weight"] = host(lp["w_dq"])[i].T
                tensors[pre + "self_attn.q_a_layernorm.weight"] = host(
                    lp["q_norm"]
                )[i]
                tensors[pre + "self_attn.q_b_proj.weight"] = host(lp["w_uq"])[i].T
            else:
                tensors[pre + "self_attn.q_proj.weight"] = host(lp["w_q"])[i].T
            tensors[pre + "self_attn.o_proj.weight"] = host(lp["wo"])[i].T
        else:
            tensors[pre + "self_attn.q_proj.weight"] = host(lp["wq"])[i].T
            tensors[pre + "self_attn.k_proj.weight"] = host(lp["wk"])[i].T
            tensors[pre + "self_attn.v_proj.weight"] = host(lp["wv"])[i].T
            tensors[pre + "self_attn.o_proj.weight"] = host(lp["wo"])[i].T
            if cfg.attn_bias:
                tensors[pre + "self_attn.q_proj.bias"] = host(lp["bq"])[i]
                tensors[pre + "self_attn.k_proj.bias"] = host(lp["bk"])[i]
                tensors[pre + "self_attn.v_proj.bias"] = host(lp["bv"])[i]
            if cfg.qk_norm:
                tensors[pre + "self_attn.q_norm.weight"] = host(
                    lp["q_head_norm"]
                )[i]
                tensors[pre + "self_attn.k_norm.weight"] = host(
                    lp["k_head_norm"]
                )[i]
        if layer_moe:
            gate_name, exp_pre, w_names = (
                ("mlp.gate.weight", "mlp.experts.",
                 ("gate_proj.weight", "up_proj.weight", "down_proj.weight"))
                if cfg.is_mla or cfg.qk_norm  # deepseek + qwen3-moe naming
                else ("block_sparse_moe.gate.weight", "block_sparse_moe.experts.",
                      ("w1.weight", "w3.weight", "w2.weight"))
            )
            tensors[pre + gate_name] = host(lp["router"])[i].T
            if lp.get("router_bias") is not None:
                tensors[pre + "mlp.gate.e_score_correction_bias"] = host(
                    lp["router_bias"]
                )[i]
            for j in range(cfg.num_experts):
                ep = pre + exp_pre + f"{j}."
                tensors[ep + w_names[0]] = host(lp["w_gate"])[i, j].T
                tensors[ep + w_names[1]] = host(lp["w_up"])[i, j].T
                tensors[ep + w_names[2]] = host(lp["w_down"])[i, j].T
            if cfg.n_shared_experts > 0:
                tensors[pre + "mlp.shared_experts.gate_proj.weight"] = host(
                    lp["w_sh_gate"]
                )[i].T
                tensors[pre + "mlp.shared_experts.up_proj.weight"] = host(
                    lp["w_sh_up"]
                )[i].T
                tensors[pre + "mlp.shared_experts.down_proj.weight"] = host(
                    lp["w_sh_down"]
                )[i].T
        else:
            tensors[pre + "mlp.gate_proj.weight"] = host(lp["w_gate"])[i].T
            tensors[pre + "mlp.up_proj.weight"] = host(lp["w_up"])[i].T
            tensors[pre + "mlp.down_proj.weight"] = host(lp["w_down"])[i].T
    write_safetensors(os.path.join(path, "model.safetensors"), tensors)


# ------------------------------------------------------------- LoRA (peft)

# peft target-module names -> stacked-leaf projection names (llama family)
_LORA_PROJ_MAP = {
    "q_proj": "wq",
    "k_proj": "wk",
    "v_proj": "wv",
    "o_proj": "wo",
    "gate_proj": "w_gate",
    "up_proj": "w_up",
    "down_proj": "w_down",
}


def load_lora_checkpoint(path: str, cfg: ModelConfig):
    """Load one peft-layout LoRA adapter dir into the executor's format:
    {proj: (A [L, in, r], B [L, r, out])} with the peft scaling
    (lora_alpha / r) folded into B. Layers the adapter does not cover
    stay zero. Expects `adapter_model.safetensors` with
    `...layers.{i}.(self_attn|mlp).<target>.lora_{A,B}.weight` keys
    (peft stores A as [r, in] and B as [out, r]) and an optional
    `adapter_config.json` carrying r / lora_alpha."""
    import json as _json

    cfg_path = os.path.join(path, "adapter_config.json")
    alpha = r_cfg = None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            acfg = _json.load(f)
        alpha = acfg.get("lora_alpha")
        r_cfg = acfg.get("r")
    st_path = os.path.join(path, "adapter_model.safetensors")
    if not os.path.exists(st_path):
        raise FileNotFoundError(f"no adapter_model.safetensors in {path}")

    per_proj: Dict[str, Dict[int, Dict[str, np.ndarray]]] = {}
    layer_re = re.compile(r"\.layers\.(\d+)\.")
    for key, arr in read_safetensors(st_path):
        m = layer_re.search(key)
        if m is None:
            continue
        layer = int(m.group(1))
        tail = key[m.end():]  # e.g. self_attn.q_proj.lora_A.weight
        parts = tail.split(".")
        if len(parts) < 3 or parts[-1] != "weight":
            continue
        which = parts[-2]  # lora_A | lora_B
        target = parts[-3]
        proj = _LORA_PROJ_MAP.get(target)
        if proj is None or which not in ("lora_A", "lora_B"):
            continue
        per_proj.setdefault(proj, {}).setdefault(layer, {})[which] = arr
    if not per_proj:
        raise ValueError(f"{st_path}: no recognizable lora_A/lora_B keys")

    out = {}
    for proj, layers in per_proj.items():
        any_layer = next(iter(layers.values()))
        if "lora_A" not in any_layer or "lora_B" not in any_layer:
            raise ValueError(f"{proj}: incomplete lora_A/lora_B pair")
        r = any_layer["lora_A"].shape[0]
        e_in = any_layer["lora_A"].shape[1]
        e_out = any_layer["lora_B"].shape[0]
        scaling = (alpha / (r_cfg or r)) if alpha else 1.0
        A = np.zeros((cfg.num_layers, e_in, r), np.float32)
        B = np.zeros((cfg.num_layers, r, e_out), np.float32)
        for layer, pair in layers.items():
            if layer >= cfg.num_layers:
                raise ValueError(
                    f"{proj}: adapter layer {layer} out of range"
                )
            A[layer] = pair["lora_A"].astype(np.float32).T  # [in, r]
            B[layer] = pair["lora_B"].astype(np.float32).T * scaling
        out[proj] = (A, B)
    return out


def save_lora_checkpoint(adapter, path: str, alpha=None, r=None) -> None:
    """Write {proj: (A [L, in, r], B [L, r, out])} as a peft-layout dir
    (testing/roundtrip; B is UNSCALED here — pass alpha/r to record the
    scaling load_lora_checkpoint will fold in)."""
    import json as _json

    os.makedirs(path, exist_ok=True)
    inv = {v: k for k, v in _LORA_PROJ_MAP.items()}
    tensors: Dict[str, np.ndarray] = {}
    for proj, (A, B) in adapter.items():
        target = inv[proj]
        grp = "self_attn" if proj.startswith("w") and proj[1] in "qkvo" \
            else "mlp"
        for layer in range(A.shape[0]):
            base = (
                f"base_model.model.model.layers.{layer}.{grp}.{target}"
            )
            tensors[f"{base}.lora_A.weight"] = np.ascontiguousarray(
                A[layer].T.astype(np.float32)
            )
            tensors[f"{base}.lora_B.weight"] = np.ascontiguousarray(
                B[layer].T.astype(np.float32)
            )
    write_safetensors(
        os.path.join(path, "adapter_model.safetensors"), tensors
    )
    if alpha is not None:
        with open(os.path.join(path, "adapter_config.json"), "w") as f:
            _json.dump({"lora_alpha": alpha, "r": r}, f)
