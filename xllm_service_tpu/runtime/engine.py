"""Continuous-batching inference engine.

Engine-tier core (the reference's analog lives in the absent xLLM submodule;
this implements the runtime its service layer assumes — SURVEY.md §2.3):
admission with prefix-cache reuse, one fixed-shape decode step per iteration
over R slots, incremental block allocation with recompute-preemption, block
commits under chained hashes, and heartbeat-ready load/latency metrics +
KV cache events (proto contract: xllm_rpc_service.proto:44-58).

Pure host-side orchestration: all device work goes through ModelExecutor's
two jitted step functions, so nothing here ever triggers a recompile.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Set, Tuple

import numpy as np

from xllm_service_tpu.common.concurrency import (
    claim_thread,
    release_thread,
    thread_owned,
)
from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.common.hashing import prefix_block_hashes
from xllm_service_tpu.common.types import (
    FinishReason,
    KvCacheEvent,
    LatencyMetrics,
    LoadMetrics,
    LogProb,
    LogProbData,
    RequestOutput,
    SequenceOutput,
    Status,
    StatusCode,
    Usage,
)
from xllm_service_tpu.obs import (
    BATCH_BUCKETS,
    LATENCY_BUCKETS_MS,
    MetricsRegistry,
)
from xllm_service_tpu.ops.sampling import SamplingParams
from xllm_service_tpu.runtime.block_manager import BlockManager, OutOfBlocksError
from xllm_service_tpu.runtime import compile_cache as compile_cache_mod
from xllm_service_tpu.runtime.executor import ModelExecutor, SamplingBatch


@dataclass
class EngineRequest:
    request_id: str
    prompt_token_ids: List[int]
    sampling: SamplingParams
    # Called from the engine thread once per generated token (and once on
    # finish); return False to cancel (reference OutputCallback contract,
    # common/xllm/output.h:131).
    callback: Callable[[RequestOutput], bool]
    arrival_time: float = field(default_factory=time.monotonic)
    # PD disaggregation (prefill side): emit the first token, then hand the
    # sequence off instead of decoding (reference flow: prefill instance
    # returns the first chunk, decode instance continues —
    # rpc_service/service.h:61-71). `handoff` receives a KVHandoff.
    prefill_only: bool = False
    handoff: Optional[Callable[["KVHandoff"], None]] = None
    # Pipelined PD handoff (docs/PD_DISAGGREGATION.md): when set on a
    # prefill_only request, the chunked-prefill loop calls
    # `kv_stream.send_chunk(KVStreamChunk)` on the engine thread after each
    # PARTIAL chunk lands, exporting the newly completed full blocks while
    # the next chunk is still prefilling. The hook returns True when the
    # chunk was accepted for delivery (the blocks then ride the stream and
    # the final handoff carries only the tail); False — or a later
    # `kv_stream.aborted` — makes the final handoff monolithic again
    # (kv_start_block=0, full export). Single-chunk prompts never call it.
    kv_stream: Optional[object] = None
    # EPD multimodal: encoder-produced media embeddings [m, E] injected at
    # these absolute prompt positions (placeholder tokens). Requests with
    # media bypass the prefix cache — placeholder ids alone cannot key
    # content-addressed blocks across different images.
    mm_embeds: Optional[object] = None
    mm_positions: Optional[object] = None
    # Streamed encoder handoff (docs/EPD.md): embeddings are still
    # arriving per-item over the /mm/chunk session while this request is
    # admitted. The admission loop gates each prefill chunk on
    # `mm_stream.ready_upto(chunk_end)` — text chunks before the first
    # uncovered placeholder prefill WHILE the encoder streams — and
    # materializes mm_embeds/mm_positions from `assembled()` once every
    # item landed. Expiry (mm_stream_deadline_s) rejects the request;
    # abort alone does not (the monolithic fallback push completes it).
    mm_stream: Optional[object] = None
    # Per-media merged-token grids [(t, gh, gw), ...] in document order
    # (t > 1 = video): _mrope_positions lays the (t, h, w) streams from
    # these instead of inferring a square still-image grid from the span
    # length. Absent/short lists fall back to the inference.
    mm_grids: Optional[object] = None
    # Guided decoding: "json" constrains the output to a JSON object via
    # the engine's mask table (set_guided_context must have been called);
    # "json_schema" additionally constrains it to `schema` (a JSON-Schema
    # dict in the supported strict subset — guided/schema_fsm).
    guided: Optional[str] = None
    schema: Optional[dict] = None
    # Multi-LoRA adapter row in the executor's stacks (0 = base model).
    adapter_idx: int = 0
    # Mid-stream failover resume: the last `resume_from` entries of
    # prompt_token_ids are REPLAYED generation output from a dead
    # instance, not client prompt. The real engine needs no special
    # handling (re-prefill + continue IS resume; prefix caching makes the
    # replay cheap); deterministic stand-ins (FakeEngine) use it to keep
    # the continuation byte-identical to the unfaulted stream.
    resume_from: int = 0
    # Hybrid online/offline (north-star config 5; reference vestige
    # request.h:38, unconsumed there): offline work admits only behind
    # online work and its RUNNING decodes are preempted (recompute-style)
    # when online requests are waiting for slots or blocks.
    offline: bool = False

    @property
    def has_media(self) -> bool:
        return (
            self.mm_embeds is not None or self.mm_stream is not None
        ) and len(self.mm_positions or ()) > 0


@dataclass
class KVHandoff:
    """Everything a decode peer needs to continue a prefilled sequence.

    Only FULL committed blocks migrate; the sub-block tail (< block_size
    tokens plus the first generated token) is recomputed by the importer's
    prefill path, which keeps the chained-hash prefix-cache semantics exact
    on both sides. The TPU analog of the reference's RDMA KV pull whose
    handles the service relays (types.h:174-177): in-process peers receive
    `kv` as a device array (ICI path: jax.device_put to the peer mesh);
    cross-host peers receive it serialized over the data plane (DCN path).
    """

    request_id: str
    # prompt + the first generated token
    token_ids: List[int]
    first_token: int
    first_logprob: float
    num_full_blocks: int
    # chained hashes of the migrated full blocks, in order
    block_hashes: List[bytes]
    # [2, L, num_full_blocks - kv_start_block, Hkv, BS, D] (k, v stacked);
    # None when no full blocks remain to carry (short prompt -> pure
    # recompute on the decode side, or every block already rode the
    # streaming session)
    kv: Optional[object]
    usage_prompt_tokens: int = 0
    # Pipelined handoff: blocks [0, kv_start_block) were already delivered
    # through the per-chunk streaming session (they sit committed in the
    # importer's prefix cache); `kv` covers [kv_start_block,
    # num_full_blocks). 0 = monolithic payload, exactly the old contract.
    kv_start_block: int = 0


@dataclass
class KVStreamChunk:
    """One pipelined-handoff chunk: the full blocks completed by a partial
    prefill chunk, exported while later chunks are still prefilling.

    `block_hashes` are the chained hashes of blocks [start_block,
    start_block + n); `kv` is the device export [2, L, n, Hkv, BS, D]. The
    importer lands them straight into its prefix cache (content-addressed
    commit), so delivery order across chunks does not matter and a lost
    chunk only costs recompute of its span — never correctness."""

    request_id: str
    start_block: int
    block_hashes: List[bytes]
    kv: object
    prompt_tokens: int
    # Total full blocks the whole prompt will migrate (session sizing /
    # receive-side reservation hint).
    total_blocks_hint: int = 0


class _Seq:
    __slots__ = (
        "req", "slot", "tokens", "block_ids", "num_cached", "generated",
        "last_committed_block", "prefill_done_time", "last_token_time",
        "prefilled", "chunk_len", "prefill_start_time", "head_hash",
        "json_state", "json_upto", "schema_spec",
        "rope_pos3", "rope_delta", "admit_gen", "streamed_blocks",
        "stream_hashes", "admit_hashes", "pf_dispatched",
        "spec_ngrams", "spec_idx_upto",
    )

    def __init__(self, req: EngineRequest, slot: int):
        self.req = req
        self.slot = slot
        self.tokens: List[int] = list(req.prompt_token_ids)
        self.block_ids: List[int] = []
        self.num_cached = 0
        self.generated: List[Tuple[int, float]] = []  # (token, logprob)
        self.last_committed_block = -1  # index into block_ids
        self.prefill_done_time = 0.0
        self.last_token_time = 0.0
        # Chunked-prefill state: `prefilled` = prompt tokens whose KV is
        # already in this seq's cache blocks (>= num_cached once the first
        # partial chunk lands); `chunk_len` = this step's budgeted chunk.
        # A mid-prefill seq waits in the queue HOLDING its slot and blocks
        # (continued FIRST each step); decode steps run between chunks.
        self.prefilled = 0
        self.chunk_len = 0
        self.prefill_start_time = 0.0  # first chunk's t0 (true TTFT base)
        self.head_hash: Optional[bytes] = None  # block-0 chained hash
        # Guided decoding: exact JSON automaton state consumed up to
        # generated[json_upto]; lazily advanced by _guided_row (survives
        # preemption with the _Seq; rebuilt on PD import since the state
        # walks `generated`). None after an automaton reject = permissive
        # from then on (never expected under the mask; belt+braces).
        self.json_state = "INIT"
        self.json_upto = 0
        self.schema_spec = None  # compiled SchemaSpec, cached at first use
        # Qwen2-VL M-RoPE: [3, prompt_len] position streams + the (<= 0)
        # lag of generation rope positions behind token counts; None/0
        # for everything but media prompts on an mrope model.
        self.rope_pos3 = None
        self.rope_delta = 0
        # Pipelined PD handoff: full blocks already exported through the
        # request's kv_stream hook (the final handoff carries only
        # [streamed_blocks, num_full_blocks)); `stream_hashes` caches the
        # chained block hashes, extended incrementally per chunk.
        self.streamed_blocks = 0
        self.stream_hashes: List[bytes] = []
        # Admission-time chained hashes of the prompt's full blocks: the
        # mid-prefill re-match (_extend_midchunk_match) walks them at every
        # chunk boundary so blocks that land DURING chunked prefill — a
        # fabric peer fetch, a streamed PD chunk, another sequence's
        # commit — are adopted instead of recomputed. Empty for
        # media/LoRA requests (they bypass the cache).
        self.admit_hashes: List[bytes] = []
        # Bumped by _slot_admit: distinguishes a re-admission of the SAME
        # sequence object from the occupancy an in-flight step sampled for
        # (preempt + same-pass resume into the same slot must not let the
        # stale in-flight token through the drain's identity check).
        self.admit_gen = 0
        # Mixed (ragged) stepping: prompt tokens DISPATCHED through
        # prefill chunks, >= `prefilled` while a chunk is in flight — the
        # step builder cuts the next chunk from here so back-to-back
        # chunks pipeline instead of waiting out each drain.
        self.pf_dispatched = 0
        # Prompt-lookup drafting index (speculative decode): suffix
        # n-gram -> follow position over this sequence's own history,
        # extended incrementally per emitted token so proposing k drafts
        # is O(ngram_max^2) per step instead of a full history rescan
        # (_propose_drafts). `spec_idx_upto` = history length whose
        # gram-ends are indexed (always one short of len(tokens): the
        # newest gram has no follow token yet and must never self-match).
        self.spec_ngrams: Dict[tuple, int] = {}
        self.spec_idx_upto = 0


class _InFlight:
    """One dispatched-but-undrained decode step (overlapped pipeline).

    `tokens`/`logprobs` are DEVICE arrays still being computed; `slots`
    snapshots slot -> (_Seq, admit_gen) at dispatch time so the drain can
    tell whether a slot still belongs to the exact occupancy it sampled for
    (a seq finished, cancelled, preempted — or preempted and re-admitted —
    between dispatch and drain gets its late token discarded: the
    one-step-late stop semantics, docs/ENGINE_PIPELINE.md)."""

    __slots__ = (
        "tokens", "logprobs", "slots", "t0", "nactive", "total_ctx", "pf",
        "n_emit", "pf_tok", "pf_lp",
    )

    def __init__(
        self, tokens, logprobs, slots, t0, nactive, total_ctx, pf=(),
        n_emit=None, pf_tok=None, pf_lp=None,
    ):
        self.tokens = tokens
        self.logprobs = logprobs
        self.slots = slots
        self.t0 = t0
        self.nactive = nactive
        self.total_ctx = total_ctx
        # Mixed (ragged) step: [(seq, admit_gen, row_idx, chunk_start,
        # chunk_end)] prefill rows riding this dispatch — their sampled
        # tokens sit at output index R + row_idx (docs/KERNELS.md), or in
        # pf_tok/pf_lp when this is a speculative verify step.
        self.pf = pf
        # Pipelined speculative verify: tokens/logprobs are [R, S] and
        # each slot consumes its first n_emit[slot] entries at drain
        # (None = plain decode step). pf_tok/pf_lp carry the fused
        # prefill rows' samples ([P]) for verify steps.
        self.n_emit = n_emit
        self.pf_tok = pf_tok
        self.pf_lp = pf_lp


# The waiting queue holds fresh EngineRequests and preempted _Seqs (which
# resume with their full token history + generation accounting intact).
_QueueItem = "EngineRequest | _Seq"


class InferenceEngine:
    def __init__(
        self,
        engine_cfg: EngineConfig,
        executor: Optional[ModelExecutor] = None,
        eos_token_ids: Tuple[int, ...] = (),
    ):
        self.cfg = engine_cfg
        self.executor = executor or ModelExecutor(engine_cfg)
        self.eos_token_ids = set(eos_token_ids)
        self.block_size = self.executor.block_size
        self.R = self.executor.R
        self.max_blocks = self.executor.max_blocks_per_seq
        from xllm_service_tpu.runtime.native_blocks import create_block_manager

        self.block_mgr = create_block_manager(
            self.executor.num_blocks, self.block_size,
            seed=engine_cfg.murmur_hash3_seed,
        )
        # Host (DRAM) cache tier: committed blocks evicted from HBM are
        # copied to host memory and re-imported on a later prefix match
        # (num_host_blocks=0 disables — reference tier contract proto:47).
        # The SSD tier catches DRAM's own evictions on local disk.
        self.host_pool = None
        self.ssd_pool = None
        if engine_cfg.num_host_blocks > 0:
            from xllm_service_tpu.runtime.host_cache import HostKVPool, SsdKVPool

            self.host_pool = HostKVPool(engine_cfg.num_host_blocks)
            self.block_mgr.on_evict = self._offload_to_host
            if engine_cfg.num_ssd_blocks > 0:
                import os
                import tempfile

                directory = engine_cfg.ssd_cache_dir or os.path.join(
                    tempfile.gettempdir(), f"xllm-ssd-cache-{os.getpid()}"
                )
                self.ssd_pool = SsdKVPool(
                    directory, engine_cfg.num_ssd_blocks
                )

        self._waiting: Deque[EngineRequest] = collections.deque()  # guarded by: self._lock
        # KV imports from prefill peers, landed on the engine thread
        # (BlockManager is engine-thread-only).
        self._pending_imports: Deque[Tuple[EngineRequest, KVHandoff]] = (
            collections.deque()
        )
        # Streamed-chunk blocks from a pipelined PD handoff, landed on the
        # engine thread ahead of the session's commit.
        self._pending_kv_chunks: Deque[Tuple[List[bytes], object]] = (
            collections.deque()
        )
        # Prefix-fabric export requests (peer /kv/fetch): served on the
        # engine thread — the block manager and host/SSD pools are
        # engine-thread-only, and an off-thread export could read a block
        # mid-eviction. Each entry: {"hashes", "event", "result"}.
        self._pending_exports: Deque[dict] = collections.deque()  # guarded by: self._lock
        # Prefix-fabric coordinated eviction hook: called on the engine
        # thread as on_cold_evict(block_hash, host_kv) when a committed
        # block is about to leave the LAST local tier (host-pool eviction
        # with no SSD tier below it). Must never block — the instance
        # layer enqueues the offer and returns.
        self.on_cold_evict = None
        # Distributed-tracing hook: span_hook(request_id, stage, **fields)
        # set by the instance layer ONLY when tracing is enabled — None
        # keeps the token path free of any per-step tracing work.
        self.span_hook = None
        self._running: Dict[int, _Seq] = {}  # slot -> seq
        self._free_slots = list(range(self.R - 1, -1, -1))
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self._cancelled: set = set()  # guarded by: self._lock

        # Stepping mode: overlapped one-step-lookahead pipeline by default;
        # sync_engine=True (or XLLM_SYNC_ENGINE=1) forces fully synchronous
        # stepping; XLLM_SYNC_ENGINE=0 force-enables overlap over a
        # sync_engine=True config. Speculative decoding rides the pipeline
        # too (verify inputs gathered on-device from the in-flight step's
        # variable accepted counts) unless XLLM_SPEC_PIPELINE=0 /
        # enable_spec_pipeline=False degrades it to sync verify stepping.
        # Eligibility is a LIVE per-step decision — the `_force_sync`
        # property re-reads both hatches every step, so a flip lands on a
        # running engine at the next iteration (ISSUE 13 satellite); the
        # attribute below only snapshots the construction-time value for
        # introspection.
        import os as _os

        _env = _os.environ.get("XLLM_SYNC_ENGINE", "")
        self.sync_engine = (
            True if _env == "1"
            else False if _env == "0"
            else engine_cfg.sync_engine
        )

        # Mixed (ragged) stepping: the step builder emits ONE batch of
        # decode slots + due prefill chunks per iteration
        # (executor.mixed_start -> models.<family>.mixed_step ->
        # ops.attention.mixed_attention; docs/KERNELS.md) instead of
        # alternating a prefill step and a decode step. Split stepping is
        # the escape hatch: enable_mixed_step=False or XLLM_MIXED_STEP=0
        # (=1 force-enables over a False config); guided/sync/speculative
        # iterations and model families without a mixed_step (MLA) fall
        # back to split automatically.
        _menv = _os.environ.get("XLLM_MIXED_STEP", "")
        self.mixed_step_enabled = (
            True if _menv == "1"
            else False if _menv == "0"
            else engine_cfg.enable_mixed_step
        ) and getattr(self.executor, "supports_mixed", False)
        # Test hook: drive the ragged Pallas kernel branch in interpret
        # mode on CPU (the dispatcher convention every kernel follows).
        self._ragged_interpret = (
            _os.environ.get("XLLM_RAGGED_INTERPRET") == "1"
        )
        # Sequences mid-chunked-prefill under mixed stepping: they hold
        # slot + blocks (like split mode's waiting-held mid-chunk seqs)
        # but live HERE, keyed by request id, so the step builder can cut
        # chunk c+1 while chunk c is still in flight.
        self._pf_active: Dict[str, _Seq] = {}

        # Persistent decode-batch state: per-slot arrays mutated ONLY on
        # admit/finish/cancel/preempt (plus vectorized per-step position and
        # step-count advances) — the per-step O(R) SamplingBatch rebuild is
        # gone from the hot loop. `_ps_gen` bumps on every slot mutation and
        # keys the packed logit-bias cache.
        R = self.R
        self._block_tables = np.zeros((R, self.max_blocks), np.int32)
        self._ps_gen = 0
        self._ps_temps = np.zeros((R,), np.float32)
        self._ps_top_k = np.zeros((R,), np.int32)
        self._ps_top_p = np.ones((R,), np.float32)
        self._ps_seeds = np.zeros((R,), np.uint32)
        self._ps_steps = np.zeros((R,), np.int32)
        self._ps_presence = np.zeros((R,), np.float32)
        self._ps_frequency = np.zeros((R,), np.float32)
        self._ps_min_p = np.zeros((R,), np.float32)
        self._ps_adapter = np.zeros((R,), np.int32)
        self._ps_rope_delta = np.zeros((R,), np.int32)
        self._n_min_p = 0
        self._n_adapter = 0
        self._n_rope = 0
        self._n_bias = 0
        self._bias_rows: List[tuple] = [()] * R
        self._bias_cache: Tuple[Optional[np.ndarray], Optional[np.ndarray]] = (
            None, None,
        )
        self._bias_cache_gen = -1
        self._guided_slots: set = set()
        # Dispatch-side virtual state: positions/steps run one token AHEAD
        # of seq.tokens while a step is in flight (_ps_pending = dispatched
        # but not yet drained, 0 or 1 under one-step lookahead). `_fresh`
        # marks slots whose next input token must come from the host
        # (admission/resume/sync drain) instead of the in-flight device
        # sample.
        self._ps_active = np.zeros((R,), bool)
        self._ps_last_tok = np.zeros((R,), np.int32)
        self._ps_positions = np.zeros((R,), np.int32)
        self._ps_pending = np.zeros((R,), np.int32)
        self._ps_gen_count = np.zeros((R,), np.int32)
        self._ps_tok_count = np.zeros((R,), np.int32)
        self._ps_max_new = np.zeros((R,), np.int32)
        self._fresh = np.zeros((R,), bool)
        self._inflight: Optional[_InFlight] = None
        # Overlap accounting (exported via metrics + bench --engine-mode).
        self.decode_dispatches = 0
        self.mixed_steps = 0  # mixed dispatches actually carrying pf rows
        self.overlap_steps = 0
        # Collective-overlap accounting (ISSUE 18): dispatches whose
        # traced programs carry the ring collective-matmul schedule.
        # Resolved ONCE here — the hatch bakes into the jitted steps at
        # first trace, so a mid-run env flip doesn't change the programs
        # and must not change the count.
        self._overlap_collectives = (
            1 if getattr(self.executor, "overlap_collectives_active", False)
            else 0
        )
        self.collective_overlap_steps = 0
        self.late_stop_discards = 0
        self.loop_errors = 0
        self.kv_chunk_land_errors = 0
        self.host_gap_ms_sum = 0.0
        self.host_gap_steps = 0
        self._t_host_free: Optional[float] = None
        # Latency windows (ms) for LatencyMetrics.
        self._ttft_window: Deque[Tuple[float, float]] = collections.deque()
        self._tbt_window: Deque[Tuple[float, float]] = collections.deque()
        self._profile_ttft: List[Tuple[int, float]] = []
        self._profile_tpot: List[Tuple[int, int, float]] = []
        # Guided decoding context (set_guided_context): device mask table
        # lives on the executor; the engine keeps token bytes + row
        # liveness for exact host tracking.
        self._guided_tokens: Optional[List[bytes]] = None
        self._guided_row_any: Optional[np.ndarray] = None
        # json_schema mode: compiled specs by canonical schema key, the
        # (schema, exact-state) -> dynamic-row memo, the next free row in
        # the executor table's dynamic region, and the lazily built
        # first-byte token index the bitmap builder prefilters with.
        self._schema_specs: Dict[str, object] = {}
        self._schema_row_cache: Dict[tuple, int] = {}
        self._schema_row_next = 0
        self._schema_fbi = None
        self._schema_flush_pending = False
        # (schema, exact-state) -> [V] bool bitmap, shared between the
        # engine step loop and prewarm_schema (HTTP admission threads):
        # the vocab-wide Python byte walk is the expensive part of a
        # first state visit, and precomputing it at admission keeps the
        # step loop from stalling every running decode (advisor finding,
        # round 4). Plain dict ops are GIL-atomic; values are immutable.
        self._schema_bitmap_cache: Dict[tuple, np.ndarray] = {}
        self._prewarmed_schema_keys: set = set()
        self._guided_eos: Optional[List[int]] = None
        # Speculative-decoding accounting: verify steps run, slot-steps
        # (active sequences summed over steps), and tokens emitted — the
        # mean tokens/slot-step is the realized speedup over plain decode.
        self.spec_steps = 0
        self.spec_slot_steps = 0
        self.spec_tokens_emitted = 0
        # Composed-path accounting (ISSUE 13): verify steps dispatched
        # through the overlapped pipeline vs on the sync path, pipelined
        # dispatches that applied a guided mask row in-graph, and the
        # per-slot guided fallback — host-paced skips (a guided slot held
        # out of one dispatch so its NEXT mask row derives from the exact
        # host automaton state; the engine itself never flushes).
        self.spec_pipeline_steps = 0
        self.spec_sync_steps = 0
        self.guided_ingraph_steps = 0
        self.guided_paced_skips = 0
        # Prefix-cache effectiveness over fresh admissions (bench/metrics).
        self.prefix_cached_tokens = 0
        self.prefix_prompt_tokens = 0
        # Blocks adopted by the mid-prefill re-match (chunk-boundary cache
        # pickup of blocks that landed AFTER admission — fabric fetches,
        # streamed PD chunks, sibling commits).
        self.midprefill_adopted_blocks = 0
        # Recompute-preemption accounting (any cause: pool pressure,
        # hybrid-scheduling eviction).
        self.preemptions = 0
        self._build_metrics()

    def _build_metrics(self) -> None:
        """Engine registry (obs.metrics), rendered into the instance's
        /metrics and scraped by the master under an instance label. Hot
        paths observe histograms directly; everything already counted by
        an attribute (preemptions, prefix-cache, block manager, host
        tiers) exports via pull functions so the step loop pays nothing
        extra."""
        self.metrics = MetricsRegistry()
        self._m_ttft = self.metrics.histogram(
            "xllm_engine_ttft_ms", "Prefill time to first token",
            buckets=LATENCY_BUCKETS_MS,
        )
        self._m_tbt = self.metrics.histogram(
            "xllm_engine_tbt_ms", "Time between tokens per running "
            "sequence", buckets=LATENCY_BUCKETS_MS,
        )
        self._m_batch = self.metrics.histogram(
            "xllm_engine_decode_batch_size",
            "Active sequences per decode step (batch occupancy)",
            buckets=BATCH_BUCKETS,
        )
        self._m_steps = self.metrics.counter(
            "xllm_engine_decode_steps_total", "Decode (or verify) steps "
            "executed",
        )
        # Overlapped-pipeline instruments (docs/ENGINE_PIPELINE.md): the
        # host gap is the wall time between finishing one step's host
        # bookkeeping and dispatching the next decode step — the window the
        # device would idle through in sync mode; overlap hides it behind
        # the in-flight step.
        self._m_host_gap = self.metrics.histogram(
            "xllm_engine_host_gap_ms",
            "Host bookkeeping gap between one decode step's drain and the "
            "next dispatch", buckets=LATENCY_BUCKETS_MS,
        )
        self.metrics.gauge(
            "xllm_engine_overlap_depth",
            "Decode steps currently in flight on the device (0 = idle or "
            "sync mode, 1 = one-step lookahead active)",
        ).set_function(lambda: 1 if self._inflight is not None else 0)
        self.metrics.counter(
            "xllm_engine_overlapped_steps_total",
            "Decode steps dispatched while the prior step was still in "
            "flight",
        ).set_function(lambda: self.overlap_steps)
        # Collective-overlap + compile-cache instruments (ISSUE 18,
        # docs/OBSERVABILITY.md). Hit/miss semantics: a dispatch that
        # reused an already-lowered program is a hit; every fresh
        # lowering past the prewarm watermark is a miss (with no
        # prewarm, ALL lowerings are misses).
        self.metrics.counter(
            "xllm_engine_collective_overlap_steps_total",
            "Engine dispatches whose traced step programs carry the "
            "ring collective-matmul schedule (XLLM_OVERLAP_COLLECTIVES "
            "on a tp>1/ep>1 mesh)",
        ).set_function(lambda: self.collective_overlap_steps)
        self.metrics.counter(
            "xllm_engine_compile_cache_misses_total",
            "Fresh program lowerings past the prewarm watermark (the "
            "first-post-idle-recompile class prewarm_programs exists "
            "to kill)",
        ).set_function(lambda: self.compile_cache_misses())
        self.metrics.counter(
            "xllm_engine_compile_cache_hits_total",
            "Engine dispatches served from already-compiled programs "
            "(no fresh lowering)",
        ).set_function(lambda: self.compile_cache_hits())
        self.metrics.counter(
            "xllm_engine_compile_cache_prewarm_ms_total",
            "Wall-clock ms spent compiling the bucket-program family "
            "at instance start (prewarm_programs)",
        ).set_function(
            lambda: getattr(self.executor, "prewarm_ms", 0.0)
        )
        self.metrics.counter(
            "xllm_engine_late_stop_discards_total",
            "In-flight sampled tokens discarded because their sequence "
            "stopped/cancelled/preempted one step earlier",
        ).set_function(lambda: self.late_stop_discards)
        self.metrics.counter(
            "xllm_engine_loop_errors_total",
            "Engine-loop iterations that raised (loop stays alive)",
        ).set_function(lambda: self.loop_errors)
        # Mixed (ragged) step instruments (docs/KERNELS.md +
        # docs/OBSERVABILITY.md): how often the fused prefill+decode
        # dispatch runs and how it composes.
        self.metrics.counter(
            "xllm_engine_mixed_steps_total",
            "Engine steps that fused prefill chunk rows with the decode "
            "batch in one dispatch",
        ).set_function(lambda: self.mixed_steps)
        self._m_mixed_pf_rows = self.metrics.histogram(
            "xllm_engine_mixed_batch_prefill_rows",
            "Prefill chunk rows per mixed dispatch",
            buckets=BATCH_BUCKETS,
        )
        self._m_mixed_dec_rows = self.metrics.histogram(
            "xllm_engine_mixed_batch_decode_rows",
            "Active decode slots per mixed dispatch",
            buckets=BATCH_BUCKETS,
        )
        # Composed-path instruments (ISSUE 13, docs/ENGINE_PIPELINE.md):
        # speculative verify inside the overlapped pipeline + in-graph
        # guided masking, with the per-slot fallback counters.
        self._m_spec_accepted = self.metrics.histogram(
            "xllm_engine_spec_accepted_len",
            "Tokens emitted per slot per speculative verify step "
            "(accepted prefix + the corrected/bonus token)",
            buckets=BATCH_BUCKETS,
        )
        self.metrics.counter(
            "xllm_engine_spec_pipeline_steps_total",
            "Speculative verify steps dispatched through the overlapped "
            "pipeline (device-resident accepted-token feedback)",
        ).set_function(lambda: self.spec_pipeline_steps)
        self.metrics.counter(
            "xllm_engine_spec_sync_steps_total",
            "Speculative verify steps run on the sync path (hatch or "
            "transition fallback)",
        ).set_function(lambda: self.spec_sync_steps)
        self.metrics.counter(
            "xllm_engine_guided_ingraph_steps_total",
            "Pipelined dispatches that applied at least one guided mask "
            "row in-graph (no engine flush)",
        ).set_function(lambda: self.guided_ingraph_steps)
        self.metrics.counter(
            "xllm_engine_guided_paced_skips_total",
            "Guided slots held out of one pipelined dispatch so their "
            "next mask row derives from the exact host automaton state "
            "(the per-slot — not per-engine — fallback)",
        ).set_function(lambda: self.guided_paced_skips)
        # Resolved attention-dispatch accounting: which kernel actually
        # served each engine dispatch (the env var alone told the record
        # nothing — ISSUE 9). Names resolve once at engine build from the
        # executor's cache/geometry (kernel choices are process-static:
        # the jitted steps bake them in at first trace).
        self._m_kernel_dispatch = self.metrics.counter(
            "xllm_engine_kernel_dispatch_total",
            "Engine device dispatches by resolved attention kernel",
            labelnames=("kernel",),
        )
        rep = (
            self.executor.kernel_report()
            if hasattr(self.executor, "kernel_report") else {}
        )
        self._kernel_names = {
            "decode": rep.get("decode", "unknown"),
            "prefill": rep.get("prefill", "unknown"),
            "mq": rep.get("mq", "unknown"),
            # The report resolves XLLM_RAGGED_INTERPRET (incl. tile
            # eligibility), so "ragged" here means the ragged branch
            # actually dispatches — not merely that a hook is set.
            "mixed": (
                "ragged" if rep.get("mixed") == "ragged"
                else f"mixed[{rep.get('decode', '?')}+"
                f"{rep.get('prefill', '?')}]"
            ),
        }
        self.metrics.counter(
            "xllm_engine_kv_chunk_land_errors_total",
            "Streamed PD chunks that failed to land into the prefix "
            "cache after being acked (their span recomputes at commit)",
        ).set_function(lambda: self.kv_chunk_land_errors)
        self.metrics.counter(
            "xllm_engine_preemptions_total",
            "Recompute-style preemptions (pool pressure + hybrid "
            "eviction)",
        ).set_function(lambda: self.preemptions)
        self.metrics.counter(
            "xllm_engine_prefix_cached_tokens_total",
            "Prompt tokens served from the prefix cache at admission",
        ).set_function(lambda: self.prefix_cached_tokens)
        self.metrics.counter(
            "xllm_engine_prefix_prompt_tokens_total",
            "Prompt tokens eligible for prefix-cache matching",
        ).set_function(lambda: self.prefix_prompt_tokens)
        self.metrics.counter(
            "xllm_engine_midprefill_rematch_blocks_total",
            "KV blocks adopted at a chunk boundary after landing "
            "mid-prefill (fabric fetches, streamed PD chunks, sibling "
            "commits)",
        ).set_function(lambda: self.midprefill_adopted_blocks)
        # NO waiting-depth / KV-usage gauges here: the instance front door
        # already exports those via get_load_metrics (they would duplicate
        # xllm_engine_waiting_requests / xllm_engine_kv_cache_usage in the
        # same merged exposition).
        self.metrics.gauge(
            "xllm_engine_running_requests", "Sequences holding decode "
            "slots",
        ).set_function(lambda: len(self._running))
        self.metrics.counter(
            "xllm_engine_block_evictions_total",
            "Committed blocks evicted from the device pool",
        ).set_function(lambda: getattr(self.block_mgr, "evictions_total", 0))
        self.metrics.counter(
            "xllm_engine_host_cache_hits_total",
            "Host (DRAM) tier prefix-block hits",
        ).set_function(
            lambda: getattr(self.host_pool, "hits", 0)
            if self.host_pool is not None else 0
        )
        self.metrics.counter(
            "xllm_engine_host_cache_misses_total",
            "Host (DRAM) tier lookups that missed",
        ).set_function(
            lambda: getattr(self.host_pool, "misses", 0)
            if self.host_pool is not None else 0
        )
        self.metrics.counter(
            "xllm_engine_host_cache_evictions_total",
            "Blocks LRU-evicted from the host (DRAM) tier",
        ).set_function(
            lambda: getattr(self.host_pool, "evictions", 0)
            if self.host_pool is not None else 0
        )
        # Grouped-MoE dispatch instruments (docs/MOE.md +
        # docs/OBSERVABILITY.md): expert load, capacity overflow, and
        # group occupancy for the grouped ragged expert dispatch —
        # pull-only from the executor's async-callback accumulators, so
        # the step loop and the overlap pipeline pay nothing. The
        # hot-expert share doubles as the per-instance load signal the
        # master's routing reads next to cache hits
        # (LoadMetrics.moe_hot_expert_frac).
        ex = self.executor
        if getattr(getattr(ex, "cfg", None), "is_moe", False) and hasattr(
            ex, "moe_stats"
        ):
            # One moe_stats() snapshot serves the whole scrape: the
            # scalar metrics plus num_experts gauge children would
            # otherwise re-lock and copy the counts array N+3 times per
            # render (256 experts on a V3-class config). 0.25 s staleness
            # is invisible at scrape cadence; dict swaps are GIL-atomic.
            _memo = {"t": 0.0, "s": None}

            def _snap():
                now = time.monotonic()
                if _memo["s"] is None or now - _memo["t"] > 0.25:
                    _memo["s"] = ex.moe_stats()
                    _memo["t"] = now
                return _memo["s"]

            self.metrics.counter(
                "xllm_engine_moe_assignments_total",
                "Routed (token, expert) assignments dispatched through "
                "the grouped MoE path, summed over layers",
            ).set_function(lambda: _snap()["assignments"])
            self.metrics.counter(
                "xllm_engine_moe_dropped_total",
                "Assignments dropped at expert-group capacity "
                "(XLLM_MOE_CAPACITY_FACTOR overflow)",
            ).set_function(lambda: _snap()["dropped"])
            self.metrics.gauge(
                "xllm_engine_moe_hot_expert_frac",
                "Hottest expert's share of routed assignments "
                "(cumulative; 1/num_experts = perfectly balanced)",
            ).set_function(lambda: _snap()["hot_expert_frac"])
            self.metrics.gauge(
                "xllm_engine_moe_group_occupancy_frac",
                "Live rows per grouped-dispatch capacity row "
                "(cumulative; low = capacity over-provisioned)",
            ).set_function(lambda: _snap()["occupancy_frac"])
            g = self.metrics.gauge(
                "xllm_engine_moe_expert_load",
                "Per-expert share of routed assignments (cumulative)",
                labelnames=("expert",),
            )
            for i in range(int(ex.moe_stats()["experts"])):
                def _share(i=i):
                    s = _snap()
                    return (
                        float(s["expert_counts"][i]) / s["assignments"]
                        if s["assignments"] else 0.0
                    )
                g.labels(expert=str(i)).set_function(_share)

    # -------------------------------------------------------------- public

    def add_request(self, req: EngineRequest) -> None:
        with self._lock:
            self._waiting.append(req)
        self._work.set()

    def wake(self) -> None:
        """External work signal (streamed mm chunk landed, etc.): a
        request parked at an admission gate re-checks without waiting
        out the loop's idle poll."""
        self._work.set()

    def cancel(self, request_id: str) -> None:
        with self._lock:
            self._cancelled.add(request_id)
        self._work.set()

    def has_work(self) -> bool:
        return bool(
            self._waiting
            or self._running
            or self._pf_active
            or self._pending_imports
            or self._pending_kv_chunks
            or self._pending_exports
            or self._inflight is not None
        )

    def compile_cache_misses(self) -> int:
        """Fresh lowerings past the executor's prewarm watermark (every
        lowering when nothing was prewarmed)."""
        ex = self.executor
        count = getattr(ex, "lowering_count", None)
        if count is None:
            return 0
        return max(0, count() - getattr(ex, "prewarmed_lowerings", 0))

    def compile_cache_hits(self) -> int:
        """Dispatches that reused an already-compiled program."""
        return max(0, self.decode_dispatches - self.compile_cache_misses())

    def start(self) -> None:
        if self.cfg.warmup_on_start and hasattr(self.executor, "warmup"):
            # With a keyed persistent cache dir configured, walk the
            # FULL bucket-program family (runtime/compile_cache.py) so
            # no first-post-idle dispatch ever lowers fresh — the disk
            # cache amortizes the enumeration across restarts. Without
            # a dir the full walk would pay its whole compile bill
            # every start, so keep the classic split-step warmup.
            if compile_cache_mod.resolve_cache_dir(self.cfg) and hasattr(
                self.executor, "prewarm_programs"
            ):
                self.executor.prewarm_programs()
            else:
                self.executor.warmup()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        self._work.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self.ssd_pool is not None:
            self.ssd_pool.close()

    # ------------------------------------------------------------- metrics

    def get_load_metrics(self) -> LoadMetrics:
        # Expert hotness rides the heartbeat-visible load snapshot so the
        # master can weigh MoE routing skew next to cache hits (ISSUE 15;
        # 0.0 for dense models / grouped dispatch off — the field is
        # inert). The read is scrape-safe: it never drains the pipeline.
        moe_frac = 0.0
        ex = self.executor
        if getattr(getattr(ex, "cfg", None), "is_moe", False) and hasattr(
            ex, "moe_stats"
        ):
            moe_frac = float(ex.moe_stats()["hot_expert_frac"])
        return LoadMetrics(
            waiting_requests_num=len(self._waiting),
            gpu_cache_usage_perc=self.block_mgr.usage,
            moe_hot_expert_frac=moe_frac,
        )

    def get_latency_metrics(self, window_s: float = 30.0) -> LatencyMetrics:
        now = time.monotonic()
        for dq in (self._ttft_window, self._tbt_window):
            while dq and now - dq[0][0] > window_s:
                dq.popleft()
        return LatencyMetrics(
            recent_max_ttft=int(max((v for _, v in self._ttft_window), default=0)),
            recent_max_tbt=int(max((v for _, v in self._tbt_window), default=0)),
        )

    def take_cache_event(self) -> KvCacheEvent:
        return self.block_mgr.take_cache_event()

    def cache_snapshot(self) -> list:
        """Every committed prefix-cache block hash — the takeover
        reconciliation manifest (POST /reconcile) and the fabric's
        post-ejection heartbeat cache resync. Racy read by design: a hash
        that commits or evicts mid-snapshot merely drifts the master's
        index by one heartbeat (both block managers retry the rare
        resize-during-iteration internally)."""
        fn = getattr(self.block_mgr, "committed_hashes", None)
        return fn() if callable(fn) else []

    def cache_snapshot_event(self) -> KvCacheEvent:
        """Full-tier cache snapshot as a KvCacheEvent — the heartbeat
        cache RESYNC payload after the master pruned this instance's
        index locations (breaker ejection): HBM commits as stored, host/
        SSD holdings as offload entries, so every tier's locations
        rebuild, not just the hot one. Racy off-thread reads like
        cache_snapshot: one-beat drift is the contract."""
        stored = set(self.cache_snapshot())
        offload: Dict[bytes, str] = {}
        for pool, tier in ((self.host_pool, "dram"), (self.ssd_pool, "ssd")):
            if pool is None:
                continue
            for h in pool.hashes():
                if h not in stored and h not in offload:
                    offload[h] = tier
        return KvCacheEvent(stored_cache=stored, offload_cache=offload)

    def profiling_data(self):
        return list(self._profile_ttft), list(self._profile_tpot)

    # ---------------------------------------------------------------- loop

    def _loop(self) -> None:
        # This thread owns the slot arrays, block manager, and host/SSD
        # pools until the loop exits (docs/STATIC_ANALYSIS.md): the
        # @thread_owned("engine") surfaces runtime-assert it under
        # XLLM_THREAD_CHECKS=1, and graftlint's thread-ownership pass
        # checks their call sites statically.
        claim_thread(self, "engine")
        try:
            self._loop_owned()
        finally:
            release_thread(self, "engine")

    @thread_owned("engine")
    def _loop_owned(self) -> None:
        log = logging.getLogger(__name__)
        while not self._stop:
            if not self.has_work():
                self._work.wait(timeout=0.05)
                self._work.clear()
                continue
            try:
                produced = self.step()
                if produced == 0 and self._inflight is None:
                    # Waiting work that cannot run yet (e.g. blocked on KV
                    # capacity): sleep on the work event — set when KV
                    # blocks are freed (_finish), imports/cancels land, or
                    # new requests arrive — instead of a blind busy-backoff.
                    self._work.wait(timeout=0.05)
                    self._work.clear()
            except Exception:  # pragma: no cover — keep the loop alive
                self.loop_errors += 1
                log.exception("engine loop iteration failed")
                time.sleep(0.1)

    # ---------------------------------------------------------------- step

    @property
    def _force_sync(self) -> bool:
        """LIVE pipeline-eligibility decision (ISSUE 13 satellite): the
        XLLM_SYNC_ENGINE and XLLM_SPEC_PIPELINE hatches are re-read on
        every step, so flipping either on a running engine takes effect
        at the next iteration — step() flushes the in-flight step at the
        transition. Guided sequences no longer appear here: they ride
        the pipeline host-paced (per-slot, see _apply_guided_pacing)."""
        import os as _os

        _env = _os.environ.get("XLLM_SYNC_ENGINE", "")
        sync = (
            True if _env == "1"
            else False if _env == "0"
            else self.cfg.sync_engine
        )
        if sync:
            return True
        if self.cfg.speculative_tokens > 0:
            _senv = _os.environ.get("XLLM_SPEC_PIPELINE", "")
            return not (
                True if _senv == "1"
                else False if _senv == "0"
                else self.cfg.enable_spec_pipeline
            )
        return False

    @thread_owned("engine")
    def step(self) -> int:
        """One engine iteration: land migrated KV, admit + prefill new
        requests, then one decode (or speculative verify) step. Returns
        number of tokens produced.

        Overlapped mode (default): the dispatch for step N+1 happens
        BEFORE step N's results are consumed, so host bookkeeping runs
        while the device computes — for plain decode AND speculative
        verify (step N+1's verify inputs are gathered on-device from
        step N's variable accepted counts). Guided sequences ride the
        pipeline host-paced per slot. Sync mode — the escape hatch, or
        XLLM_SPEC_PIPELINE=0 degrading speculative engines — fetches and
        books each step before dispatching the next; the eligibility
        decision is re-made every step so hatch flips land mid-run."""
        if not self._running and self._inflight is None:
            self._t_host_free = None  # idle time is not a host gap
        self._drain_imports()
        self._drain_export_requests()
        self._drain_cancelled()
        self._maybe_flush_schema_rows()
        if self._force_sync:
            # Sync path (hatch / spec-pipeline degrade): flush the
            # pipeline at the transition (_flush_pipeline_state drains
            # the in-flight step and requeues mixed-held mid-prefill
            # seqs into the split midchunk flow).
            produced0 = self._flush_pipeline_state()
            admitted = self._admit()
            produced = self._decode_once()
            return produced0 + admitted + produced
        if self.cfg.speculative_tokens > 0:
            # Pipelined speculative stepping: draft+verify as a
            # pipelined unit, fused with due prefill chunks when the
            # model family supports it (docs/ENGINE_PIPELINE.md).
            return self._step_spec()
        if self.mixed_step_enabled:
            # Mixed (ragged) stepping: ONE dispatch carries the decode
            # batch AND the due prefill chunks (docs/KERNELS.md).
            return self._step_mixed()
        produced0 = 0
        if self._pf_active:
            # Mode flip mid-prefill (mixed stepping turned off): drain
            # the in-flight mixed step, requeue the held seqs.
            produced0 = self._flush_pipeline_state()
        admitted = self._admit()
        produced = self._step_overlap()
        return produced0 + admitted + produced

    @thread_owned("engine")
    def _step_overlap(self) -> int:
        """One pipeline iteration: dispatch decode step N+1 (fed from step
        N's device-resident tokens), THEN drain/book step N while N+1 runs."""
        nxt = self._dispatch_decode()
        produced = self._drain_step(self._inflight, nxt)
        self._inflight = nxt
        return produced

    @thread_owned("engine")
    def _flush_inflight(self) -> int:
        """Drain any in-flight step without dispatching a successor (mode
        transitions and shutdown): surviving slots return to host feeding."""
        produced = self._drain_step(self._inflight, None)
        self._inflight = None
        return produced

    @thread_owned("engine")
    def _flush_pipeline_state(self) -> int:
        """Mode-transition flush: drain the in-flight step AND hand any
        mixed-held mid-prefill seqs back to the split midchunk flow —
        they keep slot + blocks and continue FIRST, like any split-mode
        mid-chunk seq. One implementation for every transition (sync
        hatch, mixed-off flip, spec fuse-support flip)."""
        produced = self._flush_inflight()
        if self._pf_active:
            with self._lock:
                self._waiting.extendleft(
                    reversed(list(self._pf_active.values()))
                )
            self._pf_active.clear()
        return produced

    # ------------------------------------------------ mixed (ragged) step

    @thread_owned("engine")
    def _step_mixed(self) -> int:
        """One mixed-pipeline iteration: cut the due prefill chunks
        (continuations first — they hold slots and blocks — then fresh
        admissions), dispatch them FUSED with decode step N+1, then
        drain/book step N while N+1 runs. Ineligible admissions (media /
        guided / SP) prefill through the split path in the same
        iteration; the overlap contract (device-resident decode
        feedback, one-step-late stops) is unchanged
        (docs/ENGINE_PIPELINE.md + docs/KERNELS.md)."""
        items_meta: List[tuple] = []
        budget = self._continue_pf_chunks(
            items_meta, self.cfg.max_prefill_tokens
        )
        legacy = self._admit(mixed_collect=items_meta, budget=budget)
        nxt = self._dispatch_mixed(items_meta)
        produced = self._drain_step(self._inflight, nxt)
        self._inflight = nxt
        return legacy + produced

    @thread_owned("engine")
    def _continue_pf_chunks(self, items_meta: List[tuple],
                            budget: int) -> int:
        """Cut the next chunk for every mid-prefill seq (_pf_active) with
        tokens left to dispatch. Back-to-back chunks PIPELINE: chunk c+1
        is cut from `pf_dispatched` (the dispatched extent) while chunk c
        is still in flight, so chunked prefill advances every iteration
        like split mode — drain-side bookkeeping (`prefilled`, KV
        streaming, finish) stays one step behind. The chunk-boundary
        cache re-match runs at the DISPATCHED frontier even while a
        chunk is in flight — in-flight chunks only write below the
        frontier, so frontier-aligned adoption never touches their
        blocks (see the call-site comment and _extend_midchunk_match).

        One mixed dispatch carries ONE padded-length bucket (the first
        due chunk's), exactly like _prefill_group's same-bucket grouping:
        a prefill row's numerics are only byte-stable at a fixed Lpad, so
        padding a short chunk to a longer peer's bucket would break
        mixed ≡ split parity (docs/KERNELS.md). Mismatched seqs stop the
        walk (FIFO head-of-line, like the split queue) and ride the next
        iteration's dispatch."""
        group_max = getattr(self.executor, "PREFILL_GROUP_MAX", 8)
        bucket = None
        for seq in list(self._pf_active.values()):
            if budget <= 0 or len(items_meta) >= group_max:
                break
            if seq.pf_dispatched >= len(seq.tokens):
                continue  # final chunk in flight; waiting on its drain
            # Adopt blocks that landed since the last boundary (fabric
            # fetch, streamed PD chunk, sibling commit) at the DISPATCHED
            # frontier — live even while a chunk is in flight (the chunk
            # writes only below the frontier). The hash chain covers at
            # most tokens[:n-1], so at least the final token always
            # remains to dispatch.
            seq.pf_dispatched += self._extend_midchunk_match(
                seq, frontier=seq.pf_dispatched
            )
            chunk = min(len(seq.tokens) - seq.pf_dispatched, budget)
            b = self.executor.bucket_len(chunk)
            if bucket is None:
                bucket = b
            elif b != bucket:
                break
            items_meta.append((seq, seq.pf_dispatched, chunk))
            budget -= chunk
        return budget

    @thread_owned("engine")
    def _build_pf_items(self, items_meta: List[tuple], t0: float):
        """PrefillItems + drain entries for the due chunks riding a
        fused dispatch (shared by _dispatch_mixed and _dispatch_verify).
        Guided seqs' FINAL chunks carry their host-derived mask row —
        exact at dispatch, because a mid-prefill seq has no decode step
        in flight (its automaton state is host truth)."""
        from xllm_service_tpu.runtime.executor import PrefillItem

        items = []
        pf_entries = []
        for j, (seq, start, n) in enumerate(items_meta):
            s = seq.req.sampling
            table = np.zeros((self.max_blocks,), np.int32)
            table[: len(seq.block_ids)] = seq.block_ids
            final = start + n >= len(seq.tokens)
            # First chunk: TTFT base. The unset check (0.0 = never set)
            # covers a deferred first chunk whose start moved past
            # num_cached via frontier adoption before it dispatched.
            if start <= seq.num_cached or seq.prefill_start_time == 0.0:
                seq.prefill_start_time = t0
            items.append(PrefillItem(
                token_ids=np.asarray(seq.tokens[start:start + n], np.int32),
                start_pos=start,
                block_table=table,
                temperature=s.temperature,
                top_k=s.top_k,
                top_p=s.top_p,
                seed=s.seed,
                step=len(seq.generated),
                presence=getattr(s, "presence_penalty", 0.0),
                frequency=getattr(s, "frequency_penalty", 0.0),
                # Final-chunk-only sampling features, exactly like the
                # split path (_prefill_admitted): intermediate chunks'
                # sampled tokens are discarded.
                logit_bias=(
                    tuple(getattr(s, "logit_bias", ()) or ())
                    if final else ()
                ),
                mask_row=(
                    self._guided_row(seq)
                    if final and seq.req.guided
                    and self._guided_tokens is not None
                    else -1
                ),
                adapter_idx=seq.req.adapter_idx,
                min_p=getattr(s, "min_p", 0.0) if final else 0.0,
                prior_tokens=(
                    np.asarray([t for t, _ in seq.generated], np.int32)
                    if seq.generated and final
                    and (
                        getattr(s, "presence_penalty", 0.0)
                        or getattr(s, "frequency_penalty", 0.0)
                    )
                    else None
                ),
            ))
            pf_entries.append((seq, seq.admit_gen, j, start, start + n))
            seq.pf_dispatched = start + n
        return items, pf_entries

    @thread_owned("engine")
    def _apply_guided_pacing(self, can: np.ndarray) -> np.ndarray:
        """Per-slot guided pipeline rule (docs/ENGINE_PIPELINE.md): a
        guided slot joins a dispatch only when NO step of its own is in
        flight, so its mask row derives from the EXACT host automaton
        state (which has consumed every emitted token). The slot runs
        host-paced — every other pipeline iteration — instead of
        flushing the whole engine; unguided slots are unaffected."""
        for slot in self._guided_slots:
            if can[slot] and self._ps_pending[slot] > 0:
                can[slot] = False
                self.guided_paced_skips += 1
        return can

    def _guided_mask_rows(self, can: np.ndarray) -> Optional[np.ndarray]:
        """[R] mask-table rows for the guided slots riding this dispatch
        (None when none do). Dispatched guided slots are always
        host-paced fresh, so _guided_row sees the exact state."""
        if self._guided_tokens is None or not self._guided_slots:
            return None
        rows = None
        for slot in self._guided_slots:
            if can[slot]:
                if rows is None:
                    rows = np.full(
                        (self.R,), self.executor.permissive_row, np.int32
                    )
                rows[slot] = self._guided_row(self._running[slot])
        return rows

    @thread_owned("engine")
    def _dispatch_mixed(self, items_meta: List[tuple]) -> Optional[_InFlight]:
        """Dispatch decode step N+1 fused with the due prefill chunks as
        ONE device step (executor.mixed_start). With no due chunks this
        is exactly _dispatch_decode — the fused shapes only compile when
        a mixed batch actually exists."""
        if not items_meta:
            return self._dispatch_decode()
        R = self.R
        can = (
            self._ps_active
            & (self._ps_gen_count + self._ps_pending < self._ps_max_new)
            & (
                self._ps_tok_count + self._ps_pending
                < self.cfg.max_seq_len
            )
        )
        can = self._apply_guided_pacing(can)
        if can.any():
            self._ensure_decode_capacity(1, mask=can)
            can &= self._ps_active  # the capacity pass may have preempted
        batch = self._sampling_batch_view()
        rows = self._guided_mask_rows(can)
        if rows is not None:
            batch.mask_rows = rows
            self.guided_ingraph_steps += 1
        prev = self._inflight
        fresh_mask = self._fresh | ~can
        assert prev is not None or bool(fresh_mask[can].all())
        self._observe_host_gap()
        t0 = time.monotonic()
        items, pf_entries = self._build_pf_items(items_meta, t0)
        prev_tokens = prev.tokens[:R] if prev is not None else None
        tokens, logprobs = self.executor.mixed_start(
            items,
            self._ps_last_tok,
            fresh_mask,
            prev_tokens,
            self._ps_positions,
            self._block_tables,
            can,
            batch,
            interpret=self._ragged_interpret,
        )
        nactive = int(can.sum())
        total_ctx = int(self._ps_positions[can].sum()) + nactive
        snapshot = {}
        for slot in np.nonzero(can)[0]:
            seq = self._running[int(slot)]
            snapshot[int(slot)] = (seq, seq.admit_gen)
        self._ps_pending[can] += 1
        self._ps_positions[can] += 1
        self._ps_steps[can] += 1
        self._fresh[can] = False
        self._m_batch.observe(nactive)
        self._m_steps.inc()
        self.decode_dispatches += 1
        self.collective_overlap_steps += self._overlap_collectives
        self.mixed_steps += 1
        self._m_mixed_pf_rows.observe(len(items))
        self._m_mixed_dec_rows.observe(nactive)
        self._m_kernel_dispatch.labels(
            kernel=self._kernel_names["mixed"]
        ).inc()
        if prev is not None:
            self.overlap_steps += 1
        return _InFlight(
            tokens, logprobs, snapshot, t0, nactive, total_ctx,
            pf=pf_entries,
        )

    # ------------------------------------------------------------ admission

    @staticmethod
    def _item_req(item) -> EngineRequest:
        return item.req if isinstance(item, _Seq) else item

    @thread_owned("engine")
    def _drain_cancelled(self) -> None:
        dropped = []
        with self._lock:
            cancelled = self._cancelled
            self._cancelled = set()
            if not cancelled:
                return
            kept: Deque = collections.deque()
            for item in self._waiting:
                if self._item_req(item).request_id in cancelled:
                    dropped.append(item)
                else:
                    kept.append(item)
            self._waiting = kept
        for item in dropped:
            # A mid-chunk seq waits HOLDING its slot and blocks — release
            # both (ordinary waiting items hold neither).
            if isinstance(item, _Seq) and item.block_ids:
                self.block_mgr.free(item.block_ids)
                item.block_ids = []
                self._free_slots.append(item.slot)
            self._notify_cancelled(self._item_req(item))
        # Mixed-step mid-prefill seqs hold slot + blocks in _pf_active:
        # release both; any chunk still in flight for them drains to a
        # discard (the pf identity check below misses on the removed
        # entry) — the freed blocks' device writes are ordered before any
        # re-user's, exactly the late-stop-discard argument.
        for rid, seq in list(self._pf_active.items()):
            if rid in cancelled:
                del self._pf_active[rid]
                self.block_mgr.free(seq.block_ids)
                seq.block_ids = []
                self._free_slots.append(seq.slot)
                self._notify_cancelled(seq.req)
        for slot, seq in list(self._running.items()):
            if seq.req.request_id in cancelled:
                self._finish(seq, FinishReason.NONE, cancelled=True)

    @thread_owned("engine")
    def _admit(self, mixed_collect=None, budget=None) -> int:
        """Admit waiting requests up to max_prefill_tokens and prefill them
        in BATCHED compiled steps (executor.prefill_batch groups by length
        bucket) — one slow prefill no longer serializes the whole queue and
        concurrent short prompts share a single device step (round-1 weak
        item 4).

        Mixed (ragged) stepping passes `mixed_collect`: freshly admitted
        seqs ELIGIBLE for the fused step (plain text — no media/stream,
        no guided mask, no SP-ring routing) are appended there (and
        registered in _pf_active) instead of prefilling here; their
        chunks ride the SAME dispatch as the decode batch
        (_dispatch_mixed). Ineligible requests keep the split prefill
        path below, in the same iteration."""
        if budget is None:
            budget = self.cfg.max_prefill_tokens
        pool_capacity = self.block_mgr.num_blocks - 1
        rejects: List[Tuple[EngineRequest, StatusCode, str]] = []
        batch: List[_Seq] = []
        # Full-block hashes the CURRENT batch will commit. A waiting request
        # sharing a prefix with an in-batch member (chained hashes: any
        # overlap implies block-0 overlap) is deferred one step so it
        # prefix-matches the committed blocks instead of redundantly
        # prefilling the shared prefix in the same batched step.
        pending_hashes: set = set()

        # Streamed-media requests deferred this round (embeddings for
        # their next chunk still in flight): re-fronted after the scan so
        # they never head-of-line-block text traffic behind them.
        deferred: List = []

        # Mid-chunk seqs continue FIRST, wherever they sit in the queue: a
        # preempted/blocked item appendleft'd in front of one must not
        # starve it — it HOLDS slot + blocks that only further chunks can
        # turn into output (it is not in _running, so it is neither
        # preemptible nor evictable; skipping it could deadlock the pool).
        with self._lock:
            midchunk = [
                x
                for x in self._waiting
                if isinstance(x, _Seq) and x.block_ids
            ]
            for x in midchunk:
                self._waiting.remove(x)
        for seq in midchunk:
            if seq.req.mm_stream is not None:
                # Streamed encoder handoff (docs/EPD.md): the next chunk
                # may only run once every placeholder it covers has
                # landed — text-only chunks before the first uncovered
                # placeholder keep prefilling while the encoder streams.
                pos_end = seq.prefilled + min(
                    len(seq.tokens) - seq.prefilled, max(budget, 1)
                )
                gate = self._mm_gate(seq.req, pos_end)
                if gate == "wait":
                    # Park in `deferred` (re-fronted after the scan), NOT
                    # back into _waiting: the head-admission loop below
                    # treats any _Seq it sees as fresh/preempted — it
                    # would pop a second slot and overwrite the held
                    # block_ids (leaking both) if this seq reached it.
                    deferred.append(seq)
                    continue
                if gate != "ready":
                    # Expired/desynced stream: release the held slot +
                    # blocks (this seq is not in _running — nothing else
                    # can reclaim them) and error-finish.
                    self.block_mgr.free(seq.block_ids)
                    seq.block_ids = []
                    self._free_slots.append(seq.slot)
                    rejects.append(
                        (seq.req, StatusCode.UNAVAILABLE, gate)
                    )
                    continue
            # Mid-prefill re-match: blocks that landed since the last
            # chunk (a fabric peer fetch racing this prefill, a streamed
            # PD chunk, a sibling's commit) are adopted at the chunk
            # boundary — the remaining tail shrinks instead of
            # recomputing KV the cache now holds.
            self._extend_midchunk_match(seq)
            chunk = min(len(seq.tokens) - seq.prefilled, max(budget, 1))
            budget -= chunk
            seq.chunk_len = chunk
            if seq.head_hash is not None:
                pending_hashes.add(seq.head_hash)
            batch.append(seq)

        # Priority admission (hybrid online/offline): stable-partition the
        # queue so every online item precedes every offline one. Relative
        # order within each class is preserved; mid-chunk seqs were
        # already extracted above, so nothing here holds blocks.
        with self._lock:
            if any(self._item_req(x).offline for x in self._waiting) and any(
                not self._item_req(x).offline for x in self._waiting
            ):
                ordered = sorted(
                    self._waiting, key=lambda x: self._item_req(x).offline
                )  # sort is stable: online (False) first
                self._waiting.clear()
                self._waiting.extend(ordered)

        while budget > 0:
            with self._lock:
                if not self._waiting:
                    break
                head_item = self._waiting[0]
                head = self._item_req(head_item)
                # Sanity-reject BEFORE any preemption decision: evicting
                # offline work for a head that is then rejected would
                # sacrifice its KV for nothing (review finding, r4).
                htoks = (
                    head_item.tokens if isinstance(head_item, _Seq)
                    else head_item.prompt_token_ids
                )
                if len(htoks) >= self.cfg.max_seq_len:
                    self._waiting.popleft()
                    rejects.append(
                        (head, StatusCode.INVALID_ARGUMENT,
                         "prompt exceeds max_seq_len")
                    )
                    continue
                if math.ceil(
                    (len(htoks) + 1) / self.block_size
                ) > pool_capacity:
                    self._waiting.popleft()
                    rejects.append(
                        (head, StatusCode.RESOURCE_EXHAUSTED,
                         "request needs more KV blocks than the pool holds")
                    )
                    continue
                if head.mm_stream is not None:
                    # Streamed encoder handoff: admit only when the first
                    # chunk's placeholders have landed; otherwise defer
                    # WITHOUT blocking the queue behind this request.
                    gate = self._mm_gate(head, min(len(htoks), budget))
                    if gate == "wait":
                        self._waiting.popleft()
                        deferred.append(head_item)
                        continue
                    if gate != "ready":
                        self._waiting.popleft()
                        rejects.append(
                            (head, StatusCode.UNAVAILABLE, gate)
                        )
                        continue
                no_slot = not self._free_slots
            if no_slot:
                # Online head + every slot busy: preempt a running OFFLINE
                # decode (recompute-style) instead of stalling the burst.
                if not self._preempt_offline_for(head):
                    break
                continue
            with self._lock:
                if not self._waiting or not self._free_slots:
                    # only this thread pops the head, but re-check anyway
                    break
                item = self._waiting[0]
                tokens = item.tokens if isinstance(item, _Seq) else item.prompt_token_ids
                n_tok = len(tokens)
                if n_tok >= self.cfg.max_seq_len:
                    self._waiting.popleft()
                    rejects.append(
                        (self._item_req(item), StatusCode.INVALID_ARGUMENT,
                         "prompt exceeds max_seq_len")
                    )
                    continue
                # Need blocks for all current tokens + the next one.
                need_total = math.ceil((n_tok + 1) / self.block_size)
                if need_total > pool_capacity:
                    # Can NEVER fit — reject instead of stalling the queue
                    # head forever.
                    self._waiting.popleft()
                    rejects.append(
                        (self._item_req(item), StatusCode.RESOURCE_EXHAUSTED,
                         "request needs more KV blocks than the pool holds")
                    )
                    continue
                if not self.block_mgr.can_allocate(need_total):
                    blocked_on_pool = True
                else:
                    blocked_on_pool = False
                    self._waiting.popleft()
            if blocked_on_pool:
                # Online head + pool pressure: free blocks by preempting a
                # running OFFLINE decode, then retry this head.
                if not self._preempt_offline_for(self._item_req(item)):
                    break
                continue

            # Hash OUTSIDE the lock (long prompts hash thousands of blocks;
            # add_request/cancel must not stall behind it). Safe: this
            # thread is the only one that pops/appendlefts _waiting.
            # Media requests bypass the cache (their KV depends on encoder
            # embeddings the token-id hash cannot see); so do LoRA-adapter
            # requests — their KV depends on the adapter, and the chained
            # token-id hashes are adapter-blind (a base/other-adapter hit
            # would serve the WRONG cached KV).
            req0 = self._item_req(item)
            has_media = req0.has_media or bool(req0.adapter_idx)
            head_hashes = (
                []
                if has_media
                else prefix_block_hashes(
                    tokens[: n_tok - 1], self.block_size, self.block_mgr.seed
                )
            )
            if head_hashes and head_hashes[0] in pending_hashes:
                # Defer: shares a prefix with this batch — next step's
                # prefix match will reuse the blocks this batch commits.
                with self._lock:
                    self._waiting.appendleft(item)
                break

            if isinstance(item, _Seq):  # resuming a preempted sequence
                seq = item
                seq.slot = self._free_slots.pop()
            else:
                seq = _Seq(item, self._free_slots.pop())
            # Prefix-cache match — never the entire context (at least one
            # token must run to produce logits). The hash chain (already
            # computed for the dedup check) is shared with the host-tier
            # continuation. Media requests bypass the cache entirely
            # (head_hashes is empty for them).
            hashes = head_hashes
            num_cached, cached_blocks = self.block_mgr.match_prefix(
                seq.tokens[: n_tok - 1], hashes=hashes
            )
            if self.host_pool is not None and not has_media:
                num_cached, cached_blocks = self._extend_match_from_host(
                    hashes, num_cached, list(cached_blocks)
                )
            seq.num_cached = num_cached
            seq.block_ids = list(cached_blocks)
            seq.last_committed_block = len(cached_blocks) - 1
            new_blocks = need_total - len(cached_blocks)
            try:
                seq.block_ids += self.block_mgr.allocate(new_blocks)
            except OutOfBlocksError:
                self.block_mgr.free(seq.block_ids)
                seq.block_ids = []
                self._free_slots.append(seq.slot)
                with self._lock:
                    self._waiting.appendleft(item)
                break
            if not isinstance(item, _Seq):
                # Prefix-cache effectiveness counters, AFTER allocation
                # succeeds — an OutOfBlocksError requeue retries the same
                # raw item and would double-count (review finding, r5);
                # preemption resumes (_Seq items) re-match their own
                # blocks and are not cache "hits". bench_serving reports
                # the fleet hit rate from these.
                self.prefix_cached_tokens += num_cached
                self.prefix_prompt_tokens += max(n_tok - 1, 0)

            # Chunked prefill: the step budget is STRICT — a long uncached
            # suffix prefills across steps (decode runs between chunks, so
            # one long prompt no longer spikes every running request's
            # TBT). The sequence keeps its slot and blocks while waiting
            # for its next chunk.
            seq.prefilled = seq.num_cached
            seq.chunk_len = min(len(seq.tokens) - seq.prefilled, budget)
            seq.head_hash = hashes[0] if hashes else None
            seq.admit_hashes = hashes  # mid-prefill re-match walks these
            budget -= seq.chunk_len
            pending_hashes.update(hashes)
            if mixed_collect is not None and self._mixed_eligible(seq):
                seq.pf_dispatched = seq.prefilled
                self._pf_active[seq.req.request_id] = seq
                # One Lpad bucket per mixed dispatch (byte-parity with
                # _prefill_group's same-bucket grouping): a seq whose
                # first chunk pads differently still ADMITS now (slot +
                # blocks held) but its chunk rides the next iteration's
                # dispatch via _continue_pf_chunks.
                if (
                    len(mixed_collect) < getattr(
                        self.executor, "PREFILL_GROUP_MAX", 8
                    )
                    and (
                        not mixed_collect
                        or self.executor.bucket_len(seq.chunk_len)
                        == self.executor.bucket_len(mixed_collect[0][2])
                    )
                ):
                    mixed_collect.append(
                        (seq, seq.prefilled, seq.chunk_len)
                    )
                continue
            batch.append(seq)

        if deferred:
            # Deferred streamed-media items return to the FRONT in their
            # original relative order (stream landings set the work event,
            # so the next step re-checks their coverage).
            with self._lock:
                self._waiting.extendleft(reversed(deferred))
        admitted = self._prefill_admitted(batch) if batch else 0
        for req, code, msg in rejects:
            self._reject(req, code, msg)
        return admitted

    def _mm_gate(self, req: EngineRequest, pos_end: int) -> str:
        """Streamed-media admission gate for one prefill chunk ending at
        absolute position `pos_end` (docs/EPD.md): "ready" when every
        placeholder below it has landed (materializing the final arrays
        once the stream completes), "wait" while chunks are in flight, or
        an error message when the stream desynced or hit its deadline
        (the caller error-finishes — exactly the legacy timeout surface,
        moved off the HTTP thread)."""
        ms = req.mm_stream
        if ms is None:
            return "ready"
        err = ms.failed()
        if err:
            return f"media embedding stream failed: {err}"
        if ms.complete():
            emb, pos = ms.assembled()
            req.mm_embeds = emb
            req.mm_positions = [int(p) for p in pos]
            req.mm_stream = None
            return "ready"
        if ms.expired():
            return "media embeddings never arrived (stream deadline)"
        return "ready" if ms.ready_upto(pos_end) else "wait"

    def _sp_eligible(self, s: _Seq) -> bool:
        """Whether this seq routes through the sequence-parallel ring
        prefill (prefill_long). The ring recomputes from position 0 (no
        prefix reuse), so SP is only a win when the prompt is long AND
        mostly uncached (uncached suffix >= 8x the cached prefix).
        Mid-chunk seqs stay batched (the ring would discard landed
        chunks); LoRA / min_p / logit_bias / guided / penalized-resume
        requests stay batched because prefill_long samples without those
        features. Shared by the split prefill router and the mixed-step
        eligibility check."""
        sp_thresh = self.cfg.sp_prefill_threshold
        if sp_thresh <= 0 or not getattr(self.executor, "supports_sp", False):
            return False
        sp = s.req.sampling
        penalized_resume = s.generated and (
            getattr(sp, "presence_penalty", 0.0)
            or getattr(sp, "frequency_penalty", 0.0)
        )
        return (
            not s.req.has_media
            and not s.req.adapter_idx
            and not getattr(sp, "min_p", 0.0)
            and not getattr(sp, "logit_bias", ())
            and not s.req.guided
            and not penalized_resume
            and s.prefilled <= s.num_cached
            and len(s.tokens) - s.num_cached >= sp_thresh
            and len(s.tokens) - s.num_cached >= 8 * s.num_cached
        )

    def _mixed_eligible(self, seq: _Seq) -> bool:
        """Whether a freshly admitted seq can ride the fused mixed step.
        Media prompts (embedding injection + M-RoPE streams), streamed
        encoder handoffs, and SP-ring prompts keep the split prefill
        path. Guided requests DO ride the mixed batch (ISSUE 13): their
        final chunk samples under a host-derived mask row applied
        in-graph (_build_pf_items), and their decode steps run
        host-paced inside the pipeline instead of forcing split.
        prefill_only requests (the PD prefill role, incl. kv_stream
        sessions) stay split: they never decode — there is nothing
        to fuse with — and their per-chunk KV exports are timed to the
        synchronous prefill loop (docs/PD_DISAGGREGATION.md)."""
        req = seq.req
        return (
            not req.has_media
            and req.mm_stream is None
            and not req.prefill_only
            and not self._sp_eligible(seq)
        )

    @thread_owned("engine")
    def _prefill_admitted(self, batch: List[_Seq]) -> int:
        from xllm_service_tpu.runtime.executor import PrefillItem
        # Long-context path: prompts past the SP threshold prefill over the
        # mesh's sequence-parallel ring (ring attention) one at a time;
        # they skip prefix reuse (ring attends from position 0) and media
        # requests stay on the batched path (embedding injection).
        if self.cfg.sp_prefill_threshold > 0 and getattr(
            self.executor, "supports_sp", False
        ):
            sp_batch = [s for s in batch if self._sp_eligible(s)]
            if sp_batch:
                batch = [s for s in batch if s not in sp_batch]
                done = self._prefill_sp(sp_batch)
                return done + (
                    self._prefill_admitted(batch) if batch else 0
                )
        items = []
        for seq in batch:
            table = np.zeros((self.max_blocks,), np.int32)
            table[: len(seq.block_ids)] = seq.block_ids
            s = seq.req.sampling
            start = seq.prefilled
            n = seq.chunk_len or (len(seq.tokens) - start)
            # Media embeddings for this chunk: final arrays, or — on a
            # still-streaming handoff — whatever items have landed (the
            # admission gate guaranteed in-chunk coverage; the executor
            # drops positions outside the chunk).
            mm_e = mm_p = None
            if seq.req.has_media:
                if seq.req.mm_stream is not None:
                    mm_e, mm_p = seq.req.mm_stream.assembled()
                else:
                    mm_e = np.asarray(seq.req.mm_embeds, np.float32)
                    mm_p = np.asarray(seq.req.mm_positions, np.int64)
            items.append(
                PrefillItem(
                    token_ids=np.asarray(
                        seq.tokens[start:start + n], np.int32
                    ),
                    start_pos=start,
                    block_table=table,
                    temperature=s.temperature,
                    top_k=s.top_k,
                    top_p=s.top_p,
                    seed=s.seed,
                    step=len(seq.generated),
                    mm_embeds=(
                        np.asarray(mm_e, np.float32)
                        if mm_e is not None else None
                    ),
                    mm_positions=(
                        np.asarray(mm_p, np.int64)
                        if mm_p is not None else None
                    ),
                    rope_positions=(
                        self._mrope_positions(seq)[:, start:start + n]
                        if self._mrope_active(seq)
                        else None
                    ),
                    presence=getattr(s, "presence_penalty", 0.0),
                    frequency=getattr(s, "frequency_penalty", 0.0),
                    # Only the FINAL chunk's sampled token survives, so
                    # intermediate chunks skip the bias (and its compiled
                    # variant), like prior_tokens below.
                    logit_bias=(
                        tuple(getattr(s, "logit_bias", ()) or ())
                        if start + n >= len(seq.tokens)
                        else ()
                    ),
                    mask_row=(
                        self._guided_row(seq)
                        if seq.req.guided
                        and self._guided_tokens is not None
                        and start + n >= len(seq.tokens)
                        else -1
                    ),
                    adapter_idx=seq.req.adapter_idx,
                    # final chunk only, like logit_bias/mask_row: the
                    # intermediate chunks' sampled tokens are discarded
                    min_p=(
                        getattr(s, "min_p", 0.0)
                        if start + n >= len(seq.tokens)
                        else 0.0
                    ),
                    # Only the FINAL chunk's sampled token survives, so
                    # intermediate chunks skip the [P, V] histogram (and
                    # the penalized compiled variant) entirely.
                    prior_tokens=(
                        np.asarray(
                            [t for t, _ in seq.generated], np.int32
                        )
                        if seq.generated
                        and start + n >= len(seq.tokens)
                        and (
                            getattr(s, "presence_penalty", 0.0)
                            or getattr(s, "frequency_penalty", 0.0)
                        )
                        else None
                    ),
                )
            )
        t0 = time.monotonic()
        for seq in batch:
            # First chunk: TTFT base. The unset check (0.0 = never set)
            # covers a seq whose first chunk never dispatched before
            # adoption advanced `prefilled` past num_cached (mixed-mode
            # requeue after a mode flip).
            if seq.prefilled <= seq.num_cached or (
                seq.prefill_start_time == 0.0
            ):
                seq.prefill_start_time = t0
        self._m_kernel_dispatch.labels(
            kernel=self._kernel_names["prefill"]
        ).inc(self._prefill_group_count(items))
        outs = self.executor.prefill_batch(items)
        now = time.monotonic()
        admitted = 0
        for seq, item, (tok, lp) in zip(batch, items, outs):
            end = seq.prefilled + len(item.token_ids)
            if end < len(seq.tokens):
                # Partial chunk: KV landed; the chunk-tail "token" sampled
                # from a mid-prompt position is discarded. The seq returns
                # to the queue (holding slot + blocks) for its next chunk;
                # decode steps run in between. Counts as progress (the
                # loop must not back off between chunks).
                seq.prefilled = end
                self._stream_chunk_kv(seq)
                with self._lock:
                    self._waiting.appendleft(seq)
                admitted += 1
                continue
            seq.prefilled = end
            # Client-perceived TTFT spans ALL chunks (+ interleaved decode
            # steps) from the first chunk's start — for single-chunk seqs
            # this is the whole batched step: slightly pessimistic per seq,
            # conservative for the TimePredictor fit.
            ms = (now - seq.prefill_start_time) * 1000
            self._finish_prefill(
                seq, tok, lp, now, ms,
                len(seq.tokens) - seq.num_cached,
            )
            admitted += 1
        return admitted

    @thread_owned("engine")
    def _finish_prefill(
        self,
        seq: "_Seq",
        tok: int,
        lp: float,
        now: float,
        ms: float,
        profiled_len: int,
    ) -> None:
        """Shared post-prefill bookkeeping for the batched and SP paths:
        TTFT windows + profiling curve, block commit, first token, running
        insert, emit, and the prefill-only handoff."""
        self._ttft_window.append((now, ms))
        self._m_ttft.observe(ms)
        self._profile_ttft.append((profiled_len, ms))
        seq.prefill_done_time = seq.last_token_time = now
        self._commit_full_blocks(seq)
        seq.generated.append((tok, lp))
        seq.tokens.append(tok)
        # Penalty state: (re)build this slot's generated-token histogram —
        # fresh admission carries one token, preemption/PD resume the full
        # history. Skipped for penalty-free requests (the common case):
        # their counts are never READ, and any later penalized occupant of
        # the slot re-seeds on its own admission — so the prefill hot path
        # avoids a scatter over the donated [R, V] histogram.
        s = seq.req.sampling
        if (
            getattr(s, "presence_penalty", 0.0)
            or getattr(s, "frequency_penalty", 0.0)
        ) and hasattr(self.executor, "seed_slot_counts"):
            self.executor.seed_slot_counts(
                seq.slot, [t for t, _ in seq.generated]
            )
        self._slot_admit(seq)
        self._running[seq.slot] = seq
        alive = self._emit(seq, finished=self._check_stop(seq))
        if alive and seq.req.prefill_only:
            self._handoff(seq)

    def _prefill_group_count(self, items) -> int:
        """How many compiled dispatches executor.prefill_batch will launch
        for these items — executor.prefill_groups IS its grouping walk —
        so the kernel-dispatch counter counts DEVICE dispatches, not
        engine-level calls. Fake executors without bucketing count as
        one."""
        groups = getattr(self.executor, "prefill_groups", None)
        if groups is None or not items:
            return 1
        return len(groups(items))

    @thread_owned("engine")
    def _prefill_sp(self, batch: List[_Seq]) -> int:
        """Ring-attention prefill for long prompts (one jitted call per
        sequence; the sp mesh ring IS the batch dimension here). The ring
        attends from position 0, so a prefix-cache match is traded for
        FRESH blocks — overwriting shared cached blocks with a recompute
        would mutate other sequences' context mid-flight."""
        admitted = 0
        for seq in batch:
            if seq.num_cached:
                self.block_mgr.free(seq.block_ids)
                need_total = math.ceil(
                    (len(seq.tokens) + 1) / self.block_size
                )
                try:
                    seq.block_ids = self.block_mgr.allocate(need_total)
                except OutOfBlocksError:
                    seq.block_ids = []
                    self._free_slots.append(seq.slot)
                    with self._lock:
                        self._waiting.appendleft(seq)
                    continue
                seq.num_cached = 0
                seq.last_committed_block = -1
            table = np.zeros((self.max_blocks,), np.int32)
            table[: len(seq.block_ids)] = seq.block_ids
            s = seq.req.sampling
            t0 = time.monotonic()
            self._m_kernel_dispatch.labels(kernel="ring-sp").inc()
            tok, lp = self.executor.prefill_long(
                np.asarray(seq.tokens, np.int32),
                table,
                temperature=s.temperature,
                top_k=s.top_k,
                top_p=s.top_p,
                seed=s.seed,
                step=len(seq.generated),
            )
            now = time.monotonic()
            ms = (now - t0) * 1000
            self._finish_prefill(seq, tok, lp, now, ms, len(seq.tokens))
            admitted += 1
        return admitted

    # ------------------------------------------------- host (DRAM) tier

    def _offload_to_host(self, items: List[Tuple[int, bytes]]) -> List[bytes]:
        """BlockManager eviction hook: copy ALL victims' KV to the host pool
        in one bulk device->host transfer BEFORE the device blocks are
        reused. Returns the hashes saved, which become offload('dram')
        heartbeat deltas instead of removed."""
        kv = np.asarray(
            self.executor.export_blocks([b for b, _ in items])
        )  # [2, L, n, Hkv, BS, D] — one device sync for the batch
        for i, (_, block_hash) in enumerate(items):
            for ev_hash, ev_kv in self.host_pool.put(block_hash, kv[:, :, i]):
                self._demote_to_ssd(ev_hash, ev_kv)
        # Only report hashes that SURVIVED the whole batch: a later put()
        # may have LRU-evicted an earlier one — claiming it saved would
        # leave a dangling DRAM entry in the master's index.
        return [h for _, h in items if h in self.host_pool]

    def _demote_to_ssd(self, block_hash: bytes, kv: np.ndarray) -> None:
        """DRAM eviction lands on disk when the SSD tier is enabled
        (dram->ssd transition, reference proto:47); otherwise the hash is
        gone from this instance — the fabric's coordinated-eviction hook
        gets one last look at the host array (offer the block to an
        under-utilized peer) before the local drop is recorded."""
        if self.ssd_pool is None:
            hook = self.on_cold_evict
            if hook is not None:
                try:
                    hook(block_hash, kv)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "on_cold_evict hook failed; block drops locally"
                    )
            self.block_mgr.record_host_removed(block_hash)
            return
        for dropped in self.ssd_pool.put(block_hash, kv):
            self._record_cold_removed(dropped)
        self.block_mgr.record_tier_offload(block_hash, "ssd")

    def _record_cold_removed(self, block_hash: bytes) -> None:
        """A cold tier dropped this hash — but another tier may still hold
        it (DRAM re-population after an SSD spill); only report the tier
        the instance still serves from, never a false removal."""
        if self.host_pool is not None and block_hash in self.host_pool:
            self.block_mgr.record_tier_offload(block_hash, "dram")
        elif self.ssd_pool is not None and block_hash in self.ssd_pool:
            self.block_mgr.record_tier_offload(block_hash, "ssd")
        else:
            self.block_mgr.record_host_removed(block_hash)

    def _extend_match_from_host(
        self, hashes: List[bytes], num_cached: int, cached_blocks: List[int]
    ) -> Tuple[int, List[int]]:
        """Continue a prefix match into the host tier: consecutive host-held
        blocks after the HBM hit are re-imported (one bulk host->device copy)
        and recommitted, re-promoting their index entries to HBM."""
        start = len(cached_blocks)
        run: List[Tuple[bytes, np.ndarray]] = []
        for h in hashes[start:]:
            kv = self.host_pool.get(h)
            if kv is None and self.ssd_pool is not None:
                kv = self.ssd_pool.get(h)
            if kv is None:
                break
            run.append((h, kv))
        if not run or not self.block_mgr.can_allocate(len(run)):
            return num_cached, cached_blocks
        try:
            ids = self.block_mgr.allocate(len(run))
        except OutOfBlocksError:
            return num_cached, cached_blocks
        stacked = np.stack([kv for _, kv in run], axis=2)  # [2, L, n, ...]
        self.executor.import_blocks(stacked, np.asarray(ids))
        for bid, (h, _) in zip(ids, run):
            self.block_mgr.commit_block(bid, h)
        return num_cached + len(run) * self.block_size, cached_blocks + ids

    # ------------------------------------------------- prefix KV fabric

    @thread_owned("engine")
    def _extend_midchunk_match(self, seq: _Seq,
                               frontier: Optional[int] = None) -> int:
        """Chunk-boundary cache pickup: if the NEXT un-prefilled blocks'
        hashes are now committed locally (they landed after admission —
        a fabric peer fetch, a streamed PD chunk, a sibling sequence's
        commit), swap the sequence's fresh blocks for the cached ones and
        advance past them. This is what makes a peer fetch genuinely
        OVERLAP chunked prefill of the uncovered tail: each chunk
        boundary re-checks, so blocks that arrive mid-prefill are
        adopted instead of recomputed. Only runs on block-aligned
        boundaries; `last_committed_block` is left alone so the normal
        commit walk still registers this sequence's own chunks.

        `frontier=None` (the split prefill loop) adopts from and
        advances `seq.prefilled`. The mixed step builder instead passes
        its DISPATCHED frontier (`pf_dispatched`) so adoption stays live
        under the chunk pipeline: an in-flight chunk writes only blocks
        BELOW the frontier, every swapped block lies wholly beyond it,
        and `prefilled` catches up when the next chunk — cut from the
        advanced frontier — drains. Returns the tokens adopted (the
        caller's frontier advance)."""
        hashes = seq.admit_hashes
        bs = self.block_size
        start = seq.prefilled if frontier is None else frontier
        if (
            not hashes
            or start % bs
            or seq.req.has_media
            or seq.req.adapter_idx
        ):
            return 0
        idx = start // bs
        adopted = 0
        while idx < len(hashes) and idx < len(seq.block_ids):
            bid = self.block_mgr.lookup_hash(hashes[idx])
            if bid is None:
                break
            if bid == seq.block_ids[idx]:
                # Already swapped in by a mixed-frontier adoption
                # (frontier=pf_dispatched) before a mode flip requeued
                # this seq: `prefilled` never caught up, so count the
                # block covered NOW — cutting the next split chunk from
                # `prefilled` would recompute KV into a CACHED block
                # other live sequences hold references to.
                if frontier is None and idx * bs >= seq.prefilled:
                    seq.prefilled = (idx + 1) * bs
                    idx += 1
                    continue
                break
            # Swap: take a cache reference on the committed block, release
            # this seq's never-written fresh block back to the pool.
            old = seq.block_ids[idx]
            self.block_mgr.acquire_cached(bid)
            self.block_mgr.free([old])
            seq.block_ids[idx] = bid
            if frontier is None:
                seq.prefilled += bs
            adopted += 1
            idx += 1
        if adopted:
            self.prefix_cached_tokens += adopted * bs
            self.midprefill_adopted_blocks += adopted
        return adopted * bs

    def export_cached_blocks(
        self, hashes: List[bytes], timeout: float = 10.0
    ) -> Tuple[List[bytes], Optional[np.ndarray]]:
        """Serve a peer's prefix fetch: export the KV of every requested
        hash this instance holds on ANY tier. Thread-safe entry (HTTP
        serving thread); the export itself runs on the engine thread —
        the block manager and host/SSD pools are engine-thread-only, and
        an off-thread device export could read a block mid-eviction.
        Returns (served_hashes, kv [2, L, n, Hkv, BS, D]) with kv a HOST
        array, or ([], None) on timeout / nothing held."""
        job = {
            "hashes": [bytes(h) for h in hashes],
            "event": threading.Event(),
            "result": ([], None),
        }
        with self._lock:
            self._pending_exports.append(job)
        self._work.set()
        if not job["event"].wait(timeout):
            return [], None
        return job["result"]

    @thread_owned("engine")
    def _drain_export_requests(self) -> None:
        while True:
            with self._lock:
                if not self._pending_exports:
                    return
                job = self._pending_exports.popleft()
            try:
                job["result"] = self._export_cached(job["hashes"])
            except Exception:
                logging.getLogger(__name__).exception(
                    "prefix-fabric block export failed; peer recomputes"
                )
                job["result"] = ([], None)
            finally:
                job["event"].set()

    @thread_owned("engine")
    def _export_cached(self, hashes: List[bytes]):
        """Engine-thread export body: HBM blocks gather in ONE device
        export; host/SSD blocks read from their pools. Requested order is
        preserved in the stacked result. On a tp-sharded executor an
        all-HBM export stays PER-SHARD end-to-end (shard_wire.ShardedKV:
        each tp shard's host copy reads off its own device — no
        cross-shard gather; the /kv/fetch frame then ships N per-shard
        block sets). Mixing in host/SSD-tier blocks — stored flat —
        degrades that response to the flat layout."""
        from xllm_service_tpu.parallel import shard_wire

        served: List[bytes] = []
        seen: Set[bytes] = set()
        arrays: Dict[bytes, np.ndarray] = {}
        # Per-shard per-block pieces [nc, L, Hc/tp, BS, D] (head axis 2
        # once the block axis is sliced away) for sharded HBM exports.
        pieces: Dict[bytes, List[np.ndarray]] = {}
        hbm: List[Tuple[bytes, int]] = []
        for h in hashes:
            if h in seen:
                continue  # duplicate hash in the request
            seen.add(h)
            bid = self.block_mgr.lookup_hash(h)
            if bid is not None:
                hbm.append((h, bid))
                served.append(h)
                continue
            kv = self.host_pool.get(h) if self.host_pool is not None else None
            if kv is None and self.ssd_pool is not None:
                kv = self.ssd_pool.get(h)
            if kv is not None:
                arrays[h] = np.asarray(kv)
                served.append(h)
        if hbm:
            stacked = shard_wire.to_host(
                self.executor.export_blocks([b for _, b in hbm])
            )
            if isinstance(stacked, shard_wire.ShardedKV):
                for i, (h, _) in enumerate(hbm):
                    pieces[h] = [
                        np.asarray(s)[:, :, i] for s in stacked.shards
                    ]
            else:
                for i, (h, _) in enumerate(hbm):
                    arrays[h] = stacked[:, :, i]
        if not served:
            return [], None
        if pieces and not arrays:
            nsh = len(next(iter(pieces.values())))
            return served, shard_wire.ShardedKV([
                np.stack([pieces[h][s] for h in served], axis=2)
                for s in range(nsh)
            ])
        for h, pc in pieces.items():
            arrays[h] = np.concatenate(pc, axis=2)
        return served, np.stack([arrays[h] for h in served], axis=2)

    # ------------------------------------------------- PD disaggregation

    def _stream_chunk_kv(self, seq: _Seq) -> None:
        """Pipelined handoff: after a PARTIAL prefill chunk lands, export
        the newly completed full blocks to the request's kv_stream hook so
        they migrate while the next chunk is still prefilling. Safe vs.
        later prefill steps: export_blocks gathers into a fresh device
        buffer, and prompt blocks below `prefilled` are never rewritten.
        Media/LoRA prompts never stream (their KV never enters the
        hash-addressed migration path) and neither do resumed sequences
        (generated history makes the token/hash split ambiguous)."""
        req = seq.req
        stream = req.kv_stream
        if (
            stream is None
            or not req.prefill_only
            or getattr(stream, "aborted", False)
            or req.has_media
            or req.adapter_idx
            or seq.generated
        ):
            return
        avail = seq.prefilled // self.block_size
        if avail <= seq.streamed_blocks:
            return
        prompt_len = len(seq.tokens)
        hashes = self._stream_prefix_hashes(seq, avail)
        chunk = KVStreamChunk(
            request_id=req.request_id,
            start_block=seq.streamed_blocks,
            block_hashes=hashes[seq.streamed_blocks: avail],
            kv=self.executor.export_blocks(
                seq.block_ids[seq.streamed_blocks: avail]
            ),
            prompt_tokens=prompt_len,
            total_blocks_hint=prompt_len // self.block_size,
        )
        try:
            ok = stream.send_chunk(chunk)
        except Exception:  # hook errors must not kill the engine loop
            logging.getLogger(__name__).exception(
                "kv_stream hook failed for %s; falling back to the "
                "monolithic handoff", req.request_id,
            )
            ok = False
        if ok:
            seq.streamed_blocks = avail

    def _stream_prefix_hashes(self, seq: _Seq, nblocks: int) -> List[bytes]:
        """Chained hashes of seq.tokens' first `nblocks` full blocks,
        extended INCREMENTALLY across chunks via the per-seq cache —
        rehashing the whole prefix per chunk would be O(blocks x chunks)
        on exactly the long prompts the pipeline targets."""
        from xllm_service_tpu.common.hashing import extend_prefix_block_hashes

        cache = extend_prefix_block_hashes(
            seq.stream_hashes, seq.tokens, nblocks,
            self.block_size, self.block_mgr.seed,
        )
        return cache[:nblocks]

    @thread_owned("engine")
    def _handoff(self, seq: _Seq) -> None:
        """Prefill side: export this sequence's full committed blocks and
        hand them to the peer transport, then release the local sequence.
        The committed blocks stay in the local prefix cache (evictable), so
        cache-aware routing keeps its affinity signal."""
        full = seq.last_committed_block + 1
        if full <= 0:
            hashes = []
        elif seq.req.kv_stream is not None:
            # Streaming requests: extend the per-chunk hash cache instead
            # of rehashing the whole prefix a second time.
            hashes = self._stream_prefix_hashes(seq, full)
        else:
            hashes = prefix_block_hashes(
                seq.tokens[: full * self.block_size],
                self.block_size,
                self.block_mgr.seed,
            )
        # Pipelined handoff: blocks already delivered through the stream
        # session ride nothing twice — the commit payload carries only the
        # tail. A session that aborted (peer rejection / send failure)
        # falls back to the full monolithic export: the blocks are still
        # held right here, so the retry is free.
        streamed = seq.streamed_blocks
        stream = seq.req.kv_stream
        if stream is not None and getattr(stream, "aborted", False):
            streamed = 0
        streamed = max(0, min(streamed, full))
        kv = None
        if full > streamed:
            # Stays a DEVICE array: the in-process (colocated-PD / ICI
            # analog) path imports it without ever touching the host; the
            # HTTP/DCN path converts at serialization (kv_frame_to_bytes).
            # Safe vs. the block free below: export_blocks gathers into a
            # fresh buffer on the device stream before any later step can
            # rewrite the freed blocks.
            kv = self.executor.export_blocks(seq.block_ids[streamed:full])
        payload = KVHandoff(
            request_id=seq.req.request_id,
            token_ids=list(seq.tokens),
            first_token=seq.generated[0][0],
            first_logprob=seq.generated[0][1],
            num_full_blocks=full,
            block_hashes=list(hashes),
            kv=kv,
            usage_prompt_tokens=len(seq.req.prompt_token_ids),
            kv_start_block=streamed,
        )
        try:
            seq.req.handoff(payload)
        except Exception:
            import traceback

            traceback.print_exc()
            # The commit will never be sent — don't leak the session.
            self._dispose_stream(seq.req)
        # release slot + block refs; committed blocks become evictable-cached
        if seq.slot in self._running:
            del self._running[seq.slot]
            self._free_slots.append(seq.slot)
            self._slot_clear(seq.slot)
        self.block_mgr.free(seq.block_ids)
        seq.block_ids = []

    def import_sequence(
        self, req: EngineRequest, handoff: KVHandoff
    ) -> None:
        """Decode side: continue a sequence prefilled by a peer. Thread-safe
        entry; the KV landing happens on the engine thread."""
        with self._lock:
            self._pending_imports.append((req, handoff))
        self._work.set()

    def import_kv_blocks(self, block_hashes: List[bytes], kv) -> None:
        """Pipelined-handoff receive side: land one streamed chunk's full
        blocks into the local prefix cache (committed under their chained
        hashes, immediately evictable). Thread-safe entry; the landing runs
        on the engine thread. The later commit handoff's admission picks
        the blocks up through the ordinary prefix match — a chunk that
        never arrives only costs recompute of its span."""
        with self._lock:
            self._pending_kv_chunks.append((list(block_hashes), kv))
        self._work.set()

    def _drain_imports(self) -> None:
        while True:
            with self._lock:
                if not self._pending_kv_chunks:
                    break
                hashes, kv = self._pending_kv_chunks.popleft()
            try:
                self._land_migrated_blocks(hashes, kv)
            except Exception:
                # Counted (xllm_engine_kv_chunk_land_errors_total): the
                # chunk was already acked to the sender, so a landing
                # failure is otherwise invisible until the commit's
                # prefix match silently recomputes.
                self.kv_chunk_land_errors += 1
                logging.getLogger(__name__).exception(
                    "streamed KV chunk failed to land; the commit will "
                    "recompute its span"
                )
        while True:
            with self._lock:
                if not self._pending_imports:
                    return
                req, h = self._pending_imports.popleft()
            self._do_import(req, h)

    def _land_migrated_blocks(self, hashes: List[bytes], kv) -> None:
        """Land migrated full blocks into the local cache under their
        chained hashes (hashes[i] names kv[:, :, i]); blocks whose hash is
        already cached locally are skipped (dedup). Shared by the
        monolithic handoff import and the streamed-chunk path. Raises on
        malformed payloads — callers degrade to recompute."""
        expect = self.executor.migration_shape(len(hashes))
        if kv.shape != expect:
            raise ValueError(
                f"handoff KV shape {kv.shape} != local cache layout "
                f"{expect} — PD pair config mismatch; recomputing"
            )
        if any(
            not isinstance(hb, bytes) or len(hb) != 16 for hb in hashes
        ):
            raise ValueError("malformed block hash in handoff; recomputing")
        fresh = [
            i
            for i, hb in enumerate(hashes)
            if self.block_mgr.lookup_hash(hb) is None
        ]
        ids = []
        if fresh:
            try:
                ids = self.block_mgr.allocate(len(fresh))
            except OutOfBlocksError:
                ids = []
        if ids:
            try:
                self.executor.import_blocks(
                    kv[:, :, np.asarray(fresh, np.int32)],
                    np.asarray(ids),
                )
            except Exception:
                self.block_mgr.free(ids)
                raise
            for bid, i in zip(ids, fresh):
                self.block_mgr.commit_block(bid, hashes[i])
            # drop our temporary ref; blocks stay evictable-cached
            # until admission re-acquires them via match_prefix
            self.block_mgr.free(ids)

    def _do_import(self, req: EngineRequest, h: KVHandoff) -> None:
        # Land migrated full blocks into the local cache under their chained
        # hashes. On ANY problem — capacity, a PD pair whose engine configs
        # diverge (block_size/layers/heads/dtype), a corrupt payload — fall
        # back to pure recompute: the resume _Seq below is seeded regardless,
        # so admission prefills the whole prompt locally and the request
        # never vanishes. A pipelined handoff's kv covers only blocks
        # [kv_start_block, num_full_blocks) — the earlier ones arrived (or
        # were lost, costing only recompute) through the streamed chunks.
        start = max(int(getattr(h, "kv_start_block", 0) or 0), 0)
        if h.num_full_blocks > start and h.kv is not None:
            try:
                if len(h.block_hashes) != h.num_full_blocks:
                    raise ValueError(
                        f"{len(h.block_hashes)} block hashes for "
                        f"{h.num_full_blocks} blocks; recomputing"
                    )
                # numpy from the HTTP/DCN path; a device jax.Array from the
                # in-process local path (no host round-trip — the slice and
                # import below run device-side).
                self._land_migrated_blocks(h.block_hashes[start:], h.kv)
            except Exception:
                import traceback

                traceback.print_exc()
        # Seed a resume-sequence: prompt + first generated token; admission
        # treats it like a preempted sequence — prefix match picks up the
        # imported blocks, only the sub-block tail is recomputed, and the
        # next emitted token is the SECOND one (the prefill peer already
        # streamed the first).
        seq = _Seq(req, slot=-1)
        seq.tokens = list(h.token_ids)
        seq.generated = [(h.first_token, h.first_logprob)]
        with self._lock:
            self._waiting.append(seq)
        self._work.set()

    @staticmethod
    def _dispose_stream(req: EngineRequest) -> None:
        """A request that will never hand off tears its streaming session
        down (peer-side entry + offer keepalives) instead of leaking it
        until the receiver's TTL reap."""
        stream = req.kv_stream
        if stream is None:
            return
        try:
            fn = getattr(stream, "dispose", None)
            if fn is not None:
                fn()
        except Exception:
            pass

    def _reject(self, req: EngineRequest, code: StatusCode, msg: str) -> None:
        self._dispose_stream(req)
        out = RequestOutput(
            request_id=req.request_id,
            status=Status(code, msg),
            finished=True,
        )
        try:
            req.callback(out)
        except Exception:
            pass

    def _notify_cancelled(self, req: EngineRequest) -> None:
        self._dispose_stream(req)
        out = RequestOutput(
            request_id=req.request_id,
            finished=True,
            cancelled=True,
            status=Status(StatusCode.CANCELLED, "cancelled"),
        )
        try:
            req.callback(out)
        except Exception:
            pass

    # -------------------------------------------------------------- decode

    @thread_owned("engine")
    def _ensure_decode_capacity(self, width: int, mask=None) -> None:
        """Ensure block capacity for every position the coming decode step
        may write: `width` tokens starting at each slot's next input
        position (the persistent dispatch position — one token ahead of
        seq.tokens while a step is in flight), capped at max_seq_len.
        Preempts (victim-first, then self) on pool exhaustion. `mask`
        restricts the pass to dispatchable slots (overlap mode skips
        length-stopped slots whose position already sits at the limit)."""
        max_len = self.cfg.max_seq_len
        for slot, seq in sorted(self._running.items()):
            if slot not in self._running:  # preempted earlier this pass
                continue
            if mask is not None and not mask[slot]:
                continue
            pos = int(self._ps_positions[slot])
            tl = max(1, min(width, max_len - pos))
            need = (pos + tl - 1) // self.block_size + 1
            while len(seq.block_ids) < need:
                try:
                    seq.block_ids += self.block_mgr.allocate(1)
                    self._block_tables[slot, len(seq.block_ids) - 1] = (
                        seq.block_ids[-1]
                    )
                except OutOfBlocksError:
                    victim = self._pick_preemption_victim(exclude=slot)
                    if victim is None:
                        # Nothing to preempt: preempt this seq itself.
                        self._preempt(seq)
                        break
                    self._preempt(victim)
            else:
                continue

    # ------------------------------------------- persistent batch state

    def _set_opt(self, arr: np.ndarray, slot: int, val, count_attr: str):
        """Write one optional-feature array entry, maintaining the count of
        nonzero entries so _sampling_batch_view can pass None (and keep the
        cheaper compiled variant) when the feature is unused batch-wide."""
        old = arr[slot]
        arr[slot] = val
        setattr(
            self, count_attr,
            getattr(self, count_attr) + int(bool(val)) - int(bool(old)),
        )

    @thread_owned("engine")
    def _slot_admit(self, seq: _Seq) -> None:
        """Install a sequence's sampling params + dispatch state into the
        persistent per-slot arrays (fresh admission, preemption resume, PD
        import resume). Together with _slot_clear this is the ONLY write
        path for sampling state — steady-state decode steps reuse the
        arrays untouched instead of rebuilding a SamplingBatch."""
        slot = seq.slot
        s = seq.req.sampling
        self._ps_temps[slot] = s.temperature
        self._ps_top_k[slot] = s.top_k
        self._ps_top_p[slot] = s.top_p
        self._ps_seeds[slot] = s.seed & 0xFFFFFFFF
        self._ps_steps[slot] = len(seq.generated)
        self._ps_presence[slot] = getattr(s, "presence_penalty", 0.0)
        self._ps_frequency[slot] = getattr(s, "frequency_penalty", 0.0)
        self._set_opt(
            self._ps_min_p, slot, getattr(s, "min_p", 0.0), "_n_min_p"
        )
        self._set_opt(
            self._ps_adapter, slot, seq.req.adapter_idx, "_n_adapter"
        )
        self._set_opt(
            self._ps_rope_delta, slot, getattr(seq, "rope_delta", 0) or 0,
            "_n_rope",
        )
        bias = tuple(getattr(s, "logit_bias", ()) or ())
        self._n_bias += int(bool(bias)) - int(bool(self._bias_rows[slot]))
        self._bias_rows[slot] = bias
        if seq.req.guided:
            self._guided_slots.add(slot)
        else:
            self._guided_slots.discard(slot)
        row = self._block_tables[slot]
        row[:] = 0
        row[: len(seq.block_ids)] = seq.block_ids
        self._ps_active[slot] = True
        self._ps_last_tok[slot] = seq.tokens[-1]
        self._ps_positions[slot] = len(seq.tokens) - 1
        self._ps_pending[slot] = 0
        self._ps_gen_count[slot] = len(seq.generated)
        self._ps_tok_count[slot] = len(seq.tokens)
        self._ps_max_new[slot] = s.max_new_tokens
        self._fresh[slot] = True
        seq.admit_gen += 1
        self._ps_gen += 1

    @thread_owned("engine")
    def _slot_clear(self, slot: int) -> None:
        """Reset one slot's persistent arrays (finish/cancel/preempt/
        handoff) — inactive rows carry the same neutral values the old
        per-step rebuild zero-filled them with."""
        self._ps_active[slot] = False
        self._ps_pending[slot] = 0
        self._ps_temps[slot] = 0.0
        self._ps_top_k[slot] = 0
        self._ps_top_p[slot] = 1.0
        self._ps_seeds[slot] = 0
        self._ps_steps[slot] = 0
        self._ps_presence[slot] = 0.0
        self._ps_frequency[slot] = 0.0
        self._set_opt(self._ps_min_p, slot, 0.0, "_n_min_p")
        self._set_opt(self._ps_adapter, slot, 0, "_n_adapter")
        self._set_opt(self._ps_rope_delta, slot, 0, "_n_rope")
        self._n_bias -= int(bool(self._bias_rows[slot]))
        self._bias_rows[slot] = ()
        self._guided_slots.discard(slot)
        self._block_tables[slot, :] = 0
        self._ps_last_tok[slot] = 0
        self._ps_positions[slot] = 0
        self._ps_gen_count[slot] = 0
        self._ps_tok_count[slot] = 0
        self._ps_max_new[slot] = 0
        self._fresh[slot] = False
        self._ps_gen += 1

    def _refresh_slot_arrays(self, slot: int, seq: _Seq) -> None:
        """Re-derive a slot's dispatch state from host truth. The
        speculative path emits a VARIABLE token count per step, so the
        incremental +1 advances the plain paths use would drift."""
        self._ps_steps[slot] = len(seq.generated)
        self._ps_positions[slot] = len(seq.tokens) - 1
        self._ps_last_tok[slot] = seq.tokens[-1]
        self._ps_gen_count[slot] = len(seq.generated)
        self._ps_tok_count[slot] = len(seq.tokens)

    def _sampling_batch_view(self) -> SamplingBatch:
        """SamplingBatch over the persistent arrays — zero per-step
        allocation. The packed logit-bias arrays are cached keyed on the
        running-set generation (_ps_gen), so the no-bias common case never
        calls pack_logit_bias and steady-state biased batches pack once per
        membership change, not once per step."""
        if self._n_bias:
            if self._bias_cache_gen != self._ps_gen:
                from xllm_service_tpu.ops.sampling import pack_logit_bias

                self._bias_cache = pack_logit_bias(self._bias_rows, self.R)
                self._bias_cache_gen = self._ps_gen
            bias_ids, bias_vals = self._bias_cache
        else:
            bias_ids = bias_vals = None
        return SamplingBatch(
            self._ps_temps, self._ps_top_k, self._ps_top_p, self._ps_seeds,
            self._ps_steps, self._ps_presence, self._ps_frequency,
            bias_ids, bias_vals,
            adapter_idx=self._ps_adapter if self._n_adapter else None,
            min_p=self._ps_min_p if self._n_min_p else None,
            rope_delta=self._ps_rope_delta if self._n_rope else None,
        )

    def _observe_host_gap(self) -> None:
        """Record the host-bookkeeping gap between the previous step's
        drain and this dispatch — the window sync mode spends with the
        device idle, and overlap mode hides behind the in-flight step."""
        if self._t_host_free is not None:
            gap = (time.monotonic() - self._t_host_free) * 1000
            self._m_host_gap.observe(gap)
            self.host_gap_ms_sum += gap
            self.host_gap_steps += 1

    @thread_owned("engine")
    def _decode_once(self) -> int:
        if self.cfg.speculative_tokens > 0:
            return self._decode_spec_once()
        if not self._running:
            return 0
        self._ensure_decode_capacity(1)
        if not self._running:
            return 0

        active = self._ps_active.copy()
        batch = self._sampling_batch_view()
        if self._guided_tokens is not None and self._guided_slots:
            rows = np.full((self.R,), self.executor.permissive_row, np.int32)
            for slot, seq in self._running.items():
                rows[slot] = self._guided_row(seq)
            batch.mask_rows = rows

        self._observe_host_gap()
        t0 = time.monotonic()
        tokens, logprobs = self.executor.decode(
            self._ps_last_tok,
            self._ps_positions,
            self._block_tables,
            active,
            batch,
        )
        self._m_kernel_dispatch.labels(
            kernel=self._kernel_names["decode"]
        ).inc()
        step_ms = (time.monotonic() - t0) * 1000
        nactive = int(active.sum())
        total_ctx = int(self._ps_positions[active].sum()) + nactive
        self._profile_tpot.append((nactive, total_ctx, step_ms))
        self._m_batch.observe(nactive)
        self._m_steps.inc()
        self.decode_dispatches += 1
        self.collective_overlap_steps += self._overlap_collectives
        self._ps_steps[active] += 1
        self._ps_positions[active] += 1

        produced = 0
        now = time.monotonic()
        for slot in list(self._running.keys()):
            seq = self._running[slot]
            tok, lp = int(tokens[slot]), float(logprobs[slot])
            tbt_ms = (now - seq.last_token_time) * 1000
            self._tbt_window.append((now, tbt_ms))
            self._m_tbt.observe(tbt_ms)
            seq.last_token_time = now
            seq.generated.append((tok, lp))
            seq.tokens.append(tok)
            self._ps_last_tok[slot] = tok
            self._ps_gen_count[slot] += 1
            self._ps_tok_count[slot] += 1
            self._fresh[slot] = True
            self._commit_full_blocks(seq)
            produced += 1
            self._emit(seq, finished=self._check_stop(seq))
        self._t_host_free = time.monotonic()
        return produced

    # ------------------------------------------------ overlapped pipeline

    @thread_owned("engine")
    def _dispatch_decode(self) -> Optional[_InFlight]:
        """Dispatch the next overlapped decode step, returning its in-flight
        record (None when nothing is dispatchable). Continuing slots feed
        from the PREVIOUS step's device-resident sampled tokens — the
        autoregressive feedback never round-trips the host. Freshly
        admitted/resumed slots feed from the host array. Length-predictable
        stops (max_new_tokens / max_seq_len) are excluded up front; the
        token-dependent ones (EOS / stop ids) surface at drain, one step
        late, and cost exactly one discarded sample."""
        if not self._running:
            return None
        can = (
            self._ps_active
            & (self._ps_gen_count + self._ps_pending < self._ps_max_new)
            & (
                self._ps_tok_count + self._ps_pending
                < self.cfg.max_seq_len
            )
        )
        can = self._apply_guided_pacing(can)
        if not can.any():
            return None
        self._ensure_decode_capacity(1, mask=can)
        can &= self._ps_active  # the capacity pass may have preempted
        if not can.any():
            return None
        batch = self._sampling_batch_view()
        rows = self._guided_mask_rows(can)
        if rows is not None:
            batch.mask_rows = rows
            self.guided_ingraph_steps += 1
        prev = self._inflight
        # Non-dispatched rows read the (defined) host value; dispatched
        # rows read the device feedback unless freshly (re)admitted.
        fresh_mask = self._fresh | ~can
        # Invariant: a non-fresh dispatched slot's feed lives in the
        # in-flight step — with no in-flight step every slot is host-fed.
        assert prev is not None or bool(fresh_mask[can].all())
        self._observe_host_gap()
        t0 = time.monotonic()
        tokens, logprobs = self.executor.decode_start(
            self._ps_last_tok,
            fresh_mask,
            # A mixed in-flight step's output is [R + P]; the decode
            # feedback is always the leading R slots.
            prev.tokens[: self.R] if prev is not None else None,
            self._ps_positions,
            self._block_tables,
            can,
            batch,
        )
        self._m_kernel_dispatch.labels(
            kernel=self._kernel_names["decode"]
        ).inc()
        nactive = int(can.sum())
        total_ctx = int(self._ps_positions[can].sum()) + nactive
        snapshot = {}
        for slot in np.nonzero(can)[0]:
            seq = self._running[int(slot)]
            snapshot[int(slot)] = (seq, seq.admit_gen)
        self._ps_pending[can] += 1
        self._ps_positions[can] += 1
        self._ps_steps[can] += 1
        self._fresh[can] = False
        self._m_batch.observe(nactive)
        self._m_steps.inc()
        self.decode_dispatches += 1
        self.collective_overlap_steps += self._overlap_collectives
        if prev is not None:
            self.overlap_steps += 1
        return _InFlight(tokens, logprobs, snapshot, t0, nactive, total_ctx)

    @thread_owned("engine")
    def _drain_step(
        self, flt: Optional[_InFlight], newer: Optional[_InFlight]
    ) -> int:
        """Consume one in-flight step's results (blocks until the device
        finishes it — while `newer`, if any, already executes behind it).
        Per-token emit, tracer windows, block commits, and stop checks all
        live here, off the dispatch path. Late tokens for sequences no
        longer running are discarded; surviving slots not covered by a
        newer dispatch return to host feeding."""
        if flt is None:
            return 0
        if flt.n_emit is not None:
            return self._drain_spec(flt, newer)
        tokens = np.asarray(flt.tokens)
        logprobs = np.asarray(flt.logprobs)
        step_ms = (time.monotonic() - flt.t0) * 1000
        self._profile_tpot.append((flt.nactive, flt.total_ctx, step_ms))
        produced = 0
        now = time.monotonic()
        for slot, (seq, gen) in flt.slots.items():
            if self._running.get(slot) is not seq or seq.admit_gen != gen:
                # The seq stopped/cancelled/was preempted after dispatch
                # (admit_gen also catches a preempt + re-admission of the
                # SAME seq into the SAME slot): one-step-late stop —
                # exactly one over-produced sample to drop (a preempted
                # seq re-samples it deterministically on resume; same
                # (seed, step) key, same context).
                self.late_stop_discards += 1
                continue
            self._ps_pending[slot] -= 1
            tok, lp = int(tokens[slot]), float(logprobs[slot])
            tbt_ms = (now - seq.last_token_time) * 1000
            self._tbt_window.append((now, tbt_ms))
            self._m_tbt.observe(tbt_ms)
            seq.last_token_time = now
            seq.generated.append((tok, lp))
            seq.tokens.append(tok)
            self._ps_last_tok[slot] = tok
            self._ps_gen_count[slot] += 1
            self._ps_tok_count[slot] += 1
            ent = newer.slots.get(slot) if newer is not None else None
            if ent is None or ent[0] is not seq or ent[1] != gen:
                self._fresh[slot] = True
            self._commit_full_blocks(seq)
            produced += 1
            self._emit(seq, finished=self._check_stop(seq))
        produced += self._drain_pf_rows(flt, tokens, logprobs)
        if self.span_hook is not None and produced:
            # One span per drained STEP BATCH (never per token): the
            # engine's decode cadence on the merged timeline.
            self.span_hook(
                "", "step_batch",
                nactive=flt.nactive, produced=produced,
                step_ms=round(step_ms, 3),
            )
        self._t_host_free = time.monotonic()
        return produced

    @thread_owned("engine")
    def _drain_pf_rows(self, flt: _InFlight, tokens, logprobs) -> int:
        """Prefill rows riding a fused dispatch: advance `prefilled`,
        keep the PD chunk stream fed, and on the FINAL chunk run the
        shared post-prefill bookkeeping (_finish_prefill installs the
        slot — the seq starts decoding host-fed next dispatch). A seq
        whose entry no longer matches _pf_active was cancelled after
        dispatch: its chunk's sampled token is discarded like any
        late-stop token. admit_gen guards the same _Seq object being
        re-admitted between dispatch and drain, like the decode-slot
        check. Plain mixed steps carry the pf samples at output rows
        [R + j]; speculative verify steps carry them in pf_tok/pf_lp."""
        pf_tok = (
            np.asarray(flt.pf_tok) if flt.pf_tok is not None else None
        )
        pf_lp = np.asarray(flt.pf_lp) if flt.pf_lp is not None else None
        produced = 0
        for seq, gen, j, c_start, c_end in flt.pf:
            if (
                self._pf_active.get(seq.req.request_id) is not seq
                or seq.admit_gen != gen
            ):
                self.late_stop_discards += 1
                continue
            seq.prefilled = c_end
            if self.span_hook is not None:
                # Per prefill CHUNK (bounded by chunk count, not tokens);
                # keyed by the engine request id — the instance layer's
                # srid-keyed admit span brackets the whole prefill.
                self.span_hook(
                    seq.req.request_id, "prefill_chunk",
                    prefilled=c_end, total=len(seq.tokens),
                    final=c_end >= len(seq.tokens),
                )
            if c_end < len(seq.tokens):
                self._stream_chunk_kv(seq)
                produced += 1
                continue
            del self._pf_active[seq.req.request_id]
            if pf_tok is not None:
                tok = int(pf_tok[j])
                lp = float(pf_lp[j])
            else:
                tok = int(tokens[self.R + j])
                lp = float(logprobs[self.R + j])
            fin = time.monotonic()
            ms = (fin - seq.prefill_start_time) * 1000
            self._finish_prefill(
                seq, tok, lp, fin, ms, len(seq.tokens) - seq.num_cached
            )
            produced += 1
        return produced

    # ------------------------------------------------------------ M-RoPE

    def _mrope_active(self, seq: _Seq) -> bool:
        return bool(
            getattr(self.executor.cfg, "mrope_section", ())
            and seq.req.has_media
        )

    def _mrope_positions(self, seq: _Seq) -> np.ndarray:
        """[3, len(seq.tokens)] (t, h, w) rope streams for a media
        sequence — the HF Qwen2-VL get_rope_index algorithm for square
        still-image grids: text advances all three streams together; an
        image span of m = g*g merged tokens pins t at the span start,
        lays h/w on the g x g grid, and resumes text at start + g. Also
        fixes the sequence's rope_delta (generation positions continue
        from the compressed maximum, not the token count).

        Covers GENERATED tokens too — preemption/PD resume re-prefills
        prompt + generated, so the streams extend on demand with the
        compressed continuation (token i: i + rope_delta, all equal)."""
        need = len(seq.tokens)
        if seq.rope_pos3 is not None and seq.rope_pos3.shape[1] >= need:
            return seq.rope_pos3
        if seq.rope_pos3 is not None:
            base = seq.rope_pos3
            have = base.shape[1]
            ext = (
                np.arange(have, need, dtype=np.int32) + seq.rope_delta
            )[None, :].repeat(3, axis=0)
            seq.rope_pos3 = np.concatenate([base, ext], axis=1)
            return seq.rope_pos3
        L = len(seq.req.prompt_token_ids)
        pos = np.zeros((3, L), np.int32)
        spans = []  # (start, length) contiguous placeholder runs
        mm = sorted(int(p) for p in seq.req.mm_positions)
        run_start = None
        prev = None
        for p in mm:
            if run_start is None:
                run_start = prev = p
                continue
            if p == prev + 1:
                prev = p
                continue
            spans.append((run_start, prev - run_start + 1))
            run_start = prev = p
        if run_start is not None:
            spans.append((run_start, prev - run_start + 1))
        grids = [tuple(int(v) for v in g) for g in (seq.req.mm_grids or ())]
        gi = 0  # next undeclared-grid index (document order, like spans)
        cur = 0  # next rope position value
        idx = 0  # next prompt index to fill
        for s0, m in spans:
            while idx < s0:  # text before the span
                pos[:, idx] = cur
                cur += 1
                idx += 1
            # Declared grids (HF get_rope_index, video-capable): consume
            # greedily — ADJACENT media parts share one contiguous
            # placeholder run, so a span may cover several grids. Each
            # grid's t stream advances per temporal slice of gh*gw
            # tokens, h/w lay the slice; text (or the next medium)
            # resumes at cur + max(t, gh, gw).
            rem = m
            while rem > 0 and gi < len(grids):
                t, gh, gw = grids[gi]
                n_g = t * gh * gw
                if n_g > rem:
                    break
                sl = gh * gw
                for j in range(n_g):
                    pos[0, idx + j] = cur + j // sl
                    pos[1, idx + j] = cur + (j % sl) // gw
                    pos[2, idx + j] = cur + j % gw
                cur += max(t, gh, gw)
                idx += n_g
                rem -= n_g
                gi += 1
            if rem == 0:
                continue
            m = rem
            g = int(round(math.sqrt(m)))
            if g * g != m:
                # non-square span (unknown grid): degrade to sequential
                for j in range(m):
                    pos[:, idx + j] = cur + j
                cur += m
            else:
                for j in range(m):
                    pos[0, idx + j] = cur
                    pos[1, idx + j] = cur + j // g
                    pos[2, idx + j] = cur + j % g
                cur += g
            idx += m
        while idx < L:
            pos[:, idx] = cur
            cur += 1
            idx += 1
        seq.rope_pos3 = pos
        seq.rope_delta = cur - L  # <= 0: image spans compress positions
        if need > L:  # resumed with generated history: extend now
            return self._mrope_positions(seq)
        return pos

    # --------------------------------------------------- guided decoding

    def set_lora_adapters(self, adapters) -> "Dict[str, int]":
        """Install LoRA adapters on the executor (see
        ModelExecutor.set_lora_adapters); returns {name: row}."""
        self.lora_names = self.executor.set_lora_adapters(adapters)
        return self.lora_names

    def set_guided_context(
        self, table: np.ndarray, token_bytes: List[bytes],
        eos_ids: Optional[List[int]] = None,
    ) -> None:
        """Install the JSON-mode mask table ([M, V] bool, one row per
        abstract automaton state — guided/json_fsm.token_mask_table) and
        the per-id byte surfaces the host tracker walks. `eos_ids` is the
        EOS set the TABLE was built with (engine EOS unioned with the
        tokenizer's — instance_serving._build_guided_context); schema
        bitmaps must use the same set or completed documents could never
        emit EOS in deployments where the engine's own set is empty."""
        self.executor.set_guided_table(table)
        self._guided_tokens = token_bytes
        self._guided_row_any = table.any(axis=1)
        self._guided_eos = (
            sorted(set(eos_ids)) if eos_ids is not None
            else sorted(self.eos_token_ids)
        )

    def _guided_row(self, seq: _Seq) -> int:
        """Mask-table row for the seq's NEXT sampled token, advancing the
        exact automaton through any not-yet-consumed emitted tokens.
        Returns the permissive row for unguided seqs, on automaton reject
        (cannot happen under the mask), or for an all-false row (vocab
        cannot express the needed byte — degrade open rather than hang)."""
        from xllm_service_tpu.guided import json_fsm

        perm = self.executor.permissive_row
        if self._guided_tokens is None:
            return perm
        if seq.req.guided == "json_schema":
            spec = seq.schema_spec
            if spec is None:  # first touch (False = compile failed, sticky)
                spec = self._schema_spec_for(seq.req)
                seq.schema_spec = spec if spec is not None else False
            if not spec:
                return perm
            st = self._advance_exact(seq, spec)
            if st is None:
                return perm
            return self._schema_state_row(spec, st)
        if seq.req.guided != "json":
            return perm
        st = self._advance_exact(seq, None)
        if st is None:
            return perm
        row = json_fsm.abstract_index(st)
        if self._guided_row_any is not None and not self._guided_row_any[row]:
            return perm
        return row

    def _advance_exact(self, seq: _Seq, spec):
        """Advance the seq's exact automaton (generic JSON when spec is
        None, schema otherwise) through unconsumed emitted tokens."""
        from xllm_service_tpu.guided import json_fsm, schema_fsm

        if seq.json_state == "INIT":
            seq.json_state = (
                schema_fsm.initial_state(spec) if spec is not None
                else json_fsm.initial_state()
            )
            seq.json_upto = 0
        st = seq.json_state
        toks = self._guided_tokens
        while st is not None and seq.json_upto < len(seq.generated):
            tok = seq.generated[seq.json_upto][0]
            tb = toks[tok] if 0 <= tok < len(toks) else b""
            st = (
                schema_fsm.advance_bytes(spec, st, tb) if spec is not None
                else json_fsm.advance_bytes(st, tb)
            )
            seq.json_upto += 1
        seq.json_state = st
        return st

    def _schema_spec_for(self, req: EngineRequest):
        """Compiled SchemaSpec for the request's schema (memoized by
        canonical schema JSON; compile errors were already rejected at
        the API layer — degrade open if one slips through)."""
        from xllm_service_tpu.guided import schema_fsm

        if req.schema is None:
            return None
        # NO sort_keys: declaration order IS the emission contract.
        key = json.dumps(req.schema, separators=(",", ":"))
        spec = self._schema_specs.get(key)
        if spec is None:
            try:
                spec = schema_fsm.compile_schema(req.schema)
            except schema_fsm.SchemaError:
                logging.getLogger(__name__).warning(
                    "json_schema compile failed post-admission; serving "
                    "unconstrained"
                )
                return None
            # Bounded memo: distinct schemas can be unbounded on a
            # long-lived server (per-request enum values etc.) — evict
            # oldest-inserted past the cap; live seqs keep their spec via
            # seq.schema_spec, so eviction only costs a recompile. The
            # row cache is swept of perm-degrade entries likewise (row
            # entries are already bounded by the dynamic region + flush).
            if len(self._schema_specs) >= 128:
                self._schema_specs.pop(next(iter(self._schema_specs)))
            if len(self._schema_row_cache) >= 8192:
                # perm-degrade entries accumulate without consuming rows;
                # recycle at the next step boundary (mid-step clears could
                # overwrite a row another slot was just assigned).
                self._schema_flush_pending = True
            self._schema_specs[key] = spec
        return spec

    def _schema_state_row(self, spec, st) -> int:
        """Dynamic-row index for an exact schema state: memoized (incl.
        permissive-degrade outcomes — recomputing a full-vocab bitmap per
        step would stall the batch); first visit computes the token
        bitmap and writes it into the executor table's dynamic region.
        On exhaustion the region is flushed BETWEEN steps (a mid-step
        flush could overwrite a row another slot was just assigned) and
        this state degrades open for one step."""
        from xllm_service_tpu.guided import schema_fsm

        ex = self.executor
        perm = ex.permissive_row
        base = getattr(ex, "dynamic_row_base", None)
        if base is None:
            return perm
        key = (spec.source_key, st)
        row = self._schema_row_cache.get(key)
        if row is not None:
            return row
        if self._schema_row_next >= getattr(ex, "num_dynamic_rows", 0):
            # Flush at the next step boundary; this step degrades open.
            if not self._schema_flush_pending:
                self._schema_flush_pending = True
                logging.getLogger(__name__).warning(
                    "guided json_schema: dynamic mask rows exhausted; "
                    "flushing the region at the next step"
                )
            return perm
        bits = self._schema_bitmap_cache.get(key)
        if bits is None:
            bits = self._compute_schema_bitmap(spec, st)
            self._schema_bitmap_put(key, bits)
        if not bits.any():
            self._schema_row_cache[key] = perm  # memoize the degrade
            return perm
        row = base + self._schema_row_next
        self._schema_row_next += 1
        ex.update_guided_row(row, bits)
        self._schema_row_cache[key] = row
        return row

    def _compute_schema_bitmap(self, spec, st) -> np.ndarray:
        """token_bitmap for one exact state (callable from ANY thread —
        everything it reads is immutable or benignly-racy)."""
        from xllm_service_tpu.guided import schema_fsm

        if self._schema_fbi is None:
            # Benign race: two threads may both build; either result is
            # correct and the GIL makes the attribute swap atomic.
            self._schema_fbi = schema_fsm.build_first_byte_index(
                self._guided_tokens
            )
        eos = getattr(self, "_guided_eos", None)
        return schema_fsm.token_bitmap(
            spec, st, self._schema_fbi, len(self._guided_tokens),
            eos if eos is not None else sorted(self.eos_token_ids),
        )

    def _schema_bitmap_put(self, key, bits: np.ndarray) -> None:
        cache = self._schema_bitmap_cache
        if len(cache) >= 4096:  # ~vocab/8 bytes per entry; bound memory
            try:
                cache.pop(next(iter(cache)))
            except (StopIteration, KeyError, RuntimeError):
                pass
        cache[key] = bits

    # Canonical-walk byte preferences: quote first (opens a string value
    # / closes string content), then brace-open, then terminators (end a
    # number / container, move to the next key), digits last so numbers
    # stay one digit — the walk emits one minimal document, visiting
    # every skeleton state and each value node's free-content entry
    # state once.
    _PREWARM_BYTES = (0x22, 0x7B, 0x7D, 0x5D, 0x2C, 0x3A, 0x31)

    def prewarm_schema(self, schema) -> None:
        """Called from the API layer at ADMISSION (HTTP thread) after the
        schema compiles: walk one canonical document through the
        automaton, computing and caching the token bitmap of every state
        visited — object skeleton, key strings, and each value's
        free-content state (the expensive ones: a free string accepts
        most of the vocab, ~vocab Python byte walks). By the time the
        engine step loop first assembles this request, the bitmaps it
        needs are cache hits, so running decodes never stall behind the
        byte walk (advisor finding, round 4). States off the canonical
        path (deep inside free content) still compute lazily on the
        loop, but those are the cheap self-loop variants."""
        from xllm_service_tpu.guided import schema_fsm

        if self._guided_tokens is None or schema is None:
            return
        try:
            spec = schema_fsm.compile_schema(schema)
        except schema_fsm.SchemaError:
            return
        # Once per distinct schema: repeat admissions of a warmed schema
        # skip the canonical walk entirely (review finding, r5). Set ops
        # are GIL-atomic; a racing double-walk is benign (same results).
        if spec.source_key in self._prewarmed_schema_keys:
            return
        if len(self._prewarmed_schema_keys) >= 512:
            self._prewarmed_schema_keys.clear()
        self._prewarmed_schema_keys.add(spec.source_key)
        st = schema_fsm.initial_state(spec)
        seen = set()
        for _ in range(512):  # walk bound (counters make states unique)
            if st is None or st in seen:
                return
            seen.add(st)
            key = (spec.source_key, st)
            if key not in self._schema_bitmap_cache:
                self._schema_bitmap_put(
                    key, self._compute_schema_bitmap(spec, st)
                )
            if schema_fsm.is_complete(st):
                return
            # Prefer a successor not yet visited (a whitespace or digit
            # self-loop must not end the walk while unvisited skeleton
            # remains); an all-seen frontier terminates via the cycle
            # check above.
            nxt = None
            fallback = None
            for b in (*self._PREWARM_BYTES, *range(256)):
                cand = schema_fsm.advance_byte_top(spec, st, b)
                if cand is None:
                    continue
                if cand not in seen:
                    nxt = cand
                    break
                if fallback is None:
                    fallback = cand
            st = nxt if nxt is not None else fallback

    def _maybe_flush_schema_rows(self) -> None:
        """Between-steps recycle of the dynamic mask-row region: drop the
        memo and restart allocation. Live sequences re-derive their rows
        from their current exact state on the next assembly, so no row
        index can be stale."""
        if self._schema_flush_pending:
            self._schema_flush_pending = False
            # Discard writes still buffered for pre-flush rows: the memo
            # clear makes every live row re-derive and re-stage, and a
            # stale buffered write must not share one batched
            # .at[rows].set with a fresh write to the same recycled index
            # (duplicate-index winner is unspecified in JAX — advisor
            # finding, round 4).
            pend = getattr(self.executor, "_pending_guided_rows", None)
            if pend is not None:
                pend.clear()
            self._schema_row_cache.clear()
            self._schema_row_next = 0

    def _guided_rows_spec(self, seq: _Seq, drafts: np.ndarray, S: int):
        """Per-position mask rows for a verify step: position 0 uses the
        current state; position j continues through drafts 0..j-1 (the
        accepted tokens ARE the drafts). An illegal draft leaves later
        positions permissive — sampling rejects at the illegal position
        anyway."""
        from xllm_service_tpu.guided import json_fsm, schema_fsm

        perm = self.executor.permissive_row
        rows = np.full((S,), perm, np.int32)
        r0 = self._guided_row(seq)
        rows[0] = r0
        if r0 == perm:
            return rows
        schema = seq.req.guided == "json_schema"
        # _guided_row above already resolved + cached the spec on the seq.
        spec = seq.schema_spec or None if schema else None
        st = seq.json_state
        toks = self._guided_tokens
        for j in range(1, S):
            d = int(drafts[j - 1])
            tb = toks[d] if 0 <= d < len(toks) else b""
            st = (
                schema_fsm.advance_bytes(spec, st, tb) if schema
                else json_fsm.advance_bytes(st, tb)
            )
            if st is None:
                break
            if schema:
                rows[j] = self._schema_state_row(spec, st)
            else:
                row = json_fsm.abstract_index(st)
                rows[j] = row if self._guided_row_any[row] else perm
        return rows

    # ------------------------------------------------- speculative decode

    def _propose_drafts(self, seq: _Seq, k: int) -> np.ndarray:
        """Prompt-lookup drafting: match the newest suffix n-gram (longest
        first, down to 1) against the sequence's own prompt+generation
        history and propose the k tokens that followed the most recent
        earlier occurrence. No draft model, no extra device work —
        repetitive text (code, quotes, structured output) accepts several
        tokens per step; random text degrades to plain decoding (the
        verify step always emits >= 1 token).

        O(ngram_max) per step (ISSUE 13 satellite): a per-seq rolling
        index maps each n-gram to the position AFTER its latest
        occurrence, extended incrementally as history grows — the old
        implementation re-materialized the lookback window and ran a
        sliding-window scan over every n-gram length on every step
        (O(lookback x ngram_max)). Gram-ends are indexed only up to
        len(tokens) - 2 (the newest gram has no follow token yet), so
        the suffix can never match itself; a long RESUMED history
        (preemption / PD import) back-fills in one pass bounded by
        `speculative_lookback`. Stale follow positions from a replaced
        token list (test stand-ins) fall through to shorter grams.
        Memory stays bounded by the lookback too: past ~2x the window's
        worth of entries the index rebuilds from the trailing window
        (amortized O(ngram_max)/step — the rebuild happens once per
        lookback's worth of emitted tokens)."""
        toks = seq.tokens
        m = len(toks)
        n_cfg = self.cfg.speculative_ngram_max
        lookback = self.cfg.speculative_lookback
        try:
            idx = seq.spec_ngrams
            upto = seq.spec_idx_upto
        except AttributeError:  # stand-in seq objects without the slots
            idx = seq.spec_ngrams = {}
            upto = 0
        if len(idx) > 2 * n_cfg * lookback:
            idx.clear()
            upto = 0
        start = max(upto, m - 1 - lookback)
        for end in range(start, m - 1):
            hi = end + 1
            for n in range(1, min(n_cfg, hi) + 1):
                idx[tuple(toks[hi - n: hi])] = hi
        seq.spec_idx_upto = max(m - 1, upto)
        n_max = min(n_cfg, m - 1)
        for n in range(n_max, 0, -1):
            f = idx.get(tuple(toks[m - n: m]))
            if f is not None:
                follow = toks[f: f + k]
                if follow:
                    out = np.empty((k,), np.int32)
                    out[: len(follow)] = follow
                    out[len(follow):] = follow[-1]
                    return out
        return np.full((k,), toks[-1], np.int32)

    @thread_owned("engine")
    def _step_spec(self) -> int:
        """One pipelined speculative iteration (docs/ENGINE_PIPELINE.md):
        cut the due prefill chunks, dispatch verify step N+1 fused with
        them (the composed path: verify rows are q_len = k+1 ragged rows
        next to the chunks — docs/KERNELS.md), then drain/book step N
        while N+1 runs. Step N+1's verify inputs — last accepted token,
        position and step base — are gathered ON DEVICE from step N's
        output, so the VARIABLE accepted count never round-trips the
        host; the host proposes drafts from its one-step-late history,
        which is sound because point-mass acceptance makes the emitted
        stream draft-independent (ops/sampling.py)."""
        items_meta: List[tuple] = []
        produced0 = 0
        fuse = self.mixed_step_enabled and getattr(
            self.executor, "supports_spec_mixed", False
        )
        if fuse:
            budget = self._continue_pf_chunks(
                items_meta, self.cfg.max_prefill_tokens
            )
            legacy = self._admit(mixed_collect=items_meta, budget=budget)
        else:
            if self._pf_active:
                # Mixed support flipped off mid-run: drain and hand the
                # held seqs to the split midchunk flow.
                produced0 = self._flush_pipeline_state()
            legacy = self._admit()
        nxt = self._dispatch_verify(items_meta)
        produced = self._drain_step(self._inflight, nxt)
        self._inflight = nxt
        return produced0 + legacy + produced

    @thread_owned("engine")
    def _dispatch_verify(
        self, items_meta: List[tuple]
    ) -> Optional[_InFlight]:
        """Dispatch speculative verify step N+1 without fetching results
        (executor.verify_start), optionally fused with due prefill
        chunks. Guided slots join host-paced (exact automaton state at
        dispatch — their drafts AND mask rows derive from fully drained
        history); length-stops surface one step late as discards, and
        the capacity pass covers TWO steps of worst-case emission
        because the in-flight step may advance a slot by up to S before
        this dispatch's writes land."""
        k = self.cfg.speculative_tokens
        S = k + 1
        R = self.R
        can = self._apply_guided_pacing(self._ps_active.copy())
        # Host-fed slots re-derive their dispatch state from token truth
        # BEFORE the capacity pass reads positions: the sync verify path
        # refreshes lazily at the start of its own next step, so a
        # sync->pipeline hatch flip would otherwise dispatch from arrays
        # that lag the last sync step's variable emissions.
        for slot in np.nonzero(can & self._fresh)[0]:
            seq = self._running.get(int(slot))
            if seq is not None:
                self._refresh_slot_arrays(int(slot), seq)
        if can.any():
            self._ensure_decode_capacity(2 * S, mask=can)
            can &= self._ps_active  # the capacity pass may have preempted
        if not can.any() and not items_meta:
            return None
        batch = self._sampling_batch_view()
        prev = self._inflight
        fresh_mask = self._fresh | ~can
        assert prev is not None or bool(fresh_mask[can].all())
        drafts = np.zeros((R, k), np.int32)
        for slot in np.nonzero(can)[0]:
            drafts[int(slot)] = self._propose_drafts(
                self._running[int(slot)], k
            )
        if self._guided_tokens is not None and any(
            can[s] for s in self._guided_slots
        ):
            rows = np.full(
                (R, S), self.executor.permissive_row, np.int32
            )
            for slot in self._guided_slots:
                if can[slot]:
                    rows[slot] = self._guided_rows_spec(
                        self._running[slot], drafts[slot], S
                    )
            batch.mask_rows = rows
            self.guided_ingraph_steps += 1
        self._observe_host_gap()
        t0 = time.monotonic()
        items, pf_entries = self._build_pf_items(items_meta, t0)
        tokens, logprobs, n_emit, pf_tok, pf_lp = (
            self.executor.verify_start(
                items,
                drafts,
                self._ps_last_tok,
                self._ps_positions,
                self._ps_steps,
                fresh_mask,
                prev.tokens if prev is not None else None,
                prev.n_emit if prev is not None else None,
                self._block_tables,
                can,
                batch,
                interpret=self._ragged_interpret,
            )
        )
        nactive = int(can.sum())
        total_ctx = int(self._ps_positions[can].sum()) + nactive
        snapshot = {}
        for slot in np.nonzero(can)[0]:
            seq = self._running[int(slot)]
            snapshot[int(slot)] = (seq, seq.admit_gen)
        self._ps_pending[can] += 1
        self._fresh[can] = False
        self._m_batch.observe(nactive)
        self._m_steps.inc()
        self.decode_dispatches += 1
        self.collective_overlap_steps += self._overlap_collectives
        self.spec_steps += 1
        self.spec_slot_steps += nactive
        self.spec_pipeline_steps += 1
        if items:
            self.mixed_steps += 1
            self._m_mixed_pf_rows.observe(len(items))
            self._m_mixed_dec_rows.observe(nactive)
            self._m_kernel_dispatch.labels(
                kernel=self._kernel_names["mixed"]
            ).inc()
        else:
            self._m_kernel_dispatch.labels(
                kernel=self._kernel_names["mq"]
            ).inc()
        if prev is not None:
            self.overlap_steps += 1
        return _InFlight(
            tokens, logprobs, snapshot, t0, nactive, total_ctx,
            pf=pf_entries, n_emit=n_emit, pf_tok=pf_tok, pf_lp=pf_lp,
        )

    @thread_owned("engine")
    def _drain_spec(
        self, flt: _InFlight, newer: Optional[_InFlight]
    ) -> int:
        """Consume one pipelined verify step's results — the speculative
        twin of _drain_step's decode booking: each surviving slot emits
        its accepted prefix + the corrected/bonus token (1..S tokens,
        exactly _decode_spec_once's host loop), one step late. A slot
        that stopped/cancelled/was preempted after dispatch discards
        the WHOLE row (the one-step-late stop contract, scaled to
        variable emission); surviving slots re-derive their host
        dispatch state from token truth — incremental +1 advances
        cannot track variable accepted counts."""
        tokens = np.asarray(flt.tokens)
        logprobs = np.asarray(flt.logprobs)
        n_emit = np.asarray(flt.n_emit)
        step_ms = (time.monotonic() - flt.t0) * 1000
        self._profile_tpot.append((flt.nactive, flt.total_ctx, step_ms))
        produced = 0
        now = time.monotonic()
        for slot, (seq, gen) in flt.slots.items():
            if self._running.get(slot) is not seq or seq.admit_gen != gen:
                self.late_stop_discards += 1
                continue
            self._ps_pending[slot] -= 1
            ne = int(n_emit[slot])
            self._m_spec_accepted.observe(ne)
            self.spec_tokens_emitted += ne
            if ne:
                tbt_ms = (now - seq.last_token_time) * 1000
                self._tbt_window.append((now, tbt_ms))
                self._m_tbt.observe(tbt_ms)
                seq.last_token_time = now
            alive = True
            for i in range(ne):
                tok, lp = int(tokens[slot, i]), float(logprobs[slot, i])
                seq.generated.append((tok, lp))
                seq.tokens.append(tok)
                self._commit_full_blocks(seq)
                produced += 1
                if not self._emit(seq, finished=self._check_stop(seq)):
                    alive = False  # finished/cancelled: drop the rest
                    break
            if alive and self._running.get(slot) is seq:
                self._refresh_slot_arrays(slot, seq)
                ent = newer.slots.get(slot) if newer is not None else None
                if ent is None or ent[0] is not seq or ent[1] != gen:
                    self._fresh[slot] = True
        produced += self._drain_pf_rows(flt, tokens, logprobs)
        self._t_host_free = time.monotonic()
        return produced

    @thread_owned("engine")
    def _decode_spec_once(self) -> int:
        """Speculative variant of _decode_once: feed [last_token, k drafts]
        per sequence, verify in one pass, emit the accepted prefix + one
        corrected/bonus token. Identical output stream to the plain path
        (see EngineConfig.speculative_tokens), 1..k+1 tokens per step."""
        if not self._running:
            return 0
        k = self.cfg.speculative_tokens
        S = k + 1
        max_len = self.cfg.max_seq_len
        # Variable emission counts: re-derive dispatch state from host
        # truth before the capacity pass reads the position array.
        for slot, seq in self._running.items():
            self._refresh_slot_arrays(slot, seq)
        self._ensure_decode_capacity(S)
        if not self._running:
            return 0

        token_ids = np.zeros((self.R, S), np.int32)
        positions = np.zeros((self.R,), np.int32)
        true_len = np.zeros((self.R,), np.int32)
        active = np.zeros((self.R,), bool)
        batch = self._sampling_batch_view()
        for slot, seq in self._running.items():
            pos = len(seq.tokens) - 1
            token_ids[slot, 0] = seq.tokens[-1]
            token_ids[slot, 1:] = self._propose_drafts(seq, k)
            positions[slot] = pos
            true_len[slot] = max(1, min(S, max_len - pos))
            active[slot] = True
        if self._guided_tokens is not None and any(
            s.req.guided for s in self._running.values()
        ):
            rows = np.full(
                (self.R, S), self.executor.permissive_row, np.int32
            )
            for slot, seq in self._running.items():
                rows[slot] = self._guided_rows_spec(
                    seq, token_ids[slot, 1:], S
                )
            batch.mask_rows = rows

        t0 = time.monotonic()
        self._m_kernel_dispatch.labels(
            kernel=self._kernel_names["mq"]
        ).inc()
        tokens, logprobs, n_emit = self.executor.verify(
            token_ids,
            positions,
            true_len,
            self._block_tables,
            active,
            batch,
        )
        step_ms = (time.monotonic() - t0) * 1000
        nactive = int(active.sum())
        total_ctx = int(positions[active].sum()) + nactive
        self._profile_tpot.append((nactive, total_ctx, step_ms))
        self._m_batch.observe(nactive)
        self._m_steps.inc()
        self.decode_dispatches += 1
        self.collective_overlap_steps += self._overlap_collectives
        self.spec_steps += 1
        self.spec_sync_steps += 1
        self.spec_slot_steps += nactive
        self.spec_tokens_emitted += int(n_emit[active].sum())

        produced = 0
        now = time.monotonic()
        for slot in list(self._running.keys()):
            seq = self._running[slot]
            tbt_ms = (now - seq.last_token_time) * 1000
            self._tbt_window.append((now, tbt_ms))
            self._m_tbt.observe(tbt_ms)
            seq.last_token_time = now
            for i in range(int(n_emit[slot])):
                tok, lp = int(tokens[slot, i]), float(logprobs[slot, i])
                seq.generated.append((tok, lp))
                seq.tokens.append(tok)
                self._commit_full_blocks(seq)
                produced += 1
                if not self._emit(seq, finished=self._check_stop(seq)):
                    break  # finished or cancelled: drop remaining tokens
        return produced

    # ---------------------------------------------------------- preemption

    def _pick_preemption_victim(self, exclude: int) -> Optional[_Seq]:
        candidates = [s for sl, s in self._running.items() if sl != exclude]
        if not candidates:
            return None
        # Offline work is always sacrificed before online work; within a
        # class, youngest first (least work lost on recompute).
        offline = [s for s in candidates if s.req.offline]
        pool = offline or candidates
        return max(pool, key=lambda s: s.req.arrival_time)

    @thread_owned("engine")
    def _preempt_offline_for(self, head: EngineRequest) -> bool:
        """Hybrid-scheduling preemption: an ONLINE head waiting on slots
        or blocks evicts one RUNNING offline decode (recompute-style; the
        victim requeues BEHIND online work and resumes when pressure
        clears). Returns False when the head is itself offline or no
        offline victim is running. Called WITHOUT self._lock held."""
        if head.offline:
            return False
        victims = [s for s in self._running.values() if s.req.offline]
        if not victims:
            return False
        victim = max(victims, key=lambda s: s.req.arrival_time)
        self._preempt(victim, requeue_front=False)
        return True

    @thread_owned("engine")
    def _preempt(self, seq: _Seq, requeue_front: bool = True) -> None:
        """Recompute-style preemption: release blocks and requeue the _Seq
        itself, preserving token history and generation accounting (KV is
        recomputed on re-admission; prefix-cache blocks soften the cost).
        Offline victims of online pressure requeue at the BACK
        (requeue_front=False) so the admission partition keeps online
        work ahead of them."""
        self.preemptions += 1
        self.block_mgr.free(seq.block_ids)
        seq.block_ids = []
        seq.last_committed_block = -1
        del self._running[seq.slot]
        self._free_slots.append(seq.slot)
        self._slot_clear(seq.slot)
        with self._lock:
            if requeue_front:
                self._waiting.appendleft(seq)
            else:
                self._waiting.append(seq)

    # ------------------------------------------------------------- commits

    def _commit_full_blocks(self, seq: _Seq) -> None:
        """Commit newly filled blocks under their chained hashes. Media
        requests never commit (their KV depends on encoder embeddings the
        token-id hash cannot see) and neither do LoRA-adapter requests
        (adapter-dependent KV under adapter-blind hashes)."""
        if seq.req.has_media or seq.req.adapter_idx:
            return
        full = len(seq.tokens) // self.block_size
        committed = seq.last_committed_block + 1
        if full <= committed:
            return
        hashes = prefix_block_hashes(
            seq.tokens[: full * self.block_size], self.block_size,
            seed=self.block_mgr.seed,
        )
        for i in range(committed, full):
            self.block_mgr.commit_block(seq.block_ids[i], hashes[i])
        seq.last_committed_block = full - 1

    # ---------------------------------------------------------------- stop

    def _check_stop(self, seq: _Seq) -> Optional[FinishReason]:
        s = seq.req.sampling
        tok = seq.tokens[-1]
        if not s.ignore_eos and tok in self.eos_token_ids:
            return FinishReason.STOP
        if tok in s.stop_token_ids:
            return FinishReason.STOP
        if len(seq.generated) >= s.max_new_tokens:
            return FinishReason.LENGTH
        if len(seq.tokens) >= self.cfg.max_seq_len:
            return FinishReason.LENGTH
        return None

    # ---------------------------------------------------------------- emit

    @thread_owned("engine")
    def _emit(self, seq: _Seq, finished: Optional[FinishReason]) -> bool:
        tok, lp = seq.generated[-1]
        s = seq.req.sampling
        seq_out = SequenceOutput(
            index=0,
            token_ids=[tok],
            finish_reason=finished or FinishReason.NONE,
        )
        if s.logprobs:
            seq_out.logprobs = [LogProb(data=LogProbData(token_id=tok, logprob=lp))]
        out = RequestOutput(
            request_id=seq.req.request_id,
            outputs=[seq_out],
            usage=Usage(
                num_prompt_tokens=len(seq.req.prompt_token_ids),
                num_generated_tokens=len(seq.generated),
            ),
            finished=finished is not None,
        )
        keep_going = True
        try:
            keep_going = seq.req.callback(out)
        except Exception:  # callback errors must not kill the engine loop
            import traceback

            traceback.print_exc()
            keep_going = False
        if finished is not None:
            self._finish(seq, finished)
            return False
        if keep_going is False:
            self._finish(seq, FinishReason.NONE, cancelled=True)
            return False
        return True

    @thread_owned("engine")
    def _finish(
        self, seq: _Seq, reason: FinishReason, cancelled: bool = False
    ) -> None:
        # A prefill_only request reaching _finish (cancel, or EOS/limit on
        # its very first token) will never run its handoff — its streaming
        # session must not leak on the decode peer.
        self._dispose_stream(seq.req)
        if seq.slot in self._running:
            del self._running[seq.slot]
            self._free_slots.append(seq.slot)
            self._slot_clear(seq.slot)
        self.block_mgr.free(seq.block_ids)
        seq.block_ids = []
        # Slot + blocks freed: wake a loop that backed off with waiting
        # work blocked on KV capacity (the event replaces the old blind
        # sleep in _loop).
        self._work.set()
        if cancelled:
            out = RequestOutput(
                request_id=seq.req.request_id,
                finished=True,
                cancelled=True,
                status=Status(StatusCode.CANCELLED, "cancelled"),
            )
            try:
                seq.req.callback(out)
            except Exception:
                pass
