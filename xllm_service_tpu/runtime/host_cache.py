"""Host (DRAM) KV-cache tier for evicted prefix blocks.

Engine-side analog of the reference's multi-tier cache: its engine emits
offload events whose tier transitions the service index tracks
(reference xllm_service/scheduler/managers/global_kvcache_mgr.cpp:177-225,
proto:47 `offload_cache`). Here, committed blocks evicted from the HBM pool
are copied into pinned host memory instead of dropped; a later prefix match
re-imports them (HBM re-promotion) for the cost of a host->device copy
instead of a recompute.

TPU design note: transfers ride the same host<->HBM DMA path jax uses for
np.asarray / device_put; blocks are [2, L, Hkv, BS, D] contiguous arrays so
each offload/restore is one bulk copy, not a per-token scatter.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np


class HostKVPool:
    """LRU pool of content-addressed KV blocks in host DRAM.

    Keys are the chained murmur3 block hashes (the cross-tier contract);
    values are [2, L, Hkv, BS, D] host arrays (k, v stacked).
    """

    def __init__(self, capacity_blocks: int):
        if capacity_blocks <= 0:
            raise ValueError("HostKVPool needs capacity > 0")
        self.capacity = capacity_blocks
        self._data: "OrderedDict[bytes, np.ndarray]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, block_hash: bytes) -> bool:
        return block_hash in self._data

    def get(self, block_hash: bytes) -> Optional[np.ndarray]:
        kv = self._data.get(block_hash)
        if kv is not None:
            self._data.move_to_end(block_hash)
        return kv

    def put(self, block_hash: bytes, kv: np.ndarray) -> List[bytes]:
        """Store a block; returns the hashes LRU-evicted to make room."""
        evicted: List[bytes] = []
        if block_hash in self._data:
            self._data.move_to_end(block_hash)
            return evicted
        while len(self._data) >= self.capacity:
            h, _ = self._data.popitem(last=False)
            evicted.append(h)
        self._data[block_hash] = np.ascontiguousarray(kv)
        return evicted
