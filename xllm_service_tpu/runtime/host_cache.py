"""Host (DRAM) KV-cache tier for evicted prefix blocks.

Engine-side analog of the reference's multi-tier cache: its engine emits
offload events whose tier transitions the service index tracks
(reference xllm_service/scheduler/managers/global_kvcache_mgr.cpp:177-225,
proto:47 `offload_cache`). Here, committed blocks evicted from the HBM pool
are copied into pinned host memory instead of dropped; a later prefix match
re-imports them (HBM re-promotion) for the cost of a host->device copy
instead of a recompute.

TPU design note: transfers ride the same host<->HBM DMA path jax uses for
np.asarray / device_put; blocks are [2, L, Hkv, BS, D] contiguous arrays so
each offload/restore is one bulk copy, not a per-token scatter.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import List, Optional, Tuple

import numpy as np


class HostKVPool:
    """LRU pool of content-addressed KV blocks in host DRAM.

    Keys are the chained murmur3 block hashes (the cross-tier contract);
    values are [2, L, Hkv, BS, D] host arrays (k, v stacked).
    """

    def __init__(self, capacity_blocks: int):
        if capacity_blocks <= 0:
            raise ValueError("HostKVPool needs capacity > 0")
        self.capacity = capacity_blocks
        self._data: "OrderedDict[bytes, np.ndarray]" = OrderedDict()
        # Observability counters (engine-thread only, like the pool):
        # exported as xllm_engine_host_cache_{hits,misses,evictions}_total.
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._data)

    def __contains__(self, block_hash: bytes) -> bool:
        return block_hash in self._data

    def hashes(self) -> List[bytes]:
        """Every held hash (fabric cache-resync snapshots). Racy
        off-thread read by design — callers tolerate one-beat drift; the
        retry only guards resize-during-iteration."""
        for _ in range(3):
            try:
                return list(self._data)
            except RuntimeError:
                continue
        return []

    def get(self, block_hash: bytes) -> Optional[np.ndarray]:
        kv = self._data.get(block_hash)
        if kv is not None:
            self.hits += 1
            self._data.move_to_end(block_hash)
        else:
            self.misses += 1
        return kv

    def put(
        self, block_hash: bytes, kv: np.ndarray
    ) -> List[Tuple[bytes, np.ndarray]]:
        """Store a block; returns the (hash, kv) pairs LRU-evicted to make
        room — the caller may demote them to a colder tier (SSD)."""
        evicted: List[Tuple[bytes, np.ndarray]] = []
        if block_hash in self._data:
            self._data.move_to_end(block_hash)
            return evicted
        while len(self._data) >= self.capacity:
            h, arr = self._data.popitem(last=False)
            self.evictions += 1
            evicted.append((h, arr))
        self._data[block_hash] = np.ascontiguousarray(kv)
        return evicted


class SsdKVPool:
    """Coldest tier: content-addressed KV blocks on local disk (the
    reference's SSD tier — global_kvcache_mgr.cpp tier transitions,
    proto:47). One .npy file per block, LRU by insertion/touch order."""

    def __init__(self, directory: str, capacity_blocks: int):
        if capacity_blocks <= 0:
            raise ValueError("SsdKVPool needs capacity > 0")
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.capacity = capacity_blocks
        self._index: "OrderedDict[bytes, tuple]" = OrderedDict()
        # Purge stale spill files from prior runs: the in-memory index
        # starts empty, so anything on disk is unreachable garbage.
        for f in os.listdir(directory):
            if f.endswith(".kv"):
                try:
                    os.remove(os.path.join(directory, f))
                except OSError:
                    pass

    def close(self) -> None:
        """Delete this pool's spill files (engine shutdown)."""
        for _, (path, _, _) in self._index.items():
            try:
                os.remove(path)
            except OSError:
                pass
        self._index.clear()

    def __len__(self) -> int:
        return len(self._index)

    def __contains__(self, block_hash: bytes) -> bool:
        return block_hash in self._index

    def hashes(self) -> List[bytes]:
        """Every held hash (fabric cache-resync snapshots); same racy-read
        contract as HostKVPool.hashes."""
        for _ in range(3):
            try:
                return list(self._index)
            except RuntimeError:
                continue
        return []

    def _path(self, block_hash: bytes) -> str:
        return os.path.join(self.dir, block_hash.hex() + ".kv")

    def get(self, block_hash: bytes) -> Optional[np.ndarray]:
        entry = self._index.get(block_hash)
        if entry is None:
            return None
        self._index.move_to_end(block_hash)
        path, dtype, shape = entry
        try:
            with open(path, "rb") as f:
                return np.frombuffer(f.read(), dtype=dtype).reshape(shape)
        except Exception:
            self._index.pop(block_hash, None)
            return None

    def put(self, block_hash: bytes, kv: np.ndarray) -> List[bytes]:
        """Spill a block to disk; returns hashes dropped entirely. Raw
        bytes + in-index (dtype, shape) metadata — np.save cannot
        round-trip ml_dtypes bfloat16."""
        dropped: List[bytes] = []
        if block_hash in self._index:
            self._index.move_to_end(block_hash)
            return dropped
        while len(self._index) >= self.capacity:
            h, (path, _, _) = self._index.popitem(last=False)
            try:
                os.remove(path)
            except OSError:
                pass
            dropped.append(h)
        kv = np.ascontiguousarray(kv)
        path = self._path(block_hash)
        with open(path, "wb") as f:
            f.write(kv.tobytes())
        self._index[block_hash] = (path, kv.dtype, kv.shape)
        return dropped
