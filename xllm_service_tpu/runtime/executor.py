"""Model executor: owns params + paged KV cache on a device mesh and exposes
jitted prefill/decode steps with fused sampling.

Engine-tier component (the reference's analog is inside the absent xLLM
submodule; the service-visible contracts it must honor are the 128-token
block size and the KV-handle metadata relayed in InstanceMetaInfo —
SURVEY.md §2.3).

TPU design points:
  * one compiled decode step for a FIXED batch of R slots — batch
    composition changes never recompile (SURVEY.md §7 hard part 3);
  * prefill lengths are bucketed; each bucket compiles once;
  * KV caches are donated through every step (in-place update, no HBM copy);
  * sampling runs on-device inside the same jit — only R int32 tokens +
    R float32 logprobs cross back to the host per step;
  * params/caches carry NamedShardings from parallel/sharding.py; under
    multi-device meshes XLA emits the TP collectives.
"""

from __future__ import annotations

import logging
import math
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from xllm_service_tpu.common.config import EngineConfig
from xllm_service_tpu.runtime import compile_cache as compile_cache_mod
from xllm_service_tpu import models
from xllm_service_tpu.models.configs import (
    ModelConfig,
    approx_param_count,
    get_model_config,
)
from xllm_service_tpu.ops import sampling as sampling_ops
from xllm_service_tpu.parallel.mesh import build_mesh
from xllm_service_tpu.ops import kv_cache as kvc
from xllm_service_tpu.parallel.sharding import (
    check_tp_divisibility,
    kv_cache_sharding,
    kv_scale_sharding,
    param_shardings,
    resolve_kv_packing,
)


@dataclass
class SamplingBatch:
    """Device-ready per-slot sampling params for the fixed decode batch."""

    temperature: np.ndarray  # [R] float32
    top_k: np.ndarray  # [R] int32
    top_p: np.ndarray  # [R] float32
    seeds: np.ndarray  # [R] uint32
    steps: np.ndarray  # [R] int32 (per-request generated-token count)
    # OpenAI penalties over generated tokens; None = all zeros (no penalty).
    presence: Optional[np.ndarray] = None  # [R] float32
    frequency: Optional[np.ndarray] = None  # [R] float32
    # OpenAI logit_bias, sparse: ids [R, K] int32 + vals [R, K] float32
    # (padding entries (0, 0.0)); None = no bias anywhere in the batch.
    bias_ids: Optional[np.ndarray] = None
    bias_vals: Optional[np.ndarray] = None
    # Guided decoding: per-slot rows into the executor's mask table
    # (set_guided_table); unguided slots carry the permissive row. None =
    # nothing guided in the batch. Decode: [R]; verify: [R, S].
    mask_rows: Optional[np.ndarray] = None
    # Multi-LoRA: per-slot adapter rows (0 = base). None = whole batch on
    # the base model (the LoRA einsums trace away entirely).
    adapter_idx: Optional[np.ndarray] = None
    # min_p filtering; None = disabled for the whole batch.
    min_p: Optional[np.ndarray] = None
    # Qwen2-VL M-RoPE: per-slot rope-position lag (<= 0; image spans
    # compress positions). None = no VLM sequences in the batch.
    rope_delta: Optional[np.ndarray] = None


@dataclass
class PrefillItem:
    """One sequence's uncached prompt suffix for a batched prefill step."""

    token_ids: np.ndarray  # [n] int32
    start_pos: int  # cached tokens before this chunk (prefix-cache hit)
    block_table: np.ndarray  # [>=ceil((start_pos+n)/bs)] int32
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    step: int = 0
    # Media-token injection (EPD): embeddings [m, E] overwrite the prompt's
    # placeholder rows at these ABSOLUTE prompt positions.
    mm_embeds: Optional[np.ndarray] = None
    mm_positions: Optional[np.ndarray] = None
    # Penalty state for the token sampled at (re)admission: prior generated
    # tokens (non-empty on preemption/PD resume) and the penalty strengths.
    presence: float = 0.0
    frequency: float = 0.0
    prior_tokens: Optional[np.ndarray] = None
    # OpenAI logit_bias pairs ((token_id, bias), ...) for the token
    # sampled at (re)admission.
    logit_bias: tuple = ()
    # Guided decoding mask row for the admission-sampled token (-1 = none).
    mask_row: int = -1
    # Multi-LoRA adapter row (0 = base).
    adapter_idx: int = 0
    min_p: float = 0.0
    # Qwen2-VL M-RoPE: (t, h, w) position streams for THIS CHUNK's
    # tokens, [3, n] (None = standard 1D positions). Cache slots stay
    # token-count-based; only the q/k rotation reads these.
    rope_positions: Optional[np.ndarray] = None


_COMPILATION_CACHE_DIR: Optional[str] = None
# Guards lazy _embed_jit creation: /v1/embeddings arrives on concurrent
# HTTP handler threads; double-tracing a 20-40s TPU compile must not race.
import threading as _threading

_EMBED_INIT_LOCK = _threading.Lock()


def _setup_compilation_cache(cache_dir: str) -> None:
    """Set the process-global persistent jit cache ONCE (restarts / PD
    role flips / elastic scale-outs then skip the 20-40 s/shape TPU
    compiles). The jax config is process-global, so first non-empty dir
    wins; a co-resident engine asking for a DIFFERENT dir gets a warning
    and shares the first (an engine with "" simply doesn't call this —
    it cannot unset what another engine enabled)."""
    global _COMPILATION_CACHE_DIR
    if _COMPILATION_CACHE_DIR is not None:
        if _COMPILATION_CACHE_DIR != cache_dir:
            import warnings

            warnings.warn(
                f"compilation_cache_dir={cache_dir!r} ignored: process "
                f"already caches to {_COMPILATION_CACHE_DIR!r} (jax "
                f"config is process-global)",
                stacklevel=3,
            )
        return
    _COMPILATION_CACHE_DIR = cache_dir
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # jax initializes the persistent cache ONCE, at the first compile —
    # any compile before this point (weight init of an earlier cacheless
    # engine, a warmed-up sibling model) permanently pins it to the
    # no-dir state and every later write silently vanishes. Reset so the
    # next compile re-initializes against the dir just configured.
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _jax_cc,
        )

        _jax_cc.reset_cache()
    except Exception:
        pass  # never let cache plumbing take an engine down
    # XLLM_COMPILE_CACHE_MIN_COMPILE_S: persistence floor (s) below which
    # a compile isn't written to disk. 0.5 keeps TPU caches lean; the
    # CPU bench/tests pin 0 so their sub-second programs persist and the
    # cold-vs-warm compile_ms delta is measurable.
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ.get("XLLM_COMPILE_CACHE_MIN_COMPILE_S", "0.5")),
    )


class ModelExecutor:
    # guided decoding: index of the appended all-True row once
    # set_guided_table runs; a safe default for unguided paths
    permissive_row = 0

    def __init__(
        self,
        engine_cfg: EngineConfig,
        model_cfg: Optional[ModelConfig] = None,
        mesh: Optional[Mesh] = None,
        init_seed: int = 0,
    ):
        self.engine_cfg = engine_cfg
        # Multi-host: join the process group BEFORE the first backend
        # touch, so build_mesh below sees the GLOBAL device list
        # (parallel/distributed.py; no-op when coordinator_address is "").
        if engine_cfg.coordinator_address:
            from xllm_service_tpu.parallel import distributed

            distributed.bootstrap(
                engine_cfg.coordinator_address,
                engine_cfg.num_processes,
                engine_cfg.process_id,
            )
        if model_cfg is not None:
            self.cfg = model_cfg
        elif engine_cfg.checkpoint_path and os.path.exists(
            os.path.join(engine_cfg.checkpoint_path, "config.json")
        ):
            # Real HF checkpoint dirs carry their own architecture — the
            # registry is for test/bench configs (runtime/weights.py).
            from xllm_service_tpu.runtime.weights import config_from_hf

            self.cfg = config_from_hf(
                engine_cfg.checkpoint_path, name=engine_cfg.model
            )
        else:
            self.cfg = get_model_config(engine_cfg.model)
        # Model-family dispatch (llama-style GQA vs deepseek-style MLA) —
        # every family exports the same step-function surface.
        self.model_mod = models.get_module(self.cfg)
        self.num_caches = models.num_caches(self.cfg)
        self.mesh = mesh or build_mesh(
            engine_cfg.dp_size, engine_cfg.tp_size, engine_cfg.ep_size,
            engine_cfg.sp_size,
        )
        tp = self.mesh.shape.get("tp", 1)
        ep = self.mesh.shape.get("ep", 1)
        # resolve_kv_packing downgraded the cache to the unpacked layout
        # (tp doesn't divide the packed head count): decode runs the
        # gather path, and the degradation must be VISIBLE — kernel_report
        # marks it "gather-fallback" and the engine's
        # xllm_engine_kernel_dispatch_total counts it under that label
        # instead of burying one warning in the logs.
        self.kv_pack_fallback = False
        if tp > 1 or ep > 1:
            check_tp_divisibility(self.cfg, tp, ep)
            # Packed head_dim<128 rows shard only when tp divides the
            # packed count; otherwise serve unpacked via the gather path.
            resolved = resolve_kv_packing(self.cfg, tp)
            if resolved is not self.cfg:
                self.kv_pack_fallback = True
                logging.getLogger(__name__).warning(
                    "tp=%d doesn't divide the packed KV-head count of %s "
                    "(Hkv=%d, D=%d): serving the UNPACKED cache layout — "
                    "decode uses the gather path, not the Pallas kernel; "
                    "tp=%d would restore packing",
                    tp, self.cfg.name, self.cfg.num_kv_heads,
                    self.cfg.head_dim,
                    self.cfg.num_kv_heads
                    // kvc.kv_pack_factor(
                        self.cfg.num_kv_heads, self.cfg.head_dim
                    ),
                )
            self.cfg = resolved

        # Persistent compile cache, KEYED by (config hash, jax version,
        # mesh shape): a restarted instance with the same geometry
        # reloads every executable from disk; a changed geometry gets a
        # fresh keyspace (runtime/compile_cache.py, ISSUE 18).
        self.compile_cache_key = ""
        _cache_base = compile_cache_mod.resolve_cache_dir(engine_cfg)
        if _cache_base:
            self.compile_cache_key = compile_cache_mod.cache_key(
                engine_cfg, self.cfg, self.mesh
            )
            _setup_compilation_cache(
                compile_cache_mod.keyed_dir(_cache_base, self.compile_cache_key)
            )
        # Prewarm bookkeeping (prewarm_programs): lowerings present when
        # the prewarm finished (0 = never prewarmed — every lowering is
        # a compile-cache miss for the engine's instruments).
        self.prewarm_ms = 0.0
        self.prewarmed_lowerings = 0
        self.dtype = jnp.bfloat16 if engine_cfg.dtype == "bfloat16" else jnp.float32
        # int8 KV cache: halves decode's HBM traffic (the bound resource);
        # params/activations stay in model dtype.
        if engine_cfg.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(
                f"kv_cache_dtype={engine_cfg.kv_cache_dtype!r}: expected "
                f"'auto' (model dtype) or 'int8'"
            )
        if engine_cfg.weight_dtype not in ("auto", "int8", "int4"):
            raise ValueError(
                f"weight_dtype={engine_cfg.weight_dtype!r}: expected "
                f"'auto' (model dtype), 'int8', or 'int4'"
            )
        self.kv_quantized = engine_cfg.kv_cache_dtype == "int8"
        self.R = engine_cfg.max_running_requests
        self.block_size = engine_cfg.block_size
        self.num_blocks = self._decide_num_blocks()
        self.max_blocks_per_seq = math.ceil(
            engine_cfg.max_seq_len / self.block_size
        )

        p_shardings = param_shardings(
            self.cfg, self.mesh, ep_axis="ep" if ep > 1 else None
        )
        # MLA's latent cache has no KV-head axis to shard — it is shared by
        # all heads and replicated across tp (each device's head shard
        # reads the whole latent context; ~3.5x smaller than sharded GQA
        # K/V anyway).
        kv_sharding = (
            NamedSharding(self.mesh, P())
            if self.cfg.is_mla
            else kv_cache_sharding(self.mesh)
        )

        with self.mesh:
            if engine_cfg.checkpoint_path:
                from xllm_service_tpu.runtime.weights import load_checkpoint

                self.params = load_checkpoint(
                    engine_cfg.checkpoint_path, self.cfg, self.dtype, p_shardings
                )
            else:
                init_fn = jax.jit(
                    lambda key: self.model_mod.init_params(
                        self.cfg, key, self.dtype
                    ),
                    out_shardings=p_shardings,
                )
                self.params = init_fn(jax.random.key(init_seed))
            if engine_cfg.weight_dtype in ("int8", "int4"):
                self._quantize_weights(
                    p_shardings,
                    bits=4 if engine_cfg.weight_dtype == "int4" else 8,
                )

            # [L, N, Hkv, BS, D]: KV-head-major within a block so the Pallas
            # decode kernel can DMA one (block, head) tile of shape [BS, D]
            # with TPU-legal last-two-dims tiling. MLA families cache one
            # latent row per token instead: [L, N, 1, BS, C].
            cache_heads, cache_dim = models.cache_row_dims(self.cfg)
            cache_shape = (
                self.cfg.num_layers,
                self.num_blocks,
                cache_heads,
                self.block_size,
                cache_dim,
            )
            scale_sharding = (
                NamedSharding(self.mesh, P())
                if self.cfg.is_mla
                else kv_scale_sharding(self.mesh)
            )
            cache_sharding = kvc.PagedKV(
                kv_sharding,
                scale_sharding if self.kv_quantized else None,
            )
            if self.num_caches == 2:
                alloc = jax.jit(
                    lambda: (
                        kvc.alloc_cache(
                            cache_shape, self.dtype, self.kv_quantized
                        ),
                        kvc.alloc_cache(
                            cache_shape, self.dtype, self.kv_quantized
                        ),
                    ),
                    out_shardings=(cache_sharding, cache_sharding),
                )
                self.k_cache, self.v_cache = alloc()
            else:
                # Latent cache rides the k slot; v is a 1-element dummy
                # threaded through the step scans untouched. Int8 uses
                # sub-channel scales with the group boundary on
                # kv_lora_rank, so the latent and RoPE segments of each
                # concat(c_kv, k_pe) row quantize independently.
                groups = 1
                if self.kv_quantized:
                    groups = kvc.mla_scale_groups(
                        self.cfg.kv_lora_rank,
                        self.cfg.qk_rope_head_dim,
                        self.cfg.mla_cache_dim,
                    )
                alloc = jax.jit(
                    lambda: kvc.alloc_cache(
                        cache_shape, self.dtype, self.kv_quantized, groups
                    ),
                    out_shardings=cache_sharding,
                )
                self.k_cache = alloc()
                self.v_cache = kvc.PagedKV(
                    jnp.zeros(
                        (self.cfg.num_layers, 1, 1, 1, 1), self.dtype
                    ),
                    None,
                )

        # Generated-token histogram per slot (presence/frequency penalties).
        # int32 [R, V] — 32 MB at V=128K, R=64; donated through every step.
        with self.mesh:
            self.token_counts = jax.jit(
                lambda: jnp.zeros((self.R, self.cfg.vocab_size), jnp.int32)
            )()
        self._decode_jit = jax.jit(
            self._decode_impl, donate_argnums=(0, 1, 2),
            static_argnames=("use_kernel",)
        )
        self._prefill_jit = jax.jit(
            self._prefill_impl, donate_argnums=(0, 1)
        )
        def _import_impl(k, v, blocks, ids):
            # blocks [2, L, P, Hkv, BS, D] in model dtype (migration payloads
            # stay bf16 on the wire/host tiers; int8 caches requantize here).
            k = kvc.set_blocks(k, ids, blocks[0])
            if self.num_caches == 2:
                v = kvc.set_blocks(v, ids, blocks[1])
            return k, v

        self._import_jit = jax.jit(_import_impl, donate_argnums=(0, 1))
        self.prefill_buckets = sorted(
            b for b in engine_cfg.prefill_buckets if b <= engine_cfg.max_seq_len
        )
        # Buckets must cover max_seq_len so any admissible suffix fits.
        if not self.prefill_buckets or self.prefill_buckets[-1] < engine_cfg.max_seq_len:
            self.prefill_buckets.append(engine_cfg.max_seq_len)

        # Grouped-MoE dispatch stats (docs/MOE.md, docs/OBSERVABILITY.md):
        # each grouped dispatch in a jitted step emits its per-layer
        # (assignment counts, overflow drops, capacity rows) through an
        # async jax.debug.callback into _moe_sink — the host accumulators
        # below feed the engine's obs pull gauges and the master-visible
        # expert-hotness load signal without ever blocking the device or
        # the overlap pipeline.
        self._moe_mu = _threading.Lock()
        self._moe_counts = np.zeros(
            (max(self.cfg.num_experts, 1),), np.int64
        )  # guarded by: self._moe_mu
        self._moe_dropped = 0  # guarded by: self._moe_mu
        self._moe_capacity_rows = 0  # guarded by: self._moe_mu

    # ------------------------------------------------------- multi-LoRA

    def set_lora_adapters(self, adapters) -> Dict[str, int]:
        """Install per-request LoRA adapters over the base weights.

        `adapters`: {name: {proj: (A [L, E_in, r], B [L, r, out])}} with
        proj in the family's QUANTIZABLE_WEIGHT_LEAVES names (wq, wk, wv,
        wo, w_gate, w_up, w_down); scaling (alpha/r) must already be
        folded into B (runtime/weights.load_lora_checkpoint does). The
        stacks install into params["layers"] as lora_<proj>_{a,b} leaves
        [L, n_a+1, ...] with the all-zero BASE row at index 0, so the
        existing scan/jit plumbing carries them and requests with
        adapter_idx 0 get exact base outputs. Returns {name: row}."""
        if self.cfg.is_mla:
            raise ValueError(
                "LoRA serving is supported for the llama family only"
            )
        if not adapters:
            return {}
        names = list(adapters)
        projs = sorted({p for a in adapters.values() for p in a})
        if self.cfg.is_moe and any(
            p in ("w_gate", "w_up", "w_down") for p in projs
        ):
            raise ValueError(
                "LoRA on MoE expert MLPs is not supported (attention "
                "projections only for MoE models)"
            )
        L = self.cfg.num_layers
        with self.mesh:
            rep = NamedSharding(self.mesh, P())
            for proj in projs:
                shapes = [
                    adapters[n][proj] for n in names if proj in adapters[n]
                ]
                r = max(a.shape[-1] for a, _ in shapes)
                e_in = shapes[0][0].shape[1]
                out = shapes[0][1].shape[2]
                A = np.zeros((L, len(names) + 1, e_in, r), np.float32)
                B = np.zeros((L, len(names) + 1, r, out), np.float32)
                for i, n in enumerate(names):
                    if proj not in adapters[n]:
                        continue
                    a_n, b_n = adapters[n][proj]
                    A[:, i + 1, :, : a_n.shape[-1]] = a_n
                    B[:, i + 1, : b_n.shape[1], :] = b_n
                self.params["layers"][f"lora_{proj}_a"] = jax.device_put(
                    jnp.asarray(A, self.dtype), rep
                )
                self.params["layers"][f"lora_{proj}_b"] = jax.device_put(
                    jnp.asarray(B, self.dtype), rep
                )
        self.lora_names = {n: i + 1 for i, n in enumerate(names)}
        return self.lora_names

    # -------------------------------------------------- guided decoding

    def set_guided_table(
        self, table: np.ndarray, dynamic_rows: int = 256
    ) -> None:
        """Install the guided-decoding token-mask table [M, V] bool (one
        row per abstract automaton state). A permissive all-True row is
        appended at index M — unguided slots point there, so one compiled
        step serves mixed guided/unguided batches. `dynamic_rows` extra
        rows follow for per-request schema masks (json_schema mode):
        written lazily via update_guided_row as the schema automaton
        visits states, all-False until then (the engine never points a
        slot at an unwritten row)."""
        M, V = table.shape
        full = np.ones((M + 1 + dynamic_rows, V), dtype=bool)
        full[:M] = table
        full[M + 1:] = False
        self._guided_table = jnp.asarray(full)
        self._pending_guided_rows.clear()
        self.permissive_row = M
        self.dynamic_row_base = M + 1
        self.num_dynamic_rows = dynamic_rows

    def update_guided_row(self, row: int, bits: np.ndarray) -> None:
        """Stage one dynamic mask-row write. Writes are BUFFERED and
        applied as a single batched .at[rows].set the next time the table
        is consumed (guided_table property) — a per-row functional update
        would copy the whole [M+1+D, V] device array once per newly
        visited schema state (review finding, r4)."""
        self._pending_guided_rows.append((row, np.asarray(bits, dtype=bool)))

    @property
    def _pending_guided_rows(self) -> list:
        if not hasattr(self, "_pending_rows_buf"):
            self._pending_rows_buf = []
        return self._pending_rows_buf

    def _flushed_guided_table(self):
        pend = self._pending_guided_rows
        if pend:
            rows = jnp.asarray([r for r, _ in pend], jnp.int32)
            bits = jnp.asarray(np.stack([b for _, b in pend]))
            self._guided_table = self._guided_table.at[rows].set(bits)
            pend.clear()
        return self._guided_table

    @property
    def guided_table(self):
        if getattr(self, "_guided_table", None) is None:
            return None
        return self._flushed_guided_table()

    # ----------------------------------------------------------- sizing

    def _quantize_weights(self, p_shardings, bits: int = 8) -> None:
        """In-place W8/W4 pass over the stacked matmul leaves
        (ops/quant.py): each eligible leaf becomes {"q": int8|int4, "s":
        scales}, sharded like the original. W8 scales drop the contracted
        -2 axis from the spec; W4 group scales keep the leaf's rank (the
        group axis aligns with the contracting axis), so they reuse the
        weight's own sharding. Leaf-by-leaf with donation so peak HBM
        never holds two full copies."""
        from jax.sharding import NamedSharding, PartitionSpec
        from xllm_service_tpu.ops import quant

        names = getattr(self.model_mod, "QUANTIZABLE_WEIGHT_LEAVES", ())
        if not names:
            raise ValueError(
                f"weight_dtype=int{bits}: model family "
                f"{self.model_mod.__name__} has no quantizable-leaf map"
            )
        for stack in ("layers", "dense_layers"):
            if stack not in self.params:
                continue
            for name in names:
                leaf = self.params[stack].get(name)
                if leaf is None:
                    continue
                sh = p_shardings[stack][name]
                spec = list(sh.spec) + [None] * (
                    leaf.ndim - len(sh.spec)
                )
                group = 128
                if bits == 4:
                    # W4 group scales keep the leaf's rank, so they reuse
                    # the weight's own sharding — but a tp-sharded
                    # contracting axis must split into whole scale groups
                    # on every shard: use the largest divisor <= 128 of
                    # the per-shard dim (never one giant group, which
                    # would silently coarsen quantization).
                    s_sh = sh
                    tp_ax = spec[-2]
                    shards = (
                        self.mesh.shape.get(tp_ax, 1) if tp_ax else 1
                    )
                    per_shard = leaf.shape[-2] // shards
                    group = min(per_shard, 128)
                    while per_shard % group:
                        group -= 1
                else:
                    s_sh = NamedSharding(
                        sh.mesh, PartitionSpec(*(spec[:-2] + spec[-1:]))
                    )
                qfn = jax.jit(
                    lambda w, g=group: quant.quantize_weight(
                        w, self.dtype, bits=bits, group=g
                    ),
                    out_shardings={"q": sh, "s": s_sh},
                    donate_argnums=(0,),
                )
                self.params[stack][name] = qfn(leaf)

    def _decide_num_blocks(self) -> int:
        if self.engine_cfg.num_blocks > 0:
            return self.engine_cfg.num_blocks
        # Size the KV pool from free HBM after params (bench/real use).
        cfg = self.cfg
        dtype_bytes = 2 if self.engine_cfg.dtype == "bfloat16" else 4
        # Param residency and KV element size are SEPARATE quantities:
        # int8 weights shrink only the former (matmul leaves become
        # 1 byte + per-out-channel scales; embed/lm_head/norms stay full
        # precision — ~1.15 bytes/param blended), while the KV element
        # size tracks kv_cache_dtype below.
        param_bytes = {
            "int8": 1.15,
            # int4 packs two weights per byte; scales (1/group) + the
            # unquantized embed/lm_head/norm share blend to ~0.65.
            "int4": 0.65,
        }.get(self.engine_cfg.weight_dtype, dtype_bytes)
        n_params = approx_param_count(cfg)
        try:
            stats = jax.devices()[0].memory_stats() or {}
            total_hbm = stats.get("bytes_limit", 16 * 2**30)
        except Exception:
            total_hbm = 16 * 2**30
        tp = self.mesh.shape.get("tp", 1)
        # XLA's AOT peak-memory estimate counts donated KV caches on both
        # sides of the step, so budget for 2x the pool (params are not
        # donated and count once).
        budget = (
            total_hbm * self.engine_cfg.hbm_utilization
            - n_params * param_bytes / tp
        ) / 2
        cache_heads, cache_dim = models.cache_row_dims(self.cfg)
        # int8 cache: 1 byte/element + 4-byte f32 scale per sub-channel
        # group (G=8 for GQA rows, mla_scale_groups for MLA — must match
        # the alloc path's grouping or the pool over/undersizes).
        scale_groups = kvc.GQA_SCALE_GROUPS
        if self.kv_quantized and self.cfg.is_mla:
            scale_groups = kvc.mla_scale_groups(
                self.cfg.kv_lora_rank,
                self.cfg.qk_rope_head_dim,
                self.cfg.mla_cache_dim,
            )
        kv_elem_bytes = (
            1 + 4.0 * scale_groups / cache_dim
            if self.kv_quantized
            else dtype_bytes
        )
        # MLA's latent cache is replicated (no KV-head axis to shard);
        # for GQA, check_tp_divisibility guarantees tp divides
        # num_kv_heads and resolve_kv_packing has already unpacked the
        # layout if tp didn't divide the packed count — so cache_heads
        # (post-resolve cache_row_dims) is always tp-divisible here.
        heads_per_dev = (
            cache_heads if self.cfg.is_mla else cache_heads // tp
        )
        block_bytes = (
            models.num_caches(self.cfg)
            * self.cfg.num_layers
            * self.block_size
            * heads_per_dev
            * cache_dim
            * kv_elem_bytes
        )
        n = int(budget // block_bytes)
        if n < 16:
            import warnings

            warnings.warn(
                f"KV pool auto-sizing collapsed to the 16-block floor "
                f"(budget {budget/2**30:.2f} GiB, block {block_bytes/2**20:.2f} "
                f"MiB): params leave almost no HBM headroom; expect thrashing",
                stacklevel=2,
            )
        return max(n, 16)

    # ------------------------------------------------------------ step fns

    def _decode_impl(
        self,
        k_cache,
        v_cache,
        counts,  # [R, V] int32 generated-token histogram (donated)
        params,
        fresh_tokens,  # [R] host-fed input ids (admissions / sync mode)
        fresh_mask,  # [R] bool — True: input from fresh_tokens
        prev_tokens,  # [R] DEVICE-resident sampled tokens from the prior
        #              step (overlapped pipeline feeds them back without a
        #              host round-trip); sync callers pass fresh_tokens
        positions,
        block_tables,
        active,
        temperature,
        top_k,
        top_p,
        step_keys,
        presence,
        frequency,
        bias_ids=None,
        bias_vals=None,
        mask_rows=None,  # [R] rows into guided_table
        guided_table=None,  # [M+1, V] bool
        lora_idx=None,  # [R] adapter rows (0 = base)
        min_p=None,  # [R]
        use_kernel=None,
        rope_delta=None,  # [R] M-RoPE position lag (Qwen2-VL image spans)
    ):
        token_ids = jnp.where(fresh_mask, fresh_tokens, prev_tokens)
        step_kwargs = (
            {"lora_idx": lora_idx} if lora_idx is not None else {}
        )
        if rope_delta is not None:
            step_kwargs["rope_delta"] = rope_delta
        logits, k_cache, v_cache = self.model_mod.decode_step(
            params,
            self.cfg,
            k_cache,
            v_cache,
            token_ids,
            positions,
            block_tables,
            active,
            use_kernel=use_kernel,
            **step_kwargs,
        )
        tokens, logprob, _ = sampling_ops.sample_tokens(
            logits, temperature, top_k, top_p, step_keys,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
            min_p=min_p,
        )
        counts = counts.at[
            jnp.arange(tokens.shape[0]), tokens
        ].add(active.astype(jnp.int32))
        return k_cache, v_cache, counts, tokens, logprob

    def _prefill_impl(
        self,
        k_cache,
        v_cache,
        params,
        token_ids,  # [P, Lpad]
        start_pos,  # [P]
        true_len,  # [P]
        block_tables,  # [P, CB] — sliced to the group's context bound
        temperature,  # [P]
        top_k,  # [P]
        top_p,  # [P]
        step_keys,  # [P]
        mm_embeds=None,  # [P, M, E] or None
        mm_positions=None,  # [P, M] chunk-relative (pad = Lpad)
        counts=None,  # [P, V] prior-token histogram (penalized items only)
        presence=None,  # [P]
        frequency=None,  # [P]
        bias_ids=None,  # [P, K]
        bias_vals=None,  # [P, K]
        mask_rows=None,  # [P] rows into guided_table
        guided_table=None,
        lora_idx=None,  # [P] adapter rows (0 = base)
        min_p=None,  # [P]
        rope_positions=None,  # [P, 3, Lpad] M-RoPE streams (image spans)
    ):
        step_kwargs = (
            {"lora_idx": lora_idx} if lora_idx is not None else {}
        )
        if rope_positions is not None:
            step_kwargs["rope_positions"] = rope_positions
        logits, k_cache, v_cache = self.model_mod.prefill_batch_step(
            params, self.cfg, k_cache, v_cache, token_ids, start_pos,
            true_len, block_tables,
            embed_overrides=mm_embeds, override_positions=mm_positions,
            **step_kwargs,
        )
        # Penalties at (re)admission: when any item in the group carries
        # presence/frequency penalties, the caller passes its prior-token
        # histogram so the token sampled HERE is penalized exactly like
        # every decode-step token (ADVICE r2). Penalty-free groups (the
        # common case) skip the [P, V] transfer entirely.
        tokens, logprob, _ = sampling_ops.sample_tokens(
            logits, temperature, top_k, top_p, step_keys,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
            min_p=min_p,
        )
        return k_cache, v_cache, tokens, logprob

    def _verify_impl(
        self,
        k_cache,
        v_cache,
        counts,  # [R, V] int32 (donated)
        params,
        token_ids,  # [R, S] — last accepted token then S-1 draft tokens
        start_pos,  # [R] — position of the first fed token
        true_len,  # [R] — fed tokens this row may write/emit (0 = inactive)
        block_tables,  # [R, CB]
        temperature,
        top_k,
        top_p,
        step_keys,  # [R, S, 2]
        active,  # [R] bool
        presence,
        frequency,
        bias_ids=None,
        bias_vals=None,
        mask_rows=None,  # [R, S] rows into guided_table
        guided_table=None,
        lora_idx=None,  # [R] adapter rows (0 = base)
        min_p=None,  # [R]
        rope_delta=None,  # [R] M-RoPE position lag (<= 0)
    ):
        """Speculative-decoding verify step: one forward pass over S
        positions per sequence (the prefill machinery with `all_logits`),
        then point-mass speculative acceptance (ops/sampling.py). KV rows
        for ALL S positions are written; rows past the accepted prefix are
        stale garbage that attention can never read (masked by seq_lens)
        and the next step overwrites."""
        step_kwargs = (
            {"lora_idx": lora_idx} if lora_idx is not None else {}
        )
        if rope_delta is not None:
            # generation positions have equal (t, h, w) streams; only the
            # lag vs cache positions matters
            S_ = token_ids.shape[1]
            base = (start_pos + rope_delta)[:, None] + jnp.arange(
                S_, dtype=jnp.int32
            )[None]
            step_kwargs["rope_positions"] = jnp.broadcast_to(
                base[:, None, :], (base.shape[0], 3, S_)
            )
        logits, k_cache, v_cache = self.model_mod.prefill_batch_step(
            params, self.cfg, k_cache, v_cache, token_ids, start_pos,
            true_len, block_tables, all_logits=True, **step_kwargs,
        )  # [R, S, V]
        drafts = token_ids[:, 1:]
        tokens, logprobs, n_emit, counts = sampling_ops.speculative_sample(
            logits, drafts, temperature, top_k, top_p, step_keys,
            limits=true_len, active=active,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
            min_p=min_p,
        )
        return k_cache, v_cache, counts, tokens, logprobs, n_emit

    # ---------------------------------------------------------- public API

    def verify(
        self,
        token_ids: np.ndarray,  # [R, S]
        positions: np.ndarray,  # [R] — position of the first fed token
        true_len: np.ndarray,  # [R] — <= S; 0 for inactive rows
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Speculative decode step. Returns (tokens [R, S], logprobs [R, S],
        n_emit [R]): each active row emits its first n_emit tokens (>= 1 —
        a verify step subsumes a plain decode step)."""
        self._set_shard_ctx()
        if not hasattr(self, "_verify_jit"):
            self._verify_jit = jax.jit(
                self._verify_impl, donate_argnums=(0, 1, 2)
            )
        S = token_ids.shape[1]
        # Per-position keys on the sequential schedule: position j uses
        # step base+j, so the emitted stream is bit-identical to the
        # non-speculative path under the same seeds.
        seeds = jnp.asarray(batch.seeds, jnp.uint32)
        keys = jnp.stack(
            [
                sampling_ops.make_step_keys(
                    seeds, jnp.asarray(batch.steps, jnp.int32) + j
                )
                for j in range(S)
            ],
            axis=1,
        )  # [R, S, 2]
        need = 1
        if active.any():
            last_pos = np.asarray(positions) + np.asarray(true_len) - 1
            need = int(
                (last_pos[np.asarray(active)].max() // self.block_size) + 1
            )
        CB = self._pow2_bucket(need, self.max_blocks_per_seq)
        R = self.R
        zeros = np.zeros((R,), np.float32)
        presence = batch.presence if batch.presence is not None else zeros
        frequency = batch.frequency if batch.frequency is not None else zeros
        bias_kwargs = {}
        if batch.bias_ids is not None:
            bias_kwargs = dict(
                bias_ids=jnp.asarray(batch.bias_ids, jnp.int32),
                bias_vals=jnp.asarray(batch.bias_vals, jnp.float32),
            )
        if batch.mask_rows is not None:
            bias_kwargs.update(
                mask_rows=jnp.asarray(batch.mask_rows, jnp.int32),
                guided_table=self._flushed_guided_table(),
            )
        if batch.adapter_idx is not None:
            bias_kwargs.update(
                lora_idx=jnp.asarray(batch.adapter_idx, jnp.int32)
            )
        if batch.min_p is not None:
            bias_kwargs.update(min_p=jnp.asarray(batch.min_p, jnp.float32))
        if batch.rope_delta is not None:
            bias_kwargs.update(
                rope_delta=jnp.asarray(batch.rope_delta, jnp.int32)
            )
        (
            self.k_cache, self.v_cache, self.token_counts,
            tokens, logprobs, n_emit,
        ) = self._verify_jit(
            self.k_cache,
            self.v_cache,
            self.token_counts,
            self.params,
            jnp.asarray(token_ids, jnp.int32),
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(true_len, jnp.int32),
            jnp.asarray(block_tables[:, :CB], jnp.int32),
            jnp.asarray(batch.temperature, jnp.float32),
            jnp.asarray(batch.top_k, jnp.int32),
            jnp.asarray(batch.top_p, jnp.float32),
            keys,
            jnp.asarray(active),
            jnp.asarray(presence, jnp.float32),
            jnp.asarray(frequency, jnp.float32),
            **bias_kwargs,
        )
        return np.asarray(tokens), np.asarray(logprobs), np.asarray(n_emit)

    def bucket_len(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        return self.prefill_buckets[-1]

    # Prefill group-size buckets: bounded compile count, P=8 amortizes the
    # per-step overhead for bursts of short concurrent prompts.
    PREFILL_GROUP_MAX = 8

    @staticmethod
    def _pow2_bucket(n: int, cap: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return min(b, cap)

    def prefill_groups(
        self, items: List["PrefillItem"]
    ) -> List[List[int]]:
        """Partition item indices into the compiled-dispatch groups
        prefill_batch launches: sorted by padded-length bucket (so a
        short prompt never pads to a long one's bucket), at most
        PREFILL_GROUP_MAX same-bucket items per group. One group = one
        jitted call — the engine's kernel-dispatch counter shares this
        walk so it counts DEVICE dispatches."""
        order = sorted(
            range(len(items)),
            key=lambda i: self.bucket_len(len(items[i].token_ids)),
        )
        groups: List[List[int]] = []
        i = 0
        while i < len(order):
            bucket = self.bucket_len(len(items[order[i]].token_ids))
            group_idx: List[int] = []
            while (
                i < len(order)
                and len(group_idx) < self.PREFILL_GROUP_MAX
                and self.bucket_len(len(items[order[i]].token_ids)) == bucket
            ):
                group_idx.append(order[i])
                i += 1
            groups.append(group_idx)
        return groups

    def prefill_batch(self, items: List["PrefillItem"]) -> List[Tuple[int, float]]:
        """Prefill several sequences' chunks in as few compiled steps as
        possible. Items are grouped by padded-length bucket (so a short
        prompt never pads to a long one's bucket) into chunks of
        <= PREFILL_GROUP_MAX with bucketed (P, Lpad, CB) shapes; each chunk
        is ONE jitted call (batched admission — round-1 weak item 4).
        Returns per-item (first_token, logprob) in input order."""
        results: List[Optional[Tuple[int, float]]] = [None] * len(items)
        for group_idx in self.prefill_groups(items):
            outs = self._prefill_group([items[g] for g in group_idx])
            for g, o in zip(group_idx, outs):
                results[g] = o
        return results  # type: ignore[return-value]

    def _prefill_group(self, group: List["PrefillItem"]) -> List[Tuple[int, float]]:
        self._set_shard_ctx()
        n_real = len(group)
        P = self._pow2_bucket(n_real, self.PREFILL_GROUP_MAX)
        Lpad = self.bucket_len(max(len(it.token_ids) for it in group))
        bs = self.block_size
        need_blocks = max(
            (it.start_pos + len(it.token_ids) + bs - 1) // bs for it in group
        )
        CB = self._pow2_bucket(max(need_blocks, 1), self.max_blocks_per_seq)

        token_ids = np.zeros((P, Lpad), np.int32)
        start_pos = np.zeros((P,), np.int32)
        true_len = np.zeros((P,), np.int32)
        tables = np.zeros((P, CB), np.int32)
        temps = np.zeros((P,), np.float32)
        top_ks = np.zeros((P,), np.int32)
        top_ps = np.ones((P,), np.float32)
        seeds = np.zeros((P,), np.uint32)
        steps = np.zeros((P,), np.int32)
        for i, it in enumerate(group):
            n = len(it.token_ids)
            token_ids[i, :n] = it.token_ids
            start_pos[i] = it.start_pos
            true_len[i] = n
            m = min(CB, len(it.block_table))
            tables[i, :m] = np.asarray(it.block_table[:m], np.int32)
            temps[i] = it.temperature
            top_ks[i] = it.top_k
            top_ps[i] = it.top_p
            seeds[i] = it.seed & 0xFFFFFFFF
            steps[i] = it.step
        keys = sampling_ops.make_step_keys(
            jnp.asarray(seeds), jnp.asarray(steps, jnp.int32)
        )
        # Media-token injection: bucket the per-seq override count to a
        # power of two; padded entries point at Lpad (the model's discard
        # row). Positions are chunk-relative; overrides outside this chunk
        # (already prefix-cached) are dropped.
        mm_counts = []
        for it in group:
            cnt = 0
            if it.mm_embeds is not None and it.mm_positions is not None:
                rel = np.asarray(it.mm_positions, np.int64) - it.start_pos
                cnt = int(((rel >= 0) & (rel < len(it.token_ids))).sum())
            mm_counts.append(cnt)
        M = self._pow2_bucket(max(mm_counts), 2**14) if any(mm_counts) else 0
        mm_args = ()
        if M:
            E = self.cfg.hidden_size
            embeds = np.zeros((P, M, E), np.float32)
            positions = np.full((P, M), Lpad, np.int32)  # default: discard
            for i, it in enumerate(group):
                if not mm_counts[i]:
                    continue
                rel = np.asarray(it.mm_positions, np.int64) - it.start_pos
                keep = (rel >= 0) & (rel < len(it.token_ids))
                positions[i, : mm_counts[i]] = rel[keep]
                embeds[i, : mm_counts[i]] = np.asarray(it.mm_embeds)[keep]
            mm_args = (jnp.asarray(embeds), jnp.asarray(positions))
        # Penalized (re)admissions: ship each item's prior-token histogram
        # so the prefill-sampled token sees the same penalties a decode
        # step would. Gated on PRIOR TOKENS actually existing — a fresh
        # penalized prompt has an all-zero histogram (exact no-op), and
        # shipping it would cost a [P, V] transfer + an unwarmed compile
        # per shape.
        pen_kwargs = {}
        b_ids, b_vals = sampling_ops.pack_logit_bias(
            [it.logit_bias for it in group], P
        )
        if b_ids is not None:
            pen_kwargs.update(
                bias_ids=jnp.asarray(b_ids), bias_vals=jnp.asarray(b_vals)
            )
        if any(it.mask_row >= 0 for it in group):
            rows = np.full((P,), self.permissive_row, np.int32)
            for i, it in enumerate(group):
                if it.mask_row >= 0:
                    rows[i] = it.mask_row
            pen_kwargs.update(
                mask_rows=jnp.asarray(rows),
                guided_table=self._flushed_guided_table(),
            )
        if any(it.adapter_idx for it in group):
            pen_kwargs.update(
                lora_idx=jnp.asarray(
                    [it.adapter_idx for it in group]
                    + [0] * (P - n_real),
                    jnp.int32,
                )
            )
        if any(it.min_p for it in group):
            pen_kwargs.update(
                min_p=jnp.asarray(
                    [it.min_p for it in group] + [0.0] * (P - n_real),
                    jnp.float32,
                )
            )
        if any(it.rope_positions is not None for it in group):
            # M-RoPE streams; items without them get the standard
            # sequential positions (equal streams == standard RoPE).
            rp = np.zeros((P, 3, Lpad), np.int32)
            for i in range(P):
                it = group[i] if i < n_real else None
                if it is not None and it.rope_positions is not None:
                    n = len(it.token_ids)
                    rp[i, :, :n] = np.asarray(it.rope_positions, np.int32)
                elif it is not None:
                    seq = it.start_pos + np.arange(Lpad, dtype=np.int32)
                    rp[i] = seq[None, :]
            pen_kwargs.update(rope_positions=jnp.asarray(rp))
        if any(
            it.prior_tokens is not None and len(it.prior_tokens)
            for it in group
        ):
            cnts = np.zeros((P, self.cfg.vocab_size), np.int32)
            pres = np.zeros((P,), np.float32)
            freq = np.zeros((P,), np.float32)
            for i, it in enumerate(group):
                pres[i] = it.presence
                freq[i] = it.frequency
                if it.prior_tokens is not None and len(it.prior_tokens):
                    np.add.at(
                        cnts[i], np.asarray(it.prior_tokens, np.int64), 1
                    )
            pen_kwargs.update(
                counts=jnp.asarray(cnts),
                presence=jnp.asarray(pres),
                frequency=jnp.asarray(freq),
            )
        self.k_cache, self.v_cache, toks, lps = self._prefill_jit(
            self.k_cache,
            self.v_cache,
            self.params,
            jnp.asarray(token_ids),
            jnp.asarray(start_pos),
            jnp.asarray(true_len),
            jnp.asarray(tables),
            jnp.asarray(temps),
            jnp.asarray(top_ks),
            jnp.asarray(top_ps),
            keys,
            *mm_args,
            **pen_kwargs,
        )
        toks = np.asarray(toks)
        lps = np.asarray(lps)
        return [(int(toks[i]), float(lps[i])) for i in range(n_real)]

    def warmup(self) -> List[Tuple[int, int]]:
        """Compile the common serving shapes against the garbage block, so
        the first real request's TTFT carries no compile (SURVEY §7 hard
        part 3 — shape-bucketed continuous batching without recompiles).

        Prefill shapes are (P, Lpad, CB); this warms EVERY reachable
        (Lpad, CB) pair at P=1 — CB is decoupled from Lpad because a
        prefix-cache hit raises start_pos, so a short suffix can carry any
        context width up to max_blocks_per_seq. Group shapes P>1 are left
        to first contact (at most log2(PREFILL_GROUP_MAX) extra compiles
        per bucket over the process lifetime, hit only under concurrent
        admission bursts). Returns the (Lpad, CB) pairs warmed."""
        warmed: List[Tuple[int, int]] = []
        for b, CB, n, sp in self._prefill_shape_family():
            table = np.zeros((self.max_blocks_per_seq,), np.int32)
            self.prefill_batch(
                [
                    PrefillItem(
                        token_ids=np.zeros((n,), np.int32),
                        start_pos=sp,
                        block_table=table,
                    )
                ]
            )
            warmed.append((b, CB))

        R = self.R
        active = np.zeros((R,), bool)
        active[0] = True
        batch = SamplingBatch(
            temperature=np.zeros(R, np.float32),
            top_k=np.zeros(R, np.int32),
            top_p=np.ones(R, np.float32),
            seeds=np.zeros(R, np.uint32),
            steps=np.zeros(R, np.int32),
        )
        # Every pow2 context-width bucket decode can hit (decode() slices
        # the table to the batch's true block bound, one compile per
        # bucket) — positions drive the bucket; writes land in block 0.
        for CB in self._decode_cb_walk():
            positions = np.zeros((R,), np.int32)
            positions[0] = CB * self.block_size - 1
            self.decode(
                np.zeros((R,), np.int32),
                positions,
                np.zeros((R, self.max_blocks_per_seq), np.int32),
                active,
                batch,
            )

        # Speculative verify shapes ([R, S] over the same pow2 CB buckets)
        # when the engine runs speculative decoding.
        spec = self.engine_cfg.speculative_tokens
        if spec > 0:
            S = spec + 1
            for CB in self._decode_cb_walk():
                positions = np.zeros((R,), np.int32)
                positions[0] = max(CB * self.block_size - S, 0)
                true_len = np.zeros((R,), np.int32)
                true_len[0] = S
                self.verify(
                    np.zeros((R, S), np.int32),
                    positions,
                    true_len,
                    np.zeros((R, self.max_blocks_per_seq), np.int32),
                    active,
                    batch,
                )
        return warmed

    # ------------------------------------- bucket-program family prewarm

    def _prefill_shape_family(self):
        """(bucket, CB, n, sp) for every reachable prefill (Lpad, CB)
        pair at P=1 — THE shape walk warmup() compiles and the mixed /
        mixed-verify prewarms reuse for their prefill halves."""
        bs = self.block_size
        max_len = self.engine_cfg.max_seq_len
        for bi, b in enumerate(self.prefill_buckets):
            n_full = min(b, max_len - 1)
            # Shortest suffix still padding to THIS bucket (for large-CB
            # prefix-hit shapes where the full-bucket suffix wouldn't fit,
            # and for the small-CB shapes short in-bucket prompts hit).
            n_min = (self.prefill_buckets[bi - 1] + 1) if bi else 1
            # CB floor matches _prefill_group's need_blocks for the
            # SHORTEST prompt in this bucket (ceil(n/bs), no +1 — the
            # next-token block is allocated by the engine, not attended).
            CB = self._pow2_bucket(
                max(1, (n_min + bs - 1) // bs), self.max_blocks_per_seq
            )
            while True:
                if CB * bs <= n_full:
                    # Natural shape: a prompt of exactly CB blocks, no
                    # prefix hit (n_min <= CB*bs <= n_full keeps the
                    # length in this bucket).
                    n, sp = CB * bs, 0
                else:
                    # Prefix-hit shape: block-aligned start_pos so
                    # need_blocks lands exactly on this CB bucket.
                    n = n_full
                    sp = (CB - (n + bs - 1) // bs) * bs
                    if sp + n >= max_len:
                        n = n_min
                        sp = (CB - (n + bs - 1) // bs) * bs
                if sp + n < max_len:
                    yield (b, CB, n, sp)
                if CB >= self.max_blocks_per_seq:
                    break
                CB = min(CB * 2, self.max_blocks_per_seq)

    def _decode_cb_walk(self):
        """Every pow2 context-width bucket a decode/verify dispatch can
        land in (1, 2, 4, ... max_blocks_per_seq)."""
        CB = 1
        while True:
            yield CB
            if CB >= self.max_blocks_per_seq:
                break
            CB = min(CB * 2, self.max_blocks_per_seq)

    # Every jit entry point the serving loop can dispatch through —
    # lowering_count() sums their dispatch-cache sizes.
    _JIT_ATTRS = (
        "_decode_jit", "_prefill_jit", "_import_jit", "_verify_jit",
        "_sp_jit", "_mixed_jit", "_verify_pipe_jit", "_mixed_verify_jit",
        "_seed_counts_jit", "_embed_jit",
    )

    def lowering_count(self) -> int:
        """Total compiled-program entries across the executor's jit
        dispatch caches — a monotone count of fresh lowerings. The
        engine diffs it per dispatch for the compile-cache hit/miss
        instruments, and the prewarm differential test asserts it stays
        FLAT across a full workload after prewarm_programs()."""
        total = 0
        for name in self._JIT_ATTRS:
            fn = getattr(self, name, None)
            size = getattr(fn, "_cache_size", None)
            if size is not None:
                try:
                    total += int(size())
                except Exception:  # pragma: no cover - jax internals
                    pass
        return total

    @property
    def overlap_collectives_active(self) -> bool:
        """Whether the jitted steps traced with the ring collective-
        matmul schedule in the hot loop (XLLM_OVERLAP_COLLECTIVES on a
        tp>1 or ep>1 mesh — ops/collective_matmul.py)."""
        from xllm_service_tpu.ops import collective_matmul as cm_ops

        if not cm_ops.overlap_collectives_enabled():
            return False
        return (
            self.mesh.shape.get("tp", 1) > 1
            or self.mesh.shape.get("ep", 1) > 1
        )

    def _mixed_step_resolved(self) -> bool:
        """The engine's mixed-step decision, replicated (XLLM_MIXED_STEP
        over EngineConfig.enable_mixed_step, gated on family support) —
        the prewarm must enumerate the builders the ENGINE will run."""
        env = os.environ.get("XLLM_MIXED_STEP", "")
        on = (
            True if env == "1"
            else False if env == "0"
            else self.engine_cfg.enable_mixed_step
        )
        return bool(on and self.supports_mixed)

    def _spec_pipeline_resolved(self) -> bool:
        env = os.environ.get("XLLM_SPEC_PIPELINE", "")
        on = (
            True if env == "1"
            else False if env == "0"
            else self.engine_cfg.enable_spec_pipeline
        )
        return bool(on and getattr(self, "supports_spec_mixed", False))

    def prewarm_programs(
        self, p_groups: bool = True, guided: bool = False
    ) -> Dict[str, object]:
        """Compile the FULL bucket-program family this executor can
        dispatch — context buckets x step builders x spec variants —
        killing the first-post-idle-recompile class PR 11 measured at
        2.7-4 s/program (ISSUE 18 tentpole b). Beyond warmup()'s split
        sync shapes this walks the overlap pipeline's device-resident-
        feedback decode variant (committed replicated prev tokens key a
        DIFFERENT lowering than the host-fed sync call), the fused
        mixed prefill+decode family (CBd x (Lpad, CBp), both feedback
        variants), and the pipelined verify / mixed-verify programs
        when speculative decoding is configured. With the keyed
        persistent cache enabled every compile also lands on disk, so a
        warm restart replays this walk as disk reads.

        `p_groups` (default on — a concurrent admission wave is the
        NORMAL case, and its P=2 group recompile is exactly the ambush
        class) walks the P>1 prefill-group shapes of the mixed family,
        pow2 up to min(PREFILL_GROUP_MAX, max_running_requests) — the
        scheduler can never group more chunks than running slots;
        `guided` adds the guided-mask program variants when a guided
        table is installed. Returns a report dict
        ({"families": {name: programs}, "programs", "prewarm_ms"}) and
        arms the zero-fresh-lowerings accounting (lowering_count)."""
        import time as _time

        t0 = _time.perf_counter()
        before = self.lowering_count()
        R = self.R
        rep = NamedSharding(self.mesh, P())
        dev_prev = jax.device_put(np.zeros((R,), np.int32), rep)
        no_fresh = np.zeros((R,), bool)
        tables = np.zeros((R, self.max_blocks_per_seq), np.int32)
        active = np.zeros((R,), bool)
        active[0] = True
        batch = SamplingBatch(
            temperature=np.zeros(R, np.float32),
            top_k=np.zeros(R, np.int32),
            top_p=np.ones(R, np.float32),
            seeds=np.zeros(R, np.uint32),
            steps=np.zeros(R, np.int32),
        )
        families: Dict[str, int] = {}
        families["split"] = len(self.warmup())

        # Overlap-pipeline decode: the steady state feeds the next step
        # from the in-flight device sample (replicated committed arrays).
        n = 0
        for CB in self._decode_cb_walk():
            positions = np.zeros((R,), np.int32)
            positions[0] = CB * self.block_size - 1
            self.decode_start(
                np.zeros((R,), np.int32), no_fresh, dev_prev,
                positions, tables, active, batch,
            )
            n += 1
        families["decode_pipe"] = n

        interp = os.environ.get("XLLM_RAGGED_INTERPRET") == "1"
        p_walk = [1]
        if p_groups:
            pmax = min(self.PREFILL_GROUP_MAX, R)
            pw = 1
            while pw < pmax:
                pw = min(pw * 2, pmax)
                p_walk.append(pw)

        def pf_items(n_tok: int, sp: int, count: int):
            return [
                PrefillItem(
                    token_ids=np.zeros((n_tok,), np.int32),
                    start_pos=sp,
                    block_table=np.zeros(
                        (self.max_blocks_per_seq,), np.int32
                    ),
                )
                for _ in range(count)
            ]

        if self._mixed_step_resolved():
            n = 0
            for b, CBp, n_tok, sp in self._prefill_shape_family():
                for Pn in p_walk:
                    items = pf_items(n_tok, sp, Pn)
                    for CBd in self._decode_cb_walk():
                        positions = np.zeros((R,), np.int32)
                        positions[0] = CBd * self.block_size - 1
                        # Both feedback variants: host-fed (first
                        # dispatch after idle/admission) and device-
                        # resident (steady state).
                        for prev, fm in (
                            (None, None), (dev_prev, no_fresh),
                        ):
                            self.mixed_start(
                                items, np.zeros((R,), np.int32), fm,
                                prev, positions, tables, active, batch,
                                interpret=interp,
                            )
                            n += 1
            families["mixed"] = n

        # Slot-histogram (re)seed: admission calls it with the pow2-
        # bucketed generation history (P=1 fresh; resume/PD-import carry
        # longer ones) — tiny scatter programs, but a fresh lowering on
        # the admission path is still a post-idle stall.
        n = 0
        pw = 1
        limit = max(int(self.engine_cfg.max_seq_len), 1)
        while True:
            self.seed_slot_counts(0, [0] * pw)
            n += 1
            if pw >= limit:
                break
            pw *= 2
        families["seed_counts"] = n

        spec = self.engine_cfg.speculative_tokens
        if spec > 0 and self._spec_pipeline_resolved():
            S = spec + 1
            n = 0
            for CB in self._decode_cb_walk():
                host_pos = np.zeros((R,), np.int32)
                host_pos[0] = max(CB * self.block_size - 2 * S, 0)
                args = (
                    np.zeros((R, spec), np.int32),  # drafts
                    np.zeros((R,), np.int32),  # host_last
                    host_pos,
                    np.zeros((R,), np.int32),  # host_steps
                    np.ones((R,), bool),  # fresh_mask
                    None, None,  # prev tokens/n_emit (device-nulled)
                    tables, active, batch,
                )
                self.verify_start([], *args, interpret=interp)
                n += 1
                if self._mixed_step_resolved():
                    for b, CBp, n_tok, sp in self._prefill_shape_family():
                        for Pn in p_walk:
                            self.verify_start(
                                pf_items(n_tok, sp, Pn), *args,
                                interpret=interp,
                            )
                            n += 1
            families["verify_pipe"] = n

        if guided and getattr(self, "_guided_table", None) is not None:
            gbatch = SamplingBatch(
                temperature=np.zeros(R, np.float32),
                top_k=np.zeros(R, np.int32),
                top_p=np.ones(R, np.float32),
                seeds=np.zeros(R, np.uint32),
                steps=np.zeros(R, np.int32),
                mask_rows=np.full((R,), self.permissive_row, np.int32),
            )
            n = 0
            for CB in self._decode_cb_walk():
                positions = np.zeros((R,), np.int32)
                positions[0] = CB * self.block_size - 1
                self.decode(
                    np.zeros((R,), np.int32), positions, tables, active,
                    gbatch,
                )
                n += 1
                if self._mixed_step_resolved():
                    b, CBp, n_tok, sp = next(
                        iter(self._prefill_shape_family())
                    )
                    self.mixed_start(
                        pf_items(n_tok, sp, 1), np.zeros((R,), np.int32),
                        no_fresh, dev_prev, positions, tables, active,
                        gbatch, interpret=interp,
                    )
                    n += 1
            families["guided"] = n

        self.prewarm_ms = (_time.perf_counter() - t0) * 1e3
        self.prewarmed_lowerings = self.lowering_count()
        report = {
            "families": families,
            "programs": self.prewarmed_lowerings - before,
            "prewarm_ms": self.prewarm_ms,
        }
        self.prewarm_report = report
        return report

    # ------------------------------------------------ SP (ring) prefill

    @property
    def supports_sp(self) -> bool:
        # Ring attention is exact FULL attention; a sliding-window model
        # must stay on the chunked path (whose kernels mask + skip blocks
        # below the window) or SP-prefilled logits would diverge.
        return (
            self.mesh.shape.get("sp", 1) > 1
            and hasattr(self.model_mod, "prefill_sp_step")
            and not getattr(self.cfg, "sliding_window", 0)
        )

    def _sp_impl(self, k_cache, v_cache, params, token_ids, true_len,
                 blk, off, temperature, top_k, top_p, step_key):
        # Per-family dispatch — supports_sp already gated on the module
        # actually providing prefill_sp_step. When the serving mesh also
        # carries a tensor axis, the ring COMPOSES with it: params keep
        # their Megatron tp sharding and ring attention shards heads
        # over tp too (parity-proven on the composed mesh in
        # __graft_entry__._composed_sp_tp_prefill).
        tp_axis = "tp" if self.mesh.shape.get("tp", 1) > 1 else None
        logits, k_all, v_all = self.model_mod.prefill_sp_step(
            params, self.cfg, token_ids, true_len, self.mesh,
            tp_axis=tp_axis,
        )
        # Scatter every token's per-layer K/V into the paged cache
        # (invalid/padded rows land in garbage block 0). Advanced indices
        # separated by slices put the token axis FIRST in the update shape:
        # [Lsp, layers, Hkv, D].
        # rows [L, Lsp, Hkv, D] -> token axis first to match the advanced-
        # index update shape [Lsp, layers, Hkv(, D)].
        di = (slice(None), blk, slice(None), off, slice(None))
        # Scale pool is [L, N, Hkv, G, BS]: off picks the BS lane.
        si = (slice(None), blk, slice(None), slice(None), off)
        rows_k = kvc.pack_rows(jnp.swapaxes(k_all, 0, 1), k_cache)
        rows_v = kvc.pack_rows(jnp.swapaxes(v_all, 0, 1), v_cache)
        k_cache = kvc.set_rows(k_cache, di, si, rows_k)
        v_cache = kvc.set_rows(v_cache, di, si, rows_v)
        tokens, logprob, _ = sampling_ops.sample_tokens(
            logits[None], temperature[None], top_k[None], top_p[None],
            step_key[None],
        )
        return k_cache, v_cache, tokens[0], logprob[0]

    def prefill_long(
        self,
        token_ids: np.ndarray,  # [n] int32 — FULL prompt (no prefix reuse)
        block_table: np.ndarray,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        step: int = 0,
    ) -> Tuple[int, float]:
        """Sequence-parallel prefill over the mesh's sp ring (long-context
        path). The prompt attends from position 0 (prefix-cache reuse is
        skipped for this path); K/V land in the paged cache and decode
        proceeds exactly as for a normal prefill."""
        assert self.supports_sp, "mesh has no sp axis"
        sp = self.mesh.shape["sp"]
        n = len(token_ids)
        pad = self.bucket_len(n)
        if pad % sp:
            pad += sp - pad % sp
        padded = np.zeros((pad,), np.int32)
        padded[:n] = token_ids
        offsets = np.arange(pad, dtype=np.int32)
        valid = offsets < n
        # Clamp the table index BEFORE the lookup: sp-rounding can push pad
        # past max_blocks * block_size, and numpy indexes eagerly inside
        # np.where (clamped rows are invalid and masked to block 0 anyway).
        idx = np.minimum(offsets // self.block_size, len(block_table) - 1)
        blk = np.where(valid, block_table[idx], 0)
        off = np.where(valid, offsets % self.block_size, 0)
        key = sampling_ops.make_step_keys(
            jnp.asarray([seed], jnp.uint32), jnp.int32(step)
        )[0]
        if not hasattr(self, "_sp_jit"):
            self._sp_jit = jax.jit(self._sp_impl, donate_argnums=(0, 1))
        with self.mesh:
            self.k_cache, self.v_cache, tok, lp = self._sp_jit(
                self.k_cache,
                self.v_cache,
                self.params,
                jnp.asarray(padded),
                jnp.int32(n),
                jnp.asarray(blk, jnp.int32),
                jnp.asarray(off, jnp.int32),
                jnp.float32(temperature),
                jnp.int32(top_k),
                jnp.float32(top_p),
                key,
            )
        return int(tok), float(lp)

    def prefill(
        self,
        token_ids: np.ndarray,  # [n] int32 — uncached suffix of the prompt
        start_pos: int,
        block_table: np.ndarray,  # [max_blocks_per_seq] int32
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        seed: int = 0,
        step: int = 0,
    ) -> Tuple[int, float]:
        return self.prefill_batch(
            [
                PrefillItem(
                    token_ids=np.asarray(token_ids, np.int32),
                    start_pos=start_pos,
                    block_table=np.asarray(block_table, np.int32),
                    temperature=temperature,
                    top_k=top_k,
                    top_p=top_p,
                    seed=seed,
                    step=step,
                )
            ]
        )[0]

    def decode(
        self,
        token_ids: np.ndarray,  # [R]
        positions: np.ndarray,  # [R]
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
        use_kernel: Optional[bool] = None,
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Synchronous decode step: dispatch + fetch (all inputs host-fed).
        The overlapped engine uses decode_start directly and fetches one
        step behind; both share the same compiled step function."""
        tokens, logprobs = self.decode_start(
            token_ids, None, None, positions, block_tables, active, batch,
            use_kernel=use_kernel,
        )
        return np.asarray(tokens), np.asarray(logprobs)

    def decode_start(
        self,
        fresh_tokens: np.ndarray,  # [R] host-fed input ids
        fresh_mask: Optional[np.ndarray],  # [R] bool; None = all fresh
        prev_tokens,  # device [R] int32 from the prior step, or None
        positions: np.ndarray,  # [R]
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
        use_kernel: Optional[bool] = None,
    ):
        """Dispatch one decode step WITHOUT fetching the results: returns
        (tokens, logprobs) as DEVICE arrays still in flight. Slots where
        fresh_mask is False take their input token from `prev_tokens` —
        the previous step's device-resident sample — so the overlapped
        pipeline's autoregressive feedback never round-trips the host."""
        self._set_shard_ctx()
        keys = sampling_ops.make_step_keys(
            jnp.asarray(batch.seeds, jnp.uint32),
            jnp.asarray(batch.steps, jnp.int32),
        )
        # Slice the block table to the batch's true context bound (pow2
        # bucket: <= log2(max_blocks) compiles). The gather fallback
        # otherwise materializes [R, max_blocks*BS] context per layer even
        # when every sequence is short.
        need = 1
        if active.any():
            need = int(
                (np.asarray(positions)[np.asarray(active)].max() // self.block_size)
                + 1
            )
        CB = self._pow2_bucket(need, self.max_blocks_per_seq)
        R = self.R
        zeros = np.zeros((R,), np.float32)
        presence = batch.presence if batch.presence is not None else zeros
        frequency = batch.frequency if batch.frequency is not None else zeros
        bias_kwargs = {}
        if batch.bias_ids is not None:
            bias_kwargs = dict(
                bias_ids=jnp.asarray(batch.bias_ids, jnp.int32),
                bias_vals=jnp.asarray(batch.bias_vals, jnp.float32),
            )
        if batch.mask_rows is not None:
            bias_kwargs.update(
                mask_rows=jnp.asarray(batch.mask_rows, jnp.int32),
                guided_table=self._flushed_guided_table(),
            )
        if batch.adapter_idx is not None:
            bias_kwargs.update(
                lora_idx=jnp.asarray(batch.adapter_idx, jnp.int32)
            )
        if batch.min_p is not None:
            bias_kwargs.update(min_p=jnp.asarray(batch.min_p, jnp.float32))
        if batch.rope_delta is not None:
            bias_kwargs.update(
                rope_delta=jnp.asarray(batch.rope_delta, jnp.int32)
            )
        fresh = jnp.asarray(fresh_tokens, jnp.int32)
        if fresh_mask is None:
            mask = jnp.ones((R,), bool)
            prev = fresh
        else:
            mask = jnp.asarray(fresh_mask)
            prev = (
                jnp.asarray(prev_tokens, jnp.int32)
                if prev_tokens is not None
                else fresh
            )
        (
            self.k_cache, self.v_cache, self.token_counts, tokens, logprobs,
        ) = self._decode_jit(
            self.k_cache,
            self.v_cache,
            self.token_counts,
            self.params,
            fresh,
            mask,
            prev,
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables[:, :CB], jnp.int32),
            jnp.asarray(active),
            jnp.asarray(batch.temperature, jnp.float32),
            jnp.asarray(batch.top_k, jnp.int32),
            jnp.asarray(batch.top_p, jnp.float32),
            keys,
            jnp.asarray(presence, jnp.float32),
            jnp.asarray(frequency, jnp.float32),
            use_kernel=use_kernel,
            **bias_kwargs,
        )
        return tokens, logprobs

    # ------------------------------------------------------- mixed step

    @property
    def supports_mixed(self) -> bool:
        """Whether this model family serves the fused mixed prefill+decode
        step (runtime/engine.py ragged step builder). MLA families keep
        the split steps until the ragged kernel grows a latent-row mode
        (docs/KERNELS.md)."""
        return hasattr(self.model_mod, "mixed_step")

    @property
    def kernel_shards(self) -> int:
        """How many per-shard kernel launches one attention dispatch fans
        into (docs/SHARDING.md): tp under the shard_map tier, 1 on
        single-device meshes, for MLA (latent cache replicated — nothing
        to shard), or with the XLLM_SHARDED_KERNELS=0 escape hatch."""
        from xllm_service_tpu.ops import attention

        tp = self.mesh.shape.get("tp", 1)
        if (
            tp <= 1
            or self.cfg.is_mla
            or not attention.sharded_kernels_enabled()
        ):
            return 1
        return tp

    def _set_shard_ctx(self) -> None:
        """Declare this executor's mesh as the calling thread's kernel
        shard context (ops/attention.py) — called at every jitted-step
        entry point so the trace (first call compiles) captures the
        right mesh even with several executors in one process. The MoE
        expert-parallel context (ops/moe.py) is declared alongside: MLA
        families clear the attention tp context (nothing to shard in a
        latent cache) but their MoE blocks still dispatch per ep
        shard."""
        from xllm_service_tpu.ops import attention, moe

        attention.set_shard_context(
            None if self.cfg.is_mla else self.mesh
        )
        moe.set_ep_context(self.mesh if self.cfg.is_moe else None)
        moe.set_stats_sink(self._moe_sink if self.cfg.is_moe else None)

    # ----------------------------------------------- grouped-MoE stats

    def _moe_sink(self, counts, dropped: int, cap_rows: int) -> None:
        """Per-grouped-dispatch stats landing from JAX's async callback
        thread (ops.moe.set_stats_sink): one call per MoE layer per
        step, only when the grouped dispatch is enabled. A foreign
        emission (a direct ops-level grouped_moe on this thread with a
        different expert count) is dropped rather than corrupting the
        accumulators."""
        with self._moe_mu:
            if counts.shape != self._moe_counts.shape:
                return
            self._moe_counts += counts.astype(np.int64)
            self._moe_dropped += int(dropped)
            self._moe_capacity_rows += int(cap_rows)

    def moe_stats(self, drain: bool = False) -> Dict[str, float]:
        """Cumulative grouped-dispatch stats: per-expert assignment
        counts (summed over layers and steps), total assignments,
        capacity-overflow drops, group occupancy, and the hot-expert
        share — the expert-hotness signal the engine exposes as a load
        gauge next to cache usage (docs/OBSERVABILITY.md). `drain`
        synchronizes with any in-flight step first (tests/shutdown);
        the default read is scrape-safe and never blocks the
        pipeline."""
        if drain:
            try:
                jax.effects_barrier()
            except Exception:  # pragma: no cover — older jax
                pass
        with self._moe_mu:
            counts = self._moe_counts.copy()
            dropped = self._moe_dropped
            cap_rows = self._moe_capacity_rows
        total = int(counts.sum())
        return {
            "experts": int(counts.shape[0]),
            "expert_counts": counts,
            "assignments": total,
            "dropped": dropped,
            "capacity_rows": cap_rows,
            "occupancy_frac": (
                (total - dropped) / cap_rows if cap_rows else 0.0
            ),
            "hot_expert_frac": (
                float(counts.max()) / total if total else 0.0
            ),
        }

    @property
    def moe_shards(self) -> int:
        """How many per-shard grouped-MoE launches one MLP dispatch fans
        into: ep under the shard_map tier, 1 on single-device meshes,
        for non-MoE families, or with the XLLM_SHARDED_KERNELS=0 escape
        hatch (the grouped oracle then runs under plain GSPMD)."""
        from xllm_service_tpu.ops import attention, moe

        ep = self.mesh.shape.get("ep", 1)
        if (
            ep <= 1
            or not self.cfg.is_moe
            or not moe.grouped_moe_enabled()
            or not attention.sharded_kernels_enabled()
            or self.cfg.num_experts % ep
        ):
            return 1
        return ep

    def kernel_report(self) -> Dict[str, str]:
        """Resolved attention-dispatch decisions for THIS executor's cache
        and geometry — what bench.py reports instead of echoing raw env
        vars (ISSUE 9 satellite). Includes the per-shard fan-out
        (`shards`) and marks the resolve_kv_packing downgrade as
        `gather-fallback` so a tp that strands the packed layout shows up
        in bench rows and /metrics, not just a log line."""
        if self.cfg.is_mla:
            from xllm_service_tpu.ops.attention import (
                resolved_mla_kernel_report,
            )

            # The latent cache rides the k slot (num_caches == 1).
            return self._add_moe_report(
                resolved_mla_kernel_report(self.k_cache)
            )
        from xllm_service_tpu.ops.attention import resolved_kernel_report

        rep = resolved_kernel_report(
            self.k_cache, self.cfg.head_dim,
            ragged_interpret=(
                os.environ.get("XLLM_RAGGED_INTERPRET") == "1"
            ),
            shards=self.kernel_shards,
        )
        if self.kv_pack_fallback and rep.get("decode", "").startswith(
            "gather"
        ):
            rep["decode"] = "gather-fallback"
        return self._add_moe_report(rep)

    def _add_moe_report(self, rep: Dict[str, str]) -> Dict[str, str]:
        """MoE rows of the resolved report (MoE configs only): `moe` is
        the dispatch the MLP block takes RIGHT NOW (dense | grouped |
        grouped-ref, docs/MOE.md), `moe_shards` the per-shard launch
        fan-out over ep — asserted (not assumed) by the EP differential
        suite, exactly like attention's `shards`."""
        if self.cfg.is_moe:
            from xllm_service_tpu.ops.moe import resolved_moe_dispatch

            rep["moe"] = resolved_moe_dispatch(
                self.cfg.hidden_size, self.cfg.moe_intermediate_size
            )
            rep["moe_shards"] = self.moe_shards
        return rep

    def _mixed_impl(
        self,
        k_cache,
        v_cache,
        counts,  # [R, V] int32 generated-token histogram (donated)
        params,
        # --- decode half: identical contract to _decode_impl ---
        fresh_tokens,  # [R]
        fresh_mask,  # [R] bool
        prev_tokens,  # [R] device-resident feedback (overlap pipeline)
        positions,  # [R]
        dec_tables,  # [R, CB]
        active,  # [R] bool
        temperature,
        top_k,
        top_p,
        step_keys,
        presence,
        frequency,
        # --- prefill half: identical contract to _prefill_impl ---
        pf_tokens,  # [P, Lpad]
        pf_start,  # [P]
        pf_len,  # [P] (0 = padded lane)
        pf_tables,  # [P, CB]
        pf_temperature,
        pf_top_k,
        pf_top_p,
        pf_keys,
        bias_ids=None,
        bias_vals=None,
        min_p=None,
        rope_delta=None,
        lora_dec=None,  # [R] adapter rows (decode slots)
        lora_pf=None,  # [P] adapter rows (prefill rows)
        pf_counts=None,
        pf_presence=None,
        pf_frequency=None,
        pf_bias_ids=None,
        pf_bias_vals=None,
        pf_min_p=None,
        mask_rows=None,  # [R] rows into guided_table (decode slots)
        pf_mask_rows=None,  # [P] rows into guided_table (prefill rows)
        guided_table=None,  # [M+1+D, V] bool
        use_ragged=None,
        interpret=False,
    ):
        """One fused engine step: decode slots + due prefill chunks in a
        single compiled dispatch (models.<family>.mixed_step). Sampling
        for each half runs the SAME ops with the SAME key schedules as
        the split _decode_impl/_prefill_impl, and the model halves keep
        their split-program shapes (mixed_step docstring), so the
        emitted streams are byte-identical to split stepping
        (tests/test_ragged_attention.py pins it). Output layout: decode
        slots first ([:R] feeds the next overlapped dispatch
        device-side), then the P prefill rows."""
        token_ids = jnp.where(fresh_mask, fresh_tokens, prev_tokens)
        dec_logits, pf_logits, k_cache, v_cache = self.model_mod.mixed_step(
            params,
            self.cfg,
            k_cache,
            v_cache,
            token_ids,
            positions,
            dec_tables,
            active,
            pf_tokens,
            pf_start,
            pf_len,
            pf_tables,
            use_ragged=use_ragged,
            lora_dec=lora_dec,
            lora_pf=lora_pf,
            rope_delta=rope_delta,
            interpret=interpret,
        )
        tokens, logprob, _ = sampling_ops.sample_tokens(
            dec_logits, temperature, top_k, top_p, step_keys,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals, min_p=min_p,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
        )
        counts = counts.at[
            jnp.arange(tokens.shape[0]), tokens
        ].add(active.astype(jnp.int32))
        pf_tokens_out, pf_logprob, _ = sampling_ops.sample_tokens(
            pf_logits, pf_temperature, pf_top_k, pf_top_p, pf_keys,
            counts=pf_counts, presence=pf_presence, frequency=pf_frequency,
            bias_ids=pf_bias_ids, bias_vals=pf_bias_vals, min_p=pf_min_p,
            allowed=(
                guided_table[pf_mask_rows]
                if pf_mask_rows is not None else None
            ),
        )
        return (
            k_cache,
            v_cache,
            counts,
            jnp.concatenate([tokens, pf_tokens_out]),
            jnp.concatenate([logprob, pf_logprob]),
        )

    def mixed_start(
        self,
        items: List["PrefillItem"],  # due prefill chunks (<= GROUP_MAX)
        fresh_tokens: np.ndarray,  # [R] host-fed decode input ids
        fresh_mask: Optional[np.ndarray],  # [R] bool; None = all fresh
        prev_tokens,  # device [R] int32 from the prior step, or None
        positions: np.ndarray,  # [R]
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
        use_ragged: Optional[bool] = None,
        interpret: bool = False,
    ):
        """Dispatch ONE mixed prefill+decode step without fetching results:
        returns (tokens, logprobs) device arrays of width R + Ppad —
        decode slots at [:R] (the overlap pipeline's device-resident
        feedback slice), prefill row j at R + j. The engine's ragged step
        builder is the only caller (docs/KERNELS.md); media/M-RoPE items
        never reach here (routed to the split prefill path). Guided
        items DO ride (ISSUE 13): final chunks carry mask_row and the
        decode half takes batch.mask_rows — both applied in-graph."""
        self._set_shard_ctx()
        R = self.R
        n_pf = len(items)
        P = self._pow2_bucket(max(n_pf, 1), self.PREFILL_GROUP_MAX)
        Lpad = self.bucket_len(
            max((len(it.token_ids) for it in items), default=1)
        )
        bs = self.block_size
        # Each half buckets its context width EXACTLY like its split
        # program (decode_start / _prefill_group) — the bucket cadence is
        # part of the byte-parity contract (a different table width means
        # a different compiled program for that half).
        need_d = 1
        if active.any():
            need_d = int(
                (np.asarray(positions)[np.asarray(active)].max() // bs) + 1
            )
        CBd = self._pow2_bucket(need_d, self.max_blocks_per_seq)
        need_p = max(
            ((it.start_pos + len(it.token_ids) + bs - 1) // bs
             for it in items),
            default=1,
        )
        CBp = self._pow2_bucket(max(need_p, 1), self.max_blocks_per_seq)

        keys = sampling_ops.make_step_keys(
            jnp.asarray(batch.seeds, jnp.uint32),
            jnp.asarray(batch.steps, jnp.int32),
        )
        zeros = np.zeros((R,), np.float32)
        presence = batch.presence if batch.presence is not None else zeros
        frequency = batch.frequency if batch.frequency is not None else zeros

        pf_args, pf_opt = self._pf_half(items, P, Lpad, CBp)

        opt = dict(pf_opt)
        if batch.bias_ids is not None:
            opt.update(
                bias_ids=jnp.asarray(batch.bias_ids, jnp.int32),
                bias_vals=jnp.asarray(batch.bias_vals, jnp.float32),
            )
        if batch.min_p is not None:
            opt.update(min_p=jnp.asarray(batch.min_p, jnp.float32))
        if batch.rope_delta is not None:
            opt.update(rope_delta=jnp.asarray(batch.rope_delta, jnp.int32))
        # Guided decoding rides per half like the split programs: the
        # decode half takes the engine's per-slot rows (sync _decode_once
        # contract), the prefill half the per-item final-chunk rows
        # (_prefill_group contract). One table serves both.
        if batch.mask_rows is not None:
            opt.update(
                mask_rows=jnp.asarray(batch.mask_rows, jnp.int32),
                guided_table=self._flushed_guided_table(),
            )
        # LoRA rides per half, gated exactly like the split programs
        # (decode_start keys on batch.adapter_idx, _prefill_group on any
        # item adapter) — an adapter on one half must not flip the other
        # half onto the lora-apply path.
        if batch.adapter_idx is not None:
            opt.update(
                lora_dec=jnp.asarray(batch.adapter_idx, jnp.int32)
            )

        fresh = jnp.asarray(fresh_tokens, jnp.int32)
        if fresh_mask is None:
            mask = jnp.ones((R,), bool)
            prev = fresh
        else:
            mask = jnp.asarray(fresh_mask)
            prev = (
                jnp.asarray(prev_tokens, jnp.int32)
                if prev_tokens is not None
                else fresh
            )
        if not hasattr(self, "_mixed_jit"):
            self._mixed_jit = jax.jit(
                self._mixed_impl,
                donate_argnums=(0, 1, 2),
                static_argnames=("use_ragged", "interpret"),
            )
        (
            self.k_cache, self.v_cache, self.token_counts, tokens, logprobs,
        ) = self._mixed_jit(
            self.k_cache,
            self.v_cache,
            self.token_counts,
            self.params,
            fresh,
            mask,
            prev,
            jnp.asarray(positions, jnp.int32),
            jnp.asarray(block_tables[:, :CBd], jnp.int32),
            jnp.asarray(active),
            jnp.asarray(batch.temperature, jnp.float32),
            jnp.asarray(batch.top_k, jnp.int32),
            jnp.asarray(batch.top_p, jnp.float32),
            keys,
            jnp.asarray(presence, jnp.float32),
            jnp.asarray(frequency, jnp.float32),
            *pf_args,
            use_ragged=use_ragged,
            interpret=interpret,
            **opt,
        )
        return tokens, logprobs

    def _pf_half(self, items: List["PrefillItem"], P: int, Lpad: int,
                 CBp: int):
        """Pack the prefill half of a fused dispatch: the positional
        arrays (tokens, start, len, tables, temps, top_k, top_p, keys —
        as jnp arrays, in _mixed_impl/_mixed_verify_impl argument order)
        plus the optional pf_* sampling features, gated per item exactly
        like _prefill_group. Shared by mixed_start and verify_start."""
        n_pf = len(items)
        pf_tokens = np.zeros((P, Lpad), np.int32)
        pf_start = np.zeros((P,), np.int32)
        pf_len = np.zeros((P,), np.int32)
        pf_tables = np.zeros((P, CBp), np.int32)
        pf_temps = np.zeros((P,), np.float32)
        pf_top_k = np.zeros((P,), np.int32)
        pf_top_p = np.ones((P,), np.float32)
        pf_seeds = np.zeros((P,), np.uint32)
        pf_steps = np.zeros((P,), np.int32)
        for i, it in enumerate(items):
            n = len(it.token_ids)
            pf_tokens[i, :n] = it.token_ids
            pf_start[i] = it.start_pos
            pf_len[i] = n
            m = min(CBp, len(it.block_table))
            pf_tables[i, :m] = np.asarray(it.block_table[:m], np.int32)
            pf_temps[i] = it.temperature
            pf_top_k[i] = it.top_k
            pf_top_p[i] = it.top_p
            pf_seeds[i] = it.seed & 0xFFFFFFFF
            pf_steps[i] = it.step
        pf_keys = sampling_ops.make_step_keys(
            jnp.asarray(pf_seeds), jnp.asarray(pf_steps, jnp.int32)
        )
        opt = {}
        if any(it.adapter_idx for it in items):
            opt.update(
                lora_pf=jnp.asarray(
                    [it.adapter_idx for it in items] + [0] * (P - n_pf),
                    jnp.int32,
                )
            )
        b_ids, b_vals = sampling_ops.pack_logit_bias(
            [it.logit_bias for it in items] + [()] * (P - n_pf), P
        )
        if b_ids is not None:
            opt.update(
                pf_bias_ids=jnp.asarray(b_ids),
                pf_bias_vals=jnp.asarray(b_vals),
            )
        if any(it.min_p for it in items):
            opt.update(
                pf_min_p=jnp.asarray(
                    [it.min_p for it in items] + [0.0] * (P - n_pf),
                    jnp.float32,
                )
            )
        if any(it.mask_row >= 0 for it in items):
            # Guided final chunks: the admission-sampled token applies
            # the host-derived mask row in-graph (mirrors
            # _prefill_group's mask_rows path).
            rows = np.full((P,), self.permissive_row, np.int32)
            for i, it in enumerate(items):
                if it.mask_row >= 0:
                    rows[i] = it.mask_row
            opt.update(
                pf_mask_rows=jnp.asarray(rows),
                guided_table=self._flushed_guided_table(),
            )
        if any(
            it.prior_tokens is not None and len(it.prior_tokens)
            for it in items
        ):
            cnts = np.zeros((P, self.cfg.vocab_size), np.int32)
            pres = np.zeros((P,), np.float32)
            freq = np.zeros((P,), np.float32)
            for i, it in enumerate(items):
                pres[i] = it.presence
                freq[i] = it.frequency
                if it.prior_tokens is not None and len(it.prior_tokens):
                    np.add.at(
                        cnts[i], np.asarray(it.prior_tokens, np.int64), 1
                    )
            opt.update(
                pf_counts=jnp.asarray(cnts),
                pf_presence=jnp.asarray(pres),
                pf_frequency=jnp.asarray(freq),
            )
        return (
            jnp.asarray(pf_tokens),
            jnp.asarray(pf_start),
            jnp.asarray(pf_len),
            jnp.asarray(pf_tables),
            jnp.asarray(pf_temps),
            jnp.asarray(pf_top_k),
            jnp.asarray(pf_top_p),
            pf_keys,
        ), opt

    # ------------------------------------------- pipelined verify (spec)

    @property
    def supports_spec_mixed(self) -> bool:
        """Whether this model family can fuse speculative verify rows
        with prefill chunks in one dispatch (mixed_verify_step). MLA
        families run the pipelined verify WITHOUT prefill fusion until
        the ragged kernel grows a latent-row mode (docs/KERNELS.md)."""
        return hasattr(self.model_mod, "mixed_verify_step")

    def _spec_state_merge(
        self, drafts, host_last, host_pos, host_steps, fresh_mask,
        prev_tokens, prev_n_emit, seeds, active,
    ):
        """In-graph verify-input gather for the pipelined speculative
        step: a slot covered by the in-flight verify step feeds from ITS
        device-resident output — last accepted token
        prev_tokens[r, n_emit-1], position/step base advanced by the
        VARIABLE accepted count — while fresh slots (admission, resume,
        pacing, post-flush) feed from host truth. true_len clamps to the
        remaining context in-graph: a row whose device position already
        reached max_seq_len goes inactive (its sequence length-stopped
        at the drain one step behind; the row's output is a late-stop
        discard), so no write ever lands past max_seq_len - 1. Keys use
        the SAME sequential per-step schedule as sync verify — computed
        in-graph because the step base is device-resident."""
        R, k = drafts.shape
        S = k + 1
        ne = jnp.clip(prev_n_emit - 1, 0, S - 1)
        carried_last = jnp.take_along_axis(
            prev_tokens, ne[:, None], axis=1
        )[:, 0]
        last = jnp.where(fresh_mask, host_last, carried_last)
        pos = jnp.where(fresh_mask, host_pos, host_pos + prev_n_emit)
        steps = jnp.where(fresh_mask, host_steps, host_steps + prev_n_emit)
        tl = jnp.clip(self.engine_cfg.max_seq_len - pos, 0, S)
        act = active & (tl > 0)
        tl = jnp.where(act, tl, 0)
        token_ids = jnp.concatenate(
            [last[:, None], drafts.astype(jnp.int32)], axis=1
        )
        keys = jnp.stack(
            [
                sampling_ops.make_step_keys(seeds, steps + j)
                for j in range(S)
            ],
            axis=1,
        )  # [R, S, 2]
        return token_ids, pos, tl, keys, act

    def _verify_pipe_impl(
        self,
        k_cache,
        v_cache,
        counts,  # [R, V] int32 (donated)
        params,
        drafts,  # [R, k] int32 — host-proposed (may lag one step:
        #          point-mass acceptance makes the stream draft-blind)
        host_last,  # [R] int32 — last token, host truth post-drain
        host_pos,  # [R] int32 — position base, host truth post-drain
        host_steps,  # [R] int32 — generated count, host truth post-drain
        fresh_mask,  # [R] bool — True: feed from host truth
        prev_tokens,  # [R, S] device — in-flight verify output tokens
        prev_n_emit,  # [R] device — in-flight accepted counts
        seeds,  # [R] uint32
        block_tables,  # [R, CB]
        active,  # [R] bool
        temperature,
        top_k,
        top_p,
        presence,
        frequency,
        bias_ids=None,
        bias_vals=None,
        mask_rows=None,  # [R, S] rows into guided_table
        guided_table=None,
        lora_idx=None,
        min_p=None,
        rope_delta=None,
    ):
        """Pipelined speculative verify WITHOUT prefill fusion: the
        _verify_impl program fed by the in-graph state merge instead of
        host-resolved inputs (docs/ENGINE_PIPELINE.md)."""
        token_ids, pos, tl, keys, act = self._spec_state_merge(
            drafts, host_last, host_pos, host_steps, fresh_mask,
            prev_tokens, prev_n_emit, seeds, active,
        )
        step_kwargs = (
            {"lora_idx": lora_idx} if lora_idx is not None else {}
        )
        if rope_delta is not None:
            S_ = token_ids.shape[1]
            base = (pos + rope_delta)[:, None] + jnp.arange(
                S_, dtype=jnp.int32
            )[None]
            step_kwargs["rope_positions"] = jnp.broadcast_to(
                base[:, None, :], (base.shape[0], 3, S_)
            )
        logits, k_cache, v_cache = self.model_mod.prefill_batch_step(
            params, self.cfg, k_cache, v_cache, token_ids, pos,
            tl, block_tables, all_logits=True, **step_kwargs,
        )
        tokens, logprobs, n_emit, counts = sampling_ops.speculative_sample(
            logits, token_ids[:, 1:], temperature, top_k, top_p, keys,
            limits=tl, active=act,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
            min_p=min_p,
        )
        return k_cache, v_cache, counts, tokens, logprobs, n_emit

    def _mixed_verify_impl(
        self,
        k_cache,
        v_cache,
        counts,
        params,
        # --- verify half: identical contract to _verify_pipe_impl ---
        drafts,
        host_last,
        host_pos,
        host_steps,
        fresh_mask,
        prev_tokens,
        prev_n_emit,
        seeds,
        ver_tables,  # [R, CBv]
        active,
        temperature,
        top_k,
        top_p,
        presence,
        frequency,
        # --- prefill half: identical contract to _mixed_impl ---
        pf_tokens,
        pf_start,
        pf_len,
        pf_tables,
        pf_temperature,
        pf_top_k,
        pf_top_p,
        pf_keys,
        bias_ids=None,
        bias_vals=None,
        mask_rows=None,  # [R, S] (verify rows)
        guided_table=None,
        lora_idx=None,
        min_p=None,
        rope_delta=None,
        lora_pf=None,
        pf_counts=None,
        pf_presence=None,
        pf_frequency=None,
        pf_bias_ids=None,
        pf_bias_vals=None,
        pf_min_p=None,
        pf_mask_rows=None,  # [P] (prefill rows)
        use_ragged=None,
        interpret=False,
    ):
        """One fused speculative engine step: the pipelined verify rows
        AND the due prefill chunks in a single compiled dispatch
        (models.<family>.mixed_verify_step). Sampling per half runs the
        same ops on the same key schedules as the split programs, so the
        composed streams stay byte-identical to sync+split
        (tests/test_spec_pipeline.py pins it). Output layout: verify
        tokens [R, S] + accepted counts, then the P prefill tokens."""
        token_ids, pos, tl, keys, act = self._spec_state_merge(
            drafts, host_last, host_pos, host_steps, fresh_mask,
            prev_tokens, prev_n_emit, seeds, active,
        )
        ver_rope = None
        if rope_delta is not None:
            ver_rope = rope_delta
        ver_logits, pf_logits, k_cache, v_cache = (
            self.model_mod.mixed_verify_step(
                params,
                self.cfg,
                k_cache,
                v_cache,
                token_ids,
                pos,
                tl,
                ver_tables,
                pf_tokens,
                pf_start,
                pf_len,
                pf_tables,
                use_ragged=use_ragged,
                lora_ver=lora_idx,
                lora_pf=lora_pf,
                ver_rope_delta=ver_rope,
                interpret=interpret,
            )
        )
        tokens, logprobs, n_emit, counts = sampling_ops.speculative_sample(
            ver_logits, token_ids[:, 1:], temperature, top_k, top_p, keys,
            limits=tl, active=act,
            counts=counts, presence=presence, frequency=frequency,
            bias_ids=bias_ids, bias_vals=bias_vals,
            allowed=(
                guided_table[mask_rows] if mask_rows is not None else None
            ),
            min_p=min_p,
        )
        pf_tok, pf_lp, _ = sampling_ops.sample_tokens(
            pf_logits, pf_temperature, pf_top_k, pf_top_p, pf_keys,
            counts=pf_counts, presence=pf_presence, frequency=pf_frequency,
            bias_ids=pf_bias_ids, bias_vals=pf_bias_vals, min_p=pf_min_p,
            allowed=(
                guided_table[pf_mask_rows]
                if pf_mask_rows is not None else None
            ),
        )
        return (
            k_cache, v_cache, counts, tokens, logprobs, n_emit,
            pf_tok, pf_lp,
        )

    def verify_start(
        self,
        items: List["PrefillItem"],  # due prefill chunks ([] = none)
        drafts: np.ndarray,  # [R, k] int32 host-proposed draft tokens
        host_last: np.ndarray,  # [R] int32
        host_pos: np.ndarray,  # [R] int32
        host_steps: np.ndarray,  # [R] int32
        fresh_mask: np.ndarray,  # [R] bool
        prev_tokens,  # device [R, S] from the in-flight verify, or None
        prev_n_emit,  # device [R] accepted counts, or None
        block_tables: np.ndarray,  # [R, max_blocks_per_seq]
        active: np.ndarray,  # [R] bool
        batch: SamplingBatch,
        interpret: bool = False,
    ):
        """Dispatch ONE pipelined speculative verify step — optionally
        fused with due prefill chunks — without fetching results.
        Returns (tokens [R, S], logprobs [R, S], n_emit [R], pf_tokens
        [P] | None, pf_logprobs [P] | None) as DEVICE arrays still in
        flight; the engine drains one step behind and feeds the next
        dispatch from these arrays (docs/ENGINE_PIPELINE.md). The
        context-bucket bound covers host positions + TWO steps of
        worst-case emission (the in-flight step's and this one's)."""
        self._set_shard_ctx()
        R = self.R
        S = drafts.shape[1] + 1
        bs = self.block_size
        max_len = self.engine_cfg.max_seq_len
        need = 1
        if active.any():
            worst = (
                int(np.asarray(host_pos)[np.asarray(active)].max())
                + 2 * S - 1
            )
            need = min(worst, max_len - 1) // bs + 1
        CB = self._pow2_bucket(max(need, 1), self.max_blocks_per_seq)
        zeros = np.zeros((R,), np.float32)
        presence = batch.presence if batch.presence is not None else zeros
        frequency = batch.frequency if batch.frequency is not None else zeros
        bias_kwargs = {}
        if batch.bias_ids is not None:
            bias_kwargs = dict(
                bias_ids=jnp.asarray(batch.bias_ids, jnp.int32),
                bias_vals=jnp.asarray(batch.bias_vals, jnp.float32),
            )
        if batch.mask_rows is not None:
            bias_kwargs.update(
                mask_rows=jnp.asarray(batch.mask_rows, jnp.int32),
                guided_table=self._flushed_guided_table(),
            )
        if batch.adapter_idx is not None:
            bias_kwargs.update(
                lora_idx=jnp.asarray(batch.adapter_idx, jnp.int32)
            )
        if batch.min_p is not None:
            bias_kwargs.update(min_p=jnp.asarray(batch.min_p, jnp.float32))
        if batch.rope_delta is not None:
            bias_kwargs.update(
                rope_delta=jnp.asarray(batch.rope_delta, jnp.int32)
            )
        if prev_tokens is None:
            # Committed device zeros with the SAME replicated sharding a
            # real verify output carries — a host numpy array here keys
            # a second pjit lowering per context bucket (unspecified- vs
            # named-sharding args), recompiling the whole verify program
            # on the first post-idle dispatch.
            cached = getattr(self, "_null_prev", None)
            if cached is None or cached[0] != S:
                # jax.sharding spelled out: `P` is shadowed by the local
                # prefill-group bucket below.
                rep = NamedSharding(self.mesh, jax.sharding.PartitionSpec())
                self._null_prev = (
                    S,
                    jax.device_put(np.zeros((R, S), np.int32), rep),
                    jax.device_put(np.zeros((R,), np.int32), rep),
                )
                cached = self._null_prev
            prev_tokens, prev_n_emit = cached[1], cached[2]
        common = (
            self.k_cache,
            self.v_cache,
            self.token_counts,
            self.params,
            jnp.asarray(drafts, jnp.int32),
            jnp.asarray(host_last, jnp.int32),
            jnp.asarray(host_pos, jnp.int32),
            jnp.asarray(host_steps, jnp.int32),
            jnp.asarray(fresh_mask),
            jnp.asarray(prev_tokens, jnp.int32),
            jnp.asarray(prev_n_emit, jnp.int32),
            jnp.asarray(batch.seeds, jnp.uint32),
            jnp.asarray(block_tables[:, :CB], jnp.int32),
            jnp.asarray(active),
            jnp.asarray(batch.temperature, jnp.float32),
            jnp.asarray(batch.top_k, jnp.int32),
            jnp.asarray(batch.top_p, jnp.float32),
            jnp.asarray(presence, jnp.float32),
            jnp.asarray(frequency, jnp.float32),
        )
        if not items:
            if not hasattr(self, "_verify_pipe_jit"):
                self._verify_pipe_jit = jax.jit(
                    self._verify_pipe_impl, donate_argnums=(0, 1, 2)
                )
            (
                self.k_cache, self.v_cache, self.token_counts,
                tokens, logprobs, n_emit,
            ) = self._verify_pipe_jit(*common, **bias_kwargs)
            return tokens, logprobs, n_emit, None, None
        n_pf = len(items)
        P = self._pow2_bucket(max(n_pf, 1), self.PREFILL_GROUP_MAX)
        Lpad = self.bucket_len(
            max((len(it.token_ids) for it in items), default=1)
        )
        need_p = max(
            ((it.start_pos + len(it.token_ids) + bs - 1) // bs
             for it in items),
            default=1,
        )
        CBp = self._pow2_bucket(max(need_p, 1), self.max_blocks_per_seq)
        pf_args, pf_opt = self._pf_half(items, P, Lpad, CBp)
        opt = dict(pf_opt)
        opt.update(bias_kwargs)
        if not hasattr(self, "_mixed_verify_jit"):
            self._mixed_verify_jit = jax.jit(
                self._mixed_verify_impl,
                donate_argnums=(0, 1, 2),
                static_argnames=("use_ragged", "interpret"),
            )
        (
            self.k_cache, self.v_cache, self.token_counts,
            tokens, logprobs, n_emit, pf_tok, pf_lp,
        ) = self._mixed_verify_jit(
            *common, *pf_args, interpret=interpret, **opt,
        )
        return tokens, logprobs, n_emit, pf_tok, pf_lp

    def seed_slot_counts(self, slot: int, generated: "List[int]") -> None:
        """(Re)build one slot's generated-token histogram — on admission
        (fresh: the prefill's first token) and on resume (preemption / PD
        import carry full generation history). Penalties depend on it."""
        if not hasattr(self, "_seed_counts_jit"):
            def _impl(counts, slot_, toks, n):
                counts = counts.at[slot_].set(0)
                ids = jnp.where(
                    jnp.arange(toks.shape[0]) < n, toks, 0
                )
                add = (jnp.arange(toks.shape[0]) < n).astype(jnp.int32)
                return counts.at[slot_, ids].add(add)

            self._seed_counts_jit = jax.jit(_impl, donate_argnums=(0,))
        P = self._pow2_bucket(max(len(generated), 1), 1 << 30)
        toks = np.zeros((P,), np.int32)
        toks[: len(generated)] = generated
        self.token_counts = self._seed_counts_jit(
            self.token_counts, jnp.int32(slot), jnp.asarray(toks),
            jnp.int32(len(generated)),
        )

    # ------------------------------------------------- KV block migration

    # ------------------------------------------------------------ embeddings

    def embed_tokens(self, inputs: List[List[int]]) -> np.ndarray:
        """/v1/embeddings path (the reference rejects the endpoint outright
        — service.cpp:441-442; implementing it EXCEEDS parity): mean-pooled,
        L2-normalized final-norm hidden states of a causal forward. Inputs
        bucket to the prefill length buckets (bounded compiles); batch of
        one per call keeps it simple — embeddings traffic is sparse
        relative to generation."""
        with _EMBED_INIT_LOCK:
            init_needed = not hasattr(self, "_embed_jit")
        if init_needed:
            def _impl(params, token_ids, true_len):
                h = self.model_mod.hidden_dense(
                    params, self.cfg, token_ids,
                    # Bucket-padding rows stay out of the grouped-MoE
                    # dispatch's routing stats/capacity (llama._mlp_block
                    # rows_valid) — the pooling mask below already
                    # excludes them from the embedding itself.
                    rows_valid=(
                        jnp.arange(token_ids.shape[1])[None, :] < true_len
                    ),
                )  # [1, L, E]
                mask = (
                    jnp.arange(h.shape[1])[None, :, None] < true_len
                ).astype(jnp.float32)
                hf = h.astype(jnp.float32) * mask
                pooled = hf.sum(axis=1) / jnp.maximum(
                    mask.sum(axis=1), 1.0
                )  # [1, E]
                return pooled / jnp.maximum(
                    jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9
                )

            with _EMBED_INIT_LOCK:
                if not hasattr(self, "_embed_jit"):
                    self._embed_jit = jax.jit(_impl)
        out = np.empty((len(inputs), self.cfg.hidden_size), np.float32)
        with self.mesh:
            for i, ids in enumerate(inputs):
                n = max(1, min(len(ids), self.engine_cfg.max_seq_len))
                pad = self.bucket_len(n)
                padded = np.zeros((1, pad), np.int32)
                padded[0, :n] = ids[:n]
                out[i] = np.asarray(
                    self._embed_jit(
                        self.params, jnp.asarray(padded), jnp.int32(n)
                    )
                )[0]
        return out

    def migration_shape(self, n_blocks: int) -> Tuple[int, ...]:
        """Expected KV-handoff payload shape for n_blocks blocks — the PD
        pair compatibility contract (engine validates incoming handoffs
        against it): [num_caches, L, n, cache_heads, BS, row_dim]."""
        ch, cd = models.cache_row_dims(self.cfg)
        return (
            self.num_caches,
            self.cfg.num_layers,
            n_blocks,
            ch,
            self.block_size,
            cd,
        )

    def migration_sharding(self) -> NamedSharding:
        """NamedSharding of a migration payload on THIS mesh: the
        cache-head axis (3) over tp, exactly like the pool it came from /
        lands into (kv_cache_sharding) — the landing target for
        per-shard wire payloads and pull-plane fetches
        (parallel/shard_wire.py). MLA latents replicate (no head axis);
        on a 1-device mesh this is effectively a single-device placement
        (the satellite's no-op case)."""
        if self.cfg.is_mla or "tp" not in self.mesh.shape:
            return NamedSharding(self.mesh, P())
        return NamedSharding(
            self.mesh, P(None, None, None, "tp", None, None)
        )

    def export_blocks(self, block_ids: np.ndarray) -> jax.Array:
        """Gather KV blocks for migration to a peer instance (PD disagg).
        Returns [2, L, n, Hkv, bs, D] on device in MODEL dtype (int8 caches
        dequantize on export so the migration payload / host-tier format is
        dtype-stable); the transfer layer moves it over ICI/DCN
        (jax.device_put to the peer mesh) or via host RPC. Under tp>1 the
        export is COMMITTED to migration_sharding (heads per shard), so
        the wire layer (shard_wire.to_host) can read per-shard host
        copies without a cross-shard gather."""
        ids = jnp.asarray(block_ids, jnp.int32)

        def grab(cache):
            if cache.quantized:
                return kvc.dequantize_pool(
                    cache.data[:, ids], cache.scale[:, ids], self.dtype
                )
            return cache.data[:, ids]

        caches = [self.k_cache, self.v_cache][: self.num_caches]
        out = jnp.stack([grab(c) for c in caches])
        if self.mesh.shape.get("tp", 1) > 1:
            out = jax.device_put(out, self.migration_sharding())
        return out

    def import_blocks(self, blocks, block_ids: np.ndarray) -> None:
        """Scatter migrated/offloaded blocks into the caches IN PLACE (the
        jitted step donates both caches — without donation each import
        would copy the whole multi-GiB pool). Block count is padded to a
        power of two (duplicate trailing id, same data: benign re-write) so
        compile count stays logarithmic.

        `blocks` may be a host array, a device array (in-process PD fast
        path — possibly committed to ANOTHER executor's mesh), or a
        per-shard `shard_wire.ShardedKV` off the wire; everything lands
        directly onto this executor's migration_sharding (one
        jax.device_put per shard — no host-side gather/reshard bounce,
        and a no-op placement on 1-device meshes)."""
        from xllm_service_tpu.parallel import shard_wire

        n = len(block_ids)
        P2 = 1
        while P2 < n:
            P2 *= 2
        ids = np.empty((P2,), np.int32)
        ids[:n] = block_ids
        ids[n:] = block_ids[n - 1] if n else 0
        # One device-side pad for both payload kinds: host (HTTP/DCN, tier
        # re-import) payloads transfer UNPADDED and pad on device; the
        # in-process PD fast path is already device-resident (no host
        # round-trip anywhere in the import).
        arr = shard_wire.assemble(blocks, self.migration_sharding())
        if P2 != n:
            pad = jnp.repeat(arr[:, :, -1:], P2 - n, axis=2)
            arr = jnp.concatenate([arr, pad], axis=2)
        self.k_cache, self.v_cache = self._import_jit(
            self.k_cache, self.v_cache, arr, jnp.asarray(ids)
        )
